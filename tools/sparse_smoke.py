"""Sparse frontier engine smoke test: the CI gate for engine/sparse.py +
the ``EngineStatic.representation`` compile key (ISSUE 19).

Fast CPU gate (~2-3 min) over six contracts:

  1. **Dense/sparse bit parity at 1k under faults**: the full CLI run
     (stats parity snapshot + deterministic Influx wire lines) is
     bit-identical between ``--engine-representation dense`` and
     ``sparse`` at 1000 nodes under packet loss + churn.
  2. **1k-node CPU-oracle parity**: the sparse engine bit-matches the
     loop-based oracle Cluster (forced-identical active sets, rotation
     off, FaultInjector-driven loss + churn) on distances, RMR m/n,
     delivered/dropped counters and the failed mask, every round.
  3. **Dense unchanged**: ``representation="dense"`` reproduces the
     committed pre-PR golden (tests/fixtures/sparse/dense_golden.json —
     parity snapshot + wire lines captured from the tree before the
     sparse engine landed) bit-for-bit.
  4. **Ledger exactness**: the capacity ledger's sparse-group closed
     forms equal the live donated buffers' ``nbytes`` per field and in
     total at two (N, C) points, and the rc stake planes really carry
     zero bytes under sparse.
  5. **The wall moves**: ``fit_budget(16GB)`` under the all-origins
     interpretation reports a strictly larger max-N for sparse than for
     dense, and clears the dense engine's documented 3,914 ceiling.
  6. **i64 key-width parity**: ``FORCE_I64_KEYS`` drives a
     within-i32-bound cluster through the i64 sort-key arms
     (engine/core.py) and every engine row stays bit-identical — run
     here rather than tier-1 because the required compile-cache clears
     would force the whole test suite behind it to recompile.

Usage: python tools/sparse_smoke.py [--seed 7] [--num-nodes 1000]
       [--rounds 6]

Exit code 0 = all contracts hold; 1 = a sparse invariant failed.
"""
import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

GOLDEN = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "tests", "fixtures", "sparse",
    "dense_golden.json")
DENSE_CEILING = 3914  # the pre-sparse 16GB all-origins fit (PR 13)


def main() -> int:
    ap = argparse.ArgumentParser(
        description="sparse frontier engine smoke (CPU, <3min)")
    ap.add_argument("--seed", type=int, default=7)
    ap.add_argument("--num-nodes", type=int, default=1000)
    ap.add_argument("--rounds", type=int, default=6)
    args = ap.parse_args()

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax
    import jax.numpy as jnp
    import numpy as np

    from gossip_sim_tpu.cli import run_simulation
    from gossip_sim_tpu.config import Config
    from gossip_sim_tpu.constants import UNREACHED
    from gossip_sim_tpu.engine import (EngineParams, init_state,
                                       make_cluster_tables, run_rounds)
    from gossip_sim_tpu.faults import FaultInjector
    from gossip_sim_tpu.identity import (NodeIndex, get_stake_bucket,
                                         pubkey_new_unique,
                                         reset_unique_pubkeys)
    from gossip_sim_tpu.obs import capacity
    from gossip_sim_tpu.obs.spans import get_registry
    from gossip_sim_tpu.oracle.cluster import Cluster, Node
    from gossip_sim_tpu.resilience import snapshot_to_jsonable
    from gossip_sim_tpu.sinks import DatapointQueue
    from gossip_sim_tpu.stats.gossip_stats import GossipStatsCollection

    t_start = time.time()
    failures = []

    def check(ok: bool, msg: str):
        print(f"  [{'ok' if ok else 'FAIL'}] {msg}")
        if not ok:
            failures.append(msg)

    # ---- gate 1: dense/sparse full-run bit parity at 1k -----------------
    print("[1/6] dense vs sparse CLI-run bit parity at "
          f"{args.num_nodes} nodes under loss+churn")

    def run_single(representation: str, n: int, iters: int = 8,
                   warm: int = 2):
        reset_unique_pubkeys()
        get_registry().reset()
        cfg = Config(num_synthetic_nodes=n, gossip_iterations=iters,
                     warm_up_rounds=warm, seed=args.seed,
                     packet_loss_rate=0.05, churn_fail_rate=0.02,
                     churn_recover_rate=0.2,
                     engine_representation=representation)
        coll = GossipStatsCollection()
        coll.set_number_of_simulations(1)
        dpq = DatapointQueue()
        run_simulation(cfg, "", coll, dpq, 0, "0", 0.0)
        return (coll.collection[0].parity_snapshot(),
                dpq.drain_deterministic_lines())

    snap_d, wire_d = run_single("dense", args.num_nodes)
    snap_s, wire_s = run_single("sparse", args.num_nodes)
    check(snap_d == snap_s,
          "sparse moves zero bits of the stats parity snapshot")
    check(wire_d == wire_s,
          "sparse moves zero bits of the deterministic Influx wire lines")

    # ---- gate 2: 1k-node sparse-engine-vs-oracle parity -----------------
    print(f"[2/6] sparse engine vs CPU oracle at {args.num_nodes} nodes "
          "(forced active sets, rotation off, loss+churn)")
    n = args.num_nodes
    knobs = dict(packet_loss_rate=0.1, churn_fail_rate=0.02,
                 churn_recover_rate=0.25)
    reset_unique_pubkeys()
    rng = np.random.default_rng(17)
    stakes_arr = rng.choice(np.arange(1, 50 * n), size=n,
                            replace=False).astype(np.int64) * 10**9
    accounts = {pubkey_new_unique(): int(s) for s in stakes_arr}
    index = NodeIndex.from_stakes(accounts)
    stakes_np = index.stakes.astype(np.int64)
    tables = make_cluster_tables(stakes_np)
    params = EngineParams(num_nodes=n, probability_of_rotation=0.0,
                          warm_up_rounds=0, impair_seed=args.seed,
                          representation="sparse", **knobs).validate()
    origins = jnp.asarray([0], jnp.int32)
    state = init_state(jax.random.PRNGKey(11), tables, origins, params)

    stakes_map = {pk: int(s) for pk, s in zip(index.pubkeys, stakes_np)}
    nodes = [Node(pk, stakes_map[pk]) for pk in index.pubkeys]
    origin_pk = index.pubkeys[0]
    active = np.asarray(state.active[0])
    for i, node in enumerate(nodes):
        bucket = get_stake_bucket(min(stakes_map[node.pubkey],
                                      stakes_map[origin_pk]))
        entry = node.active_set.entries[bucket]
        entry.peers = {index.pubkeys[j]: {index.pubkeys[j]}
                       for j in active[i] if j < n}
    node_map = {nd.pubkey: nd for nd in nodes}
    cluster = Cluster(params.push_fanout)
    impair = FaultInjector(index, seed=args.seed, **knobs)

    state, rows = run_rounds(params, tables, origins, state,
                             args.rounds, detail=True)
    dist_e = np.asarray(rows["dist"])[:, 0]
    failed_e = np.asarray(rows["failed_mask"])[:, 0]
    m_e = np.asarray(rows["m"])[:, 0]
    n_e = np.asarray(rows["n"])[:, 0]
    delivered_e = np.asarray(rows["delivered"])[:, 0]
    dropped_e = np.asarray(rows["dropped"])[:, 0]

    dist_ok = fail_ok = rmr_ok = impair_ok = True
    saw_drop = saw_churn = False
    for r in range(args.rounds):
        impair.begin_round(r)
        newly_failed, newly_recovered = impair.churn_step(
            r, node_map, cluster.failed_nodes)
        saw_churn |= bool(newly_failed or newly_recovered)
        cluster.run_gossip(origin_pk, stakes_map, node_map, impair)
        cluster.consume_messages(origin_pk, nodes)
        cluster.send_prunes(origin_pk, nodes,
                            params.prune_stake_threshold,
                            params.min_ingress_nodes, stakes_map)
        failed_o = np.array([node_map[pk].failed for pk in index.pubkeys])
        fail_ok &= bool(np.array_equal(failed_e[r], failed_o))
        dist_o = np.array(
            [-1 if cluster.distances[pk] == UNREACHED
             else cluster.distances[pk] for pk in index.pubkeys])
        dist_ok &= bool(np.array_equal(dist_e[r], dist_o))
        rmr_ok &= (m_e[r] == cluster.rmr.m and n_e[r] == cluster.rmr.n)
        impair_ok &= (delivered_e[r] == impair.delivered
                      and dropped_e[r] == impair.dropped)
        saw_drop |= impair.dropped > 0
        cluster.prune_connections(node_map, stakes_map)

    check(dist_ok, f"delivery distances bit-equal for {args.rounds} rounds")
    check(fail_ok, "churned failed mask bit-equal every round")
    check(rmr_ok, "RMR m/n counters bit-equal every round")
    check(impair_ok, "delivered/dropped counters bit-equal every round")
    check(saw_drop and saw_churn,
          "the regime exercised packet loss AND churn")
    check(tuple(np.asarray(state.rc_shi).shape) == (1, n, 0),
          "sparse state carries the rc stake planes at zero width")

    # ---- gate 3: dense unchanged vs the pre-PR golden -------------------
    print("[3/6] representation=dense reproduces the pre-PR golden")
    with open(GOLDEN) as f:
        golden = json.load(f)
    snap_g, wire_g = run_single("dense", 300, iters=10, warm=2)
    check(snapshot_to_jsonable(snap_g) == golden["snapshot"],
          "dense parity snapshot bit-equal to the pre-PR fixture")
    check(wire_g == golden["lines"],
          "dense Influx wire lines bit-equal to the pre-PR fixture")

    # ---- gate 4: ledger exactness at two (N, C) points ------------------
    print("[4/6] sparse capacity-ledger closed forms vs live nbytes")
    for (nn, cc) in ((500, 64), (1000, 50)):
        p = EngineParams(num_nodes=nn, rc_slots=cc, warm_up_rounds=0,
                         representation="sparse")
        rng = np.random.default_rng(0)
        sk = rng.choice(np.arange(1, 10 * nn), size=nn,
                        replace=False).astype(np.int64)
        tb = make_cluster_tables(sk)
        org = jnp.arange(3, dtype=jnp.int32)
        st = init_state(jax.random.PRNGKey(0), tb, org, p)
        entries = capacity.sim_state_entries(p, origin_batch=3)
        live = {f: getattr(st, f).nbytes for f in st._fields}
        exact = all(e.bytes == live[e.name] for e in entries)
        total_ok = sum(e.bytes for e in entries) == sum(live.values())
        check(exact and total_ok,
              f"(N={nn}, C={cc}): every ledger field == live nbytes, "
              f"totals equal")
        check(any(e.group == "sparse" for e in entries),
              f"(N={nn}, C={cc}): the 'sparse' ledger group is present")
        check(live["rc_shi"] == 0 and live["rc_slo"] == 0,
              f"(N={nn}, C={cc}): rc stake planes carry zero live bytes")

    # ---- gate 5: the 16GB all-origins wall moves ------------------------
    print("[5/6] fit_budget(16GB, all-origins): sparse beats dense")
    pd = EngineParams(num_nodes=1000, warm_up_rounds=0)
    ps = pd._replace(representation="sparse")
    budget = 16 << 30
    fit_d = capacity.fit_budget(pd, budget, origin_batch=1,
                                origins_scale_with_n=True)
    fit_s = capacity.fit_budget(ps, budget, origin_batch=1,
                                origins_scale_with_n=True)
    print(f"  dense fit: N={fit_d:,}  sparse fit: N={fit_s:,}")
    check(fit_s > fit_d, "sparse max-N strictly greater than dense")
    check(fit_s > DENSE_CEILING,
          f"sparse max-N clears the documented dense ceiling "
          f"({DENSE_CEILING:,})")

    # ---- gate 6: FORCE_I64_KEYS bit parity on an i32-bound cluster ------
    print("[6/6] i64 sort-key arms bit-equal to i32 (FORCE_I64_KEYS)")
    from gossip_sim_tpu.engine import clear_compile_cache
    from gossip_sim_tpu.engine import core as engine_core

    def run_small():
        sk = np.random.default_rng(5).choice(
            np.arange(1, 10_000), size=200, replace=False).astype(
            np.int64) * 10**9
        tb = make_cluster_tables(sk)
        pp = EngineParams(num_nodes=200, warm_up_rounds=0)
        org = jnp.arange(2, dtype=jnp.int32)
        st = init_state(jax.random.PRNGKey(7), tb, org, pp)
        _, rows = run_rounds(pp, tb, org, st, 6)
        return rows

    ref_rows = run_small()
    try:
        engine_core.FORCE_I64_KEYS = True
        clear_compile_cache()
        wide_rows = run_small()
    finally:
        engine_core.FORCE_I64_KEYS = False
        clear_compile_cache()
    i64_ok = all(np.array_equal(np.asarray(ref_rows[k]),
                                np.asarray(wide_rows[k])) for k in ref_rows)
    check(i64_ok, "every engine row bit-equal across the key widths")

    print(f"  elapsed: {time.time() - t_start:.1f}s")
    if failures:
        print(f"SPARSE SMOKE FAILED ({len(failures)} invariant(s)):")
        for f in failures:
            print(f"  - {f}")
        return 1
    print("SPARSE SMOKE PASSED")
    return 0


if __name__ == "__main__":
    sys.exit(main())
