"""Lane-sweep smoke test: the device-resident sweep-lane contract as a CI
gate (ISSUE 6).

A 3-point packet-loss sweep dispatched at ``--sweep-lanes 3`` — the whole
sweep as ONE batched engine program (engine/lanes.py) — against the serial
sweep as the reference arm, asserting:

  1. **bit-exactness** — every sweep point's per-sim statistics
     (coverage/RMR/hops/stranded/message counters) and its deterministic
     Influx wire payload are identical between the lane-batched and the
     serial dispatch.  The serial arm runs each point as its own
     run_simulation against an identical cluster (pubkey counter reset per
     sim — the methodology the batched origin-rank sweep's test
     established);
  2. **one compile total** — the lane arm builds exactly one engine
     executable for the whole sweep (``engine/compiles == 1``), where the
     serial arm compiles the warm-up-scan and measured-block shapes
     separately;
  3. **wall-clock win** — the lane dispatch completes faster end-to-end
     than the serial dispatch (it amortizes one compile, one init and one
     harvest across the K points; on accelerators the win is the point of
     the feature, on CPU it comes from the saved compile + init).

Usage: python tools/lane_smoke.py [--num-nodes 1000] [--steps 3]
       [--iterations 10] [--warm-up 4] [--seed 7] [--loss-start 0.05]
       [--loss-step 0.05]

Exit code 0 = all assertions hold; 1 = the lane contract broke.
"""
import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    ap = argparse.ArgumentParser(
        description="device-resident sweep-lane CI gate (CPU, <3 min)")
    ap.add_argument("--num-nodes", type=int, default=1000)
    ap.add_argument("--steps", type=int, default=3)
    ap.add_argument("--iterations", type=int, default=10)
    ap.add_argument("--warm-up", type=int, default=4)
    ap.add_argument("--seed", type=int, default=7)
    ap.add_argument("--loss-start", type=float, default=0.05)
    ap.add_argument("--loss-step", type=float, default=0.05)
    args = ap.parse_args()

    os.environ.setdefault("JAX_PLATFORMS", "cpu")

    from gossip_sim_tpu.cli import (_stepped_sweep_config, dispatch_sweeps,
                                    run_simulation)
    from gossip_sim_tpu.config import Config, StepSize, Testing
    from gossip_sim_tpu.engine import clear_compile_cache, clear_lane_cache
    from gossip_sim_tpu.identity import reset_unique_pubkeys
    from gossip_sim_tpu.obs import get_registry
    from gossip_sim_tpu.sinks import DatapointQueue
    from gossip_sim_tpu.stats.gossip_stats import GossipStatsCollection

    t0 = time.time()
    K = args.steps

    def config(**kw):
        return Config(num_synthetic_nodes=args.num_nodes,
                      gossip_iterations=args.iterations,
                      warm_up_rounds=args.warm_up,
                      test_type=Testing.PACKET_LOSS, num_simulations=K,
                      step_size=StepSize.parse(str(args.loss_step)),
                      packet_loss_rate=args.loss_start, seed=args.seed,
                      **kw)

    failures = []

    def check(ok: bool, msg: str):
        print(f"  [{'ok' if ok else 'FAIL'}] {msg}")
        if not ok:
            failures.append(msg)

    print(f"lane smoke: n={args.num_nodes} K={K} loss="
          f"{[round(args.loss_start + k * args.loss_step, 4) for k in range(K)]} "
          f"iters={args.iterations} (warm {args.warm_up})")

    # ---- serial reference arm: K points, identical cluster each --------
    reset_unique_pubkeys()
    get_registry().reset()
    clear_compile_cache()
    clear_lane_cache()
    cfg_s = config()
    coll_s = GossipStatsCollection()
    coll_s.set_number_of_simulations(K)
    dpq_s = DatapointQueue()
    t_serial = time.perf_counter()
    for i in range(K):
        reset_unique_pubkeys()
        c, start = _stepped_sweep_config(cfg_s, i, [1])
        run_simulation(c, "", coll_s, dpq_s, i, "0", start)
    t_serial = time.perf_counter() - t_serial
    pts_s = dpq_s.drain_deterministic_lines()

    # ---- lane arm: the whole sweep as one batched program --------------
    reset_unique_pubkeys()
    get_registry().reset()
    clear_compile_cache()
    clear_lane_cache()
    coll_l = GossipStatsCollection()
    coll_l.set_number_of_simulations(K)
    dpq_l = DatapointQueue()
    t_lane = time.perf_counter()
    dispatch_sweeps(config(sweep_lanes=K), "", [1], coll_l, dpq_l, "0")
    t_lane = time.perf_counter() - t_lane
    pts_l = dpq_l.drain_deterministic_lines()
    lane_compiles = int(get_registry().counter("engine/compiles"))

    print(f"  serial wall: {t_serial:.1f}s  lane wall: {t_lane:.1f}s")

    check(len(coll_l.collection) == K,
          f"lane sweep produced {K} per-sim stats "
          f"(got {len(coll_l.collection)})")
    # one canonical parity surface, shared with tests/test_sweep_compile
    mismatched = []
    for i, (a, b) in enumerate(zip(coll_s.collection, coll_l.collection)):
        sa, sb = a.parity_snapshot(), b.parity_snapshot()
        mismatched += [f"sim{i}:{k}" for k in sa if sa[k] != sb[k]]
    check(not mismatched,
          "per-sim stats bit-identical to the serial sweep"
          + (f" (diverged: {mismatched})" if mismatched else ""))
    check(pts_s == pts_l,
          f"Influx wire payload identical ({len(pts_l)} deterministic "
          f"points)" + ("" if pts_s == pts_l else
                        f" — serial {len(pts_s)} vs lane {len(pts_l)}"))
    check(lane_compiles == 1,
          f"one engine compile for the whole lane sweep "
          f"(got {lane_compiles})")
    check(t_lane < t_serial,
          f"lane dispatch faster than serial "
          f"({t_lane:.1f}s vs {t_serial:.1f}s)")

    dt = time.time() - t0
    print(f"  elapsed: {dt:.1f}s")
    if failures:
        print(f"LANE SMOKE FAILED ({len(failures)} invariant(s)):")
        for f in failures:
            print(f"  - {f}")
        return 1
    print("LANE SMOKE PASSED")
    return 0


if __name__ == "__main__":
    sys.exit(main())
