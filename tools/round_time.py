"""Trustworthy per-round compute timing via differential scan lengths."""
import os, sys
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

from gossip_sim_tpu.engine import (EngineParams, init_state,
                                   make_cluster_tables)
from gossip_sim_tpu.engine.core import round_step
from jax import lax
from functools import partial

N = int(sys.argv[1]) if len(sys.argv) > 1 else 2000
O = int(sys.argv[2]) if len(sys.argv) > 2 else 8

rng = np.random.default_rng(0)
stakes = (np.exp(rng.normal(9.5, 2.0, N)).astype(np.int64) + 1) * 10**9
tables = make_cluster_tables(stakes)
params = EngineParams(num_nodes=N, warm_up_rounds=0)
origins = jnp.arange(O, dtype=jnp.int32)
state = init_state(jax.random.PRNGKey(0), tables, origins, params)


@partial(jax.jit, static_argnums=(1,))
def run_k(state, k):
    def step(st, it):
        st2, rows = round_step(params, tables, origins, st, it)
        return st2, None
    st, _ = lax.scan(step, state, jnp.arange(k))
    return st.rc_upserts[0, 0] + st.active[0, 0, 0]


def timed(k, reps=3):
    int(run_k(state, k))  # compile
    best = 1e9
    for _ in range(reps):
        t0 = time.time()
        int(run_k(state, k))
        best = min(best, time.time() - t0)
    return best


t1 = timed(1)
t21 = timed(21)
per_round = (t21 - t1) / 20
print(f"N={N} O={O}: 1-round call {t1*1e3:.1f} ms, 21-round call "
      f"{t21*1e3:.1f} ms -> per-round {per_round*1e3:.2f} ms, "
      f"{O/per_round:.1f} origin-iters/s")
