"""Trustworthy per-round compute timing via differential scan lengths.

Thin CLI over the productized helpers in gossip_sim_tpu/obs/difftime.py
(the scan harness + differential timing used to live here, copy-pasted):
times a 1-round and a 21-round jitted scan and reports the slope as the
per-round cost, immune to dispatch overhead and first-call compile walls.

Usage: python tools/round_time.py [N] [O]
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from gossip_sim_tpu.engine import (EngineParams, init_state,
                                   make_cluster_tables)
from gossip_sim_tpu.obs.difftime import differential_time, make_round_scanner

N = int(sys.argv[1]) if len(sys.argv) > 1 else 2000
O = int(sys.argv[2]) if len(sys.argv) > 2 else 8

rng = np.random.default_rng(0)
stakes = (np.exp(rng.normal(9.5, 2.0, N)).astype(np.int64) + 1) * 10**9
tables = make_cluster_tables(stakes)
params = EngineParams(num_nodes=N, warm_up_rounds=0)
origins = jnp.arange(O, dtype=jnp.int32)
state = init_state(jax.random.PRNGKey(0), tables, origins, params)

run_k = make_round_scanner(params, tables, origins, state)
per_round, t1 = differential_time(run_k, k_small=1, k_large=21, reps=3)
t21 = t1 + 20 * per_round
print(f"N={N} O={O}: 1-round call {t1*1e3:.1f} ms, 21-round call "
      f"{t21*1e3:.1f} ms -> per-round {per_round*1e3:.2f} ms, "
      f"{O/per_round:.1f} origin-iters/s")
