"""Offline analysis of the ``node_health`` run-report section (obs/health.py).

Subcommands over a run-report JSON (schema gossip-sim-tpu/node-health/v1,
stamped by ``--health`` runs into ``report["node_health"]``):

  hot-nodes REPORT [...]      ranked hot-node attribution per metric: the
                              top-k list, the fraction of the metric total
                              it covers, and an exact-conservation check
                              against the run's stats block where one maps
  deciles REPORT [...]        stake-decile load table per metric + the
                              decile coverage-latency table
  imbalance REPORT [...]      load-imbalance Gini per metric, worst first
  diff REPORT_A REPORT_B      per-metric total/gini deltas and hot-node
                              set churn between two reports

Shared flags: ``--metric NAME`` (restrict to one metric; default = all),
``--json`` (machine-readable output).  ``hot-nodes`` adds ``--top K``
(truncate the printed list; attribution is computed over what is printed)
and ``--require-attribution PCT`` (exit 1 unless the ranked list covers at
least PCT percent of the metric total — the CI/acceptance hook).

Examples:

  python tools/health_report.py hot-nodes report.json --metric queue_dropped
  python tools/health_report.py hot-nodes report.json \\
      --metric queue_dropped --require-attribution 90
  python tools/health_report.py deciles report.json
  python tools/health_report.py imbalance report.json --json
  python tools/health_report.py diff base.json loss.json
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from gossip_sim_tpu.obs.health import HEALTH_SCHEMA  # noqa: E402

# health metric -> run-report stats key holding the same conserved count
# (traffic runs).  The qdrop/defer planes accumulate push AND pull sides,
# so they map to the *_ingress / *_egress stats, not the push-only ones.
_STATS_CROSSCHECK = {
    "queue_dropped": "queue_dropped_ingress",
    "deferred": "queue_deferred_egress",
}


def _load_section(path: str) -> tuple:
    """(report, node_health section) or SystemExit with a real reason."""
    try:
        with open(path) as f:
            report = json.load(f)
    except (OSError, ValueError) as e:
        raise SystemExit(f"cannot read run report {path}: {e}")
    sec = report.get("node_health")
    if not isinstance(sec, dict):
        raise SystemExit(f"{path}: no node_health section (pre-v8 report?)")
    schema = sec.get("schema")
    if schema not in (None, HEALTH_SCHEMA):
        raise SystemExit(f"{path}: unknown node_health schema {schema!r}")
    if not sec.get("enabled"):
        raise SystemExit(f"{path}: node_health disabled — rerun with "
                         "--health to populate the section")
    if not sec.get("metrics"):
        raise SystemExit(f"{path}: node_health enabled but empty")
    return report, sec


def _pick_metrics(sec: dict, metric: str | None) -> dict:
    metrics = sec["metrics"]
    if metric is None:
        return metrics
    if metric not in metrics:
        raise SystemExit(f"unknown metric {metric!r} (report has: "
                         f"{', '.join(sorted(metrics))})")
    return {metric: metrics[metric]}


# --------------------------------------------------------------------------
# hot-nodes
# --------------------------------------------------------------------------

def cmd_hot_nodes(args) -> int:
    report, sec = _load_section(args.report)
    metrics = _pick_metrics(sec, args.metric)
    stats = report.get("stats") or {}
    # traffic runs nest the conserved counters one level down
    if isinstance(stats.get("traffic"), dict):
        stats = stats["traffic"]
    out, rc = {}, 0
    for name, m in metrics.items():
        nodes = m["hot_nodes"]
        if args.top is not None:
            nodes = nodes[:args.top]
        listed = sum(int(e["count"]) for e in nodes)
        total = int(m["total"])
        frac = listed / total if total else 1.0
        ent = {
            "total": total,
            "listed": listed,
            "attribution_pct": round(100.0 * frac, 2),
            "hot_nodes": nodes,
        }
        ck = _STATS_CROSSCHECK.get(name)
        if ck in stats:
            ent["stats_key"] = ck
            ent["stats_value"] = int(stats[ck])
            ent["conserved"] = (int(stats[ck]) == total)
            if not ent["conserved"]:
                rc = 1
        if (args.require_attribution is not None
                and 100.0 * frac < args.require_attribution):
            ent["attribution_ok"] = False
            rc = 1
        out[name] = ent
    if args.json:
        print(json.dumps(out, indent=2))
        return rc
    for name, ent in out.items():
        print(f"{name}: total={ent['total']}  listed {len(ent['hot_nodes'])}"
              f" nodes cover {ent['listed']}"
              f" ({ent['attribution_pct']:.2f}%)")
        if "stats_key" in ent:
            tag = "OK" if ent["conserved"] else "MISMATCH"
            print(f"  conservation vs stats.{ent['stats_key']}: {tag} "
                  f"(section={ent['total']} stats={ent['stats_value']})")
        if ent.get("attribution_ok") is False:
            print(f"  attribution below --require-attribution "
                  f"{args.require_attribution}%")
        for rank, e in enumerate(ent["hot_nodes"]):
            share = 100.0 * e["count"] / ent["total"] if ent["total"] else 0.0
            print(f"  #{rank:<3d} node {e['node']:<6d} count {e['count']:<8d}"
                  f" {share:6.2f}%")
    return rc


# --------------------------------------------------------------------------
# deciles
# --------------------------------------------------------------------------

def cmd_deciles(args) -> int:
    _, sec = _load_section(args.report)
    metrics = _pick_metrics(sec, args.metric)
    out = {name: {"total": int(m["total"]), "deciles": m["deciles"]}
           for name, m in metrics.items()}
    lat = sec.get("latency")
    if lat:
        out["latency"] = lat
    if args.json:
        print(json.dumps(out, indent=2))
        return 0
    print(f"{'metric':<18s} " + " ".join(f"d{i:<7d}" for i in range(10)))
    for name, m in metrics.items():
        print(f"{name:<18s} "
              + " ".join(f"{int(x):<8d}" for x in m["deciles"]))
    if lat:
        print("\ndecile coverage-latency (decile 0 = lowest stake):")
        print(f"{'nodes':<18s} "
              + " ".join(f"{int(x):<8d}" for x in lat["decile_nodes"]))
        print(f"{'delivered':<18s} "
              + " ".join(f"{int(x):<8d}"
                         for x in lat["delivered_deciles"]))
        print(f"{'mean_latency':<18s} "
              + " ".join(f"{float(x):<8.3f}"
                         for x in lat["mean_latency_deciles"]))
    return 0


# --------------------------------------------------------------------------
# imbalance
# --------------------------------------------------------------------------

def cmd_imbalance(args) -> int:
    _, sec = _load_section(args.report)
    metrics = _pick_metrics(sec, args.metric)
    rows = sorted(((name, float(m["gini"]), int(m["total"]))
                   for name, m in metrics.items()),
                  key=lambda r: -r[1])
    if args.json:
        print(json.dumps([{"metric": n, "gini": g, "total": t}
                          for n, g, t in rows], indent=2))
        return 0
    print(f"{'metric':<18s} {'gini':>8s} {'total':>12s}")
    for n, g, t in rows:
        print(f"{n:<18s} {g:>8.4f} {t:>12d}")
    return 0


# --------------------------------------------------------------------------
# diff
# --------------------------------------------------------------------------

def cmd_diff(args) -> int:
    _, sa = _load_section(args.report_a)
    _, sb = _load_section(args.report_b)
    names = sorted(set(sa["metrics"]) | set(sb["metrics"]))
    if args.metric is not None:
        if args.metric not in names:
            raise SystemExit(f"unknown metric {args.metric!r}")
        names = [args.metric]
    out = {}
    for name in names:
        ma, mb = sa["metrics"].get(name), sb["metrics"].get(name)
        if ma is None or mb is None:
            out[name] = {"only_in": "B" if ma is None else "A"}
            continue
        hot_a = {e["node"] for e in ma["hot_nodes"]}
        hot_b = {e["node"] for e in mb["hot_nodes"]}
        out[name] = {
            "total_a": int(ma["total"]), "total_b": int(mb["total"]),
            "total_delta": int(mb["total"]) - int(ma["total"]),
            "gini_a": float(ma["gini"]), "gini_b": float(mb["gini"]),
            "gini_delta": round(float(mb["gini"]) - float(ma["gini"]), 6),
            "hot_entered": sorted(hot_b - hot_a),
            "hot_left": sorted(hot_a - hot_b),
        }
    if args.json:
        print(json.dumps(out, indent=2))
        return 0
    for name, d in out.items():
        if "only_in" in d:
            print(f"{name}: only in report {d['only_in']}")
            continue
        print(f"{name}: total {d['total_a']} -> {d['total_b']} "
              f"({d['total_delta']:+d}), gini {d['gini_a']:.4f} -> "
              f"{d['gini_b']:.4f} ({d['gini_delta']:+.4f})")
        if d["hot_entered"]:
            print(f"  hot-set entered: {d['hot_entered']}")
        if d["hot_left"]:
            print(f"  hot-set left:    {d['hot_left']}")
    return 0


# --------------------------------------------------------------------------

def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="health_report.py",
        description="analyze the node_health section of a run report")
    sub = ap.add_subparsers(dest="cmd", required=True)

    p = sub.add_parser("hot-nodes", help="ranked hot-node attribution")
    p.add_argument("report")
    p.add_argument("--metric", default=None)
    p.add_argument("--top", type=int, default=None,
                   help="truncate the ranked list to K nodes")
    p.add_argument("--require-attribution", type=float, default=None,
                   metavar="PCT", help="exit 1 unless the list covers "
                   "at least PCT%% of the metric total")
    p.add_argument("--json", action="store_true")

    p = sub.add_parser("deciles", help="stake-decile load + latency table")
    p.add_argument("report")
    p.add_argument("--metric", default=None)
    p.add_argument("--json", action="store_true")

    p = sub.add_parser("imbalance", help="per-metric Gini, worst first")
    p.add_argument("report")
    p.add_argument("--metric", default=None)
    p.add_argument("--json", action="store_true")

    p = sub.add_parser("diff", help="compare two reports' health sections")
    p.add_argument("report_a")
    p.add_argument("report_b")
    p.add_argument("--metric", default=None)
    p.add_argument("--json", action="store_true")

    args = ap.parse_args(argv)
    fn = {"hot-nodes": cmd_hot_nodes, "deciles": cmd_deciles,
          "imbalance": cmd_imbalance, "diff": cmd_diff}[args.cmd]
    try:
        return fn(args)
    except BrokenPipeError:  # pragma: no cover - piping into head
        return 0


if __name__ == "__main__":
    sys.exit(main())
