"""Ad-hoc stage profiler for round_step on the real chip (not shipped)."""
import os, sys
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import time

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from gossip_sim_tpu.engine import (EngineParams, init_state,
                                   make_cluster_tables, run_rounds)
from gossip_sim_tpu.engine.core import INF, _row_searchsorted

N, O = 2000, 8


def bench(name, fn, *args):
    out = jax.block_until_ready(fn(*args))
    t0 = time.time()
    for _ in range(10):
        out = jax.block_until_ready(fn(*args))
    dt = (time.time() - t0) / 10
    print(f"{name:28s} {dt*1e3:9.2f} ms")
    return out


rng = np.random.default_rng(0)
stakes = (np.exp(rng.normal(9.5, 2.0, N)).astype(np.int64) + 1) * 10**9
tables = make_cluster_tables(stakes)
params = EngineParams(num_nodes=N, warm_up_rounds=0)
origins = jnp.arange(O, dtype=jnp.int32)
state = init_state(jax.random.PRNGKey(0), tables, origins, params)
state = jax.block_until_ready(state)
p = params
S, F, C, K = p.active_set_size, p.push_fanout, p.rc_slots, p.inbound_cap

o1 = jnp.arange(O)
o2 = o1[:, None]
o3 = o1[:, None, None]
tgt = jnp.where(state.active < N, state.active, N)


@jax.jit
def full_round(st):
    from gossip_sim_tpu.engine import round_step
    return round_step(params, tables, origins, st, jnp.int32(5))


@jax.jit
def relax_loop(tgt):
    dist0 = jnp.full((O, N), INF, jnp.int32).at[o1, origins].set(0)

    def relax(carry):
        dist, _ = carry
        cand = jnp.where(dist < INF, dist + 1, INF)[:, :, None]
        cand = jnp.broadcast_to(cand, tgt.shape)
        new = dist.at[o3, tgt].min(cand, mode="drop")
        return new, jnp.any(new != dist)

    dist, _ = lax.while_loop(lambda c: c[1], relax, (dist0, jnp.bool_(True)))
    return dist


@jax.jit
def verb2_sort(tgt, dist):
    n_idx = jnp.arange(N, dtype=jnp.int32)[None, :]
    hop1 = jnp.minimum(dist + 1, 64 - 1)
    key1 = tgt.reshape(O, N * S)
    key2 = (hop1[:, :, None] * N + n_idx[:, :, None]).astype(jnp.int32)
    key2 = jnp.broadcast_to(key2, (O, N, S)).reshape(O, N * S)
    tgt_s, key2_s = lax.sort((key1, key2), dimension=-1, num_keys=2)
    return tgt_s, key2_s


@jax.jit
def rc_merge(tgt_s, key2_s):
    src_s = key2_s % N
    eidx = jnp.arange(N * S, dtype=jnp.int32)[None, :]
    is_start = jnp.concatenate(
        [jnp.ones((O, 1), bool), tgt_s[:, 1:] != tgt_s[:, :-1]], axis=1)
    seg_start = lax.cummax(jnp.where(is_start, eidx, 0), axis=1)
    rank = eidx - seg_start
    inb = jnp.full((O, N, K), N, jnp.int32).at[
        o2, tgt_s, rank].set(src_s, mode="drop")
    rc_src, rc_score = state.rc_src, state.rc_score
    pos = _row_searchsorted(rc_src, inb)
    return inb, pos


@jax.jit
def prune_sort(rc_src, rc_score):
    member = rc_src < N
    m_stake = tables.stakes[rc_src]
    neg_score = jnp.where(member, -rc_score, jnp.iinfo(jnp.int32).max)
    neg_stake = jnp.where(member, -m_stake, jnp.iinfo(jnp.int64).max)
    _, _, src_sorted = lax.sort(
        (neg_score, neg_stake, rc_src), dimension=-1, num_keys=3)
    return src_sorted


@jax.jit
def prune_sort_i32(rc_src, rc_score):
    member = rc_src < N
    m_stake = tables.stakes[rc_src]
    # rank stakes as i32 surrogate
    neg_score = jnp.where(member, -rc_score, jnp.iinfo(jnp.int32).max)
    neg_stake = jnp.where(member, -(m_stake >> 20).astype(jnp.int32),
                          jnp.iinfo(jnp.int32).max)
    _, _, src_sorted = lax.sort(
        (neg_score, neg_stake, rc_src), dimension=-1, num_keys=3)
    return src_sorted


st1, rows = bench("full_round", full_round, state)
dist = bench("relax_loop", relax_loop, tgt)
tgt_s, key2_s = bench("verb2_sort", verb2_sort, tgt, dist)
bench("rc_merge(partial)", rc_merge, tgt_s, key2_s)
bench("prune_sort(i64keys)", prune_sort, state.rc_src, state.rc_score)
bench("prune_sort(i32keys)", prune_sort_i32, state.rc_src, state.rc_score)
