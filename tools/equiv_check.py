"""Bit-exactness check: engine.core (v1) vs engine.core2 (sort-routed v2).

Historical validation tool for the v2 engine swap: it ran (and passed, all
configs) at the revision where both ``engine/core.py`` (scatter/gather v1)
and ``engine/core2.py`` (sort-routed v2) coexisted; check that revision out
to re-run.  Both engines share RNG stream structure, so every row and every
common state field must match exactly, round by round.

Run with JAX_PLATFORMS=cpu.
"""
import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax
import jax.numpy as jnp
import numpy as np

from gossip_sim_tpu.engine import core as c1
from gossip_sim_tpu.engine import core2 as c2
from gossip_sim_tpu.engine.params import EngineParams


def check(n=60, n_origins=3, rounds=45, seed=7, **kw):
    rng = np.random.default_rng(0)
    stakes = rng.choice(np.arange(1, 50 * n), size=n, replace=False).astype(
        np.int64) * 1_000_000_000
    params = EngineParams(num_nodes=n, warm_up_rounds=0, **kw)
    origins = jnp.arange(n_origins, dtype=jnp.int32)

    t1 = c1.make_cluster_tables(stakes)
    t2 = c2.make_cluster_tables(stakes)
    s1 = c1.init_state(jax.random.PRNGKey(seed), t1, origins, params)
    s2 = c2.init_state(jax.random.PRNGKey(seed), t2, origins, params)
    np.testing.assert_array_equal(np.asarray(s1.active), np.asarray(s2.active),
                                  err_msg="init active diverges")

    for r in range(rounds):
        s1, r1 = c1.round_step(params, t1, origins, s1, jnp.int32(r),
                               detail=True)
        s2, r2 = c2.round_step(params, t2, origins, s2, jnp.int32(r),
                               detail=True)
        for k in r1:
            np.testing.assert_array_equal(
                np.asarray(r1[k]), np.asarray(r2[k]),
                err_msg=f"row {k!r} diverges at round {r} ({kw})")
        for f in ("active", "pruned", "rc_src", "rc_score", "rc_upserts",
                  "failed", "egress_acc", "ingress_acc", "prune_acc",
                  "stranded_acc", "hops_hist_acc"):
            np.testing.assert_array_equal(
                np.asarray(getattr(s1, f)), np.asarray(getattr(s2, f)),
                err_msg=f"state {f!r} diverges after round {r} ({kw})")
        # v2-only invariants
        tf = np.asarray(s2.tfail)
        act = np.asarray(s2.active)
        fl = np.asarray(s2.failed)
        exp = np.zeros_like(tf)
        for o in range(n_origins):
            m = act[o] < n
            exp[o][m] = fl[o][np.minimum(act[o], n - 1)][m]
        np.testing.assert_array_equal(tf, exp,
                                      err_msg=f"tfail invariant at {r}")
        st = np.asarray(s2.rc_shi).astype(np.int64) << 31
        st |= np.asarray(s2.rc_slo).astype(np.int64)
        src = np.asarray(s2.rc_src)
        m = src < n
        np.testing.assert_array_equal(
            st[m], stakes[src[m]], err_msg=f"rc stake payload at {r}")
    print(f"OK rounds={rounds} {kw or ''}")


if __name__ == "__main__":
    check()
    check(probability_of_rotation=0.5, rounds=30)
    check(fail_at=5, fail_fraction=0.25, rounds=20)
    check(inbound_cap=4, rc_slots=16, received_cap=12, rounds=30)
    check(pa_slots=1, rounds=45)  # force the prune-apply fallback path
    print("ALL EQUIVALENCE CHECKS PASSED")
