"""Kill-and-resume smoke test: the resilient-execution contract as a CI
gate (ISSUE 7).

A 6-point lane-batched packet-loss sweep is run four ways, each in its
own subprocess (real process death is the thing under test):

  plain      no journal, no watchdog — the reference arm
  guarded    --checkpoint-path journal + --device-timeout-s watchdog,
             uninterrupted — must be bit-identical to plain, and the
             resilience layer must add < --overhead-budget (2%) + slack
             wall-clock on a warm engine.  The overhead is measured in
             ONE process alternating plain/guarded sweeps against the
             warm jit cache (min-of-3 each): cross-process comparisons
             on a shared CI box see 2x compile-time scheduling swings
             that would swamp a 2% bar, and warm dispatch is the regime
             an hours-long production run actually lives in
  killed     journal + GOSSIP_RESILIENCE_KILL_AFTER_UNITS=1: the worker
             SIGTERMs itself after the first committed lane batch and
             must exit with the resumable code (75)
  resumed    --resume of the killed run — must reproduce plain's per-sim
             parity snapshots and deterministic Influx wire payload
             bit-exactly, with ZERO persistent-compilation-cache misses
             (the killed arm's XLA cache serves every compile, so resume
             pays no recompiles)

Usage: python tools/resume_smoke.py [--num-nodes 600] [--steps 6]
       [--iterations 10] [--warm-up 4] [--seed 11]
       [--overhead-budget 0.02] [--overhead-slack-s 0.5]

Exit code 0 = the resilience contract holds; 1 = it broke.
"""
import argparse
import json
import os
import subprocess
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

RESUMABLE = 75


def worker(args) -> int:
    """One sweep run in this process; writes a result JSON on completion.
    Exits with the resumable code when gracefully interrupted."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    from gossip_sim_tpu import resilience
    from gossip_sim_tpu.cli import dispatch_sweeps
    from gossip_sim_tpu.config import Config, StepSize, Testing
    from gossip_sim_tpu.engine.cache import persistent_cache_counters
    from gossip_sim_tpu.identity import reset_unique_pubkeys
    from gossip_sim_tpu.obs import get_registry
    from gossip_sim_tpu.resilience import snapshot_to_jsonable
    from gossip_sim_tpu.sinks import DatapointQueue
    from gossip_sim_tpu.stats.gossip_stats import GossipStatsCollection

    reset_unique_pubkeys()
    get_registry().reset()
    resilience.reset_shutdown()
    cfg = Config(num_synthetic_nodes=args.num_nodes,
                 gossip_iterations=args.iterations,
                 warm_up_rounds=args.warm_up,
                 test_type=Testing.PACKET_LOSS,
                 num_simulations=args.steps,
                 step_size=StepSize.parse("0.05"),
                 packet_loss_rate=0.05, seed=args.seed,
                 sweep_lanes=2,
                 checkpoint_path=args.checkpoint,
                 resume_path=args.resume,
                 device_timeout_s=args.device_timeout_s,
                 compilation_cache_dir=args.cache_dir)
    coll = GossipStatsCollection()
    coll.set_number_of_simulations(args.steps)
    dpq = DatapointQueue()
    t0 = time.perf_counter()
    try:
        with resilience.signal_guard():
            dispatch_sweeps(cfg, "", [1], coll, dpq, "0")
    except resilience.ResumableInterrupt:
        return RESUMABLE
    wall = time.perf_counter() - t0
    reg = get_registry()
    result = {
        "wall_s": wall,
        "snapshots": [snapshot_to_jsonable(s.parity_snapshot())
                      for s in coll.collection],
        "lines": dpq.drain_deterministic_lines(),
        "compiles": int(reg.counter("engine/compiles")),
        "resumed_units": int(reg.counter("resilience/resumed_units")),
        "cache": persistent_cache_counters(),
    }
    with open(args.out, "w") as f:
        json.dump(result, f)
    return 0


def worker_overhead(args) -> int:
    """Alternate plain / journal+watchdog sweeps in ONE process against
    the warm jit cache; report min walls.  Writes {plain_s, guarded_s}."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    from gossip_sim_tpu import resilience
    from gossip_sim_tpu.cli import dispatch_sweeps
    from gossip_sim_tpu.config import Config, StepSize, Testing
    from gossip_sim_tpu.identity import reset_unique_pubkeys
    from gossip_sim_tpu.obs import get_registry
    from gossip_sim_tpu.sinks import DatapointQueue
    from gossip_sim_tpu.stats.gossip_stats import GossipStatsCollection

    tmp = os.path.dirname(args.out)

    def one(guarded: bool, i: int) -> float:
        reset_unique_pubkeys()
        resilience.reset_shutdown()
        kw = {}
        if guarded:
            kw = dict(checkpoint_path=os.path.join(tmp, f"oh{i}.npz"),
                      device_timeout_s=600.0)
        cfg = Config(num_synthetic_nodes=args.num_nodes,
                     gossip_iterations=args.iterations,
                     warm_up_rounds=args.warm_up,
                     test_type=Testing.PACKET_LOSS,
                     num_simulations=args.steps,
                     step_size=StepSize.parse("0.05"),
                     packet_loss_rate=0.05, seed=args.seed,
                     sweep_lanes=2, **kw)
        coll = GossipStatsCollection()
        coll.set_number_of_simulations(args.steps)
        t0 = time.perf_counter()
        dispatch_sweeps(cfg, "", [1], coll, DatapointQueue(), "0")
        return time.perf_counter() - t0

    get_registry().reset()
    one(False, 0)                      # compile carrier, untimed
    plain, guarded = [], []
    for i in range(3):                 # interleaved: shared box noise
        plain.append(one(False, i))    # hits both arms alike
        guarded.append(one(True, i))
    with open(args.out, "w") as f:
        json.dump({"plain_s": min(plain), "guarded_s": min(guarded),
                   "plain_all": plain, "guarded_all": guarded}, f)
    return 0


def main() -> int:
    ap = argparse.ArgumentParser(
        description="kill-and-resume CI gate (CPU, <3 min)")
    ap.add_argument("--num-nodes", type=int, default=600)
    ap.add_argument("--steps", type=int, default=6)
    ap.add_argument("--iterations", type=int, default=10)
    ap.add_argument("--warm-up", type=int, default=4)
    ap.add_argument("--seed", type=int, default=11)
    ap.add_argument("--overhead-budget", type=float, default=0.02,
                    help="max fractional journal+watchdog overhead on an "
                         "uninterrupted run (default 2%%)")
    ap.add_argument("--overhead-slack-s", type=float, default=0.3,
                    help="absolute slack on the overhead bar (CI-box "
                         "scheduling noise)")
    # worker modes (internal)
    ap.add_argument("--worker", action="store_true")
    ap.add_argument("--worker-overhead", action="store_true")
    ap.add_argument("--checkpoint", default="")
    ap.add_argument("--resume", default="")
    ap.add_argument("--cache-dir", default="")
    ap.add_argument("--device-timeout-s", type=float, default=0.0)
    ap.add_argument("--out", default="")
    args = ap.parse_args()
    if args.worker:
        return worker(args)
    if args.worker_overhead:
        return worker_overhead(args)

    t0 = time.time()
    tmp = tempfile.mkdtemp(prefix="resume-smoke-")
    failures = []

    def check(ok, msg):
        print(f"  [{'ok' if ok else 'FAIL'}] {msg}")
        if not ok:
            failures.append(msg)

    def run(name, extra, env_extra=None):
        out = os.path.join(tmp, f"{name}.json")
        env = dict(os.environ)
        env.setdefault("JAX_PLATFORMS", "cpu")
        if env_extra:
            env.update(env_extra)
        cmd = [sys.executable, os.path.abspath(__file__), "--worker",
               "--num-nodes", str(args.num_nodes),
               "--steps", str(args.steps),
               "--iterations", str(args.iterations),
               "--warm-up", str(args.warm_up),
               "--seed", str(args.seed), "--out", out] + extra
        t = time.perf_counter()
        rc = subprocess.run(cmd, env=env).returncode
        wall = time.perf_counter() - t
        result = None
        if os.path.exists(out):
            with open(out) as f:
                result = json.load(f)
        return rc, wall, result

    print(f"resume smoke: n={args.num_nodes} K={args.steps} lanes=2 "
          f"iters={args.iterations} (warm {args.warm_up})")
    ck = os.path.join(tmp, "sweep.npz")
    cache = os.path.join(tmp, "xla-cache")

    # 1. reference arm
    rc_plain, _, plain = run("plain", [])
    check(rc_plain == 0 and plain is not None, "plain arm completed")

    # 2. guarded, uninterrupted: bit-exact parity
    rc_g, _, guarded = run(
        "guarded", ["--checkpoint", os.path.join(tmp, "guarded.npz"),
                    "--device-timeout-s", "600"])
    check(rc_g == 0 and guarded is not None, "guarded arm completed")
    if plain and guarded:
        check(guarded["snapshots"] == plain["snapshots"]
              and guarded["lines"] == plain["lines"],
              "journal + watchdog change no bit of output")

    # 3. overhead: plain vs guarded alternated warm in one process
    out = os.path.join(tmp, "overhead.json")
    rc_o = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--worker-overhead",
         "--num-nodes", str(args.num_nodes), "--steps", str(args.steps),
         "--iterations", str(args.iterations),
         "--warm-up", str(args.warm_up), "--seed", str(args.seed),
         "--out", out],
        env={**os.environ, "JAX_PLATFORMS":
             os.environ.get("JAX_PLATFORMS", "cpu")}).returncode
    check(rc_o == 0 and os.path.exists(out), "overhead worker completed")
    if rc_o == 0 and os.path.exists(out):
        with open(out) as f:
            oh = json.load(f)
        budget = oh["plain_s"] * (1.0 + args.overhead_budget) \
            + args.overhead_slack_s
        check(oh["guarded_s"] <= budget,
              f"resilience overhead within {args.overhead_budget:.0%} "
              f"(+{args.overhead_slack_s}s slack, warm min-of-3): "
              f"{oh['guarded_s']:.2f}s vs plain {oh['plain_s']:.2f}s "
              f"(budget {budget:.2f}s)")

    # 3. kill mid-run: SIGTERM after the first committed lane batch
    rc_k, _, _ = run("killed", ["--checkpoint", ck, "--cache-dir", cache],
                     env_extra={"GOSSIP_RESILIENCE_KILL_AFTER_UNITS": "1"})
    check(rc_k == RESUMABLE,
          f"killed arm exited with the resumable code ({rc_k} == "
          f"{RESUMABLE})")
    journal = ck[:-len(".npz")] + ".journal"
    committed = 0
    if os.path.exists(journal):
        with open(journal) as f:
            committed = max(0, len(f.read().splitlines()) - 1)
    check(committed == 1, f"exactly one lane batch committed ({committed})")

    # 4. resume: bit-exact, no recompiles (warm persistent cache)
    rc_r, _, resumed = run("resumed",
                           ["--checkpoint", ck, "--resume", ck,
                            "--cache-dir", cache])
    check(rc_r == 0 and resumed is not None, "resumed arm completed")
    if plain and resumed:
        check(resumed["snapshots"] == plain["snapshots"],
              "resumed per-sim parity snapshots bit-identical to an "
              "uninterrupted run")
        check(resumed["lines"] == plain["lines"],
              f"resumed Influx wire payload bit-identical "
              f"({len(plain['lines'])} deterministic points)")
        check(resumed["resumed_units"] == 1,
              f"one unit replayed from the journal "
              f"({resumed['resumed_units']})")
        cache_stats = resumed.get("cache", {})
        check(cache_stats.get("misses", -1) == 0
              and cache_stats.get("hits", 0) >= 1,
              f"zero persistent-cache misses on resume (no recompiles): "
              f"{cache_stats}")

    dt = time.time() - t0
    print(f"  elapsed: {dt:.1f}s")
    if failures:
        print(f"RESUME SMOKE FAILED ({len(failures)} invariant(s)):")
        for f in failures:
            print(f"  - {f}")
        return 1
    print("RESUME SMOKE PASSED")
    return 0


if __name__ == "__main__":
    sys.exit(main())
