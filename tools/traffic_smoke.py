"""Concurrent-traffic smoke test: the traffic subsystem's CI gate
(traffic.py / engine/traffic.py, ISSUE 10).

Fast CPU gate (~2-3 min) over three contracts:

  1. **M=1 zero bit-impact**: with traffic_values=1 and both queue caps
     off, a single-origin run through the CLI stats path is bit-identical
     to the pre-traffic engine — parity snapshot AND deterministic Influx
     wire lines — even with every *other* traffic knob (rate, stall) set
     to nonsense: the subsystem must be invisible when off.
  2. **1k-node oracle parity under caps**: the sort-routed traffic engine
     and the loop-based TrafficOracle produce bit-identical TrafficStats
     (per-round counters, retirement records, wire lines) through the full
     CLI path under packet loss + churn + both queue caps with shared
     rotation ON.
  3. **Per-value coverage monotone in the ingress cap**: lifting the
     ingress budget must never deliver less — total first deliveries and
     mean per-value coverage are non-decreasing across cap 1 -> 2 ->
     unlimited (same seed, prune feedback negligible at this scale).

Usage: python tools/traffic_smoke.py [--num-nodes 1000] [--seed 11]
       [--traffic-values 8] [--iterations 8]

Exit code 0 = all gates hold; 1 = a traffic invariant failed.
"""
import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    ap = argparse.ArgumentParser(
        description="concurrent-traffic subsystem smoke (CPU)")
    ap.add_argument("--num-nodes", type=int, default=1000)
    ap.add_argument("--seed", type=int, default=11)
    ap.add_argument("--traffic-values", type=int, default=8)
    ap.add_argument("--traffic-rate", type=int, default=2)
    ap.add_argument("--ingress-cap", type=int, default=24)
    # low enough that a sender holding most live values (8 values x
    # fanout 6 = 48 candidates) overflows it — real egress deferral
    ap.add_argument("--egress-cap", type=int, default=32)
    ap.add_argument("--packet-loss", type=float, default=0.1)
    ap.add_argument("--churn-fail", type=float, default=0.02)
    ap.add_argument("--churn-recover", type=float, default=0.25)
    ap.add_argument("--iterations", type=int, default=8)
    args = ap.parse_args()

    os.environ.setdefault("JAX_PLATFORMS", "cpu")

    from gossip_sim_tpu.config import Config
    from gossip_sim_tpu.cli import run_simulation, run_traffic
    from gossip_sim_tpu.identity import reset_unique_pubkeys
    from gossip_sim_tpu.obs import get_registry
    from gossip_sim_tpu.sinks import DatapointQueue
    from gossip_sim_tpu.stats.gossip_stats import GossipStatsCollection
    from gossip_sim_tpu.stats.traffic import TrafficStatsCollection

    t0 = time.time()
    failures = []

    def check(ok: bool, msg: str):
        print(f"  [{'ok' if ok else 'FAIL'}] {msg}")
        if not ok:
            failures.append(msg)

    print(f"traffic smoke: n={args.num_nodes} M={args.traffic_values} "
          f"rate={args.traffic_rate} caps=({args.ingress_cap},"
          f"{args.egress_cap}) loss={args.packet_loss} "
          f"iters={args.iterations}")

    # ---- gate 1: traffic off (M=1, caps 0) has zero bit-impact ----------
    def run_single(cfg):
        reset_unique_pubkeys()
        get_registry().reset()
        coll = GossipStatsCollection()
        coll.set_number_of_simulations(1)
        dpq = DatapointQueue()
        run_simulation(cfg, "", coll, dpq, 0, "0", 0.0)
        return (coll.collection[0].parity_snapshot(),
                dpq.drain_deterministic_lines())

    base = Config(num_synthetic_nodes=200, gossip_iterations=8,
                  warm_up_rounds=2, seed=args.seed)
    # inert traffic knobs: traffic stays OFF, so they must not move a bit
    inert = Config(num_synthetic_nodes=200, gossip_iterations=8,
                   warm_up_rounds=2, seed=args.seed,
                   traffic_values=1, node_ingress_cap=0, node_egress_cap=0,
                   traffic_rate=7, traffic_stall_rounds=99)
    snap_a, wire_a = run_single(base)
    snap_b, wire_b = run_single(inert)
    check(not inert.traffic_on, "traffic_values=1 with caps off keeps the "
                                "subsystem gated out")
    check(snap_a == snap_b, "M=1/caps-off run is bit-identical to the "
                            "pre-traffic engine (stats parity snapshot)")
    check(wire_a == wire_b, "M=1/caps-off Influx wire lines are "
                            "bit-identical")

    # ---- gate 2: 1k-node engine-vs-oracle parity through the CLI --------
    def run_traffic_cfg(cfg):
        reset_unique_pubkeys()
        get_registry().reset()
        coll = TrafficStatsCollection()
        dpq = DatapointQueue()
        run_traffic(cfg, "", dpq, "0", collection=coll)
        return coll.collection, dpq.drain_deterministic_lines()

    tbase = dict(num_synthetic_nodes=args.num_nodes,
                 traffic_values=args.traffic_values,
                 traffic_rate=args.traffic_rate,
                 node_ingress_cap=args.ingress_cap,
                 node_egress_cap=args.egress_cap,
                 packet_loss_rate=args.packet_loss,
                 churn_fail_rate=args.churn_fail,
                 churn_recover_rate=args.churn_recover,
                 gossip_iterations=args.iterations, warm_up_rounds=0,
                 seed=args.seed)
    coll_t, wire_t = run_traffic_cfg(Config(**tbase))
    coll_o, wire_o = run_traffic_cfg(Config(backend="oracle", **tbase))
    sn_t = coll_t[0].parity_snapshot()
    sn_o = coll_o[0].parity_snapshot()
    check(sn_t == sn_o,
          f"engine bit-matches TrafficOracle at n={args.num_nodes}, "
          f"M={args.traffic_values} under loss+churn+caps "
          f"(rotation ON)")
    check(wire_t == wire_o, "both backends emit identical sim_traffic "
                            "wire payloads")
    qd = sum(sn_t["rounds"]["queue_dropped"])
    df = sum(sn_t["rounds"]["deferred"])
    check(qd > 0 and df > 0,
          f"the cap regime creates real contention "
          f"(queue_dropped={qd}, deferred={df})")

    # ---- gate 3: per-value coverage monotone in the ingress cap ---------
    delivered, coverage = [], []
    for cap in (1, 2, 0):
        cfg = Config(**{**tbase, "num_synthetic_nodes": 200,
                        "node_ingress_cap": cap, "node_egress_cap": 0,
                        "packet_loss_rate": 0.0, "churn_fail_rate": 0.0,
                        "churn_recover_rate": 0.0,
                        "gossip_iterations": 10})
        coll, _ = run_traffic_cfg(cfg)
        s = coll[0]
        delivered.append(sum(s.rounds["delivered"]))
        summ = s.summary()
        coverage.append((summ["value_coverage_mean"], summ["values_retired"]))
    print(f"  ingress cap 1 -> 2 -> off: delivered={delivered} "
          f"(coverage_mean, retired)={coverage}")
    check(delivered[0] <= delivered[1] <= delivered[2],
          f"first deliveries monotone in ingress cap {delivered}")

    dt = time.time() - t0
    print(f"  elapsed: {dt:.1f}s")
    if failures:
        print(f"TRAFFIC SMOKE FAILED ({len(failures)} invariant(s)):")
        for f in failures:
            print(f"  - {f}")
        return 1
    print("TRAFFIC SMOKE PASSED")
    return 0


if __name__ == "__main__":
    sys.exit(main())
