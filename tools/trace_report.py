"""Offline analysis of protocol flight-recorder traces (obs/trace.py).

Subcommands over a ``--trace-dir`` capture (schema gossip-sim-tpu/trace/v2;
v1 traces load too — they just carry no pull arrays):

  info DIR                      manifest summary + on-disk validation
  tree DIR [--round R]          reconstruct + render the delivery tree
  explain-stranded DIR [...]    root-cause every stranded node of a round
  attribute-rmr DIR [--top K]   top-K redundant edges behind the RMR
  diff DIR_A DIR_B [...]        edge-by-edge delivered-set diff of two traces
  hot-nodes DIR [...]           recompute per-node egress/ingress/drop counts
                                from the trace; --checkpoint cross-checks
                                them against the engine's accumulator planes

Shared flags: ``--round R`` (absolute round index; default = last traced),
``--col C`` (origin column for multi-origin traces; default 0), ``--json``
(machine-readable output where supported).

Examples:

  python tools/trace_report.py info /tmp/trace
  python tools/trace_report.py tree /tmp/trace --round 210
  python tools/trace_report.py explain-stranded /tmp/trace --json
  python tools/trace_report.py attribute-rmr /tmp/trace --top 10
  python tools/trace_report.py diff /tmp/base /tmp/loss --top 5
  python tools/trace_report.py hot-nodes /tmp/trace --checkpoint run.npz
"""

import argparse
import json
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from gossip_sim_tpu.obs.trace import (load_trace,  # noqa: E402
                                      validate_trace_dir)
from gossip_sim_tpu.stats import edges as E  # noqa: E402


def _round_and_col(trace, args):
    rnd = args.round if args.round is not None else int(trace.rounds[-1])
    col = args.col
    if not 0 <= col < len(trace.origins):
        raise SystemExit(f"--col {col} out of range (trace has "
                         f"{len(trace.origins)} origin column(s))")
    return rnd, col


def _round_slice(trace, rnd, col):
    at = trace.at(rnd)
    return {name: arr[col] for name, arr in at.items()}


# --------------------------------------------------------------------------
# info
# --------------------------------------------------------------------------

def cmd_info(args) -> int:
    problems = validate_trace_dir(args.trace_dir)
    if problems:
        print(f"INVALID trace in {args.trace_dir}:")
        for p in problems:
            print(f"  - {p}")
        return 1
    tr = load_trace(args.trace_dir)
    m = tr.manifest
    print(f"trace: {args.trace_dir}  [{m['schema']}]  VALID")
    print(f"  backend={m['backend']} num_nodes={m['num_nodes']} "
          f"fanout={m['push_fanout']} active_set={m['active_set_size']} "
          f"seed={m['seed']}")
    print(f"  origins ({len(m['origins'])}): {m['origins']}")
    print(f"  rounds traced: {len(tr)} "
          f"[{int(tr.rounds[0])}..{int(tr.rounds[-1])}] in "
          f"{len(m['segments'])} segment(s)"
          + (f"  GAPS: {tr.gaps}" if tr.gaps else ""))
    cov = tr.arrays["coverage"]
    dist = tr.arrays["dist"]
    failed = tr.arrays["failed"]
    stranded = ((dist < 0) & ~failed).sum(axis=-1)      # [T, O]
    print(f"  coverage mean={cov.mean():.6f} min={cov.min():.6f}; "
          f"stranded mean/round={stranded.mean():.2f} "
          f"max={int(stranded.max())}")
    trunc = [r for seg in m["segments"]
             for r in seg.get("truncated_prune_rounds", [])]
    if trunc:
        print(f"  WARNING: prune capture truncated in round(s) {trunc}")
    return 0


# --------------------------------------------------------------------------
# tree
# --------------------------------------------------------------------------

def cmd_tree(args) -> int:
    tr = load_trace(args.trace_dir)
    rnd, col = _round_and_col(tr, args)
    origin = tr.origins[col]
    s = _round_slice(tr, rnd, col)
    parent, ok = E.build_delivery_tree(s["first_src"], s["dist"], origin)
    dist = s["dist"]
    reached = dist >= 0
    print(f"delivery tree: round {rnd}, origin {origin} "
          f"({int(reached.sum())}/{tr.num_nodes} reached, "
          f"root {'OK' if ok else 'BROKEN'})")
    depth_counts = np.bincount(dist[reached])
    for h, c in enumerate(depth_counts):
        print(f"  hop {h}: {int(c)} node(s)")
    if not ok:
        print("  ERROR: recorded first deliveries do not form a tree "
              "rooted at the origin")
        return 1
    children = {}
    for n in np.nonzero(parent >= 0)[0]:
        children.setdefault(int(parent[n]), []).append(int(n))
    lines = []

    def walk(node, depth):
        if len(lines) >= args.max_nodes:
            return
        lines.append("  " + "  " * depth + f"{node} (hop {int(dist[node])})")
        for c in sorted(children.get(node, [])):
            walk(c, depth + 1)

    walk(int(origin), 0)
    print("\n".join(lines))
    if len(lines) >= args.max_nodes:
        print(f"  ... truncated at --max-nodes {args.max_nodes}")
    return 0


# --------------------------------------------------------------------------
# explain-stranded
# --------------------------------------------------------------------------

def cmd_explain_stranded(args) -> int:
    tr = load_trace(args.trace_dir)
    is_traffic = int(tr.manifest.get("traffic_slots") or 0) > 0
    vid = None
    if is_traffic:
        # traffic (v3+) traces: --col selects the VALUE SLOT; the shared
        # active set + the slot's per-value arrays slice straight into
        # explain_stranded, and (v4 adaptive) the slot's pull_hop column
        # attributes this round's pull rescues to the value
        rnd = args.round if args.round is not None else int(tr.rounds[-1])
        at = tr.at(rnd)
        v = args.col
        V = int(tr.manifest["traffic_slots"])
        if not 0 <= v < V:
            raise SystemExit(f"--col {v} out of range (trace has {V} "
                             f"value slot(s))")
        vid = int(at["value_id"][v])
        if vid < 0:
            raise SystemExit(f"value slot {v} is free at round {rnd}; "
                             f"pick a live slot (value_id >= 0)")
        origin = int(at["value_origin"][v])
        pull_hop = (at["pull_hop"][v] if "pull_hop" in at else None)
        explained = E.explain_stranded(
            at["active"], at["pruned"][v], at["peers"][v], at["code"][v],
            at["dist"][v], at["failed"], origin, pull_hop=pull_hop)
    else:
        rnd, col = _round_and_col(tr, args)
        origin = tr.origins[col]
        s = _round_slice(tr, rnd, col)
        # v2 pull traces: pass the pull hops so push-stranded nodes that a
        # pull response rescued are tagged rescued_by_pull, not stranded
        explained = E.explain_stranded(s["active"], s["pruned"], s["peers"],
                                       s["code"], s["dist"], s["failed"],
                                       origin, pull_hop=s.get("pull_hop"))
    if args.json:
        out = {"round": rnd, "origin": origin, "stranded": explained}
        if vid is not None:
            out["value_id"] = vid
            out["value_slot"] = args.col
        print(json.dumps(out, indent=2))
        return 0
    n_rescued = sum(1 for ent in explained
                    if E.CAUSE_RESCUED_BY_PULL in ent["summary"])
    tag = (f" ({n_rescued} rescued by pull)" if n_rescued else "")
    what = (f"value {vid} (slot {args.col})" if vid is not None
            else f"origin {origin}")
    print(f"stranded nodes: round {rnd}, {what} -> "
          f"{len(explained) - n_rescued} stranded{tag}")
    for ent in explained:
        causes = ent["summary"]
        top = ", ".join(f"{k}={v}" for k, v in
                        sorted(causes.items(), key=lambda kv: -kv[1]))
        print(f"  node {ent['node']}: {top}")
        if args.verbose:
            for c in ent["causes"]:
                print(f"    sender {c['sender']} slot {c['slot']}: "
                      f"{c['cause']}")
    return 0


# --------------------------------------------------------------------------
# attribute-rmr
# --------------------------------------------------------------------------

def cmd_attribute_rmr(args) -> int:
    tr = load_trace(args.trace_dir)
    _, col = _round_and_col(tr, args)
    # --round restricts attribution to one round; default = all traced
    positions = ([tr.pos_of(args.round)] if args.round is not None
                 else range(len(tr)))
    n = tr.num_nodes
    totals = {}
    total_delivered = total_redundant = total_prunes = 0
    for t in positions:
        peers = tr.arrays["peers"][t, col]
        code = tr.arrays["code"][t, col]
        dist = tr.arrays["dist"][t, col]
        first = tr.arrays["first_src"][t, col]
        total_delivered += E.delivered_edges(peers, code, dist).shape[0]
        total_prunes += int(tr.arrays["prunes_total"][t, col])
        for edge, c in E.redundant_edge_counts(peers, code, dist, first,
                                               n).items():
            totals[edge] = totals.get(edge, 0) + c
            total_redundant += c
    top = sorted(totals.items(), key=lambda kv: -kv[1])[:args.top]
    if args.json:   # machine-readable only, like explain-stranded
        print(json.dumps({"rounds": len(list(positions)),
                          "origin": tr.origins[col],
                          "delivered": total_delivered,
                          "redundant": total_redundant,
                          "prunes": total_prunes,
                          "top": [{"src": s_, "dst": d, "count": c}
                                  for (s_, d), c in top]}, indent=2))
        return 0
    print(f"RMR attribution over {len(list(positions))} traced round(s), "
          f"origin {tr.origins[col]}:")
    print(f"  delivered={total_delivered} redundant={total_redundant} "
          f"prune_messages={total_prunes}")
    print(f"  (RMR's numerator m = delivered + prunes; redundancy = "
          f"deliveries beyond each receiver's first)")
    print(f"  top {len(top)} redundant edges (src -> dst: rounds redundant):")
    for (src, dst), c in top:
        print(f"    {src} -> {dst}: {c}")
    return 0


# --------------------------------------------------------------------------
# diff
# --------------------------------------------------------------------------

def cmd_diff(args) -> int:
    a = load_trace(args.trace_dir)
    b = load_trace(args.trace_dir_b)
    if a.num_nodes != b.num_nodes:
        raise SystemExit(f"traces disagree on num_nodes: {a.num_nodes} vs "
                         f"{b.num_nodes}")
    _, col = _round_and_col(a, args)
    if not 0 <= col < len(b.origins):
        raise SystemExit(f"--col {col} out of range for trace B "
                         f"({len(b.origins)} origin column(s))")
    if a.origins[col] != b.origins[col]:
        raise SystemExit(
            f"column {col} records different origins: {a.origins[col]} (A) "
            f"vs {b.origins[col]} (B) — diffing them would compare "
            f"unrelated simulations")
    common_rounds = sorted(set(a.rounds.tolist()) & set(b.rounds.tolist()))
    if args.round is not None:
        if args.round not in common_rounds:
            raise SystemExit(f"round {args.round} is not traced by both")
        common_rounds = [args.round]
    if not common_rounds:
        raise SystemExit("traces share no rounds")
    n = a.num_nodes
    only_a = only_b = shared = 0
    edge_delta = {}
    cov_delta = []
    for rnd in common_rounds:
        sa, sb = _round_slice(a, rnd, col), _round_slice(b, rnd, col)
        d = E.diff_delivered(sa["peers"], sa["code"], sa["dist"],
                             sb["peers"], sb["code"], sb["dist"], n)
        shared += len(d["common"])
        only_a += len(d["only_a"])
        only_b += len(d["only_b"])
        for k in d["only_a"]:
            edge_delta[k] = edge_delta.get(k, 0) + 1
        for k in d["only_b"]:
            edge_delta[k] = edge_delta.get(k, 0) - 1
        cov_delta.append(float(sa["coverage"]) - float(sb["coverage"]))
    top = sorted(edge_delta.items(), key=lambda kv: -abs(kv[1]))[:args.top]
    if args.json:
        print(json.dumps({
            "rounds": len(common_rounds), "col": col,
            "shared": shared, "only_a": only_a, "only_b": only_b,
            "coverage_delta_mean": float(np.mean(cov_delta)),
            "top": [{"src": E.unpack_edge(k, n)[0],
                     "dst": E.unpack_edge(k, n)[1], "delta": c}
                    for k, c in top]}, indent=2))
        return 0
    print(f"trace diff over {len(common_rounds)} shared round(s), origin "
          f"column {col}:")
    print(f"  delivered edges: shared={shared} only_A={only_a} "
          f"only_B={only_b}")
    print(f"  coverage delta (A - B): mean {np.mean(cov_delta):+.6f}, "
          f"max |{np.max(np.abs(cov_delta)):.6f}|")
    print(f"  top {len(top)} differing edges (src -> dst: rounds_only_A - "
          f"rounds_only_B):")
    for k, c in top:
        src, dst = E.unpack_edge(k, n)
        print(f"    {src} -> {dst}: {c:+d}")
    return 0


# --------------------------------------------------------------------------
# hot-nodes
# --------------------------------------------------------------------------

def _recount_planes(tr) -> dict:
    """Recompute per-node load planes from the trace's slot outcomes, over
    the measured (post-warm-up) traced rounds — the independent evidence
    the node-health observatory's accumulators must agree with."""
    from gossip_sim_tpu.obs.trace import TRACE_DROPPED
    from gossip_sim_tpu.traffic import (TRAFFIC_DEFERRED,
                                        TRAFFIC_QUEUE_DROPPED)
    m = tr.manifest
    warm = int(m.get("warm_up_rounds", 0))
    n = tr.num_nodes
    measured = [t for t in range(len(tr)) if int(tr.rounds[t]) >= warm]
    is_traffic = int(m.get("traffic_slots") or 0) > 0
    if is_traffic:
        planes = {k: np.zeros(n, np.int64)
                  for k in ("deferred", "queue_dropped")}
        for t in measured:
            code = tr.arrays["code"][t]       # [V, N, F]
            peers = tr.arrays["peers"][t]
            # sender-side: egress-cap deferrals accrue to the source row
            planes["deferred"] += (code == TRAFFIC_DEFERRED).sum(
                axis=(0, 2)).astype(np.int64)
            # receiver-side: ingress-cap drops accrue to the target
            v, src, slot = np.nonzero(code == TRAFFIC_QUEUE_DROPPED)
            np.add.at(planes["queue_dropped"], peers[v, src, slot], 1)
    else:
        planes = {k: np.zeros(n, np.int64)
                  for k in ("egress", "ingress", "loss_dropped")}
        for t in measured:
            for col in range(len(tr.origins)):
                code = tr.arrays["code"][t, col]      # [N, F]
                peers = tr.arrays["peers"][t, col]
                dist = tr.arrays["dist"][t, col]
                dm = E.delivered_mask(code, dist)
                planes["egress"] += dm.sum(axis=-1).astype(np.int64)
                src, slot = np.nonzero(dm)
                np.add.at(planes["ingress"], peers[src, slot], 1)
                planes["loss_dropped"] += (
                    (code == TRACE_DROPPED) & (dist >= 0)[:, None]).sum(
                    axis=-1).astype(np.int64)
    return planes


#: trace-recomputed plane -> checkpoint SimState / TrafficState array
_PLANE_TO_CKPT = {
    "egress": "state.egress_acc", "ingress": "state.ingress_acc",
    "deferred": "state.defer_acc", "queue_dropped": "state.qdrop_acc",
}


def cmd_hot_nodes(args) -> int:
    tr = load_trace(args.trace_dir)
    m = tr.manifest
    warm, iters = int(m.get("warm_up_rounds", 0)), int(m["iterations"])
    planes = _recount_planes(tr)
    traced = set(int(r) for r in tr.rounds)
    complete = set(range(warm, iters)) <= traced
    out = {"num_nodes": tr.num_nodes, "complete_coverage": complete,
           "planes": {}}
    for name, plane in planes.items():
        order = np.lexsort((np.arange(len(plane)), -plane))[:args.top]
        out["planes"][name] = {
            "total": int(plane.sum()),
            "hot_nodes": [{"node": int(i), "count": int(plane[i])}
                          for i in order],
        }
    rc = 0
    if args.checkpoint:
        # cross-check: the engine's own accumulator planes (carried in
        # every sim/traffic checkpoint) must equal the trace recount
        # exactly — possible only when the trace covers every measured
        # round (and, for sim traces, every origin of the run)
        if not complete:
            raise SystemExit(
                f"ERROR: trace covers {len(traced)} round(s) but the run "
                f"measured rounds {warm}..{iters - 1}; a partial trace "
                f"cannot be cross-checked exactly against the engine's "
                f"cumulative planes")
        with np.load(args.checkpoint) as z:
            arrays = {k: z[k] for k in z.files if k.startswith("state.")}
        is_traffic = int(m.get("traffic_slots") or 0) > 0
        if not is_traffic:
            o_ck = arrays["state.egress_acc"].shape[0]
            if o_ck != len(tr.origins):
                raise SystemExit(
                    f"ERROR: checkpoint holds {o_ck} origin plane(s) but "
                    f"the trace records {len(tr.origins)} origin "
                    f"column(s); the cumulative counts are not comparable")
        out["cross_check"] = {}
        for name, key in _PLANE_TO_CKPT.items():
            if name not in planes or key not in arrays:
                continue
            ck = np.asarray(arrays[key], np.int64)
            if ck.ndim > 1:               # sim planes are [O, N]
                ck = ck.sum(axis=0)
            match = bool(np.array_equal(planes[name], ck))
            out["cross_check"][name] = {
                "match": match, "trace_total": int(planes[name].sum()),
                "checkpoint_total": int(ck.sum()),
            }
            if not match:
                bad = np.nonzero(planes[name] != ck)[0]
                out["cross_check"][name]["first_mismatches"] = [
                    {"node": int(i), "trace": int(planes[name][i]),
                     "checkpoint": int(ck[i])} for i in bad[:5]]
                rc = 1
    if args.json:
        print(json.dumps(out, indent=2))
        return rc
    print(f"hot nodes: {len(traced)} traced round(s), "
          f"{'complete' if complete else 'PARTIAL'} measured-round "
          f"coverage")
    for name, ent in out["planes"].items():
        print(f"  {name}: total={ent['total']}")
        for h in ent["hot_nodes"]:
            if h["count"] == 0:
                break
            print(f"    node {h['node']}: {h['count']}")
    for name, ent in out.get("cross_check", {}).items():
        status = "OK" if ent["match"] else "MISMATCH"
        print(f"  cross-check {name}: {status} (trace={ent['trace_total']} "
              f"checkpoint={ent['checkpoint_total']})")
        for mm in ent.get("first_mismatches", []):
            print(f"    node {mm['node']}: trace={mm['trace']} "
                  f"checkpoint={mm['checkpoint']}")
    return rc


# --------------------------------------------------------------------------

def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="trace_report",
        description="analyze protocol flight-recorder traces "
                    "(gossip-sim-tpu/trace/v1)")
    sub = ap.add_subparsers(dest="cmd", required=True)

    def common(p, b_dir=False):
        p.add_argument("trace_dir", help="--trace-dir of a recorded run")
        if b_dir:
            p.add_argument("trace_dir_b", help="second trace to diff against")
        p.add_argument("--round", type=int, default=None,
                       help="absolute round index (default: last traced)")
        p.add_argument("--col", type=int, default=0,
                       help="origin column for multi-origin traces; for "
                            "traffic traces (explain-stranded) the VALUE "
                            "SLOT to analyze")
        p.add_argument("--json", action="store_true")

    common(sub.add_parser("info", help="manifest summary + validation"))
    p = sub.add_parser("tree", help="render the delivery tree of a round")
    common(p)
    p.add_argument("--max-nodes", type=int, default=200,
                   help="cap on rendered tree lines")
    p = sub.add_parser("explain-stranded",
                       help="root-cause every stranded node of a round")
    common(p)
    p.add_argument("--verbose", action="store_true",
                   help="list every (sender, slot, cause) path")
    p = sub.add_parser("attribute-rmr",
                       help="top-K redundant edges across traced rounds")
    common(p)
    p.add_argument("--top", type=int, default=10)
    p = sub.add_parser("diff", help="edge-by-edge diff of two traces")
    common(p, b_dir=True)
    p.add_argument("--top", type=int, default=10)
    p = sub.add_parser(
        "hot-nodes",
        help="recompute per-node load planes; cross-check vs a checkpoint")
    common(p)
    p.add_argument("--top", type=int, default=10)
    p.add_argument("--checkpoint", default=None,
                   help="checkpoint .npz of the same run: assert the "
                        "engine's accumulator planes equal the trace "
                        "recount bit-for-bit")

    args = ap.parse_args(argv)
    try:
        return {
            "info": cmd_info,
            "tree": cmd_tree,
            "explain-stranded": cmd_explain_stranded,
            "attribute-rmr": cmd_attribute_rmr,
            "diff": cmd_diff,
            "hot-nodes": cmd_hot_nodes,
        }[args.cmd](args)
    except BrokenPipeError:    # output piped into head/less and closed
        return 0


if __name__ == "__main__":
    sys.exit(main())
