"""Distribution-parity report: both backends vs the reference's published
protocol numbers (README.md:216-245; BASELINE.md).

Runs the canonical workload (defaults: fanout 6, active-set 12, p=1/75,
prune-thresh 0.15, min-ingress 2, warm-up 200, 400 measured rounds —
gossip_main.rs:90,97,124,135,142,223) on a synthetic stake-realistic cluster
through the oracle and the TPU engine, collects the same statistics the
reference README reports, and writes a markdown table (PARITY.md).

The reference README run's cluster size/params are unpublished, so the
comparison is distributional (same regime), not numeric equality; the
oracle-vs-engine columns ARE directly comparable (same cluster, same
workload).

Usage: python tools/parity_report.py [--num-nodes 2000] [--measured 400]
       [--warm-up 200] [--out PARITY.md] [--skip-oracle]
"""
import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

REFERENCE = {  # README.md:216-241
    "coverage_mean": 0.984000, "coverage_median": 0.983333,
    "coverage_max": 0.996667, "coverage_min": 0.960000,
    "rmr_mean": 3.107014, "rmr_median": 2.202361,
    "rmr_max": 10.041812, "rmr_min": 1.836177,
    "hops_mean": 4.497764, "hops_median": 4.00, "hops_max": 11,
    "ldh_mean": 9.455000, "ldh_median": 9.00, "ldh_max": 11, "ldh_min": 7,
}


def make_mainnet_shaped_accounts(n, seed, zero_stake_fraction):
    """Synthetic cluster with a mainnet-like zero-stake mass: lognormal
    stakes for the staked set (the bench.py recipe, ~5 orders of magnitude
    spread) plus ``zero_stake_fraction`` unstaked nodes — the topology
    write_accounts snapshots show (write_accounts_main.rs:98-125,
    gossip.rs:892-894), which exercises bucket-0 sampling at scale."""
    import numpy as np

    from gossip_sim_tpu.identity import (pubkey_new_unique,
                                         reset_unique_pubkeys)

    reset_unique_pubkeys()
    rng = np.random.default_rng(seed)
    n_zero = int(n * zero_stake_fraction)
    sol = np.exp(rng.normal(9.5, 2.0, n - n_zero)).astype(np.int64) + 1
    stakes = np.concatenate([sol * 1_000_000_000,
                             np.zeros(n_zero, np.int64)])
    rng.shuffle(stakes)
    return {pubkey_new_unique(): int(s) for s in stakes}


def run_backend(backend, n, iterations, warm_up, seed, account_file=""):
    from gossip_sim_tpu.cli import run_simulation
    from gossip_sim_tpu.config import Config
    from gossip_sim_tpu.identity import reset_unique_pubkeys
    from gossip_sim_tpu.stats.gossip_stats import GossipStatsCollection

    reset_unique_pubkeys()
    if account_file:
        config = Config(gossip_iterations=iterations, warm_up_rounds=warm_up,
                        accounts_from_file=True, account_file=account_file,
                        backend=backend, seed=seed)
    else:
        config = Config(gossip_iterations=iterations, warm_up_rounds=warm_up,
                        num_synthetic_nodes=n, backend=backend, seed=seed)
    collection = GossipStatsCollection()
    collection.set_number_of_simulations(1)
    t0 = time.time()
    run_simulation(config, "", collection, None, 0, "0", 0.0)
    dt = time.time() - t0
    s = collection.collection[0]
    cov = s.get_coverage_stats()
    rmr = s.get_rmr_stats()
    hops = s.get_aggregate_hop_stats()
    ldh = s.get_last_delivery_hop_stats()
    return {
        "backend": backend, "elapsed_s": round(dt, 1),
        "coverage_mean": cov[0], "coverage_median": cov[1],
        "coverage_max": cov[2], "coverage_min": cov[3],
        "rmr_mean": rmr[0], "rmr_median": rmr[1],
        "rmr_max": rmr[2], "rmr_min": rmr[3],
        "hops_mean": hops[0], "hops_median": hops[1], "hops_max": hops[2],
        "ldh_mean": ldh[0], "ldh_median": ldh[1], "ldh_max": ldh[2],
        "ldh_min": ldh[3],
    }


ROWS = [
    ("Coverage mean", "coverage_mean", "{:.6f}"),
    ("Coverage median", "coverage_median", "{:.6f}"),
    ("Coverage max", "coverage_max", "{:.6f}"),
    ("Coverage min", "coverage_min", "{:.6f}"),
    ("RMR mean", "rmr_mean", "{:.6f}"),
    ("RMR median", "rmr_median", "{:.6f}"),
    ("RMR max", "rmr_max", "{:.6f}"),
    ("RMR min", "rmr_min", "{:.6f}"),
    ("Aggregate hops mean", "hops_mean", "{:.6f}"),
    ("Aggregate hops median", "hops_median", "{:.2f}"),
    ("Aggregate hops max", "hops_max", "{}"),
    ("LDH mean", "ldh_mean", "{:.6f}"),
    ("LDH median", "ldh_median", "{:.2f}"),
    ("LDH max", "ldh_max", "{}"),
    ("LDH min", "ldh_min", "{}"),
]


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--num-nodes", type=int, default=2000)
    ap.add_argument("--measured", type=int, default=400)
    ap.add_argument("--warm-up", type=int, default=200)
    ap.add_argument("--seed", type=int, default=42)
    ap.add_argument("--out", default="")
    ap.add_argument("--skip-oracle", action="store_true")
    ap.add_argument("--zero-stake-fraction", type=float, default=0.0,
                    help="> 0: mainnet-shaped cluster — lognormal stakes "
                         "plus this fraction of zero-stake nodes "
                         "(VERDICT r5 #5: exercises bucket-0 sampling and "
                         "the README's high-RMR regime)")
    ap.add_argument("--force-cpu", action="store_true",
                    help="pin the JAX CPU backend (for hosts where the "
                         "accelerator plugin hangs at init)")
    args = ap.parse_args()
    if args.force_cpu:
        import jax
        jax.config.update("jax_platforms", "cpu")
    iterations = args.warm_up + args.measured

    account_file = ""
    if args.zero_stake_fraction > 0:
        import tempfile

        from gossip_sim_tpu.ingest import write_accounts_yaml
        accounts = make_mainnet_shaped_accounts(
            args.num_nodes, args.seed, args.zero_stake_fraction)
        fd, account_file = tempfile.mkstemp(suffix=".yaml",
                                            prefix="parity-accounts-")
        os.close(fd)
        write_accounts_yaml(account_file, accounts)
        print(f"mainnet-shaped cluster: {args.num_nodes} nodes, "
              f"{sum(1 for s in accounts.values() if s == 0)} zero-stake "
              f"-> {account_file}")

    results = {}
    results["tpu"] = run_backend("tpu", args.num_nodes, iterations,
                                 args.warm_up, args.seed, account_file)
    if not args.skip_oracle:
        results["oracle"] = run_backend("oracle", args.num_nodes, iterations,
                                        args.warm_up, args.seed, account_file)

    shape = (f"mainnet-shaped ({args.zero_stake_fraction:.0%} zero-stake, "
             f"lognormal staked mass)"
             if args.zero_stake_fraction > 0 else "stake-realistic")
    cols = ["reference README"] + list(results)
    lines = [
        "# Distribution parity vs the reference's published numbers",
        "",
        f"Workload: {args.num_nodes}-node synthetic {shape} cluster, "
        f"canonical defaults (fanout 6, active-set 12, p=1/75, thresh 0.15, "
        f"min-ingress 2), warm-up {args.warm_up}, {args.measured} measured "
        f"rounds, seed {args.seed}.",
        "",
        "The reference column is the README example run "
        "(/root/reference/README.md:216-241) whose cluster size and "
        "parameters are unpublished — compare regimes, not digits. The "
        "oracle and tpu columns share the identical cluster/workload and "
        "are directly comparable to each other.",
        "",
        "| Metric | " + " | ".join(cols) + " |",
        "|" + "---|" * (len(cols) + 1),
    ]
    for label, key, fmt in ROWS:
        vals = [fmt.format(REFERENCE[key])]
        for b in results:
            vals.append(fmt.format(results[b][key]))
        lines.append(f"| {label} | " + " | ".join(vals) + " |")
    lines += ["",
              "Runtimes: " + ", ".join(
                  f"{b}: {r['elapsed_s']}s" for b, r in results.items()),
              ""]
    text = "\n".join(lines)
    print(text)
    print(json.dumps(results))
    if args.out:
        with open(args.out, "w") as f:
            f.write(text)
    return 0


if __name__ == "__main__":
    sys.exit(main())
