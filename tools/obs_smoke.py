"""Observability smoke gate: tiny CPU sim with ``--run-report``, schema
validation, and a telemetry-overhead budget.

Fast CI gate (CPU, well under 60 s):

  1. one cold run to populate the in-process jit cache (untimed),
  2. best-of-N timed runs with no obs flags,
  3. best-of-N timed runs with ``--run-report`` on,
  4. assertions: every run exits 0, the report validates against the
     obs/report.py schema with nonzero compile/round/stats spans and
     throughput, coverage is sane, the telemetry overhead is under
     ``--overhead-budget`` (default 2%) plus a small absolute slack that
     absorbs CI timer noise on sub-second runs, and two reported runs are
     deterministic (identical coverage/rmr under the fixed seed).

Usage: python tools/obs_smoke.py [--num-nodes 40] [--iterations 16]
       [--warm-up-rounds 4] [--seed 7] [--reps 2]
       [--overhead-budget 0.02] [--overhead-slack-s 0.2]

Exit code 0 = all assertions hold; 1 = an observability invariant failed.
"""
import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    ap = argparse.ArgumentParser(
        description="run-report schema + telemetry-overhead smoke "
                    "(CPU, <60s)")
    ap.add_argument("--num-nodes", type=int, default=40)
    ap.add_argument("--iterations", type=int, default=16)
    ap.add_argument("--warm-up-rounds", type=int, default=4)
    ap.add_argument("--seed", type=int, default=7)
    ap.add_argument("--reps", type=int, default=2,
                    help="timed repetitions per arm (best-of)")
    ap.add_argument("--overhead-budget", type=float, default=0.02,
                    help="max fractional telemetry overhead (default 2%%)")
    ap.add_argument("--overhead-slack-s", type=float, default=0.2,
                    help="absolute slack absorbing timer noise on "
                         "sub-second runs")
    args = ap.parse_args()

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    from gossip_sim_tpu.cli import main as cli_main
    from gossip_sim_tpu.obs import validate_run_report

    base = ["--num-synthetic-nodes", str(args.num_nodes),
            "--iterations", str(args.iterations),
            "--warm-up-rounds", str(args.warm_up_rounds),
            "--seed", str(args.seed)]

    def timed_run(extra):
        t0 = time.perf_counter()
        rc = cli_main(base + extra)
        return rc, time.perf_counter() - t0

    failures = []

    def check(ok: bool, msg: str):
        print(f"  [{'ok' if ok else 'FAIL'}] {msg}")
        if not ok:
            failures.append(msg)

    t_start = time.time()
    print(f"obs smoke: n={args.num_nodes} iters={args.iterations} "
          f"warmup={args.warm_up_rounds} reps={args.reps}")

    # 1. cold run: compile once so both timed arms run against a warm cache
    rc, t_cold = timed_run([])
    check(rc == 0, f"cold run exits 0 (took {t_cold:.2f}s)")

    # 2. timed plain arm (no obs flags)
    t_plain = min(timed_run([])[1] for _ in range(max(1, args.reps)))

    # 3. timed telemetry arm (+ determinism pair)
    reports, t_obs = [], float("inf")
    for i in range(max(2, args.reps)):
        path = f"/tmp/obs_smoke_report_{os.getpid()}_{i}.json"
        rc, dt = timed_run(["--run-report", path])
        t_obs = min(t_obs, dt)
        check(rc == 0, f"telemetry run {i} exits 0")
        try:
            with open(path) as f:
                reports.append(json.load(f))
            os.unlink(path)
        except (OSError, ValueError) as e:
            check(False, f"report {i} unreadable: {e}")

    # 4. schema + content
    for i, rep in enumerate(reports):
        problems = validate_run_report(rep)
        check(problems == [], f"report {i} schema-valid {problems or ''}")
    if reports:
        rep = reports[0]
        spans = rep.get("spans", {})
        for name in ("engine/compile", "engine/rounds", "stats/harvest",
                     "engine/init", "ingest"):
            check(spans.get(name, {}).get("total_s", 0) > 0,
                  f"span {name} nonzero")
        check(rep.get("throughput", {}).get("origin_iters_per_sec", 0) > 0,
              "throughput origin_iters_per_sec nonzero")
        check(0.0 < rep.get("coverage_mean", 0) <= 1.0,
              f"coverage_mean sane ({rep.get('coverage_mean')})")
        check(rep.get("num_nodes") == args.num_nodes,
              "num_nodes matches the cluster")
    if len(reports) >= 2:
        same = all(reports[0][k] == r[k]
                   for r in reports[1:] for k in ("coverage_mean", "rmr_mean"))
        check(same, "reported stats deterministic under the fixed seed")

    # 5. overhead budget
    budget = t_plain * (1.0 + args.overhead_budget) + args.overhead_slack_s
    overhead = (t_obs - t_plain) / t_plain if t_plain > 0 else 0.0
    print(f"  plain={t_plain:.3f}s telemetry={t_obs:.3f}s "
          f"overhead={overhead * 100:+.2f}%")
    check(t_obs <= budget,
          f"telemetry overhead within {args.overhead_budget:.0%} "
          f"(+{args.overhead_slack_s}s slack)")

    print(f"  elapsed: {time.time() - t_start:.1f}s")
    if failures:
        print(f"OBS SMOKE FAILED ({len(failures)} invariant(s)):")
        for f in failures:
            print(f"  - {f}")
        return 1
    print("OBS SMOKE PASSED")
    return 0


if __name__ == "__main__":
    sys.exit(main())
