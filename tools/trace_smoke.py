"""Flight-recorder smoke gate: 1k-node CPU sim with ``--trace-dir``,
schema + delivery-tree + stranded-explanation assertions, bit-parity and
a tracing-overhead budget, plus engine-vs-oracle first-delivery parity.

Fast CI gate (CPU, well under 60 s):

  1. one cold run to populate the in-process jit cache (untimed),
  2. best-of-N timed runs with ``--run-report`` only,
  3. best-of-N timed runs with ``--run-report`` + ``--trace-dir``,
  4. assertions:
       * the trace manifest validates (gossip-sim-tpu/trace/v1) and loads,
       * every traced round's first deliveries form a tree rooted at the
         origin,
       * the trace's covered-node counts match the stats layer (per-round
         vs the recorded coverage; mean vs the run report),
       * every stranded node gets a concrete cause from explain_stranded,
       * enabling tracing changes no simulation output bits (identical
         coverage_mean / rmr_mean in the run reports),
       * tracing overhead stays under ``--overhead-budget`` (default 5%)
         plus an absolute slack absorbing CI timer noise,
  5. engine-vs-oracle parity (``--skip-parity`` to skip): with the
     oracle's active sets forced to the engine's sampled ones and rotation
     off, both backends' traces must record identical distances,
     first-delivery edge sets and delivered edge sets every round — under
     packet loss, so the outcome codes are exercised too.

Usage: python tools/trace_smoke.py [--num-nodes 1000] [--iterations 20]
       [--warm-up-rounds 4] [--seed 7] [--reps 2]
       [--overhead-budget 0.05] [--overhead-slack-s 0.5] [--skip-parity]

Exit code 0 = all assertions hold; 1 = a flight-recorder invariant failed.
"""
import argparse
import json
import os
import shutil
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    ap = argparse.ArgumentParser(
        description="flight-recorder schema + parity + overhead smoke "
                    "(CPU, <60s)")
    ap.add_argument("--num-nodes", type=int, default=1000)
    ap.add_argument("--iterations", type=int, default=20)
    ap.add_argument("--warm-up-rounds", type=int, default=4)
    ap.add_argument("--seed", type=int, default=7)
    ap.add_argument("--reps", type=int, default=2,
                    help="timed repetitions per arm (best-of)")
    ap.add_argument("--overhead-budget", type=float, default=0.05,
                    help="max fractional tracing overhead (default 5%%)")
    ap.add_argument("--overhead-slack-s", type=float, default=0.5,
                    help="absolute slack absorbing timer noise on "
                         "sub-second runs")
    ap.add_argument("--skip-parity", action="store_true",
                    help="skip the engine-vs-oracle trace parity check")
    args = ap.parse_args()

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import numpy as np

    from gossip_sim_tpu.cli import main as cli_main
    from gossip_sim_tpu.obs.trace import load_trace, validate_trace_dir
    from gossip_sim_tpu.stats import edges as E

    base = ["--num-synthetic-nodes", str(args.num_nodes),
            "--iterations", str(args.iterations),
            "--warm-up-rounds", str(args.warm_up_rounds),
            "--seed", str(args.seed)]
    tmp = f"/tmp/trace_smoke_{os.getpid()}"
    os.makedirs(tmp, exist_ok=True)

    failures = []

    def check(ok: bool, msg: str):
        print(f"  [{'ok' if ok else 'FAIL'}] {msg}")
        if not ok:
            failures.append(msg)

    def timed_run(extra):
        t0 = time.perf_counter()
        rc = cli_main(base + extra)
        return rc, time.perf_counter() - t0

    t_start = time.time()
    print(f"trace smoke: n={args.num_nodes} iters={args.iterations} "
          f"warmup={args.warm_up_rounds} reps={args.reps}")

    # 1. cold runs: compile both arms' graphs once (trace rows compile a
    # distinct round program), so the timed arms hit a warm jit cache
    rc, t_cold = timed_run(["--run-report", f"{tmp}/cold.json"])
    check(rc == 0, f"cold plain run exits 0 (took {t_cold:.2f}s)")
    rc, t_cold_t = timed_run(["--run-report", f"{tmp}/cold_t.json",
                              "--trace-dir", f"{tmp}/cold_trace"])
    check(rc == 0, f"cold traced run exits 0 (took {t_cold_t:.2f}s)")

    # 2. timed plain arm (report only)
    t_plain = float("inf")
    plain_report = None
    for i in range(max(1, args.reps)):
        path = f"{tmp}/plain_{i}.json"
        rc, dt = timed_run(["--run-report", path])
        t_plain = min(t_plain, dt)
        check(rc == 0, f"plain run {i} exits 0")
        with open(path) as f:
            plain_report = json.load(f)

    # 3. timed traced arm
    t_trace = float("inf")
    trace_report = None
    trace_dir = f"{tmp}/trace"
    for i in range(max(1, args.reps)):
        shutil.rmtree(trace_dir, ignore_errors=True)
        path = f"{tmp}/traced_{i}.json"
        rc, dt = timed_run(["--run-report", path, "--trace-dir", trace_dir])
        t_trace = min(t_trace, dt)
        check(rc == 0, f"traced run {i} exits 0")
        with open(path) as f:
            trace_report = json.load(f)

    # 4a. schema + load
    problems = validate_trace_dir(trace_dir)
    check(problems == [], f"trace manifest + segments validate {problems or ''}")
    tr = load_trace(trace_dir)
    measured = args.iterations - args.warm_up_rounds
    check(len(tr) == measured,
          f"trace covers all {measured} measured rounds (got {len(tr)})")

    # 4b. per-round invariants: rooted tree, coverage cross-check,
    # stranded explanations
    origin = tr.origins[0]
    trees_ok = cov_ok = expl_ok = True
    for t in range(len(tr)):
        dist = tr.arrays["dist"][t, 0]
        first = tr.arrays["first_src"][t, 0]
        failed = tr.arrays["failed"][t, 0]
        _, ok = E.build_delivery_tree(first, dist, origin)
        trees_ok &= ok
        covered = int((dist >= 0).sum())
        cov_ok &= abs(covered / tr.num_nodes
                      - float(tr.arrays["coverage"][t, 0])) < 1e-6
        stranded = int(((dist < 0) & ~failed).sum())
        expl = E.explain_stranded(tr.arrays["active"][t, 0],
                                  tr.arrays["pruned"][t, 0],
                                  tr.arrays["peers"][t, 0],
                                  tr.arrays["code"][t, 0],
                                  dist, failed, origin)
        expl_ok &= (len(expl) == stranded
                    and all(e["summary"] for e in expl))
    check(trees_ok, "every traced round's delivery tree roots at the origin")
    check(cov_ok, "per-round covered-node counts match the recorded "
                  "coverage")
    check(expl_ok, "every stranded node gets a concrete cause")
    cov_trace = float(tr.arrays["coverage"].mean())
    cov_stats = float(trace_report["coverage_mean"])
    check(abs(cov_trace - cov_stats) < 1e-6,
          f"trace coverage mean matches the stats layer "
          f"({cov_trace:.6f} vs {cov_stats:.6f})")

    # 4c. bit-parity: tracing must not change simulation outputs
    same = all(plain_report[k] == trace_report[k]
               for k in ("coverage_mean", "rmr_mean"))
    check(same, "tracing changes no simulation output bits "
                "(coverage/rmr identical)")

    # 4d. overhead budget
    budget = t_plain * (1.0 + args.overhead_budget) + args.overhead_slack_s
    overhead = (t_trace - t_plain) / t_plain if t_plain > 0 else 0.0
    print(f"  plain={t_plain:.3f}s traced={t_trace:.3f}s "
          f"overhead={overhead * 100:+.2f}%")
    check(t_trace <= budget,
          f"tracing overhead within {args.overhead_budget:.0%} "
          f"(+{args.overhead_slack_s}s slack)")

    # 5. engine-vs-oracle first-delivery parity (forced active sets)
    if not args.skip_parity:
        parity_rounds = 6
        print(f"  parity: {args.num_nodes} nodes x {parity_rounds} rounds, "
              f"forced active sets, rotation off, 15% packet loss")
        import jax
        import jax.numpy as jnp

        from gossip_sim_tpu.engine import (EngineParams, init_state,
                                           make_cluster_tables, run_rounds)
        from gossip_sim_tpu.faults import FaultInjector
        from gossip_sim_tpu.identity import (NodeIndex, get_stake_bucket,
                                             pubkey_new_unique)
        from gossip_sim_tpu.obs.trace import OracleTraceCollector
        from gossip_sim_tpu.oracle.cluster import Cluster, Node

        n = args.num_nodes
        rng = np.random.default_rng(args.seed)
        stakes_arr = rng.choice(np.arange(1, 50 * n), size=n,
                                replace=False).astype(np.int64) * 10**9
        accounts = {pubkey_new_unique(): int(s) for s in stakes_arr}
        index = NodeIndex.from_stakes(accounts)
        stakes_np = index.stakes.astype(np.int64)
        tables = make_cluster_tables(stakes_np)
        params = EngineParams(num_nodes=n, probability_of_rotation=0.0,
                              warm_up_rounds=0, impair_seed=args.seed,
                              packet_loss_rate=0.15).validate()
        origins = jnp.asarray([0], jnp.int32)
        state = init_state(jax.random.PRNGKey(11), tables, origins, params)

        stakes_map = {pk: int(s) for pk, s in zip(index.pubkeys, stakes_np)}
        nodes = [Node(pk, stakes_map[pk]) for pk in index.pubkeys]
        origin_pk = index.pubkeys[0]
        active = np.asarray(state.active[0])
        for i, node in enumerate(nodes):
            bucket = get_stake_bucket(min(stakes_map[node.pubkey],
                                          stakes_map[origin_pk]))
            node.active_set.entries[bucket].peers = {
                index.pubkeys[j]: {index.pubkeys[j]}
                for j in active[i] if j < n}
        node_map = {nd.pubkey: nd for nd in nodes}
        cluster = Cluster(params.push_fanout)
        impair = FaultInjector(index, seed=args.seed, packet_loss_rate=0.15)
        collector = OracleTraceCollector(
            index, origin_pk, push_fanout=params.push_fanout,
            active_set_size=params.active_set_size,
            prune_cap=params.prune_cap)

        state, rows = run_rounds(params, tables, origins, state,
                                 parity_rounds, trace=True)
        rows = jax.tree_util.tree_map(np.asarray, rows)
        parity_ok = True
        for r in range(parity_rounds):
            impair.begin_round(r)
            collector.begin_round(cluster, node_map)
            cluster.run_gossip(origin_pk, stakes_map, node_map, impair)
            cluster.consume_messages(origin_pk, nodes)
            cluster.send_prunes(origin_pk, nodes,
                                params.prune_stake_threshold,
                                params.min_ingress_nodes, stakes_map)
            cluster.prune_connections(node_map, stakes_map)
            collector.end_round(r, cluster, node_map, [])
        _, block = collector.flush()
        for r in range(parity_rounds):
            dist_e = rows["dist"][r, 0]
            dist_o = block["dist"][r, 0]
            parity_ok &= np.array_equal(dist_e, dist_o)
            parity_ok &= np.array_equal(rows["trace_first"][r, 0],
                                        block["first_src"][r, 0])
            edges_e = E.delivered_edges(rows["trace_peers"][r, 0],
                                        rows["trace_code"][r, 0], dist_e)
            edges_o = E.delivered_edges(block["peers"][r, 0],
                                        block["code"][r, 0], dist_o)
            parity_ok &= (set(E.edge_keys(edges_e, n).tolist())
                          == set(E.edge_keys(edges_o, n).tolist()))
        check(parity_ok, "engine and oracle traces record identical "
                         "distances, first-delivery and delivered edge "
                         "sets under a fixed seed")

    shutil.rmtree(tmp, ignore_errors=True)
    print(f"  elapsed: {time.time() - t_start:.1f}s")
    if failures:
        print(f"TRACE SMOKE FAILED ({len(failures)} invariant(s)):")
        for f in failures:
            print(f"  - {f}")
        return 1
    print("TRACE SMOKE PASSED")
    return 0


if __name__ == "__main__":
    sys.exit(main())
