"""Capacity-observatory smoke test: the CI gate for obs/capacity.py +
obs/memwatch.py (ISSUE 13).

Fast CPU gate (~2 min) over four contracts:

  1. **Ledger exactness**: the closed-form ledger predicts the live
     donated-buffer pytree bytes BIT-EXACTLY — on a 1k-node push run
     (post-round SimState + ClusterTables + EngineKnobs), on a
     traffic run (TrafficState), and on a lane-batched run ([K,...]
     states); plus the N-scaling extrapolation against a second live
     instantiation at a different N.
  2. **Report schema**: a CLI run with ``--capacity-harvest
     --memwatch-interval-s`` emits a schema-valid run report whose
     capacity section carries nonzero cost-harvest fields (harvests,
     FLOPs, argument bytes) and a nonzero peak-RSS figure.
  3. **Memwatch overhead** under ``--overhead-budget`` (default 2%):
     enforced EXACTLY via the sampler's own CPU accounting
     (``sample_time_s`` / run wall, gate 2's instrumented report), plus
     an A/B wall-clock sanity net on the obs_smoke workload (absolute
     slack absorbs CI timer noise on sub-second runs).
  4. **Zero bit-impact**: enabling the harvest + sampler moves no bit of
     the stats parity snapshot or the deterministic Influx wire lines,
     and the ``sim_capacity`` series is excluded from the deterministic
     wire surface (it is wall-clock-valued, like sim_perf).

Usage: python tools/capacity_smoke.py [--num-nodes 1000] [--seed 7]
       [--reps 2] [--overhead-budget 0.02] [--overhead-slack-s 0.2]

Exit code 0 = all contracts hold; 1 = a capacity invariant failed.
"""
import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    ap = argparse.ArgumentParser(
        description="capacity ledger/harvest/memwatch smoke (CPU, <2min)")
    ap.add_argument("--num-nodes", type=int, default=1000)
    ap.add_argument("--seed", type=int, default=7)
    ap.add_argument("--reps", type=int, default=2)
    ap.add_argument("--overhead-budget", type=float, default=0.02)
    ap.add_argument("--overhead-slack-s", type=float, default=0.2)
    args = ap.parse_args()

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import numpy as np
    import jax
    import jax.numpy as jnp

    from gossip_sim_tpu.cli import main as cli_main
    from gossip_sim_tpu.cli import run_simulation
    from gossip_sim_tpu.config import Config
    from gossip_sim_tpu.engine import (EngineParams, init_state,
                                       make_cluster_tables, run_rounds)
    from gossip_sim_tpu.engine.lanes import (broadcast_state,
                                             run_rounds_lanes, stack_knobs)
    from gossip_sim_tpu.engine.traffic import (device_traffic_tables,
                                               init_traffic_state,
                                               run_traffic_rounds)
    from gossip_sim_tpu.identity import reset_unique_pubkeys
    from gossip_sim_tpu.obs import capacity, memwatch, validate_run_report
    from gossip_sim_tpu.obs.spans import get_registry
    from gossip_sim_tpu.sinks import DatapointQueue, InfluxDataPoint
    from gossip_sim_tpu.stats.gossip_stats import GossipStatsCollection

    t_start = time.time()
    failures = []

    def check(ok: bool, msg: str):
        print(f"  [{'ok' if ok else 'FAIL'}] {msg}")
        if not ok:
            failures.append(msg)

    def stakes(n):
        rng = np.random.default_rng(args.seed)
        return (np.exp(rng.normal(9.5, 2.0, n)).astype(np.int64) + 1) * 10 ** 9

    print(f"capacity smoke: n={args.num_nodes} seed={args.seed} "
          f"reps={args.reps}")

    # ---- gate 1: ledger exactness vs live donated buffers ---------------
    n = args.num_nodes
    params = EngineParams(num_nodes=n)
    tables = make_cluster_tables(stakes(n))
    origins = jnp.asarray([0], dtype=jnp.int32)
    state = init_state(jax.random.PRNGKey(args.seed), tables, origins,
                       params)
    state, _ = run_rounds(params, tables, origins, state, 2)
    live, _ = capacity.measure_pytree(state)
    pred = capacity.predict_sim_state_bytes(params, 1)
    check(pred == live,
          f"1k-node push SimState bit-exact ({pred} == {live})")
    tlive, _ = capacity.measure_pytree(tables)
    tpred = sum(e.bytes for e in capacity.cluster_tables_entries(params))
    check(tpred == tlive,
          f"ClusterTables bit-exact ({tpred} == {tlive})")
    klive, _ = capacity.measure_pytree(params.knob_values())
    kpred = sum(e.bytes for e in capacity.knobs_entries())
    check(kpred == klive, f"EngineKnobs bit-exact ({kpred} == {klive})")

    # extrapolation: the SAME closed forms at a different N must match a
    # second live instantiation
    n2 = 257
    p2 = EngineParams(num_nodes=n2)
    st2 = init_state(jax.random.PRNGKey(args.seed),
                     make_cluster_tables(stakes(n2)),
                     origins, p2)
    live2, _ = capacity.measure_pytree(st2)
    check(capacity.predict_sim_state_bytes(p2, 1) == live2,
          f"closed-form N-extrapolation matches live at n={n2}")

    # traffic run
    tn, M = 300, 8
    tparams = EngineParams(num_nodes=tn, traffic_values=M, traffic_rate=2,
                           node_ingress_cap=24, node_egress_cap=32,
                           warm_up_rounds=0)
    tstakes = stakes(tn)
    tstate = init_traffic_state(tstakes, tparams, seed=args.seed)
    tstate, _ = run_traffic_rounds(tparams, make_cluster_tables(tstakes),
                                   device_traffic_tables(tstakes), tstate, 3)
    tlive2, _ = capacity.measure_pytree(tstate)
    tpred2 = capacity.predict_traffic_state_bytes(tparams)
    check(tpred2 == tlive2,
          f"traffic TrafficState bit-exact at n={tn} M={M} "
          f"({tpred2} == {tlive2})")

    # lane-batched run
    K = 3
    lp = EngineParams(num_nodes=128)
    lt = make_cluster_tables(stakes(128))
    lst = init_state(jax.random.PRNGKey(args.seed), lt, origins, lp)
    static = lp.static_part()
    knobs = stack_knobs([lp._replace(
        probability_of_rotation=0.01 + 0.001 * k).knob_values()
        for k in range(K)])
    lstates, _ = run_rounds_lanes(static, lt, origins,
                                  broadcast_state(lst, K), knobs, 2)
    llive, _ = capacity.measure_pytree(lstates)
    lpred = capacity.predict_sim_state_bytes(lp, 1, lanes=K)
    check(lpred == llive,
          f"lane-batched [K={K}] SimState bit-exact ({lpred} == {llive})")

    # ---- gate 2: run-report capacity section ----------------------------
    report_path = f"/tmp/capacity_smoke_{os.getpid()}.json"
    # 0.1 s = 10 Hz: sampling syscalls cost ~1 ms CPU under compile
    # contention in sandboxed kernels, so 10 Hz keeps the sampler's own
    # CPU comfortably inside the 2% bound while still producing a dense
    # series (~100 points on this run)
    rc = cli_main(["--num-synthetic-nodes", "60", "--iterations", "12",
                   "--warm-up-rounds", "2", "--seed", str(args.seed),
                   "--run-report", report_path, "--capacity-harvest",
                   "--memwatch-interval-s", "0.1"])
    check(rc == 0, "capacity-instrumented CLI run exits 0")
    try:
        with open(report_path) as f:
            rep = json.load(f)
        os.unlink(report_path)
    except (OSError, ValueError) as e:
        rep = {}
        check(False, f"run report unreadable: {e}")
    if rep:
        check(validate_run_report(rep) == [], "report schema-valid")
        cap = rep.get("capacity", {})
        cost = cap.get("cost", {})
        mem = cap.get("memwatch", {})
        led = cap.get("ledger", {})
        check(cost.get("harvests", 0) > 0 and cost.get("failures", 1) == 0,
              f"cost harvest ran ({cost.get('harvests')} executables, "
              f"{cost.get('reused')} reuses)")
        check(cost.get("flops", 0) > 0
              and cost.get("peak_argument_bytes", 0) > 0,
              "cost harvest fields nonzero (flops, argument bytes)")
        check(mem.get("peak_rss_bytes", 0) > 0
              and mem.get("samples", 0) > 0,
              f"memwatch peak RSS nonzero "
              f"({mem.get('peak_rss_bytes', 0)} B, "
              f"{mem.get('samples', 0)} samples)")
        # the REAL <2% bound: exact sampler CPU accounting (the sampler
        # times its own /proc reads — sample_time_s) over the run's
        # wall, immune to the timer noise that plagues sub-second A/B
        # wall-clock comparisons
        wall = rep.get("throughput", {}).get("wall_s", 0)
        frac = (mem.get("sample_time_s", 0) / wall) if wall > 0 else 1.0
        check(frac < args.overhead_budget,
              f"measured sampler CPU {frac * 100:.3f}% of wall "
              f"< {args.overhead_budget:.0%} at 10 Hz (exact "
              f"thread-CPU accounting)")
        check(led.get("total_bytes", 0) > 0
              and led.get("bytes_per_node", 0) > 0,
              f"ledger stamped ({led.get('total_bytes', 0)} B total)")

    # ---- gate 3: memwatch wall-clock sanity on the obs_smoke workload ---
    # The binding <2% bound is the exact sampler-CPU check in gate 2;
    # this A/B wall comparison is a noise-bounded end-to-end sanity net
    # (sub-second runs need the absolute slack to absorb CI timer jitter,
    # which makes the effective wall bound looser than 2% here).
    base = ["--num-synthetic-nodes", "40", "--iterations", "16",
            "--warm-up-rounds", "4", "--seed", str(args.seed)]

    def timed_run(extra):
        t0 = time.perf_counter()
        rc = cli_main(base + extra)
        check(rc == 0, f"overhead arm exits 0 ({extra or 'plain'})")
        return time.perf_counter() - t0

    timed_run([])  # cold: warm the jit cache for both arms
    t_plain = min(timed_run([]) for _ in range(max(1, args.reps)))
    t_mw = min(timed_run(["--memwatch-interval-s", "0.02"])
               for _ in range(max(1, args.reps)))
    overhead = (t_mw - t_plain) / t_plain if t_plain > 0 else 0.0
    budget = t_plain * (1.0 + args.overhead_budget) + args.overhead_slack_s
    print(f"  plain={t_plain:.3f}s memwatch={t_mw:.3f}s "
          f"wall delta={overhead * 100:+.2f}%")
    check(t_mw <= budget,
          f"memwatch wall-clock sanity: within {args.overhead_budget:.0%} "
          f"+ {args.overhead_slack_s}s timer-noise slack")

    # ---- gate 4: zero bit-impact ----------------------------------------
    def run_single(instrument: bool):
        reset_unique_pubkeys()
        get_registry().reset()
        capacity.reset_harvests()
        capacity.set_harvest_enabled(instrument)
        mw = memwatch.MemWatch(0.01) if instrument else None
        if mw:
            mw.start()
        try:
            cfg = Config(num_synthetic_nodes=200, gossip_iterations=8,
                         warm_up_rounds=2, seed=args.seed)
            coll = GossipStatsCollection()
            coll.set_number_of_simulations(1)
            dpq = DatapointQueue()
            run_simulation(cfg, "", coll, dpq, 0, "0", 0.0)
            return (coll.collection[0].parity_snapshot(),
                    dpq.drain_deterministic_lines())
        finally:
            if mw:
                mw.stop()
            capacity.set_harvest_enabled(False)
    snap_a, wire_a = run_single(False)
    snap_b, wire_b = run_single(True)
    check(snap_a == snap_b, "harvest+memwatch move zero bits of the "
                            "stats parity snapshot")
    check(wire_a == wire_b, "harvest+memwatch move zero bits of the "
                            "deterministic Influx wire lines")

    dpq = DatapointQueue()
    dp = InfluxDataPoint("0")
    dp.create_sim_capacity_point({"peak_rss_bytes": 123, "x": 1.5})
    dpq.push_back(dp)
    check(dpq.drain_deterministic_lines() == [],
          "sim_capacity excluded from the deterministic wire surface")

    print(f"  elapsed: {time.time() - t_start:.1f}s")
    if failures:
        print(f"CAPACITY SMOKE FAILED ({len(failures)} invariant(s)):")
        for f in failures:
            print(f"  - {f}")
        return 1
    print("CAPACITY SMOKE PASSED")
    return 0


if __name__ == "__main__":
    sys.exit(main())
