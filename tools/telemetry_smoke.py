"""Live-telemetry smoke test: the CI gate for obs/telemetry.py +
obs/exporter.py (ISSUE 18).

Fast CPU gate (~3 min) over four contracts:

  1. **Mid-run scrape**: a live 1k-node traffic run started with
     ``--telemetry-port 0`` (CLI on a background thread; signal_guard
     no-ops off the main thread) has its ephemeral port discovered from
     the event log's ``telemetry_listen`` record alone, then ``/metrics``
     is polled until the ``origin_iters`` counter is nonzero AND
     advances between scrapes — strictly-parsed Prometheus text the
     whole way.  ``/status`` mid-run must be a schema-valid run report
     with the bound port stamped; ``/events`` must be schema-valid JSON.
  2. **Journal join**: a lane sweep is killed after its first committed
     unit (rc 75), then ``--resume``d to completion — both processes*
     appending to ONE ``--event-log``.  The log must validate against
     the v1 schema (including the seq restart at the resume boundary),
     and its ``journal_commit`` events must join 1:1 against the
     journal's committed units on ``(run-key fingerprint, unit id)``,
     with the fingerprint recomputed independently from the journal
     header.  (*in-process runs: cli.main's reset block is the process
     boundary under test.)
  3. **Zero bit-impact**: the full plane — open event log, bound
     exporter, a scraper thread hammering /metrics + /status throughout
     the run — moves no bit of the stats parity snapshot or the
     deterministic Influx wire lines.
  4. **Overhead** < ``--overhead-budget`` (default 2%) + absolute timer
     slack, obs_smoke-style: warm best-of-N CLI arms with and without
     ``--telemetry-port 0 --event-log``.

Usage: python tools/telemetry_smoke.py [--traffic-nodes 1000]
       [--seed 7] [--reps 2] [--overhead-budget 0.02]
       [--overhead-slack-s 0.2]

Exit code 0 = the live-telemetry contract holds; 1 = it broke.
"""
import argparse
import json
import os
import sys
import tempfile
import threading
import time
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

RESUMABLE = 75


def _get(url: str, timeout: float = 10.0) -> bytes:
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return resp.read()


def main() -> int:
    ap = argparse.ArgumentParser(
        description="live telemetry plane smoke (CPU, <3 min)")
    ap.add_argument("--traffic-nodes", type=int, default=1000,
                    help="cluster size for the live mid-run scrape gate")
    ap.add_argument("--traffic-iterations", type=int, default=600,
                    help="traffic rounds (>=2 harvest blocks so the "
                         "origin_iters counter visibly advances mid-run)")
    ap.add_argument("--seed", type=int, default=7)
    ap.add_argument("--reps", type=int, default=2)
    ap.add_argument("--overhead-budget", type=float, default=0.02)
    ap.add_argument("--overhead-slack-s", type=float, default=0.2)
    ap.add_argument("--scrape-timeout-s", type=float, default=420.0,
                    help="hard bound on the mid-run scrape gate")
    args = ap.parse_args()

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    from gossip_sim_tpu.cli import main as cli_main
    from gossip_sim_tpu.cli import run_simulation
    from gossip_sim_tpu.config import Config
    from gossip_sim_tpu.identity import reset_unique_pubkeys
    from gossip_sim_tpu.obs import (get_registry, telemetry,
                                    validate_run_report)
    from gossip_sim_tpu.obs.exporter import (TelemetryServer,
                                             parse_prometheus_text)
    from gossip_sim_tpu.obs.telemetry import (EVENT_SCHEMA, load_event_log,
                                              run_key_fingerprint,
                                              validate_event,
                                              validate_event_log)
    from gossip_sim_tpu.sinks import DatapointQueue
    from gossip_sim_tpu.stats.gossip_stats import GossipStatsCollection

    t_start = time.time()
    tmp = tempfile.mkdtemp(prefix="telemetry-smoke-")
    failures = []

    def check(ok: bool, msg: str):
        print(f"  [{'ok' if ok else 'FAIL'}] {msg}", flush=True)
        if not ok:
            failures.append(msg)

    # ---- gate 1: mid-run scrape of a live 1k-node traffic run -----------
    print(f"telemetry smoke: live scrape n={args.traffic_nodes} "
          f"iters={args.traffic_iterations}")
    evt1 = os.path.join(tmp, "traffic.events")
    run_result = {}

    def run_traffic():
        run_result["rc"] = cli_main(
            ["--num-synthetic-nodes", str(args.traffic_nodes),
             "--iterations", str(args.traffic_iterations),
             "--warm-up-rounds", "4", "--seed", str(args.seed),
             "--traffic-values", "4", "--traffic-rate", "2",
             "--node-ingress-cap", "24", "--node-egress-cap", "32",
             "--telemetry-port", "0", "--event-log", evt1])

    th = threading.Thread(target=run_traffic, name="cli-under-test")
    th.start()

    # port discovery from the event log alone (the telemetry_watch path)
    deadline = time.time() + args.scrape_timeout_s
    port = None
    while time.time() < deadline and th.is_alive() and port is None:
        if os.path.exists(evt1):
            for rec in load_event_log(evt1):
                if rec.get("ev") == "telemetry_listen":
                    port = rec.get("port")
        if port is None:
            time.sleep(0.05)
    check(port is not None,
          f"ephemeral port discovered from the event log ({port})")

    first_oi = 0.0
    advanced_oi = 0.0
    mid_status = None
    mid_events = None
    mid_progress = False
    base = f"http://127.0.0.1:{port}" if port else ""
    while port and time.time() < deadline and th.is_alive():
        try:
            metrics = parse_prometheus_text(_get(base + "/metrics").decode())
        except OSError:
            break  # run finished between the liveness check and the GET
        oi = metrics.get("gossip_sim_counter_total", {}).get(
            '{counter="origin_iters"}', 0.0)
        if oi > 0 and not first_oi:
            first_oi = oi
            # grab the other two endpoints now, provably mid-run
            mid_status = json.loads(_get(base + "/status"))
            mid_events = json.loads(_get(base + "/events"))
            mid_progress = bool(metrics.get("gossip_sim_progress_done"))
        elif first_oi and oi > first_oi:
            advanced_oi = oi
            break
        time.sleep(0.025)
    th.join(timeout=args.scrape_timeout_s)
    check(not th.is_alive() and run_result.get("rc") == 0,
          f"scraped traffic run exits 0 (rc={run_result.get('rc')})")
    check(first_oi > 0,
          f"mid-run /metrics scrape parsed strictly with nonzero "
          f"origin_iters ({int(first_oi)})")
    check(advanced_oi > first_oi,
          f"round counters advance between mid-run scrapes "
          f"({int(first_oi)} -> {int(advanced_oi)})")
    check(mid_progress, "progress gauges present mid-run "
                        "(gossip_sim_progress_done)")
    if mid_status is not None:
        check(validate_run_report(mid_status) == [],
              "mid-run /status is a schema-valid run report")
        check(mid_status.get("telemetry", {}).get("port") == port,
              f"bound port stamped into the live report "
              f"({mid_status.get('telemetry', {}).get('port')})")
    else:
        check(False, "mid-run /status scrape captured")
    if mid_events is not None:
        evs = mid_events.get("events", [])
        check(mid_events.get("schema") == EVENT_SCHEMA and evs
              and not any(p for e in evs for p in validate_event(e)),
              f"mid-run /events is schema-valid JSON ({len(evs)} events)")
    else:
        check(False, "mid-run /events scrape captured")
    log_problems = validate_event_log(evt1)
    check(log_problems == [],
          f"traffic event log validates against v1 "
          f"({log_problems[:3] or 'clean'})")
    kinds = {r.get("ev") for r in load_event_log(evt1)}
    for want in ("run_start", "telemetry_listen", "heartbeat", "run_end"):
        check(want in kinds, f"event log carries {want}")

    # ---- gate 2: interrupted+resumed lane sweep joins the journal -------
    ck = os.path.join(tmp, "sweep.npz")
    evt2 = os.path.join(tmp, "sweep.events")
    sweep_argv = ["--num-synthetic-nodes", "300", "--iterations", "10",
                  "--warm-up-rounds", "4", "--seed", "11",
                  "--test-type", "packet-loss", "--num-simulations", "6",
                  "--step-size", "0.05", "--packet-loss-rate", "0.05",
                  "--sweep-lanes", "2", "--checkpoint-path", ck,
                  "--event-log", evt2]
    os.environ["GOSSIP_RESILIENCE_KILL_AFTER_UNITS"] = "1"
    try:
        rc_kill = cli_main(sweep_argv)
    finally:
        del os.environ["GOSSIP_RESILIENCE_KILL_AFTER_UNITS"]
    check(rc_kill == RESUMABLE,
          f"killed lane sweep exits with the resumable code "
          f"({rc_kill} == {RESUMABLE})")
    rc_res = cli_main(sweep_argv + ["--resume", ck])
    check(rc_res == 0, f"resumed lane sweep completes (rc={rc_res})")

    log_problems = validate_event_log(evt2)
    check(log_problems == [],
          f"interrupted+resumed event log validates against v1, seq "
          f"restart included ({log_problems[:3] or 'clean'})")
    journal = ck[: -len(".npz")] + ".journal"
    header, units = {}, []
    if os.path.exists(journal):
        with open(journal) as f:
            lines = [ln for ln in f.read().splitlines() if ln.strip()]
        header = json.loads(lines[0])
        units = sorted(json.loads(ln)["unit"] for ln in lines[1:])
    check(units == [0, 1, 2],
          f"journal carries all three lane batches ({units})")
    fp = run_key_fingerprint(header.get("run_key", {}))
    recs = load_event_log(evt2)
    commits = sorted((r["run"], r["unit"]) for r in recs
                     if r.get("ev") == "journal_commit")
    check(commits == [(fp, u) for u in units],
          f"journal_commit events join 1:1 against journal units on "
          f"(fingerprint, unit) — fp {fp}, {len(commits)} commit(s)")
    kinds2 = {r.get("ev") for r in recs}
    for want in ("shutdown_signal", "resumable_exit", "journal_resume"):
        check(want in kinds2, f"event log carries {want}")
    resumed = [r for r in recs if r.get("ev") == "journal_resume"]
    check(bool(resumed) and resumed[0].get("units") == 1,
          f"journal_resume reports the one replayed unit "
          f"({resumed[0].get('units') if resumed else None})")

    # ---- gate 3: zero bit-impact of the whole plane ---------------------
    def run_single(instrument: bool):
        reset_unique_pubkeys()
        get_registry().reset()
        telemetry.reset()
        server = None
        stop = threading.Event()
        scraper = None
        if instrument:
            hub = telemetry.get_hub()
            hub.open_event_log(os.path.join(tmp, "bits.events"))
            hub.set_run_key({"kind": "bit-impact"})
            server = TelemetryServer(port=0)
            p = server.start()

            def hammer():
                while not stop.is_set():
                    try:
                        _get(f"http://127.0.0.1:{p}/metrics", timeout=2)
                        _get(f"http://127.0.0.1:{p}/status", timeout=2)
                    except OSError:
                        pass
                    time.sleep(0.005)

            scraper = threading.Thread(target=hammer, daemon=True)
            scraper.start()
        try:
            cfg = Config(num_synthetic_nodes=200, gossip_iterations=8,
                         warm_up_rounds=2, seed=args.seed)
            coll = GossipStatsCollection()
            coll.set_number_of_simulations(1)
            dpq = DatapointQueue()
            run_simulation(cfg, "", coll, dpq, 0, "0", 0.0)
            return (coll.collection[0].parity_snapshot(),
                    dpq.drain_deterministic_lines())
        finally:
            stop.set()
            if scraper is not None:
                scraper.join(timeout=5)
            if server is not None:
                server.stop()
            telemetry.reset()

    snap_a, wire_a = run_single(False)
    snap_b, wire_b = run_single(True)
    check(snap_a == snap_b, "event log + exporter + live scraping move "
                            "zero bits of the stats parity snapshot")
    check(wire_a == wire_b, "event log + exporter + live scraping move "
                            "zero bits of the deterministic wire lines")

    # ---- gate 4: overhead (obs_smoke-style warm A/B) --------------------
    # large enough that the plane's fixed costs (exporter bind/teardown,
    # event-log open) amortize the way they do on a real run
    base_argv = ["--num-synthetic-nodes", "120", "--iterations", "48",
                 "--warm-up-rounds", "4", "--seed", str(args.seed)]

    def timed_run(extra):
        t0 = time.perf_counter()
        rc = cli_main(base_argv + extra)
        check(rc == 0, f"overhead arm exits 0 ({extra or 'plain'})")
        return time.perf_counter() - t0

    tel_extra = ["--telemetry-port", "0",
                 "--event-log", os.path.join(tmp, "oh.events")]
    timed_run([])  # cold: warm the jit cache for both arms
    t_plain = min(timed_run([]) for _ in range(max(1, args.reps)))
    t_tel = min(timed_run(tel_extra) for _ in range(max(1, args.reps)))
    budget = t_plain * (1.0 + args.overhead_budget) + args.overhead_slack_s
    print(f"  plain={t_plain:.3f}s telemetry={t_tel:.3f}s "
          f"delta={(t_tel - t_plain) / t_plain * 100 if t_plain else 0:+.2f}%")
    check(t_tel <= budget,
          f"telemetry overhead within {args.overhead_budget:.0%} "
          f"+ {args.overhead_slack_s}s timer-noise slack "
          f"(budget {budget:.3f}s)")

    print(f"  elapsed: {time.time() - t_start:.1f}s")
    if failures:
        print(f"TELEMETRY SMOKE FAILED ({len(failures)} invariant(s)):")
        for f in failures:
            print(f"  - {f}")
        return 1
    print("TELEMETRY SMOKE PASSED")
    return 0


if __name__ == "__main__":
    sys.exit(main())
