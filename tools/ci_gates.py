"""Run every fast CI smoke gate in sequence (CPU, ~2 min total).

The gates, in dependency-light-first order:

  chaos_smoke   fault-injection invariants (loss/churn/partition)
  obs_smoke     run-report schema + telemetry overhead < 2%
  trace_smoke   flight-recorder schema/parity/overhead
  sweep_smoke   compile-once sweeps (1 compile across a knob sweep)
  pull_smoke    pull-gossip subsystem (healing, zero bit-impact, parity)
  lane_smoke    device-resident sweep lanes (bit-exact vs serial, 1
                compile, wall-clock < serial)
  resume_smoke  resilient execution (ISSUE 7): SIGTERM mid lane sweep ->
                resumable exit code, bit-exact --resume with zero
                persistent-cache misses, journal+watchdog overhead < 2%
  traffic_smoke concurrent traffic (ISSUE 10): M=1/caps-off zero
                bit-impact, 1k-node engine-vs-TrafficOracle parity under
                loss+churn+queue caps, per-value coverage monotone in
                the ingress cap
  adaptive_smoke adaptive push-pull (ISSUE 11): converges >= 1 value on
                the BENCH_r07 traffic config where push converges 0,
                zero bit-impact at mode=push, 1k-node adaptive
                engine-vs-oracle parity under loss+churn+caps
  capacity_smoke capacity observatory (ISSUE 13): ledger bit-exact vs
                live buffer bytes (push/traffic/lanes), schema-valid
                run-report capacity section with nonzero cost-harvest +
                peak-RSS fields, memwatch overhead < 2%, zero bit-impact
                on parity snapshots and wire lines
  health_smoke  node-health observatory (ISSUE 17): --health zero
                bit-impact on parity snapshots and deterministic wire
                lines, 1k-node engine-vs-oracle health-plane parity
                under faults, digest decile sums equal cluster
                aggregates (device == numpy), overhead < 2%
  telemetry_smoke live telemetry plane (ISSUE 18): mid-run /metrics +
                /status scrape of a live 1k-node traffic run on an
                ephemeral --telemetry-port (valid Prometheus text,
                schema-valid JSON, advancing round counters), event-log
                v1 schema validation with a 1:1 join against the run
                journal's committed units, zero bit-impact, overhead <2%
  bench_trend   BENCH_r*.json trend regression (ISSUE 19): the two most
                recent committed bench snapshots compared metric by
                metric; any >10% regression on a tracked metric fails
                CI instead of relying on manual diffing
  sparse_smoke  sparse frontier engine (ISSUE 19): dense/sparse CLI-run
                bit parity at 1k under loss+churn, 1k-node sparse
                engine-vs-CPU-oracle parity, representation=dense
                bit-equal to the committed pre-PR golden, sparse
                capacity-ledger closed forms == live nbytes at two
                (N, C) points, 16GB all-origins fit strictly beyond
                the dense ceiling
  serve_smoke   gossip-as-a-service daemon (ISSUE 20): mid-flight
                continuous-batching admissions bit-identical (parity
                snapshot + deterministic wire lines) to solo
                run_lane_sweep, ledger 413/429/400 refusals with zero
                device allocations, SIGTERM drain -> exit 75 -> --resume
                completes every intake-journaled request bit-exactly
                with zero persistent-cache misses, zero steady-state
                recompiles on the warm dyn-lane executable

Usage: python tools/ci_gates.py [--only NAME[,NAME...]] [--list] [--json]

``--only`` runs a subset (fifteen serial gates take a while — pick the
ones your change touches); ``--list`` prints the registry and exits.
The summary table carries each gate's wall time; ``--json`` replaces it
with one machine-readable JSON object (the last line of output) carrying
per-gate status/rc/wall-time for CI dashboards.

Exit code 0 = every gate passed; 1 = at least one failed (each gate's
output streams through, and a summary prints at the end).
"""
import argparse
import os
import subprocess
import sys
import time

HERE = os.path.dirname(os.path.abspath(__file__))
GATES = ["chaos_smoke", "obs_smoke", "trace_smoke", "sweep_smoke",
         "pull_smoke", "lane_smoke", "resume_smoke", "traffic_smoke",
         "adaptive_smoke", "capacity_smoke", "health_smoke",
         "telemetry_smoke", "bench_trend", "sparse_smoke", "serve_smoke"]

# per-gate extra argv: most gates run bare; bench_trend only gates CI
# when asked to fail on regressions, and only on the newest committed
# round (the history carries known, documented re-budgeting slowdowns)
GATE_ARGS = {"bench_trend": ["--fail-on-regression", "--latest-only"]}


def main() -> int:
    ap = argparse.ArgumentParser(description="run all CI smoke gates")
    ap.add_argument("--only", default="",
                    help="comma-separated subset of gates to run")
    ap.add_argument("--list", action="store_true",
                    help="print the gate registry and exit")
    ap.add_argument("--json", action="store_true",
                    help="print the summary as one machine-readable JSON "
                         "object (the last output line) instead of the "
                         "human table")
    ap.add_argument("--timeout", type=int, default=600,
                    help="per-gate hard timeout (seconds)")
    args = ap.parse_args()
    if args.list:
        for gate in GATES:
            print(gate)
        return 0
    selected = ([g.strip() for g in args.only.split(",") if g.strip()]
                if args.only else GATES)
    unknown = [g for g in selected if g not in GATES]
    if unknown:
        print(f"unknown gate(s): {unknown}; have {GATES}")
        return 2

    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    results = []
    for gate in selected:
        print(f"\n===== {gate} =====", flush=True)
        t0 = time.time()
        try:
            rc = subprocess.run(
                [sys.executable, os.path.join(HERE, f"{gate}.py")]
                + GATE_ARGS.get(gate, []),
                env=env, timeout=args.timeout).returncode
        except subprocess.TimeoutExpired:
            rc = -9
        results.append((gate, rc, round(time.time() - t0, 1)))

    failed = sum(rc != 0 for _, rc, _ in results)
    if args.json:
        import json
        print(json.dumps({
            "gates": [{"name": gate,
                       "status": ("pass" if rc == 0 else
                                  "timeout" if rc == -9 else "fail"),
                       "rc": rc, "wall_s": dt}
                      for gate, rc, dt in results],
            "failed": failed,
            "ok": failed == 0,
        }, sort_keys=True))
        return 1 if failed else 0
    print("\n===== CI gate summary =====")
    for gate, rc, dt in results:
        status = "PASS" if rc == 0 else ("TIMEOUT" if rc == -9
                                         else f"FAIL rc={rc}")
        print(f"  {gate:<15} {status:<12} {dt}s")
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
