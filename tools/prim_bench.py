"""Primitive microbenchmarks with in-jit repetition (axon tunnel has ~70ms
round-trip latency, so single-shot timing is meaningless).  Not shipped."""
import sys
import time
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

REPS = 20


def bench(name, make_fn, *args):
    """make_fn(x, i) -> array; we scan it REPS times with i varying and a
    data dependency threaded through a scalar to defeat CSE/hoisting."""
    try:
        @partial(jax.jit, static_argnums=(1,))
        def run(args, k):
            def body(c, i):
                out = jnp.ravel(make_fn(*args, i + c))
                # dynamic index defeats XLA's slice-through-op simplifications
                pos = ((i * 1297 + c) % out.shape[0]).astype(jnp.int32)
                return lax.dynamic_index_in_dim(
                    out, pos, keepdims=False).astype(jnp.int32), None
            c, _ = lax.scan(body, jnp.int32(0), jnp.arange(k))
            return c
        int(run(args, 1)); int(run(args, REPS + 1))
        t1 = min(time.time() * 0 + _t(run, args, 1) for _ in range(2))
        t2 = min(_t(run, args, REPS + 1) for _ in range(2))
        dt = (t2 - t1) / REPS
        print(f"{name:46s} {dt*1e3:9.3f} ms")
    except Exception as e:
        print(f"{name:46s} FAILED: {type(e).__name__} {str(e)[:90]}")


def _t(run, args, k):
    t0 = time.time()
    int(run(args, k))
    return time.time() - t0


def suite(O, N, S=12, D=64):
    print(f"=== O={O} N={N} S={S} D={D}")
    rng = np.random.default_rng(0)
    tgt = jnp.asarray(rng.integers(0, N, (O, N, S)), dtype=jnp.int32)
    dist = jnp.asarray(rng.integers(0, 15, (O, N)), dtype=jnp.int32)
    inb = jnp.asarray(rng.integers(0, N, (O, N, D)), dtype=jnp.int32)
    o3 = jnp.arange(O)[:, None, None]
    key1 = tgt.reshape(O, N * S)
    key2 = jnp.asarray(rng.integers(0, 1 << 30, (O, N * S)), dtype=jnp.int32)
    keys_i32 = jnp.asarray(rng.integers(0, 1 << 30, (O, N, 50)), jnp.int32)

    bench("scatter_min [O,N,S]->[O,N]",
          lambda t, d, i: d.at[o3, jnp.minimum(t + i, N)].min(
              jnp.broadcast_to(d[:, :, None] + 1, t.shape), mode="drop"),
          tgt, dist)
    bench("scatter_add [O,N,S]->[O,N]",
          lambda t, i: jnp.zeros((O, N), jnp.int32).at[
              o3, jnp.minimum(t + i, N)].add(1, mode="drop"), tgt)
    bench("gather+min [O,N,D]",
          lambda d, ix, i: jnp.min(
              (d + i)[jnp.arange(O)[:, None, None], ix], axis=-1),
          dist, inb)
    bench("gather [O,NS] flat",
          lambda d, t, i: (d + i).reshape(O, N)[
              jnp.arange(O)[:, None], jnp.minimum(t.reshape(O, N * S), N - 1)],
          dist, tgt)
    bench("sort 1key i32 [O,NS]",
          lambda a, i: lax.sort(((a + i) % (1 << 30),), dimension=-1,
                                num_keys=1)[0], key1)
    bench("sort 2key i32 [O,NS]",
          lambda a, b, i: lax.sort((a + i, b), dimension=-1, num_keys=2)[0],
          key1, key2)
    bench("sort rows 1key [O,N,50]",
          lambda a, i: lax.sort((a + i,), dimension=-1, num_keys=1)[0],
          keys_i32)
    bench("sort rows 3key [O,N,50]",
          lambda a, i: lax.sort((a + i, a, a), dimension=-1, num_keys=3)[2],
          keys_i32)
    bench("cummax [O,NS]",
          lambda a, i: lax.cummax(a + i, axis=1), key2)
    bench("assoc_scan min [O,NS]",
          lambda a, i: lax.associative_scan(jnp.minimum, a + i, axis=1), key2)
    bench("top_k 12 [O,N,50]",
          lambda a, i: lax.top_k(a + i, 12)[0], keys_i32)
    bench("binsearch50 [O,N,S] into [O,N,50]",
          lambda q, s, i: _bsearch(s, jnp.minimum(q + i, N)), tgt, keys_i32)
    if N <= 4096:
        A = jnp.asarray(rng.random((O, N, N)) < (S / N), dtype=jnp.bfloat16)
        f8 = jnp.asarray(rng.random((O, 8, N)), dtype=jnp.bfloat16)
        bench("bf16 [O,8,N]@[O,N,N]",
              lambda f, A, i: jnp.matmul(f + i.astype(jnp.bfloat16), A),
              f8, A)
    M = jnp.ones((4096, 4096), jnp.bfloat16)
    bench("bf16 4096^3 matmul",
          lambda m, i: (m + i.astype(jnp.bfloat16)) @ m, M)
    bench("elementwise x*2+1 [O,N,50]",
          lambda a, i: (a + i) * 2 + 1, keys_i32)


def _bsearch(sorted_rows, queries):
    import math
    C = sorted_rows.shape[-1]
    lo = jnp.zeros(queries.shape, jnp.int32)
    hi = jnp.full(queries.shape, C, jnp.int32)
    for _ in range(max(1, math.ceil(math.log2(C))) + 1):
        act = lo < hi
        mid = (lo + hi) // 2
        vals = jnp.take_along_axis(sorted_rows, jnp.minimum(mid, C - 1),
                                   axis=-1)
        less = vals < queries
        lo = jnp.where(act & less, mid + 1, lo)
        hi = jnp.where(act & ~less, mid, hi)
    return lo


if __name__ == "__main__":
    if len(sys.argv) > 1 and sys.argv[1] == "big":
        suite(32, 10000)
    else:
        suite(8, 2000)
