"""TPU primitive microbenchmarks (differential in-jit repetition).

The axon tunnel has ~70ms round-trip latency, so single-shot timing is
meaningless; each op is scanned REPS times inside one jit with a data
dependency threaded through a scalar to defeat CSE/hoisting, and the cost is
(t[REPS+1] - t[1]) / REPS.

Three suites (historically prim_bench{,2,3}.py; collapsed in round 5):
  1 generic primitives (sorts, scatters, gathers, scans, matmuls)
  2 the exact primitives of the sort-routed round (engine/core.py)
  3 block gathers + compacted-F hop ops

Usage: python tools/prim_bench.py [--suite 1|2|3|all] [--big]
Not shipped as part of the package; dev-only.
"""
import argparse
import time
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

REPS = 20


def bench(name, make_fn, *args):
    """make_fn(*args, i) -> array; scanned k times inside one jit."""
    try:
        @partial(jax.jit, static_argnums=(1,))
        def run(args, k):
            def body(c, i):
                out = jnp.ravel(make_fn(*args, i + c))
                # dynamic index defeats XLA's slice-through-op simplifications
                pos = ((i * 1297 + c) % out.shape[0]).astype(jnp.int32)
                return lax.dynamic_index_in_dim(
                    out, pos, keepdims=False).astype(jnp.int32), None
            c, _ = lax.scan(body, jnp.int32(0), jnp.arange(k))
            return c
        int(run(args, 1)); int(run(args, REPS + 1))
        t1 = min(_t(run, args, 1) for _ in range(2))
        t2 = min(_t(run, args, REPS + 1) for _ in range(2))
        print(f"{name:52s} {(t2-t1)/REPS*1e3:9.3f} ms")
    except Exception as e:
        print(f"{name:52s} FAILED: {type(e).__name__} {str(e)[:80]}")


def _t(run, args, k):
    t0 = time.time()
    int(run(args, k))
    return time.time() - t0


# --------------------------------------------------------------------------
# suite 1: generic primitives
# --------------------------------------------------------------------------

def suite1(O, N, S=12, D=64):
    print(f"=== suite 1 (generic): O={O} N={N} S={S} D={D}")
    rng = np.random.default_rng(0)
    tgt = jnp.asarray(rng.integers(0, N, (O, N, S)), dtype=jnp.int32)
    dist = jnp.asarray(rng.integers(0, 15, (O, N)), dtype=jnp.int32)
    inb = jnp.asarray(rng.integers(0, N, (O, N, D)), dtype=jnp.int32)
    o3 = jnp.arange(O)[:, None, None]
    key1 = tgt.reshape(O, N * S)
    key2 = jnp.asarray(rng.integers(0, 1 << 30, (O, N * S)), dtype=jnp.int32)
    keys_i32 = jnp.asarray(rng.integers(0, 1 << 30, (O, N, 50)), jnp.int32)

    bench("scatter_min [O,N,S]->[O,N]",
          lambda t, d, i: d.at[o3, jnp.minimum(t + i, N)].min(
              jnp.broadcast_to(d[:, :, None] + 1, t.shape), mode="drop"),
          tgt, dist)
    bench("scatter_add [O,N,S]->[O,N]",
          lambda t, i: jnp.zeros((O, N), jnp.int32).at[
              o3, jnp.minimum(t + i, N)].add(1, mode="drop"), tgt)
    bench("gather+min [O,N,D]",
          lambda d, ix, i: jnp.min(
              (d + i)[jnp.arange(O)[:, None, None], ix], axis=-1),
          dist, inb)
    bench("gather [O,NS] flat",
          lambda d, t, i: (d + i).reshape(O, N)[
              jnp.arange(O)[:, None], jnp.minimum(t.reshape(O, N * S), N - 1)],
          dist, tgt)
    bench("sort 1key i32 [O,NS]",
          lambda a, i: lax.sort(((a + i) % (1 << 30),), dimension=-1,
                                num_keys=1)[0], key1)
    bench("sort 2key i32 [O,NS]",
          lambda a, b, i: lax.sort((a + i, b), dimension=-1, num_keys=2)[0],
          key1, key2)
    bench("sort rows 1key [O,N,50]",
          lambda a, i: lax.sort((a + i,), dimension=-1, num_keys=1)[0],
          keys_i32)
    bench("sort rows 3key [O,N,50]",
          lambda a, i: lax.sort((a + i, a, a), dimension=-1, num_keys=3)[2],
          keys_i32)
    bench("cummax [O,NS]",
          lambda a, i: lax.cummax(a + i, axis=1), key2)
    bench("assoc_scan min [O,NS]",
          lambda a, i: lax.associative_scan(jnp.minimum, a + i, axis=1), key2)
    bench("top_k 12 [O,N,50]",
          lambda a, i: lax.top_k(a + i, 12)[0], keys_i32)
    bench("binsearch50 [O,N,S] into [O,N,50]",
          lambda q, s, i: _bsearch(s, jnp.minimum(q + i, N)), tgt, keys_i32)
    if N <= 4096:
        A = jnp.asarray(rng.random((O, N, N)) < (S / N), dtype=jnp.bfloat16)
        f8 = jnp.asarray(rng.random((O, 8, N)), dtype=jnp.bfloat16)
        bench("bf16 [O,8,N]@[O,N,N]",
              lambda f, A, i: jnp.matmul(f + i.astype(jnp.bfloat16), A),
              f8, A)
    M = jnp.ones((4096, 4096), jnp.bfloat16)
    bench("bf16 4096^3 matmul",
          lambda m, i: (m + i.astype(jnp.bfloat16)) @ m, M)
    bench("elementwise x*2+1 [O,N,50]",
          lambda a, i: (a + i) * 2 + 1, keys_i32)


def _bsearch(sorted_rows, queries):
    import math
    C = sorted_rows.shape[-1]
    lo = jnp.zeros(queries.shape, jnp.int32)
    hi = jnp.full(queries.shape, C, jnp.int32)
    for _ in range(max(1, math.ceil(math.log2(C))) + 1):
        act = lo < hi
        mid = (lo + hi) // 2
        vals = jnp.take_along_axis(sorted_rows, jnp.minimum(mid, C - 1),
                                   axis=-1)
        less = vals < queries
        lo = jnp.where(act & less, mid + 1, lo)
        hi = jnp.where(act & ~less, mid, hi)
    return lo


# --------------------------------------------------------------------------
# suite 2: the exact primitives of the sort-routed round
# --------------------------------------------------------------------------

def suite2(O, N, S=12, C=64, K=16, H=64):
    print(f"=== suite 2 (round primitives): O={O} N={N} S={S} C={C} K={K}")
    rng = np.random.default_rng(0)
    NS = N * S
    NK = N * K
    tgt = jnp.asarray(rng.integers(0, N, (O, N, S)), dtype=jnp.int32)
    dist = jnp.asarray(rng.integers(0, 15, (O, N)), dtype=jnp.int32)
    idxK = jnp.asarray(rng.integers(0, N, (O, N, K)), dtype=jnp.int32)
    table = jnp.asarray(rng.integers(0, 1 << 30, (N + 1,)), dtype=jnp.int32)
    flatNK = jnp.asarray(rng.integers(0, N * K, (O, NK)), dtype=jnp.int32)
    valsNK = jnp.asarray(rng.integers(0, 1 << 30, (O, NK)), dtype=jnp.int32)
    key1 = jnp.sort(tgt.reshape(O, NS), axis=-1)
    key2 = jnp.asarray(rng.integers(0, 1 << 30, (O, NS)), dtype=jnp.int32)
    rows62 = jnp.asarray(rng.integers(0, 1 << 30, (O, N, C + K)), jnp.int32)
    startpos = jnp.asarray(
        np.sort(rng.integers(0, NS + N, (O, N)), axis=-1), jnp.int32)

    bench("gather [O,N,K] from [N+1] table",
          lambda ix, t, i: (t + i)[ix], idxK, table)
    bench("gather [O,N] from [O,NS+N] (BFS extract)",
          lambda sp, v, i: jnp.take_along_axis(
              jnp.concatenate([v + i, v[:, :N]], axis=1), sp, axis=1),
          startpos, key2)
    bench("scatter [O,NK]->[O,N,K] i32",
          lambda f, v, i: jnp.zeros((O, N * K), jnp.int32).at[
              jnp.arange(O)[:, None], f].set(v + i, mode="drop"),
          flatNK, valsNK)
    bench("sort [O,NS] 2key+2payload",
          lambda a, b, i: lax.sort((a, b + i, b, b), dimension=-1,
                                   num_keys=2)[2], key1, key2)
    bench("sort [O,NS] 1key+1payload",
          lambda a, b, i: lax.sort((a + i, b), dimension=-1, num_keys=1)[1],
          key1, key2)
    bench("row sort [O,N,C+K] 1key+2payload",
          lambda r, i: lax.sort((r + i, r, r), dimension=-1, num_keys=1)[1],
          rows62)
    bench("row sort [O,N,C+K] 4key",
          lambda r, i: lax.sort((r + i, r, r, r), dimension=-1, num_keys=4)[3],
          rows62)
    bench("seg log-shift min [O,NS]",
          lambda k1, v, i: _seg_min(k1, v + i), key1, key2)
    bench("onehot hist [O,N]->[O,H]",
          lambda d, i: jnp.sum(
              ((d + i) % H)[:, :, None] == jnp.arange(H)[None, None, :],
              axis=1, dtype=jnp.int32), dist)
    bench("cumsum i64-as-2xi32 rows [O,N,C]",
          lambda r, i: _cumsum64(r[..., :C] + i, r[..., :C]), rows62)
    bench("while10 x elementwise [O,NS]",
          lambda v, i: lax.while_loop(
              lambda c: c[1] < 10,
              lambda c: (jnp.minimum(c[0], c[0] * 3 + i), c[1] + 1),
              (v, jnp.int32(0)))[0], key2)


def _seg_min(sorted_keys, vals):
    O, M = vals.shape
    is_start = jnp.concatenate(
        [jnp.ones((O, 1), bool),
         sorted_keys[:, 1:] != sorted_keys[:, :-1]], axis=1)
    x = vals
    blocked = is_start
    sh = 1
    while sh < M:
        prev = jnp.pad(x, ((0, 0), (sh, 0)), constant_values=1 << 30)[:, :M]
        pb = jnp.pad(blocked, ((0, 0), (sh, 0)), constant_values=True)[:, :M]
        x = jnp.where(blocked, x, jnp.minimum(x, prev))
        blocked = blocked | pb
        sh *= 2
    return x


def _cumsum64(hi, lo):
    chi = jnp.cumsum(hi, axis=-1)
    clo = jnp.cumsum(lo, axis=-1)
    return chi + (clo >> 16)


# --------------------------------------------------------------------------
# suite 3: block gathers + compacted-F hop ops
# --------------------------------------------------------------------------

def suite3(O, N, F=6, K=16):
    print(f"=== suite 3 (hop ops): O={O} N={N} F={F} K={K}")
    rng = np.random.default_rng(0)
    NF = N * F
    M = NF + N
    vals = jnp.asarray(rng.integers(0, 1 << 30, (O, M + K)), jnp.int32)
    startpos = jnp.asarray(
        np.sort(rng.integers(0, M, (O, N)), axis=-1), jnp.int32)
    keyNF = jnp.sort(jnp.asarray(
        rng.integers(0, 2 * N, (O, NF)), jnp.int32), axis=-1)

    bench("block gather [O,N,K] windows from [O,M]",
          lambda sp, v, i: jnp.take_along_axis(
              v + i, jnp.minimum(
                  sp[:, :, None] + jnp.arange(K)[None, None, :],
                  M + K - 1).reshape(O, N * K), axis=1),
          startpos, vals)
    bench("block gather [O,N,4] windows",
          lambda sp, v, i: jnp.take_along_axis(
              v + i, jnp.minimum(
                  sp[:, :, None] + jnp.arange(4)[None, None, :],
                  M + K - 1).reshape(O, N * 4), axis=1),
          startpos, vals)
    bench("random gather [O,N] from [O,M]",
          lambda sp, v, i: jnp.take_along_axis(v + i, sp, axis=1),
          startpos, vals)
    bench("sort [O,NF] 1key i32",
          lambda a, i: lax.sort(((a + i) % (1 << 29),), dimension=-1,
                                num_keys=1)[0], keyNF)
    bench("sort [O,NF] 1key+1payload",
          lambda a, i: lax.sort((a + i, a), dimension=-1, num_keys=1)[1],
          keyNF)
    bench("sort [O,NF+N] 1key+1payload",
          lambda v, i: lax.sort((v[:, :M] + i, v[:, :M]), dimension=-1,
                                num_keys=1)[1], vals)
    bench("row sort+slice [O,N,12]->[O,N,6]",
          lambda a, i: lax.sort(
              ((a + i).reshape(O, N, 12), a.reshape(O, N, 12)),
              dimension=-1, num_keys=1)[1][..., :6],
          vals[:, :N * 12])


SUITES = {"1": suite1, "2": suite2, "3": suite3}


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--suite", default="all", choices=["1", "2", "3", "all"])
    ap.add_argument("--big", action="store_true",
                    help="O=32 N=10000 (target shapes) instead of O=8 N=2000")
    args = ap.parse_args()
    O, N = (32, 10000) if args.big else (8, 2000)
    for name, fn in SUITES.items():
        if args.suite in (name, "all"):
            fn(O, N)
