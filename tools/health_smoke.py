"""Node-health observatory smoke test: the CI gate for obs/health.py +
the engine health planes (ISSUE 17).

Fast CPU gate (~2 min) over four contracts:

  1. **Zero bit-impact**: enabling ``--health`` moves no bit of the
     stats parity snapshot or the deterministic Influx wire lines, and
     the ``sim_node_health`` series is excluded from the deterministic
     wire surface (it carries run-shaped attribution, like sim_perf /
     sim_capacity).
  2. **1k-node oracle parity**: every engine health plane (sent / recv /
     deferred / queue-dropped / prunes both sides / rescued / latency /
     delivered) matches a loop-based ``TrafficOracle`` recount
     bit-for-bit on the acceptance regime (1024 nodes, loss + churn +
     caps tight enough that queue drops actually fire).
  3. **Digest exactness**: the on-device digest's decile sums equal the
     cluster-wide aggregates exactly, and the whole digest (deciles,
     top-k, Gini parts) is bit-identical to the numpy twin on the real
     planes.
  4. **Overhead < 2%**: the gated-on engine stays within the overhead
     budget of the gated-off engine on an A/B wall-clock comparison
     (absolute slack absorbs CI timer noise on sub-second runs).

Usage: python tools/health_smoke.py [--seed 7] [--reps 2]
       [--overhead-budget 0.02] [--overhead-slack-s 0.2]

Exit code 0 = all contracts hold; 1 = a health invariant failed.
"""
import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    ap = argparse.ArgumentParser(
        description="node-health observatory smoke (CPU, <2min)")
    ap.add_argument("--seed", type=int, default=7)
    ap.add_argument("--reps", type=int, default=2)
    ap.add_argument("--overhead-budget", type=float, default=0.02)
    ap.add_argument("--overhead-slack-s", type=float, default=0.2)
    args = ap.parse_args()

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import numpy as np

    from gossip_sim_tpu.cli import run_simulation
    from gossip_sim_tpu.config import Config
    from gossip_sim_tpu.engine import make_cluster_tables
    from gossip_sim_tpu.engine.params import EngineParams
    from gossip_sim_tpu.engine.traffic import (device_traffic_tables,
                                               init_traffic_state,
                                               run_traffic_rounds)
    from gossip_sim_tpu.identity import reset_unique_pubkeys
    from gossip_sim_tpu.obs import health
    from gossip_sim_tpu.obs.spans import get_registry
    from gossip_sim_tpu.sinks import DatapointQueue, InfluxDataPoint
    from gossip_sim_tpu.stats.gossip_stats import GossipStatsCollection
    from gossip_sim_tpu.traffic import TrafficOracle

    t_start = time.time()
    failures = []

    def check(ok: bool, msg: str):
        print(f"  [{'ok' if ok else 'FAIL'}] {msg}")
        if not ok:
            failures.append(msg)

    def stakes(n):
        rng = np.random.default_rng(args.seed)
        return rng.choice(np.arange(1, 50 * n), size=n,
                          replace=False).astype(np.int64) * 10**6

    # ---- gate 1: zero bit-impact -----------------------------------------
    print("[1/4] zero bit-impact of --health on the deterministic surface")

    def run_single(health_on: bool):
        reset_unique_pubkeys()
        get_registry().reset()
        cfg = Config(num_synthetic_nodes=200, gossip_iterations=8,
                     warm_up_rounds=2, seed=args.seed, health=health_on)
        coll = GossipStatsCollection()
        coll.set_number_of_simulations(1)
        dpq = DatapointQueue()
        run_simulation(cfg, "", coll, dpq, 0, "0", 0.0)
        return (coll.collection[0].parity_snapshot(),
                dpq.drain_deterministic_lines())

    snap_off, wire_off = run_single(False)
    snap_on, wire_on = run_single(True)
    check(snap_off == snap_on,
          "--health moves zero bits of the stats parity snapshot")
    check(wire_off == wire_on,
          "--health moves zero bits of the deterministic Influx wire lines")

    dpq = DatapointQueue()
    dp = InfluxDataPoint("0")
    dp.create_sim_node_health_point(0, {"queue_dropped_total": 12,
                                        "queue_dropped_gini": 0.4})
    dpq.push_back(dp)
    check(dpq.drain_deterministic_lines() == [],
          "sim_node_health excluded from the deterministic wire surface")

    # ---- gate 2: 1k-node oracle parity -----------------------------------
    print("[2/4] 1k-node engine-vs-oracle plane parity under faults")
    plane_to_oracle = {
        "sent_acc": "node_sent", "recv_acc": "node_recv",
        "defer_acc": "node_deferred", "qdrop_acc": "node_queue_dropped",
        "prune_acc": "node_prune_sent",
        "health_prune_recv": "node_prune_recv",
        "health_lat_acc": "node_lat_sum",
        "health_del_acc": "node_delivered",
        "health_rescued_acc": "node_rescued",
    }
    n = 1024
    rounds = 6
    params = EngineParams(
        num_nodes=n, traffic_values=16, traffic_rate=3,
        node_ingress_cap=24, node_egress_cap=48, traffic_stall_rounds=3,
        warm_up_rounds=0, probability_of_rotation=0.05, impair_seed=99,
        packet_loss_rate=0.15, churn_fail_rate=0.03,
        churn_recover_rate=0.3, min_num_upserts=5, health=True).validate()
    sk = stakes(n)
    tables = make_cluster_tables(sk)
    tt = device_traffic_tables(sk)
    st = init_traffic_state(sk, params, args.seed)
    st, _ = run_traffic_rounds(params, tables, tt, st, rounds)

    orc = TrafficOracle(
        sk, seed=args.seed, impair_seed=params.impair_seed,
        traffic_values=params.traffic_values,
        traffic_rate=params.traffic_rate,
        node_ingress_cap=params.node_ingress_cap,
        node_egress_cap=params.node_egress_cap,
        traffic_stall_rounds=params.traffic_stall_rounds,
        push_fanout=params.push_fanout,
        active_set_size=params.active_set_size,
        min_num_upserts=params.min_num_upserts,
        probability_of_rotation=params.probability_of_rotation,
        packet_loss_rate=params.packet_loss_rate,
        churn_fail_rate=params.churn_fail_rate,
        churn_recover_rate=params.churn_recover_rate)
    acc = {f: np.zeros(n, np.int64) for f in plane_to_oracle}
    for it in range(rounds):
        tr = orc.run_round(it)
        for plane, fld in plane_to_oracle.items():
            acc[plane] += getattr(tr, fld)
    for plane in plane_to_oracle:
        check(np.array_equal(np.asarray(getattr(st, plane)), acc[plane]),
              f"plane {plane} bit-equal to oracle recount")
    check(acc["qdrop_acc"].sum() > 0,
          f"regime exercises queue drops ({acc['qdrop_acc'].sum()} drops)")

    # ---- gate 3: digest exactness ----------------------------------------
    print("[3/4] digest: decile sums equal aggregates, device == numpy")
    ids = health.stake_decile_ids(sk)
    stack = np.stack([np.asarray(getattr(st, p), np.int64)
                      for p in plane_to_oracle])
    dv = health.digest_stack(stack, ids, 10)
    nv = health.digest_stack_np(stack, ids, 10)
    for key in nv:
        check(np.array_equal(dv[key], nv[key]),
              f"digest[{key}] device == numpy twin")
    check(np.array_equal(dv["deciles"].sum(axis=1), stack.sum(axis=1)),
          "decile sums equal the cluster-wide aggregates exactly")

    # ---- gate 4: health overhead < budget --------------------------------
    print("[4/4] health overhead within budget (A/B wall clock)")

    def timed_run(health_on: bool):
        reset_unique_pubkeys()
        get_registry().reset()
        cfg = Config(num_synthetic_nodes=400, gossip_iterations=16,
                     warm_up_rounds=4, seed=args.seed, health=health_on)
        coll = GossipStatsCollection()
        coll.set_number_of_simulations(1)
        t0 = time.perf_counter()
        run_simulation(cfg, "", coll, DatapointQueue(), 0, "0", 0.0)
        return time.perf_counter() - t0

    timed_run(False)  # cold: warm the jit cache shapes
    timed_run(True)
    t_off = min(timed_run(False) for _ in range(max(1, args.reps)))
    t_on = min(timed_run(True) for _ in range(max(1, args.reps)))
    overhead = (t_on - t_off) / t_off if t_off > 0 else 0.0
    budget = t_off * (1.0 + args.overhead_budget) + args.overhead_slack_s
    print(f"  off={t_off:.3f}s on={t_on:.3f}s "
          f"wall delta={overhead * 100:+.2f}%")
    check(t_on <= budget,
          f"health overhead within {args.overhead_budget:.0%} "
          f"+ {args.overhead_slack_s}s timer-noise slack")

    print(f"  elapsed: {time.time() - t_start:.1f}s")
    if failures:
        print(f"HEALTH SMOKE FAILED ({len(failures)} invariant(s)):")
        for f in failures:
            print(f"  - {f}")
        return 1
    print("HEALTH SMOKE PASSED")
    return 0


if __name__ == "__main__":
    sys.exit(main())
