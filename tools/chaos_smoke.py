"""Chaos smoke test: a 1k-node sim under packet loss + churn + partition
with fixed seeds, asserting graceful degradation and recovery.

Fast CI gate (CPU, well under 60s): runs the jitted engine directly —
warm-up-free, single origin — through three phases:

  1. baseline      [0, partition_at)           loss + churn only
  2. partitioned   [partition_at, heal_at)     cross-partition edges suppressed
  3. healed        [heal_at, iterations)       loss + churn only again

and checks the robustness contract: coverage under partition collapses to
roughly the origin's side, suppression happens only inside the window,
churn holds a nonzero failed population that also shrinks (recovery), and
post-heal coverage regains COVERAGE_RECOVERY_THRESHOLD within
--recover-within iterations.

Usage: python tools/chaos_smoke.py [--num-nodes 1000] [--seed 7]
       [--packet-loss 0.1] [--churn-fail 0.01] [--churn-recover 0.2]
       [--partition-at 8] [--heal-at 20] [--iterations 40]
       [--recover-within 10]

Exit code 0 = all assertions hold; 1 = a chaos invariant failed.
"""
import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    ap = argparse.ArgumentParser(
        description="1k-node loss+churn+partition smoke (CPU, <60s)")
    ap.add_argument("--num-nodes", type=int, default=1000)
    ap.add_argument("--seed", type=int, default=7)
    ap.add_argument("--packet-loss", type=float, default=0.1)
    ap.add_argument("--churn-fail", type=float, default=0.01)
    ap.add_argument("--churn-recover", type=float, default=0.2)
    ap.add_argument("--partition-at", type=int, default=8)
    ap.add_argument("--heal-at", type=int, default=20)
    ap.add_argument("--iterations", type=int, default=40)
    ap.add_argument("--recover-within", type=int, default=10,
                    help="iterations after heal by which coverage must "
                         "regain the recovery threshold")
    args = ap.parse_args()

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax
    import jax.numpy as jnp
    import numpy as np

    from gossip_sim_tpu.constants import COVERAGE_RECOVERY_THRESHOLD
    from gossip_sim_tpu.engine import (EngineParams, init_state,
                                       make_cluster_tables, run_rounds)

    t0 = time.time()
    n = args.num_nodes
    rng = np.random.default_rng(args.seed)
    stakes = rng.choice(np.arange(1, 50 * n), size=n,
                        replace=False).astype(np.int64) * 10**9
    tables = make_cluster_tables(stakes)
    params = EngineParams(
        num_nodes=n, warm_up_rounds=0,
        packet_loss_rate=args.packet_loss,
        churn_fail_rate=args.churn_fail,
        churn_recover_rate=args.churn_recover,
        partition_at=args.partition_at, heal_at=args.heal_at,
        impair_seed=args.seed).validate()
    origins = jnp.arange(1, dtype=jnp.int32)
    state = init_state(jax.random.PRNGKey(args.seed), tables, origins, params)
    state, rows = run_rounds(params, tables, origins, state, args.iterations)

    cov = np.asarray(rows["coverage"])[:, 0]
    sup = np.asarray(rows["suppressed"])[:, 0]
    drop = np.asarray(rows["dropped"])[:, 0]
    failed = np.asarray(rows["failed_count"])[:, 0]
    pa, ha = args.partition_at, args.heal_at

    failures = []

    def check(ok: bool, msg: str):
        print(f"  [{'ok' if ok else 'FAIL'}] {msg}")
        if not ok:
            failures.append(msg)

    print(f"chaos smoke: n={n} loss={args.packet_loss} "
          f"churn={args.churn_fail}/{args.churn_recover} "
          f"partition=[{pa},{ha}) iters={args.iterations}")
    print(f"  coverage: baseline={cov[:pa].mean():.3f} "
          f"partitioned={cov[pa:ha].mean():.3f} "
          f"healed-tail={cov[ha + args.recover_within:].mean():.3f}")

    check(drop.sum() > 0, "packet loss dropped messages")
    check(sup[pa:ha].sum() > 0, "partition suppressed cross-edges")
    check(sup[:pa].sum() == 0 and sup[ha:].sum() == 0,
          "no suppression outside the partition window")
    check(failed[1:].max() > 0, "churn failed some nodes")
    check((np.diff(failed.astype(np.int64)) < 0).any(),
          "churned nodes recovered (failed set shrank)")
    check(cov[pa:ha].max() < COVERAGE_RECOVERY_THRESHOLD,
          "partition degraded coverage below the recovery threshold")
    window = cov[ha:ha + args.recover_within]
    check(window.size > 0 and
          (window >= COVERAGE_RECOVERY_THRESHOLD).any(),
          f"coverage recovered >= {COVERAGE_RECOVERY_THRESHOLD} within "
          f"{args.recover_within} iterations of heal")

    dt = time.time() - t0
    print(f"  elapsed: {dt:.1f}s")
    if failures:
        print(f"CHAOS SMOKE FAILED ({len(failures)} invariant(s)):")
        for f in failures:
            print(f"  - {f}")
        return 1
    print("CHAOS SMOKE PASSED")
    return 0


if __name__ == "__main__":
    sys.exit(main())
