"""Pull-gossip smoke test: the anti-entropy subsystem's CI gate (pull.py).

Fast CPU gate (<60s) over three contracts:

  1. **Healing**: under heavy packet loss (default 20%), push-pull mode's
     mean measured coverage is >= push-only's, and pull actually rescues
     stranded nodes (nonzero rescue count).
  2. **Zero bit-impact**: with --gossip-mode push, every engine row and
     every SimState array is bit-identical to the engine's defaults — the
     pull subsystem must be invisible when off.
  3. **Oracle parity at 1k nodes**: the sort-routed engine's pull phase and
     the loop-based PullOracle (pull.py) make bit-identical decisions
     round by round (requests/responses/misses/drops/rescues and per-node
     pull hops) under combined packet loss + churn.

Usage: python tools/pull_smoke.py [--num-nodes 1000] [--seed 11]
       [--packet-loss 0.2] [--pull-fanout 3] [--iterations 24]

Exit code 0 = all gates hold; 1 = a pull invariant failed.
"""
import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    ap = argparse.ArgumentParser(
        description="pull-gossip subsystem smoke (CPU, <60s)")
    ap.add_argument("--num-nodes", type=int, default=1000)
    ap.add_argument("--seed", type=int, default=11)
    ap.add_argument("--packet-loss", type=float, default=0.2)
    ap.add_argument("--pull-fanout", type=int, default=3)
    ap.add_argument("--pull-bloom-fp", type=float, default=0.1)
    ap.add_argument("--churn-fail", type=float, default=0.01)
    ap.add_argument("--churn-recover", type=float, default=0.2)
    ap.add_argument("--iterations", type=int, default=24)
    args = ap.parse_args()

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax
    import jax.numpy as jnp
    import numpy as np

    from gossip_sim_tpu.engine import (EngineParams, init_state,
                                       make_cluster_tables, run_rounds)
    from gossip_sim_tpu.pull import PullOracle

    t0 = time.time()
    n, iters = args.num_nodes, args.iterations
    rng = np.random.default_rng(args.seed)
    stakes = rng.choice(np.arange(1, 50 * n), size=n,
                        replace=False).astype(np.int64) * 10**9
    tables = make_cluster_tables(stakes)
    origins = jnp.arange(1, dtype=jnp.int32)

    failures = []

    def check(ok: bool, msg: str):
        print(f"  [{'ok' if ok else 'FAIL'}] {msg}")
        if not ok:
            failures.append(msg)

    def run(params, **kw):
        state = init_state(jax.random.PRNGKey(args.seed), tables, origins,
                           params)
        state, rows = run_rounds(params, tables, origins, state, iters, **kw)
        return state, jax.tree_util.tree_map(np.asarray, rows)

    print(f"pull smoke: n={n} loss={args.packet_loss} "
          f"pull_fanout={args.pull_fanout} iters={iters}")

    # ---- gate 1: push-pull heals a lossy network -------------------------
    lossy = EngineParams(num_nodes=n, warm_up_rounds=0,
                         packet_loss_rate=args.packet_loss,
                         churn_fail_rate=args.churn_fail,
                         churn_recover_rate=args.churn_recover,
                         impair_seed=args.seed).validate()
    pp = lossy._replace(gossip_mode="push-pull",
                        pull_fanout=args.pull_fanout,
                        pull_bloom_fp_rate=args.pull_bloom_fp).validate()
    _, r_push = run(lossy)
    _, r_pp = run(pp)
    cov_push = float(r_push["coverage"].mean())
    cov_pp = float(r_pp["coverage"].mean())
    rescued = int(r_pp["pull_rescued"].sum())
    print(f"  coverage: push-only={cov_push:.4f} push-pull={cov_pp:.4f} "
          f"rescued={rescued}")
    check(cov_pp >= cov_push,
          f"push-pull coverage >= push-only under {args.packet_loss:.0%} "
          f"loss ({cov_pp:.4f} vs {cov_push:.4f})")
    check((r_pp["coverage"] >= r_push["coverage"]).all(),
          "per-round coverage never drops below the push-only run")
    check(rescued > 0, "pull responses rescued stranded nodes")
    check(int((r_pp["pull_requests"]
               - r_pp["pull_responses"] - r_pp["pull_misses"]).sum()) == 0,
          "request accounting closes (requests == responses + misses)")

    # ---- gate 2: mode=push has zero bit-impact ---------------------------
    base = EngineParams(num_nodes=n, warm_up_rounds=0).validate()
    off = base._replace(gossip_mode="push", pull_fanout=7,
                        pull_bloom_fp_rate=0.5, pull_request_cap=2)
    s_a, r_a = run(base, detail=True)
    s_b, r_b = run(off, detail=True)
    bit_ok = set(r_a) == set(r_b) and "pull_requests" not in r_a
    for k in r_a:
        bit_ok &= bool(np.array_equal(r_a[k], r_b[k]))
    for f in s_a._fields:
        bit_ok &= bool(np.array_equal(np.asarray(getattr(s_a, f)),
                                      np.asarray(getattr(s_b, f))))
    check(bit_ok, "mode=push is bit-identical to the pre-pull engine "
                  "(rows + state, pull knobs ignored)")

    # ---- gate 3: 1k-node engine-vs-oracle pull parity --------------------
    _, rows = run(pp, detail=True)
    po = PullOracle(stakes, seed=args.seed, pull_fanout=args.pull_fanout,
                    pull_bloom_fp_rate=args.pull_bloom_fp,
                    pull_slots=pp.pull_slots_resolved,
                    packet_loss_rate=args.packet_loss)
    mismatches = 0
    for r in range(iters):
        res = po.run_round(r, rows["dist"][r, 0], rows["failed_mask"][r, 0])
        for name, val in (("pull_requests", res.requests),
                          ("pull_responses", res.responses),
                          ("pull_misses", res.misses),
                          ("pull_dropped", res.dropped),
                          ("pull_rescued", len(res.rescued))):
            if int(rows[name][r, 0]) != int(val):
                mismatches += 1
        if not np.array_equal(rows["pull_hop"][r, 0],
                              res.pull_hop.astype(np.int32)):
            mismatches += 1
    check(mismatches == 0,
          f"engine pull phase bit-matches PullOracle across {iters} rounds "
          f"at n={n} under loss+churn")

    dt = time.time() - t0
    print(f"  elapsed: {dt:.1f}s")
    if failures:
        print(f"PULL SMOKE FAILED ({len(failures)} invariant(s)):")
        for f in failures:
            print(f"  - {f}")
        return 1
    print("PULL SMOKE PASSED")
    return 0


if __name__ == "__main__":
    sys.exit(main())
