"""Terminal tailer for the live telemetry plane (ISSUE 18).

Two modes:

* **HTTP** (``--url http://127.0.0.1:PORT``): poll ``/status`` +
  ``/metrics`` every ``--interval`` seconds and render a compact live
  line per loop — progress/ETA per heartbeat label, origin-iters
  throughput, RSS, Influx sender deliveries and queue-drop counters.
  ``--once`` prints one frame and exits (scriptable).
* **Event log** (``--event-log PATH``): pretty-print the structured
  event stream (schema ``gossip-sim-tpu/events/v1``); ``--follow``
  keeps tailing as the run appends.

Discovering the port of a live run: the run logs it
("telemetry: serving ... on http://127.0.0.1:PORT"), stamps it into the
run report's ``telemetry.port``, and emits it as a ``telemetry_listen``
event — so ``--event-log PATH --url auto`` resolves the port from the
log's last ``telemetry_listen`` record.

Zero dependencies beyond the stdlib; works against any run started with
``--telemetry-port`` (single, sweeps, lanes, origin-rank, all-origins,
traffic, oracle).  Against a ``--serve`` daemon the frame adds the
gossip-as-a-service view: lane occupancy with per-lane request
id/tenant/progress/ETA, queue depth, per-tenant admitted/rejected
counters, and the ledger budget reservation (serve/, ISSUE 20).

Usage:
  python tools/telemetry_watch.py --url http://127.0.0.1:8321
  python tools/telemetry_watch.py --event-log run.events --url auto
  python tools/telemetry_watch.py --event-log run.events --follow
"""
import argparse
import json
import os
import sys
import time
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _get(url: str, timeout: float = 5.0) -> bytes:
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return resp.read()


def _fmt_bytes(n: float) -> str:
    for unit in ("B", "KiB", "MiB", "GiB", "TiB"):
        if abs(n) < 1024 or unit == "TiB":
            return f"{n:.1f}{unit}" if unit != "B" else f"{int(n)}B"
        n /= 1024
    return f"{n:.1f}TiB"


def _fmt_eta(eta_s) -> str:
    if eta_s is None or eta_s < 0:
        return "?"
    s = int(eta_s)
    return f"{s // 3600}:{s % 3600 // 60:02d}:{s % 60:02d}"


def resolve_url(args) -> str:
    """``--url auto``: pull the port from the event log's last
    ``telemetry_listen`` record."""
    if args.url != "auto":
        return args.url.rstrip("/")
    if not args.event_log:
        raise SystemExit("--url auto needs --event-log to resolve the port")
    from gossip_sim_tpu.obs.telemetry import load_event_log
    port = host = None
    for rec in load_event_log(args.event_log):
        if rec.get("ev") == "telemetry_listen":
            port = rec.get("port")
            host = rec.get("host", "127.0.0.1")
    if not port:
        raise SystemExit(f"no telemetry_listen event in {args.event_log} "
                         f"(was the run started with --telemetry-port?)")
    return f"http://{host}:{port}"


def render_frame(url: str) -> str:
    """One status frame from /status + /metrics."""
    status = json.loads(_get(url + "/status"))
    metrics_raw = _get(url + "/metrics").decode()
    # cheap metric pulls without a full parser dependency
    from gossip_sim_tpu.obs.exporter import parse_prometheus_text
    metrics = parse_prometheus_text(metrics_raw)

    def m(name, default=0.0):
        vals = metrics.get(f"gossip_sim_{name}")
        if not vals:
            return default
        return next(iter(vals.values()))

    lines = []
    thr = status.get("throughput", {})
    lines.append(
        f"run: {status.get('platform', '?')} n={status.get('num_nodes', 0)} "
        f"wall={thr.get('wall_s', 0):.1f}s "
        f"oi/s={thr.get('origin_iters_per_sec', 0):.0f} "
        f"compiles={status.get('compiles', 0)} "
        f"cache_hits={status.get('cache_hits', 0)}")
    # per-label progress gauges
    done = metrics.get("gossip_sim_progress_done", {})
    total = metrics.get("gossip_sim_progress_total", {})
    pct = metrics.get("gossip_sim_progress_pct", {})
    rate = metrics.get("gossip_sim_progress_rate", {})
    eta = metrics.get("gossip_sim_progress_eta_seconds", {})
    for labels in sorted(done):
        label = labels.split('"')[1] if '"' in labels else labels
        e = eta.get(labels, -1)
        lines.append(
            f"  {label}: {int(done[labels])}/{int(total.get(labels, 0))} "
            f"({pct.get(labels, 0):.1f}%) {rate.get(labels, 0):.2f}/s "
            f"ETA {_fmt_eta(None if e < 0 else e)}")
    rss = m("rss_bytes")
    peak = m("peak_rss_bytes")
    lines.append(f"  rss: {_fmt_bytes(rss)} (peak {_fmt_bytes(peak)})")
    influx = status.get("influx", {})
    if influx:
        lines.append(
            f"  influx: sent={influx.get('points_sent', 0)} "
            f"retries={influx.get('retries', 0)} "
            f"spooled={influx.get('spooled_points', 0)} "
            f"dropped={influx.get('dropped_points', 0)} "
            f"queue={influx.get('queue_depth', 0)}")
    # queue-drop / delivery counters (traffic + faulted runs)
    counters = status.get("counters", {})
    drops = {k: v for k, v in counters.items()
             if "drop" in k or k == "messages_delivered"}
    if drops:
        lines.append("  counters: " + " ".join(
            f"{k}={int(v)}" for k, v in sorted(drops.items())))
    # gossip-as-a-service daemon view (serve/, ISSUE 20)
    serve = status.get("serve") or {}
    if serve.get("enabled"):
        lines.append(
            f"  serve: {serve.get('busy', 0)}/{serve.get('lanes', 0)} "
            f"lane(s) busy, {serve.get('queued', 0)} queued "
            f"(block {serve.get('block_rounds', 0)} rounds"
            + (", DRAINING" if serve.get("draining") else "") + ")")
        lines.append(
            f"    requests: {serve.get('admitted', 0)} admitted / "
            f"{serve.get('rejected', 0)} rejected / "
            f"{serve.get('completed', 0)} done of "
            f"{serve.get('received', 0)} received")
        if serve.get("budget_bytes"):
            lines.append(
                f"    budget: {_fmt_bytes(serve.get('bytes_in_use', 0))} "
                f"of {_fmt_bytes(serve['budget_bytes'])} reserved")
        adm = serve.get("tenants_admitted") or {}
        rej = serve.get("tenants_rejected") or {}
        for tenant in sorted(set(adm) | set(rej)):
            lines.append(f"      {tenant}: {adm.get(tenant, 0)} admitted, "
                         f"{rej.get(tenant, 0)} rejected")
        for ld in serve.get("lane_detail") or []:
            if ld.get("busy"):
                lines.append(
                    f"    lane {ld.get('lane')}: {ld.get('id')} "
                    f"({ld.get('tenant')}) "
                    f"{ld.get('rounds_done', 0)}/"
                    f"{ld.get('total_rounds', 0)} rounds "
                    f"ETA {_fmt_eta(ld.get('eta_s', -1))}")
            else:
                lines.append(f"    lane {ld.get('lane')}: idle")
    committed = m("journal_committed_units_total")
    if committed:
        lines.append(f"  journal: {int(committed)} unit(s) committed, "
                     f"resumable")
    ev = m("events_emitted_total")
    lines.append(f"  events: {int(ev)} emitted")
    return "\n".join(lines)


def watch_http(args) -> int:
    url = resolve_url(args)
    while True:
        try:
            frame = render_frame(url)
        except (OSError, ValueError) as e:
            if args.once:
                print(f"scrape failed: {e}", file=sys.stderr)
                return 1
            print(f"[{time.strftime('%H:%M:%S')}] scrape failed: {e} "
                  f"(run finished?)")
            return 0
        print(f"[{time.strftime('%H:%M:%S')}] {url}")
        print(frame)
        if args.once:
            return 0
        time.sleep(max(0.2, args.interval))


def _render_event(rec: dict) -> str:
    ts = time.strftime("%H:%M:%S", time.localtime(rec.get("ts", 0)))
    ev = rec.get("ev", "?")
    skip = {"schema", "seq", "ts", "ev", "run"}
    detail = " ".join(f"{k}={rec[k]}" for k in rec if k not in skip)
    run = rec.get("run", "")
    return f"[{ts}] {ev:<16} {detail}" + (f"  (run {run})" if run else "")


def watch_events(args) -> int:
    path = args.event_log
    try:
        f = open(path, encoding="utf-8")
    except OSError as e:
        print(f"cannot open {path}: {e}", file=sys.stderr)
        return 1
    with f:
        while True:
            line = f.readline()
            if line:
                line = line.strip()
                if not line:
                    continue
                try:
                    print(_render_event(json.loads(line)))
                except ValueError:
                    pass
                continue
            if not args.follow:
                return 0
            time.sleep(0.25)


def main() -> int:
    ap = argparse.ArgumentParser(
        description="live tailer for --telemetry-port / --event-log runs")
    ap.add_argument("--url", default="",
                    help="telemetry endpoint base (http://127.0.0.1:PORT); "
                         "'auto' resolves the port from --event-log's "
                         "telemetry_listen event")
    ap.add_argument("--event-log", default="",
                    help="structured event log to print/follow")
    ap.add_argument("--interval", type=float, default=2.0,
                    help="poll interval for --url mode (seconds)")
    ap.add_argument("--once", action="store_true",
                    help="print one frame and exit")
    ap.add_argument("--follow", action="store_true",
                    help="event-log mode: keep tailing as the run appends")
    args = ap.parse_args()
    if args.url:
        return watch_http(args)
    if args.event_log:
        return watch_events(args)
    ap.print_help()
    return 2


if __name__ == "__main__":
    sys.exit(main())
