"""Stage-level differential profiling of the v2 round at target shapes.

The scan harness + differential timing live in
gossip_sim_tpu/obs/difftime.py (time_stage); this file only defines the
stage computations and the shapes.
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from gossip_sim_tpu.engine import EngineParams, init_state, make_cluster_tables
from gossip_sim_tpu.engine import core as C
from gossip_sim_tpu.obs.difftime import time_stage

REPS = 10

N = int(sys.argv[1]) if len(sys.argv) > 1 else 10000
O = int(sys.argv[2]) if len(sys.argv) > 2 else 32

rng = np.random.default_rng(0)
stakes = (np.exp(rng.normal(9.5, 2.0, N)).astype(np.int64) + 1) * 10**9
tables = make_cluster_tables(stakes)
params = EngineParams(num_nodes=N, warm_up_rounds=0)
origins = jnp.arange(O, dtype=jnp.int32)
state = init_state(jax.random.PRNGKey(0), tables, origins, params)
state = jax.block_until_ready(state)
p = params
S, F, Cc, K, H, T = (p.active_set_size, p.push_fanout, p.rc_slots,
                     p.k_inbound, p.hist_bins, p.rot_tries)
NF, NK, NS = N * F, N * K, N * S


def bench(name, make_fn, *args):
    try:
        per_call = time_stage(make_fn, args, reps=REPS, timing_reps=2)
        print(f"{name:46s} {per_call*1e3:9.3f} ms")
    except Exception as e:
        print(f"{name:46s} FAILED: {type(e).__name__} {str(e)[:90]}")


peer = state.active
origin_col = origins[:, None, None]
iota_n = jnp.arange(N, dtype=jnp.int32)[None, :]
pseudo_t = jnp.broadcast_to(iota_n, (O, N))
tgt = jnp.where(peer[..., :F] < N, peer[..., :F], N)
tgtf = tgt.reshape(O, NF)
dist = jnp.asarray(rng.integers(0, 12, (O, N)), jnp.int32)
inbK = jnp.asarray(rng.integers(0, N + 1, (O, N, K)), jnp.int32)
rc_src = jnp.sort(jnp.asarray(
    rng.integers(0, N + 1, (O, N, Cc)), jnp.int32), axis=-1)
rc_i = jnp.asarray(rng.integers(0, 1 << 20, (O, N, Cc)), jnp.int32)


def verb1(st, i):
    valid = (st.active + i * 0 < N) & (~st.pruned) & (st.active != origin_col)
    skey = jnp.where(valid, jnp.arange(S, dtype=jnp.int32)[None, None, :], S)
    return lax.sort((skey + i * 0, st.active, st.tfail.astype(jnp.int32)),
                    dimension=-1, num_keys=1)[1]


def bfs_hop(tgt_, fr, i):
    contrib = (fr + i * 0 > 0)[:, :, None] & (tgt_ < N)
    k_edge = jnp.where(tgt_ < N, tgt_ * 2 + jnp.where(contrib, 0, 1), C.BIG)
    k1 = jnp.concatenate([k_edge.reshape(O, NF), pseudo_t * 2 + 1], axis=1)
    (s1,) = lax.sort((k1,), dimension=-1, num_keys=1)
    k2 = jnp.where(C._boundary(s1 >> 1), s1, C.BIG)
    (s2,) = lax.sort((k2,), dimension=-1, num_keys=1)
    return (s2[:, :N] & 1) == 0


def verb2_sortchain(tgt_, dist_, i):
    hop1 = jnp.minimum(dist_ + i * 0 + 1, H - 1)
    kv = ((hop1[:, :, None] << 14) | iota_n[:, :, None]).astype(jnp.int32)
    kv = jnp.broadcast_to(kv, (O, N, F)).reshape(O, NF)
    shi_e = jnp.broadcast_to(tables.shi[None, :N, None], (O, N, F)).reshape(O, NF)
    slo_e = jnp.broadcast_to(tables.slo[None, :N, None], (O, N, F)).reshape(O, NF)
    kd = jnp.where(tgt_ < N, tgt_, N).reshape(O, NF)
    kd_c = jnp.concatenate([kd, pseudo_t], axis=1)
    kv_c = jnp.concatenate([kv, jnp.full((O, N), C.BIG)], axis=1)
    shi_c = jnp.concatenate([shi_e, jnp.zeros((O, N), jnp.int32)], axis=1)
    slo_c = jnp.concatenate([slo_e, jnp.zeros((O, N), jnp.int32)], axis=1)
    st_, skv, shi_s, slo_s = lax.sort(
        (kd_c, kv_c, shi_c, slo_c), dimension=-1, num_keys=2)
    rank = C._rank_in_run(st_)
    keep = (skv != C.BIG) & (st_ < N) & (rank < K)
    gk = jnp.where(keep, (st_ * K + rank) * 2, C.BIG)
    slot_keys = jnp.broadcast_to(
        jnp.arange(NK, dtype=jnp.int32)[None, :] * 2 + 1, (O, NK))
    ga = jnp.concatenate([gk, slot_keys], axis=1)
    kv_a = jnp.concatenate([skv, jnp.full((O, NK), C.BIG)], axis=1)
    shi_a = jnp.concatenate([shi_s, jnp.zeros((O, NK), jnp.int32)], axis=1)
    slo_a = jnp.concatenate([slo_s, jnp.zeros((O, NK), jnp.int32)], axis=1)
    sA, kvA, hiA, loA = lax.sort((ga, kv_a, shi_a, slo_a),
                                 dimension=-1, num_keys=1)
    gB = jnp.where(C._boundary(sA >> 1), sA, C.BIG)
    sB, kvB, hiB, loB = lax.sort((gB, kvA, hiA, loA),
                                 dimension=-1, num_keys=1)
    return kvB[:, :NK]


def rc_merge(rc, inb, i):
    fk = jnp.concatenate([rc * 2, (inb + i * 0) * 2 + 1], axis=-1)
    fpos = jnp.concatenate(
        [jnp.broadcast_to(jnp.full((1, 1, Cc), C.BIG), (O, N, Cc)),
         jnp.broadcast_to(jnp.arange(K, dtype=jnp.int32)[None, None, :],
                          (O, N, K))], axis=-1)
    fk_s, fpos_s = lax.sort((fk, fpos), dimension=-1, num_keys=1)
    back = lax.sort((fpos_s, fk_s), dimension=-1, num_keys=1)[1]
    mk_s, a, b_, c_ = lax.sort((fk, fpos, fpos, fpos),
                               dimension=-1, num_keys=1)
    ck_s = lax.sort((mk_s, a, b_, c_), dimension=-1, num_keys=1)[0]
    return back + ck_s


def decide(rc, sc, i):
    member = rc < N
    mx = jnp.iinfo(jnp.int32).max
    neg = jnp.where(member, -(sc + i * 0), mx)
    return lax.sort((neg, neg, neg, rc, sc, sc),
                    dimension=-1, num_keys=4)[3]


def apply_small(st, i):
    NP = p.pa_slots
    edge_keys = (jnp.minimum(st.active, N - 1) * C.PACK
                 + iota_n[:, :, None]).reshape(O, NS)
    edge_keys = jnp.where((st.active < N).reshape(O, NS),
                          edge_keys * 2 + 1, C.BIG) + i * 0
    edge_pos = jnp.broadcast_to(
        jnp.arange(NS, dtype=jnp.int32)[None, :], (O, NS))
    pair_keys = jnp.full((O, N * NP), C.BIG)
    k = jnp.concatenate([edge_keys, pair_keys], axis=1)
    ppos = jnp.concatenate([edge_pos, jnp.full((O, N * NP), C.BIG)], axis=1)
    ks, pos_s = lax.sort((k, ppos), dimension=-1, num_keys=1)
    hit_s = jnp.concatenate(
        [jnp.zeros((O, 1), bool),
         ((ks[:, 1:] >> 1) == (ks[:, :-1] >> 1))], axis=1)
    return lax.sort((pos_s, hit_s.astype(jnp.int32)),
                    dimension=-1, num_keys=1)[1]


def rotate(st, i):
    u = jnp.asarray(rng.random((O, N, T, 2)), jnp.float32)
    members = C._sample_fast(tables, origins, u[..., 0] + i * 0, u[..., 1])
    perm_t = jnp.broadcast_to(tables.sampler.perm[None, :], (O, N))
    cands = C._lookup(perm_t, members.reshape(O, N * T), N).reshape(O, N, T)
    chosen = cands[..., 0]
    cf = C._lookup(st.failed.astype(jnp.int32),
                   jnp.minimum(chosen, N - 1), N)
    return chosen + cf


def sample_only(st, i):
    u = jnp.asarray(rng.random((O, N, T, 2)), jnp.float32)
    return C._sample_fast(tables, origins, u[..., 0] + i * 0, u[..., 1])


fr0 = jnp.zeros((O, N), jnp.int32).at[:, 0].set(1)
bench("verb1 compaction rowsort", verb1, state)
bench("bfs single hop (2 sorts)", bfs_hop, tgt, fr0)
bench("verb2 sort chain (3 big sorts)", verb2_sortchain, tgt, dist)
bench("rc merge (4 row sorts approx)", rc_merge, rc_src, inbK)
bench("decide 4-key row sort", decide, rc_src, rc_i)
bench("apply small path (2 sorts)", apply_small, state)
bench("rotate (sample+2 lookups)", rotate, state)
bench("sample_fast only", sample_only, state)
