"""Adaptive push-pull smoke test: the direction-switch CI gate
(adaptive.py / engine gating, ISSUE 11).

Fast CPU gate (~3-5 min) over three contracts:

  1. **BENCH_r07 rescue**: on the exact traffic configuration whose push
     baseline converges 0 of 80 values (n=1000, M=64 slots, rate 4,
     ingress 256 / egress 384 — BENCH_r07 drops ~270k messages and every
     value starves at ~98.7% coverage), ``--gossip-mode adaptive``
     converges >= 1 value, with per-value rescue attribution in the
     retirement records.  The push arm re-runs in the same window to
     prove the 0 baseline is not a round-budget artifact.
  2. **Zero bit-impact at mode=push**: a push-mode traffic run with the
     adaptive switch knobs set to aggressive values is bit-identical —
     parity snapshot AND deterministic Influx wire lines — to the bare
     push run: the switch exists only in the adaptive graph.
  3. **1k-node oracle parity**: the sort-routed traffic engine and the
     loop-based TrafficOracle produce bit-identical TrafficStats
     (per-round counters incl. the pull-rescue series, retirement records
     with terminal causes, wire lines) through the full CLI path under
     packet loss + churn + both queue caps in adaptive mode.

Usage: python tools/adaptive_smoke.py [--num-nodes 1000] [--rounds 16]

Exit code 0 = all gates hold; 1 = an adaptive invariant failed.
"""
import argparse
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    ap = argparse.ArgumentParser(
        description="adaptive push-pull smoke (CPU)")
    ap.add_argument("--num-nodes", type=int, default=1000)
    ap.add_argument("--rounds", type=int, default=12,
                    help="rounds for the BENCH_r07 rescue arms")
    ap.add_argument("--seed", type=int, default=11)
    args = ap.parse_args()

    os.environ.setdefault("JAX_PLATFORMS", "cpu")

    from bench import synthetic_stakes
    from gossip_sim_tpu.config import Config
    from gossip_sim_tpu.cli import run_traffic
    from gossip_sim_tpu.engine import EngineParams, make_cluster_tables
    from gossip_sim_tpu.engine.traffic import (device_traffic_tables,
                                               init_traffic_state,
                                               run_traffic_rounds)
    from gossip_sim_tpu.identity import reset_unique_pubkeys
    from gossip_sim_tpu.obs import get_registry
    from gossip_sim_tpu.sinks import DatapointQueue
    from gossip_sim_tpu.stats.traffic import TrafficStatsCollection

    t0 = time.time()
    failures = []

    def check(ok: bool, msg: str):
        print(f"  [{'ok' if ok else 'FAIL'}] {msg}", flush=True)
        if not ok:
            failures.append(msg)

    # ---- gate 1: adaptive rescues the BENCH_r07 starvation regime -------
    n = args.num_nodes
    stakes = synthetic_stakes(n)
    tables = make_cluster_tables(stakes)
    tt = device_traffic_tables(stakes)
    bench_kw = dict(num_nodes=n, warm_up_rounds=0, traffic_values=64,
                    traffic_rate=4, node_ingress_cap=256,
                    node_egress_cap=384, traffic_stall_rounds=4)
    print(f"adaptive smoke: BENCH_r07 config n={n} M=64 rate=4 "
          f"caps=(256,384) x {args.rounds} rounds, both arms")

    def run_arm(mode):
        p = EngineParams(gossip_mode=mode, **bench_kw).validate()
        st = init_traffic_state(stakes, p, seed=0)
        st, rows = run_traffic_rounds(p, tables, tt, st, args.rounds)
        rm = np.asarray(rows["ret_mask"])
        return {
            "converged": int(np.asarray(rows["converged"]).sum()),
            "retired": int(np.asarray(rows["retired"]).sum()),
            "qdropped": int(np.asarray(rows["queue_dropped"]).sum()),
            "rescued": (int(np.asarray(rows["pull_rescued"]).sum())
                        if "pull_rescued" in rows else 0),
            "ret_rescued": int(np.asarray(rows["ret_rescued"])[rm].sum()),
        }

    push = run_arm("push")
    adapt = run_arm("adaptive")
    print(f"  push:     {push}")
    print(f"  adaptive: {adapt}")
    check(push["qdropped"] > 0, "the cap regime drops messages (the "
                                "starvation mechanism is active)")
    check(push["converged"] == 0,
          f"push baseline converges 0 values ({push['converged']})")
    check(adapt["converged"] >= 1,
          f"adaptive converges >= 1 value where push converges 0 "
          f"(got {adapt['converged']})")
    check(adapt["ret_rescued"] > 0,
          f"retired values carry per-value rescue attribution "
          f"({adapt['ret_rescued']} rescued nodes on records)")

    # ---- gate 2: zero bit-impact at mode=push ---------------------------
    def run_traffic_cfg(cfg):
        reset_unique_pubkeys()
        get_registry().reset()
        coll = TrafficStatsCollection()
        dpq = DatapointQueue()
        run_traffic(cfg, "", dpq, "0", collection=coll)
        return coll.collection, dpq.drain_deterministic_lines()

    tbase = dict(num_synthetic_nodes=200, traffic_values=8, traffic_rate=2,
                 node_ingress_cap=24, node_egress_cap=32,
                 packet_loss_rate=0.1, churn_fail_rate=0.02,
                 churn_recover_rate=0.25, gossip_iterations=8,
                 warm_up_rounds=0, seed=args.seed)
    coll_a, wire_a = run_traffic_cfg(Config(**tbase))
    coll_b, wire_b = run_traffic_cfg(Config(
        adaptive_switch_threshold=0.1, adaptive_switch_hysteresis=0.05,
        **tbase))
    check(coll_a[0].parity_snapshot() == coll_b[0].parity_snapshot(),
          "mode=push traffic is bit-identical with adaptive knobs set "
          "(stats parity snapshot)")
    check(wire_a == wire_b, "mode=push Influx wire lines are bit-identical")

    # ---- gate 3: 1k-node adaptive engine-vs-oracle parity ---------------
    pbase = dict(num_synthetic_nodes=n, gossip_mode="adaptive",
                 adaptive_switch_threshold=0.6,
                 adaptive_switch_hysteresis=0.1,
                 traffic_values=8, traffic_rate=2,
                 node_ingress_cap=24, node_egress_cap=32,
                 packet_loss_rate=0.1, churn_fail_rate=0.02,
                 churn_recover_rate=0.25, gossip_iterations=8,
                 warm_up_rounds=0, seed=args.seed)
    coll_t, wire_t = run_traffic_cfg(Config(**pbase))
    coll_o, wire_o = run_traffic_cfg(Config(backend="oracle", **pbase))
    sn_t = coll_t[0].parity_snapshot()
    sn_o = coll_o[0].parity_snapshot()
    check(sn_t == sn_o,
          f"adaptive engine bit-matches TrafficOracle at n={n} under "
          f"loss+churn+caps (rotation ON)")
    check(wire_t == wire_o, "both backends emit identical sim_traffic + "
                            "sim_adaptive wire payloads")
    pr = sum(sn_t.get("adaptive_rounds", {}).get("pull_sent", []))
    check(pr > 0, f"the parity regime exercised the pull-rescue path "
                  f"({pr} rescue requests)")

    dt = time.time() - t0
    print(f"  elapsed: {dt:.1f}s")
    if failures:
        print(f"ADAPTIVE SMOKE FAILED ({len(failures)} invariant(s)):")
        for f in failures:
            print(f"  - {f}")
        return 1
    print("ADAPTIVE SMOKE PASSED")
    return 0


if __name__ == "__main__":
    sys.exit(main())
