"""Re-send a durable Influx spool (``--influx-spool``, sinks/influx.py).

A run whose InfluxDB endpoint was down past the sender's retry budget
appends the affected points — original per-point timestamps included — to
an on-disk line-protocol spool instead of discarding them.  This tool
replays that spool against the endpoint once it is healthy:

  python tools/influx_replay.py SPOOL [--influx l|i] [--batch 200]
                                [--dry-run] [--keep]

Credentials come from the same env/.env variables the simulator uses
(GOSSIP_SIM_INFLUX_USERNAME / _PASSWORD / _DATABASE).  Each batch goes
through the simulator's own sender (retry + backoff, sinks/influx.py), so
transient hiccups during replay are absorbed the same way.  On full
success the spool is renamed to ``<spool>.sent`` (``--keep`` leaves it);
on partial failure the spool is left untouched so the replay can be
re-run — InfluxDB deduplicates points on identical series + timestamp, so
re-sending an already-delivered line is harmless.

Exit code 0 = every point acknowledged (or --dry-run), 1 = sends failed,
2 = usage/credential errors.
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    ap = argparse.ArgumentParser(
        description="re-send a durable Influx line-protocol spool")
    ap.add_argument("spool", help="spool file written via --influx-spool")
    ap.add_argument("--influx", default="l", choices=["l", "i"],
                    help="endpoint selector, as the simulator's --influx "
                         "(l = localhost, i = internal-metrics)")
    ap.add_argument("--batch", type=int, default=200,
                    help="lines per POST body")
    ap.add_argument("--dry-run", action="store_true",
                    help="parse + count the spool, send nothing")
    ap.add_argument("--keep", action="store_true",
                    help="do not rename the spool after a full replay")
    args = ap.parse_args()

    from gossip_sim_tpu.constants import get_influx_url
    from gossip_sim_tpu.sinks import InfluxDB, load_dotenv

    if not os.path.exists(args.spool):
        print(f"spool not found: {args.spool}")
        return 2
    with open(args.spool) as f:
        raw = f.read().splitlines()
    # a torn final line (killed mid-append) is unparseable line protocol:
    # a valid point line ends in a nanosecond timestamp token
    lines = []
    for ln in raw:
        ln = ln.strip()
        if not ln:
            continue
        tail = ln.rsplit(" ", 1)[-1]
        if not tail.isdigit():
            print(f"skipping torn/invalid spool line: {ln[:60]!r}...")
            continue
        lines.append(ln)
    print(f"{args.spool}: {len(lines)} point line(s)")
    if args.dry_run or not lines:
        return 0

    load_dotenv()
    try:
        username = os.environ["GOSSIP_SIM_INFLUX_USERNAME"]
        password = os.environ["GOSSIP_SIM_INFLUX_PASSWORD"]
        database = os.environ["GOSSIP_SIM_INFLUX_DATABASE"]
    except KeyError as e:
        print(f"{e.args[0]} is not set")
        return 2

    db = InfluxDB(get_influx_url(args.influx), username, password, database)
    sent_before = 0
    for lo in range(0, len(lines), args.batch):
        body = "\n".join(lines[lo:lo + args.batch]) + "\n"
        db._post(body)
    stats = db.sender_stats()
    ok = stats["dropped_points"] == 0 and stats["points_sent"] > sent_before
    print(f"replay: {stats['points_sent']} batch(es) acknowledged, "
          f"{stats['dropped_points']} failed, {stats['retries']} retries")
    if ok and not args.keep:
        os.replace(args.spool, args.spool + ".sent")
        print(f"spool renamed to {args.spool}.sent")
    elif not ok:
        print("spool left in place; re-run once the endpoint is healthy")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
