"""Sweep smoke test: the compile-once sweep contract as a CI gate.

A 3-step packet-loss sweep at n=1000 on the jitted engine (the sweep
harness pattern, gossip_main.rs:774-951), asserting the ISSUE-4 contract:

  1. **one compile total** — stepping a numeric EngineKnobs field across
     K sims builds exactly one round-scan executable (steps 2..K are
     jit-cache hits), and the span registry records engine/compiles == 1
     with K-1 engine/cache_hits;
  2. **bit-exactness** — every engine row of every sweep step is
     bit-identical to a per-sim fresh-compile run of the same parameters
     (the compiled-once executable computes exactly what K independent
     compiles would);
  3. **amortization is real** — wall-clock of each warm step 2..K stays
     below --max-warm-fraction of step 1 (which carries the compile).

Usage: python tools/sweep_smoke.py [--num-nodes 1000] [--steps 3]
       [--iterations 10] [--seed 7] [--loss-start 0.05] [--loss-step 0.05]
       [--max-warm-fraction 0.5]

Exit code 0 = all assertions hold; 1 = the compile-once contract broke.
"""
import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    ap = argparse.ArgumentParser(
        description="compile-once sweep CI gate (CPU, <60s)")
    ap.add_argument("--num-nodes", type=int, default=1000)
    ap.add_argument("--steps", type=int, default=3)
    ap.add_argument("--iterations", type=int, default=10)
    ap.add_argument("--seed", type=int, default=7)
    ap.add_argument("--loss-start", type=float, default=0.05)
    ap.add_argument("--loss-step", type=float, default=0.05)
    ap.add_argument("--max-warm-fraction", type=float, default=0.5,
                    help="each warm step's wall time must stay below this "
                         "fraction of step 1 (which carries the compile)")
    args = ap.parse_args()

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax
    import jax.numpy as jnp
    import numpy as np

    from gossip_sim_tpu.engine import (EngineParams, clear_compile_cache,
                                       compiled_cache_size, init_state,
                                       make_cluster_tables, run_rounds)
    from gossip_sim_tpu.obs import get_registry

    t0 = time.time()
    n, K = args.num_nodes, args.steps
    rng = np.random.default_rng(args.seed)
    stakes = rng.choice(np.arange(1, 50 * n), size=n,
                        replace=False).astype(np.int64) * 10**9
    tables = make_cluster_tables(stakes)
    origins = jnp.arange(1, dtype=jnp.int32)
    rates = [args.loss_start + k * args.loss_step for k in range(K)]
    step_params = [
        EngineParams(num_nodes=n, warm_up_rounds=0, impair_seed=args.seed,
                     packet_loss_rate=r).validate()
        for r in rates]

    failures = []

    def check(ok: bool, msg: str):
        print(f"  [{'ok' if ok else 'FAIL'}] {msg}")
        if not ok:
            failures.append(msg)

    print(f"sweep smoke: n={n} K={K} packet-loss rates={rates} "
          f"iters={args.iterations}")

    # ---- sweep arm: K steps against one executable ---------------------
    reg = get_registry()
    reg.reset()
    clear_compile_cache()
    cache0 = compiled_cache_size()
    times, sweep_rows = [], []
    for k, params in enumerate(step_params):
        t_step = time.perf_counter()
        state = init_state(jax.random.PRNGKey(args.seed), tables, origins,
                           params)
        state, rows = run_rounds(params, tables, origins, state,
                                 args.iterations)
        rows = jax.tree_util.tree_map(np.asarray, rows)
        times.append(time.perf_counter() - t_step)
        sweep_rows.append(rows)
    cache_delta = compiled_cache_size() - cache0
    print(f"  step wall times: {[round(t, 3) for t in times]} s")

    check(cache_delta == 1,
          f"exactly one compiled executable across {K} steps "
          f"(got {cache_delta})")
    check(int(reg.counter("engine/compiles")) == 1,
          f"registry engine/compiles == 1 "
          f"(got {int(reg.counter('engine/compiles'))})")
    check(int(reg.counter("engine/cache_hits")) == K - 1,
          f"registry engine/cache_hits == {K - 1} "
          f"(got {int(reg.counter('engine/cache_hits'))})")

    warm_ok = all(t <= args.max_warm_fraction * times[0] for t in times[1:])
    check(warm_ok,
          f"warm steps 2..{K} each below {args.max_warm_fraction:.2f}x of "
          f"step 1 ({times[0]:.3f}s)")

    # the sweep actually spanned distinct regimes
    drop_totals = [int(r["dropped"].sum()) for r in sweep_rows]
    check(all(b > a for a, b in zip(drop_totals, drop_totals[1:])),
          f"drop counts increase along the rate sweep ({drop_totals})")

    # ---- reference arm: per-sim fresh-compile runs ---------------------
    for k, params in enumerate(step_params):
        clear_compile_cache()
        state = init_state(jax.random.PRNGKey(args.seed), tables, origins,
                           params)
        state, rows = run_rounds(params, tables, origins, state,
                                 args.iterations)
        rows = jax.tree_util.tree_map(np.asarray, rows)
        mismatched = [key for key in rows
                      if not np.array_equal(rows[key], sweep_rows[k][key])]
        check(not mismatched,
              f"step {k + 1} bit-identical to its fresh-compile run"
              + (f" (diverged: {mismatched})" if mismatched else ""))

    dt = time.time() - t0
    print(f"  elapsed: {dt:.1f}s")
    if failures:
        print(f"SWEEP SMOKE FAILED ({len(failures)} invariant(s)):")
        for f in failures:
            print(f"  - {f}")
        return 1
    print("SWEEP SMOKE PASSED")
    return 0


if __name__ == "__main__":
    sys.exit(main())
