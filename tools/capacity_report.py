"""Capacity planner: the ledger's closed forms answered as questions.

Pure host arithmetic over obs/capacity.py — no JAX, no device, instant.
Answers the ROADMAP item 1 planning questions directly:

  * what does the current config cost per node, and which subsystem owns
    the bytes? (the ledger table)
  * what is the largest N that fits a memory budget?
    (``--fit-budget 16GB``)
  * what would n=100k / n=1M cost, and which dense terms blow up?
    (``--project``; the O(N^2)-flagged arrays under the all-origins
    interpretation are exactly the tables the sparse representation
    removes — price it with ``--representation sparse``)

The all-origins interpretation (``--all-origins``, default ON — it is
the north-star workload) scales the origin axis with N, so every
``[O, N, ...]`` array is flagged quadratic; ``--origin-batch B`` instead
analyzes a fixed batch (memory then scales linearly and the fit answers
"how big a cluster fits per batch").

``--representation sparse`` prices the sparse frontier engine
(engine/sparse.py): the rc_shi/rc_slo stake planes leave the ledger
(derived per round from the cluster tables), which is what moves the
16 GB all-origins fit past the dense wall.  The i64 sort-key path lifts
the old 32767 i32 packing cap to MAX_NODES = 2^24 (engine/core.py), so
the 100k/1M projections are engine-reachable sizes, not hypotheticals.

Usage:
  python tools/capacity_report.py [--num-nodes 1000] [--fit-budget 16GB]
      [--project 100000,1000000] [--all-origins | --origin-batch B]
      [--representation dense|sparse] [--sweep-lanes K]
      [--traffic-values M] [--gossip-mode MODE] [--trace] [--top 12]
      [--json]
"""
import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from gossip_sim_tpu.engine.params import EngineParams  # noqa: E402
from gossip_sim_tpu.obs import capacity  # noqa: E402

ENGINE_NODE_CAP = 1 << 24  # engine/core.py MAX_NODES (i64 sort-key path)


def human(n: float) -> str:
    for unit in ("B", "KiB", "MiB", "GiB", "TiB"):
        if abs(n) < 1024 or unit == "TiB":
            return f"{n:.2f} {unit}" if unit != "B" else f"{int(n)} B"
        n /= 1024
    return f"{n:.2f} TiB"


def build_params(args, num_nodes: int) -> EngineParams:
    caps = {}
    if args.traffic_values > 1:
        caps = dict(traffic_values=args.traffic_values,
                    node_ingress_cap=args.node_ingress_cap,
                    node_egress_cap=args.node_egress_cap)
    return EngineParams(num_nodes=num_nodes,
                        push_fanout=args.push_fanout,
                        active_set_size=args.active_set_size,
                        gossip_mode=args.gossip_mode,
                        representation=args.representation, **caps)


def main() -> int:
    ap = argparse.ArgumentParser(
        description="closed-form capacity planning over the exact memory "
                    "ledger (obs/capacity.py)")
    ap.add_argument("--num-nodes", type=int, default=1000)
    ap.add_argument("--push-fanout", type=int, default=6)
    ap.add_argument("--active-set-size", type=int, default=12)
    ap.add_argument("--gossip-mode", default="push",
                    choices=["push", "pull", "push-pull", "adaptive"])
    ap.add_argument("--representation", default="dense",
                    choices=["dense", "sparse"],
                    help="engine execution layout to price: sparse drops "
                         "the rc_shi/rc_slo [O,N,C] stake planes (derived "
                         "from the cluster tables each round, "
                         "engine/sparse.py)")
    ap.add_argument("--traffic-values", type=int, default=1,
                    help="analyze the traffic engine with M value slots")
    ap.add_argument("--node-ingress-cap", type=int, default=0)
    ap.add_argument("--node-egress-cap", type=int, default=0)
    ap.add_argument("--sweep-lanes", type=int, default=0)
    ap.add_argument("--trace", action="store_true",
                    help="include the flight-recorder block buffers")
    ap.add_argument("--all-origins", dest="all_origins",
                    action="store_true", default=None,
                    help="origin axis tracks N (default; the web-scale "
                         "interpretation that makes [O,N,..] terms N^2)")
    ap.add_argument("--origin-batch", type=int, default=0,
                    help="analyze a fixed origin batch instead of "
                         "--all-origins")
    ap.add_argument("--fit-budget", default="",
                    help="byte budget, e.g. 16GB / 512MiB / 2e9: print "
                         "the largest N that fits")
    ap.add_argument("--project", default="100000,1000000",
                    help="comma-separated N values to project the "
                         "footprint at (default 100k, 1M)")
    ap.add_argument("--top", type=int, default=12,
                    help="ledger rows to print (largest first)")
    ap.add_argument("--json", action="store_true",
                    help="emit the full ledger + answers as JSON")
    args = ap.parse_args()

    osn = not args.origin_batch if args.all_origins is None \
        else args.all_origins
    ob = args.origin_batch or (args.num_nodes if osn else 1)
    params = build_params(args, args.num_nodes)
    led = capacity.capacity_ledger(params, origin_batch=ob,
                                   lanes=args.sweep_lanes,
                                   trace=args.trace,
                                   origins_scale_with_n=osn)

    projections = []
    for ns in args.project.split(","):
        ns = ns.strip()
        if not ns:
            continue
        n = int(float(ns))
        total = capacity.ledger_total_at(params, n, origin_batch=ob,
                                         lanes=args.sweep_lanes,
                                         trace=args.trace,
                                         origins_scale_with_n=osn)
        projections.append({"num_nodes": n, "total_bytes": total,
                            "bytes_per_node": round(total / n, 2),
                            "beyond_engine_cap": n > ENGINE_NODE_CAP})

    answers = {"ledger": led, "projections": projections,
               "representation": args.representation}
    if args.fit_budget:
        budget = capacity.parse_size(args.fit_budget)
        fit_n = capacity.fit_budget(params, budget, origin_batch=ob,
                                    lanes=args.sweep_lanes,
                                    trace=args.trace,
                                    origins_scale_with_n=osn)
        answers["fit_budget"] = {"budget_bytes": budget,
                                 "budget": args.fit_budget,
                                 "largest_n": fit_n,
                                 "beyond_engine_cap":
                                     fit_n > ENGINE_NODE_CAP}

    # gossip-as-a-service admission price (serve/, ISSUE 20): the SAME
    # closed form the --serve daemon's ledger admission charges per
    # request (obs/capacity.predict_request_bytes)
    per_req = capacity.predict_request_bytes(params, 1)
    answers["serve_admission"] = {"request_bytes": per_req}
    if args.fit_budget:
        answers["serve_admission"]["requests_per_budget"] = \
            answers["fit_budget"]["budget_bytes"] // max(per_req, 1)

    if args.json:
        print(json.dumps(answers, indent=2))
        return 0

    mode = ("all-origins (O tracks N)" if osn
            else f"origin_batch={ob}")
    print(f"capacity ledger: n={args.num_nodes} {mode} "
          f"mode={args.gossip_mode} repr={args.representation}"
          + (f" M={args.traffic_values}" if args.traffic_values > 1 else "")
          + (f" lanes={args.sweep_lanes}" if args.sweep_lanes else "")
          + (" +trace" if args.trace else ""))
    print(f"  total {human(led['total_bytes'])} "
          f"({led['bytes_per_node']} B/node); "
          f"state {human(led['state_bytes'])}")
    print("  by subsystem:")
    for group, b in sorted(led["groups"].items(), key=lambda kv: -kv[1]):
        print(f"    {group:<16} {human(b):>12}  "
              f"{100.0 * b / max(led['total_bytes'], 1):5.1f}%")
    rows = sorted((e for e in led["entries"] if e["exact"]),
                  key=lambda e: -e["bytes"])[: args.top]
    print(f"  largest arrays (top {len(rows)}):")
    for e in rows:
        flag = "  <-- O(N^2) DENSE" if e["n_degree"] >= 2 else ""
        print(f"    {e['name']:<22} {human(e['bytes']):>12}  "
              f"{e['formula']}{flag}")

    # exact arrays only — the workspace rows are estimates excluded from
    # the fit math, so they must not be named as what "blocks" a budget
    dense = [e for e in led["entries"]
             if e["n_degree"] >= 2 and e["exact"]]
    ws_dense = [e for e in led["entries"]
                if e["n_degree"] >= 2 and not e["exact"]]
    if dense:
        print(f"  dense O(N^2) terms under this interpretation: "
              f"{len(dense)} arrays, {human(led['dense_bytes'])} exact"
              + (f" (+ {len(ws_dense)} workspace sort-buffer estimates, "
                 f"measured by the XLA temp-bytes harvest)"
                 if ws_dense else ""))
        if args.representation == "dense":
            print("  (compare --representation sparse: the rc stake "
                  "planes leave the ledger, engine/sparse.py)")

    if projections:
        print("  projections (closed-form, exact):")
        for pr in projections:
            cap_note = ("  [beyond engine cap 2^24: shard nodes]"
                        if pr["beyond_engine_cap"] else "")
            print(f"    n={pr['num_nodes']:>9,}: "
                  f"{human(pr['total_bytes']):>12} "
                  f"({pr['bytes_per_node']} B/node){cap_note}")

    if "fit_budget" in answers:
        fb = answers["fit_budget"]
        print(f"  fit --fit-budget {fb['budget']} "
              f"({human(fb['budget_bytes'])}): largest N = "
              f"{fb['largest_n']:,}"
              + ("  [beyond engine cap 2^24]"
                 if fb["beyond_engine_cap"] else ""))
        blocked = [pr for pr in projections
                   if pr["num_nodes"] > fb["largest_n"]]
        for pr in blocked:
            over = pr["total_bytes"] / max(fb["budget_bytes"], 1)
            top_dense = sorted(dense, key=lambda e: -e["bytes"])[:6]
            print(f"    n={pr['num_nodes']:,} does NOT fit "
                  f"({over:.1f}x the budget); blocking dense arrays: "
                  + (", ".join(f"{e['name']} ({e['formula']})"
                               for e in top_dense)
                     if top_dense else "none flagged — linear terms "
                     "dominate; raise the batch or shard nodes"))

    sa = answers["serve_admission"]
    print(f"  serve admission price (--serve ledger): "
          f"{human(sa['request_bytes'])} per request"
          + (f"; {sa['requests_per_budget']:,} request(s) fit the budget"
             if "requests_per_budget" in sa else ""))
    return 0


if __name__ == "__main__":
    sys.exit(main())
