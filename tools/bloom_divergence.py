"""Measure the bloom false-positive divergence (exact masks vs 0.1-fp blooms).

The reference filters push targets through per-peer bloom filters with a 10%
false-positive rate (push_active_set.rs:122-123), so it occasionally
*over-prunes*: a peer is skipped for an origin nobody ever pruned.  Both of
this framework's backends use exact prune state instead (documented
divergence).  This experiment quantifies what that omission changes, by
running the CPU oracle twice on the same cluster — exact sets vs
reference-geometry blooms (oracle/active_set.py BloomFilter) — and comparing
coverage / RMR / prune volume / stranded counts.

Usage: python tools/bloom_divergence.py [--num-nodes 2000] [--iterations 100]
       [--warm-up 20] [--seed 42] [--json out.json]
"""
import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from gossip_sim_tpu.identity import reset_unique_pubkeys
from gossip_sim_tpu.ingest import synthetic_accounts
from gossip_sim_tpu.oracle.active_set import BloomFilter, PushActiveSet
from gossip_sim_tpu.oracle.cluster import Cluster, Node
from gossip_sim_tpu.oracle.rustrng import ChaChaRng


class CountingBloom:
    """BloomFilter plus an exact shadow set: counts probes where the bloom
    answers True for an item never added (a genuine false positive — the
    over-prune event the reference's 0.1-fp blooms can produce)."""

    def __init__(self, inner, stats):
        self.inner = inner
        self.shadow = set()
        self.stats = stats

    def add(self, item):
        self.inner.add(item)
        self.shadow.add(item)

    def __contains__(self, item):
        hit = item in self.inner
        self.stats["probes"] += 1
        if hit and item not in self.shadow:
            self.stats["false_positives"] += 1
        return hit


def run_mode(accounts, mode, args):
    rng = ChaChaRng.from_seed_byte(args.seed % 256)
    n = len(accounts)
    fp_stats = {"probes": 0, "false_positives": 0}
    counter = [0]

    def bloom_factory(peer, r):
        # salt_seed (not the sim rng) keeps both modes on the identical RNG
        # stream; any remaining divergence is caused by fp events alone
        counter[0] += 1
        return CountingBloom(BloomFilter(n, salt_seed=counter[0]), fp_stats)

    factory = None if mode == "exact" else bloom_factory
    nodes = [Node(pk, st, factory) for pk, st in accounts.items()]
    stakes = dict(accounts)
    node_map = {nd.pubkey: nd for nd in nodes}
    origin = max(accounts.items(), key=lambda kv: kv[1])[0]
    for nd in nodes:
        nd.initialize_gossip(rng, stakes, 12)

    cluster = Cluster(6)
    cov, rmr, stranded, prunes = [], [], [], 0
    t0 = time.time()
    for it in range(args.iterations):
        cluster.run_gossip(origin, stakes, node_map)
        cluster.consume_messages(origin, nodes)
        cluster.send_prunes(origin, nodes, 0.15, 2, stakes)
        cluster.prune_connections(node_map, stakes)
        cluster.chance_to_rotate(rng, nodes, 12, stakes, 1 / 75)
        if it >= args.warm_up:
            c, _ = cluster.coverage(stakes)
            cov.append(c)
            rmr.append(cluster.relative_message_redundancy()[0])
            stranded.append(len(cluster.stranded_nodes()))
            prunes += sum(len(p) for p in cluster.prunes.values())
    dt = time.time() - t0
    m = len(cov)
    return {
        "mode": mode,
        "coverage_mean": sum(cov) / m,
        "coverage_min": min(cov),
        "rmr_mean": sum(rmr) / m,
        "stranded_total": sum(stranded),
        "prune_messages": prunes,
        "measured_rounds": m,
        "elapsed_s": round(dt, 1),
        "bloom_probes": fp_stats["probes"],
        "bloom_false_positives": fp_stats["false_positives"],
    }


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--num-nodes", type=int, default=2000)
    ap.add_argument("--iterations", type=int, default=100)
    ap.add_argument("--warm-up", type=int, default=20)
    ap.add_argument("--seed", type=int, default=42)
    ap.add_argument("--json", default="")
    args = ap.parse_args()

    reset_unique_pubkeys()
    rng = ChaChaRng.from_seed_byte(args.seed % 256)
    accounts = synthetic_accounts(args.num_nodes, rng)

    results = [run_mode(accounts, m, args) for m in ("exact", "bloom")]
    ex, bl = results
    delta = {
        "coverage_mean_delta": bl["coverage_mean"] - ex["coverage_mean"],
        "rmr_mean_delta": bl["rmr_mean"] - ex["rmr_mean"],
        "stranded_total_delta": bl["stranded_total"] - ex["stranded_total"],
        "prune_messages_delta": bl["prune_messages"] - ex["prune_messages"],
    }
    out = {"num_nodes": args.num_nodes, "iterations": args.iterations,
           "warm_up": args.warm_up, "seed": args.seed,
           "exact": ex, "bloom": bl, "delta": delta}
    print(json.dumps(out, indent=2))
    if args.json:
        with open(args.json, "w") as f:
            json.dump(out, f, indent=2)
    return 0


if __name__ == "__main__":
    sys.exit(main())
