"""Second-round microbenchmarks: the exact primitives of the redesigned round.
Differential in-jit repetition (axon round-trip ~70ms). Not shipped."""
import sys
import time
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

REPS = 20


def bench(name, make_fn, *args):
    try:
        @partial(jax.jit, static_argnums=(1,))
        def run(args, k):
            def body(c, i):
                out = jnp.ravel(make_fn(*args, i + c))
                pos = ((i * 1297 + c) % out.shape[0]).astype(jnp.int32)
                return lax.dynamic_index_in_dim(
                    out, pos, keepdims=False).astype(jnp.int32), None
            c, _ = lax.scan(body, jnp.int32(0), jnp.arange(k))
            return c
        int(run(args, 1)); int(run(args, REPS + 1))
        t1 = min(_t(run, args, 1) for _ in range(2))
        t2 = min(_t(run, args, REPS + 1) for _ in range(2))
        print(f"{name:52s} {(t2-t1)/REPS*1e3:9.3f} ms")
    except Exception as e:
        print(f"{name:52s} FAILED: {type(e).__name__} {str(e)[:80]}")


def _t(run, args, k):
    t0 = time.time()
    int(run(args, k))
    return time.time() - t0


def suite(O, N, S=12, C=64, K=16, H=64):
    print(f"=== O={O} N={N} S={S} C={C} K={K}")
    rng = np.random.default_rng(0)
    NS = N * S
    NK = N * K
    tgt = jnp.asarray(rng.integers(0, N, (O, N, S)), dtype=jnp.int32)
    dist = jnp.asarray(rng.integers(0, 15, (O, N)), dtype=jnp.int32)
    idxK = jnp.asarray(rng.integers(0, N, (O, N, K)), dtype=jnp.int32)
    table = jnp.asarray(rng.integers(0, 1 << 30, (N + 1,)), dtype=jnp.int32)
    o3 = jnp.arange(O)[:, None, None]
    flatNK = jnp.asarray(rng.integers(0, N * K, (O, NK)), dtype=jnp.int32)
    valsNK = jnp.asarray(rng.integers(0, 1 << 30, (O, NK)), dtype=jnp.int32)
    key1 = jnp.sort(tgt.reshape(O, NS), axis=-1)
    key2 = jnp.asarray(rng.integers(0, 1 << 30, (O, NS)), dtype=jnp.int32)
    rows62 = jnp.asarray(rng.integers(0, 1 << 30, (O, N, C + K)), jnp.int32)
    startpos = jnp.asarray(
        np.sort(rng.integers(0, NS + N, (O, N)), axis=-1), jnp.int32)

    bench("gather [O,N,K] from [N+1] table",
          lambda ix, t, i: (t + i)[ix], idxK, table)
    bench("gather [O,N] from [O,NS+N] (BFS extract)",
          lambda sp, v, i: jnp.take_along_axis(
              jnp.concatenate([v + i, v[:, :N]], axis=1), sp, axis=1),
          startpos, key2)
    bench("scatter [O,NK]->[O,N,K] i32",
          lambda f, v, i: jnp.zeros((O, N * K), jnp.int32).at[
              jnp.arange(O)[:, None], f].set(v + i, mode="drop"),
          flatNK, valsNK)
    bench("sort [O,NS] 2key+2payload",
          lambda a, b, i: lax.sort((a, b + i, b, b), dimension=-1,
                                   num_keys=2)[2], key1, key2)
    bench("sort [O,NS] 1key+1payload",
          lambda a, b, i: lax.sort((a + i, b), dimension=-1, num_keys=1)[1],
          key1, key2)
    bench("row sort [O,N,C+K] 1key+2payload",
          lambda r, i: lax.sort((r + i, r, r), dimension=-1, num_keys=1)[1],
          rows62)
    bench("row sort [O,N,C+K] 4key",
          lambda r, i: lax.sort((r + i, r, r, r), dimension=-1, num_keys=4)[3],
          rows62)
    bench("seg log-shift min [O,NS]",
          lambda k1, v, i: _seg_min(k1, v + i), key1, key2)
    bench("onehot hist [O,N]->[O,H]",
          lambda d, i: jnp.sum(
              ((d + i) % H)[:, :, None] == jnp.arange(H)[None, None, :],
              axis=1, dtype=jnp.int32), dist)
    bench("cumsum i64-as-2xi32 rows [O,N,C]",
          lambda r, i: _cumsum64(r[..., :C] + i, r[..., :C]), rows62)
    bench("while10 x elementwise [O,NS]",
          lambda v, i: lax.while_loop(
              lambda c: c[1] < 10,
              lambda c: (jnp.minimum(c[0], c[0] * 3 + i), c[1] + 1),
              (v, jnp.int32(0)))[0], key2)


def _seg_min(sorted_keys, vals):
    O, M = vals.shape
    is_start = jnp.concatenate(
        [jnp.ones((O, 1), bool),
         sorted_keys[:, 1:] != sorted_keys[:, :-1]], axis=1)
    x = vals
    blocked = is_start
    sh = 1
    while sh < M:
        prev = jnp.pad(x, ((0, 0), (sh, 0)), constant_values=1 << 30)[:, :M]
        pb = jnp.pad(blocked, ((0, 0), (sh, 0)), constant_values=True)[:, :M]
        x = jnp.where(blocked, x, jnp.minimum(x, prev))
        blocked = blocked | pb
        sh *= 2
    return x


def _cumsum64(hi, lo):
    chi = jnp.cumsum(hi, axis=-1)
    clo = jnp.cumsum(lo, axis=-1)
    return chi + (clo >> 16)


if __name__ == "__main__":
    if len(sys.argv) > 1 and sys.argv[1] == "big":
        suite(32, 10000)
    else:
        suite(8, 2000)
