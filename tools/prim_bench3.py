"""Third-round microbenchmarks: block gathers + compacted-F hop ops."""
import sys
import time
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

REPS = 20


def bench(name, make_fn, *args):
    try:
        @partial(jax.jit, static_argnums=(1,))
        def run(args, k):
            def body(c, i):
                out = jnp.ravel(make_fn(*args, i + c))
                pos = ((i * 1297 + c) % out.shape[0]).astype(jnp.int32)
                return lax.dynamic_index_in_dim(
                    out, pos, keepdims=False).astype(jnp.int32), None
            c, _ = lax.scan(body, jnp.int32(0), jnp.arange(k))
            return c
        int(run(args, 1)); int(run(args, REPS + 1))
        t1 = min(_t(run, args, 1) for _ in range(2))
        t2 = min(_t(run, args, REPS + 1) for _ in range(2))
        print(f"{name:52s} {(t2-t1)/REPS*1e3:9.3f} ms")
    except Exception as e:
        print(f"{name:52s} FAILED: {type(e).__name__} {str(e)[:80]}")


def _t(run, args, k):
    t0 = time.time()
    int(run(args, k))
    return time.time() - t0


def suite(O, N, F=6, K=16):
    print(f"=== O={O} N={N} F={F} K={K}")
    rng = np.random.default_rng(0)
    NF = N * F
    M = NF + N
    vals = jnp.asarray(rng.integers(0, 1 << 30, (O, M + K)), jnp.int32)
    startpos = jnp.asarray(
        np.sort(rng.integers(0, M, (O, N)), axis=-1), jnp.int32)
    keyNF = jnp.sort(jnp.asarray(
        rng.integers(0, 2 * N, (O, NF)), jnp.int32), axis=-1)

    # block gather: windows [startpos, startpos+K) from [O, M+K]
    def block_gather(sp, v, i):
        idx = sp[:, :, None] + jnp.arange(K)[None, None, :]
        return jnp.take_along_axis(
            (v + i)[:, :, None], jnp.minimum(idx, M + K - 1).reshape(
                O, N * K)[:, :, None], axis=1)
    bench("block gather [O,N,K] windows from [O,M]",
          lambda sp, v, i: jnp.take_along_axis(
              v + i, jnp.minimum(
                  sp[:, :, None] + jnp.arange(K)[None, None, :],
                  M + K - 1).reshape(O, N * K), axis=1),
          startpos, vals)
    bench("block gather [O,N,4] windows",
          lambda sp, v, i: jnp.take_along_axis(
              v + i, jnp.minimum(
                  sp[:, :, None] + jnp.arange(4)[None, None, :],
                  M + K - 1).reshape(O, N * 4), axis=1),
          startpos, vals)
    bench("random gather [O,N] from [O,M]",
          lambda sp, v, i: jnp.take_along_axis(v + i, sp, axis=1),
          startpos, vals)
    bench("sort [O,NF] 1key i32",
          lambda a, i: lax.sort(((a + i) % (1 << 29),), dimension=-1,
                                num_keys=1)[0], keyNF)
    bench("sort [O,NF] 1key+1payload",
          lambda a, i: lax.sort((a + i, a), dimension=-1, num_keys=1)[1],
          keyNF)
    bench("sort [O,NF+N] 1key+1payload",
          lambda v, i: lax.sort((v[:, :M] + i, v[:, :M]), dimension=-1,
                                num_keys=1)[1], vals)
    bench("row sort+slice [O,N,12]->[O,N,6]",
          lambda a, i: lax.sort(
              ((a + i).reshape(O, N, 12), a.reshape(O, N, 12)),
              dimension=-1, num_keys=1)[1][..., :6],
          vals[:, :N * 12])


if __name__ == "__main__":
    if len(sys.argv) > 1 and sys.argv[1] == "big":
        suite(32, 10000)
    else:
        suite(8, 2000)
