"""BENCH trajectory differ: the nine-plus BENCH_r*.json files as one table.

The BENCH rounds accumulate one JSON file per PR (BENCH_r01..r08 at the
time of writing) and the trajectory had to be eyeballed across them.
This tool loads every round, prints each tracked metric's trajectory
with per-round deltas, and flags regressions worse than ``--threshold``
(default 10%) against the previous round that carried the metric.

Rounds measured on different platforms are not comparable (r01-r03 ran
on CPU fallback semantics before the probe cache; an eventual TPU round
will re-baseline everything): a platform change is annotated as a BREAK,
and deltas across it are reported but never flagged as regressions.

Usage: python tools/bench_trend.py [--dir .] [--threshold 0.10]
       [--metrics value,sweep_steps_per_sec,...] [--fail-on-regression]
       [--latest-only]

Exit code: 0 (report only) unless --fail-on-regression and at least one
same-platform regression was flagged.  ``--latest-only`` counts only
regressions entering the NEWEST round — the CI-gate form (ci_gates.py
registers ``--fail-on-regression --latest-only``): the committed history
already contains known, documented slowdowns (r06-r08 re-budgeting), and
a gate must judge the round under review, not re-litigate the past.
"""
import argparse
import glob
import json
import os
import re
import sys

#: (metric key path, higher_is_better) — dotted paths reach into nested
#: rung dicts; missing keys simply skip the round
DEFAULT_METRICS = [
    ("value", True),                        # origin_iters_per_sec
    ("compile_s", False),
    ("init_s", False),
    ("sweep_steps_per_sec", True),
    ("lane_sweep_steps_per_sec", True),
    ("lane_sweep.vs_serial_sweep", True),
    ("traffic_steps_per_sec", True),
    ("traffic.values_converged_per_sec", True),
    ("adaptive_traffic_steps_per_sec", True),
    ("adaptive_traffic.values_rescued", True),
    ("health_overhead_pct", False),             # BENCH_r10+ (ISSUE 17)
    ("coverage_mean", True),
    ("capacity.mem_bytes_per_node", False),     # BENCH_r09+ (ISSUE 13)
    ("capacity.peak_rss_bytes", False),
    ("capacity.xla_peak_temp_bytes", False),
    ("sparse_steps_per_sec", True),             # BENCH_r10+ (ISSUE 19)
    ("sparse.mem_bytes_per_node", False),
    ("sparse.xla_temp_bytes", False),
    ("serve_requests_per_sec", True),           # BENCH_r11+ (ISSUE 20)
]

#: Reported but never flagged: derived ratios of two metrics that are
#: BOTH tracked above double-flag real slowdowns (the component metric
#: already fails the gate) and misfire when both components improve
#: unevenly (r10: serial sweep +39%, lanes +27% -> ratio "-11%").
REPORT_ONLY = {"lane_sweep.vs_serial_sweep"}


def lookup(d: dict, path: str):
    cur = d
    for part in path.split("."):
        if not isinstance(cur, dict) or part not in cur:
            return None
        cur = cur[part]
    return cur if isinstance(cur, (int, float)) \
        and not isinstance(cur, bool) else None


def load_rounds(directory: str) -> list:
    files = sorted(
        glob.glob(os.path.join(directory, "BENCH_r*.json")),
        # basename only: an 'rN' component in --dir must not collapse
        # every sort key onto the directory's number
        key=lambda p: int(re.search(r"r(\d+)",
                                    os.path.basename(p)).group(1)))
    rounds = []
    for path in files:
        try:
            with open(path) as f:
                data = json.load(f)
        except (OSError, ValueError) as e:
            print(f"  [skip] {os.path.basename(path)}: unreadable ({e})")
            continue
        if "parsed" in data and "value" not in data:
            # r01-r05 era: the driver wrapped the worker line under
            # "parsed" (None when every rung failed that round)
            data = data.get("parsed") or {}
        rounds.append((os.path.basename(path), data))
    return rounds


def fmt(v: float) -> str:
    if v is None:
        return "-"
    if abs(v) >= 1e6:
        return f"{v:.3g}"
    if isinstance(v, float) and v != int(v):
        return f"{v:.2f}"
    return str(int(v))


def main() -> int:
    ap = argparse.ArgumentParser(
        description="diff metrics across BENCH_r*.json rounds")
    ap.add_argument("--dir", default=os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))),
        help="directory holding BENCH_r*.json (default: repo root)")
    ap.add_argument("--threshold", type=float, default=0.10,
                    help="fractional regression flag threshold "
                         "(default 10%%)")
    ap.add_argument("--metrics", default="",
                    help="comma-separated dotted metric paths overriding "
                         "the default set; prefix a path with '-' to "
                         "mark it lower-is-better (e.g. -compile_s)")
    ap.add_argument("--fail-on-regression", action="store_true",
                    help="exit 1 when a same-platform regression beyond "
                         "the threshold is flagged")
    ap.add_argument("--latest-only", action="store_true",
                    help="flag only regressions entering the newest "
                         "round (the CI-gate form; history still prints)")
    args = ap.parse_args()

    rounds = load_rounds(args.dir)
    if len(rounds) < 2:
        print(f"need >= 2 BENCH rounds in {args.dir}, found {len(rounds)}")
        return 0 if rounds else 1

    metrics = ([(m.strip().lstrip("-"), not m.strip().startswith("-"))
                for m in args.metrics.split(",") if m.strip()]
               if args.metrics else DEFAULT_METRICS)

    names = [re.search(r"r(\d+)", name).group(0) for name, _ in rounds]
    platforms = [data.get("platform", "?") for _, data in rounds]
    print("rounds:   " + "  ".join(f"{n}({p})"
                                   for n, p in zip(names, platforms)))
    breaks = [i for i in range(1, len(platforms))
              if platforms[i] != platforms[i - 1]]
    if breaks:
        print("platform BREAKs after: "
              + ", ".join(names[i - 1] for i in breaks)
              + " (cross-platform deltas reported, never flagged)")

    regressions = []
    for path, higher_better in metrics:
        series = [lookup(data, path) for _, data in rounds]
        if all(v is None for v in series):
            continue
        cells = []
        prev_val, prev_idx = None, None
        for i, v in enumerate(series):
            if v is None:
                cells.append("-")
                continue
            cell = fmt(v)
            if prev_val not in (None, 0):
                delta = (v - prev_val) / abs(prev_val)
                worse = (-delta if higher_better else delta)
                same_platform = platforms[i] == platforms[prev_idx]
                cell += f" ({delta:+.0%})"
                if (worse > args.threshold and same_platform
                        and path not in REPORT_ONLY):
                    counted = (not args.latest_only
                               or i == len(series) - 1)
                    cell += " REGRESSION" if counted else " (regressed)"
                    if counted:
                        regressions.append(
                            (path, names[prev_idx], names[i], delta))
            cells.append(cell)
            prev_val, prev_idx = v, i
        arrow = "^" if higher_better else "v"
        print(f"  {path:<38}[{arrow}] " + " | ".join(cells))

    if regressions:
        print(f"\n{len(regressions)} regression(s) beyond "
              f"{args.threshold:.0%}:")
        for path, a, b, delta in regressions:
            print(f"  {path}: {a} -> {b} ({delta:+.1%})")
    else:
        print(f"\nno same-platform regressions beyond {args.threshold:.0%}")
    return 1 if (regressions and args.fail_on_regression) else 0


if __name__ == "__main__":
    sys.exit(main())
