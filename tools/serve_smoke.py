"""Gossip-as-a-service smoke test: the CI gate for the serve/ daemon
(ISSUE 20).

Four acceptance gates over the continuous-batching scenario daemon, each
arm its own subprocess (the daemon owns global state — pubkey counter,
telemetry hub, jit caches — so cross-arm isolation must be real):

  a. **Mid-flight parity**: a warm 2-lane daemon admits five requests —
     one HTTP request deliberately held until the first is provably
     mid-flight (rounds_done > 0), plus one through the spool intake —
     and every request's parity snapshot AND deterministic Influx wire
     lines must be byte-identical to the same config run SOLO through
     run_lane_sweep.  The event log must validate (v2) and must show at
     least one admission landing between another request's admission and
     completion (continuous batching actually happened, not a lucky
     serial schedule).
  b. **Ledger admission**: an over-budget request is 413-rejected with
     the ledger-predicted and available byte counts in the refusal, and
     the daemon provably makes ZERO device allocations for it (the lazy
     device plane is never initialized).  Queue-full 429, unknown-knob
     400, and duplicate-id 400 ride along.
  c. **Crash recovery**: GOSSIP_RESILIENCE_KILL_AFTER_UNITS=1 SIGTERMs
     the daemon after its first committed request; it must drain
     co-resident lanes (committing them too), admit nothing new, and
     exit 75.  A restart of the same argv + --resume must complete every
     intake-journaled request with snapshots + wire lines bit-identical
     to the solo references, with ZERO persistent-compilation-cache
     misses (the killed arm's XLA cache serves every restart compile).
  d. **Zero steady-state recompiles**: engine/compiles scraped from
     /metrics at the first completion equals the end-of-run counter —
     admissions into the warm executable after warmup never recompile
     (knob VALUES are traced; only a gate-union flip may compile, once,
     and arm a's first admission documents that flag on its event).

Usage: python tools/serve_smoke.py [--nodes 400] [--iterations 60]
       [--warm-up 10] [--block 5] [--seed 5]

Exit code 0 = the gossip-as-a-service contract holds; 1 = it broke.
"""
import argparse
import json
import os
import subprocess
import sys
import tempfile
import threading
import time
import urllib.error
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

RESUMABLE = 75

# the five scenario requests every arm shares: two tenants interleaved,
# distinct seeds/origins, loss knobs on 1+3 (3 proves a traced VALUE
# change recompiles nothing), a5 submitted through the spool intake.
# Seeds are all == 5 (mod 256) on purpose: the daemon's synthetic
# cluster is generated ONCE from the base config's seed % 256
# (cli.load_cluster_accounts), and a request's seed drives only the
# simulation PRNG + impairment hashes — so the solo reference arm
# (which re-derives the cluster from the request config) reproduces
# the daemon's exact stake distribution only for seeds in the same
# residue class as --seed 5.
SPECS = [
    {"id": "a1", "tenant": "alice", "seed": 261, "origin_rank": 2,
     "start_ts": "0", "knobs": {"packet_loss_rate": 0.05}},
    {"id": "a2", "tenant": "bob", "seed": 517, "origin_rank": 1,
     "start_ts": "0", "knobs": {}},
    {"id": "a3", "tenant": "alice", "seed": 773, "origin_rank": 3,
     "start_ts": "0", "knobs": {"packet_loss_rate": 0.08}},
    {"id": "a4", "tenant": "bob", "seed": 1029, "origin_rank": 1,
     "start_ts": "0", "knobs": {}},
]
SPOOL_SPEC = {"id": "a5", "tenant": "carol", "seed": 1285,
              "origin_rank": 2, "start_ts": "0", "knobs": {}}


def base_argv(args):
    return ["--serve", "--num-synthetic-nodes", str(args.nodes),
            "--iterations", str(args.iterations),
            "--warm-up-rounds", str(args.warm_up),
            "--seed", str(args.seed), "--serve-lanes", "2",
            "--serve-block-rounds", str(args.block)]


def _get(url: str, timeout: float = 10.0) -> bytes:
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return resp.read()


# ---------------------------------------------------------------------------
# worker: one daemon run (cli.main on the MAIN thread so signal handlers
# install; the HTTP client drives intake from a background thread)
# ---------------------------------------------------------------------------
def worker_serve(args) -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    from gossip_sim_tpu.cli import main as cli_main
    from gossip_sim_tpu.obs import get_registry
    from gossip_sim_tpu.obs.exporter import parse_prometheus_text
    from gossip_sim_tpu.obs.telemetry import load_event_log
    from gossip_sim_tpu.engine.cache import persistent_cache_counters
    from gossip_sim_tpu.resilience import journal_path
    from gossip_sim_tpu.sinks.influx import deterministic_wire_lines

    specs = json.loads(args.specs) if args.specs else []
    argv = base_argv(args) + ["--telemetry-port", "0",
                              "--event-log", args.event_log,
                              "--serve-idle-timeout-s", "120"]
    if args.max_requests:
        argv += ["--serve-max-requests", str(args.max_requests)]
    if args.checkpoint:
        argv += ["--checkpoint-path", args.checkpoint]
    if args.resume:
        argv += ["--resume", args.resume]
    if args.cache_dir:
        argv += ["--compilation-cache-dir", args.cache_dir]
    if args.spool:
        argv += ["--serve-spool-dir", args.spool]

    out = {"submit": {}, "results": {}, "compiles_at_first_done": -1.0}
    done = threading.Event()

    def client():
        port = None
        deadline = time.time() + 120
        while time.time() < deadline and port is None and not done.is_set():
            if os.path.exists(args.event_log):
                for rec in load_event_log(args.event_log):
                    if rec.get("ev") == "telemetry_listen":
                        port = rec.get("port")
            if port is None:
                time.sleep(0.05)
        out["port"] = port
        if port is None or done.is_set():
            return
        base = f"http://127.0.0.1:{port}"

        def submit(spec):
            body = json.dumps(spec).encode()
            dl = time.time() + 90
            while True:  # routes mount just after the port binds: retry
                req = urllib.request.Request(base + "/submit", data=body,
                                             method="POST")
                try:
                    return 200, json.loads(_get_req(req))
                except urllib.error.HTTPError as e:
                    if e.code == 404 and time.time() < dl:
                        time.sleep(0.1)
                        continue
                    return e.code, json.loads(e.read() or b"{}")
                except OSError:
                    if time.time() < dl:
                        time.sleep(0.1)
                        continue
                    return -1, {}

        def _get_req(req):
            with urllib.request.urlopen(req, timeout=10) as resp:
                return resp.read()

        def result(rid):
            # urllib only raises for >=400: a 202 "still running" reply
            # comes back as a success, so read the REAL status code
            try:
                with urllib.request.urlopen(f"{base}/result/{rid}",
                                            timeout=10) as resp:
                    return resp.status, json.loads(resp.read())
            except urllib.error.HTTPError as e:
                return e.code, json.loads(e.read() or b"{}")

        for i, spec in enumerate(specs):
            if i == 1 and args.stagger:
                # hold the second submission until the first request is
                # provably mid-flight: >=1 block done, not yet finished
                dl = time.time() + 240
                while time.time() < dl and not done.is_set():
                    code, p = result(specs[0]["id"])
                    if code == 200 or (code == 202
                                       and p.get("rounds_done", 0) > 0):
                        break
                    time.sleep(0.01)
            code, body = submit(spec)
            out["submit"][spec["id"]] = {"code": code, "body": body}
        if args.spool and args.spool_spec:
            sp = json.loads(args.spool_spec)
            tmp = os.path.join(args.spool, sp["id"] + ".json.tmp")
            with open(tmp, "w") as f:
                json.dump(sp, f)
            os.replace(tmp, os.path.join(args.spool, sp["id"] + ".json"))
            specs.append(sp)

        pending = {s["id"] for s in specs}
        dl = time.time() + 420
        while pending and time.time() < dl and not done.is_set():
            for rid in sorted(pending):
                try:
                    code, p = result(rid)
                except OSError:
                    return
                if code == 200:
                    out["results"][rid] = p
                    pending.discard(rid)
            if out["results"] and out["compiles_at_first_done"] < 0:
                try:  # gate d: the counter the moment work first retired
                    m = parse_prometheus_text(
                        _get(base + "/metrics").decode())
                    out["compiles_at_first_done"] = m.get(
                        "gossip_sim_counter_total", {}).get(
                        '{counter="engine/compiles"}', -1.0)
                except (OSError, ValueError):
                    pass
            time.sleep(0.05)

    th = threading.Thread(target=client, daemon=True)
    th.start()
    rc = cli_main(argv)
    done.set()
    th.join(timeout=10)
    out["rc"] = rc
    out["compiles_end"] = float(get_registry().counter("engine/compiles"))
    out["cache"] = persistent_cache_counters()
    if args.checkpoint:  # the authoritative per-request outputs
        jp = journal_path(args.checkpoint)
        out["journal"] = {}
        if os.path.exists(jp):
            with open(jp) as f:
                lines = [ln for ln in f.read().splitlines() if ln.strip()]
            for ln in lines[1:]:
                rec = json.loads(ln)
                payload = rec.get("payload", rec)
                spec = payload.get("request") or {}
                sims = payload.get("sims") or []
                out["journal"][str(spec.get("id"))] = {
                    "unit": rec.get("unit"),
                    "snapshot": sims[0][1].get("snapshot") if sims else None,
                    # journaled lines are whole point bodies (multi-line,
                    # timestamped, replayed verbatim): split to wire lines
                    # before normalizing
                    "dlines": deterministic_wire_lines(
                        [ln for body in payload.get("lines", [])
                         for ln in body.splitlines()]),
                }
    with open(args.out, "w") as f:
        json.dump(out, f)
    return rc


# ---------------------------------------------------------------------------
# worker: solo references — each spec run alone through run_lane_sweep
# ---------------------------------------------------------------------------
def worker_solo(args) -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    from gossip_sim_tpu import resilience
    from gossip_sim_tpu.cli import build_parser, config_from_args, \
        run_lane_sweep
    from gossip_sim_tpu.identity import reset_unique_pubkeys
    from gossip_sim_tpu.obs import get_registry
    from gossip_sim_tpu.resilience import snapshot_to_jsonable
    from gossip_sim_tpu.serve import parse_request
    from gossip_sim_tpu.sinks import DatapointQueue
    from gossip_sim_tpu.stats.gossip_stats import GossipStatsCollection

    # the daemon's base config, bit for bit: same argv, same parser
    base = config_from_args(build_parser().parse_args(base_argv(args)))
    out = {}
    for spec in json.loads(args.specs):
        req = parse_request(spec, base, default_id="solo")
        rc = req.request_config(base)
        reset_unique_pubkeys()
        get_registry().reset()
        resilience.reset_shutdown()
        coll = GossipStatsCollection()
        coll.set_number_of_simulations(1)
        dpq = DatapointQueue()
        run_lane_sweep(rc, "", [rc.origin_rank], coll, dpq,
                       spec.get("start_ts", "0"))
        out[spec["id"]] = {
            "snapshot": snapshot_to_jsonable(
                coll.collection[0].parity_snapshot()),
            "dlines": dpq.drain_deterministic_lines(),
        }
    with open(args.out, "w") as f:
        json.dump(out, f)
    return 0


# ---------------------------------------------------------------------------
def main() -> int:
    ap = argparse.ArgumentParser(
        description="gossip-as-a-service daemon smoke (CPU)")
    ap.add_argument("--nodes", type=int, default=400)
    ap.add_argument("--iterations", type=int, default=60)
    ap.add_argument("--warm-up", type=int, default=10)
    ap.add_argument("--block", type=int, default=5)
    ap.add_argument("--seed", type=int, default=5)
    # worker modes (internal)
    ap.add_argument("--worker-serve", action="store_true")
    ap.add_argument("--worker-solo", action="store_true")
    ap.add_argument("--specs", default="")
    ap.add_argument("--spool-spec", default="")
    ap.add_argument("--spool", default="")
    ap.add_argument("--stagger", action="store_true")
    ap.add_argument("--max-requests", type=int, default=0)
    ap.add_argument("--checkpoint", default="")
    ap.add_argument("--resume", default="")
    ap.add_argument("--cache-dir", default="")
    ap.add_argument("--event-log", default="")
    ap.add_argument("--out", default="")
    args = ap.parse_args()
    if args.worker_serve:
        return worker_serve(args)
    if args.worker_solo:
        return worker_solo(args)

    t0 = time.time()
    tmp = tempfile.mkdtemp(prefix="serve-smoke-")
    failures = []

    def check(ok, msg):
        print(f"  [{'ok' if ok else 'FAIL'}] {msg}", flush=True)
        if not ok:
            failures.append(msg)

    def run_worker(name, mode, extra, env_extra=None):
        out = os.path.join(tmp, f"{name}.json")
        env = dict(os.environ)
        env.setdefault("JAX_PLATFORMS", "cpu")
        if env_extra:
            env.update(env_extra)
        cmd = [sys.executable, os.path.abspath(__file__), mode,
               "--nodes", str(args.nodes),
               "--iterations", str(args.iterations),
               "--warm-up", str(args.warm_up), "--block", str(args.block),
               "--seed", str(args.seed), "--out", out] + extra
        rc = subprocess.run(cmd, env=env, timeout=560).returncode
        result = None
        if os.path.exists(out):
            with open(out) as f:
                result = json.load(f)
        return rc, result

    print(f"serve smoke: n={args.nodes} iters={args.iterations} "
          f"(warm {args.warm_up}) lanes=2 block={args.block}")

    # ---- gate b first: pure admission logic, no daemon loop needed ------
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    from gossip_sim_tpu.config import Config
    from gossip_sim_tpu.serve import ServeDaemon
    cfg_b = Config(num_synthetic_nodes=150, gossip_iterations=20,
                   warm_up_rounds=4, seed=3, serve=True, serve_lanes=2,
                   serve_block_rounds=5, serve_memory_budget="8KiB")
    d = ServeDaemon(cfg_b, "", None, "0", None)
    code, payload = d.submit_raw(json.dumps(
        {"id": "big", "tenant": "alice", "seed": 1}))
    check(code == 413, f"over-budget request refused with 413 ({code})")
    check(payload.get("predicted_bytes", 0) > 8192
          and payload.get("available_bytes") == 8192
          and payload.get("budget_bytes") == 8192,
          f"413 carries the ledger-predicted + available byte counts "
          f"({payload.get('predicted_bytes')} predicted vs 8192 budget)")
    check(not d._device_ready and d.tables is None and d.states is None,
          "rejection priced host-side: zero device allocations "
          "(device plane never initialized)")
    check(d.admission.counters == {"received": 1, "admitted": 0,
                                   "rejected": 1, "completed": 0},
          f"admission counters attribute the refusal "
          f"({d.admission.counters})")
    cfg_q = Config(num_synthetic_nodes=150, gossip_iterations=20,
                   warm_up_rounds=4, seed=3, serve=True, serve_lanes=2,
                   serve_block_rounds=5, serve_max_queue=1)
    d2 = ServeDaemon(cfg_q, "", None, "0", None)
    c1, _ = d2.submit_raw(json.dumps({"id": "q1", "seed": 1}))
    c2, p2 = d2.submit_raw(json.dumps({"id": "q2", "seed": 2}))
    check(c1 == 200 and c2 == 429,
          f"queue-full request refused with 429 ({c1}, {c2}: "
          f"{p2.get('reason', p2)})")
    c3, _ = d2.submit_raw(json.dumps({"id": "q1", "seed": 3}))
    c4, _ = d2.submit_raw(json.dumps({"id": "q3", "knobs": {"bogus": 1}}))
    check(c3 == 400 and c4 == 400,
          f"duplicate id + unknown knob refused with 400 ({c3}, {c4})")
    check(not d2._device_ready, "intake alone touches no device state")

    # ---- solo references (gates a + c compare against these) ------------
    all_specs = SPECS + [SPOOL_SPEC]
    rc_solo, solo = run_worker(
        "solo", "--worker-solo", ["--specs", json.dumps(all_specs)])
    check(rc_solo == 0 and solo is not None
          and set(solo or {}) == {s["id"] for s in all_specs},
          f"solo reference arm completed ({sorted(solo or {})})")

    # ---- arm A: warm daemon, staggered + spool intake (gates a, d) ------
    evt_a = os.path.join(tmp, "serve.events")
    spool = os.path.join(tmp, "spool")
    cache = os.path.join(tmp, "xla-cache")  # shared: arm A compiles the
    os.makedirs(spool, exist_ok=True)       # dyn kernel once, C/D reuse it
    rc_a, arm_a = run_worker(
        "daemon", "--worker-serve",
        ["--specs", json.dumps(SPECS), "--stagger",
         "--spool", spool, "--spool-spec", json.dumps(SPOOL_SPEC),
         "--max-requests", str(len(SPECS) + 1),
         "--checkpoint", os.path.join(tmp, "serve.npz"),
         "--cache-dir", cache, "--event-log", evt_a])
    check(rc_a == 0 and arm_a is not None,
          f"daemon arm served {len(SPECS) + 1} requests and exited 0 "
          f"(rc={rc_a})")
    arm_a = arm_a or {}
    sub = arm_a.get("submit", {})
    check(all(sub.get(s["id"], {}).get("code") == 200 for s in SPECS),
          f"every HTTP submission accepted "
          f"({ {k: v.get('code') for k, v in sub.items()} })")
    jr = arm_a.get("journal", {})
    for spec in all_specs:
        rid, ref = spec["id"], (solo or {}).get(spec["id"], {})
        got = jr.get(rid, {})
        check(bool(got) and got.get("snapshot") == ref.get("snapshot"),
              f"{rid}: daemon parity snapshot bit-identical to solo "
              f"run_lane_sweep")
        check(bool(got) and got.get("dlines") == ref.get("dlines")
              and got.get("dlines"),
              f"{rid}: deterministic Influx wire lines bit-identical to "
              f"solo ({len(got.get('dlines') or [])} lines)")
    for rid, res in arm_a.get("results", {}).items():
        check(res.get("snapshot") == jr.get(rid, {}).get("snapshot"),
              f"{rid}: /result payload matches the journaled snapshot")
    res_5 = os.path.join(spool, SPOOL_SPEC["id"] + ".result.json")
    check(os.path.exists(res_5), "spool intake wrote a5.result.json")
    if os.path.exists(res_5):
        with open(res_5) as f:
            sp_res = json.load(f)
        check(sp_res.get("snapshot") == (solo or {}).get(
            SPOOL_SPEC["id"], {}).get("snapshot"),
              "spool result snapshot bit-identical to solo")

    from gossip_sim_tpu.obs.telemetry import (load_event_log,
                                              validate_event_log)
    problems = validate_event_log(evt_a)
    check(problems == [],
          f"serve event log validates ({problems[:3] or 'clean'})")
    recs = load_event_log(evt_a)
    kinds = {r.get("ev") for r in recs}
    for want in ("request_received", "request_admitted",
                 "request_completed", "lane_evicted"):
        check(want in kinds, f"event log carries {want}")
    admit_at, done_at = {}, {}
    for i, r in enumerate(recs):
        if r.get("ev") == "request_admitted":
            admit_at[r.get("id")] = i
        elif r.get("ev") == "request_completed":
            done_at[r.get("id")] = i
    overlapped = any(
        admit_at[r] < admit_at[s] < done_at.get(r, -1)
        for r in admit_at for s in admit_at if r != s)
    check(overlapped,
          "continuous batching observed: an admission landed while "
          "another request was mid-flight")
    unions = [r.get("gate_union") for r in recs
              if r.get("ev") == "request_admitted"]
    check(any(unions),
          f"the one impairment gate-union widening is flagged on its "
          f"admission event ({unions})")

    # ---- gate d: zero recompiles at steady state ------------------------
    mid = arm_a.get("compiles_at_first_done", -1.0)
    end = arm_a.get("compiles_end", -2.0)
    check(mid > 0 and mid == end,
          f"zero steady-state recompiles: engine/compiles at first "
          f"completion == at exit ({mid} == {end})")

    # ---- gate c: kill mid-service, restart, bit-exact completion --------
    ck = os.path.join(tmp, "killed.npz")
    evt_k = os.path.join(tmp, "killed.events")
    rc_k, arm_k = run_worker(
        "killed", "--worker-serve",
        ["--specs", json.dumps(SPECS), "--checkpoint", ck,
         "--cache-dir", cache, "--event-log", evt_k,
         "--max-requests", str(len(SPECS))],
        env_extra={"GOSSIP_RESILIENCE_KILL_AFTER_UNITS": "1"})
    check(rc_k == RESUMABLE,
          f"killed daemon drained and exited with the resumable code "
          f"({rc_k} == {RESUMABLE})")
    committed = sorted((arm_k or {}).get("journal", {}))
    check(0 < len(committed) < len(SPECS),
          f"kill landed mid-service: {len(committed)}/{len(SPECS)} "
          f"requests committed ({committed})")
    intake = []
    intake_path = ck[:-len(".npz")] + ".journal.intake"
    if os.path.exists(intake_path):
        with open(intake_path) as f:
            intake = [json.loads(ln)["id"] for ln in
                      f.read().splitlines() if ln.strip()]
    check(sorted(intake) == sorted(s["id"] for s in SPECS),
          f"intake sidecar journaled every accepted request ({intake})")

    evt_r = os.path.join(tmp, "restart.events")
    rc_r, arm_r = run_worker(
        "restart", "--worker-serve",
        ["--specs", "[]", "--checkpoint", ck, "--resume", ck,
         "--cache-dir", cache, "--event-log", evt_r,
         "--max-requests", str(len(SPECS))])
    check(rc_r == 0, f"restarted daemon completed the journaled work "
                     f"and exited 0 (rc={rc_r})")
    jr_r = (arm_r or {}).get("journal", {})
    check(sorted(jr_r) == sorted(s["id"] for s in SPECS),
          f"restart completed every intake-journaled request "
          f"({sorted(jr_r)})")
    for spec in SPECS:
        rid, ref = spec["id"], (solo or {}).get(spec["id"], {})
        got = jr_r.get(rid, {})
        tag = ("replayed" if rid in committed else "recomputed")
        check(bool(got) and got.get("snapshot") == ref.get("snapshot")
              and got.get("dlines") == ref.get("dlines"),
              f"{rid}: {tag} after restart, bit-identical to solo")
    cache_stats = (arm_r or {}).get("cache", {})
    check(cache_stats.get("misses", -1) == 0
          and cache_stats.get("hits", 0) >= 1,
          f"zero persistent-cache misses on restart (no recompiles): "
          f"{cache_stats}")

    print(f"  elapsed: {time.time() - t0:.1f}s")
    if failures:
        print(f"SERVE SMOKE FAILED ({len(failures)} invariant(s)):")
        for f in failures:
            print(f"  - {f}")
        return 1
    print("SERVE SMOKE PASSED")
    return 0


if __name__ == "__main__":
    sys.exit(main())
