"""Simulation configuration, sweep test types, and step sizes.

Mirrors the reference's ``Config`` (gossip.rs:111-133), ``Testing``
(gossip.rs:33-76) and ``StepSize`` (gossip.rs:78-109).  Flag names and
defaults are the compatibility contract (gossip_main.rs:53-241).
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field, replace


class Testing(enum.Enum):
    ACTIVE_SET_SIZE = "active-set-size"
    PUSH_FANOUT = "push-fanout"
    MIN_INGRESS_NODES = "min-ingress-nodes"
    PRUNE_STAKE_THRESHOLD = "prune-stake-threshold"
    ORIGIN_RANK = "origin-rank"
    FAIL_NODES = "fail-nodes"
    ROTATE_PROBABILITY = "rotate-probability"
    PACKET_LOSS = "packet-loss"
    CHURN = "churn"
    PULL_FANOUT = "pull-fanout"
    TRAFFIC_RATE = "traffic-rate"
    NODE_INGRESS_CAP = "node-ingress-cap"
    ADAPTIVE_THRESHOLD = "adaptive-threshold"
    NO_TEST = "no-test"

    def __str__(self):
        # Display names match the reference (gossip.rs:45-58).
        return {
            Testing.ACTIVE_SET_SIZE: "ActiveSetSize",
            Testing.PUSH_FANOUT: "PushFanout",
            Testing.MIN_INGRESS_NODES: "MinIngressNodes",
            Testing.PRUNE_STAKE_THRESHOLD: "PruneStakeThreshold",
            Testing.ORIGIN_RANK: "OriginRank",
            Testing.FAIL_NODES: "FailNodes",
            Testing.ROTATE_PROBABILITY: "RotateProbability",
            Testing.PACKET_LOSS: "PacketLoss",
            Testing.CHURN: "Churn",
            Testing.PULL_FANOUT: "PullFanout",
            Testing.TRAFFIC_RATE: "TrafficRate",
            Testing.NODE_INGRESS_CAP: "NodeIngressCap",
            Testing.ADAPTIVE_THRESHOLD: "AdaptiveThreshold",
            Testing.NO_TEST: "NoTest",
        }[self]

    @classmethod
    def parse(cls, s: str) -> "Testing":
        for t in cls:
            if t.value == s:
                return t
        raise ValueError(f"Invalid test type: {s}")


@dataclass(frozen=True)
class StepSize:
    """Integer-or-float sweep step (gossip.rs:78-109)."""

    value: float
    is_integer: bool

    @classmethod
    def parse(cls, s: str) -> "StepSize":
        try:
            return cls(value=int(s), is_integer=True)
        except ValueError:
            return cls(value=float(s), is_integer=False)

    def as_int(self) -> int:
        return int(self.value)

    def as_float(self) -> float:
        return float(self.value)

    def __str__(self):
        return str(int(self.value)) if self.is_integer else str(self.value)


@dataclass
class Config:
    """Flat simulation config (gossip.rs:111-133). Defaults from
    gossip_main.rs:90,97,104,113,124,135,142,150-169,204-224."""

    gossip_push_fanout: int = 6
    gossip_active_set_size: int = 12
    gossip_iterations: int = 1
    accounts_from_file: bool = False
    account_file: str = ""
    origin_rank: int = 1
    probability_of_rotation: float = 0.013333
    prune_stake_threshold: float = 0.15
    min_ingress_nodes: int = 2
    filter_zero_staked_nodes: bool = False
    num_buckets_for_stranded_node_hist: int = 10
    num_buckets_for_message_hist: int = 5
    num_buckets_for_hops_stats_hist: int = 15
    fraction_to_fail: float = 0.1
    when_to_fail: int = 0
    test_type: Testing = Testing.NO_TEST
    num_simulations: int = 1
    step_size: StepSize = field(default_factory=lambda: StepSize(1, True))
    warm_up_rounds: int = 200
    print_stats: bool = False

    # Network-impairment / fault-injection knobs (faults.py; both backends,
    # bit-equivalent decisions under a shared seed).  All-off defaults keep
    # every output bit-identical to the unimpaired simulator:
    packet_loss_rate: float = 0.0   # per-message Bernoulli drop probability
    churn_fail_rate: float = 0.0    # per-iteration P(alive node fails)
    churn_recover_rate: float = 0.0  # per-iteration P(failed node recovers)
    partition_at: int = -1          # iteration the stake bipartition starts
    heal_at: int = -1               # iteration it heals (-1 = never)

    # Pull-gossip / anti-entropy (pull.py; both backends, bit-equivalent
    # decisions under the shared seed).  gossip_mode "push" keeps every
    # output bit-identical to the push-only simulator:
    gossip_mode: str = "push"       # "push" | "pull" | "push-pull" |
                                    # "adaptive" (adaptive.py)
    pull_fanout: int = 2            # pull requests per live node per round
    pull_interval: int = 1          # rounds between pull exchanges
    pull_bloom_fp_rate: float = 0.1  # bloom false-positive probability
    pull_request_cap: int = 0       # requests served per peer (<=0 = no cap)

    # Adaptive push-pull (adaptive.py): direction-optimizing switch knobs,
    # meaningful only under gossip_mode "adaptive".  Both are traced
    # EngineKnobs leaves, so an adaptive-threshold sweep compiles once:
    adaptive_switch_threshold: float = 0.9   # coverage fraction flipping
                                             # a sim/value into pull phase
    adaptive_switch_hysteresis: float = 0.05  # window below the threshold
                                              # before flipping back

    # Concurrent traffic (traffic.py; both backends, bit-equivalent
    # decisions under the shared seed).  traffic_values == 1 with both
    # queue caps at 0 keeps every output bit-identical to the
    # single-value simulator (the subsystem is fully gated out):
    traffic_values: int = 1         # concurrent value slots (static M)
    traffic_rate: int = 1           # new values injected per round
    node_ingress_cap: int = 0       # msgs accepted/node/round (<=0 = off)
    node_egress_cap: int = 0        # msgs sent/node/round (<=0 = off)
    traffic_stall_rounds: int = 3   # no-progress rounds before a value
                                    # retires un-converged

    # TPU-framework extensions (not in the reference):
    backend: str = "tpu"            # "tpu" | "oracle"
    seed: int = 42                  # deterministic by construction
    num_synthetic_nodes: int = 0    # >0: synthetic cluster instead of file/RPC
    all_origins: bool = False       # vmap the origin axis (north-star mode)
    origin_batch: int = 0           # origins per device batch (0 = auto)
    sweep_lanes: int = 0            # >0: run knob sweeps lane-batched — K
                                    # sweep points vmapped into one device
                                    # program, ceil(K/lanes) batched calls
                                    # (engine/lanes.py); 0 = serial sweep.
                                    # Only traced-knob test types are
                                    # lane-eligible (cli.LANE_SWEEP_TYPES);
                                    # others warn and run serially
    checkpoint_path: str = ""       # save sim state (periodically + at end);
                                    # multi-unit runs (sweeps, lane mode,
                                    # --all-origins) additionally keep a
                                    # sibling run journal (resilience.py)
    resume_path: str = ""           # load sim state / journal and continue
    checkpoint_every_s: float = 0.0  # min seconds between periodic
                                    # checkpoint autosaves on the single-
                                    # run path (0 = every harvest block,
                                    # the pre-resilience cadence)
    device_timeout_s: float = 0.0   # watchdog bound on one engine
                                    # dispatch (resilience.py); 0 = off
    device_retries: int = 2         # transient-failure retries per
                                    # supervised dispatch
    on_device_failure: str = ""     # "" = unsupervised unless a timeout
                                    # is set; "cpu-fallback" re-executes
                                    # the failed unit on the CPU backend;
                                    # "abort" exits with the resumable
                                    # exit code (journal committed)
    influx_spool: str = ""          # durable spool file: Influx points
                                    # dropped after retry exhaustion are
                                    # appended here as line protocol and
                                    # re-sendable via tools/influx_replay
    mesh_devices: int = 0           # 0 = all available devices
    mesh_node_shards: int = 1       # shard the per-origin node axis over
                                    # this many devices per origin-shard
                                    # (parallel/mesh.py; must divide
                                    # mesh_devices)
    jax_profile_dir: str = ""       # capture jax.profiler trace of measured
                                    # rounds (tpu backend); XProf shows the
                                    # round/* named_scope stages (obs/)
    run_report_path: str = ""       # write the machine-readable run report
                                    # (obs/report.py schema) to this path
    memwatch_interval_s: float = 0.0  # live footprint sampler interval
                                    # (obs/memwatch.py): poll host RSS +
                                    # device memory_stats every this many
                                    # seconds; 0 = off (the run report
                                    # still carries the kernel peak-RSS
                                    # high-water mark)
    capacity_harvest: bool = False  # XLA cost harvest (obs/capacity.py):
                                    # capture cost_analysis/
                                    # memory_analysis per compiled engine
                                    # executable.  Costs ONE extra XLA
                                    # compile per distinct executable
                                    # (cheap with --compilation-cache-dir)
                                    # and zero bits of simulation impact
    trace_dir: str = ""             # flight recorder (obs/trace.py): write
                                    # per-round protocol event traces
                                    # (schema gossip-sim-tpu/trace/v1) here
    trace_origins: int = 4          # --all-origins mode: how many sampled
                                    # origins to flight-record (per-origin
                                    # RNG streams make the sampled replay
                                    # bit-identical to the batched sims)
    trace_prune_cap: int = 0        # prune pairs captured per (origin,
                                    # round); 0 = auto (16*num_nodes).
                                    # Raise when the trace manifest flags
                                    # truncated_prune_rounds
    health: bool = False            # node-health observatory (obs/health.py):
                                    # accumulate per-node load/latency/drop
                                    # planes inside the jitted round and
                                    # digest them per measured block (decile
                                    # segment sums + hot-node top-k).  Off =
                                    # every output bit-identical to today
    health_topk: int = 10           # hot nodes extracted per digest (the
                                    # [k,·] harvest; report + sim_node_health)
    engine_representation: str = "dense"  # gossip-round execution layout
                                    # (engine/sparse.py): "dense" keeps the
                                    # full-width sort-routed round; "sparse"
                                    # reroutes over the candidate edge list
                                    # (segment reductions + scatters) and
                                    # derives the rc stake planes from the
                                    # cluster tables.  Bit-identical rows
                                    # and state either way — sparse is the
                                    # memory/scale representation
    compilation_cache_dir: str = ""  # persistent XLA compilation cache
                                    # (engine/cache.py): compiled
                                    # executables are reused across
                                    # processes/CI runs; "" falls back to
                                    # $GOSSIP_COMPILATION_CACHE, unset = off
    telemetry_port: int = -1        # live telemetry plane (obs/exporter.py):
                                    # serve /metrics + /status + /events on
                                    # 127.0.0.1:PORT while the run is live;
                                    # 0 = ephemeral port (stamped into the
                                    # log + run report), -1 = off
    event_log: str = ""             # structured event log (obs/telemetry.py,
                                    # schema gossip-sim-tpu/events/v1):
                                    # append heartbeat/journal/watchdog/
                                    # Influx/signal events as JSONL here

    # -- gossip-as-a-service daemon (serve/, ISSUE 20) ---------------------
    serve: bool = False             # run the continuous-batching scenario
                                    # daemon instead of a one-shot path
    serve_lanes: int = 4            # K: warm device lanes the daemon holds
    serve_block_rounds: int = 25    # scheduler tick granularity (rounds per
                                    # dispatch; snapped down to a divisor of
                                    # gossip_iterations so lanes retire
                                    # exactly at block boundaries)
    serve_memory_budget: str = ""   # ledger budget gating admission
                                    # (parse_size: "16GB"; "" = unlimited)
    serve_max_queue: int = 64       # queued requests across all tenants
                                    # before 429 (0 = reject when no lane)
    serve_spool_dir: str = ""       # watched intake directory (*.json
                                    # request specs; results written back)
    serve_max_requests: int = 0     # exit 0 after N completions (0 = run
                                    # until idle-timeout/signal; gates+bench)
    serve_idle_timeout_s: float = 0.0  # exit 0 after this long with no
                                    # work in flight or queued (0 = never)

    def stepped(self, **kw) -> "Config":
        return replace(self, **kw)

    @property
    def impairments_on(self) -> bool:
        """Any fault-injection knob beyond the reference's one-shot
        FAIL_NODES (mirrors EngineParams.has_impairments)."""
        return (self.packet_loss_rate > 0.0 or self.churn_fail_rate > 0.0
                or self.churn_recover_rate > 0.0 or self.partition_at >= 0)

    @property
    def wants_delivery_stats(self) -> bool:
        """Record delivered/dropped/suppressed counters: when impairments
        are on, OR when the run is a point of an impairment sweep — so the
        sweep's rate-0 baseline still emits its delivery series and the
        degradation trend has an anchor."""
        return (self.impairments_on
                or self.test_type in (Testing.PACKET_LOSS, Testing.CHURN))

    @property
    def has_pull(self) -> bool:
        """The gossip mode includes the pull (anti-entropy) phase — and
        with it the pull counters/series (a PULL_FANOUT sweep requires a
        pull mode; the CLI rejects it otherwise)."""
        return self.gossip_mode != "push"

    @property
    def traffic_on(self) -> bool:
        """The concurrent-traffic subsystem is engaged (traffic.py):
        more than one value slot, or a queue cap constraining the
        single-value stream.  Mirrors EngineParams.has_traffic — False
        keeps the run on the unmodified single-value paths."""
        return (self.traffic_values > 1 or self.node_ingress_cap > 0
                or self.node_egress_cap > 0)
