"""Node identity layer: pubkeys, base58, stake buckets, and the pubkey<->index map.

The TPU engine works on dense int32 node indices; 32-byte pubkeys exist only at
the I/O edge.  This module provides:

  * ``Pubkey`` — a 32-byte identity with byte-wise ordering (reference:
    solana_sdk Pubkey ordering, used by gossip.rs:1064 ``nodes.sort_by_key``)
    and base58 string form (string ordering is the consume_messages tie-break,
    gossip.rs:638-645).
  * ``pubkey_new_unique`` — deterministic counter-based pubkey generator
    mirroring ``Pubkey::new_unique`` (big-endian counter in the first 8 bytes),
    used to reproduce reference test fixtures.
  * ``get_stake_bucket`` — log2 stake bucketing (reference:
    push_active_set.rs:190-196).
  * ``NodeIndex`` — the bidirectional pubkey<->index mapping.  Indices are
    assigned in **base58-string sort order** so that integer index order equals
    the reference's string tie-break order; the dense engine then tie-breaks on
    the index alone.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass

import numpy as np

from .constants import LAMPORTS_PER_SOL, NUM_PUSH_ACTIVE_SET_ENTRIES

_B58_ALPHABET = "123456789ABCDEFGHJKLMNPQRSTUVWXYZabcdefghijkmnopqrstuvwxyz"
_B58_INDEX = {c: i for i, c in enumerate(_B58_ALPHABET)}


def b58encode(raw: bytes) -> str:
    """Base58 (bitcoin alphabet) encode, preserving leading zero bytes as '1's."""
    n_zeros = len(raw) - len(raw.lstrip(b"\0"))
    num = int.from_bytes(raw, "big")
    chars = []
    while num > 0:
        num, rem = divmod(num, 58)
        chars.append(_B58_ALPHABET[rem])
    return "1" * n_zeros + "".join(reversed(chars))


def b58decode(s: str, length: int = 32) -> bytes:
    n_ones = len(s) - len(s.lstrip("1"))
    num = 0
    for c in s[n_ones:]:
        num = num * 58 + _B58_INDEX[c]
    return b"\0" * n_ones + num.to_bytes(length - n_ones, "big")


class Pubkey:
    """32-byte node identity. Ordered byte-wise; displayed as base58."""

    __slots__ = ("raw", "_s")

    def __init__(self, raw: bytes):
        assert len(raw) == 32
        self.raw = raw
        self._s = None

    @classmethod
    def from_string(cls, s: str) -> "Pubkey":
        return cls(b58decode(s, 32))

    def to_string(self) -> str:
        if self._s is None:
            self._s = b58encode(self.raw)
        return self._s

    def __str__(self):
        return self.to_string()

    def __repr__(self):
        return f"Pubkey({self.to_string()})"

    def __eq__(self, other):
        return isinstance(other, Pubkey) and self.raw == other.raw

    def __lt__(self, other):
        return self.raw < other.raw

    def __le__(self, other):
        return self.raw <= other.raw

    def __hash__(self):
        return hash(self.raw)


_unique_lock = threading.Lock()
_unique_counter = 1


def pubkey_new_unique() -> Pubkey:
    """Counter-based unique pubkey: big-endian counter in bytes [0..8).

    Mirrors ``Pubkey::new_unique`` so reference test fixtures (hardcoded base58
    strings like ``1111111QLbz7JHiBTspS962RLKV8GndWFwiEaqKM``) reproduce.
    """
    global _unique_counter
    with _unique_lock:
        i = _unique_counter
        _unique_counter += 1
    return Pubkey(i.to_bytes(8, "big") + b"\0" * 24)


def reset_unique_pubkeys(start: int = 1) -> None:
    """Reset the new_unique counter (test fixtures, and journal resume —
    a resumed sweep restores the counter so later synthetic clusters draw
    the same pubkeys an uninterrupted run would, resilience.py)."""
    global _unique_counter
    with _unique_lock:
        _unique_counter = int(start)


def peek_unique_pubkeys() -> int:
    """The next value ``pubkey_new_unique`` will consume (journal
    position marker; does not advance the counter)."""
    with _unique_lock:
        return _unique_counter


def get_stake_bucket(stake: int) -> int:
    """Map a lamport stake to one of 25 log2 buckets.

    bucket = min(bit_length(stake // LAMPORTS_PER_SOL), 24)
    (reference: push_active_set.rs:190-196; 64 - leading_zeros == bit_length).
    """
    sol = int(stake) // LAMPORTS_PER_SOL
    return min(sol.bit_length(), NUM_PUSH_ACTIVE_SET_ENTRIES - 1)


def stake_buckets_array(stakes_lamports: np.ndarray) -> np.ndarray:
    """Vectorized ``get_stake_bucket`` over an int64/object array of lamports."""
    sol = np.asarray(stakes_lamports, dtype=np.uint64) // np.uint64(LAMPORTS_PER_SOL)
    # bit_length via log2-free loop on uint64: use frexp-safe integer method.
    out = np.zeros(sol.shape, dtype=np.int32)
    v = sol.copy()
    while np.any(v):
        nz = v > 0
        out[nz] += 1
        v >>= np.uint64(1)
    return np.minimum(out, NUM_PUSH_ACTIVE_SET_ENTRIES - 1)


@dataclass
class NodeIndex:
    """Bidirectional pubkey <-> dense index mapping.

    Indices are assigned in base58-string order so that ``index_a < index_b``
    iff ``str(pk_a) < str(pk_b)``; the engine's (hops, index) inbound ranking
    then matches the reference's (hops, pubkey-string) sort
    (gossip.rs:638-645) exactly.
    """

    pubkeys: list  # index -> Pubkey
    stakes: np.ndarray  # index -> lamports (uint64)
    _index: dict = None  # pubkey raw bytes -> index

    @classmethod
    def from_stakes(cls, accounts: dict) -> "NodeIndex":
        """accounts: {Pubkey | str: stake_lamports}."""
        pairs = []
        for pk, stake in accounts.items():
            if not isinstance(pk, Pubkey):
                pk = Pubkey.from_string(pk)
            pairs.append((pk.to_string(), pk, int(stake)))
        pairs.sort(key=lambda t: t[0])
        pubkeys = [p for _, p, _ in pairs]
        stakes = np.array([s for _, _, s in pairs], dtype=np.uint64)
        index = {p.raw: i for i, p in enumerate(pubkeys)}
        return cls(pubkeys=pubkeys, stakes=stakes, _index=index)

    def __len__(self):
        return len(self.pubkeys)

    def index_of(self, pk: Pubkey) -> int:
        return self._index[pk.raw]

    def buckets(self) -> np.ndarray:
        return stake_buckets_array(self.stakes)
