"""gossip_sim_tpu — TPU-native Solana gossip push-protocol simulator.

A ground-up JAX/XLA re-design of gregcusack/gossip-sim (see SURVEY.md): the
per-iteration BFS + prune + rotate loop is recast as batched dense-array
kernels under ``jit``/``vmap``/``shard_map``, with a faithful seeded CPU
oracle backend as the parity referee.

Layout:
  identity   pubkey <-> dense-index mapping, base58, stake buckets
  ingest     YAML / JSON-RPC account loading (gossip.rs:883-1005)
  config     Config / Testing / StepSize (gossip.rs:33-133)
  oracle     CPU oracle backend incl. bit-exact rand/ChaCha port
  engine     TPU backend: jitted five-verb round, vmapped origins
  parallel   device mesh + origin/node-axis sharding
  stats      GossipStats suite (gossip_stats.rs)
  sinks      InfluxDB line-protocol sink (influx_db.rs)
  cli        experiment driver + sweep harness (gossip_main.rs)
"""

__version__ = "0.4.0"
