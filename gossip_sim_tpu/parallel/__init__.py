"""Device-mesh construction and state sharding.

The origin axis is this framework's data-parallel axis (each origin is an
independent simulation, gossip_main.rs:292-647 — no cross-origin traffic, so
origin sharding rides ICI with zero steady-state collectives).  The node axis
of the per-origin state can additionally be sharded ("model" style) for very
large clusters; XLA/GSPMD inserts the all-reduce-min for the frontier
relaxation and the all-to-alls for the edge sort automatically.
"""

from .mesh import make_mesh, shard_sim, state_shardings

__all__ = ["make_mesh", "shard_sim", "state_shardings"]
