"""Mesh + sharding specs for ``SimState``.

Replaces the reference's rayon thread-parallelism (gossip_main.rs:271,
gossip.rs:747) and its *absent* distributed backend (SURVEY.md §2.3) with a
``jax.sharding.Mesh`` over ('origins', 'nodes'):

  * 'origins' — embarrassingly parallel batch of independent single-origin
    sims; the primary scaling axis (shard O).
  * 'nodes'   — optional second axis sharding the per-origin [N, ...] state;
    GSPMD lowers the engine's sort-routed frontier/ranking steps to
    sharded sorts with ICI collectives at the shard boundaries.
"""

from __future__ import annotations

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def make_mesh(n_devices: int = 0, node_shards: int = 1,
              devices=None) -> Mesh:
    """Build an ('origins', 'nodes') mesh over the first ``n_devices``."""
    if devices is None:
        devices = jax.devices()
    if n_devices <= 0:
        n_devices = len(devices)
    devices = devices[:n_devices]
    assert n_devices % node_shards == 0, (n_devices, node_shards)
    arr = np.array(devices).reshape(n_devices // node_shards, node_shards)
    return Mesh(arr, ("origins", "nodes"))


def state_shardings(mesh: Mesh, shard_nodes: bool = True) -> dict:
    """PartitionSpec per SimState field (field name -> spec)."""
    n = "nodes" if shard_nodes else None
    return {
        "key": P("origins"),
        "active": P("origins", n),
        "pruned": P("origins", n),
        "tfail": P("origins", n),
        "rc_src": P("origins", n),
        "rc_score": P("origins", n),
        "rc_shi": P("origins", n),
        "rc_slo": P("origins", n),
        "rc_upserts": P("origins", n),
        "failed": P("origins", n),
        "egress_acc": P("origins", n),
        "ingress_acc": P("origins", n),
        "prune_acc": P("origins", n),
        "stranded_acc": P("origins", n),
        "hops_hist_acc": P("origins"),
        # pull-gossip accumulators (pull.py): histogram rows replicate on
        # the node axis like hops_hist_acc, rescue counts shard with it
        "pull_hops_hist_acc": P("origins"),
        "pull_rescued_acc": P("origins", n),
        # node-health observatory planes (obs/health.py): [O, N], shard
        # with the other per-node accumulators
        "health_prune_recv": P("origins", n),
        "health_first_round": P("origins", n),
        # adaptive direction bit (adaptive.py): [O], per-origin-sim
        "adaptive_pull_on": P("origins"),
    }


def shard_sim(mesh: Mesh, state, origins, shard_nodes: bool = True):
    """Place a SimState + origin vector onto the mesh."""
    specs = state_shardings(mesh, shard_nodes)
    state = type(state)(**{
        f: jax.device_put(getattr(state, f), NamedSharding(mesh, specs[f]))
        for f in specs})
    origins = jax.device_put(origins, NamedSharding(mesh, P("origins")))
    return state, origins
