"""Shared constants.

TPU-native re-implementation of the reference's shared definitions
(reference: lib.rs:8-17, push_active_set.rs:11, received_cache.rs:21,78,81,
gossip.rs:31).
"""

# Solana native-token scale (reference: solana_sdk::native_token::LAMPORTS_PER_SOL,
# used at push_active_set.rs:191).
LAMPORTS_PER_SOL = 1_000_000_000

# Number of stake buckets in a push active set (reference: push_active_set.rs:11).
NUM_PUSH_ACTIVE_SET_ENTRIES = 25

# Received-cache gating / scoring constants (reference: received_cache.rs:21,78,81).
MIN_NUM_UPSERTS = 20
RECEIVED_CACHE_CAPACITY = 50
NUM_DUPS_THRESHOLD = 2

# CRDS unique pubkey capacity; the received cache is sized 2x this
# (reference: gossip.rs:31,906).
CRDS_UNIQUE_PUBKEY_CAPACITY = 8192

# Sentinel distance for unreached nodes (reference uses u64::MAX, gossip.rs:490).
UNREACHED = (1 << 64) - 1

# RPC endpoints (reference: lib.rs:8-9).
API_MAINNET_BETA = "https://api.mainnet-beta.solana.com"
API_TESTNET = "https://api.testnet.solana.com"

# Influx endpoints (reference: lib.rs:11-12).
INFLUX_INTERNAL_METRICS = "https://internal-metrics.solana.com:8086"
INFLUX_LOCALHOST = "http://localhost:8086"

# Coverage level a healed/recovering cluster must regain for the
# iterations-to-recover metric (faults.py workloads); matches the CLI's
# poor-coverage warning threshold (gossip_main.rs:408).
COVERAGE_RECOVERY_THRESHOLD = 0.95

# Histogram bounds (reference: lib.rs:14-17).
VALIDATOR_STAKE_DISTRIBUTION_NUM_BUCKETS = 50
AGGREGATE_HOPS_FAIL_NODES_HISTOGRAM_UPPER_BOUND = 40.0
AGGREGATE_HOPS_MIN_INGRESS_NODES_HISTOGRAM_UPPER_BOUND = 50
STANDARD_HISTOGRAM_UPPER_BOUND = 30


def get_json_rpc_url(url: str) -> str:
    """Resolve RPC URL monikers (reference: lib.rs:88-94)."""
    return {"m": API_MAINNET_BETA, "mainnet-beta": API_MAINNET_BETA,
            "t": API_TESTNET, "testnet": API_TESTNET}.get(url, url)


def get_influx_url(url: str) -> str:
    """Resolve Influx URL monikers (reference: lib.rs:96-102)."""
    return {"i": INFLUX_INTERNAL_METRICS, "internal-metrics": INFLUX_INTERNAL_METRICS,
            "l": INFLUX_LOCALHOST, "localhost": INFLUX_LOCALHOST}.get(url, url)


class Stats:
    """f64 stat display wrapper (reference: lib.rs:58-64,76-86):
    ``Stats.mean(x)`` formats as "Mean: {x:.6}" etc."""

    def __init__(self, kind: str, value: float):
        self.kind = kind
        self.value = value

    mean = classmethod(lambda cls, v: cls("Mean", v))
    median = classmethod(lambda cls, v: cls("Median", v))
    max = classmethod(lambda cls, v: cls("Max", v))
    min = classmethod(lambda cls, v: cls("Min", v))

    def __str__(self):
        return f"{self.kind}: {self.value:.6f}"

    def __eq__(self, other):
        return (isinstance(other, type(self)) and self.kind == other.kind
                and self.value == other.value)


class HopsStats(Stats):
    """Hop stat display wrapper (reference: lib.rs:50-56,66-74): means get
    6 decimals, medians 2, max/min print as integers."""

    def __str__(self):
        if self.kind == "Mean":
            return f"Mean: {self.value:.6f}"
        if self.kind == "Median":
            return f"Median: {self.value:.2f}"
        return f"{self.kind}: {int(self.value)}"
