"""Simulation-state checkpoint / resume.

The reference has no checkpointing (SURVEY.md §5) — its closest artifact is
the account-file snapshot (write_accounts_main.rs:118-125).  Long sweeps on
TPU make resumability a cheap win: ``SimState`` is a flat pytree of arrays,
so one ``.npz`` captures the whole simulation (active sets, prune bits,
received caches, accumulators, RNG keys) plus the static params that shaped
it.  Loading validates shape-defining params so a resumed run can't silently
continue under a different compiled geometry.
"""

from __future__ import annotations

import json
import logging
import os
import tempfile

import numpy as np

from .engine.params import EngineParams

log = logging.getLogger(__name__)

_FORMAT_VERSION = 9
# v1 checkpoints predate the tfail/rc_shi/rc_slo SimState fields; all three
# are derivable from active/failed/rc_src plus the cluster stake table, so
# v1 files remain loadable when ``tables`` is passed to restore_sim_state.
# v2 predates the fault-injection subsystem (faults.py); v3 adds the
# ``impair`` meta block recording the impairment configuration the state
# evolved under.  Because every impairment decision is a stateless counter
# hash of (impair_seed, iteration, node ids), no extra *array* state is
# needed for bit-exact resumption mid-churn — the ``failed`` mask (already
# stored) plus the recorded knobs fully determine the continuation.  v2
# files backfill an all-off impair block on load.  v4 adds the pull-gossip
# subsystem (pull.py): the ``pull_hops_hist_acc``/``pull_rescued_acc``
# accumulators and a ``pull`` meta block; pre-v4 files were written by the
# push-only engine, so both accumulators backfill as zeros (exact — no
# pull rounds ever ran) and the pull block as mode "push".  v5 adds the
# run-journal layer (resilience.py): a ``resilience`` meta block naming
# the sibling journal file and the committed-unit count at save time, so
# a resumed run can cross-check the state npz against the journal.  No
# new arrays — pre-v5 files backfill an empty block and stay loadable.
# v6 adds the concurrent-traffic subsystem (traffic.py): a ``traffic``
# meta block (knob schedule) on every checkpoint plus a second checkpoint
# *kind* — ``kind="traffic"`` files carry a ``TrafficState`` pytree
# (shared active set, M value slots, queue accumulators) and the
# serialized TrafficStats, written/read by save_traffic_state /
# restore_traffic_state.  Pre-v6 files backfill an all-off traffic block
# and kind "sim".  v7 adds the adaptive push-pull subsystem
# (adaptive.py): an ``adaptive`` meta block (switch threshold/hysteresis
# knobs), the SimState ``adaptive_pull_on`` direction bit, and the
# TrafficState ``v_pull``/``v_rescued``/``v_qdrop`` per-value arrays.
# Pre-v7 files were written by engines whose direction bit was
# identically False and whose rescue/qdrop counters never existed, so all
# four arrays backfill as zeros (exact) and the adaptive block as the
# engine defaults.  v8 adds the node-health observatory (obs/health.py):
# the SimState ``health_prune_recv``/``health_first_round`` planes and the
# TrafficState ``health_prune_recv``/``health_lat_acc``/``health_del_acc``/
# ``health_rescued_acc`` planes, plus a ``health`` meta block (the gate and
# digest top-k).  Pre-v8 files were written by engines with no health gate,
# so every plane backfills as zeros — exact, because the gated-off engine
# carries the planes as identical zeros.  The committed v1-v7 fixtures in
# tests/fixtures/checkpoints pin that forward-compat contract forever
# (tests/test_checkpoint.py).  v9 adds the sparse frontier engine
# (engine/sparse.py): a ``repr`` meta block recording the
# ``representation`` compile key the state evolved under.  No new arrays —
# but sparse-written files carry zero-width ``[O, N, 0]`` rc_shi/rc_slo
# planes (the sparse round derives them from the cluster stake table), so
# restore_sim_state re-derives full planes via ``tables`` when resuming
# dense, and conversely collapses stored full planes to zero-width when
# resuming sparse.  Pre-v9 files backfill representation "dense".
_READABLE_VERSIONS = (1, 2, 3, 4, 5, 6, 7, 8, 9)

# EngineParams fields that define array shapes; a mismatch makes the stored
# state unusable under the new compile geometry.
_SHAPE_FIELDS = ("num_nodes", "active_set_size", "rc_slots", "hist_bins")

# EngineParams fields describing the impairment schedule (v3 meta block);
# the all-off backfill for pre-v3 files derives from the engine's own
# defaults so the two can never drift apart.
_IMPAIR_FIELDS = ("packet_loss_rate", "churn_fail_rate",
                  "churn_recover_rate", "partition_at", "heal_at",
                  "impair_seed")
_IMPAIR_DEFAULTS = {f: EngineParams._field_defaults[f]
                    for f in _IMPAIR_FIELDS}

# EngineParams fields describing the pull-gossip schedule (v4 meta block);
# like the impair block, the stateless counter hashes mean the recorded
# knobs + the stored state fully determine a bit-exact continuation.
_PULL_FIELDS = ("gossip_mode", "pull_fanout", "pull_interval",
                "pull_bloom_fp_rate", "pull_request_cap")
_PULL_DEFAULTS = {f: EngineParams._field_defaults[f] for f in _PULL_FIELDS}

# EngineParams fields describing the concurrent-traffic schedule (v6 meta
# block); same contract as impair/pull — knobs + state fully determine a
# bit-exact continuation (every traffic decision is a stateless counter
# hash of (impair_seed, iteration, ids), traffic.py).
_TRAFFIC_FIELDS = ("traffic_values", "traffic_rate", "node_ingress_cap",
                   "node_egress_cap", "traffic_stall_rounds")
_TRAFFIC_DEFAULTS = {f: EngineParams._field_defaults[f]
                     for f in _TRAFFIC_FIELDS}

# shape-defining fields for kind="traffic" checkpoints (TrafficState
# arrays are [V, N, ...]-shaped; hist_bins never shapes traffic state)
_TRAFFIC_SHAPE_FIELDS = ("num_nodes", "active_set_size", "rc_slots",
                         "traffic_values")

# EngineParams fields describing the adaptive push-pull schedule (v7 meta
# block); same contract as impair/pull/traffic — knobs + state fully
# determine a bit-exact continuation (the direction bit is carried state,
# every rescue decision a stateless counter hash, adaptive.py).
_ADAPTIVE_FIELDS = ("adaptive_switch_threshold", "adaptive_switch_hysteresis")
_ADAPTIVE_DEFAULTS = {f: EngineParams._field_defaults[f]
                      for f in _ADAPTIVE_FIELDS}

# EngineParams fields describing the node-health observatory (v8 meta
# block); the gate is static, so the recorded value documents what the
# planes in the file actually accumulated (False -> all-zero planes).
_HEALTH_FIELDS = ("health",)
_HEALTH_DEFAULTS = {f: EngineParams._field_defaults[f]
                    for f in _HEALTH_FIELDS}

# EngineParams fields naming the engine representation (v9 meta block);
# the key is static (a compile-geometry choice, params.py), and both
# representations produce bit-identical states, so a resume may switch —
# restore_sim_state reshapes the derived rc stake planes to match.
_REPR_FIELDS = ("representation",)
_REPR_DEFAULTS = {f: EngineParams._field_defaults[f]
                  for f in _REPR_FIELDS}


def save_state(path: str, state, params, config=None,
               iteration: int = 0, resilience: dict | None = None,
               kind: str = "sim", extra_meta: dict | None = None) -> None:
    """Write SimState + EngineParams (+ optional Config) to one .npz.

    ``iteration`` records how many gossip rounds produced this state; a
    resumed run continues from there (the engine's per-round RNG keys fold
    in the absolute iteration number, so resumption is bit-exact).
    ``kind`` distinguishes the state pytree stored: "sim" (SimState) or
    "traffic" (TrafficState, v6); ``extra_meta`` merges extra JSON-able
    blocks into the meta (e.g. the serialized TrafficStats)."""
    arrays = {f"state.{name}": np.asarray(getattr(state, name))
              for name in state._fields}
    pdict = dict(params._asdict())
    meta = {
        "format_version": _FORMAT_VERSION,
        "kind": str(kind),
        "params": pdict,
        "impair": {f: pdict.get(f, _IMPAIR_DEFAULTS[f])
                   for f in _IMPAIR_FIELDS},
        "pull": {f: pdict.get(f, _PULL_DEFAULTS[f]) for f in _PULL_FIELDS},
        # v6: the concurrent-traffic schedule (all-off on plain sims)
        "traffic": {f: pdict.get(f, _TRAFFIC_DEFAULTS[f])
                    for f in _TRAFFIC_FIELDS},
        # v7: the adaptive push-pull switch knobs (adaptive.py)
        "adaptive": {f: pdict.get(f, _ADAPTIVE_DEFAULTS[f])
                     for f in _ADAPTIVE_FIELDS},
        # v8: the node-health observatory gate (obs/health.py)
        "health": {f: pdict.get(f, _HEALTH_DEFAULTS[f])
                   for f in _HEALTH_FIELDS},
        # v9: the engine representation compile key (engine/sparse.py)
        "repr": {f: pdict.get(f, _REPR_DEFAULTS[f])
                 for f in _REPR_FIELDS},
        "iteration": int(iteration),
        # v5: journal cross-reference (resilience.py) — {} for plain
        # single-run checkpoints with no journal alongside
        "resilience": dict(resilience or {}),
    }
    if extra_meta:
        meta.update(extra_meta)
    if config is not None:
        cfg = dict(vars(config))
        cfg["test_type"] = str(cfg["test_type"])
        cfg["step_size"] = str(cfg["step_size"])
        meta["config"] = cfg
    # Atomic write: savez to a temp file in the target directory, then
    # os.replace — a killed run can never leave a truncated --resume source.
    if not path.endswith(".npz"):
        path += ".npz"   # np.savez would append it; make the target explicit
    fd, tmp = tempfile.mkstemp(
        suffix=".npz", prefix=".ckpt-", dir=os.path.dirname(path) or ".")
    try:
        with os.fdopen(fd, "wb") as f:
            np.savez_compressed(f, __meta__=np.frombuffer(
                json.dumps(meta).encode(), dtype=np.uint8), **arrays)
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    log.info("checkpoint saved: %s (%s arrays)", path, len(arrays))


def load_state(path: str, params=None, expect_kind=None):
    """Read a checkpoint -> (SimState-field dict, stored-params dict, meta).

    If ``params`` is given, shape-defining fields are validated against the
    stored ones and a mismatch raises ``ValueError``.  ``expect_kind``
    ("sim"/"traffic") rejects a wrong-kind file BEFORE the shape check, so
    the caller's guidance message wins over a confusing shape mismatch
    (e.g. ``traffic_values=64 != current 1`` on a plain-run --resume of a
    traffic checkpoint).
    """
    with np.load(path) as z:
        meta = json.loads(bytes(z["__meta__"]).decode())
        if meta.get("format_version") not in _READABLE_VERSIONS:
            raise ValueError(
                f"unsupported checkpoint version {meta.get('format_version')}")
        arrays = {k[len("state."):]: z[k] for k in z.files
                  if k.startswith("state.")}
    stored = meta["params"]
    # pre-v3 backfill: impairment knobs default to all-off; pre-v4: the
    # push-only mode; pre-v5: no journal alongside; pre-v6: traffic off,
    # kind "sim"
    meta.setdefault("impair", dict(_IMPAIR_DEFAULTS))
    meta.setdefault("pull", dict(_PULL_DEFAULTS))
    meta.setdefault("resilience", {})
    meta.setdefault("traffic", dict(_TRAFFIC_DEFAULTS))
    meta.setdefault("adaptive", dict(_ADAPTIVE_DEFAULTS))
    meta.setdefault("health", dict(_HEALTH_DEFAULTS))
    meta.setdefault("repr", dict(_REPR_DEFAULTS))
    meta.setdefault("kind", "sim")
    if expect_kind is not None and meta["kind"] != expect_kind:
        hint = ("restore_traffic_state / the --traffic-values run path"
                if meta["kind"] == "traffic" else "restore_sim_state")
        raise ValueError(
            f"checkpoint {path} holds a {meta['kind']!r}-kind state, not "
            f"{expect_kind!r}; resume it with the matching run mode "
            f"({hint})")
    if params is not None:
        shape_fields = (_TRAFFIC_SHAPE_FIELDS if meta["kind"] == "traffic"
                        else _SHAPE_FIELDS)
        for f in shape_fields:
            if getattr(params, f) != stored[f]:
                raise ValueError(
                    f"checkpoint {f}={stored[f]} != current {getattr(params, f)}")
        for f in _IMPAIR_FIELDS:
            if getattr(params, f, _IMPAIR_DEFAULTS[f]) != meta["impair"][f]:
                log.warning(
                    "WARNING: resuming with %s=%s but checkpoint was written "
                    "with %s — the continuation's impairment schedule "
                    "diverges from the original run",
                    f, getattr(params, f, _IMPAIR_DEFAULTS[f]),
                    meta["impair"][f])
        for f in _PULL_FIELDS:
            if getattr(params, f, _PULL_DEFAULTS[f]) != meta["pull"][f]:
                log.warning(
                    "WARNING: resuming with %s=%s but checkpoint was written "
                    "with %s — the continuation's pull schedule diverges "
                    "from the original run",
                    f, getattr(params, f, _PULL_DEFAULTS[f]),
                    meta["pull"][f])
        for f in _TRAFFIC_FIELDS:
            if getattr(params, f, _TRAFFIC_DEFAULTS[f]) != meta["traffic"][f]:
                log.warning(
                    "WARNING: resuming with %s=%s but checkpoint was written "
                    "with %s — the continuation's traffic schedule diverges "
                    "from the original run",
                    f, getattr(params, f, _TRAFFIC_DEFAULTS[f]),
                    meta["traffic"][f])
        for f in _ADAPTIVE_FIELDS:
            if (getattr(params, f, _ADAPTIVE_DEFAULTS[f])
                    != meta["adaptive"][f]):
                log.warning(
                    "WARNING: resuming with %s=%s but checkpoint was written "
                    "with %s — the continuation's adaptive switch schedule "
                    "diverges from the original run",
                    f, getattr(params, f, _ADAPTIVE_DEFAULTS[f]),
                    meta["adaptive"][f])
        for f in _HEALTH_FIELDS:
            if (getattr(params, f, _HEALTH_DEFAULTS[f])
                    != meta["health"][f]):
                log.warning(
                    "WARNING: resuming with %s=%s but checkpoint was written "
                    "with %s — the health planes cover only the rounds run "
                    "under an enabled gate",
                    f, getattr(params, f, _HEALTH_DEFAULTS[f]),
                    meta["health"][f])
        for f in _REPR_FIELDS:
            if (getattr(params, f, _REPR_DEFAULTS[f])
                    != meta["repr"][f]):
                log.info(
                    "resuming with %s=%s but checkpoint was written with %s "
                    "— both representations are bit-identical, so the "
                    "continuation is exact; the rc stake planes are "
                    "re-derived to match the new shape",
                    f, getattr(params, f, _REPR_DEFAULTS[f]),
                    meta["repr"][f])
    return arrays, stored, meta


def restore_sim_state(path: str, params=None, tables=None):
    """Read a checkpoint and rebuild a device-resident ``SimState``.

    ``tables`` (a ``ClusterTables``) lets v1 checkpoints backfill the
    derived fields added later (tfail, rc_shi, rc_slo).
    """
    import jax.numpy as jnp

    from .engine import SimState

    arrays, stored, meta = load_state(path, params, expect_kind="sim")
    missing = set(SimState._fields) - set(arrays)
    # pre-v4 files were written by the push-only engine: the pull
    # accumulators are exactly zero (no pull round ever ran)
    pull_fields = {"pull_hops_hist_acc", "pull_rescued_acc"}
    if missing & pull_fields:
        o, n = arrays["failed"].shape
        h = int(stored.get("hist_bins",
                           EngineParams._field_defaults["hist_bins"]))
        if "pull_hops_hist_acc" in missing:
            arrays["pull_hops_hist_acc"] = np.zeros((o, h), np.int32)
        if "pull_rescued_acc" in missing:
            arrays["pull_rescued_acc"] = np.zeros((o, n), np.int32)
        missing = set(SimState._fields) - set(arrays)
    if "adaptive_pull_on" in missing:
        # pre-v7 files were written by engines whose direction bit was
        # identically False (no adaptive mode existed) — zeros are exact
        arrays["adaptive_pull_on"] = np.zeros(
            (arrays["failed"].shape[0],), bool)
        missing = set(SimState._fields) - set(arrays)
    health_fields = {"health_prune_recv", "health_first_round"}
    if missing & health_fields:
        # pre-v8 files predate the node-health observatory; the gated-off
        # engine carries these planes as identical zeros, so zeros are exact
        o, n = arrays["failed"].shape
        for f in missing & health_fields:
            arrays[f] = np.zeros((o, n), np.int32)
        missing = set(SimState._fields) - set(arrays)
    # v9 representation switch: the sparse round carries the rc stake
    # planes as zero-width [O, N, 0] arrays (derived from the cluster
    # stake table each round), so the planes stored in the file may not
    # match the shape the CURRENT representation expects.  Resuming
    # sparse: collapse whatever is stored to zero-width.  Resuming dense
    # from a sparse-written file: drop the zero-width planes and let the
    # derivation below rebuild them from ``tables``.
    target_repr = (getattr(params, "representation", None)
                   if params is not None else None)
    if target_repr is None:
        target_repr = stored.get("representation", "dense")
    if target_repr == "sparse":
        o, _ = arrays["failed"].shape
        n = stored["num_nodes"]
        arrays["rc_shi"] = np.zeros((o, n, 0), np.int32)
        arrays["rc_slo"] = np.zeros((o, n, 0), np.int32)
        missing = set(SimState._fields) - set(arrays)
    else:
        for f in ("rc_shi", "rc_slo"):
            if f in arrays and arrays[f].ndim == 3 \
                    and arrays[f].shape[-1] == 0:
                del arrays[f]
        missing = set(SimState._fields) - set(arrays)
    derivable = {"tfail", "rc_shi", "rc_slo"}
    if missing and missing <= derivable and tables is not None:
        n = stored["num_nodes"]
        active = arrays["active"]                      # [O, N, S], N = empty
        failed = arrays["failed"]                      # [O, N] bool
        stakes = np.asarray(tables.stakes)             # [N+1], pad 0 at N
        if "tfail" in missing:
            pad_failed = np.concatenate(
                [failed, np.zeros((failed.shape[0], 1), bool)], axis=1)
            arrays["tfail"] = np.take_along_axis(
                pad_failed[:, :, None], np.minimum(active, n), axis=1)
        if "rc_shi" in missing or "rc_slo" in missing:
            rc_stake = stakes[np.minimum(arrays["rc_src"], n)]
            arrays["rc_shi"] = (rc_stake >> 31).astype(np.int32)
            arrays["rc_slo"] = (rc_stake & 0x7FFFFFFF).astype(np.int32)
        missing = set(SimState._fields) - set(arrays)
    if missing:
        raise ValueError(f"checkpoint missing fields: {sorted(missing)}")
    return SimState(**{f: jnp.asarray(arrays[f]) for f in SimState._fields}), \
        stored, meta


def save_traffic_state(path: str, state, params, config=None,
                       iteration: int = 0,
                       traffic_stats: dict | None = None) -> None:
    """Write a kind="traffic" v6 checkpoint: the TrafficState pytree
    (shared active set, M value slots, queue accumulators) plus the
    serialized TrafficStats (stats/traffic.py state_dict) so a resumed
    run re-reports the pre-interrupt rounds and retirement records
    exactly."""
    save_state(path, state, params, config=config, iteration=iteration,
               kind="traffic",
               extra_meta={"traffic_stats": traffic_stats or {}})


def restore_traffic_state(path: str, params=None):
    """Read a kind="traffic" checkpoint -> (TrafficState, stored-params,
    meta).  ``meta["traffic_stats"]`` carries the TrafficStats snapshot
    for stats-exact resume."""
    import jax.numpy as jnp

    from .engine.traffic import TrafficState

    arrays, stored, meta = load_state(path, params, expect_kind="traffic")
    missing = set(TrafficState._fields) - set(arrays)
    adaptive_fields = {"v_pull", "v_rescued", "v_qdrop"}
    if missing & adaptive_fields:
        # pre-v7 traffic checkpoints: the adaptive direction bits and
        # rescue/qdrop counters did not exist — zeros are exact (no pull
        # phase ever ran, no per-value drop attribution was recorded)
        v = arrays["v_live"].shape[0]
        if "v_pull" in missing:
            arrays["v_pull"] = np.zeros((v,), bool)
        if "v_rescued" in missing:
            arrays["v_rescued"] = np.zeros((v,), np.int32)
        if "v_qdrop" in missing:
            arrays["v_qdrop"] = np.zeros((v,), np.int32)
        missing = set(TrafficState._fields) - set(arrays)
    health_fields = {"health_prune_recv", "health_lat_acc",
                     "health_del_acc", "health_rescued_acc"}
    if missing & health_fields:
        # pre-v8 traffic checkpoints predate the node-health observatory;
        # the gated-off engine carries the planes as identical zeros
        n = arrays["failed"].shape[0]
        for f in missing & health_fields:
            arrays[f] = np.zeros((n,), np.int32)
        missing = set(TrafficState._fields) - set(arrays)
    if missing:
        raise ValueError(f"traffic checkpoint missing fields: "
                         f"{sorted(missing)}")
    state = TrafficState(**{f: jnp.asarray(arrays[f])
                            for f in TrafficState._fields})
    meta.setdefault("traffic_stats", {})
    return state, stored, meta
