"""Simulation-state checkpoint / resume.

The reference has no checkpointing (SURVEY.md §5) — its closest artifact is
the account-file snapshot (write_accounts_main.rs:118-125).  Long sweeps on
TPU make resumability a cheap win: ``SimState`` is a flat pytree of arrays,
so one ``.npz`` captures the whole simulation (active sets, prune bits,
received caches, accumulators, RNG keys) plus the static params that shaped
it.  Loading validates shape-defining params so a resumed run can't silently
continue under a different compiled geometry.
"""

from __future__ import annotations

import json
import logging
import os
import tempfile

import numpy as np

from .engine.params import EngineParams

log = logging.getLogger(__name__)

_FORMAT_VERSION = 5
# v1 checkpoints predate the tfail/rc_shi/rc_slo SimState fields; all three
# are derivable from active/failed/rc_src plus the cluster stake table, so
# v1 files remain loadable when ``tables`` is passed to restore_sim_state.
# v2 predates the fault-injection subsystem (faults.py); v3 adds the
# ``impair`` meta block recording the impairment configuration the state
# evolved under.  Because every impairment decision is a stateless counter
# hash of (impair_seed, iteration, node ids), no extra *array* state is
# needed for bit-exact resumption mid-churn — the ``failed`` mask (already
# stored) plus the recorded knobs fully determine the continuation.  v2
# files backfill an all-off impair block on load.  v4 adds the pull-gossip
# subsystem (pull.py): the ``pull_hops_hist_acc``/``pull_rescued_acc``
# accumulators and a ``pull`` meta block; pre-v4 files were written by the
# push-only engine, so both accumulators backfill as zeros (exact — no
# pull rounds ever ran) and the pull block as mode "push".  v5 adds the
# run-journal layer (resilience.py): a ``resilience`` meta block naming
# the sibling journal file and the committed-unit count at save time, so
# a resumed run can cross-check the state npz against the journal.  No
# new arrays — pre-v5 files backfill an empty block and stay loadable;
# the committed v1-v4 fixtures in tests/fixtures/checkpoints pin that
# forward-compat contract forever (tests/test_checkpoint.py).
_READABLE_VERSIONS = (1, 2, 3, 4, 5)

# EngineParams fields that define array shapes; a mismatch makes the stored
# state unusable under the new compile geometry.
_SHAPE_FIELDS = ("num_nodes", "active_set_size", "rc_slots", "hist_bins")

# EngineParams fields describing the impairment schedule (v3 meta block);
# the all-off backfill for pre-v3 files derives from the engine's own
# defaults so the two can never drift apart.
_IMPAIR_FIELDS = ("packet_loss_rate", "churn_fail_rate",
                  "churn_recover_rate", "partition_at", "heal_at",
                  "impair_seed")
_IMPAIR_DEFAULTS = {f: EngineParams._field_defaults[f]
                    for f in _IMPAIR_FIELDS}

# EngineParams fields describing the pull-gossip schedule (v4 meta block);
# like the impair block, the stateless counter hashes mean the recorded
# knobs + the stored state fully determine a bit-exact continuation.
_PULL_FIELDS = ("gossip_mode", "pull_fanout", "pull_interval",
                "pull_bloom_fp_rate", "pull_request_cap")
_PULL_DEFAULTS = {f: EngineParams._field_defaults[f] for f in _PULL_FIELDS}


def save_state(path: str, state, params, config=None,
               iteration: int = 0, resilience: dict | None = None) -> None:
    """Write SimState + EngineParams (+ optional Config) to one .npz.

    ``iteration`` records how many gossip rounds produced this state; a
    resumed run continues from there (the engine's per-round RNG keys fold
    in the absolute iteration number, so resumption is bit-exact)."""
    arrays = {f"state.{name}": np.asarray(getattr(state, name))
              for name in state._fields}
    pdict = dict(params._asdict())
    meta = {
        "format_version": _FORMAT_VERSION,
        "params": pdict,
        "impair": {f: pdict.get(f, _IMPAIR_DEFAULTS[f])
                   for f in _IMPAIR_FIELDS},
        "pull": {f: pdict.get(f, _PULL_DEFAULTS[f]) for f in _PULL_FIELDS},
        "iteration": int(iteration),
        # v5: journal cross-reference (resilience.py) — {} for plain
        # single-run checkpoints with no journal alongside
        "resilience": dict(resilience or {}),
    }
    if config is not None:
        cfg = dict(vars(config))
        cfg["test_type"] = str(cfg["test_type"])
        cfg["step_size"] = str(cfg["step_size"])
        meta["config"] = cfg
    # Atomic write: savez to a temp file in the target directory, then
    # os.replace — a killed run can never leave a truncated --resume source.
    if not path.endswith(".npz"):
        path += ".npz"   # np.savez would append it; make the target explicit
    fd, tmp = tempfile.mkstemp(
        suffix=".npz", prefix=".ckpt-", dir=os.path.dirname(path) or ".")
    try:
        with os.fdopen(fd, "wb") as f:
            np.savez_compressed(f, __meta__=np.frombuffer(
                json.dumps(meta).encode(), dtype=np.uint8), **arrays)
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    log.info("checkpoint saved: %s (%s arrays)", path, len(arrays))


def load_state(path: str, params=None):
    """Read a checkpoint -> (SimState-field dict, stored-params dict, meta).

    If ``params`` is given, shape-defining fields are validated against the
    stored ones and a mismatch raises ``ValueError``.
    """
    with np.load(path) as z:
        meta = json.loads(bytes(z["__meta__"]).decode())
        if meta.get("format_version") not in _READABLE_VERSIONS:
            raise ValueError(
                f"unsupported checkpoint version {meta.get('format_version')}")
        arrays = {k[len("state."):]: z[k] for k in z.files
                  if k.startswith("state.")}
    stored = meta["params"]
    # pre-v3 backfill: impairment knobs default to all-off; pre-v4: the
    # push-only mode; pre-v5: no journal alongside
    meta.setdefault("impair", dict(_IMPAIR_DEFAULTS))
    meta.setdefault("pull", dict(_PULL_DEFAULTS))
    meta.setdefault("resilience", {})
    if params is not None:
        for f in _SHAPE_FIELDS:
            if getattr(params, f) != stored[f]:
                raise ValueError(
                    f"checkpoint {f}={stored[f]} != current {getattr(params, f)}")
        for f in _IMPAIR_FIELDS:
            if getattr(params, f, _IMPAIR_DEFAULTS[f]) != meta["impair"][f]:
                log.warning(
                    "WARNING: resuming with %s=%s but checkpoint was written "
                    "with %s — the continuation's impairment schedule "
                    "diverges from the original run",
                    f, getattr(params, f, _IMPAIR_DEFAULTS[f]),
                    meta["impair"][f])
        for f in _PULL_FIELDS:
            if getattr(params, f, _PULL_DEFAULTS[f]) != meta["pull"][f]:
                log.warning(
                    "WARNING: resuming with %s=%s but checkpoint was written "
                    "with %s — the continuation's pull schedule diverges "
                    "from the original run",
                    f, getattr(params, f, _PULL_DEFAULTS[f]),
                    meta["pull"][f])
    return arrays, stored, meta


def restore_sim_state(path: str, params=None, tables=None):
    """Read a checkpoint and rebuild a device-resident ``SimState``.

    ``tables`` (a ``ClusterTables``) lets v1 checkpoints backfill the
    derived fields added later (tfail, rc_shi, rc_slo).
    """
    import jax.numpy as jnp

    from .engine import SimState

    arrays, stored, meta = load_state(path, params)
    missing = set(SimState._fields) - set(arrays)
    # pre-v4 files were written by the push-only engine: the pull
    # accumulators are exactly zero (no pull round ever ran)
    pull_fields = {"pull_hops_hist_acc", "pull_rescued_acc"}
    if missing & pull_fields:
        o, n = arrays["failed"].shape
        h = int(stored.get("hist_bins",
                           EngineParams._field_defaults["hist_bins"]))
        if "pull_hops_hist_acc" in missing:
            arrays["pull_hops_hist_acc"] = np.zeros((o, h), np.int32)
        if "pull_rescued_acc" in missing:
            arrays["pull_rescued_acc"] = np.zeros((o, n), np.int32)
        missing = set(SimState._fields) - set(arrays)
    derivable = {"tfail", "rc_shi", "rc_slo"}
    if missing and missing <= derivable and tables is not None:
        n = stored["num_nodes"]
        active = arrays["active"]                      # [O, N, S], N = empty
        failed = arrays["failed"]                      # [O, N] bool
        stakes = np.asarray(tables.stakes)             # [N+1], pad 0 at N
        if "tfail" in missing:
            pad_failed = np.concatenate(
                [failed, np.zeros((failed.shape[0], 1), bool)], axis=1)
            arrays["tfail"] = np.take_along_axis(
                pad_failed[:, :, None], np.minimum(active, n), axis=1)
        if "rc_shi" in missing or "rc_slo" in missing:
            rc_stake = stakes[np.minimum(arrays["rc_src"], n)]
            arrays["rc_shi"] = (rc_stake >> 31).astype(np.int32)
            arrays["rc_slo"] = (rc_stake & 0x7FFFFFFF).astype(np.int32)
        missing = set(SimState._fields) - set(arrays)
    if missing:
        raise ValueError(f"checkpoint missing fields: {sorted(missing)}")
    return SimState(**{f: jnp.asarray(arrays[f]) for f in SimState._fields}), \
        stored, meta
