"""Stake-weighted sampling without replacement (Fenwick-tree successive sampling).

Equivalent of ``solana_gossip::weighted_shuffle::WeightedShuffle`` as consumed
by the reference active-set rotation (push_active_set.rs:164).  Semantics:

  * ``shuffle(rng)`` lazily yields indices; each yield draws exactly one
    ``gen_range_u64(0, remaining_sum)`` from the rng and removes the selected
    weight (successive / Plackett-Luce sampling).
  * Zero weights are never selected (rotation weights are always >= 1,
    push_active_set.rs:109).

RNG consumption matches the reference exactly (one uniform draw per yielded
index), so a ChaCha-seeded run reproduces the reference's draws bit-for-bit.
"""

from __future__ import annotations


class WeightedShuffle:
    def __init__(self, weights):
        n = len(weights)
        self.size = n + 1
        self.tree = [0] * self.size  # 1-based Fenwick tree
        self.sum = 0
        for k, w in enumerate(weights, start=1):
            w = int(w)
            if w < 0:
                continue
            self.sum += w
            while k < self.size:
                self.tree[k] += w
                k += k & -k
        # Highest power of two <= size, for the Fenwick descend.
        self.top = 1 << (self.size.bit_length() - 1)

    def _cumsum(self, k: int) -> int:
        out = 0
        while k > 0:
            out += self.tree[k]
            k -= k & -k
        return out

    def _search(self, val: int):
        """Smallest 1-based k with cumsum(k) > val; returns (k, weight_k)."""
        pos = 0
        rem = val
        step = self.top
        while step > 0:
            nxt = pos + step
            if nxt < self.size and self.tree[nxt] <= rem:
                rem -= self.tree[nxt]
                pos = nxt
            step >>= 1
        k = pos + 1
        weight = self._cumsum(k) - self._cumsum(k - 1)
        return k, weight

    def _remove(self, k: int, weight: int):
        self.sum -= weight
        while k < self.size:
            self.tree[k] -= weight
            k += k & -k

    def shuffle(self, rng):
        """Lazily yield 0-based indices in successive-sampling order."""
        while self.sum > 0:
            val = rng.gen_range_u64(0, self.sum)
            k, w = self._search(val)
            self._remove(k, w)
            yield k - 1
