"""Per-origin inbound-peer scoring and prune decisions.

Oracle equivalent of the reference's ``ReceivedCache`` /
``ReceivedCacheEntry`` (received_cache.rs:11-132):

  * ``record(origin, node, num_dups)``: first delivery (num_dups == 0) bumps
    the upsert count; timely deliveries (num_dups < 2) bump the peer's score
    (inserting it unconditionally); late deliveries only reserve a slot while
    under the 50-entry cap (received_cache.rs:83-98).
  * ``prune(...)``: gated on >= 20 upserts; on firing, the entry's state is
    consumed (score reset — the reference's ``mem::take``,
    received_cache.rs:55) and peers are sorted by (score, stake) descending;
    the first ``min_ingress_nodes`` survive, plus peers until the running
    (exclusive) stake prefix-sum reaches
    ``stake_threshold * min(stake(self), stake(origin))``; the rest are pruned
    (received_cache.rs:100-131).

Divergence (documented): on exact (score, stake) ties the reference's unstable
sort is nondeterministic; we tie-break by pubkey bytes ascending.
"""

from __future__ import annotations

from collections import OrderedDict

from ..constants import (MIN_NUM_UPSERTS, NUM_DUPS_THRESHOLD,
                         RECEIVED_CACHE_CAPACITY)


class ReceivedCacheEntry:
    __slots__ = ("nodes", "num_upserts")

    def __init__(self):
        self.nodes = {}  # Pubkey -> score
        self.num_upserts = 0

    def record(self, node, num_dups):
        if num_dups == 0:
            self.num_upserts += 1
        if num_dups < NUM_DUPS_THRESHOLD:
            self.nodes[node] = self.nodes.get(node, 0) + 1
        elif len(self.nodes) < RECEIVED_CACHE_CAPACITY:
            self.nodes.setdefault(node, 0)

    def prune(self, pubkey, origin, stake_threshold, min_ingress_nodes, stakes):
        """Yield pruned peers (received_cache.rs:100-131). Consumes self's state."""
        min_stake = min(stakes.get(pubkey, 0), stakes.get(origin, 0))
        # f64 multiply then truncation to u64, as in the reference.
        min_ingress_stake = int(float(min_stake) * stake_threshold)
        ranked = sorted(
            ((node, score, stakes.get(node, 0)) for node, score in self.nodes.items()),
            key=lambda t: (-t[1], -t[2], t[0].raw),
        )
        pruned = []
        cum = 0
        for idx, (node, _score, stake) in enumerate(ranked):
            old = cum
            cum += stake
            if idx < min_ingress_nodes:
                continue
            if old < min_ingress_stake:
                continue
            pruned.append(node)
        return pruned


class ReceivedCache:
    """LRU of per-origin entries (received_cache.rs:11-63)."""

    def __init__(self, capacity):
        self.capacity = capacity
        self.cache = OrderedDict()  # origin -> ReceivedCacheEntry, LRU order

    def record(self, origin, node, num_dups):
        entry = self.cache.get(origin)
        if entry is not None:
            self.cache.move_to_end(origin)  # LruCache::get_mut promotes
        else:
            entry = ReceivedCacheEntry()
            self.cache[origin] = entry
            while len(self.cache) > self.capacity:
                self.cache.popitem(last=False)
        entry.record(node, num_dups)

    def prune(self, pubkey, origin, stake_threshold, min_ingress_nodes, stakes):
        """Upsert-gated prune; resets the entry's scores when the gate passes
        (received_cache.rs:38-63). Uses peek (no LRU promotion)."""
        entry = self.cache.get(origin)
        if entry is None or entry.num_upserts < MIN_NUM_UPSERTS:
            return []
        taken, fresh = entry, ReceivedCacheEntry()
        self.cache[origin] = fresh  # mem::take: reset in place, keep LRU slot
        return [n for n in taken.prune(pubkey, origin, stake_threshold,
                                       min_ingress_nodes, stakes)
                if n != origin]
