"""CPU oracle simulation engine: the five-verb gossip round.

Faithful Python equivalent of the reference's ``Cluster`` / ``Node``
(gossip.rs:135-856).  Per iteration:

  1. ``run_gossip``      — BFS from the origin through each node's active set,
                           truncated to push_fanout (gossip.rs:494-615).
  2. ``consume_messages`` — each destination ranks inbound peers by
                           (hops, pubkey-string) and records them
                           (gossip.rs:618-653).
  3. ``send_prunes``      — upsert-gated prune decisions (gossip.rs:657-697).
  4. ``prune_connections``— prunees add the pruner to their filters
                           (gossip.rs:701-737).
  5. ``chance_to_rotate`` — Bernoulli(p) incremental active-set rotation
                           (gossip.rs:739-754).

Divergence from the reference (documented, deliberate): all randomness flows
through one explicit seeded rng — the reference's entropy-seeded per-thread
RNGs (gossip.rs:747-753, gossip_main.rs:269) make production runs
unreproducible and are not carried forward.

Network impairments (packet loss / churn / partition) are driven by an
optional ``faults.FaultInjector`` whose stateless counter hashes match the
TPU engine bit-for-bit; ``run_gossip`` takes it as an optional argument and
the per-round delivered/dropped/suppressed counters live on the injector.
"""

from __future__ import annotations

import logging
from collections import deque

import numpy as np

from ..constants import CRDS_UNIQUE_PUBKEY_CAPACITY, UNREACHED
from ..obs.trace import (TRACE_CANDIDATE, TRACE_DROPPED, TRACE_FAILED_TARGET,
                         TRACE_SUPPRESSED)
from .active_set import PushActiveSet
from .received_cache import ReceivedCache
from .rmr import RelativeMessageRedundancy

log = logging.getLogger(__name__)


class Node:
    """Per-validator state (gossip.rs:774-856)."""

    def __init__(self, pubkey, stake, filter_factory=None):
        self.pubkey = pubkey
        self.stake = stake
        # filter_factory: None = exact prune sets; see PushActiveSet for the
        # bloom-fidelity mode (tools/bloom_divergence.py)
        self.active_set = PushActiveSet(filter_factory)
        self.received_cache = ReceivedCache(2 * CRDS_UNIQUE_PUBKEY_CAPACITY)
        self.failed = False

    def rotate_active_set(self, rng, active_set_size, stakes):
        """Re-sample the active set from all other nodes (gossip.rs:815-842).

        Candidates are always sorted (by pubkey bytes) for determinism — the
        reference sorts only under ``test`` (gossip.rs:833-835); sorted order
        is the canonical order here.
        """
        candidates = sorted(pk for pk in stakes if pk != self.pubkey)
        self.active_set.rotate(rng, active_set_size, candidates, stakes)

    def initialize_gossip(self, rng, stakes, active_set_size):
        self.rotate_active_set(rng, active_set_size, stakes)

    def fail_node(self):
        self.failed = True


class Cluster:
    """Per-iteration simulation state + the five protocol verbs
    (gossip.rs:135-772)."""

    def __init__(self, push_fanout):
        self.gossip_push_fanout = push_fanout
        self.visited = set()
        self.distances = {}
        self.orders = {}       # dest -> {src -> hops}
        self.mst = {}          # src -> set(dest) first-delivery edges
        self.pushes = {}       # src -> set(dest) all push edges
        self.prunes = {}       # pruner -> {prunee -> [origins]}
        self.rmr = RelativeMessageRedundancy()
        self.failed_nodes = set()
        self.total_prunes = 0
        self.egress_message_count = {}
        self.ingress_message_count = {}
        self.prune_messages_sent = {}
        # flight recorder (obs/trace.py): when armed (a list, set by
        # OracleTraceCollector.begin_round), run_gossip appends one
        # (src, dst, TRACE_* code) event per attempted fanout slot
        self.edge_log = None
        # pull phase (pull.py): run_pull stores this round's PullRound here;
        # coverage/stranded/hops observers fold the rescues in
        self.pull = None
        self.pull_index = None   # NodeIndex used to translate pull results

    def _clear(self, stakes):
        self.visited.clear()
        self.distances = {pk: UNREACHED for pk in stakes}
        self.orders.clear()
        self.mst.clear()
        self.pushes.clear()
        self.prunes.clear()
        self.rmr.reset()
        self.total_prunes = 0
        self.egress_message_count.clear()
        self.ingress_message_count.clear()
        self.prune_messages_sent.clear()
        self.pull = None

    # -- verb 1: push/diffuse ------------------------------------------------

    def run_gossip(self, origin_pubkey, stakes, node_map, impair=None):
        """BFS through active sets truncated to fanout (gossip.rs:494-615).

        ``impair``: optional ``faults.FaultInjector``.  Partition-suppressed
        and loss-dropped pushes consume their fanout slot exactly like pushes
        to failed targets (gossip.rs:538-541) and contribute nothing to
        delivery, ingress, consume ranking, or RMR's m; the injector counts
        delivered/dropped/suppressed per round."""
        self._clear(stakes)
        self.distances[origin_pubkey] = 0
        self.visited.add(origin_pubkey)
        self.rmr.increment_n()
        queue = deque([origin_pubkey])
        fanout = self.gossip_push_fanout
        while queue:
            current = queue.popleft()
            dist = self.distances[current]
            node = node_map[current]
            self.pushes[current] = set()
            self.egress_message_count[current] = 0
            peers = node.active_set.get_nodes(current, origin_pubkey, stakes)
            for _, neighbor in zip(range(fanout), peers):
                if node_map[neighbor].failed:
                    if self.edge_log is not None:
                        self.edge_log.append(
                            (current, neighbor, TRACE_FAILED_TARGET))
                    continue  # failed targets consume a fanout slot, nothing else
                if impair is not None:
                    outcome = impair.classify_edge(current, neighbor)
                    if outcome != "delivered":
                        if self.edge_log is not None:
                            self.edge_log.append(
                                (current, neighbor,
                                 TRACE_SUPPRESSED if outcome == "suppressed"
                                 else TRACE_DROPPED))
                        continue  # suppressed/dropped: slot consumed only
                if self.edge_log is not None:
                    self.edge_log.append((current, neighbor, TRACE_CANDIDATE))
                self.pushes[current].add(neighbor)
                self.egress_message_count[current] += 1
                self.ingress_message_count[neighbor] = (
                    self.ingress_message_count.get(neighbor, 0) + 1)
                # The reference checks here that the neighbor hasn't pruned us
                # (gossip.rs:564-568), but prunes are cleared at round start so
                # the check is vacuous; the active-set filters are the real
                # enforcement and are exercised by the golden tests.
                self.rmr.increment_m()
                if neighbor not in self.visited:
                    self.visited.add(neighbor)
                    self.distances[neighbor] = dist + 1
                    queue.append(neighbor)
                    self.mst.setdefault(current, set()).add(neighbor)
                    self.rmr.increment_n()
                self.orders.setdefault(neighbor, {})[current] = dist + 1

    # -- pull phase (anti-entropy; pull.py) ----------------------------------

    def run_pull(self, pull_oracle, it, index, node_map):
        """One pull request/response exchange against this round's push
        outcome (pull.PullOracle — the identical spec the engine's
        ``round/pull`` block implements).  Pull deliveries join coverage /
        hops / stranded accounting tagged pull-sourced; request/response
        messages flow into the ingress/egress counters.  Must run after
        ``run_gossip`` (it consumes this round's distances)."""
        from ..constants import UNREACHED

        n = len(index)
        hops = np.full(n, -1, np.int64)
        for pk, d in self.distances.items():
            if d != UNREACHED:
                hops[index.index_of(pk)] = d
        failed = np.array([node_map[pk].failed for pk in index.pubkeys],
                          dtype=bool)
        self.pull = pull_oracle.run_round(it, hops, failed)
        self.pull_index = index
        for i in np.nonzero(self.pull.egress)[0]:
            pk = index.pubkeys[int(i)]
            self.egress_message_count[pk] = (
                self.egress_message_count.get(pk, 0)
                + int(self.pull.egress[i]))
        for i in np.nonzero(self.pull.ingress)[0]:
            pk = index.pubkeys[int(i)]
            self.ingress_message_count[pk] = (
                self.ingress_message_count.get(pk, 0)
                + int(self.pull.ingress[i]))
        return self.pull

    def pull_rescued_pubkeys(self):
        """{pubkey: pull hop} for this round's pull-rescued nodes."""
        if self.pull is None or not self.pull.rescued:
            return {}
        pks = self.pull_index.pubkeys
        return {pks[i]: hop for i, hop in self.pull.rescued.items()}

    def hops_with_pull(self):
        """``distances`` with pull-rescued nodes folded in at their pull
        hop — the combined per-node hop view the stats layer records."""
        rescued = self.pull_rescued_pubkeys()
        if not rescued:
            return self.distances
        merged = dict(self.distances)
        merged.update(rescued)
        return merged

    # -- verb 2: consume -----------------------------------------------------

    def consume_messages(self, origin, nodes):
        """Rank inbound peers by (hops, pubkey-string) and record
        (gossip.rs:618-653)."""
        for node in nodes:
            if node.pubkey == origin:
                continue
            sources = self.orders.get(node.pubkey)
            if not sources:
                continue
            ranked = sorted(sources.items(),
                            key=lambda kv: (kv[1], kv[0].to_string()))
            for num_dups, (src, _hops) in enumerate(ranked):
                node.received_cache.record(origin, src, num_dups)

    # -- verb 3: prune decisions ---------------------------------------------

    def send_prunes(self, origin, nodes, prune_stake_threshold,
                    min_ingress_nodes, stakes):
        """Each node decides whom to prune for this origin (gossip.rs:657-697).
        Prune messages count toward RMR's m (gossip.rs:684-687)."""
        for node in nodes:
            pruned = node.received_cache.prune(
                node.pubkey, origin, prune_stake_threshold,
                min_ingress_nodes, stakes)
            prunes = {peer: [origin] for peer in pruned}
            for origins in prunes.values():
                self.rmr.increment_m_by(len(origins))
            self.prunes[node.pubkey] = prunes

    # -- verb 4: prune application -------------------------------------------

    def prune_connections(self, node_map, stakes):
        """Prunees add (pruner, origin) to their active-set filters
        (gossip.rs:701-737)."""
        for pruner, prunes in self.prunes.items():
            if prunes:
                self.total_prunes += len(prunes)
            count = self.prune_messages_sent.setdefault(pruner, 0)
            for prunee, origins in prunes.items():
                node = node_map.get(prunee)
                if node is not None:
                    node.active_set.prune(prunee, pruner, origins, stakes)
                count += len(origins)
            self.prune_messages_sent[pruner] = count

    # -- verb 5: rotation ----------------------------------------------------

    def chance_to_rotate(self, rng, nodes, active_set_size, stakes,
                         probability_of_rotation):
        """Bernoulli(p) incremental rotation per node (gossip.rs:739-754).
        Returns the pubkeys that rotated (flight-recorder rotation epochs)."""
        rotated = []
        for node in nodes:
            if rng.gen_f64() < probability_of_rotation:
                node.rotate_active_set(rng, active_set_size, stakes)
                rotated.append(node.pubkey)
        return rotated

    # -- fault injection -----------------------------------------------------

    def fail_nodes(self, fraction_to_fail, nodes, rng):
        """Fail a random fraction of nodes permanently (gossip.rs:756-771)."""
        total = int(fraction_to_fail * len(nodes))
        order = list(range(len(nodes)))
        # Fisher-Yates driven by the explicit rng (reference shuffles with
        # thread_rng, gossip.rs:763-764).
        for i in range(len(order) - 1, 0, -1):
            j = rng.gen_range_u64(0, i + 1)
            order[i], order[j] = order[j], order[i]
        for i in order[:total]:
            nodes[i].fail_node()
            self.failed_nodes.add(nodes[i].pubkey)

    def apply_churn(self, impair, it, node_map):
        """Per-iteration fail/recover churn (faults.FaultInjector.churn_step);
        keeps ``failed_nodes`` in sync so stranded stats exclude currently
        failed nodes.  Returns (newly_failed, newly_recovered) pubkeys."""
        return impair.churn_step(it, node_map, self.failed_nodes)

    # -- observers -----------------------------------------------------------

    def coverage(self, stakes):
        """(fraction visited, #unvisited) (gossip.rs:321-327); pull-rescued
        nodes (pull.py) count as visited."""
        rescued = len(self.pull.rescued) if self.pull is not None else 0
        return ((len(self.visited) + rescued) / len(stakes),
                len(stakes) - len(self.visited) - rescued)

    def stranded_nodes(self):
        """Unreached and not failed (gossip.rs:329-345); nodes rescued by a
        pull response this round are not stranded."""
        rescued = self.pull_rescued_pubkeys()
        return [pk for pk, d in self.distances.items()
                if d == UNREACHED and pk not in self.failed_nodes
                and pk not in rescued]

    def relative_message_redundancy(self):
        """Memoized RMR accessor (gossip.rs:435-443)."""
        if self.rmr.rmr == 0.0:
            return self.rmr.calculate()
        return self.rmr.rmr, self.rmr.m, self.rmr.n

    def clear_message_counts(self):
        for d in (self.egress_message_count, self.ingress_message_count,
                  self.prune_messages_sent):
            for k in d:
                d[k] = 0

    # -- debug dumps (gossip.rs:365-431; the per-edge debug workflow of
    # README.md:274-354) ------------------------------------------------------

    def print_hops(self):
        log.debug("DISTANCES FROM ORIGIN")
        for pubkey, hops in self.distances.items():
            log.debug("dest node, hops: (%s, %s)", pubkey, hops)

    def print_node_orders(self):
        """A => {B => 4}: A received a message in 4 hops through B
        (gossip.rs:374-390)."""
        log.debug("NODE ORDERS")
        for recv_pubkey, neighbors in self.orders.items():
            log.debug("----- dest node, num_inbound: %s, %s -----",
                      recv_pubkey, len(neighbors))
            for peer, order in neighbors.items():
                log.debug("neighbor pubkey, order: %s, %s", peer, order)

    def print_mst(self):
        log.debug("MST: ")
        for src, dests in self.mst.items():
            log.debug("##### src: %s #####", src)
            for dest in dests:
                log.debug("dest: %s", dest)

    def print_prunes(self):
        log.debug("PRUNES: ")
        for pruner, prunes in self.prunes.items():
            log.debug("--------- Pruner: %s ---------", pruner)
            for prunee in prunes:
                log.debug("Prunee: %s", prunee)

    def print_pushes(self):
        log.debug("PUSHES: ")
        for src, dests in self.pushes.items():
            log.debug("************* SRC: %s, # %s *************",
                      src, len(dests))
            for dst in dests:
                log.debug("Dest: %s", dst)


def make_cluster_nodes(accounts, filter_zero_staked=False):
    """Build Node objects from {Pubkey: stake} (gossip.rs:883-925)."""
    return [Node(pk, stake) for pk, stake in accounts.items()
            if not filter_zero_staked or stake != 0]
