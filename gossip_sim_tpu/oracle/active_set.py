"""Push active set: 25 stake-bucketed entries of push peers with prune filters.

Oracle (CPU) equivalent of the reference's ``PushActiveSet`` /
``PushActiveSetEntry`` (push_active_set.rs:24-187) with one documented
divergence: the per-peer pruned-origin *bloom filter* (false-positive rate 0.1,
<=32768 bits, push_active_set.rs:122-123) is replaced by an exact set, so the
oracle never over-prunes due to bloom false positives.  Everything else —
bucket selection by min(stake(self), stake(origin)), insertion-order iteration,
self-seeded filters (a peer never receives messages originating from itself,
push_active_set.rs:179), incremental rotation with oldest-first eviction
(push_active_set.rs:153-186) — matches the reference bit-for-bit under the
same RNG stream.
"""

from __future__ import annotations

import hashlib
import math

from ..constants import NUM_PUSH_ACTIVE_SET_ENTRIES
from ..identity import get_stake_bucket
from .weighted_shuffle import WeightedShuffle


class BloomFilter:
    """Statistical stand-in for the reference's per-peer prune bloom
    (``Bloom::random(cluster_size, 0.1, 32768)``, push_active_set.rs:122-123).

    Same geometry — ``num_bits = -n ln(p) / ln(2)^2`` capped at 32768,
    ``num_keys = round(m/n * ln 2)`` — with Kirsch-Mitzenmacher double
    hashing over the two independent 32-bit halves of a blake2b-64 digest
    instead of the reference's keyed FNV: *false-positive rate* parity, not
    bit parity.  Used by the bloom-fidelity experiment
    (tools/bloom_divergence.py) to measure the over-prune effect the
    engine's exact masks deliberately omit."""

    __slots__ = ("m", "k", "salts", "bits")

    def __init__(self, num_items, rng=None, false_rate=0.1, max_bits=32768,
                 salt_seed=None):
        n = max(1, int(num_items))
        m = int(math.ceil(n * abs(math.log(false_rate)) / (math.log(2) ** 2)))
        self.m = max(1, min(max_bits, m))
        self.k = max(1, round(self.m / n * math.log(2)))
        if salt_seed is not None:
            # deterministic salts that do NOT consume the simulation RNG —
            # keeps exact-mode and bloom-mode runs on identical RNG streams
            # so a comparison isolates genuine false-positive effects
            d = hashlib.blake2b(salt_seed.to_bytes(8, "little"),
                                digest_size=4 * self.k).digest()
            self.salts = [int.from_bytes(d[4 * i:4 * i + 4], "little")
                          for i in range(self.k)]
        else:
            # keyed hashes drawn from the sim's RNG stream (reference-like:
            # Bloom::random draws keys from the thread rng)
            self.salts = [rng.gen_range_u64(0, 1 << 32)
                          for _ in range(self.k)]
        self.bits = 0

    def _positions(self, item):
        raw = item.raw if hasattr(item, "raw") else bytes(item)
        d = int.from_bytes(hashlib.blake2b(raw, digest_size=8).digest(),
                           "little")
        h1 = d & 0xFFFFFFFF
        h2 = (d >> 32) | 1
        return [(h1 + s * h2) % self.m for s in self.salts]

    def add(self, item):
        for p in self._positions(item):
            self.bits |= 1 << p

    def __contains__(self, item):
        return all(self.bits >> p & 1 for p in self._positions(item))


class PushActiveSetEntry:
    """Insertion-ordered map: peer pubkey -> pruned-origin filter (an exact
    set by default; a ``BloomFilter`` in bloom-fidelity mode)."""

    def __init__(self, filter_factory=None):
        self.peers = {}  # Pubkey -> filter; python dicts preserve insertion order
        # filter_factory(peer, rng) -> filter pre-seeded with peer's own key
        self.filter_factory = filter_factory

    def __len__(self):
        return len(self.peers)

    def get_nodes(self, origin, force_push=None):
        """Yield peers (insertion order) whose filter does not contain origin
        (push_active_set.rs:128-141)."""
        for node, pruned in self.peers.items():
            if origin not in pruned or (force_push is not None and force_push(node)):
                yield node

    def prune(self, node, origin):
        """Add origin to node's pruned-filter if node is a current peer
        (push_active_set.rs:143-151)."""
        s = self.peers.get(node)
        if s is not None:
            s.add(origin)

    def rotate(self, rng, size, nodes, weights):
        """Incremental rotation (push_active_set.rs:153-186).

        Walk the weighted shuffle, inserting unseen peers (filter self-seeded
        with the peer's own key) until len exceeds ``size``; then evict oldest
        entries down to ``size``.  With a full entry this swaps in exactly one
        new peer and evicts the oldest.
        """
        for idx in WeightedShuffle(weights).shuffle(rng):
            if len(self.peers) > size:
                break
            node = nodes[idx]
            if node in self.peers:
                continue
            # self-seed: never push origin==peer to peer
            # (push_active_set.rs:179)
            if self.filter_factory is None:
                self.peers[node] = {node}
            else:
                f = self.filter_factory(node, rng)
                f.add(node)
                self.peers[node] = f
        while len(self.peers) > size:
            oldest = next(iter(self.peers))
            del self.peers[oldest]


class PushActiveSet:
    """25 stake-bucket entries (push_active_set.rs:24-119).

    ``filter_factory``: None = exact prune sets (the default, documented
    divergence); pass ``lambda peer, rng: BloomFilter(cluster_size, rng)``
    for reference-geometry bloom fidelity."""

    def __init__(self, filter_factory=None):
        self.entries = [PushActiveSetEntry(filter_factory)
                        for _ in range(NUM_PUSH_ACTIVE_SET_ENTRIES)]

    def _entry(self, stake):
        return self.entries[get_stake_bucket(stake)]

    def get_nodes(self, pubkey, origin, stakes, force_push=None):
        """Peers to push to for a value owned by ``origin``
        (push_active_set.rs:38-52): bucket by min(stake(self), stake(origin))."""
        stake = min(stakes.get(pubkey, 0), stakes.get(origin, 0))
        return self._entry(stake).get_nodes(origin, force_push)

    def prune(self, pubkey, node, origins, stakes):
        """Stop pushing messages from ``origins`` to ``node``
        (push_active_set.rs:56-71)."""
        my_stake = stakes.get(pubkey, 0)
        for origin in origins:
            if origin == pubkey:
                continue
            stake = min(my_stake, stakes.get(origin, 0))
            self._entry(stake).prune(node, origin)

    def rotate(self, rng, size, nodes, stakes):
        """Re-sample every bucket entry (push_active_set.rs:73-114).

        For entry k, candidate j's weight is (min(bucket_j, k) + 1)^2.
        """
        buckets = [get_stake_bucket(stakes.get(n, 0)) for n in nodes]
        for k, entry in enumerate(self.entries):
            weights = [(min(b, k) + 1) ** 2 for b in buckets]
            entry.rotate(rng, size, nodes, weights)
