"""Push active set: 25 stake-bucketed entries of push peers with prune filters.

Oracle (CPU) equivalent of the reference's ``PushActiveSet`` /
``PushActiveSetEntry`` (push_active_set.rs:24-187) with one documented
divergence: the per-peer pruned-origin *bloom filter* (false-positive rate 0.1,
<=32768 bits, push_active_set.rs:122-123) is replaced by an exact set, so the
oracle never over-prunes due to bloom false positives.  Everything else —
bucket selection by min(stake(self), stake(origin)), insertion-order iteration,
self-seeded filters (a peer never receives messages originating from itself,
push_active_set.rs:179), incremental rotation with oldest-first eviction
(push_active_set.rs:153-186) — matches the reference bit-for-bit under the
same RNG stream.
"""

from __future__ import annotations

from ..constants import NUM_PUSH_ACTIVE_SET_ENTRIES
from ..identity import get_stake_bucket
from .weighted_shuffle import WeightedShuffle


class PushActiveSetEntry:
    """Insertion-ordered map: peer pubkey -> set of pruned origins."""

    def __init__(self):
        self.peers = {}  # Pubkey -> set(Pubkey); python dicts preserve insertion order

    def __len__(self):
        return len(self.peers)

    def get_nodes(self, origin, force_push=None):
        """Yield peers (insertion order) whose filter does not contain origin
        (push_active_set.rs:128-141)."""
        for node, pruned in self.peers.items():
            if origin not in pruned or (force_push is not None and force_push(node)):
                yield node

    def prune(self, node, origin):
        """Add origin to node's pruned-filter if node is a current peer
        (push_active_set.rs:143-151)."""
        s = self.peers.get(node)
        if s is not None:
            s.add(origin)

    def rotate(self, rng, size, nodes, weights):
        """Incremental rotation (push_active_set.rs:153-186).

        Walk the weighted shuffle, inserting unseen peers (filter self-seeded
        with the peer's own key) until len exceeds ``size``; then evict oldest
        entries down to ``size``.  With a full entry this swaps in exactly one
        new peer and evicts the oldest.
        """
        for idx in WeightedShuffle(weights).shuffle(rng):
            if len(self.peers) > size:
                break
            node = nodes[idx]
            if node in self.peers:
                continue
            self.peers[node] = {node}  # self-seed: never push origin==peer to peer
        while len(self.peers) > size:
            oldest = next(iter(self.peers))
            del self.peers[oldest]


class PushActiveSet:
    """25 stake-bucket entries (push_active_set.rs:24-119)."""

    def __init__(self):
        self.entries = [PushActiveSetEntry() for _ in range(NUM_PUSH_ACTIVE_SET_ENTRIES)]

    def _entry(self, stake):
        return self.entries[get_stake_bucket(stake)]

    def get_nodes(self, pubkey, origin, stakes, force_push=None):
        """Peers to push to for a value owned by ``origin``
        (push_active_set.rs:38-52): bucket by min(stake(self), stake(origin))."""
        stake = min(stakes.get(pubkey, 0), stakes.get(origin, 0))
        return self._entry(stake).get_nodes(origin, force_push)

    def prune(self, pubkey, node, origins, stakes):
        """Stop pushing messages from ``origins`` to ``node``
        (push_active_set.rs:56-71)."""
        my_stake = stakes.get(pubkey, 0)
        for origin in origins:
            if origin == pubkey:
                continue
            stake = min(my_stake, stakes.get(origin, 0))
            self._entry(stake).prune(node, origin)

    def rotate(self, rng, size, nodes, stakes):
        """Re-sample every bucket entry (push_active_set.rs:73-114).

        For entry k, candidate j's weight is (min(bucket_j, k) + 1)^2.
        """
        buckets = [get_stake_bucket(stakes.get(n, 0)) for n in nodes]
        for k, entry in enumerate(self.entries):
            weights = [(min(b, k) + 1) ** 2 for b in buckets]
            entry.rotate(rng, size, nodes, weights)
