"""CPU oracle backend: faithful, seeded, dictionary-based reference semantics.

This backend is the parity referee for the TPU engine (SURVEY.md build plan
step 2); it mirrors /root/reference/src semantics including the exact
ChaCha/rand RNG stream (see ``rustrng``).
"""

from .cluster import Cluster, Node, make_cluster_nodes
from .rmr import RelativeMessageRedundancy
from .rustrng import ChaChaRng
from .weighted_shuffle import WeightedShuffle

__all__ = [
    "ChaChaRng",
    "Cluster",
    "Node",
    "RelativeMessageRedundancy",
    "WeightedShuffle",
    "make_cluster_nodes",
]
