"""Bit-exact Python port of the RNG stack the reference simulator uses.

The reference drives all sampling through ``rand_chacha::ChaChaRng`` (ChaCha20,
rand_chacha 0.2.2 / rand 0.7, pinned in Cargo.toml) seeded as
``ChaChaRng::from_seed([189u8; 32])`` in every test (e.g. gossip.rs:1046).
Reproducing that stream exactly lets us port the reference's golden tests
(exact stakes, exact active-set membership) instead of only statistical checks.

Implements:
  * ChaCha20 block function + the rand_core 0.5 ``BlockRng`` buffering
    discipline (4 blocks / 64 u32 words per refill, u64 = lo-word | hi-word<<32,
    including the buffer-straddling path).
  * rand 0.7 ``gen_range(low, high)`` for u64 (widening-multiply rejection
    sampling, uniform.rs ``sample_single``).
  * rand 0.7 ``gen::<f64>()`` Standard distribution ((v >> 11) * 2^-53).

This is a clean-room reimplementation from the published algorithm
specifications (ChaCha20 RFC 8439 core; rand crate documented behavior).
"""

from __future__ import annotations

MASK32 = 0xFFFFFFFF
MASK64 = 0xFFFFFFFFFFFFFFFF

_CONSTANTS = (0x61707865, 0x3320646E, 0x79622D32, 0x6B206574)


def _quarter(x, a, b, c, d):
    x[a] = (x[a] + x[b]) & MASK32
    x[d] ^= x[a]
    x[d] = ((x[d] << 16) | (x[d] >> 16)) & MASK32
    x[c] = (x[c] + x[d]) & MASK32
    x[b] ^= x[c]
    x[b] = ((x[b] << 12) | (x[b] >> 20)) & MASK32
    x[a] = (x[a] + x[b]) & MASK32
    x[d] ^= x[a]
    x[d] = ((x[d] << 8) | (x[d] >> 24)) & MASK32
    x[c] = (x[c] + x[d]) & MASK32
    x[b] ^= x[c]
    x[b] = ((x[b] << 7) | (x[b] >> 25)) & MASK32


def _chacha20_block(key_words, counter, nonce_words):
    init = list(_CONSTANTS) + list(key_words) + [
        counter & MASK32,
        (counter >> 32) & MASK32,
        nonce_words[0],
        nonce_words[1],
    ]
    x = list(init)
    for _ in range(10):  # 10 double rounds = 20 rounds
        _quarter(x, 0, 4, 8, 12)
        _quarter(x, 1, 5, 9, 13)
        _quarter(x, 2, 6, 10, 14)
        _quarter(x, 3, 7, 11, 15)
        _quarter(x, 0, 5, 10, 15)
        _quarter(x, 1, 6, 11, 12)
        _quarter(x, 2, 7, 8, 13)
        _quarter(x, 3, 4, 9, 14)
    return [(a + b) & MASK32 for a, b in zip(x, init)]


class ChaChaRng:
    """rand_chacha 0.2.2-compatible ChaCha20 RNG (64-bit counter, stream 0)."""

    BUF_WORDS = 64  # 4 blocks per refill

    def __init__(self, seed: bytes, stream: int = 0):
        assert len(seed) == 32
        self.key = [int.from_bytes(seed[i * 4:(i + 1) * 4], "little") for i in range(8)]
        self.nonce = [stream & MASK32, (stream >> 32) & MASK32]
        self.counter = 0
        self.buf: list = []
        self.index = self.BUF_WORDS  # force refill on first use

    @classmethod
    def from_seed_byte(cls, byte: int) -> "ChaChaRng":
        """ChaChaRng::from_seed([byte; 32]) — the reference test seeding idiom."""
        return cls(bytes([byte]) * 32)

    def _generate(self):
        buf = []
        for i in range(4):
            buf.extend(_chacha20_block(self.key, self.counter + i, self.nonce))
        self.counter += 4
        self.buf = buf

    def next_u32(self) -> int:
        if self.index >= self.BUF_WORDS:
            self._generate()
            self.index = 0
        v = self.buf[self.index]
        self.index += 1
        return v

    def next_u64(self) -> int:
        # rand_core 0.5 BlockRng::next_u64 semantics, incl. straddling.
        idx = self.index
        if idx < self.BUF_WORDS - 1:
            self.index += 2
            return self.buf[idx] | (self.buf[idx + 1] << 32)
        if idx >= self.BUF_WORDS:
            self._generate()
            self.index = 2
            return self.buf[0] | (self.buf[1] << 32)
        # exactly one word left
        x = self.buf[self.BUF_WORDS - 1]
        self._generate()
        self.index = 1
        return (self.buf[0] << 32) | x

    # ---- rand 0.7 distributions ----

    def gen_range_u64(self, low: int, high: int) -> int:
        """rand 0.7 UniformInt::<u64>::sample_single(low, high) — half-open."""
        rng_span = (high - low) & MASK64
        lz = 64 - rng_span.bit_length()
        zone = ((rng_span << lz) & MASK64) - 1 & MASK64
        while True:
            v = self.next_u64()
            prod = v * rng_span
            hi, lo = prod >> 64, prod & MASK64
            if lo <= zone:
                return (low + hi) & MASK64

    def gen_f64(self) -> float:
        """rand 0.7 Standard f64: (next_u64() >> 11) * 2^-53."""
        return (self.next_u64() >> 11) * (1.0 / (1 << 53))
