"""Relative message redundancy: rmr = m / (n - 1) - 1.

m counts push messages *and* prune messages (gossip.rs:571,684-687);
n counts nodes that received the message, including the origin
(gossip.rs:508,594).  Reference: gossip_stats.rs:466-547.
"""

from __future__ import annotations


class RelativeMessageRedundancy:
    __slots__ = ("m", "n", "rmr")

    def __init__(self):
        self.m = 0
        self.n = 0
        self.rmr = 0.0

    def increment_m(self):
        self.m += 1

    def increment_m_by(self, amount):
        self.m += amount

    def increment_n(self):
        self.n += 1

    def reset(self):
        self.m = 0
        self.n = 0
        self.rmr = 0.0

    def calculate(self):
        if self.n == 0:
            raise ZeroDivisionError("RMR: n is 0")
        if self.n == 1:
            # only the origin holds the message — delivery collapsed under
            # impairment (faults.py); the engine reports 0.0 here too
            self.rmr = 0.0
        else:
            self.rmr = self.m / (self.n - 1) - 1.0
        return self.rmr, self.m, self.n
