"""Experiment driver + sweep harness (reference: gossip_main.rs).

Flag names, defaults and sweep semantics are the compatibility contract
(gossip_main.rs:53-241,774-951).  Extensions beyond the reference surface
(``--backend``, ``--seed``, ``--num-synthetic-nodes``, ``--all-origins``,
``--origin-batch``) select the TPU engine, the deterministic RNG stream, and
the origin-parallel vmap mode the reference lacks.
"""

from __future__ import annotations

import argparse
import contextlib
import logging
import os
import sys
import time

import numpy as np

from .config import Config, StepSize, Testing
from .constants import (AGGREGATE_HOPS_FAIL_NODES_HISTOGRAM_UPPER_BOUND,
                        AGGREGATE_HOPS_MIN_INGRESS_NODES_HISTOGRAM_UPPER_BOUND,
                        API_MAINNET_BETA, COVERAGE_RECOVERY_THRESHOLD,
                        STANDARD_HISTOGRAM_UPPER_BOUND, UNREACHED,
                        VALIDATOR_STAKE_DISTRIBUTION_NUM_BUCKETS,
                        get_influx_url, get_json_rpc_url)
from .identity import NodeIndex
from .ingest import (fetch_vote_accounts_rpc, filter_accounts,
                     load_accounts_yaml, log_cluster_summary,
                     synthetic_accounts)
from .obs import Heartbeat, get_registry
from .oracle.rustrng import ChaChaRng
from . import resilience
from .resilience import (RESUMABLE_EXIT_CODE, DeviceDispatchError,
                         InfluxTee, ResumableInterrupt, RunJournal,
                         check_interrupt, journal_path,
                         replay_influx_lines, restore_pubkey_counter,
                         restore_stats, run_key_from_config, signal_guard,
                         stats_unit_payload, supervised_call, supervision)
from .sinks import (DatapointQueue, InfluxDataPoint, InfluxThread,
                    load_dotenv)
from .stats.gossip_stats import GossipStats, GossipStatsCollection

log = logging.getLogger("gossip_sim_tpu")

# gossip_main.rs:408; by design the recovery metric (faults.py) uses the
# same bar — a run warned as "poor coverage" is exactly one not yet
# recovered, so the two must never drift apart
POOR_COVERAGE_THRESHOLD = COVERAGE_RECOVERY_THRESHOLD

#: measured rounds per device->host harvest block (single-origin and
#: origin-rank paths).  Module-level so resilience tests can shrink it and
#: exercise multi-block journals without thousand-round runs.
HARVEST_BLOCK = 256


def _blocked(out):
    """``jax.block_until_ready`` on a ``(state, rows)`` pair, returning it.

    Supervised dispatch closures use this so device-side failures (and
    hangs, for the watchdog) surface inside the attempt instead of at a
    later harvest."""
    import jax
    state, rows = out
    jax.block_until_ready(rows)
    return state, rows


def _warn_shape_truncation(rows, params) -> tuple[int, int]:
    """Dense-shape divergence guard (engine rows -> loud warning).

    The engine ranks at most ``k_inbound`` inbound edges per (dest, round)
    and keeps ``rc_slots`` received-cache entries; anything beyond is
    counted (``inb_dropped`` / ``rc_overflow``) but silently truncated,
    at which point scoring diverges from received_cache.rs:83-98.  Surface
    it instead of letting sweeps drift."""
    dropped = int(np.asarray(rows["inb_dropped"]).sum())
    overflow = int(np.asarray(rows["rc_overflow"]).sum())
    clamped = int(np.asarray(rows.get("hop_clamped", 0)).sum())
    # total entries received into the cache path: every delivered message is
    # one (src, score) candidate entry per destination.  Summed over nodes,
    # the engine's per-target ingress equals the delivered count, so both
    # the per-round rows and the all-origins aggregate can supply it.
    received = int(np.asarray(rows.get("delivered", 0)).sum())
    if clamped:
        log.warning(
            "WARNING: %s hop sample(s) reached the top on-device histogram "
            "bin (hist_bins=%s) and were clamped — aggregate hop mean/"
            "median/max under-report the true tail. Raise "
            "EngineParams.hist_bins.", clamped, params.hist_bins)
    if dropped:
        log.warning(
            "WARNING: %s inbound message(s) exceeded the engine's ranking "
            "width (inbound_cap=%s) and were dropped from peer scoring — "
            "results may diverge from the reference semantics. Raise "
            "EngineParams.inbound_cap.", dropped, params.k_inbound)
    if overflow:
        pct = (f" ({100.0 * overflow / received:.2f}% of the {received} "
               f"entries received)" if received > 0 else "")
        log.warning(
            "WARNING: %s received-cache entries%s exceeded rc_slots=%s and "
            "were evicted early — prune decisions may diverge. Raise "
            "EngineParams.rc_slots.", overflow, pct, params.rc_slots)
    return dropped, overflow


def _engine_call_span(reg, fallback: str = "engine/rounds"):
    """The first jitted rounds call of a run carries the trace+compile cost
    (obs/report.py span conventions), so it records under engine/compile;
    later calls — warm-cache re-runs in a sweep, steady-state measured
    blocks — record under ``fallback``.  Returns (context manager,
    counts_toward_throughput): only engine/rounds time may feed the
    origin-iters / messages throughput denominators."""
    name = ("engine/compile" if reg.count("engine/compile") == 0
            else fallback)
    return reg.span(name), name == "engine/rounds"


def _enable_compilation_cache(config) -> None:
    """Persistent XLA compilation cache (engine/cache.py): point JAX at
    ``--compilation-cache-dir`` / $GOSSIP_COMPILATION_CACHE so compiled
    executables survive this process.  Called from every TPU run path;
    idempotent, no-op when neither source names a directory.  A broken
    cache directory is a lost optimization, not a dead run: failures warn
    and the simulation proceeds uncached."""
    from .engine import enable_persistent_cache
    try:
        ccdir = enable_persistent_cache(config.compilation_cache_dir)
    except Exception as e:
        log.warning("WARNING: could not enable the persistent compilation "
                    "cache (%s); continuing uncached", e)
        ccdir = None
    get_registry().set_info("compilation_cache_dir", ccdir or "")


def _sync_cache_counters() -> None:
    """Push the persistent-cache hit/miss counts and the engine's
    compile/reuse counters' backing info into the registry so run reports
    and bench lines carry them.  Safe when JAX never came up (oracle-only
    runs): the engine package is only consulted if already imported."""
    if "gossip_sim_tpu.engine.cache" not in sys.modules:
        return
    from .engine.cache import persistent_cache_counters, persistent_cache_dir
    reg = get_registry()
    reg.set_info("persistent_cache", persistent_cache_counters())
    if reg.info("compilation_cache_dir") is None:
        reg.set_info("compilation_cache_dir", persistent_cache_dir() or "")


def _note_capacity_ledger(config, params, *, origin_batch: int = 1,
                          lanes: int = 0) -> None:
    """Stamp the run's closed-form capacity ledger (obs/capacity.py) into
    registry info so the run report's ``capacity.ledger`` section and the
    ``sim_capacity`` Influx point carry exact byte attribution for THIS
    configuration.  Pure host arithmetic (~100 us); called once per run
    path where the EngineParams and the batch geometry are known.  A
    telemetry failure must never kill a run."""
    try:
        from .obs import capacity
        led = capacity.capacity_ledger(
            params, origin_batch=origin_batch, lanes=lanes,
            trace=bool(config.trace_dir),
            origins_scale_with_n=config.all_origins)
        get_registry().set_info("capacity_ledger", led)
    except Exception as e:  # pragma: no cover - telemetry-only path
        log.warning("WARNING: capacity ledger unavailable (%s)", e)


def _impair_params(config) -> dict:
    """EngineParams kwargs for the fault-injection knobs (engine/params.py)."""
    return dict(packet_loss_rate=config.packet_loss_rate,
                churn_fail_rate=config.churn_fail_rate,
                churn_recover_rate=config.churn_recover_rate,
                partition_at=config.partition_at,
                heal_at=config.heal_at,
                impair_seed=config.seed)


def _pull_params(config) -> dict:
    """EngineParams kwargs for the pull-gossip knobs (pull.py) and the
    adaptive direction-switch knobs (adaptive.py)."""
    return dict(gossip_mode=config.gossip_mode,
                pull_fanout=config.pull_fanout,
                pull_interval=config.pull_interval,
                pull_bloom_fp_rate=config.pull_bloom_fp_rate,
                pull_request_cap=config.pull_request_cap,
                adaptive_switch_threshold=config.adaptive_switch_threshold,
                adaptive_switch_hysteresis=config.adaptive_switch_hysteresis)


def _traffic_params(config) -> dict:
    """EngineParams kwargs for the concurrent-traffic knobs (traffic.py)."""
    return dict(traffic_values=config.traffic_values,
                traffic_rate=config.traffic_rate,
                node_ingress_cap=config.node_ingress_cap,
                node_egress_cap=config.node_egress_cap,
                traffic_stall_rounds=config.traffic_stall_rounds)


def _engine_params(config, num_nodes: int):
    """The EngineParams a Config selects (engine/params.py) — the single
    construction every TPU run path (single-sim, origin-rank sweep, lane
    sweep) resolves through, so their compile keys and knob vectors can
    never drift.  The one-shot fail event only arms on a FAIL_NODES run,
    matching the reference's sweep gating (gossip_main.rs:449-452)."""
    from .engine import EngineParams
    return EngineParams(
        num_nodes=num_nodes,
        push_fanout=config.gossip_push_fanout,
        active_set_size=config.gossip_active_set_size,
        probability_of_rotation=config.probability_of_rotation,
        prune_stake_threshold=config.prune_stake_threshold,
        min_ingress_nodes=config.min_ingress_nodes,
        warm_up_rounds=config.warm_up_rounds,
        fail_at=(config.when_to_fail
                 if config.test_type == Testing.FAIL_NODES else -1),
        fail_fraction=(config.fraction_to_fail
                       if config.test_type == Testing.FAIL_NODES else 0.0),
        trace_prune_cap=config.trace_prune_cap,
        health=config.health,
        representation=config.engine_representation,
        **_impair_params(config),
        **_pull_params(config),
        **_traffic_params(config),
    )


def _make_pull_oracle(config, index):
    """Oracle-side pull driver (pull.PullOracle), or None for push mode.
    Mode "adaptive" wraps it in the direction-switch gate
    (adaptive.AdaptiveOracle — a drop-in whose gated rounds report the
    same empty PullRound an off-interval round does)."""
    if not config.has_pull:
        return None
    kwargs = dict(
        seed=config.seed,
        pull_fanout=config.pull_fanout, pull_interval=config.pull_interval,
        pull_bloom_fp_rate=config.pull_bloom_fp_rate,
        pull_request_cap=config.pull_request_cap,
        packet_loss_rate=config.packet_loss_rate,
        partition_at=config.partition_at, heal_at=config.heal_at)
    stakes = index.stakes.astype(np.int64)
    if config.gossip_mode == "adaptive":
        from .adaptive import AdaptiveOracle
        return AdaptiveOracle(
            stakes,
            adaptive_switch_threshold=config.adaptive_switch_threshold,
            adaptive_switch_hysteresis=config.adaptive_switch_hysteresis,
            **kwargs)
    from .pull import PullOracle
    return PullOracle(stakes, **kwargs)


def _make_trace_writer(config, index, origin_indices, *, backend,
                       params=None):
    """Flight-recorder writer for ``--trace-dir`` (obs/trace.py), or None
    (with a warning) when the run has no measured rounds to trace.

    ``push_fanout`` is recorded post-clamp (the engine caps it at the
    active-set size, engine/core.py round_step) so the manifest matches the
    captured array shapes; the oracle path passes no ``params``, so its
    prune cap resolves through the same EngineParams.prune_cap rule
    (``--trace-prune-cap``; 0 = auto 16*N, capped at N*rc_slots) and the
    two backends' manifests can never drift."""
    # params.py is JAX-free, so the oracle path stays accelerator-agnostic
    from .engine.params import EngineParams
    from .obs.trace import TraceWriter

    if config.gossip_iterations <= config.warm_up_rounds:
        log.warning("WARNING: --trace-dir set but no measured rounds "
                    "(iterations <= warm-up-rounds); no trace written")
        return None
    fanout = min(config.gossip_push_fanout, config.gossip_active_set_size)
    if params is None:
        params = EngineParams(num_nodes=len(index),
                              trace_prune_cap=config.trace_prune_cap,
                              **_pull_params(config))
    prune_cap = params.prune_cap
    return TraceWriter(
        config.trace_dir, backend=backend, num_nodes=len(index),
        push_fanout=fanout,
        active_set_size=config.gossip_active_set_size,
        prune_cap=prune_cap,
        gossip_mode=params.gossip_mode,
        pull_slots=(params.pull_slots_resolved if params.has_pull else 0),
        origins=[int(i) for i in origin_indices],
        origin_pubkeys=[index.pubkeys[int(i)].to_string()
                        for i in origin_indices],
        seed=config.seed, warm_up_rounds=config.warm_up_rounds,
        iterations=config.gossip_iterations, config=config)


def build_parser() -> argparse.ArgumentParser:
    """The reference CLI surface (gossip_main.rs:53-241) + TPU extensions."""
    p = argparse.ArgumentParser(
        prog="gossip-sim",
        description="TPU-native Solana gossip push-protocol simulator")
    p.add_argument("--url", dest="json_rpc_url", default=API_MAINNET_BETA,
                   metavar="URL_OR_MONIKER", help="solana's json rpc url")
    p.add_argument("--account-file", default="", metavar="PATH",
                   help="yaml of solana accounts to either read from or write to")
    p.add_argument("--accounts-from-yaml", action="store_true",
                   help="set to read in key/stake pairs from yaml. "
                        "use with --account-file <path>")
    p.add_argument("--filter-zero-staked-nodes", "-f", action="store_true",
                   help="Filter out all zero-staked nodes")
    p.add_argument("--push-fanout", type=int, default=6,
                   help="gossip push fanout")
    p.add_argument("--active-set-size", type=int, default=12,
                   help="gossip push active set entry size")
    p.add_argument("--iterations", type=int, default=1,
                   help="gossip iterations")
    p.add_argument("--origin-rank", type=int, nargs="+", default=[1],
                   help="Select an origin with origin rank for gossip "
                        "(1 = largest stake). Pass a list with "
                        "--test-type origin-rank to sweep.")
    p.add_argument("--rotation-probability", "-p", type=float, default=0.013333,
                   help="After each round of gossip, rotate a node's active "
                        "set with probability 0 <= p <= 1")
    p.add_argument("--min-ingress-nodes", type=int, default=2,
                   help="Minimum number of incoming peers a node must keep")
    p.add_argument("--prune-stake-threshold", type=float, default=0.15,
                   help="Ensure a node is connected to a minimum stake of "
                        "prune_stake_threshold*node.stake()")
    p.add_argument("--num-buckets-stranded", type=int, default=10,
                   help="Number of buckets for the stranded node histogram")
    p.add_argument("--num-buckets-message", type=int, default=5,
                   help="Number of buckets for the ingress/egress message histograms")
    p.add_argument("--num-buckets-hops", type=int, default=15,
                   help="Number of buckets for the hops_stats histogram")
    p.add_argument("--test-type", default="no-test",
                   choices=[t.value for t in Testing],
                   help="Type of sweep to run")
    p.add_argument("--num-simulations", type=int, default=1,
                   help="Number of simulations to run")
    p.add_argument("--step-size", default="1",
                   help="Size of step for test_type (int or float)")
    p.add_argument("--fraction-to-fail", type=float, default=0.1,
                   help="Fail fraction-to-fail of total nodes in cluster")
    p.add_argument("--when-to-fail", type=int, default=0,
                   help="On what iteration should the nodes fail")
    p.add_argument("--warm-up-rounds", type=int, default=200,
                   help="Number of gossip rounds to run before measuring statistics")
    # ---- fault injection / network impairments (faults.py) -------------
    p.add_argument("--packet-loss-rate", type=float, default=0.0,
                   help="drop each gossip message with this probability "
                        "(stateless counter hash; bit-equivalent across "
                        "backends)")
    p.add_argument("--churn-fail-rate", type=float, default=0.0,
                   help="per-iteration probability that an alive node fails")
    p.add_argument("--churn-recover-rate", type=float, default=0.0,
                   help="per-iteration probability that a failed node "
                        "recovers and rejoins delivery")
    p.add_argument("--partition-at", type=int, default=-1,
                   help="iteration at which a stake-balanced bipartition "
                        "starts suppressing cross-partition messages "
                        "(-1 = never)")
    p.add_argument("--heal-at", type=int, default=-1,
                   help="iteration at which the partition heals (-1 = never)")
    # ---- pull gossip / anti-entropy (pull.py) ---------------------------
    p.add_argument("--gossip-mode", default="push",
                   choices=["push", "pull", "push-pull", "adaptive"],
                   help="protocol phases to simulate: push (the reference "
                        "protocol; default, bit-identical to the push-only "
                        "simulator), pull (anti-entropy only), "
                        "push-pull (both; pull rescues push-stranded "
                        "nodes), or adaptive (direction-optimizing: push "
                        "while coverage is low, the pull phase activates "
                        "once it crosses --adaptive-switch-threshold; in "
                        "traffic mode the switch is per value and "
                        "pull-rescues heal queue-drop starvation, "
                        "adaptive.py)")
    p.add_argument("--adaptive-switch-threshold", type=float, default=0.9,
                   help="adaptive mode: coverage fraction at which a "
                        "sim/value flips from push into its pull phase "
                        "(traced knob — threshold sweeps compile once)")
    p.add_argument("--adaptive-switch-hysteresis", type=float, default=0.05,
                   help="adaptive mode: the direction bit flips back to "
                        "push only when coverage falls below threshold - "
                        "hysteresis (stops boundary thrash)")
    p.add_argument("--pull-fanout", type=int, default=2,
                   help="pull requests each live node sends per pull round "
                        "(stake-weighted peer sampling)")
    p.add_argument("--pull-interval", type=int, default=1,
                   help="rounds between pull exchanges (pull runs when "
                        "iteration %% interval == 0)")
    p.add_argument("--pull-bloom-fp-rate", type=float, default=0.1,
                   help="bloom-filter false-positive probability of the "
                        "pull request digest (a holder wrongly filters "
                        "the value out; Solana's bloom targets 0.1)")
    p.add_argument("--pull-request-cap", type=int, default=0,
                   help="max pull requests a peer serves per round "
                        "(<= 0 = unlimited); excess requests are counted "
                        "as capped misses")
    p.add_argument("--traffic-values", type=int, default=1,
                   help="concurrent CRDS value slots (traffic.py): > 1 "
                        "switches to the M-value traffic engine — a "
                        "deterministic stake-weighted injection schedule "
                        "where all in-flight values share ONE active-set/"
                        "prune/rotation state and contend for per-node "
                        "queue budgets.  1 with both caps off (default) is "
                        "bit-identical to the single-value simulator")
    p.add_argument("--traffic-rate", type=int, default=1,
                   help="new values injected per round at counter-hashed "
                        "stake-weighted origins (traffic mode; injections "
                        "beyond free slots are counted as dropped)")
    p.add_argument("--node-ingress-cap", type=int, default=0,
                   help="messages a node ACCEPTS per round across all "
                        "in-flight values (<= 0 = unlimited); excess "
                        "arrivals are dropped with a queue_dropped outcome")
    p.add_argument("--node-egress-cap", type=int, default=0,
                   help="messages a node SENDS per round across all "
                        "in-flight values (<= 0 = unlimited); excess "
                        "candidates defer to the next round (a send queue)")
    p.add_argument("--traffic-stall-rounds", type=int, default=3,
                   help="consecutive no-progress rounds before an "
                        "unconverged value retires and frees its slot")
    p.add_argument("--influx", default="n",
                   help="Influx for reporting metrics. i for internal-metrics, "
                        "l for localhost, n for none")
    p.add_argument("--print-stats", action="store_true",
                   help="Print Gossip Stats to console at end of simulation")
    # ---- TPU-framework extensions --------------------------------------
    p.add_argument("--backend", default="tpu", choices=["tpu", "oracle"],
                   help="tpu = JAX engine; oracle = faithful CPU reference")
    p.add_argument("--seed", type=int, default=42,
                   help="Deterministic RNG seed (both backends)")
    p.add_argument("--num-synthetic-nodes", type=int, default=0,
                   help=">0: run on a synthetic seeded cluster instead of "
                        "an account file / RPC")
    p.add_argument("--all-origins", action="store_true",
                   help="TPU backend: batch-simulate every node as origin "
                        "(vmap over the origin axis)")
    p.add_argument("--origin-batch", type=int, default=0,
                   help="origins per device batch in --all-origins mode "
                        "(0 = auto)")
    p.add_argument("--sweep-lanes", type=int, default=0,
                   help="tpu backend: run a traced-knob sweep (packet-loss, "
                        "churn, pull-fanout, rotate-probability, prune-"
                        "stake-threshold, min-ingress-nodes, fail-nodes) "
                        "lane-batched — K sweep points stacked on a vmapped "
                        "lane axis run as ceil(K/lanes) compiled device "
                        "programs with a single harvest each, bit-identical "
                        "to the serial sweep (engine/lanes.py). 0 = serial. "
                        "Shape-stepping sweeps (active-set-size, push-"
                        "fanout) and origin-rank fall back to their "
                        "existing paths")
    p.add_argument("--mesh-devices", type=int, default=0,
                   help="devices to shard origin batches over in "
                        "--all-origins mode (0 = all available)")
    p.add_argument("--mesh-node-shards", type=int, default=1,
                   help="--all-origins mode: additionally shard the "
                        "per-origin node axis over this many devices per "
                        "origin-shard (parallel/mesh.py; must divide the "
                        "mesh device count; 1 = origins axis only)")
    p.add_argument("--profile-dir", "--jax-profile", dest="jax_profile_dir",
                   default="", metavar="DIR",
                   help="tpu backend: capture a jax.profiler trace of the "
                        "measured rounds into DIR (view with TensorBoard "
                        "or xprof; the round/* named scopes label the "
                        "protocol verbs)")
    p.add_argument("--run-report", dest="run_report_path", default="",
                   metavar="PATH",
                   help="write a machine-readable run report JSON to PATH: "
                        "config, environment, span timings, throughput, "
                        "fault + influx counters (schema shared with "
                        "bench.py; see obs/report.py)")
    p.add_argument("--memwatch-interval-s", type=float, default=0.0,
                   metavar="S",
                   help="capacity observatory (obs/memwatch.py): sample "
                        "host RSS + device memory_stats every S seconds "
                        "on a low-overhead thread; peak + series land in "
                        "the run report's capacity section and the "
                        "sim_capacity Influx series. 0 = off (the report "
                        "still carries the kernel peak-RSS mark). Zero "
                        "bit-impact on simulation output")
    p.add_argument("--capacity-harvest", action="store_true",
                   help="capacity observatory (obs/capacity.py): capture "
                        "XLA cost_analysis/memory_analysis (FLOPs, "
                        "argument/output/temp/generated-code bytes) per "
                        "compiled engine executable, keyed by compile-"
                        "cache entry so warm calls reuse the harvest. "
                        "Costs one extra XLA compile per distinct "
                        "executable (pair with --compilation-cache-dir "
                        "to make it a disk hit); zero bit-impact")
    p.add_argument("--health", action="store_true",
                   help="node-health observatory (obs/health.py): "
                        "accumulate per-node load/latency/drop planes "
                        "inside the jitted round (egress/ingress, queue "
                        "drops by side, prunes issued AND received, "
                        "first-delivery rounds, pull rescues) and digest "
                        "them on device per measured block — stake-decile "
                        "segment sums + top-k hot nodes, so the host only "
                        "harvests [10,·]/[k,·] arrays. Feeds the REQUIRED "
                        "node_health run-report section, the "
                        "sim_node_health Influx series, and "
                        "tools/health_report.py. Off = bit-identical "
                        "output to a build without the gate")
    p.add_argument("--health-topk", type=int, default=10,
                   help="hot nodes extracted per health digest metric "
                        "(the [k,·] harvest; --health only)")
    p.add_argument("--engine-representation", default="dense",
                   choices=["dense", "sparse"],
                   help="gossip-round execution layout (engine/sparse.py): "
                        "dense keeps the full-width sort-routed round; "
                        "sparse reroutes delivery/BFS/inbound ranking over "
                        "the bounded candidate edge list (segment "
                        "reductions + deterministic scatters) and derives "
                        "the received-cache stake planes from the cluster "
                        "tables instead of carrying two [O,N,C] arrays — "
                        "bit-identical rows and state, roughly half the "
                        "received-cache bytes, and the representation the "
                        "capacity model prices past the dense all-origins "
                        "wall (tools/capacity_report.py --representation "
                        "sparse). Push mode only; traffic needs dense")
    p.add_argument("--trace-dir", default="", metavar="DIR",
                   help="flight recorder (obs/trace.py): capture per-round "
                        "protocol events (delivery edges + outcomes, first-"
                        "delivery tree, prune pairs, rotations, active-set "
                        "snapshots) of the measured rounds into DIR as a "
                        "versioned npz trace (gossip-sim-tpu/trace/v1); "
                        "analyze with tools/trace_report.py")
    p.add_argument("--trace-origins", type=int, default=4,
                   help="--all-origins mode: flight-record this many "
                        "sampled origins (their per-origin RNG streams "
                        "replay bit-identically outside the batch)")
    p.add_argument("--trace-prune-cap", type=int, default=0,
                   help="flight recorder: prune pairs captured per "
                        "(origin, round); 0 = auto (16 * num_nodes). "
                        "Raise when the trace manifest flags "
                        "truncated_prune_rounds")
    p.add_argument("--compilation-cache-dir", default="", metavar="DIR",
                   help="tpu backend: persistent XLA compilation cache "
                        "(engine/cache.py). Compiled executables are "
                        "serialized to DIR and reused by later processes "
                        "(sweep re-runs, CI, bench). Defaults to "
                        "$GOSSIP_COMPILATION_CACHE when unset")
    p.add_argument("--checkpoint-path", default="",
                   help="save the simulation state (SimState arrays + "
                        "params) to this .npz after each measured block and "
                        "at the end; resume with --resume")
    p.add_argument("--resume", dest="resume_path", default="",
                   help="tpu backend: continue an interrupted run "
                        "bit-exactly. Single runs: load a "
                        "--checkpoint-path .npz and continue from its "
                        "recorded iteration (stats are recorded for the "
                        "remaining rounds). Sweeps / --sweep-lanes / "
                        "--all-origins: replay the run journal's "
                        "committed units into stats/Influx verbatim and "
                        "restart from the first uncommitted unit "
                        "(resilience.py)")
    p.add_argument("--checkpoint-every-s", type=float, default=0.0,
                   help="minimum seconds between periodic checkpoint "
                        "autosaves on the single-run path (0 = save "
                        "after every harvest block)")
    p.add_argument("--device-timeout-s", type=float, default=0.0,
                   help="watchdog bound on one engine dispatch "
                        "(resilience.py): a call exceeding this is "
                        "treated as a hung device and retried with "
                        "backoff (0 = no watchdog)")
    p.add_argument("--device-retries", type=int, default=2,
                   help="transient-failure retries per supervised "
                        "engine dispatch (exponential backoff)")
    p.add_argument("--on-device-failure", default="",
                   choices=["", "cpu-fallback", "abort"],
                   help="after the retry budget: cpu-fallback re-executes "
                        "the failed unit on the CPU backend and flags the "
                        "run report (device_failures/fallback_units); "
                        "abort exits with the resumable exit code "
                        f"({RESUMABLE_EXIT_CODE}) and a committed "
                        "journal. Passing either value enables "
                        "supervision even without --device-timeout-s")
    p.add_argument("--influx-spool", default="", metavar="PATH",
                   help="durable sink spool: Influx points dropped after "
                        "retry exhaustion or queue overflow are appended "
                        "to PATH as line protocol instead of discarded; "
                        "re-send with tools/influx_replay.py")
    p.add_argument("--telemetry-port", type=int, default=-1, metavar="PORT",
                   help="live telemetry plane (obs/exporter.py): serve "
                        "/metrics (Prometheus text), /status (the evolving "
                        "run report as JSON) and /events (recent "
                        "structured events) on 127.0.0.1:PORT while the "
                        "run is live. 0 binds an ephemeral port (stamped "
                        "into the log, registry info and the run report's "
                        "telemetry section); omit to keep the exporter "
                        "off. Watch with tools/telemetry_watch.py")
    p.add_argument("--event-log", default="", metavar="PATH",
                   help="structured event log (obs/telemetry.py, schema "
                        "gossip-sim-tpu/events/v1): append heartbeat "
                        "ticks, journal commits/resumes, watchdog retries/"
                        "CPU fallbacks, SIGTERM/SIGINT, and Influx retry/"
                        "spool/drop events to PATH as JSONL. Records carry "
                        "the run-key fingerprint + unit id, so they join "
                        "the resilience journal's committed units; append "
                        "mode makes one PATH span an interrupted-and-"
                        "resumed run")
    p.add_argument("--serve", action="store_true",
                   help="gossip-as-a-service (serve/, ISSUE 20): run a "
                        "long-lived continuous-batching daemon holding "
                        "--serve-lanes warm device lanes, admitting "
                        "scenario requests over POST /submit on the "
                        "telemetry port or a watched --serve-spool-dir. "
                        "Also reachable as `python -m gossip_sim_tpu "
                        "serve`")
    p.add_argument("--serve-lanes", type=int, default=4, metavar="K",
                   help="warm device lanes the serve daemon batches "
                        "(fixed compile geometry; requests splice into "
                        "free lanes as others retire)")
    p.add_argument("--serve-block-rounds", type=int, default=25,
                   metavar="B",
                   help="serve scheduler tick: rounds per batched "
                        "dispatch, snapped down to a divisor of "
                        "--iterations so lanes retire exactly at block "
                        "boundaries")
    p.add_argument("--serve-memory-budget", default="", metavar="BYTES",
                   help="ledger budget gating serve admission (e.g. "
                        "2GiB): requests are priced with the closed-form "
                        "capacity ledger BEFORE any device contact; "
                        "over-budget submissions get 413 with the "
                        "predicted and available byte counts (empty = "
                        "unmetered)")
    p.add_argument("--serve-max-queue", type=int, default=64,
                   help="queued serve requests across all tenants before "
                        "submissions get 429 (FIFO per tenant, "
                        "round-robin across tenants)")
    p.add_argument("--serve-spool-dir", default="", metavar="DIR",
                   help="watched serve intake directory: drop "
                        "<name>.json request specs, collect "
                        "<id>.result.json")
    p.add_argument("--serve-max-requests", type=int, default=0,
                   metavar="N",
                   help="exit 0 after N completed serve requests "
                        "(0 = run until SIGTERM; smoke/bench hook)")
    p.add_argument("--serve-idle-timeout-s", type=float, default=0.0,
                   help="exit 0 after this many seconds with no running "
                        "or queued serve request (0 = run until "
                        "SIGTERM)")
    return p


def config_from_args(args) -> Config:
    prob = args.rotation_probability
    if not 0.0 <= prob <= 1.0:
        raise SystemExit("rotation-probability must be between 0 and 1")
    if not 0.0 <= args.prune_stake_threshold <= 1.0:
        raise SystemExit("prune-stake-threshold must be between 0 and 1")
    for flag in ("packet_loss_rate", "churn_fail_rate", "churn_recover_rate"):
        if not 0.0 <= getattr(args, flag) <= 1.0:
            raise SystemExit(
                f"{flag.replace('_', '-')} must be between 0 and 1")
    if args.heal_at >= 0 and args.partition_at < 0:
        raise SystemExit("heal-at requires partition-at")
    if args.partition_at >= 0 and 0 <= args.heal_at < args.partition_at:
        raise SystemExit("heal-at must not precede partition-at")
    if not 0.0 <= args.pull_bloom_fp_rate <= 1.0:
        raise SystemExit("pull-bloom-fp-rate must be between 0 and 1")
    if args.gossip_mode != "push":
        if args.pull_fanout < 1:
            raise SystemExit("pull-fanout must be >= 1")
        if args.pull_interval < 1:
            raise SystemExit("pull-interval must be >= 1")
    if args.gossip_mode == "adaptive":
        if not 0.0 < args.adaptive_switch_threshold <= 1.0:
            raise SystemExit("adaptive-switch-threshold must be in (0, 1]")
        if not (0.0 <= args.adaptive_switch_hysteresis
                < args.adaptive_switch_threshold):
            raise SystemExit("adaptive-switch-hysteresis must be in "
                             "[0, adaptive-switch-threshold)")
    if args.mesh_node_shards < 1:
        raise SystemExit("mesh-node-shards must be >= 1")
    if args.sweep_lanes < 0:
        raise SystemExit("sweep-lanes must be >= 0")
    if args.memwatch_interval_s < 0:
        raise SystemExit("memwatch-interval-s must be >= 0")
    if args.health_topk < 1:
        raise SystemExit("health-topk must be >= 1")
    return Config(
        gossip_push_fanout=args.push_fanout,
        gossip_active_set_size=args.active_set_size,
        gossip_iterations=args.iterations,
        accounts_from_file=args.accounts_from_yaml,
        account_file=args.account_file,
        origin_rank=args.origin_rank[0],
        probability_of_rotation=prob,
        prune_stake_threshold=args.prune_stake_threshold,
        min_ingress_nodes=args.min_ingress_nodes,
        filter_zero_staked_nodes=args.filter_zero_staked_nodes,
        num_buckets_for_stranded_node_hist=args.num_buckets_stranded,
        num_buckets_for_message_hist=args.num_buckets_message,
        num_buckets_for_hops_stats_hist=args.num_buckets_hops,
        fraction_to_fail=args.fraction_to_fail,
        when_to_fail=args.when_to_fail,
        packet_loss_rate=args.packet_loss_rate,
        churn_fail_rate=args.churn_fail_rate,
        churn_recover_rate=args.churn_recover_rate,
        partition_at=args.partition_at,
        heal_at=args.heal_at,
        gossip_mode=args.gossip_mode,
        pull_fanout=args.pull_fanout,
        pull_interval=args.pull_interval,
        pull_bloom_fp_rate=args.pull_bloom_fp_rate,
        pull_request_cap=args.pull_request_cap,
        adaptive_switch_threshold=args.adaptive_switch_threshold,
        adaptive_switch_hysteresis=args.adaptive_switch_hysteresis,
        traffic_values=args.traffic_values,
        traffic_rate=args.traffic_rate,
        node_ingress_cap=args.node_ingress_cap,
        node_egress_cap=args.node_egress_cap,
        traffic_stall_rounds=args.traffic_stall_rounds,
        test_type=Testing.parse(args.test_type),
        num_simulations=args.num_simulations,
        step_size=StepSize.parse(args.step_size),
        warm_up_rounds=args.warm_up_rounds,
        print_stats=args.print_stats,
        backend=args.backend,
        seed=args.seed,
        num_synthetic_nodes=args.num_synthetic_nodes,
        all_origins=args.all_origins,
        origin_batch=args.origin_batch,
        sweep_lanes=args.sweep_lanes,
        checkpoint_path=args.checkpoint_path,
        resume_path=args.resume_path,
        checkpoint_every_s=args.checkpoint_every_s,
        device_timeout_s=args.device_timeout_s,
        device_retries=args.device_retries,
        on_device_failure=args.on_device_failure,
        influx_spool=args.influx_spool,
        mesh_devices=args.mesh_devices,
        mesh_node_shards=args.mesh_node_shards,
        jax_profile_dir=args.jax_profile_dir,
        run_report_path=args.run_report_path,
        memwatch_interval_s=args.memwatch_interval_s,
        capacity_harvest=args.capacity_harvest,
        health=args.health,
        health_topk=args.health_topk,
        engine_representation=args.engine_representation,
        trace_dir=args.trace_dir,
        trace_origins=args.trace_origins,
        trace_prune_cap=args.trace_prune_cap,
        compilation_cache_dir=args.compilation_cache_dir,
        telemetry_port=args.telemetry_port,
        event_log=args.event_log,
        serve=args.serve,
        serve_lanes=args.serve_lanes,
        serve_block_rounds=args.serve_block_rounds,
        serve_memory_budget=args.serve_memory_budget,
        serve_max_queue=args.serve_max_queue,
        serve_spool_dir=args.serve_spool_dir,
        serve_max_requests=args.serve_max_requests,
        serve_idle_timeout_s=args.serve_idle_timeout_s,
    )


def find_nth_largest_node(n, items):
    """Min-heap nth-largest-stake selection (gossip_main.rs:279-290).

    ``items``: [(key, stake)]. Returns the first item whose stake equals the
    nth largest stake value (duplicates counted separately).
    """
    import heapq
    if n <= 0:
        return None
    heap = []
    for _, stake in items:
        if len(heap) < n:
            heapq.heappush(heap, stake)
        elif stake >= heap[0]:
            heapq.heapreplace(heap, stake)
    if not heap:
        return None
    target = heap[0]
    for item in items:
        if item[1] == target:
            return item
    return None


def load_cluster_accounts(config: Config, json_rpc_url: str):
    """Resolve the account source (gossip_main.rs:302-328) -> ({pk: stake},
    source label)."""
    reg = get_registry()
    with reg.span("ingest"):
        if config.num_synthetic_nodes > 0:
            rng = ChaChaRng.from_seed_byte(config.seed % 256)
            accounts = synthetic_accounts(config.num_synthetic_nodes, rng)
            label = f"synthetic:{config.num_synthetic_nodes}"
        elif config.accounts_from_file:
            if not config.account_file:
                log.error("need --account-file <path> with "
                          "--accounts-from-yaml")
                raise SystemExit(-1)
            log.info("Reading %s", config.account_file)
            accounts = load_accounts_yaml(config.account_file)
            label = config.account_file
        else:
            url = get_json_rpc_url(json_rpc_url)
            log.info("json_rpc_url: %s", url)
            accounts = fetch_vote_accounts_rpc(url)
            label = url
        accounts = filter_accounts(accounts, config.filter_zero_staked_nodes)
        log_cluster_summary(accounts)
    reg.set_info("num_nodes", len(accounts))
    reg.set_info("account_source", label)
    return accounts, label


# --------------------------------------------------------------------------
# backends
# --------------------------------------------------------------------------

def _run_oracle_backend(config: Config, accounts, origin_pubkey, stats,
                        dp_queue, sim_iter, start_ts):
    """The reference's per-iteration loop, verbatim, on the CPU oracle
    (gossip_main.rs:425-565)."""
    from .oracle.cluster import Cluster, Node

    if config.checkpoint_path:
        log.warning("WARNING: --checkpoint-path is supported by the tpu "
                    "backend only; the oracle backend will not write %s",
                    config.checkpoint_path)
    if config.health:
        log.warning("WARNING: --health digests come from the engine's "
                    "on-device planes (tpu backend) or the traffic "
                    "oracle; the single-origin oracle backend leaves the "
                    "node_health report section disabled")
    reg = get_registry()
    reg.set_info("platform", "oracle")
    rng = ChaChaRng.from_seed_byte(config.seed % 256)
    stakes = dict(accounts)
    index = NodeIndex.from_stakes(accounts)
    nodes = [Node(pk, stake) for pk, stake in accounts.items()]
    node_map = {nd.pubkey: nd for nd in nodes}
    log.info("Simulating Gossip and setting active sets. Please wait.....")
    with reg.span("engine/init"):
        for node in nodes:
            node.initialize_gossip(rng, stakes, config.gossip_active_set_size)
    log.info("Simulation Complete!")

    impair = None
    if config.wants_delivery_stats:
        # also built with all-zero knobs for an impairment sweep's baseline
        # point, where it classifies every push as delivered
        from .faults import FaultInjector
        impair = FaultInjector(
            index, seed=config.seed,
            packet_loss_rate=config.packet_loss_rate,
            churn_fail_rate=config.churn_fail_rate,
            churn_recover_rate=config.churn_recover_rate,
            partition_at=config.partition_at, heal_at=config.heal_at)

    # pull (anti-entropy) phase driver (pull.py) — the identical stateless
    # spec the engine's round/pull block implements
    pull_oracle = _make_pull_oracle(config, index)

    tracer = collector = None
    if config.trace_dir:
        from .obs.trace import OracleTraceCollector
        tracer = _make_trace_writer(
            config, index, [index.index_of(origin_pubkey)],
            backend="oracle")
        if tracer is not None:
            collector = OracleTraceCollector(
                index, origin_pubkey,
                push_fanout=min(config.gossip_push_fanout,
                                config.gossip_active_set_size),
                active_set_size=config.gossip_active_set_size,
                prune_cap=tracer.manifest["prune_cap"],
                gossip_mode=config.gossip_mode,
                pull_slots=tracer.manifest["pull_slots"])

    def _flush_trace():
        flushed = collector.flush()
        if flushed is not None:
            with reg.span("trace/write"):
                seg = tracer.add_block(*flushed)
            _push_sim_trace_point(dp_queue, sim_iter, start_ts, seg)

    # pull-only mode: the push phase emits nothing (fanout 0 truncates every
    # push list), mirroring the engine's has_push=False gating
    cluster = Cluster(config.gossip_push_fanout
                      if config.gossip_mode != "pull" else 0)
    hb = Heartbeat(config.gossip_iterations, label="oracle rounds",
                   unit="iter")
    for it in range(config.gossip_iterations):
        t_it = time.perf_counter()
        if it % 10 == 0:
            log.info("GOSSIP ITERATION: %s", it)
            hb.beat(it)
            _push_config_point(config, dp_queue, sim_iter, start_ts)
        if config.test_type == Testing.FAIL_NODES and it == config.when_to_fail:
            cluster.fail_nodes(config.fraction_to_fail, nodes, rng)
            stats.set_failed_nodes(cluster.failed_nodes)
        if impair is not None:
            impair.begin_round(it)
            if impair.has_churn:
                cluster.apply_churn(impair, it, node_map)
        trace_this = collector is not None and it >= config.warm_up_rounds
        if trace_this:
            # PRE-round snapshot: the active sets/pruned bits verb 1 is
            # about to push through (the engine captures the same instant)
            collector.begin_round(cluster, node_map)
        cluster.run_gossip(origin_pubkey, stakes, node_map, impair)
        adaptive_pre = None
        if pull_oracle is not None:
            # adaptive mode: capture the direction bit in effect BEFORE
            # run_round's end-of-round switch update (the engine's
            # adaptive_pull_active row)
            if config.gossip_mode == "adaptive":
                adaptive_pre = bool(pull_oracle.pull_active)
                if collector is not None:
                    collector.adaptive_on = adaptive_pre
            # anti-entropy exchange against this round's push outcome
            cluster.run_pull(pull_oracle, it, index, node_map)
        cluster.consume_messages(origin_pubkey, nodes)
        cluster.send_prunes(origin_pubkey, nodes, config.prune_stake_threshold,
                            config.min_ingress_nodes, stakes)
        cluster.prune_connections(node_map, stakes)
        if log.isEnabledFor(logging.DEBUG):
            # the reference's debug-level dumps (gossip_main.rs:501-503,
            # gossip.rs:365-431; workflow in README.md:274-354)
            cluster.print_hops()
            cluster.print_node_orders()
            cluster.print_mst()
            cluster.print_pushes()
            cluster.print_prunes()
        rotated = cluster.chance_to_rotate(rng, nodes,
                                           config.gossip_active_set_size,
                                           stakes,
                                           config.probability_of_rotation)
        if trace_this:
            collector.end_round(it, cluster, node_map, rotated)
            if (it + 1 - config.warm_up_rounds) % 256 == 0:
                _flush_trace()
        if it >= config.warm_up_rounds:
            # measured simulation compute only — warm-up rounds and the
            # stats harvest below stay out, mirroring the TPU path's
            # engine/rounds vs stats/harvest split
            reg.record("engine/rounds", time.perf_counter() - t_it)
            reg.add("origin_iters", 1)
        if it + 1 == config.warm_up_rounds:
            cluster.clear_message_counts()
        post_heal = config.heal_at >= 0 and it >= config.heal_at
        if post_heal or it >= config.warm_up_rounds:
            coverage, n_stranded = cluster.coverage(stakes)
        if post_heal:
            # recovery metric sees every post-heal round, warm-up included
            stats.note_post_heal_coverage(it, coverage)
        if it >= config.warm_up_rounds:
            t_h = time.perf_counter()
            steady = it - config.warm_up_rounds
            if coverage < POOR_COVERAGE_THRESHOLD:
                log.warning("WARNING: poor coverage for origin: %s, %s",
                            origin_pubkey, coverage)
            stats.insert_coverage(coverage)
            stats.insert_hops_stat(cluster.hops_with_pull())
            stats.insert_stranded_nodes(cluster.stranded_nodes(), stakes)
            stats.calculate_outbound_branching_factor(cluster.pushes)
            stats.update_message_counts(cluster.egress_message_count,
                                        cluster.ingress_message_count)
            stats.update_prune_counts(cluster.prune_messages_sent)
            rmr_result = cluster.relative_message_redundancy()
            stats.insert_rmr(rmr_result[0])
            if impair is not None:
                stats.insert_delivery(impair.delivered, impair.dropped,
                                      impair.suppressed,
                                      len(cluster.failed_nodes))
            if pull_oracle is not None:
                pr = cluster.pull
                stats.insert_pull(pr.requests, pr.responses, pr.misses,
                                  pr.dropped, pr.suppressed,
                                  len(pr.rescued))
            if adaptive_pre is not None:
                sw = pull_oracle.switch_rounds
                stats.insert_adaptive(
                    adaptive_pre, int(bool(sw) and sw[-1][0] == it))
            _push_iteration_points(config, dp_queue, sim_iter, start_ts,
                                   stats, steady, coverage, rmr_result)
            reg.record("stats/harvest", time.perf_counter() - t_h)
            reg.add("messages_delivered", rmr_result[1])
    if collector is not None:
        _flush_trace()
        tracer.finalize()
        log.info("protocol trace written to %s", config.trace_dir)
    if impair is not None and impair.has_churn:
        stats.set_failed_nodes(cluster.failed_nodes)
    return stakes


def _run_tpu_backend(config: Config, accounts, origin_pubkey, stats,
                     dp_queue, sim_iter, start_ts):
    """The same simulation on the JAX engine: warm-up as one fused scan,
    measured rounds harvested per-iteration into the stats layer."""
    import jax
    import jax.numpy as jnp

    from .engine import init_state, make_cluster_tables, run_rounds

    reg = get_registry()
    _enable_compilation_cache(config)
    index = NodeIndex.from_stakes(accounts)
    stakes = dict(accounts)
    N = len(index)
    params = _engine_params(config, N)
    with reg.span("engine/tables"):
        tables = make_cluster_tables(index.stakes.astype(np.int64))
    reg.set_info("platform", jax.devices()[0].platform)
    reg.set_info("origin_batch", 1)
    _note_capacity_ledger(config, params)
    origin_idx = index.index_of(origin_pubkey)
    origins = jnp.asarray([origin_idx], dtype=jnp.int32)

    tracer = None
    if config.trace_dir:
        from .obs.trace import block_from_engine_rows
        tracer = _make_trace_writer(config, index, [origin_idx],
                                    backend="tpu", params=params)

    start_iter = 0
    if config.resume_path:
        from .checkpoint import restore_sim_state
        with reg.span("checkpoint/restore"):
            state, _, meta = restore_sim_state(config.resume_path, params,
                                               tables)
        start_iter = int(meta.get("iteration", 0))
        saved_cfg = meta.get("config", {})
        # any field that changes round dynamics breaks the bit-exact-
        # continuation contract; surface every drift, not just identity
        for f in ("origin_rank", "seed", "num_synthetic_nodes",
                  "gossip_push_fanout", "gossip_active_set_size",
                  "probability_of_rotation", "prune_stake_threshold",
                  "min_ingress_nodes", "warm_up_rounds",
                  "fraction_to_fail", "when_to_fail",
                  "packet_loss_rate", "churn_fail_rate",
                  "churn_recover_rate", "partition_at", "heal_at"):
            if f in saved_cfg and saved_cfg[f] != getattr(config, f):
                log.warning("WARNING: resuming with %s=%s but checkpoint "
                            "was written with %s=%s — continuation is NOT "
                            "bit-exact with a full run under the new value",
                            f, getattr(config, f), f, saved_cfg[f])
        log.info("Resumed simulation state from %s at iteration %s",
                 config.resume_path, start_iter)
        if start_iter >= config.gossip_iterations:
            # do NOT fall through: the save paths below would rewrite the
            # checkpoint's iteration with the smaller --iterations while
            # keeping the further-evolved state arrays
            log.warning("WARNING: checkpoint already at iteration %s >= "
                        "--iterations %s; nothing to run", start_iter,
                        config.gossip_iterations)
            return stakes
    else:
        log.info("Simulating Gossip and setting active sets. Please wait.....")
        with reg.span("engine/init"):
            state = init_state(jax.random.PRNGKey(config.seed), tables,
                               origins, params)
            jax.block_until_ready(state)
        log.info("Simulation Complete!")

    def _record_failed():
        failed_idx = np.nonzero(np.asarray(state.failed)[0])[0]
        stats.set_failed_nodes({index.pubkeys[i] for i in failed_idx})

    last_save = [float("-inf")]

    def _save_checkpoint(iteration, force=True):
        """Write the v4/v5 state npz.  Periodic block saves pass
        ``force=False`` and are throttled by --checkpoint-every-s (0 =
        every block, the pre-resilience cadence); boundary saves (end of
        run, fail event, graceful shutdown) always write."""
        if not config.checkpoint_path:
            return
        now = time.monotonic()
        if (not force and config.checkpoint_every_s > 0
                and now - last_save[0] < config.checkpoint_every_s):
            return
        from .checkpoint import save_state
        with reg.span("checkpoint/save"):
            save_state(config.checkpoint_path, state, params, config,
                       iteration=iteration)
        last_save[0] = now

    if config.resume_path and 0 <= params.fail_at < start_iter:
        _record_failed()

    warm = min(config.warm_up_rounds, config.gossip_iterations)
    if start_iter < warm:
        # match the oracle loop's progress logs + influx config cadence
        # (gossip_main.rs:426-447) without harvesting warm-up detail
        for it in range(start_iter, warm, 10):
            log.info("GOSSIP ITERATION: %s", it)
            _push_config_point(config, dp_queue, sim_iter, start_ts)
        # the run's first jitted call carries the compile; later sims in a
        # sweep hit the jit cache and record as plain warm-up compute
        cm, _ = _engine_call_span(reg, fallback="engine/warmup")
        with cm:
            state, wrows = _dispatch_supervised(
                config, "warmup-scan",
                lambda st: _blocked(run_rounds(params, tables, origins, st,
                                               warm - start_iter,
                                               start_it=start_iter)), state)
        if config.heal_at >= 0 and config.heal_at < warm:
            # post-heal coverage inside the warm-up scan still feeds the
            # recovery metric (iteration-exact, like the oracle loop and
            # the all-origins aggregate path)
            for t, cov in enumerate(
                    np.asarray(wrows["coverage"])[:, 0].tolist()):
                if start_iter + t >= config.heal_at:
                    stats.note_post_heal_coverage(start_iter + t, cov)
        if start_iter <= params.fail_at < warm:
            _record_failed()
        _save_checkpoint(warm)
    measured = config.gossip_iterations - warm
    if measured <= 0:
        _save_checkpoint(config.gossip_iterations)
        return stakes

    # Harvest measured rounds in blocks to bound host-side detail arrays.
    profile_cm = (jax.profiler.trace(config.jax_profile_dir)
                  if config.jax_profile_dir else contextlib.nullcontext())
    block = HARVEST_BLOCK
    done = max(0, start_iter - warm)
    hb = Heartbeat(measured, label=f"sim {sim_iter} measured rounds",
                   unit="iter")
    with profile_cm:
        while done < measured:
            n_it = min(block, measured - done)
            start_it = warm + done
            t_blk = time.perf_counter()
            # without a warm-up scan (warm-up 0 / resume past warm-up) the
            # first measured block carries the compile: keep it out of the
            # steady-state rounds span and throughput denominators
            cm, counted = _engine_call_span(reg)

            def _block_dispatch(st):
                st, rws = run_rounds(params, tables, origins, st, n_it,
                                     start_it=start_it, detail=True,
                                     trace=tracer is not None)
                return st, jax.tree_util.tree_map(np.asarray, rws)

            with cm:
                state, rows = _dispatch_supervised(
                    config, f"measured-block-{start_it}", _block_dispatch,
                    state)
            blk_wall = time.perf_counter() - t_blk
            if counted:
                reg.add("origin_iters", n_it)
                reg.add("messages_delivered", int(rows["delivered"].sum()))
            if tracer is not None:
                with reg.span("trace/write"):
                    seg = tracer.add_block(start_it,
                                           block_from_engine_rows(rows))
                _push_sim_trace_point(dp_queue, sim_iter, start_ts, seg)
            with reg.span("stats/harvest"):
                _warn_shape_truncation(rows, params)
                if (params.fail_at >= 0
                        and start_it <= params.fail_at < start_it + n_it):
                    _record_failed()
                for t in range(n_it):
                    it = start_it + t
                    if it % 10 == 0:
                        log.info("GOSSIP ITERATION: %s", it)
                        _push_config_point(config, dp_queue, sim_iter,
                                           start_ts)
                    _feed_measured_round(stats, rows, t, 0, it, config, index,
                                         stakes, origin_pubkey, dp_queue,
                                         sim_iter, start_ts)
            done += n_it
            hb.beat(done)
            _push_sim_perf_point(dp_queue, sim_iter, start_ts, blk_wall,
                                 n_it, 1)
            _emit_node_health(config, tables, state, dp_queue, sim_iter,
                              start_ts, warm + done, traffic=False)
            _save_checkpoint(warm + done, force=False)
            if resilience.shutdown_requested():
                # finish-the-harvest contract: this block's stats are fed
                # and the state is durably saved before exiting resumable
                _save_checkpoint(warm + done)
                if tracer is not None:
                    tracer.finalize()
                raise ResumableInterrupt(
                    f"single-run checkpoint saved at iteration "
                    f"{warm + done}; resume with --resume "
                    f"{config.checkpoint_path}"
                    if config.checkpoint_path else
                    f"run stopped at iteration {warm + done} with no "
                    f"--checkpoint-path; this simulation restarts from "
                    f"scratch")
    if tracer is not None:
        tracer.finalize()
        log.info("protocol trace written to %s", config.trace_dir)
    if config.jax_profile_dir:
        log.info("jax.profiler trace written to %s", config.jax_profile_dir)

    _feed_message_counters(stats, state, 0, index)
    _emit_node_health(config, tables, state, None, sim_iter, start_ts,
                      config.gossip_iterations, traffic=False, final=True)
    if params.has_churn:
        # mirror the oracle backend: report the final churn-failed set
        _record_failed()
    _save_checkpoint(config.gossip_iterations)
    return stakes


def _feed_measured_round(stats, rows, t, col, it, config, index, stakes,
                         origin_pubkey, dp_queue, sim_iter, start_ts):
    """Insert one measured round (origin column ``col`` of harvested rows)
    into the stats layer — the reference's per-iteration stat block
    (gossip_main.rs:480-563)."""
    steady = it - config.warm_up_rounds
    coverage = float(rows["coverage"][t, col])
    if config.heal_at >= 0 and it >= config.heal_at:
        stats.note_post_heal_coverage(it, coverage)
    if coverage < POOR_COVERAGE_THRESHOLD:
        log.warning("WARNING: poor coverage for origin: %s, %s",
                    origin_pubkey, coverage)
    dist = rows["dist"][t, col]            # [N], -1 = unreached (push)
    if "pull_hop" in rows:
        # fold pull rescues into the per-node hop view (pull.py), exactly
        # like the oracle's hops_with_pull()
        ph = rows["pull_hop"][t, col]
        dist = np.where(dist >= 0, dist, ph)
    hops = np.where(dist < 0, UNREACHED, dist.astype(np.uint64))
    stranded_mask = rows["stranded_mask"][t, col]
    stranded = [index.pubkeys[i] for i in np.nonzero(stranded_mask)[0]]
    stats.insert_coverage(coverage)
    stats.insert_hops_stat(hops.tolist())
    stats.insert_stranded_nodes(stranded, stakes)
    stats.insert_branching_factor(float(rows["branching"][t, col]))
    rmr_result = (float(rows["rmr"][t, col]), int(rows["m"][t, col]),
                  int(rows["n"][t, col]))
    stats.insert_rmr(rmr_result[0])
    if config.wants_delivery_stats:
        stats.insert_delivery(int(rows["delivered"][t, col]),
                              int(rows["dropped"][t, col]),
                              int(rows["suppressed"][t, col]),
                              int(rows["failed_count"][t, col]))
    if "pull_requests" in rows:
        stats.insert_pull(int(rows["pull_requests"][t, col]),
                          int(rows["pull_responses"][t, col]),
                          int(rows["pull_misses"][t, col]),
                          int(rows["pull_dropped"][t, col]),
                          int(rows["pull_suppressed"][t, col]),
                          int(rows["pull_rescued"][t, col]))
    if "adaptive_pull_active" in rows:
        stats.insert_adaptive(int(rows["adaptive_pull_active"][t, col]),
                              int(rows["adaptive_switched"][t, col]))
    _push_iteration_points(config, dp_queue, sim_iter, start_ts,
                           stats, steady, coverage, rmr_result)


def _feed_message_counters(stats, state, col, index):
    """Message counters accumulate on-device across measured rounds; feed
    the trackers once (equals the reference's per-round cumulative
    updates)."""
    n = len(index)
    egress = np.asarray(state.egress_acc)[col]
    ingress = np.asarray(state.ingress_acc)[col]
    prunes = np.asarray(state.prune_acc)[col]
    stats.update_message_counts(
        {index.pubkeys[i]: int(egress[i]) for i in range(n)},
        {index.pubkeys[i]: int(ingress[i]) for i in range(n)})
    stats.update_prune_counts(
        {index.pubkeys[i]: int(prunes[i]) for i in range(n)})


def run_origin_rank_sweep(config: Config, json_rpc_url: str, origin_ranks,
                          stats_collection: GossipStatsCollection, dp_queue,
                          start_ts: str):
    """ORIGIN_RANK sweep as ONE origin-batched engine call (SURVEY.md §2.3
    "batch parameter grids where shapes allow").

    The serial path (gossip_main.rs:872-891) runs R full simulations; here
    the R origins ride the engine's origin axis in a single init + scan.
    Per-origin RNG streams fold the origin index exactly as a single-origin
    run does (engine/core.py init_state), so each rank's statistics are
    bit-identical to its serial run — tested in tests/test_cli.py."""
    import jax
    import jax.numpy as jnp

    from .engine import init_state, make_cluster_tables, run_rounds

    get_registry().set_info("run_path", "origin-rank-sweep")

    # Journal + state checkpoint (resilience.py; lifts the old "not
    # supported by the batched origin-rank sweep" warning): one unit per
    # measured harvest block.  A unit commits every origin column's
    # parity snapshot + the block's wire lines, alongside a v5 state npz;
    # resume restores the state + per-column stats and replays the lines.
    journal = _open_journal(
        config, "origin-rank",
        # Config carries only origin_ranks[0]; the full swept list shapes
        # every unit, so it must be part of the drift fingerprint
        {"origin_ranks": [int(r) for r in
                          origin_ranks[:config.num_simulations]]})
    if journal is not None:
        restore_pubkey_counter(journal.header_pubkey_counter())
    first_block = journal.committed_prefix() if journal is not None else 0
    feed = _unit_feed(journal, dp_queue)

    accounts, source_label = load_cluster_accounts(config, json_rpc_url)
    index = NodeIndex.from_stakes(accounts)
    stakes = dict(accounts)
    N = len(index)
    R = config.num_simulations
    configs, origin_pks = [], []
    for i in range(R):
        c = config.stepped(origin_rank=origin_ranks[i])
        if len(accounts) < c.origin_rank:
            raise SystemExit(
                f"ERROR: origin_rank larger than number of simulation "
                f"nodes. nodes: {len(accounts)}, origin_rank: {c.origin_rank}")
        configs.append(c)
        origin_pks.append(
            find_nth_largest_node(c.origin_rank, list(accounts.items()))[0])
    origins = jnp.asarray([index.index_of(pk) for pk in origin_pks],
                          dtype=jnp.int32)
    log.info("##### BATCHED ORIGIN-RANK SWEEP: %s origins in one engine "
             "call #####", R)

    params = _engine_params(config, N)
    reg = get_registry()
    _enable_compilation_cache(config)
    with reg.span("engine/tables"):
        tables = make_cluster_tables(index.stakes.astype(np.int64))
    reg.set_info("platform", jax.devices()[0].platform)
    reg.set_info("origin_batch", R)
    _note_capacity_ledger(config, params, origin_batch=R)

    stats_list = []
    for i, c in enumerate(configs):
        log.info("##### SIMULATION ITERATION: %s #####", i)
        log.info("ORIGIN: %s", origin_pks[i])
        stats = GossipStats()
        stats.set_simulation_parameters(c)
        stats.set_origin(origin_pks[i])
        stats.initialize_message_stats(stakes)
        stats.build_validator_stake_distribution_histogram(
            VALIDATOR_STAKE_DISTRIBUTION_NUM_BUCKETS, stakes)
        stats_list.append(stats)

    warm = min(config.warm_up_rounds, config.gossip_iterations)
    measured = config.gossip_iterations - warm
    block = HARVEST_BLOCK
    done = 0

    tracer = None
    if config.trace_dir:
        # one trace, one origin column per swept rank (per-origin RNG
        # streams make each column bit-identical to its serial run); on
        # resume the writer merges already-captured segments
        from .obs.trace import block_from_engine_rows
        tracer = _make_trace_writer(
            config, index, [index.index_of(pk) for pk in origin_pks],
            backend="tpu", params=params)

    if first_block > 0:
        # resume: state from the v5 npz, per-column stats from the last
        # committed unit's snapshots, wire lines replayed verbatim
        from .checkpoint import restore_sim_state
        ckpt = config.resume_path or config.checkpoint_path
        with reg.span("checkpoint/restore"):
            state, _, meta = restore_sim_state(ckpt, params, tables)
        last = journal.records[first_block - 1]
        stats_list = [restore_stats(p, configs[col], stakes)
                      for col, p in enumerate(last["sims"])]
        for b in range(first_block):
            replay_influx_lines(dp_queue,
                                journal.records[b].get("lines", []))
        done = min(first_block * block, measured)
        if int(meta.get("iteration", warm + done)) != warm + done:
            # a kill between save_state and journal.commit leaves the
            # state one block ahead of the journal; the missing block's
            # stats cannot be reconstructed, so continuing would silently
            # break the bit-exactness contract
            raise SystemExit(
                f"ERROR: checkpoint {ckpt} is at iteration "
                f"{meta.get('iteration')} but the journal holds "
                f"{first_block} committed block(s) (= iteration "
                f"{warm + done}); the run died between the state save "
                f"and the journal commit. Remove {journal.path} and "
                f"{ckpt} to start fresh.")
        log.info("resume: origin-rank sweep restored at iteration %s "
                 "(%s/%s measured rounds done)", warm + done, done,
                 measured)
    else:
        if dp_queue is not None:
            dp = InfluxDataPoint(start_ts, 0)
            dp.create_test_type_point(
                config.num_simulations, config.gossip_iterations,
                config.warm_up_rounds, config.step_size, len(accounts),
                config.probability_of_rotation, source_label,
                str(float(origin_ranks[0])), config.test_type)
            dp.create_validator_stake_distribution_histogram_point(
                stats_list[0].get_validator_stake_distribution_histogram())
            dp.set_start()
            feed.push_back(dp)

        log.info("Simulating Gossip and setting active sets. "
                 "Please wait.....")
        with reg.span("engine/init"):
            state = init_state(jax.random.PRNGKey(config.seed), tables,
                               origins, params)
            jax.block_until_ready(state)
        log.info("Simulation Complete!")

        if warm > 0:
            for it in range(0, warm, 10):
                log.info("GOSSIP ITERATION: %s", it)
            cm, _ = _engine_call_span(reg, fallback="engine/warmup")
            with cm:
                state, wrows = _dispatch_supervised(
                    config, "origin-rank-warmup",
                    lambda st: _blocked(run_rounds(params, tables, origins,
                                                   st, warm)), state)
            if config.heal_at >= 0 and config.heal_at < warm:
                # heal inside warm-up: the recovery metric still needs
                # every post-heal round (iteration-exact, like the other
                # run paths)
                cov_w = np.asarray(wrows["coverage"])        # [warm, R]
                for it in range(config.heal_at, warm):
                    for col in range(R):
                        stats_list[col].note_post_heal_coverage(
                            it, float(cov_w[it, col]))
    hb = Heartbeat(measured, label="origin-rank sweep measured rounds",
                   unit="iter")
    unit = first_block
    while done < measured:
        n_it = min(block, measured - done)
        start_it = warm + done
        t_blk = time.perf_counter()
        cm, counted = _engine_call_span(reg)

        def _block_dispatch(st):
            st, rws = run_rounds(params, tables, origins, st, n_it,
                                 start_it=start_it, detail=True,
                                 trace=tracer is not None)
            return st, jax.tree_util.tree_map(np.asarray, rws)

        with cm:
            state, rows = _dispatch_supervised(
                config, f"origin-rank-block-{unit}", _block_dispatch, state)
        blk_wall = time.perf_counter() - t_blk
        if counted:
            reg.add("origin_iters", R * n_it)
            reg.add("messages_delivered", int(rows["delivered"].sum()))
        if tracer is not None:
            with reg.span("trace/write"):
                seg = tracer.add_block(start_it, block_from_engine_rows(rows))
            _push_sim_trace_point(feed, 0, start_ts, seg)
        with reg.span("stats/harvest"):
            _warn_shape_truncation(rows, params)
            for t in range(n_it):
                it = start_it + t
                if it % 10 == 0:
                    log.info("GOSSIP ITERATION: %s", it)
                for col in range(R):
                    if it % 10 == 0:
                        _push_config_point(configs[col], feed, col,
                                           start_ts)
                    _feed_measured_round(stats_list[col], rows, t, col, it,
                                         configs[col], index, stakes,
                                         origin_pks[col], feed, col,
                                         start_ts)
        done += n_it
        _push_sim_perf_point(feed, 0, start_ts, blk_wall, n_it, R)
        if journal is not None:
            from .checkpoint import save_state
            with reg.span("checkpoint/save"):
                save_state(config.checkpoint_path or config.resume_path,
                           state, params, config, iteration=warm + done,
                           resilience={
                               "journal": os.path.basename(journal.path),
                               "committed_units": unit + 1})
            journal.commit(unit, {
                "iteration": warm + done,
                "sims": [stats_unit_payload(stats_list[col])
                         for col in range(R)],
                "lines": _take_unit_lines(feed)})
            hb.note_committed(done)
        unit += 1
        check_interrupt(journal)
        hb.beat(done)

    if journal is not None:
        journal.close()
    if tracer is not None:
        tracer.finalize()
        log.info("protocol trace written to %s", config.trace_dir)
    for col in range(R):
        _feed_message_counters(stats_list[col], state, col, index)
        _finalize_sim_stats(configs[col], stats_list[col], stakes,
                            stats_collection, feed, col, start_ts)


def run_lane_sweep(config: Config, json_rpc_url: str, origin_ranks,
                   stats_collection: GossipStatsCollection, dp_queue,
                   start_ts: str):
    """A traced-knob sweep as lane-batched device programs (ISSUE 6).

    The serial sweep runs K simulations through one warm executable but
    still pays K engine calls with a host harvest between them.  Here the
    K sweep points' :class:`EngineKnobs` vectors stack onto a vmapped
    **lane** axis (engine/lanes.py) and the whole sweep executes as
    ``ceil(K / --sweep-lanes)`` batched calls — each one compiled program
    covering init-to-finish of every lane, with a single ``[K, ...]``
    device->host harvest.  Per-lane rows and final state are bit-identical
    to the serial sweep (tests/test_sweep_compile.py, tools/lane_smoke.py),
    and each lane feeds the SAME per-sim stats/report/Influx paths the
    serial loop uses, in the same sweep order.

    A lane batch that the sweep doesn't fill (K % lanes != 0) is padded by
    repeating the last point's knobs; padded lanes are computed and then
    dropped before any stats/Influx feeding, so they can never leak.

    Like the batched origin-rank sweep, the cluster is loaded ONCE and
    every sweep point runs against it (that is the point of a parameter
    sweep).  File/RPC account sources give the serial loop the same
    cluster per sim anyway; synthetic clusters advance the global pubkey
    counter per load, so serial sims technically run on freshly-numbered
    pubkeys — comparisons reset the counter per serial arm, exactly as
    tests/test_cli.py does for the origin-rank batch."""
    import jax
    import jax.numpy as jnp

    get_registry().set_info("run_path", "lane-sweep")

    from .engine import (broadcast_state, check_lane_knobs, init_state,
                         lane_state, make_cluster_tables, merge_lane_statics,
                         run_rounds_lanes, stack_knobs)
    from .stats.aggregate import lane_rows

    if config.trace_dir:
        raise SystemExit(
            "ERROR: --trace-dir is not supported with --sweep-lanes: the "
            "flight recorder captures one sim's event stream per trace and "
            "a lane batch runs K sims inside one device program. Drop "
            "--sweep-lanes to trace a serial sweep (one trace per sim).")

    K = config.num_simulations
    L = max(1, min(config.sweep_lanes, K))
    n_batches = (K + L - 1) // L
    sweep = [_stepped_sweep_config(config, i, origin_ranks)
             for i in range(K)]

    # Lane-mode resumability (resilience.py; lifts PR 6's explicit
    # guard_lane_checkpoint gap): one journal unit per lane batch.  A
    # batch commits its sims' parity snapshots + wire lines after the
    # single [K,...] harvest; resume replays committed batches and
    # recomputes from the first uncommitted one — base_state re-derives
    # from the seed, so no device state needs to be stored.
    journal = _open_journal(config, "lane-sweep")
    first_batch = journal.committed_prefix() if journal is not None else 0
    if journal is not None:
        # the synthetic cluster load below must see the counter position
        # the interrupted run recorded at sweep start (no-op on a fresh
        # journal or non-synthetic sources)
        restore_pubkey_counter(journal.header_pubkey_counter())
    feed = _unit_feed(journal, dp_queue)

    accounts, source_label = load_cluster_accounts(config, json_rpc_url)
    if len(accounts) < config.origin_rank:
        raise SystemExit(
            f"ERROR: origin_rank larger than number of simulation nodes. "
            f"nodes: {len(accounts)}, origin_rank: {config.origin_rank}")
    origin = find_nth_largest_node(config.origin_rank, list(accounts.items()))
    origin_pubkey = origin[0]
    stakes = dict(accounts)
    index = NodeIndex.from_stakes(accounts)
    N = len(index)

    params_list = [_engine_params(c, N).validate() for c, _ in sweep]
    static = merge_lane_statics([p.static_part() for p in params_list])
    knob_list = [p.knob_values() for p in params_list]
    check_lane_knobs(static, knob_list)

    reg = get_registry()
    _enable_compilation_cache(config)
    with reg.span("engine/tables"):
        tables = make_cluster_tables(index.stakes.astype(np.int64))
    reg.set_info("platform", jax.devices()[0].platform)
    reg.set_info("origin_batch", 1)
    reg.set_info("sweep_lanes", L)
    reg.set_info("lane_batches", n_batches)
    _note_capacity_ledger(config, params_list[0], lanes=L)
    origin_idx = index.index_of(origin_pubkey)
    origins = jnp.asarray([origin_idx], dtype=jnp.int32)

    log.info("##### LANE-BATCHED SWEEP: %s sims x %s lanes = %s batched "
             "engine call(s) #####", K, L, n_batches)
    log.info("ORIGIN: %s", origin_pubkey)

    # per-sweep-point stats, constructed exactly as run_simulation does so
    # the collection the serial sweep builds and this one are identical
    stats_list = []
    for c, _ in sweep:
        stats = GossipStats()
        stats.set_simulation_parameters(c)
        stats.set_origin(origin_pubkey)
        stats.initialize_message_stats(stakes)
        stats.build_validator_stake_distribution_histogram(
            VALIDATOR_STAKE_DISTRIBUTION_NUM_BUCKETS, stakes)
        stats_list.append(stats)

    total = config.gossip_iterations
    warm = min(config.warm_up_rounds, total)
    measured = total - warm
    if measured <= 0:
        # unreachable via dispatch_sweeps (_lane_sweep_blocker routes this
        # config class to the serial loop, which owns the degenerate
        # behavior); kept as a guard for direct callers
        log.warning("WARNING: no measured rounds (iterations <= warm-up-"
                    "rounds); lane sweep has nothing to harvest")
        return

    log.info("Simulating Gossip and setting active sets. Please wait.....")
    with reg.span("engine/init"):
        base_state = init_state(jax.random.PRNGKey(config.seed), tables,
                                origins, params_list[0])
        jax.block_until_ready(base_state)
    log.info("Simulation Complete!")

    profile_cm = (jax.profiler.trace(config.jax_profile_dir)
                  if config.jax_profile_dir else contextlib.nullcontext())
    hb = Heartbeat(n_batches, label="lane sweep", unit="lane batch")
    with profile_cm:
        for b in range(n_batches):
            ids = list(range(b * L, min((b + 1) * L, K)))
            if b < first_batch:
                # journal replay: committed batches feed stats/Influx
                # verbatim — never recomputed, never double-fed
                payload = journal.records[b]
                for i, sim_payload in payload.get("sims", []):
                    log.info("##### SIMULATION ITERATION: %s (replayed "
                             "from journal) #####", i)
                    _replay_finished_sim(sim_payload, sweep[int(i)][0],
                                         stakes, stats_collection)
                replay_influx_lines(dp_queue, payload.get("lines", []))
                hb.note_committed(b + 1)
                hb.beat(b + 1)
                continue
            padded = ids + [ids[-1]] * (L - len(ids))
            kstack = stack_knobs([knob_list[i] for i in padded])
            t_blk = time.perf_counter()
            # batch 1 carries the (single) compile; batches 2.. are pure
            # warm execution and feed the throughput denominators
            cm, counted = _engine_call_span(reg)

            def _lane_dispatch(base):
                sts = broadcast_state(base, L)
                sts, rws = run_rounds_lanes(static, tables, origins,
                                            sts, kstack, total,
                                            detail=True)
                return sts, jax.tree_util.tree_map(np.asarray, rws)

            with cm:
                states, rows = _dispatch_supervised(
                    config, f"lane-batch-{b}", _lane_dispatch, base_state)
            blk_wall = time.perf_counter() - t_blk
            if counted:
                reg.add("origin_iters", len(ids) * measured)
                reg.add("messages_delivered",
                        int(rows["delivered"][warm:, :len(ids)].sum()))
            with reg.span("stats/harvest"):
                for pos, i in enumerate(ids):
                    _harvest_lane(config, sweep[i], stats_list[i],
                                  lane_rows(rows, pos), lane_state(states,
                                                                   pos),
                                  params_list[i], index, stakes,
                                  origin_pubkey, feed, i, start_ts,
                                  warm, total, len(accounts), source_label)
                    _finalize_sim_stats(sweep[i][0], stats_list[i], stakes,
                                        stats_collection, feed, i,
                                        start_ts)
            _push_sim_perf_point(feed, ids[0], start_ts, blk_wall,
                                 measured, len(ids))
            if journal is not None:
                journal.commit(b, {
                    "sims": [[i, stats_unit_payload(stats_list[i])]
                             for i in ids],
                    "lines": _take_unit_lines(feed)})
                hb.note_committed(b + 1)
            check_interrupt(journal)
            hb.beat(b + 1)
    if journal is not None:
        journal.close()
    hb.finish()


def _harvest_lane(config, sweep_point, stats, lrows, lane_st, params, index,
                  stakes, origin_pubkey, dp_queue, sim_iter, start_ts,
                  warm, total, num_accounts, source_label):
    """Feed one harvested lane through the serial per-sim paths: the
    Influx preamble run_simulation emits, the warm-up cadence, every
    measured round via _feed_measured_round, and the end-of-run counters.
    ``lrows`` leaves are [total, O] (the full run, warm-up included);
    only rounds >= ``warm`` feed statistics, like the serial blocks."""
    c, start_value = sweep_point
    log.info("##### SIMULATION ITERATION: %s #####", sim_iter)
    if sim_iter == 0 and dp_queue is not None:
        dp = InfluxDataPoint(start_ts, 0)
        start = ("N/A" if c.test_type == Testing.NO_TEST
                 else str(start_value))
        dp.create_test_type_point(
            config.num_simulations, config.gossip_iterations,
            config.warm_up_rounds, config.step_size, num_accounts,
            config.probability_of_rotation, source_label, start,
            config.test_type)
        dp.create_validator_stake_distribution_histogram_point(
            stats.get_validator_stake_distribution_histogram())
        dp_queue.push_back(dp)
    if dp_queue is not None:
        dp = InfluxDataPoint(start_ts, sim_iter)
        dp.set_start()
        dp_queue.push_back(dp)

    # warm-up cadence (progress log + config point every 10 rounds), as
    # the serial TPU path emits before its warm-up scan
    for it in range(0, warm, 10):
        log.info("GOSSIP ITERATION: %s", it)
        _push_config_point(c, dp_queue, sim_iter, start_ts)
    if c.heal_at >= 0 and c.heal_at < warm:
        # heal inside warm-up: the recovery metric still sees every
        # post-heal round (iteration-exact, like the serial paths)
        cov_w = lrows["coverage"][:warm, 0]
        for it in range(c.heal_at, warm):
            stats.note_post_heal_coverage(it, float(cov_w[it]))

    _warn_shape_truncation(_lane_rows_measured(lrows, warm), params)
    for it in range(warm, total):
        if it % 10 == 0:
            log.info("GOSSIP ITERATION: %s", it)
            _push_config_point(c, dp_queue, sim_iter, start_ts)
        _feed_measured_round(stats, lrows, it, 0, it, c, index, stakes,
                             origin_pubkey, dp_queue, sim_iter, start_ts)

    if params.fail_at >= 0 or params.has_churn:
        # one-shot fail masks never change after fail_at and churn is
        # reported at end-of-run, so the final lane state carries exactly
        # what the serial path records
        failed_idx = np.nonzero(np.asarray(lane_st.failed)[0])[0]
        stats.set_failed_nodes({index.pubkeys[j] for j in failed_idx})
    _feed_message_counters(stats, lane_st, 0, index)


def _lane_rows_measured(lrows, warm):
    """The measured-round slice of a lane's full-run rows (the view the
    truncation warnings should see — warm-up truncation is counted by the
    serial path's warm scan rows too, but its rows are discarded there)."""
    return {k: v[warm:] for k, v in lrows.items()}


def _trace_replay_origins(config: Config, params, tables, index,
                          origin_sample, dp_queue, start_ts):
    """Flight-record a sampled origin subset of an --all-origins run.

    Tracing every origin of an all-origins batch is shape-prohibitive
    (rounds x origins x N x F), so the recorder replays the first
    ``--trace-origins`` origins through a blocked traced scan instead.
    Because each origin-sim's RNG stream folds only (seed, origin index,
    iteration) — never the batch composition — the replayed rounds are
    bit-identical to those origins' sims inside the batch: the trace IS the
    batch's trace for the sampled columns.  Replay time is bounded by the
    sample size and stays out of the engine/rounds throughput spans."""
    import jax
    import jax.numpy as jnp

    from .engine import init_state, run_rounds
    from .obs.trace import block_from_engine_rows

    reg = get_registry()
    tracer = _make_trace_writer(config, index, origin_sample, backend="tpu",
                                params=params)
    if tracer is None:     # no measured rounds (already warned)
        return
    origins = jnp.asarray(origin_sample, dtype=jnp.int32)
    warm = min(config.warm_up_rounds, config.gossip_iterations)
    measured = config.gossip_iterations - warm
    log.info("all-origins: flight-recording %s sampled origin(s) "
             "(bit-identical replay) into %s", len(origin_sample),
             config.trace_dir)
    with reg.span("trace/replay"):
        state = init_state(jax.random.PRNGKey(config.seed), tables, origins,
                           params)
        if warm > 0:
            state, _ = run_rounds(params, tables, origins, state, warm)
        done, block = 0, 256
        while done < measured:
            n_it = min(block, measured - done)
            state, rows = run_rounds(params, tables, origins, state, n_it,
                                     start_it=warm + done, detail=True,
                                     trace=True)
            rows = jax.tree_util.tree_map(np.asarray, rows)
            seg = tracer.add_block(warm + done, block_from_engine_rows(rows))
            _push_sim_trace_point(dp_queue, 0, start_ts, seg)
            done += n_it
    tracer.finalize()
    log.info("protocol trace written to %s", config.trace_dir)


def run_all_origins(config: Config, json_rpc_url: str, dp_queue=None,
                    start_ts: str = "0", accounts=None,
                    origin_indices=None) -> dict:
    """Origin-parallel mode (TPU extension, SURVEY.md §2.3): every node is an
    origin, vmapped in batches and sharded across the device mesh when more
    than one device is available (``Config.mesh_devices``; 0 = all).

    Emits the full aggregate stats suite from the on-device accumulators
    (coverage/RMR/hops/LDH/stranded/branching + message histograms) and the
    aggregate Influx series.  Returns a summary dict (also logged); the
    ``stats`` key carries the finalized ``AllOriginsStats``.

    ``accounts``/``origin_indices`` are injection points for tests and the
    driver's multichip dryrun, which exercises exactly this code path."""
    import jax
    import jax.numpy as jnp

    from .engine import (EngineParams, init_state, make_cluster_tables,
                         run_rounds)
    from .stats.aggregate import AllOriginsStats

    get_registry().set_info("run_path", "all-origins")
    # Journal (resilience.py): one unit per origin batch; the aggregate
    # accumulators snapshot into an .aggstate.npz sidecar at each commit,
    # so resume reloads them and re-dispatches only uncommitted batches.
    journal = _open_journal(config, "all-origins")
    if journal is not None:
        restore_pubkey_counter(journal.header_pubkey_counter())
    first_unit = journal.committed_prefix() if journal is not None else 0
    sidecar = (journal.path[: -len(".journal")] + ".aggstate.npz"
               if journal is not None else None)
    feed = _unit_feed(journal, dp_queue)

    if accounts is None:
        accounts, _ = load_cluster_accounts(config, json_rpc_url)
    reg = get_registry()
    _enable_compilation_cache(config)
    index = NodeIndex.from_stakes(accounts)
    N = len(index)
    reg.set_info("num_nodes", N)
    params = EngineParams(
        num_nodes=N,
        push_fanout=config.gossip_push_fanout,
        active_set_size=config.gossip_active_set_size,
        probability_of_rotation=config.probability_of_rotation,
        prune_stake_threshold=config.prune_stake_threshold,
        min_ingress_nodes=config.min_ingress_nodes,
        warm_up_rounds=config.warm_up_rounds,
        trace_prune_cap=config.trace_prune_cap,
        **_impair_params(config),
        **_pull_params(config),
    )
    with reg.span("engine/tables"):
        tables = make_cluster_tables(index.stakes.astype(np.int64))

    # ---- device mesh (parallel/mesh.py): origins axis is collective-free
    mesh = None
    n_dev = len(jax.devices())
    reg.set_info("platform", jax.devices()[0].platform)
    mesh_dev = config.mesh_devices or n_dev
    if mesh_dev > n_dev:
        log.warning("WARNING: --mesh-devices %s > %s visible device(s); "
                    "clamping", mesh_dev, n_dev)
        mesh_dev = n_dev
    node_shards = max(1, config.mesh_node_shards)
    if node_shards > 1 and (mesh_dev < node_shards
                            or mesh_dev % node_shards != 0):
        log.warning("WARNING: --mesh-node-shards %s does not divide the "
                    "%s-device mesh; falling back to origin-axis sharding "
                    "only", node_shards, mesh_dev)
        node_shards = 1
    if mesh_dev > 1:
        from .parallel import make_mesh
        mesh = make_mesh(mesh_dev, node_shards=node_shards)
        log.info("all-origins: sharding origin batches over %s devices "
                 "(%s origin-shard(s) x %s node-shard(s))",
                 mesh_dev, mesh_dev // node_shards, node_shards)

    all_origins = (np.arange(N, dtype=np.int32) if origin_indices is None
                   else np.asarray(origin_indices, dtype=np.int32))
    total_o = len(all_origins)
    batch = config.origin_batch or max(1, min(64, (1 << 22) // max(N, 1)))
    if total_o > 0:
        batch = min(batch, total_o)
    if mesh is not None:
        o_shards = mesh_dev // node_shards
        batch = max(o_shards, batch // o_shards * o_shards)
    reg.set_info("origin_batch", batch)
    _note_capacity_ledger(config, params, origin_batch=batch)
    reg.set_info("mesh_shape",
                 [mesh_dev // node_shards, node_shards]
                 if mesh is not None else [1])
    single_batch = total_o <= batch

    agg = AllOriginsStats(index, params.hist_bins)
    # node-health accumulation: per-batch SimState planes sum into one
    # [P, N] i64 stack (journal-sidecar-carried, so a resumed run keeps
    # the committed batches' counts)
    health_stack_acc = (np.zeros((len(SIM_HEALTH_METRICS), N), np.int64)
                        if config.health else None)
    health_decile_ids = (np.asarray(tables.stake_decile)
                         if config.health else None)
    hb = Heartbeat(total_o, label="all-origins", unit="origin")
    # the registry counter is process-cumulative; the summary reports this
    # run's delta so library callers invoking run_all_origins repeatedly
    # (tests, the driver dryrun) don't inherit earlier runs' padding
    padded_before = reg.counter("padded_sims")
    padded_restored = 0
    skip_lo = 0
    if first_unit > 0:
        stored_batch = int(journal.records[0].get("batch", batch))
        if stored_batch != batch:
            raise SystemExit(
                f"ERROR: --resume origin batch {batch} does not match the "
                f"journal's {stored_batch} (different --origin-batch / "
                f"mesh?); remove {journal.path} to start fresh")
        sd = _load_agg_sidecar(sidecar)
        sidecar_units = int(sd.pop("committed_units", first_unit))
        if sidecar_units == first_unit + 1:
            # killed between the sidecar save and the journal commit: the
            # sidecar already folded batch `first_unit`, so commit the
            # missing record now instead of re-dispatching the batch and
            # double-counting its origins in the aggregates
            log.warning("WARNING: aggregate sidecar is one batch ahead of "
                        "the journal (killed mid-commit); committing the "
                        "missing unit %s record", first_unit)
            journal.commit(first_unit, {"lo": int(first_unit * batch),
                                        "batch": int(batch)})
            first_unit += 1
        elif sidecar_units != first_unit:
            raise SystemExit(
                f"ERROR: aggregate sidecar {sidecar} holds "
                f"{sidecar_units} committed batch(es) but the journal "
                f"holds {first_unit}; the two artifacts cannot be "
                f"reconciled. Remove {journal.path} and {sidecar} to "
                f"start fresh.")
        padded_restored = int(sd.pop("padded_sims", 0))
        restored_health = sd.pop("node_health_stack", None)
        if health_stack_acc is not None and restored_health is not None:
            health_stack_acc += np.asarray(restored_health, np.int64)
        agg.load_state_dict(sd)
        for b in range(first_unit):
            replay_influx_lines(dp_queue,
                                journal.records[b].get("lines", []))
        skip_lo = first_unit * batch
        hb.note_committed(min(skip_lo, total_o))
        hb.beat(min(skip_lo, total_o))
        log.info("resume: all-origins restored %s committed batch(es) "
                 "(%s/%s origins) from %s", first_unit,
                 min(skip_lo, total_o), total_o, sidecar)
    t0 = time.time()

    def _dispatch(lo):
        """Launch one origin batch (init + rounds) without waiting on the
        device.  Every chunk — including the tail — is padded to the full
        ``batch`` width so the whole run compiles exactly one batch shape;
        padded sims run on origin 0 and are sliced off before aggregation
        (``padded_sims`` counts them in the run report)."""
        chunk = all_origins[lo:lo + batch]
        n_valid = len(chunk)
        if n_valid < batch:
            # counted at harvest, not here: a supervised retry re-runs
            # this dispatch and would double-count the padding
            chunk = np.concatenate(
                [chunk, np.zeros(batch - n_valid, np.int32)])
        origins = jnp.asarray(chunk, dtype=jnp.int32)
        with reg.span("engine/init"):
            state = init_state(jax.random.PRNGKey(config.seed), tables,
                               origins, params)
        if mesh is not None:
            from .parallel import shard_sim
            state, origins = shard_sim(mesh, state, origins,
                                       shard_nodes=node_shards > 1)
        # Span conventions (obs/report.py): the first batch's call carries
        # the compile (host-blocking at dispatch) and records under
        # engine/compile; later batches dispatch asynchronously and their
        # device time records under engine/rounds at harvest.  A
        # single-batch run has no steady-state batch to time, so it records
        # under engine/rounds with the compile embedded (the same caveat a
        # freshly-compiled bench elapsed_s has) rather than reporting zero
        # throughput.
        t_blk = time.perf_counter()
        if single_batch:
            with reg.span("engine/rounds"):
                state, rows = run_rounds(params, tables, origins, state,
                                         config.gossip_iterations)
                rows = jax.tree_util.tree_map(
                    lambda a: np.asarray(a)[..., :n_valid], rows)
            harvested = True
        else:
            # first jitted call of the PROCESS carries the compile (the
            # _engine_call_span convention) — keyed on the span count,
            # not lo == 0, so a resumed run (skip_lo > 0) still records
            # it and the supervisor's compile-carrier timeout exemption
            # expires after one batch
            cm = (reg.span("engine/compile")
                  if reg.count("engine/compile") == 0
                  else contextlib.nullcontext())
            with cm:
                state, rows = run_rounds(params, tables, origins, state,
                                         config.gossip_iterations)
            harvested = False
        counted = lo > 0 or single_batch
        return (lo, n_valid, state, rows, t_blk, time.perf_counter(),
                counted, harvested)

    # end of the last engine/rounds window: batch timing windows are
    # clamped to start no earlier than the previous one ended, so the
    # pipelined windows tile the steady state instead of overlapping
    # (their sum stays <= wall-clock)
    rounds_end = [0.0]

    def _harvest(job):
        """Block on one dispatched batch and feed the aggregates.  With
        double buffering the next batch is already queued on the device, so
        this host-side work (np.asarray transfer + stats accumulation)
        overlaps its compute instead of serializing on it."""
        lo, n_valid, state, rows, t_blk, t_disp_end, counted, harvested = job
        if n_valid < batch:
            reg.add("padded_sims", batch - n_valid)
        if harvested:
            blk_wall = time.perf_counter() - t_blk
        else:
            # engine/rounds keeps its pre-pipelining meaning — device
            # compute from dispatch-complete to results-on-host — so the
            # throughput denominators (obs/report.py) stay comparable; the
            # clamp keeps consecutive windows from double-counting the
            # overlapped host work between them
            basis = max(t_disp_end, rounds_end[0])
            rows = jax.tree_util.tree_map(
                lambda a: np.asarray(a)[..., :n_valid], rows)
            end = time.perf_counter()
            blk_wall = end - basis
            rounds_end[0] = end
            if counted:
                reg.record("engine/rounds", blk_wall)
        if counted:
            reg.add("origin_iters", n_valid * config.gossip_iterations)
            reg.add("messages_delivered", int(rows["delivered"].sum()))
        with reg.span("stats/harvest"):
            state_np = jax.tree_util.tree_map(np.asarray, state)
            state_np = type(state_np)(**{
                f: getattr(state_np, f)[:n_valid] for f in state_np._fields})
            agg.add_batch(rows, state_np, config.warm_up_rounds,
                          heal_at=config.heal_at,
                          impaired=config.impairments_on,
                          pull=config.has_pull)
            if health_stack_acc is not None:
                bstack = _sim_health_stack_np(state_np)
                # in-place: the accumulator is a closed-over name
                np.add(health_stack_acc, bstack, out=health_stack_acc)
                try:
                    from .obs import health
                    dig = health.digest_stack_np(bstack, health_decile_ids,
                                                 config.health_topk)
                    _publish_node_health(
                        config, SIM_HEALTH_METRICS, dig, health_decile_ids,
                        feed, 0, start_ts, lo // batch,
                        source="all-origins", final=False)
                except Exception as e:  # pragma: no cover - telemetry only
                    log.warning("WARNING: node-health digest not emitted "
                                "(%s)", e)
        _push_sim_perf_point(feed, 0, start_ts, blk_wall,
                             config.gossip_iterations, n_valid)
        log.info("all-origins: %s/%s origins done",
                 min(lo + n_valid, total_o), total_o)
        if journal is not None:
            sd = agg.state_dict()
            sd["padded_sims"] = padded_restored + int(
                reg.counter("padded_sims") - padded_before)
            sd["committed_units"] = lo // batch + 1
            if health_stack_acc is not None:
                sd["node_health_stack"] = health_stack_acc
            _save_agg_sidecar(sidecar, sd)
            journal.commit(lo // batch, {"lo": int(lo), "batch": int(batch),
                                         "lines": _take_unit_lines(feed)})
            hb.note_committed(min(lo + n_valid, total_o))
        hb.beat(min(lo + n_valid, total_o))

    # double-buffered pipeline: dispatch batch k+1 before harvesting batch
    # k, so the host-side harvest overlaps the device compute of the next
    # batch (two batches are in flight at peak — budget device memory for
    # 2x the batch state when sizing --origin-batch).  A supervised run
    # (watchdog / cpu-fallback) serializes instead: each batch is one
    # retryable unit whose results must be on host before the next
    # dispatch, so a failed dispatch can be re-executed in isolation.
    supervised = supervision(config) is not None
    pending = None
    for lo in range(skip_lo, total_o, batch):
        if supervised:
            def _unit(_state, lo=lo):
                job = _dispatch(lo)
                jb_lo, n_valid, st, rows, t_blk, t_disp, counted, hv = job
                if not hv:
                    # materialize inside the attempt so device failures
                    # surface here, where the supervisor can retry
                    rows = jax.tree_util.tree_map(
                        lambda a: np.asarray(a)[..., :n_valid], rows)
                st = jax.tree_util.tree_map(np.asarray, st)
                return (jb_lo, n_valid, st, rows, t_blk, t_disp, counted,
                        hv)
            _harvest(_dispatch_supervised(
                config, f"origin-batch-{lo // batch}", _unit))
        else:
            job = _dispatch(lo)
            if pending is not None:
                _harvest(pending)
            pending = job
        check_interrupt(journal)
    if pending is not None:
        _harvest(pending)
        check_interrupt(journal)
    if journal is not None:
        journal.close()
    dt = time.time() - t0

    if config.trace_dir:
        if config.trace_origins <= 0:
            log.warning("WARNING: --trace-dir set with --trace-origins 0; "
                        "no trace written")
        else:
            sample = [int(o) for o in
                      all_origins[:min(config.trace_origins, total_o)]]
            _trace_replay_origins(config, params, tables, index, sample,
                                  dp_queue, start_ts)

    if agg.measured_points == 0:
        log.warning("WARNING: no measured rounds (iterations <= "
                    "warm-up-rounds); skipping stats/influx")
        return {
            "num_nodes": N, "num_origins": total_o,
            "iterations": config.gossip_iterations, "measured_points": 0,
            "coverage_mean": 0.0, "rmr_mean": 0.0, "elapsed_s": dt,
            "origin_iters_per_sec": total_o * config.gossip_iterations / dt,
            "mesh_devices": mesh_dev if mesh is not None else 1,
            "mesh_node_shards": node_shards if mesh is not None else 1,
            "padded_sims": padded_restored + int(
            reg.counter("padded_sims") - padded_before),
            "hop_clamped": 0,
            "stats": agg,
        }
    agg.finalize(config)
    if health_stack_acc is not None:
        try:
            from .obs import health
            dig = health.digest_stack_np(health_stack_acc,
                                         health_decile_ids,
                                         config.health_topk)
            _publish_node_health(config, SIM_HEALTH_METRICS, dig,
                                 health_decile_ids, None, 0, start_ts,
                                 total_o, source="all-origins", final=True)
        except Exception as e:  # pragma: no cover - telemetry-only path
            log.warning("WARNING: node-health digest not emitted (%s)", e)
    _warn_shape_truncation(
        {"inb_dropped": agg.inb_dropped, "rc_overflow": agg.rc_overflow,
         "hop_clamped": agg.hop_clamped,
         # per-node ingress summed over nodes == total delivered entries,
         # the denominator for the rc-overflow percentage
         "delivered": int(agg.ingress.sum())},
        params)
    if config.print_stats:
        agg.print_all()
    agg.emit_influx(dp_queue, start_ts)
    summary = {
        "num_nodes": N,
        "num_origins": total_o,
        "iterations": config.gossip_iterations,
        "measured_points": agg.measured_points,
        "coverage_mean": agg.coverage_stats.mean,
        "rmr_mean": agg.rmr_stats.mean,
        "elapsed_s": dt,
        "origin_iters_per_sec": total_o * config.gossip_iterations / dt,
        "mesh_devices": mesh_dev if mesh is not None else 1,
        "mesh_node_shards": node_shards if mesh is not None else 1,
        "padded_sims": padded_restored + int(
        reg.counter("padded_sims") - padded_before),
        # LDH/hop-histogram clamp guard (VERDICT r5 #7): measured hop
        # samples clamped into the top on-device bin — 0 means the
        # aggregate hop/LDH stats are exact, nonzero already warned above
        "hop_clamped": int(agg.hop_clamped),
        "stats": agg,
    }
    if config.has_pull:
        summary.update({
            "pull_requests": int(agg.total_pull_requests),
            "pull_responses": int(agg.total_pull_responses),
            "pull_misses": int(agg.total_pull_requests
                               - agg.total_pull_responses),
            "pull_dropped": int(agg.total_pull_dropped),
            "pull_suppressed": int(agg.total_pull_suppressed),
            "pull_rescued": int(agg.total_pull_rescued),
        })
    # queue-cap drops ride next to the hop-clamp count in every summary
    # line (traffic runs report real counts via run_traffic; keeping the
    # keys here too means a capped run can never be mistaken for a
    # lossless one by a dashboard reading either summary shape), split by
    # queue side like the traffic summary: ingress = receiver-cap drops,
    # egress = sender-cap deferrals
    summary["queue_dropped"] = 0
    summary["queue_dropped_ingress"] = 0
    summary["queue_deferred_egress"] = 0
    log.info("ALL-ORIGINS SUMMARY: %s",
             {k: v for k, v in summary.items() if k != "stats"})
    return summary


# --------------------------------------------------------------------------
# influx helpers
# --------------------------------------------------------------------------

def _push_sim_perf_point(dp_queue, sim_iter, start_ts, block_wall_s, n_iters,
                         n_origins):
    """Runtime-telemetry series (obs/): one point per measured round block
    with its wall time, throughput, and the sender queue depth — the live
    "is the sim keeping up / is the sink backed up" signal."""
    if dp_queue is None:
        return
    thr = n_origins * n_iters / block_wall_s if block_wall_s > 0 else 0.0
    dp = InfluxDataPoint(start_ts, sim_iter)
    dp.create_sim_perf_point(round(block_wall_s, 6), round(thr, 2),
                             len(dp_queue), n_iters)
    dp_queue.push_back(dp)


def _push_sim_trace_point(dp_queue, sim_iter, start_ts, seg):
    """Flight-recorder series: one point per trace segment flush (rounds
    captured, delivered edges, prune pairs, bytes written)."""
    if dp_queue is None or seg is None:
        return
    dp = InfluxDataPoint(start_ts, sim_iter)
    dp.create_sim_trace_point(seg["end_round"] - seg["start_round"],
                              seg["delivered_edges"], seg["prunes"],
                              seg["bytes"])
    dp_queue.push_back(dp)


def _push_config_point(config, dp_queue, sim_iter, start_ts):
    if dp_queue is None:
        return
    dp = InfluxDataPoint(start_ts, sim_iter)
    dp.create_config_point(
        config.gossip_push_fanout, config.gossip_active_set_size,
        config.origin_rank, config.prune_stake_threshold,
        config.min_ingress_nodes, config.fraction_to_fail,
        config.probability_of_rotation)
    dp_queue.push_back(dp)


def _push_iteration_points(config, dp_queue, sim_iter, start_ts, stats,
                           steady, coverage, rmr_result):
    if dp_queue is None:
        return
    dp = InfluxDataPoint(start_ts, sim_iter)
    dp.create_rmr_data_point(rmr_result)
    dp.create_data_point(coverage, "coverage")
    dp.create_hops_stat_point(stats.get_hops_stat_by_iteration(steady))
    dp.create_stranded_node_stat_point(
        stats.get_stranded_node_stats_by_iteration(steady))
    dp.create_data_point(
        stats.get_outbound_branching_factor_by_index(steady),
        "branching_factor")
    if stats.has_delivery_stats():
        dp.create_delivery_point(
            int(stats.delivered_stats.collection[-1]),
            int(stats.dropped_stats.collection[-1]),
            int(stats.suppressed_stats.collection[-1]),
            stats.failed_count_series[-1])
    if stats.has_pull_stats():
        dp.create_sim_pull_point(
            int(stats.pull_requests_stats.collection[-1]),
            int(stats.pull_responses_stats.collection[-1]),
            int(stats.pull_misses_stats.collection[-1]),
            int(stats.pull_dropped_stats.collection[-1]),
            int(stats.pull_suppressed_stats.collection[-1]),
            int(stats.pull_rescued_stats.collection[-1]))
    if stats.has_adaptive_stats():
        dp.create_sim_adaptive_point(steady, {
            "active": stats.adaptive_active_series[-1],
            "switched": stats.adaptive_switched_series[-1]})
    dp.create_iteration_point(steady, sim_iter)
    dp_queue.push_back(dp)


def _push_end_of_sim_points(config, dp_queue, sim_iter, start_ts, stats):
    if dp_queue is None:
        return
    dp = InfluxDataPoint(start_ts, sim_iter)
    c = stats.stranded_node_collection
    dp.create_stranded_iteration_point(
        c.total_stranded_iterations,
        c.stranded_iterations_per_node,
        c.mean_stranded_per_iteration,
        c.mean_stranded_iterations_per_stranded_node,
        c.median_stranded_iterations_per_stranded_node,
        c.weighted_stranded_node_mean_stake,
        c.weighted_stranded_node_median_stake)
    dp.create_histogram_point("stranded_node_histogram",
                              stats.get_stranded_node_histogram())
    dp.create_histogram_point("aggregate_hops_histogram",
                              stats.get_aggregate_hop_stat_histogram())
    dp.create_messages_point("egress_message_count",
                             stats.get_egress_messages_histogram(), sim_iter)
    dp.create_messages_point("ingress_message_count",
                             stats.get_ingress_messages_histogram(), sim_iter)
    dp.create_messages_point("prune_message_count",
                             stats.get_prune_message_histogram(), sim_iter)
    if stats.recovery_iterations is not None:
        # single-origin run: one recovery sample (mean == max; 0 with
        # unrecovered=1 when coverage never came back)
        rec = stats.recovery_iterations
        dp.create_recovery_point(1, float(max(rec, 0)), max(rec, 0),
                                 int(rec < 0))
    dp.create_iteration_point(0, sim_iter)
    dp_queue.push_back(dp)


# --------------------------------------------------------------------------
# one simulation (gossip_main.rs:292-647)
# --------------------------------------------------------------------------

def run_simulation(config: Config, json_rpc_url: str,
                   stats_collection: GossipStatsCollection,
                   dp_queue, sim_iter: int, start_ts: str,
                   start_value: float):
    log.info("##### SIMULATION ITERATION: %s #####", sim_iter)
    accounts, source_label = load_cluster_accounts(config, json_rpc_url)
    log.info("%s", config)

    if len(accounts) < config.origin_rank:
        raise SystemExit(
            f"ERROR: origin_rank larger than number of simulation nodes. "
            f"nodes: {len(accounts)}, origin_rank: {config.origin_rank}")

    origin = find_nth_largest_node(config.origin_rank, list(accounts.items()))
    origin_pubkey = origin[0]
    stakes = dict(accounts)
    log.info("ORIGIN: %s", origin_pubkey)
    log.info("Calculating the MSTs for origin: %s, stake: %s",
             origin_pubkey, stakes[origin_pubkey])

    stats = GossipStats()
    stats.set_simulation_parameters(config)
    stats.set_origin(origin_pubkey)
    stats.initialize_message_stats(stakes)
    stats.build_validator_stake_distribution_histogram(
        VALIDATOR_STAKE_DISTRIBUTION_NUM_BUCKETS, stakes)

    if sim_iter == 0 and dp_queue is not None:
        dp = InfluxDataPoint(start_ts, sim_iter)
        start = "N/A" if config.test_type == Testing.NO_TEST else str(start_value)
        dp.create_test_type_point(
            config.num_simulations, config.gossip_iterations,
            config.warm_up_rounds, config.step_size, len(accounts),
            config.probability_of_rotation, source_label, start,
            config.test_type)
        dp.create_validator_stake_distribution_histogram_point(
            stats.get_validator_stake_distribution_histogram())
        dp_queue.push_back(dp)

    if dp_queue is not None:
        dp = InfluxDataPoint(start_ts, sim_iter)
        dp.set_start()
        dp_queue.push_back(dp)

    runner = (_run_oracle_backend if config.backend == "oracle"
              else _run_tpu_backend)
    stakes = runner(config, accounts, origin_pubkey, stats, dp_queue,
                    sim_iter, start_ts)

    _finalize_sim_stats(config, stats, stakes, stats_collection, dp_queue,
                        sim_iter, start_ts)


def _finalize_sim_stats(config, stats, stakes, stats_collection, dp_queue,
                        sim_iter, start_ts):
    """End-of-simulation histograms + calculations + collection push
    (gossip_main.rs:567-645)."""
    if stats.is_empty():
        return
    _build_final_stats(config, stats, stakes)
    stats_collection.push(stats)
    _push_end_of_sim_points(config, dp_queue, sim_iter, start_ts, stats)


def _build_final_stats(config, stats, stakes):
    """The end-of-sim histogram builds + calculations alone — shared by
    the live path above and the journal replay path, which re-finalizes a
    restored snapshot instead of re-emitting its Influx points (those are
    replayed verbatim from the journal, resilience.py)."""
    stats.build_stranded_node_histogram(
        config.gossip_iterations - config.warm_up_rounds, 0,
        config.num_buckets_for_stranded_node_hist)
    if config.test_type == Testing.FAIL_NODES:
        stats.build_aggregate_hops_stats_histogram(
            int(AGGREGATE_HOPS_FAIL_NODES_HISTOGRAM_UPPER_BOUND
                * (1.0 + config.fraction_to_fail)),
            0, config.num_buckets_for_hops_stats_hist)
    elif config.test_type == Testing.MIN_INGRESS_NODES:
        stats.build_aggregate_hops_stats_histogram(
            AGGREGATE_HOPS_MIN_INGRESS_NODES_HISTOGRAM_UPPER_BOUND,
            0, config.num_buckets_for_hops_stats_hist)
    else:
        stats.build_aggregate_hops_stats_histogram(
            STANDARD_HISTOGRAM_UPPER_BOUND, 0,
            config.num_buckets_for_hops_stats_hist)
    stats.build_message_histograms(
        config.num_buckets_for_message_hist, True, stakes)
    stats.build_prune_histogram(
        config.num_buckets_for_message_hist, True, stakes)
    stats.run_all_calculations()


# --------------------------------------------------------------------------
# run journal + supervised dispatch helpers (resilience.py)
# --------------------------------------------------------------------------

def _open_journal(config: Config, kind: str, extra_key: dict | None = None):
    """The run journal a multi-unit path keeps next to the checkpoint
    path, or None when neither --checkpoint-path nor --resume was given.
    On resume, the committed-unit count is logged and the caller replays
    ``journal.records[0..committed_prefix())`` before recomputing.
    ``extra_key`` folds per-path inputs outside the Config (e.g. the
    full origin-rank list) into the drift fingerprint."""
    if (config.checkpoint_path and config.resume_path
            and config.checkpoint_path != config.resume_path):
        # the single-run npz path supports load-old/save-new; a journal
        # is one append-only file serving both roles, so a split pair
        # would silently discard the resumable units next to the old path
        raise SystemExit(
            "ERROR: journal-mode runs (sweeps, --sweep-lanes, "
            "--all-origins) need --checkpoint-path and --resume to name "
            "the SAME path; got "
            f"{config.checkpoint_path!r} vs {config.resume_path!r}")
    base = config.checkpoint_path or config.resume_path
    if not base:
        return None
    jp = journal_path(base)
    resume = bool(config.resume_path)
    if resume and not os.path.exists(jp):
        log.warning("WARNING: --resume given but journal %s does not "
                    "exist; starting the run from scratch", jp)
    journal = RunJournal(jp, run_key_from_config(config, kind, extra_key),
                         resume=resume)
    k = journal.committed_prefix()
    if k:
        get_registry().add("resilience/resumed_units", k)
        log.info("resume: journal %s holds %s committed unit(s); "
                 "replaying them verbatim and restarting at unit %s",
                 jp, k, k)
    return journal


def _save_agg_sidecar(path: str, state_dict: dict) -> None:
    """Atomically persist an AllOriginsStats.state_dict() (+ the padding
    counter) next to the journal — tmp + os.replace, same contract as
    checkpoint.save_state."""
    import tempfile
    fd, tmp = tempfile.mkstemp(suffix=".npz", prefix=".aggstate-",
                               dir=os.path.dirname(path) or ".")
    try:
        with os.fdopen(fd, "wb") as f:
            np.savez_compressed(f, **{k: np.asarray(v)
                                      for k, v in state_dict.items()})
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def _load_agg_sidecar(path: str) -> dict:
    if not os.path.exists(path):
        raise SystemExit(
            f"ERROR: --resume found a journal but no aggregate sidecar at "
            f"{path}; remove the journal to start fresh")
    with np.load(path) as z:
        return {k: z[k] for k in z.files}


def _unit_feed(journal, dp_queue):
    """The datapoint sink run paths push into: a journaling tee when a
    journal is active (so each unit's wire lines commit with it), else
    the plain queue."""
    if journal is not None and dp_queue is not None:
        return InfluxTee(dp_queue)
    return dp_queue


def _take_unit_lines(feed) -> list:
    return feed.take_unit_lines() if isinstance(feed, InfluxTee) else []


def _replay_finished_sim(payload, config, stakes, stats_collection):
    """Rebuild one journaled, *finished* sim into the collection: restore
    the parity snapshot, re-run the end-of-sim calculations (exact — they
    are pure functions of the restored series), and push in sweep order.
    Influx is NOT re-fed here; the unit's stored lines replay
    separately."""
    if not payload:
        return None
    stats = restore_stats(payload, config, stakes)
    if not stats.is_empty():
        _build_final_stats(config, stats, stakes)
        stats_collection.push(stats)
    return stats


def _dispatch_supervised(config: Config, label: str, run_fn, state=None):
    """Run one engine unit under the resilience supervisor when enabled
    (resilience.supervision), else call straight through (zero added
    work on the default path).

    ``run_fn(state)`` performs the dispatch and must materialize its
    results on the host before returning (so device failures surface
    inside the attempt).  When supervised, ``state`` is snapshotted to
    host numpy first and every attempt — retries and the CPU fallback —
    rebuilds fresh device arrays from it, because the engine donates its
    state buffers and a failed dispatch may have invalidated them."""
    policy = supervision(config)
    if policy is None:
        return run_fn(state)
    import jax
    import jax.numpy as jnp

    if policy.timeout_s > 0 and get_registry().count("engine/compile") == 0:
        # The run's FIRST jitted dispatch carries the compile (the same
        # convention _engine_call_span encodes).  A slow compile is not a
        # hung device — and XLA compiles measurably slower on a watchdog
        # thread — so the carrier runs inline, unguarded by the timeout;
        # retry + CPU fallback still cover its *errors*.  Warm dispatches
        # (every later unit, where a stall means a wedged device) get the
        # full hang watchdog.
        from .resilience import DispatchPolicy
        policy = DispatchPolicy(timeout_s=0.0, retries=policy.retries,
                                backoff_s=policy.backoff_s,
                                on_failure=policy.on_failure)

    host = (jax.tree_util.tree_map(np.asarray, state)
            if state is not None else None)

    def _attempt():
        st = (jax.tree_util.tree_map(jnp.asarray, host)
              if host is not None else None)
        return run_fn(st)

    def _fallback():
        cpu = jax.devices("cpu")[0]
        with jax.default_device(cpu):
            return _attempt()

    return supervised_call(label, _attempt, policy, cpu_fallback=_fallback)


# --------------------------------------------------------------------------
# run-report + influx-drain helpers (obs/)
# --------------------------------------------------------------------------

def _push_sim_capacity_point(dp_queue, start_ts: str) -> None:
    """End-of-run ``sim_capacity`` point (obs/capacity.py ledger totals +
    obs/memwatch.py peaks + cost-harvest peaks).  Wall-clock-valued, so
    drain_deterministic_lines drops it — the parity surface is
    unaffected whether or not capacity telemetry ran."""
    if dp_queue is None:
        return
    try:
        from .obs import capacity, memwatch
        led = get_registry().info("capacity_ledger") or {}
        cost = capacity.harvest_summary()
        mem = memwatch.snapshot()
        dp = InfluxDataPoint(start_ts)
        dp.create_sim_capacity_point({
            "ledger_total_bytes": int(led.get("total_bytes", 0)),
            "ledger_state_bytes": int(led.get("state_bytes", 0)),
            "bytes_per_node": float(led.get("bytes_per_node", 0.0)),
            "dense_bytes": int(led.get("dense_bytes", 0)),
            "peak_rss_bytes": int(mem.get("peak_rss_bytes", 0)),
            "peak_device_bytes": int(mem.get("peak_device_bytes", 0)),
            "memwatch_samples": int(mem.get("samples", 0)),
            "xla_peak_temp_bytes": int(cost.get("peak_temp_bytes", 0)),
            "xla_peak_argument_bytes": int(
                cost.get("peak_argument_bytes", 0)),
            "xla_flops": float(cost.get("flops", 0.0)),
            "cost_harvests": int(cost.get("harvests", 0)),
        })
        dp_queue.push_back(dp)
    except Exception as e:  # pragma: no cover - telemetry-only path
        log.warning("WARNING: sim_capacity point not emitted (%s)", e)


#: node-health digest metric rows, in stack order (obs/health.py).  The
#: single-origin planes live on SimState, the traffic planes on
#: TrafficState; "deferred" is the egress-side queue drop, "queue_dropped"
#: the ingress side — the two sides the summary line reports separately.
SIM_HEALTH_METRICS = ("egress", "ingress", "prunes_sent", "prunes_recv",
                      "rescued", "stranded", "first_round_sum", "delivered")
TRAFFIC_HEALTH_METRICS = ("sent", "recv", "deferred", "queue_dropped",
                          "prunes_sent", "prunes_recv", "rescued",
                          "lat_sum", "delivered")


def _health_stack(state, *, traffic: bool):
    """[P, N] i32 device stack of the run's health metric planes, row
    order matching SIM_/TRAFFIC_HEALTH_METRICS.  SimState planes are
    [O, N] — the origin axis sums on device, so the host never transfers
    an O(N)-per-origin array."""
    import jax.numpy as jnp
    if traffic:
        return jnp.stack([
            state.sent_acc, state.recv_acc, state.defer_acc,
            state.qdrop_acc, state.prune_acc, state.health_prune_recv,
            state.health_rescued_acc, state.health_lat_acc,
            state.health_del_acc])
    fr = state.health_first_round      # round+1 encoding, 0 = unreached
    rows = [state.egress_acc, state.ingress_acc, state.prune_acc,
            state.health_prune_recv, state.pull_rescued_acc,
            state.stranded_acc, jnp.maximum(fr - 1, 0),
            (fr > 0).astype(jnp.int32)]
    return jnp.stack([jnp.sum(r, axis=0, dtype=jnp.int32) for r in rows])


def _sim_health_stack_np(state) -> np.ndarray:
    """Host twin of ``_health_stack(traffic=False)`` over an already-
    materialized (numpy) SimState batch -> [P, N] i64 (the all-origins
    path sums these per-batch stacks across the whole origin axis)."""
    fr = np.asarray(state.health_first_round, np.int64)
    rows = [np.asarray(state.egress_acc, np.int64),
            np.asarray(state.ingress_acc, np.int64),
            np.asarray(state.prune_acc, np.int64),
            np.asarray(state.health_prune_recv, np.int64),
            np.asarray(state.pull_rescued_acc, np.int64),
            np.asarray(state.stranded_acc, np.int64),
            np.maximum(fr - 1, 0), (fr > 0).astype(np.int64)]
    return np.stack([r.sum(axis=0) for r in rows])


def _health_latency_table(names, dig, decile_sizes):
    """Decile coverage-latency table: per-decile mean first-delivery
    latency (traffic: lat_sum/delivered; sim: first_round_sum/delivered)
    plus node counts, so the low-stake deciles' first-delivery gap is
    directly readable from the report."""
    i_lat = names.index("lat_sum" if "lat_sum" in names
                        else "first_round_sum")
    i_del = names.index("delivered")
    lat = dig["deciles"][i_lat]
    delivered = dig["deciles"][i_del]
    return {
        "decile_nodes": [int(x) for x in decile_sizes],
        "lat_sum_deciles": [int(x) for x in lat],
        "delivered_deciles": [int(x) for x in delivered],
        "mean_latency_deciles": [
            round(float(s) / float(d), 4) if d else 0.0
            for s, d in zip(lat, delivered)],
    }


def _emit_node_health(config, tables, state, dp_queue, sim_iter, start_ts,
                      block: int, *, traffic: bool, final: bool = False):
    """Per-block node-health digest (obs/health.py): ONE extra device
    dispatch whose host harvest is [10,·]/[k,·] arrays, emitted as a
    ``sim_node_health`` point; ``final`` additionally stamps the
    run-report ``node_health`` section into registry info.  A telemetry
    failure must never kill a run."""
    if not config.health:
        return
    try:
        from .obs import health
        names = TRAFFIC_HEALTH_METRICS if traffic else SIM_HEALTH_METRICS
        dig = health.digest_stack(_health_stack(state, traffic=traffic),
                                  tables.stake_decile,
                                  config.health_topk)
        _publish_node_health(config, names, dig,
                             np.asarray(tables.stake_decile), dp_queue,
                             sim_iter, start_ts, block,
                             source="traffic" if traffic else "sim",
                             final=final)
    except Exception as e:  # pragma: no cover - telemetry-only path
        log.warning("WARNING: node-health digest not emitted (%s)", e)


def _publish_node_health(config, names, dig, decile_ids, dp_queue, sim_iter,
                         start_ts, block, *, source, final):
    """Shared back half of the health emitters: the per-block
    ``sim_node_health`` point and (on ``final``) the run-report section
    stamp.  ``dig`` comes from digest_stack (engine) or digest_stack_np
    (oracle) — bit-identical by construction."""
    from .obs import health
    k = config.health_topk
    if dp_queue is not None:
        dp = InfluxDataPoint(start_ts, sim_iter)
        dp.create_sim_node_health_point(
            block, health.influx_values(names, dig, topk=k))
        dp_queue.push_back(dp)
    if final:
        sizes = np.bincount(np.asarray(decile_ids),
                            minlength=health.NUM_DECILES)
        section = health.build_node_health_section(
            names, dig, enabled=True, topk=k, source=source,
            latency=_health_latency_table(names, dig, sizes))
        get_registry().set_info("node_health", section)


def _drain_influx(dp_queue, influx_thread, start_ts: str = "0",
                  emit_capacity: bool = False):
    """Push the end sentinel, drain the reporter thread, and surface the
    sender's delivery accounting (points sent / dropped / retries) at
    end-of-run instead of only inside the drain log.  ``emit_capacity``
    (main()'s end-of-run drains) rides the run's ``sim_capacity`` point
    out just before the sentinel."""
    try:
        # every main() exit passes through here: close the footprint
        # series before the capacity point / run report read it
        from .obs import memwatch as _mw
        _mw.stop()
    except Exception:  # pragma: no cover
        pass
    if dp_queue is None:
        return None
    if emit_capacity:
        _push_sim_capacity_point(dp_queue, start_ts)
    dp = InfluxDataPoint()
    dp.set_last_datapoint()
    dp_queue.push_back(dp)
    if influx_thread is None:
        return None
    with get_registry().span("influx/drain"):
        influx_thread.join()
    sender = influx_thread.sender_stats()
    sender["queue_depth_at_exit"] = len(dp_queue)
    log.info("influx sender: %s point(s) sent, %s dropped, %s spooled, "
             "%s transient-failure retr%s", sender["points_sent"],
             sender["dropped_points"], sender.get("spooled_points", 0),
             sender["retries"],
             "y" if sender["retries"] == 1 else "ies")
    return sender


def _collection_summaries(collection):
    """(stats, faults) run-report sections from a finished sweep
    collection; (None, None) when nothing was measured."""
    sims = [s for s in collection.collection if not s.is_empty()]
    if not sims:
        return None, None
    stats = {
        "num_simulations": len(sims),
        "coverage_mean": float(np.mean([s.coverage_stats.mean
                                        for s in sims])),
        "rmr_mean": float(np.mean([s.rmr_stats.mean for s in sims])),
    }
    delivery = [s for s in sims if s.has_delivery_stats()]
    faults = None
    if delivery:
        faults = {
            "delivered": int(sum(sum(s.delivered_stats.collection)
                                 for s in delivery)),
            "dropped": int(sum(sum(s.dropped_stats.collection)
                               for s in delivery)),
            "suppressed": int(sum(sum(s.suppressed_stats.collection)
                                  for s in delivery)),
            "failed_final": int(max((s.failed_count_series[-1]
                                     for s in delivery
                                     if s.failed_count_series), default=0)),
        }
    pulls = [s for s in sims if s.has_pull_stats()]
    if pulls:
        # run-report pull section rides in the free-form stats dict
        # (obs/report.py schema unchanged)
        stats["pull"] = {
            "requests": int(sum(sum(s.pull_requests_stats.collection)
                                for s in pulls)),
            "responses": int(sum(sum(s.pull_responses_stats.collection)
                                 for s in pulls)),
            "misses": int(sum(sum(s.pull_misses_stats.collection)
                              for s in pulls)),
            "dropped": int(sum(sum(s.pull_dropped_stats.collection)
                               for s in pulls)),
            "suppressed": int(sum(sum(s.pull_suppressed_stats.collection)
                                  for s in pulls)),
            "rescued": int(sum(sum(s.pull_rescued_stats.collection)
                               for s in pulls)),
        }
    adapt = [s for s in sims if s.has_adaptive_stats()]
    if adapt:
        # run-report adaptive section (single-origin path): rounds the
        # direction bit was on + the switch events it took to get there
        stats["adaptive"] = {
            "pull_active_rounds": int(sum(sum(s.adaptive_active_series)
                                          for s in adapt)),
            "switch_events": int(sum(sum(s.adaptive_switched_series)
                                     for s in adapt)),
        }
    return stats, faults


def _write_run_report(config, stats=None, faults=None, influx=None):
    if not config.run_report_path:
        return
    from .obs.report import (build_run_report, validate_run_report,
                             write_run_report)
    _sync_cache_counters()
    report = build_run_report(config, get_registry(), stats=stats,
                              influx=influx, faults=faults)
    problems = validate_run_report(report)
    if problems:  # self-check: a malformed report is a bug, not a crash
        log.warning("WARNING: run report failed schema self-check: %s",
                    problems)
    write_run_report(config.run_report_path, report)
    log.info("run report written to %s", config.run_report_path)


# --------------------------------------------------------------------------
# concurrent-traffic runs (traffic.py / engine/traffic.py — ISSUE 10)
# --------------------------------------------------------------------------

#: test types a traffic run can sweep; all five step traced EngineKnobs
#: leaves, so every traffic sweep compiles once and is lane-eligible
TRAFFIC_SWEEP_TYPES = (Testing.TRAFFIC_RATE, Testing.NODE_INGRESS_CAP,
                       Testing.PACKET_LOSS, Testing.CHURN,
                       Testing.ADAPTIVE_THRESHOLD)


def _push_sim_traffic_point(config, dp_queue, sim_iter, start_ts, it, vals):
    if dp_queue is None:
        return
    from .stats.traffic import ROUND_FIELDS
    dp = InfluxDataPoint(start_ts, sim_iter)
    dp.create_sim_traffic_point(it, {k: vals[k] for k in ROUND_FIELDS})
    dp_queue.push_back(dp)


def _push_sim_traffic_summary_point(dp_queue, sim_iter, start_ts, summary):
    if dp_queue is None:
        return
    dp = InfluxDataPoint(start_ts, sim_iter)
    dp.create_sim_traffic_summary_point(summary)
    dp_queue.push_back(dp)


def _push_sim_adaptive_point(dp_queue, sim_iter, start_ts, it, vals):
    """One sim_adaptive point per measured round (adaptive traffic mode:
    the ADAPTIVE_ROUND_FIELDS pull-rescue counters)."""
    if dp_queue is None:
        return
    dp = InfluxDataPoint(start_ts, sim_iter)
    dp.create_sim_adaptive_point(it, vals)
    dp_queue.push_back(dp)


def _traffic_oracle(config, params, stakes_np):
    """The loop-based TrafficOracle a Config selects — the engine's
    geometry fields come off the SAME EngineParams so the two backends can
    never disagree on k_inbound/rc widths."""
    from .traffic import TrafficOracle
    return TrafficOracle(
        stakes_np, seed=config.seed, impair_seed=params.impair_seed,
        traffic_values=params.traffic_values,
        traffic_rate=params.traffic_rate,
        node_ingress_cap=params.node_ingress_cap,
        node_egress_cap=params.node_egress_cap,
        traffic_stall_rounds=params.traffic_stall_rounds,
        push_fanout=params.push_fanout,
        active_set_size=params.active_set_size,
        init_draws=params.init_draws, k_inbound=params.k_inbound,
        received_cap=params.received_cap, rc_slots=params.rc_slots,
        min_num_upserts=params.min_num_upserts,
        prune_stake_threshold=params.prune_stake_threshold,
        min_ingress_nodes=params.min_ingress_nodes,
        probability_of_rotation=params.probability_of_rotation,
        rot_tries=params.rot_tries, hist_bins=params.hist_bins,
        packet_loss_rate=params.packet_loss_rate,
        churn_fail_rate=params.churn_fail_rate,
        churn_recover_rate=params.churn_recover_rate,
        partition_at=params.partition_at, heal_at=params.heal_at,
        gossip_mode=params.gossip_mode,
        adaptive_switch_threshold=params.adaptive_switch_threshold,
        adaptive_switch_hysteresis=params.adaptive_switch_hysteresis,
        pull_fanout=params.pull_fanout,
        pull_slots=(params.pull_slots_resolved if params.has_pull else 0),
        pull_bloom_fp_rate=params.pull_bloom_fp_rate)


def _feed_traffic_rows(stats, config, dp_queue, sim_iter, start_ts, rows,
                       start_it, n_it, num_nodes, lane=None):
    """Harvested traffic rows -> TrafficStats + sim_traffic Influx points
    (measured rounds only; the warm-up scan discards its rows)."""
    from .stats.traffic import ADAPTIVE_ROUND_FIELDS, ROUND_FIELDS
    from .traffic import retire_record
    sel = (lambda arr, t: arr[t] if lane is None else arr[t, lane])
    adaptive = "pull_sent" in rows
    for t in range(n_it):
        it = start_it + t
        vals = {k: int(sel(rows[k], t)) for k in ROUND_FIELDS}
        if adaptive:
            vals.update({k: int(sel(rows[k], t))
                         for k in ADAPTIVE_ROUND_FIELDS})
        stats.feed_round(it, vals)
        recs = []
        ret = np.asarray(sel(rows["ret_mask"], t))
        for m in np.nonzero(ret)[0]:
            g = lambda name: sel(rows[name], t)[m]
            recs.append(retire_record(
                int(g("ret_vid")), int(g("ret_origin")), int(g("ret_birth")),
                it, int(g("ret_holders")), num_nodes, int(g("ret_m")),
                bool(g("ret_full")), int(g("ret_hops_sum")),
                rescued=int(g("ret_rescued")), qdrops=int(g("ret_qdrop"))))
        if recs:
            stats.feed_records(recs)
        if it % 10 == 0:
            log.info("TRAFFIC ITERATION: %s (live=%s retired=%s)", it,
                     vals["live"], vals["retired"])
        _push_sim_traffic_point(config, dp_queue, sim_iter, start_ts, it,
                                vals)
        if adaptive:
            _push_sim_adaptive_point(
                dp_queue, sim_iter, start_ts, it,
                {k: vals[k] for k in ADAPTIVE_ROUND_FIELDS})


def _traffic_final_from_state(state) -> dict:
    """End-of-run accumulator summary off a TrafficState (engine side)."""
    return {
        "live_at_end": int(np.asarray(state.v_live).sum()),
        "injected": int(state.inj_acc),
        "inject_dropped": int(state.injdrop_acc),
        "retired": int(state.ret_acc),
        "converged": int(state.conv_acc),
        "deferred": int(np.asarray(state.defer_acc).sum()),
        "queue_dropped": int(np.asarray(state.qdrop_acc).sum()),
        "sent": int(np.asarray(state.sent_acc).sum()),
        "recv": int(np.asarray(state.recv_acc).sum()),
        "prunes": int(np.asarray(state.prune_acc).sum()),
    }


def _run_traffic_oracle_point(config, params, stakes_np, stats, dp_queue,
                              sim_iter, start_ts):
    """One traffic simulation on the loop-based CPU oracle."""
    reg = get_registry()
    reg.set_info("platform", "oracle")
    if config.trace_dir:
        log.warning("WARNING: traffic traces are captured by the engine; "
                    "--trace-dir is ignored on --backend oracle")
    if config.resume_path or config.checkpoint_path:
        log.warning("WARNING: traffic checkpoints are written by the tpu "
                    "backend only; --checkpoint-path/--resume ignored on "
                    "--backend oracle")
    with reg.span("engine/init"):
        oracle = _traffic_oracle(config, params, stakes_np)
    warm = config.warm_up_rounds
    totals = {k: 0 for k in ("injected", "inject_dropped", "retired",
                             "converged", "deferred", "queue_dropped",
                             "sent", "recv", "prunes")}
    adaptive = config.gossip_mode == "adaptive"
    if adaptive:
        from .stats.traffic import ADAPTIVE_ROUND_FIELDS
    health_acc = None
    if config.health:
        # oracle twin of the engine's TrafficState health planes: the
        # warm-gated host-side sum of run_round's per-node rows, digested
        # through the SAME integer math (digest_stack_np) at end of run
        from .obs.health import stake_decile_ids
        health_decile_ids = stake_decile_ids(stakes_np)
        health_acc = np.zeros((len(TRAFFIC_HEALTH_METRICS), len(stakes_np)),
                              np.int64)
    hb = Heartbeat(config.gossip_iterations, label="traffic rounds",
                   unit="iter")
    for it in range(config.gossip_iterations):
        t_it = time.perf_counter()
        tr = oracle.run_round(it)
        if it >= warm:
            reg.record("engine/rounds", time.perf_counter() - t_it)
            vals = {k: getattr(tr, k) for k in
                    ("injected", "inject_dropped", "live", "sends",
                     "deferred", "failed_target", "suppressed", "dropped",
                     "arrived", "queue_dropped", "accepted", "delivered",
                     "redundant", "prunes_sent", "retired", "converged",
                     "hop_clamped", "qdepth_max", "inflow_max")}
            if adaptive:
                vals.update({k: getattr(tr, k)
                             for k in ADAPTIVE_ROUND_FIELDS})
            stats.feed_round(it, vals)
            stats.feed_records(tr.records)
            totals["injected"] += tr.injected
            totals["inject_dropped"] += tr.inject_dropped
            totals["retired"] += tr.retired
            totals["converged"] += tr.converged
            # pull-rescue traffic joins the same totals the engine's node
            # accumulators sum (requests: requester egress + peer ingress;
            # responses: peer egress + requester ingress)
            totals["deferred"] += tr.deferred + tr.pull_deferred
            totals["queue_dropped"] += (tr.queue_dropped
                                        + tr.pull_queue_dropped)
            totals["sent"] += tr.sends + tr.pull_sent + tr.pull_responses
            totals["recv"] += (tr.accepted + tr.pull_served
                               + tr.pull_responses)
            totals["prunes"] += tr.prunes_sent
            if health_acc is not None:
                health_acc += np.stack([
                    tr.node_sent, tr.node_recv, tr.node_deferred,
                    tr.node_queue_dropped, tr.node_prune_sent,
                    tr.node_prune_recv, tr.node_rescued, tr.node_lat_sum,
                    tr.node_delivered])
            _push_sim_traffic_point(config, dp_queue, sim_iter, start_ts,
                                    it, vals)
            if adaptive:
                _push_sim_adaptive_point(
                    dp_queue, sim_iter, start_ts, it,
                    {k: vals[k] for k in ADAPTIVE_ROUND_FIELDS})
        if it % 10 == 0:
            hb.beat(it)
    if health_acc is not None:
        try:
            from .obs import health
            dig = health.digest_stack_np(health_acc, health_decile_ids,
                                         config.health_topk)
            _publish_node_health(config, TRAFFIC_HEALTH_METRICS, dig,
                                 health_decile_ids, dp_queue, sim_iter,
                                 start_ts, config.gossip_iterations,
                                 source="oracle-traffic", final=True)
        except Exception as e:  # pragma: no cover - telemetry-only path
            log.warning("WARNING: node-health digest not emitted (%s)", e)
    live = sum(sl is not None for sl in oracle.slots)
    stats.feed_final(dict(live_at_end=live, **totals))


def _run_traffic_tpu_point(config, params, stakes_np, index, stats,
                           dp_queue, sim_iter, start_ts):
    """One traffic simulation on the JAX engine: warm-up as one fused
    scan, measured rounds harvested in blocks; v6 traffic checkpoints
    (state + serialized stats) make it preemption-safe."""
    import jax

    from .engine import make_cluster_tables
    from .engine.traffic import (device_traffic_tables, init_traffic_state,
                                 run_traffic_rounds)

    reg = get_registry()
    _enable_compilation_cache(config)
    N = len(index)
    with reg.span("engine/tables"):
        tables = make_cluster_tables(stakes_np)
        ttables = device_traffic_tables(stakes_np)
    reg.set_info("platform", jax.devices()[0].platform)
    _note_capacity_ledger(config, params)

    tracer = None
    if config.trace_dir:
        from .obs.trace import TraceWriter, traffic_block_from_engine_rows
        if config.gossip_iterations <= config.warm_up_rounds:
            log.warning("WARNING: --trace-dir set but no measured rounds; "
                        "no trace written")
        else:
            tracer = TraceWriter(
                config.trace_dir, backend="tpu", num_nodes=N,
                push_fanout=min(params.push_fanout, params.active_set_size),
                active_set_size=params.active_set_size,
                prune_cap=params.split()[0].traffic_prune_cap,
                traffic_slots=params.traffic_values,
                gossip_mode=params.gossip_mode,
                pull_slots=(params.pull_slots_resolved
                            if params.has_pull else 0),
                origins=[], origin_pubkeys=[], seed=config.seed,
                warm_up_rounds=config.warm_up_rounds,
                iterations=config.gossip_iterations, config=config)

    start_iter = 0
    if config.resume_path:
        from .checkpoint import restore_traffic_state
        with reg.span("checkpoint/restore"):
            state, _, meta = restore_traffic_state(config.resume_path,
                                                   params)
        stats.load_state_dict(meta.get("traffic_stats") or {})
        start_iter = int(meta.get("iteration", 0))
        log.info("Resumed traffic state from %s at iteration %s "
                 "(%s committed round(s), %s record(s))",
                 config.resume_path, start_iter, len(stats.iterations),
                 len(stats.records))
        if start_iter >= config.gossip_iterations:
            log.warning("WARNING: checkpoint already at iteration %s >= "
                        "--iterations %s; nothing to run", start_iter,
                        config.gossip_iterations)
            stats.feed_final(_traffic_final_from_state(state))
            return
    else:
        log.info("Building the shared traffic active set....")
        with reg.span("engine/init"):
            state = init_traffic_state(stakes_np, params, config.seed)
            jax.block_until_ready(state)

    last_save = [float("-inf")]

    def _save_checkpoint(iteration, force=True):
        if not config.checkpoint_path:
            return
        now = time.monotonic()
        if (not force and config.checkpoint_every_s > 0
                and now - last_save[0] < config.checkpoint_every_s):
            return
        from .checkpoint import save_traffic_state
        with reg.span("checkpoint/save"):
            save_traffic_state(config.checkpoint_path, state, params,
                               config, iteration=iteration,
                               traffic_stats=stats.state_dict())
        last_save[0] = now

    warm = min(config.warm_up_rounds, config.gossip_iterations)
    if start_iter < warm:
        cm, _ = _engine_call_span(reg, fallback="engine/warmup")
        with cm:
            state, _ = _dispatch_supervised(
                config, "traffic-warmup",
                lambda st: _blocked(run_traffic_rounds(
                    params, tables, ttables, st, warm - start_iter,
                    start_it=start_iter)), state)
        _save_checkpoint(warm)
    measured = config.gossip_iterations - warm
    done = max(0, start_iter - warm)
    hb = Heartbeat(measured, label=f"traffic sim {sim_iter} measured "
                   "rounds", unit="iter")
    while done < measured:
        n_it = min(HARVEST_BLOCK, measured - done)
        start_it = warm + done
        t_blk = time.perf_counter()
        cm, counted = _engine_call_span(reg)

        def _block_dispatch(st):
            st, rws = run_traffic_rounds(params, tables, ttables, st, n_it,
                                         start_it=start_it,
                                         trace=tracer is not None)
            return st, jax.tree_util.tree_map(np.asarray, rws)

        with cm:
            state, rows = _dispatch_supervised(
                config, f"traffic-block-{start_it}", _block_dispatch, state)
        blk_wall = time.perf_counter() - t_blk
        if counted:
            reg.add("origin_iters", n_it)
            reg.add("messages_delivered", int(rows["accepted"].sum()))
        if tracer is not None:
            from .obs.trace import traffic_block_from_engine_rows
            with reg.span("trace/write"):
                seg = tracer.add_block(start_it,
                                       traffic_block_from_engine_rows(rows))
            _push_sim_trace_point(dp_queue, sim_iter, start_ts, seg)
        with reg.span("stats/harvest"):
            _feed_traffic_rows(stats, config, dp_queue, sim_iter, start_ts,
                               rows, start_it, n_it, N)
        done += n_it
        hb.beat(done)
        _push_sim_perf_point(dp_queue, sim_iter, start_ts, blk_wall, n_it, 1)
        _emit_node_health(config, tables, state, dp_queue, sim_iter,
                          start_ts, warm + done, traffic=True)
        _save_checkpoint(warm + done, force=False)
        if resilience.shutdown_requested():
            stats.feed_final(_traffic_final_from_state(state))
            _save_checkpoint(warm + done)
            if tracer is not None:
                tracer.finalize()
            raise ResumableInterrupt(
                f"traffic checkpoint saved at iteration {warm + done}; "
                f"resume with --resume {config.checkpoint_path}"
                if config.checkpoint_path else
                f"traffic run stopped at iteration {warm + done} with no "
                f"--checkpoint-path; a re-run starts from scratch")
    if tracer is not None:
        tracer.finalize()
        log.info("traffic trace written to %s", config.trace_dir)
    _emit_node_health(config, tables, state, None, sim_iter, start_ts,
                      config.gossip_iterations, traffic=True, final=True)
    stats.feed_final(_traffic_final_from_state(state))
    _save_checkpoint(config.gossip_iterations)


def _log_traffic_summary(label, s):
    """The traffic run summary line: per-value outcomes + queue-cap drops
    surfaced alongside the hop-clamp count (a capped run must never read
    as lossless), with the queue-drop SIDE spelled out — egress-cap
    deferrals at the sender vs ingress-cap drops at the receiver are
    different bottlenecks, and lumping them misdirects capacity tuning."""
    qd_in = s.get("queue_dropped_ingress", s["queue_dropped"])
    qd_eg = s.get("queue_deferred_egress", s["queue_deferred"])
    log.info(
        "TRAFFIC SUMMARY%s: %s values injected (%s dropped at injection), "
        "%s retired (%s converged [%s by pull rescue], %s stranded "
        "[%s starved by queue drops], %s unfinished) | "
        "coverage mean %.4f | latency mean %.2f p90 %.2f rounds | "
        "value RMR mean %.3f | queue: %s deferred egress-side (max depth "
        "%s), %s dropped ingress-side (push %s + pull %s) | loss %s, "
        "hop_clamped %s",
        label, s["values_injected"], s["inject_dropped"],
        s["values_retired"], s["values_converged"], s["values_rescued"],
        s["values_stranded"], s["values_starved_queue_drop"],
        s["values_unfinished"], s["value_coverage_mean"],
        s["value_latency_mean"], s["value_latency_p90"],
        s["value_rmr_mean"], qd_eg, s["qdepth_max"],
        qd_in, s["queue_dropped"], qd_in - s["queue_dropped"],
        s["loss_dropped"], s["hop_clamped"])
    if "adaptive_pull_sent" in s:
        log.info(
            "ADAPTIVE SUMMARY%s: %s values switched to pull | rescue "
            "requests %s sent (%s deferred, %s queue-dropped), %s "
            "responses, %s nodes rescued",
            label, s["adaptive_switched_to_pull"], s["adaptive_pull_sent"],
            s["adaptive_pull_deferred"], s["adaptive_pull_queue_dropped"],
            s["adaptive_pull_responses"], s["adaptive_pull_rescued"])


def _traffic_lane_blocker(config: Config, n_points: int):
    """None when --sweep-lanes can serve this traffic sweep, else the
    reason (mirrors _lane_sweep_blocker)."""
    if config.backend != "tpu":
        return "lane mode requires --backend tpu"
    if n_points < 2:
        return "nothing to batch (num_simulations < 2)"
    if config.test_type not in TRAFFIC_SWEEP_TYPES:
        return (f"--test-type {config.test_type.value} does not step a "
                f"traffic-sweepable knob")
    if config.trace_dir:
        return "--trace-dir captures one sim's event stream"
    if config.checkpoint_path or config.resume_path:
        return "traffic checkpoints cover single runs only"
    if config.gossip_iterations <= config.warm_up_rounds:
        return "no measured rounds (iterations <= warm-up-rounds)"
    return None


def _run_traffic_lane_sweep(config, point_cfgs, accounts, collection,
                            dp_queue, start_ts, point_starts):
    """Traffic knob sweep as lane-batched device programs: K stepped knob
    vectors vmapped into ceil(K/--sweep-lanes) batched scans, each lane
    bit-identical to its serial run (engine/lanes.py contract)."""
    import jax

    from .engine import make_cluster_tables
    from .engine.lanes import stack_knobs
    from .engine.params import merge_lane_statics
    from .engine.traffic import (broadcast_traffic_state,
                                 device_traffic_tables, init_traffic_state,
                                 run_traffic_lanes, traffic_lane_state)
    from .stats.traffic import TrafficStats

    reg = get_registry()
    reg.set_info("run_path", "traffic-lane-sweep")
    _enable_compilation_cache(config)
    index = NodeIndex.from_stakes(accounts)
    stakes_np = index.stakes.astype(np.int64)
    N = len(index)
    params_list = [_engine_params(c, N).validate() for c in point_cfgs]
    splits = [p.split() for p in params_list]
    merged = merge_lane_statics(s for s, _ in splits)
    knob_list = [k for _, k in splits]
    from .engine.lanes import check_lane_knobs
    check_lane_knobs(merged, knob_list)
    with reg.span("engine/tables"):
        tables = make_cluster_tables(stakes_np)
        ttables = device_traffic_tables(stakes_np)
    reg.set_info("platform", jax.devices()[0].platform)
    K = len(point_cfgs)
    lanes = max(1, min(config.sweep_lanes, K))
    reg.set_info("sweep_lanes", lanes)
    reg.set_info("lane_batches", (K + lanes - 1) // lanes)
    _note_capacity_ledger(config, params_list[0], lanes=lanes)
    warm = min(config.warm_up_rounds, config.gossip_iterations)
    measured = config.gossip_iterations - warm
    base_state = init_traffic_state(stakes_np, params_list[0], config.seed)
    hb = Heartbeat((K + lanes - 1) // lanes, label="traffic lane sweep",
                   unit="batch")
    done_batches = 0
    for lo in range(0, K, lanes):
        hi = min(lo + lanes, K)
        width = hi - lo
        batch_knobs = knob_list[lo:hi]
        if width < lanes:
            # tail batch: pad with the last point's knobs to keep ONE
            # compiled lane width; padded lanes are never harvested
            batch_knobs = batch_knobs + [batch_knobs[-1]] * (lanes - width)
        stacked = stack_knobs(batch_knobs)
        cm, _ = _engine_call_span(reg, fallback="engine/rounds")

        # broadcast INSIDE the supervised fn: run_traffic_lanes donates
        # its lane state, so every watchdog retry / CPU-fallback attempt
        # must rebuild fresh lanes from the (host-snapshotted) base
        def _batch_dispatch(base):
            sts = broadcast_traffic_state(base, lanes)
            if warm > 0:
                sts, _ = run_traffic_lanes(merged, tables, ttables,
                                           sts, stacked, warm)
            sts, rws = run_traffic_lanes(merged, tables, ttables,
                                         sts, stacked, measured,
                                         start_it=warm)
            return sts, jax.tree_util.tree_map(np.asarray, rws)

        with cm:
            lane_st, lrows = _dispatch_supervised(
                config, f"traffic-lane-batch-{lo // lanes}",
                _batch_dispatch, base_state)
        for lane in range(width):
            i = lo + lane
            stats = TrafficStats()
            _feed_traffic_rows(stats, point_cfgs[i], dp_queue, i, start_ts,
                               lrows, warm, measured, N, lane=lane)
            stats.feed_final(_traffic_final_from_state(
                traffic_lane_state(lane_st, lane)))
            _push_sim_traffic_summary_point(dp_queue, i, start_ts,
                                            stats.summary())
            collection.push(point_starts[i], stats)
        done_batches += 1
        hb.beat(done_batches)
        check_interrupt(None)
    hb.finish()


def run_traffic(config: Config, json_rpc_url: str, dp_queue, start_ts: str,
                collection=None):
    """The concurrent-traffic run path (--traffic-values / queue caps):
    single runs, serial sweeps over TRAFFIC_SWEEP_TYPES, and lane-batched
    sweeps under --sweep-lanes.  Returns the run-report summary dict;
    ``collection`` (a TrafficStatsCollection) receives the per-point
    TrafficStats when a caller wants the full parity surface (tests,
    tools/traffic_smoke.py)."""
    from .stats.traffic import TrafficStats, TrafficStatsCollection

    get_registry().set_info(
        "run_path", "traffic-oracle" if config.backend == "oracle"
        else "traffic")
    is_sweep = (config.test_type in TRAFFIC_SWEEP_TYPES
                and config.num_simulations > 1)
    n_points = config.num_simulations if is_sweep else 1
    if is_sweep and (config.checkpoint_path or config.resume_path):
        # every point would share ONE state file: point k+1 overwrites
        # point k's checkpoint and --resume would replay one point's
        # mid-run state into all of them
        raise ValueError(
            "--checkpoint-path/--resume cover single traffic runs only; "
            "a traffic sweep has no per-point journal yet — drop the "
            "flag or run the sweep points as separate single runs")
    if collection is None:
        collection = TrafficStatsCollection()
    point_cfgs, point_starts = [], []
    for i in range(n_points):
        c, start = (_stepped_sweep_config(config, i, [config.origin_rank])
                    if is_sweep else (config, 0.0))
        if is_sweep and config.trace_dir:
            # one event stream per point (the PR 3 generic-sweep layout)
            c = c.stepped(trace_dir=os.path.join(config.trace_dir,
                                                 f"sim{i:03d}"))
        point_cfgs.append(c)
        point_starts.append(start if is_sweep else 0.0)

    lane_mode = False
    if config.sweep_lanes > 0:
        blocker = _traffic_lane_blocker(config, n_points)
        if blocker is None:
            lane_mode = True
        else:
            log.warning("WARNING: --sweep-lanes %s ignored (%s); running "
                        "the serial traffic sweep", config.sweep_lanes,
                        blocker)

    accounts, _ = load_cluster_accounts(config, json_rpc_url)
    if lane_mode:
        _run_traffic_lane_sweep(config, point_cfgs, accounts, collection,
                                dp_queue, start_ts, point_starts)
    else:
        index = NodeIndex.from_stakes(accounts)
        stakes_np = index.stakes.astype(np.int64)
        for i, c in enumerate(point_cfgs):
            log.info("##### TRAFFIC SIMULATION: %s (%s) #####", i,
                     c.test_type)
            params = _engine_params(c, len(index)).validate()
            stats = TrafficStats()
            if c.backend == "oracle":
                _run_traffic_oracle_point(c, params, stakes_np, stats,
                                          dp_queue, i, start_ts)
            else:
                _run_traffic_tpu_point(c, params, stakes_np, index, stats,
                                       dp_queue, i, start_ts)
            _push_sim_traffic_summary_point(dp_queue, i, start_ts,
                                            stats.summary())
            collection.push(point_starts[i], stats)
            check_interrupt(None)

    summaries = collection.summaries()
    for i, s in enumerate(summaries):
        _log_traffic_summary(f" (point {i})" if n_points > 1 else "", s)
    if n_points > 1:
        # the report's stats.traffic must describe the WHOLE run: merge
        # every point's rounds/records into one TrafficStats so counters
        # sum and latency/coverage/RMR aggregate over all retired values
        # (per-point summaries stay in stats.traffic_points)
        agg = TrafficStats()
        for st in collection.collection:
            agg.iterations.extend(st.iterations)
            for k in agg.rounds:
                agg.rounds[k].extend(st.rounds[k])
            for k in agg.adaptive_rounds:
                agg.adaptive_rounds[k].extend(st.adaptive_rounds[k])
            agg.records.extend(st.records)
        agg.final = {"live_at_end": sum(
            int(st.final.get("live_at_end", 0))
            for st in collection.collection)}
        out = agg.summary()
    else:
        out = dict(summaries[-1]) if summaries else {}
        out.pop("point", None)
    report = {
        "traffic": out,
        "traffic_points": summaries if n_points > 1 else [],
        "num_points": n_points,
        "sweep_lanes": config.sweep_lanes if lane_mode else 0,
    }
    if config.gossip_mode == "adaptive":
        # run-report adaptive section: the switch configuration plus the
        # pull-rescue totals and per-cause outcome counts (adaptive.py)
        report["adaptive"] = {
            "switch_threshold": config.adaptive_switch_threshold,
            "switch_hysteresis": config.adaptive_switch_hysteresis,
            "values_rescued": out.get("values_rescued", 0),
            "values_starved_queue_drop":
                out.get("values_starved_queue_drop", 0),
            "nodes_rescued": out.get("nodes_rescued", 0),
            "switched_to_pull": out.get("adaptive_switched_to_pull", 0),
            "pull_sent": out.get("adaptive_pull_sent", 0),
            "pull_responses": out.get("adaptive_pull_responses", 0),
            "pull_rescued": out.get("adaptive_pull_rescued", 0),
            "pull_deferred": out.get("adaptive_pull_deferred", 0),
            "pull_queue_dropped":
                out.get("adaptive_pull_queue_dropped", 0),
        }
    return report


# --------------------------------------------------------------------------
# sweep dispatch (gossip_main.rs:774-951)
# --------------------------------------------------------------------------

def _stepped_sweep_config(config: Config, i: int, origin_ranks):
    """Sweep point ``i``'s (stepped config, influx start value) — the
    reference's per-sim stepping (gossip_main.rs:774-951), shared by the
    serial loop and the lane-batched path so the two can never step a
    sweep differently."""
    tt = config.test_type
    if tt == Testing.ACTIVE_SET_SIZE:
        v = config.gossip_active_set_size + i * config.step_size.as_int()
        return config.stepped(gossip_active_set_size=v), \
            float(config.gossip_active_set_size)
    if tt == Testing.PUSH_FANOUT:
        v = config.gossip_push_fanout + i * config.step_size.as_int()
        c = config.stepped(gossip_push_fanout=v)
        # fanout beyond the active set would silently cap (gossip_main.rs:812)
        if v > c.gossip_active_set_size:
            c = c.stepped(gossip_active_set_size=v)
        return c, float(config.gossip_push_fanout)
    if tt == Testing.MIN_INGRESS_NODES:
        v = config.min_ingress_nodes + i * config.step_size.as_int()
        # reference reports the stepped value here
        return config.stepped(min_ingress_nodes=v), float(v)
    if tt == Testing.PRUNE_STAKE_THRESHOLD:
        v = config.prune_stake_threshold + i * config.step_size.as_float()
        return config.stepped(prune_stake_threshold=v), \
            float(config.prune_stake_threshold)
    if tt == Testing.ORIGIN_RANK:
        return config.stepped(origin_rank=origin_ranks[i]), \
            float(origin_ranks[i])
    if tt == Testing.FAIL_NODES:
        v = config.fraction_to_fail + i * config.step_size.as_float()
        return config.stepped(fraction_to_fail=v), \
            float(config.fraction_to_fail)
    if tt == Testing.ROTATE_PROBABILITY:
        v = config.probability_of_rotation + i * config.step_size.as_float()
        return config.stepped(probability_of_rotation=v), \
            float(config.probability_of_rotation)
    if tt == Testing.PACKET_LOSS:
        v = min(config.packet_loss_rate
                + i * config.step_size.as_float(), 1.0)
        return config.stepped(packet_loss_rate=v), \
            float(config.packet_loss_rate)
    if tt == Testing.CHURN:
        # sweep the fail rate; the recover rate rides along unstepped so
        # each point probes a different steady-state failed fraction
        v = min(config.churn_fail_rate
                + i * config.step_size.as_float(), 1.0)
        return config.stepped(churn_fail_rate=v), \
            float(config.churn_fail_rate)
    if tt == Testing.PULL_FANOUT:
        # pull_fanout is a traced EngineKnobs field: steps within the
        # static pull_slots width (auto: 8) reuse one compiled
        # executable (PR 4 invariant, tests/test_pull.py)
        v = config.pull_fanout + i * config.step_size.as_int()
        return config.stepped(pull_fanout=v), float(config.pull_fanout)
    if tt == Testing.TRAFFIC_RATE:
        # traced traffic knob (traffic.py): the injection rate steps
        # within the static traffic_values slot capacity, compile-free
        v = config.traffic_rate + i * config.step_size.as_int()
        return config.stepped(traffic_rate=v), float(config.traffic_rate)
    if tt == Testing.NODE_INGRESS_CAP:
        # traced traffic knob: per-node ingress queue budget
        v = config.node_ingress_cap + i * config.step_size.as_int()
        return config.stepped(node_ingress_cap=v), \
            float(config.node_ingress_cap)
    if tt == Testing.ADAPTIVE_THRESHOLD:
        # traced adaptive knob (adaptive.py): the direction-switch
        # coverage threshold — steps reuse one compiled executable and
        # are lane-eligible on both the single-origin and traffic paths
        v = min(config.adaptive_switch_threshold
                + i * config.step_size.as_float(), 1.0)
        return config.stepped(adaptive_switch_threshold=v), \
            float(config.adaptive_switch_threshold)
    return config, 0.0  # NO_TEST


#: test types whose stepped Config field maps to a traced EngineKnobs leaf
#: — the lane-eligible sweeps (ISSUE 6).  ACTIVE_SET_SIZE / PUSH_FANOUT
#: step the static compile geometry and ORIGIN_RANK has its own batched
#: path, so they stay serial.
LANE_SWEEP_TYPES = (Testing.MIN_INGRESS_NODES, Testing.PRUNE_STAKE_THRESHOLD,
                    Testing.FAIL_NODES, Testing.ROTATE_PROBABILITY,
                    Testing.PACKET_LOSS, Testing.CHURN, Testing.PULL_FANOUT,
                    Testing.ADAPTIVE_THRESHOLD)


def _lane_sweep_blocker(config: Config):
    """None when --sweep-lanes can serve this sweep, else the reason the
    dispatcher logs before falling back to the serial loop."""
    if config.backend != "tpu":
        return "lane mode requires --backend tpu"
    if config.num_simulations < 2:
        return "nothing to batch (num_simulations < 2)"
    if config.test_type not in LANE_SWEEP_TYPES:
        return (f"--test-type {config.test_type.value} does not step a "
                f"traced engine knob; lane-eligible sweeps: "
                + ", ".join(t.value for t in LANE_SWEEP_TYPES))
    if config.gossip_iterations <= config.warm_up_rounds:
        # nothing measurable to batch; the serial loop keeps its exact
        # degenerate-case behavior (preamble Influx points, warm-up-only
        # runs, post-heal coverage) instead of a lane approximation of it
        return "no measured rounds (iterations <= warm-up-rounds)"
    return None


def dispatch_sweeps(config: Config, json_rpc_url: str, origin_ranks,
                    collection: GossipStatsCollection, dp_queue,
                    start_ts: str):
    tt = config.test_type
    if (tt == Testing.ORIGIN_RANK and config.backend == "tpu"
            and config.num_simulations > 1):
        # shapes are origin-invariant, so the whole sweep batches onto the
        # engine's origin axis (one init + one scan instead of R runs)
        run_origin_rank_sweep(config, json_rpc_url, origin_ranks,
                              collection, dp_queue, start_ts)
        return
    if config.sweep_lanes > 0:
        blocker = _lane_sweep_blocker(config)
        if blocker is None:
            # traced-knob sweep: the K points ride a vmapped lane axis as
            # ceil(K/lanes) batched device programs (engine/lanes.py)
            run_lane_sweep(config, json_rpc_url, origin_ranks, collection,
                           dp_queue, start_ts)
            return
        log.warning("WARNING: --sweep-lanes %s ignored (%s); running the "
                    "serial sweep", config.sweep_lanes, blocker)
    get_registry().set_info(
        "run_path",
        ("serial-sweep" if config.num_simulations > 1 else
         "single-oracle" if config.backend == "oracle" else "single"))
    # Serial sweep: with --checkpoint-path each completed sim is one
    # journal unit; --resume replays committed sims into stats/Influx
    # verbatim and restarts at the first uncommitted one (resilience.py).
    # Single runs (num_simulations == 1) keep the mid-scan state
    # checkpoint semantics of _run_tpu_backend instead.
    journal = (_open_journal(
        config, "serial-sweep",
        # the full rank list shapes ORIGIN_RANK units (Config holds only
        # origin_ranks[0]); harmless constant for every other test type
        {"origin_ranks": [int(r) for r in
                          origin_ranks[:config.num_simulations]]}
        if config.test_type == Testing.ORIGIN_RANK else None)
        if config.num_simulations > 1 else None)
    feed = _unit_feed(journal, dp_queue)
    first = journal.committed_prefix() if journal is not None else 0
    hb = Heartbeat(config.num_simulations, label="sweep", unit="simulation")
    for i in range(config.num_simulations):
        c, start = _stepped_sweep_config(config, i, origin_ranks)
        if config.trace_dir and config.num_simulations > 1:
            # one flight-recorder directory per swept simulation; each
            # holds its own manifest + segments
            c = c.stepped(trace_dir=os.path.join(config.trace_dir,
                                                 f"sim{i:03d}"))
        if journal is not None:
            # sim-level units own resumability; the per-sim runner must
            # not also write the single-run state npz
            c = c.stepped(checkpoint_path="", resume_path="")
        if i < first:
            payload = journal.records[i]
            # loading the cluster exactly as the live sim would keeps the
            # synthetic pubkey counter (and with it every later sim's
            # cluster) on the uninterrupted run's sequence
            accounts, _ = load_cluster_accounts(c, json_rpc_url)
            log.info("##### SIMULATION ITERATION: %s (replayed from "
                     "journal) #####", i)
            _replay_finished_sim(payload.get("sim"), c, dict(accounts),
                                 collection)
            replay_influx_lines(dp_queue, payload.get("lines", []))
            hb.note_committed(i + 1)
            hb.beat(i + 1)
            continue
        before = len(collection.collection)
        run_simulation(c, json_rpc_url, collection, feed, i, start_ts,
                       start)
        if journal is not None:
            sim_payload = (stats_unit_payload(collection.collection[-1])
                           if len(collection.collection) > before else None)
            journal.commit(i, {"sim": sim_payload,
                               "lines": _take_unit_lines(feed)})
            hb.note_committed(i + 1)
        # honored with or without a journal: a SIGTERM'd sweep stops at
        # the sim boundary either way (resume replays only if journaled)
        check_interrupt(journal)
        hb.beat(i + 1)
    if journal is not None:
        journal.close()
    if config.num_simulations > 1:
        hb.finish()


def main(argv=None) -> int:
    logging.basicConfig(
        level=logging.INFO,
        format="[%(asctime)s %(levelname)s %(name)s] %(message)s")
    argv = list(argv) if argv is not None else sys.argv[1:]
    if argv[:1] == ["serve"]:
        # subcommand alias: `python -m gossip_sim_tpu serve ...`
        argv = ["--serve"] + argv[1:]
    args = build_parser().parse_args(argv)
    config = config_from_args(args)
    # one process == one run: start the telemetry registry clean so spans,
    # counters and the run report cover exactly this invocation, and clear
    # any shutdown request a previous in-process run left behind
    get_registry().reset()
    resilience.reset_shutdown()
    # capacity observatory (obs/capacity.py + obs/memwatch.py): same
    # one-process-one-run reset, opt-in XLA cost harvest, and the live
    # footprint sampler when an interval was requested.  All three are
    # bit-invisible to the simulation (tools/capacity_smoke.py).
    from .obs import capacity as _capacity
    from .obs import memwatch as _memwatch
    from .obs import telemetry as _telemetry
    _capacity.reset_harvests()
    _capacity.set_harvest_enabled(config.capacity_harvest)
    _memwatch.reset()
    if config.memwatch_interval_s > 0:
        _memwatch.start(config.memwatch_interval_s)
    # live telemetry plane (obs/telemetry.py + obs/exporter.py, ISSUE 18):
    # same one-process-one-run reset; the structured event log opens in
    # append mode so one path spans an interrupted-and-resumed run.  The
    # baseline run-key fingerprint covers unjournaled runs; _open_journal
    # re-stamps it with the journal's own key (the join contract).
    _telemetry.reset()
    if config.event_log:
        try:
            _telemetry.get_hub().open_event_log(config.event_log)
        except OSError as e:
            log.error("ERROR: --event-log %s unwritable: %s",
                      config.event_log, e)
            return 1
    _telemetry.get_hub().set_run_key(
        run_key_from_config(config, kind="run"))
    _telemetry.emit_event("run_start", pid=os.getpid(),
                          argv=list(argv) if argv is not None
                          else sys.argv[1:])
    origin_ranks = args.origin_rank
    if any(r < 1 for r in origin_ranks):
        log.error("ERROR: --origin-rank values must be >= 1 (1 = highest "
                  "stake), got: %s", origin_ranks)
        return 1

    # origin-rank count validation (gossip_main.rs:706-716); traffic runs
    # inject their own stake-weighted origins, so the rank list is moot
    if config.traffic_on:
        pass
    elif len(origin_ranks) < config.num_simulations:
        log.error("ERROR: not enough origin ranks provided for "
                  "num_simulations! origin_ranks: %s, num_simulations: %s",
                  len(origin_ranks), config.num_simulations)
        if config.test_type == Testing.ORIGIN_RANK:
            return 1
    elif len(origin_ranks) > config.num_simulations:
        log.warning("WARNING: more origin ranks than number of simulations. "
                    "Not going to hit all origin ranks")
    elif (len(origin_ranks) > 1
          and config.test_type != Testing.ORIGIN_RANK):
        log.error("ERROR: multiple origin_ranks passed in but test type is "
                  "not OriginRank. This would end up running all simulations "
                  "with origin_rank[0]: %s", origin_ranks[0])
        return 1

    if config.test_type == Testing.PULL_FANOUT and not config.has_pull:
        log.error("ERROR: --test-type pull-fanout requires a pull-capable "
                  "--gossip-mode (pull or push-pull); mode is push, so "
                  "every sweep point would be identical")
        return 1
    if (config.test_type == Testing.ADAPTIVE_THRESHOLD
            and config.gossip_mode != "adaptive"):
        log.error("ERROR: --test-type adaptive-threshold requires "
                  "--gossip-mode adaptive; the switch knobs are inert in "
                  "mode %s, so every sweep point would be identical",
                  config.gossip_mode)
        return 1
    if (config.test_type == Testing.ADAPTIVE_THRESHOLD
            and config.num_simulations > 1):
        # the stepper clamps thresholds at 1.0 — warn when the grid
        # collapses into duplicate points instead of running them mutely
        last = (config.adaptive_switch_threshold
                + (config.num_simulations - 1)
                * config.step_size.as_float())
        if last > 1.0:
            n_dup = sum(
                1 for i in range(config.num_simulations)
                if config.adaptive_switch_threshold
                + i * config.step_size.as_float() > 1.0)
            log.warning("WARNING: adaptive-threshold sweep clamps at 1.0 "
                        "— the last %d of %d points run the identical "
                        "threshold 1.0; shrink --step-size or "
                        "--num-simulations for distinct points",
                        n_dup, config.num_simulations)

    if config.traffic_values < 1:
        log.error("ERROR: --traffic-values must be >= 1 (the default 1 "
                  "with both caps off IS the plain single-value "
                  "simulator — there is no separate off value)")
        return 1
    if config.traffic_on and config.traffic_rate < 0:
        log.error("ERROR: --traffic-rate must be >= 0")
        return 1
    if config.traffic_on and config.traffic_stall_rounds < 1:
        log.error("ERROR: --traffic-stall-rounds must be >= 1 (a value "
                  "needs at least one no-progress round to retire)")
        return 1
    if (config.test_type in (Testing.TRAFFIC_RATE, Testing.NODE_INGRESS_CAP)
            and not config.traffic_on):
        log.error("ERROR: --test-type %s requires the traffic subsystem "
                  "(--traffic-values > 1 or a queue cap); every sweep "
                  "point would be identical otherwise",
                  config.test_type.value)
        return 1
    if config.traffic_on:
        if config.all_origins:
            log.error("ERROR: --all-origins and concurrent traffic are "
                      "separate workload modes; traffic injects its own "
                      "stake-weighted origins")
            return 1
        if config.has_pull and config.gossip_mode != "adaptive":
            log.error("ERROR: the traffic subsystem models concurrent "
                      "PUSH streams; fixed --gossip-mode %s is not "
                      "supported with it — per-value pull RESCUES are: "
                      "use --gossip-mode adaptive", config.gossip_mode)
            return 1
        if config.gossip_mode == "adaptive":
            # a node-ingress-cap sweep steps the cap past the base value:
            # guard the LAST point too, not just point 0, so the bound is
            # a clean startup error instead of a mid-sweep assert
            cap_max = config.node_ingress_cap
            if (config.test_type == Testing.NODE_INGRESS_CAP
                    and config.num_simulations > 1):
                cap_max += ((config.num_simulations - 1)
                            * config.step_size.as_int())
            if cap_max >= 16384:
                log.error("ERROR: adaptive traffic requires "
                          "--node-ingress-cap < 16384 (engine sort-key "
                          "packing bound; a node-ingress-cap sweep must "
                          "keep every stepped point under it); caps that "
                          "large are equivalent to no cap — use 0")
                return 1
        allowed = TRAFFIC_SWEEP_TYPES + (Testing.NO_TEST,)
        if config.test_type not in allowed:
            log.error("ERROR: --test-type %s is not runnable in traffic "
                      "mode; traffic sweeps: %s", config.test_type.value,
                      ", ".join(t.value for t in TRAFFIC_SWEEP_TYPES))
            return 1
        is_traffic_sweep = (config.test_type in TRAFFIC_SWEEP_TYPES
                            and config.num_simulations > 1)
        if is_traffic_sweep and (config.checkpoint_path
                                 or config.resume_path):
            log.error("ERROR: --checkpoint-path/--resume cover single "
                      "traffic runs only; a traffic sweep has no "
                      "per-point journal yet — drop the flag or run the "
                      "sweep points as separate single runs")
            return 1
        if config.num_simulations > 1 and not is_traffic_sweep:
            log.warning("WARNING: --num-simulations %s ignored in traffic "
                        "mode: --test-type %s does not step a "
                        "traffic-sweepable knob (traffic sweeps: %s)",
                        config.num_simulations, config.test_type.value,
                        ", ".join(t.value for t in TRAFFIC_SWEEP_TYPES))

    if config.gossip_iterations <= config.warm_up_rounds:
        log.warning("WARNING: Gossip Iterations (%s) <= Warm Up Rounds (%s). "
                    "No stats will be recorded....",
                    config.gossip_iterations, config.warm_up_rounds)

    if config.serve:
        # gossip-as-a-service daemon (serve/, ISSUE 20): validate the
        # service geometry up front — requests can only vary traced
        # knobs, so the base config must pin a servable shape
        if config.backend != "tpu":
            log.error("ERROR: --serve requires --backend tpu")
            return 1
        if config.traffic_on or config.all_origins:
            log.error("ERROR: --serve is a single-origin scenario "
                      "service; concurrent traffic and --all-origins "
                      "are separate workload modes")
            return 1
        if config.test_type != Testing.NO_TEST:
            log.error("ERROR: --serve runs NO_TEST scenarios (each "
                      "request carries its own knobs); drop --test-type")
            return 1
        if config.gossip_iterations <= config.warm_up_rounds:
            log.error("ERROR: --serve needs --iterations > "
                      "--warm-up-rounds (a request would have nothing "
                      "measurable)")
            return 1
        if config.serve_lanes < 1:
            log.error("ERROR: --serve-lanes must be >= 1")
            return 1
        if config.serve_block_rounds < 1:
            log.error("ERROR: --serve-block-rounds must be >= 1")
            return 1
        if config.trace_dir:
            log.error("ERROR: --trace-dir is not supported with --serve "
                      "(a lane batch interleaves K requests' event "
                      "streams in one capture buffer)")
            return 1
        if config.telemetry_port < 0:
            # the daemon's intake rides the telemetry plane; bind an
            # ephemeral port when none was requested (the bound port is
            # logged, stamped into registry info, and discoverable from
            # the event log's telemetry_listen record)
            config = config.stepped(telemetry_port=0)

    start_ts = str(time.time_ns())
    log.info("############################################")
    log.info("##### START_TIME: %s ######", start_ts)
    log.info("############################################")

    dp_queue = None
    influx_thread = None
    if args.influx in ("l", "i"):
        dp_queue = DatapointQueue()
        load_dotenv()
        try:
            username = os.environ["GOSSIP_SIM_INFLUX_USERNAME"]
            password = os.environ["GOSSIP_SIM_INFLUX_PASSWORD"]
            database = os.environ["GOSSIP_SIM_INFLUX_DATABASE"]
        except KeyError as e:
            log.error("%s is not set", e.args[0])
            return 1
        influx_thread = InfluxThread.spawn(
            get_influx_url(args.influx), username, password, database,
            dp_queue, spool_path=config.influx_spool)

    # live Influx sender stats through the hub (ISSUE 18): mid-run scrapes
    # see points_sent/retries/spooled_points advance instead of waiting
    # for the end-of-run drain summary
    if influx_thread is not None:
        def _live_influx_stats(thread=influx_thread, q=dp_queue):
            stats = thread.sender_stats()
            stats["queue_depth"] = len(q)
            return stats
        _telemetry.get_hub().set_provider("influx", _live_influx_stats)

    telemetry_server = None
    if config.telemetry_port >= 0:
        from .obs.exporter import TelemetryServer
        from .obs.report import build_run_report

        def _live_status():
            # the evolving run report, assembled live on each scrape —
            # the same document --run-report writes at exit
            influx_live = None
            if influx_thread is not None:
                influx_live = _live_influx_stats()
            return build_run_report(config, get_registry(),
                                    influx=influx_live)
        telemetry_server = TelemetryServer(port=config.telemetry_port,
                                           status_fn=_live_status)
        try:
            telemetry_server.start()
        except OSError as e:
            log.error("ERROR: --telemetry-port %s unbindable: %s",
                      config.telemetry_port, e)
            return 1

    def _finish_telemetry(rc: int) -> int:
        """Seal the telemetry plane on every run-section exit: emit the
        run_end event, stop the exporter, close the event log."""
        _telemetry.emit_event("run_end", rc=int(rc))
        if telemetry_server is not None:
            telemetry_server.stop()
        _telemetry.get_hub().close_event_log()
        return rc

    collection = None
    traffic_summary = None
    serve_summary = None
    try:
        with signal_guard():
            if config.serve:
                # gossip-as-a-service: the daemon runs on this (main)
                # thread until --serve-max-requests/--serve-idle-timeout-s
                # or a drain-and-exit (ResumableInterrupt -> the 75 path
                # below, with every completion already journaled)
                from .serve import run_serve
                serve_summary = run_serve(config, args.json_rpc_url,
                                          dp_queue, start_ts,
                                          telemetry_server)
            elif config.traffic_on:
                traffic_summary = run_traffic(config, args.json_rpc_url,
                                              dp_queue, start_ts)
            elif config.all_origins:
                if config.backend != "tpu":
                    log.error("--all-origins requires --backend tpu")
                    return _finish_telemetry(1)
                if dp_queue is not None:
                    log.info("all-origins: emitting run-level aggregate "
                             "Influx series (per-iteration series are a "
                             "single-origin feature)")
                summary = run_all_origins(config, args.json_rpc_url,
                                          dp_queue, start_ts)
            else:
                collection = GossipStatsCollection()
                collection.set_number_of_simulations(config.num_simulations)
                dispatch_sweeps(config, args.json_rpc_url, origin_ranks,
                                collection, dp_queue, start_ts)
    except (ResumableInterrupt, DeviceDispatchError) as e:
        # every finished unit is committed; drain what the sinks hold,
        # stamp a (partial) run report, and exit with the distinct
        # resumable code so a wrapper can loop on --resume
        log.warning("run interrupted resumably: %s", e)
        _telemetry.emit_event("resumable_exit",
                              reason=f"{type(e).__name__}: {e}"[:200])
        influx_stats = _drain_influx(dp_queue, influx_thread,
                                     start_ts, emit_capacity=True)
        stats = faults = None
        if collection is not None:
            stats, faults = _collection_summaries(collection)
        _write_run_report(config, stats=stats, faults=faults,
                          influx=influx_stats)
        ckpt = config.checkpoint_path or config.resume_path
        log.warning("exiting with resumable code %s%s", RESUMABLE_EXIT_CODE,
                    f"; resume with --resume {ckpt}" if ckpt else
                    " (no --checkpoint-path: a re-run starts from scratch)")
        return _finish_telemetry(RESUMABLE_EXIT_CODE)

    if config.serve:
        influx_stats = _drain_influx(dp_queue, influx_thread,
                                     start_ts, emit_capacity=True)
        _write_run_report(config, stats=serve_summary,
                          influx=influx_stats)
        return _finish_telemetry(0)

    if config.traffic_on:
        influx_stats = _drain_influx(dp_queue, influx_thread,
                                     start_ts, emit_capacity=True)
        _write_run_report(config, stats=traffic_summary,
                          influx=influx_stats)
        return _finish_telemetry(0)

    if config.all_origins:
        influx_stats = _drain_influx(dp_queue, influx_thread,
                                     start_ts, emit_capacity=True)
        stats = {
            "coverage_mean": summary["coverage_mean"],
            "rmr_mean": summary["rmr_mean"],
            "num_origins": summary["num_origins"],
            "measured_points": summary["measured_points"],
            "end_to_end_origin_iters_per_sec":
                summary["origin_iters_per_sec"],
            "end_to_end_elapsed_s": summary["elapsed_s"],
            "hop_clamped": summary.get("hop_clamped", 0),
        }
        if config.has_pull:
            # same key set as the single-origin/sweep path's stats.pull
            # (README run-report field table)
            stats["pull"] = {
                "requests": summary.get("pull_requests", 0),
                "responses": summary.get("pull_responses", 0),
                "misses": summary.get("pull_misses", 0),
                "dropped": summary.get("pull_dropped", 0),
                "suppressed": summary.get("pull_suppressed", 0),
                "rescued": summary.get("pull_rescued", 0),
            }
        faults = None
        agg = summary.get("stats")
        if config.impairments_on and agg is not None:
            faults = {
                "delivered": int(sum(agg.delivered_stats.collection)),
                "dropped": int(agg.total_dropped),
                "suppressed": int(agg.total_suppressed),
            }
        _write_run_report(config, stats=stats, faults=faults,
                          influx=influx_stats)
        return _finish_telemetry(0)

    influx_stats = _drain_influx(dp_queue, influx_thread, start_ts,
                                 emit_capacity=True)
    stats, faults = _collection_summaries(collection)
    _write_run_report(config, stats=stats, faults=faults,
                      influx=influx_stats)

    if config.print_stats:
        if not collection.is_empty():
            collection.print_all(config.gossip_iterations,
                                 config.warm_up_rounds, config.test_type)
        else:
            log.warning("WARNING: Gossip Stats Collection is empty. "
                        "Is `Iterations` <= `warm-up-rounds`?")
    log.info("############################################")
    log.info("##### START_TIME: %s ######", start_ts)
    log.info("############################################")
    return _finish_telemetry(0)


if __name__ == "__main__":
    sys.exit(main())
