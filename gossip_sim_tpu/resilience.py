"""Resilient execution layer: run journal, graceful shutdown, and the
device-dispatch supervisor (ISSUE 7).

The reference simulator loses everything on a preemption; PR 1-6 gave the
single-origin path a state checkpoint but left every *multi-unit* path
(serial sweeps, lane-batched sweeps, the batched origin-rank sweep,
``--all-origins``) unable to resume.  This module supplies the three
mechanisms cli.py composes into preemption-safe runs:

* :class:`RunJournal` — an append-only JSONL journal next to the
  checkpoint ``.npz``.  Each completed execution **unit** (one sim of a
  serial sweep, one lane batch, one measured block of the origin-rank
  sweep, one origin batch) commits a single self-contained record: the
  unit's per-sim :meth:`~gossip_sim_tpu.stats.gossip_stats.GossipStats.
  parity_snapshot`, the Influx line-protocol strings the unit pushed, and
  the pubkey-counter position that reproduces the unit's cluster.  A
  record is one ``json.dumps`` line flushed + fsynced; a SIGKILL mid-append
  leaves at most one partial trailing line, which the loader drops — so a
  journal is never unreadable and a committed record is never lost.
  ``--resume`` replays committed records verbatim into stats/Influx
  (deduplicated: replayed units are never recomputed or re-fed) and the
  run restarts from the first uncommitted unit.

* graceful shutdown — SIGTERM/SIGINT set a flag the run loops consult at
  unit boundaries; the in-flight unit finishes its harvest, commits, and
  the run exits with :data:`RESUMABLE_EXIT_CODE` (75, EX_TEMPFAIL) so a
  supervisor script can distinguish "resume me" from a real failure.

* :func:`supervised_call` — the device-dispatch watchdog.  An engine call
  runs in a worker thread bounded by ``--device-timeout-s``; transient
  XLA/runtime errors and timeouts are retried with exponential backoff,
  and on exhaustion ``--on-device-failure cpu-fallback`` re-executes the
  unit on the CPU backend (bit-compatible: the engine is deterministic
  per device-independent integer math) while ``abort`` raises
  :class:`DeviceDispatchError`, which cli.main converts into the
  resumable exit code after committing the journal.

Everything here is accelerator-agnostic; JAX is never imported at module
scope.
"""

from __future__ import annotations

import contextlib
import json
import logging
import os
import signal
import threading
import time

from .obs import get_registry, telemetry

log = logging.getLogger(__name__)

#: exit code of a run interrupted resumably (SIGTERM/SIGINT at a unit
#: boundary, or --on-device-failure abort): EX_TEMPFAIL — "try again",
#: distinct from 0 (done) and 1 (error).  A wrapper script can loop
#: ``while run; rc=75; do run --resume; done``.
RESUMABLE_EXIT_CODE = 75

JOURNAL_SCHEMA = "gossip-sim-tpu/journal/v1"

#: Config fields that shape a unit's content — two runs sharing these
#: produce bit-identical units, so a journal written under one set must
#: never be replayed under another.
RUN_KEY_FIELDS = (
    "gossip_push_fanout", "gossip_active_set_size", "gossip_iterations",
    "origin_rank", "probability_of_rotation", "prune_stake_threshold",
    "min_ingress_nodes", "filter_zero_staked_nodes", "fraction_to_fail",
    "when_to_fail", "num_simulations", "warm_up_rounds",
    "packet_loss_rate", "churn_fail_rate", "churn_recover_rate",
    "partition_at", "heal_at", "gossip_mode", "pull_fanout",
    "pull_interval", "pull_bloom_fp_rate", "pull_request_cap",
    "backend", "seed", "num_synthetic_nodes", "account_file",
    "sweep_lanes", "origin_batch",
)


class ResumableInterrupt(Exception):
    """A graceful-shutdown request honored at a unit boundary: the journal
    is committed up to and including the last finished unit and the run
    should exit with :data:`RESUMABLE_EXIT_CODE`."""


class DeviceTimeoutError(RuntimeError):
    """A supervised device dispatch exceeded ``--device-timeout-s``."""


class DeviceDispatchError(Exception):
    """A supervised device dispatch failed beyond its retry budget under
    ``--on-device-failure abort``.  The journal is already committed for
    every earlier unit, so the run is resumable."""


# --------------------------------------------------------------------------
# run journal
# --------------------------------------------------------------------------

def journal_path(checkpoint_path: str) -> str:
    """The journal file a checkpoint path implies (next to the state npz:
    ``foo.npz`` -> ``foo.journal``; a bare ``foo`` -> ``foo.journal``)."""
    base = checkpoint_path
    if base.endswith(".npz"):
        base = base[: -len(".npz")]
    return base + ".journal"


def run_key_from_config(config, kind: str, extra: dict | None = None) -> dict:
    """The journal's run fingerprint: the Config fields that shape unit
    content plus the unit ``kind`` (serial-sweep / lane-sweep /
    origin-rank / all-origins).  ``extra`` carries per-path inputs that
    live outside the Config — notably the full ``--origin-rank`` list,
    of which Config holds only the first element."""
    key = {f: getattr(config, f) for f in RUN_KEY_FIELDS}
    key["test_type"] = str(config.test_type)
    key["step_size"] = str(config.step_size)
    key["kind"] = kind
    if extra:
        key.update(extra)
    return key


class RunJournal:
    """Append-only unit journal (JSONL, one committed unit per line).

    Line 0 is a header carrying the schema + run key; every further line
    is ``{"unit": int, "payload": {...}}``.  ``commit`` appends, flushes
    and fsyncs — the atomicity contract is line-granular: a torn write can
    only produce a partial *last* line, which :meth:`_load` discards (with
    a warning), never a corrupted earlier record.
    """

    def __init__(self, path: str, run_key: dict, resume: bool = False):
        self.path = path
        self.run_key = dict(run_key)
        # the fingerprint every event this journal emits carries — the
        # join key between the event log and the journal's units
        self.fingerprint = telemetry.run_key_fingerprint(self.run_key)
        telemetry.get_hub().set_run_key(self.run_key)
        self.records: dict[int, dict] = {}
        self._fh = None
        existed = os.path.exists(path)
        if resume and existed:
            self._load()
            telemetry.emit_event("journal_resume", run=self.fingerprint,
                                 units=len(self.records), path=self.path)
        elif existed:
            log.warning("WARNING: overwriting existing journal %s (no "
                        "--resume given); the prior run's committed units "
                        "are discarded", path)
        if not (resume and existed):
            header = {"schema": JOURNAL_SCHEMA, "run_key": self.run_key,
                      "pubkey_counter": _peek_pubkey_counter()}
            d = os.path.dirname(path)
            if d:
                os.makedirs(d, exist_ok=True)
            with open(path, "w") as f:
                f.write(json.dumps(header) + "\n")
                f.flush()
                os.fsync(f.fileno())

    # -- loading ----------------------------------------------------------

    def _load(self) -> None:
        with open(self.path) as f:
            lines = f.read().splitlines()
        if not lines:
            raise SystemExit(f"ERROR: journal {self.path} is empty — "
                             f"remove it to start fresh")
        header = self._parse(lines[0], 0)
        if header is None or header.get("schema") != JOURNAL_SCHEMA:
            raise SystemExit(
                f"ERROR: {self.path} is not a "
                f"{JOURNAL_SCHEMA} journal — remove it to start fresh")
        stored_key = header.get("run_key", {})
        drift = {k: (stored_key.get(k), self.run_key[k])
                 for k in self.run_key
                 if stored_key.get(k) != self.run_key[k]}
        if drift:
            raise SystemExit(
                "ERROR: --resume run configuration does not match the "
                "journal's: " + ", ".join(
                    f"{k}: journal={a!r} vs now={b!r}"
                    for k, (a, b) in sorted(drift.items()))
                + f". Remove {self.path} to start fresh.")
        self.header = header
        valid_bytes = len(lines[0].encode()) + 1
        for i, line in enumerate(lines[1:], start=1):
            rec = self._parse(line, i)
            if rec is None:
                if i != len(lines) - 1:
                    log.warning("WARNING: journal %s line %s is corrupt; "
                                "units from there on are treated as "
                                "uncommitted", self.path, i)
                else:
                    log.warning("WARNING: journal %s ends in a partial "
                                "record (killed mid-commit); the unit is "
                                "treated as uncommitted", self.path)
                # truncate the torn tail so later commits append complete
                # lines instead of gluing onto the partial one
                with open(self.path, "r+") as f:
                    f.truncate(valid_bytes)
                break
            valid_bytes += len(line.encode()) + 1
            self.records[int(rec["unit"])] = rec.get("payload", {})

    @staticmethod
    def _parse(line: str, i: int):
        try:
            return json.loads(line)
        except (json.JSONDecodeError, ValueError):
            return None

    # -- committing -------------------------------------------------------

    def commit(self, unit: int, payload: dict) -> None:
        """Durably commit one finished unit (flush + fsync)."""
        if self._fh is None:
            self._fh = open(self.path, "a")
        rec = {"unit": int(unit), "payload": payload}
        self._fh.write(json.dumps(rec) + "\n")
        self._fh.flush()
        os.fsync(self._fh.fileno())
        self.records[int(unit)] = payload
        get_registry().add("resilience/committed_units", 1)
        telemetry.emit_event("journal_commit", unit=int(unit),
                             run=self.fingerprint,
                             kind=str(self.run_key.get("kind", "")))
        note_unit_committed()

    def close(self) -> None:
        if self._fh is not None:
            self._fh.close()
            self._fh = None

    # -- resume accounting ------------------------------------------------

    def committed_prefix(self) -> int:
        """Number of consecutive units [0, k) already committed — resume
        restarts at unit k (units commit in order, so holes cannot occur
        in a healthy journal but are tolerated defensively)."""
        k = 0
        while k in self.records:
            k += 1
        return k

    def header_pubkey_counter(self) -> int | None:
        """The counter position recorded when the journal was created
        (before the first cluster load) — resume restores it so synthetic
        clusters draw the same pubkeys.  The serial-sweep path needs no
        per-unit positions: replaying a unit re-loads its cluster, which
        advances the counter exactly as the live sim did."""
        hdr = getattr(self, "header", None)
        if hdr is None:
            return None
        v = hdr.get("pubkey_counter")
        return int(v) if v is not None else None


def _peek_pubkey_counter() -> int:
    from .identity import peek_unique_pubkeys
    return peek_unique_pubkeys()


def restore_pubkey_counter(value) -> None:
    """Replay-time counter restore: later units of a resumed run must see
    the same ``pubkey_new_unique`` stream an uninterrupted run would
    (synthetic clusters draw their pubkeys from it)."""
    if value is None:
        return
    from .identity import reset_unique_pubkeys
    reset_unique_pubkeys(int(value))


# --------------------------------------------------------------------------
# parity-snapshot (de)serialization + stats restoration
# --------------------------------------------------------------------------

def snapshot_to_jsonable(snap: dict) -> dict:
    """A ``GossipStats.parity_snapshot()`` as plain JSON types: pubkeys
    become their base58 strings, the failed set a sorted list.  Exact —
    Python json round-trips floats via repr and ints unbounded."""
    out = {}
    for k, v in snap.items():
        if k == "stranded":
            out[k] = {pk.to_string(): [int(s), int(c)]
                      for pk, (s, c) in v.items()}
        elif k in ("egress", "ingress", "prunes"):
            out[k] = {pk.to_string(): int(n) for pk, n in v.items()}
        elif k == "failed_nodes":
            out[k] = sorted(pk.to_string() for pk in v)
        else:
            out[k] = v
    return out


def snapshot_from_jsonable(d: dict) -> dict:
    """Inverse of :func:`snapshot_to_jsonable` — returns a dict comparable
    key-for-key with a freshly-computed parity snapshot."""
    from .identity import Pubkey
    out = {}
    for k, v in d.items():
        if k == "stranded":
            out[k] = {Pubkey.from_string(s): (vals[0], vals[1])
                      for s, vals in v.items()}
        elif k in ("egress", "ingress", "prunes"):
            out[k] = {Pubkey.from_string(s): n for s, n in v.items()}
        elif k == "failed_nodes":
            out[k] = {Pubkey.from_string(s) for s in v}
        else:
            out[k] = v
    return out


def stats_unit_payload(stats) -> dict:
    """One sim's journal payload: the canonical parity snapshot plus the
    non-snapshot state a bit-exact continuation needs (per-round hop
    maxima for LDH, the post-heal coverage series, the origin)."""
    return {
        "origin": stats.origin.to_string() if stats.origin else "",
        "snapshot": snapshot_to_jsonable(stats.parity_snapshot()),
        "hops_round_max": [int(s.max)
                           for s in stats.hops_stats.per_round_stats],
        "post_heal": [[it, cov] for it, cov in stats._post_heal_coverage],
    }


def restore_stats(payload: dict, config, stakes):
    """Rebuild a :class:`GossipStats` from a journal payload.

    The restored object reproduces ``parity_snapshot()`` exactly and — for
    the stats layer's end-of-run outputs — restores every series the
    histogram builders and ``run_all_calculations`` consume.  Per-round
    ``HopsStat``/``StrandedNodeStats`` entries are rebuilt as placeholders
    carrying exactly what later consumers read (the hop ``max`` feeding
    last-delivery-hop stats); their per-iteration mean/median fed Influx
    at capture time and those lines are replayed verbatim, never
    recomputed."""
    from .constants import VALIDATOR_STAKE_DISTRIBUTION_NUM_BUCKETS
    from .identity import Pubkey
    from .stats.gossip_stats import GossipStats
    from .stats.hops import HopsStat
    from .stats.stranded import StrandedNodeStats

    snap = snapshot_from_jsonable(payload["snapshot"])
    stats = GossipStats()
    stats.set_simulation_parameters(config)
    if payload.get("origin"):
        stats.set_origin(Pubkey.from_string(payload["origin"]))
    stats.initialize_message_stats(stakes)
    stats.build_validator_stake_distribution_histogram(
        VALIDATOR_STAKE_DISTRIBUTION_NUM_BUCKETS, stakes)

    stats.coverage_stats.collection = list(snap["coverage"])
    stats.rmr_stats.collection = list(snap["rmr"])
    stats.outbound_branching_factors.collection = list(snap["branching"])
    stats.hops_stats.raw_hop_collection = list(snap["hops"])
    for m in payload.get("hops_round_max", []):
        h = HopsStat()
        h.max = m
        stats.hops_stats.per_round_stats.append(h)
    sc = stats.stranded_node_collection
    sc.stranded_nodes = dict(snap["stranded"])
    sc.total_gossip_iterations = len(snap["coverage"])
    sc.total_nodes = len(stakes)
    sc.per_iter_stats = [StrandedNodeStats()
                         for _ in range(len(snap["coverage"]))]
    stats.egress_messages.counts = dict(snap["egress"])
    stats.ingress_messages.counts = dict(snap["ingress"])
    stats.prune_messages.counts = dict(snap["prunes"])
    stats.delivered_stats.collection = list(snap["delivered"])
    stats.dropped_stats.collection = list(snap["dropped"])
    stats.suppressed_stats.collection = list(snap["suppressed"])
    stats.failed_count_series = list(snap["failed_count_series"])
    stats.failed_nodes = set(snap["failed_nodes"])
    stats.pull_requests_stats.collection = list(snap["pull_requests"])
    stats.pull_responses_stats.collection = list(snap["pull_responses"])
    stats.pull_misses_stats.collection = list(snap["pull_misses"])
    stats.pull_dropped_stats.collection = list(snap["pull_dropped"])
    stats.pull_suppressed_stats.collection = list(snap["pull_suppressed"])
    stats.pull_rescued_stats.collection = list(snap["pull_rescued"])
    # adaptive direction-switch series (adaptive.py); absent in journals
    # written before the adaptive mode existed
    stats.adaptive_active_series = list(snap.get("adaptive_active", []))
    stats.adaptive_switched_series = list(snap.get("adaptive_switched", []))
    stats.recovery_iterations = snap["recovery_iterations"]
    stats._post_heal_coverage = [(int(it), float(cov))
                                 for it, cov in payload.get("post_heal", [])]
    return stats


# --------------------------------------------------------------------------
# influx capture / replay
# --------------------------------------------------------------------------

class InfluxTee:
    """A :class:`~gossip_sim_tpu.sinks.DatapointQueue` facade that records
    every pushed point's line-protocol body into the current unit's buffer
    while forwarding to the real queue.  ``take_unit_lines`` hands the
    buffer to the journal commit and resets it for the next unit."""

    def __init__(self, queue):
        self.queue = queue
        self._lines: list[str] = []

    def push_back(self, dp) -> None:
        self._lines.append(dp.data())
        self.queue.push_back(dp)

    def __len__(self):
        return len(self.queue)

    def take_unit_lines(self) -> list:
        lines, self._lines = self._lines, []
        return lines


def replay_influx_lines(dp_queue, lines) -> None:
    """Push journaled line-protocol bodies back onto the live queue
    verbatim — original per-point timestamps included, so the replayed
    wire payload is byte-identical to what the interrupted run emitted
    (and an Influx endpoint that already received them deduplicates on
    the identical series+timestamp)."""
    if dp_queue is None or not lines:
        return
    from .sinks import InfluxDataPoint
    for body in lines:
        dp = InfluxDataPoint()
        dp.datapoint = body
        dp_queue.push_back(dp)


# --------------------------------------------------------------------------
# graceful shutdown
# --------------------------------------------------------------------------

_shutdown_event = threading.Event()
_units_this_run = 0
_kill_after_units = 0

#: env hook for tools/resume_smoke.py: SIGTERM self after N commits so the
#: kill lands deterministically at a unit boundary's far side (the signal
#: path itself — handler, flag, commit, exit code — is what's under test)
KILL_AFTER_ENV = "GOSSIP_RESILIENCE_KILL_AFTER_UNITS"


def reset_shutdown() -> None:
    """Clear shutdown state (one process == one run; cli.main calls this
    on entry so a previous in-process run's interrupt can't leak)."""
    global _units_this_run, _kill_after_units
    _shutdown_event.clear()
    _units_this_run = 0
    _kill_after_units = int(os.environ.get(KILL_AFTER_ENV, "0") or 0)


def request_shutdown() -> None:
    """Programmatic SIGTERM equivalent (tests + the kill-after hook)."""
    _shutdown_event.set()


def shutdown_requested() -> bool:
    return _shutdown_event.is_set()


def set_kill_after_units(n: int) -> None:
    """Test hook: request shutdown after ``n`` journal commits."""
    global _kill_after_units
    _kill_after_units = int(n)


def note_unit_committed() -> None:
    global _units_this_run
    _units_this_run += 1
    if _kill_after_units and _units_this_run >= _kill_after_units:
        if _signal_handlers_installed():
            os.kill(os.getpid(), signal.SIGTERM)
        else:
            request_shutdown()


_handlers_installed = threading.Event()


def _signal_handlers_installed() -> bool:
    return _handlers_installed.is_set()


@contextlib.contextmanager
def signal_guard():
    """Install SIGTERM/SIGINT handlers that request a graceful, resumable
    shutdown.  A second SIGINT falls through to the previous handler
    (KeyboardInterrupt) so an operator can still hard-stop.  No-op when
    not on the main thread (signal.signal would raise)."""
    prev = {}
    try:
        def _handler(signum, frame):
            if signum == signal.SIGINT and shutdown_requested():
                raise KeyboardInterrupt
            log.warning(
                "received signal %s: finishing the in-flight unit, "
                "committing the journal, and exiting with the resumable "
                "exit code %s", signum, RESUMABLE_EXIT_CODE)
            # the hub lock is an RLock precisely so this emit is safe
            # even when the signal lands mid-emit on the main thread
            telemetry.emit_event("shutdown_signal", signum=int(signum))
            _shutdown_event.set()

        for sig in (signal.SIGTERM, signal.SIGINT):
            prev[sig] = signal.signal(sig, _handler)
        _handlers_installed.set()
    except ValueError:  # not the main thread — run unguarded
        prev = {}
    try:
        yield
    finally:
        _handlers_installed.clear()
        for sig, h in prev.items():
            try:
                signal.signal(sig, h)
            except ValueError:  # pragma: no cover
                pass


def check_interrupt(journal=None) -> None:
    """Unit-boundary shutdown check: raise :class:`ResumableInterrupt`
    when a graceful shutdown was requested (the caller's finished units
    are already committed)."""
    if shutdown_requested():
        raise ResumableInterrupt(
            "graceful shutdown at a unit boundary"
            + (f" ({len(journal.records)} unit(s) committed)"
               if journal is not None else ""))


# --------------------------------------------------------------------------
# device-dispatch supervisor
# --------------------------------------------------------------------------

_fault_hook = None


def set_fault_hook(fn) -> None:
    """Install a test fault injector called as ``fn(label, attempt)``
    before every supervised dispatch attempt; raising from it simulates a
    device failure.  ``None`` uninstalls.  Installing a hook also turns
    supervision on for runs that didn't opt in via flags, so tests can
    exercise the retry path without a watchdog timeout."""
    global _fault_hook
    _fault_hook = fn


class DispatchPolicy:
    """Resolved watchdog knobs for one run (see cli flags)."""

    def __init__(self, timeout_s: float = 0.0, retries: int = 2,
                 backoff_s: float = 0.5, on_failure: str = "abort"):
        self.timeout_s = float(timeout_s)
        self.retries = max(0, int(retries))
        self.backoff_s = float(backoff_s)
        self.on_failure = on_failure


def supervision(config) -> DispatchPolicy | None:
    """The dispatch policy a Config opts into, or None (unsupervised —
    the zero-overhead default).  Supervision turns on when a watchdog
    timeout is set, when ``--on-device-failure`` was passed explicitly,
    or when a test fault hook is installed."""
    timeout = getattr(config, "device_timeout_s", 0.0)
    on_failure = getattr(config, "on_device_failure", "")
    if timeout <= 0 and not on_failure and _fault_hook is None:
        return None
    return DispatchPolicy(timeout_s=timeout,
                          retries=getattr(config, "device_retries", 2),
                          # not a CLI flag: tests set it on the Config
                          # instance to skip real backoff sleeps
                          backoff_s=getattr(config, "device_backoff_s", 0.5),
                          on_failure=on_failure or "abort")


def _is_transient(exc: BaseException) -> bool:
    """Retryable device/runtime failures: XLA runtime errors surface as
    jaxlib ``XlaRuntimeError`` (a RuntimeError subclass in recent JAX) or
    plain RuntimeError/OSError; watchdog timeouts are transient by
    definition.  Programming errors (TypeError, ValueError, shape
    mismatches) are not retried — re-running wrong code is not
    resilience."""
    if isinstance(exc, (NotImplementedError, RecursionError)):
        # RuntimeError subclasses that are deterministic programming
        # errors, not device flakes
        return False
    if isinstance(exc, (DeviceTimeoutError, TimeoutError, OSError,
                        ConnectionError, RuntimeError)):
        return True
    return "XlaRuntimeError" in type(exc).__name__


def _call_with_timeout(fn, timeout_s: float, label: str):
    """Run ``fn`` bounded by ``timeout_s`` (<= 0: unbounded, in-thread).

    The watchdog thread is daemonic and abandoned on timeout — a truly
    hung device call cannot be cancelled from Python, only outwaited; the
    supervisor's job is to get the *run* unstuck (retry or CPU fallback),
    not to reclaim the wedged dispatch."""
    if timeout_s <= 0:
        return fn()
    result: list = []
    error: list = []

    def _worker():
        try:
            result.append(fn())
        except BaseException as e:  # noqa: BLE001 - re-raised below
            error.append(e)

    t = threading.Thread(target=_worker, daemon=True,
                         name=f"device-dispatch:{label}")
    t.start()
    t.join(timeout_s)
    if t.is_alive():
        raise DeviceTimeoutError(
            f"device dispatch '{label}' exceeded --device-timeout-s "
            f"{timeout_s}")
    if error:
        raise error[0]
    return result[0]


def _bump_capacity_epoch() -> None:
    """Invalidate the capacity cost harvest's compile-entry keys after a
    failed dispatch (see supervised_call).  Never lets telemetry break
    the recovery path."""
    try:
        from .obs import capacity
        capacity.bump_dispatch_epoch()
    except Exception:  # pragma: no cover
        pass


def supervised_call(label: str, attempt_fn, policy: DispatchPolicy,
                    cpu_fallback=None):
    """Run one engine unit under the watchdog/retry/fallback policy.

    ``attempt_fn`` must be safe to call repeatedly (cli rebuilds donated
    device state from a host snapshot per attempt).  Transient failures
    are retried ``policy.retries`` times with exponential backoff and
    counted in the ``resilience/device_failures`` registry counter; on
    exhaustion ``cpu-fallback`` invokes ``cpu_fallback`` (counted in
    ``resilience/fallback_units``) while ``abort`` raises
    :class:`DeviceDispatchError`."""
    reg = get_registry()
    delay = policy.backoff_s
    last = None
    for attempt in range(policy.retries + 1):
        try:
            if _fault_hook is not None:
                _fault_hook(label, attempt)
            return _call_with_timeout(attempt_fn, policy.timeout_s, label)
        except BaseException as e:  # noqa: BLE001 - classified below
            if not _is_transient(e):
                raise
            last = e
            reg.add("resilience/device_failures", 1)
            # the re-dispatch may compile a fresh executable (new buffers,
            # possibly another device): invalidate the capacity cost
            # harvest's compile-cache keying so the re-run re-harvests
            # (obs/capacity.py) instead of reusing the pre-failure entry
            _bump_capacity_epoch()
            telemetry.emit_event("device_retry", label=label,
                                 attempt=attempt + 1,
                                 error=f"{type(e).__name__}: {e}"[:200])
            if attempt < policy.retries:
                log.warning("device dispatch '%s' failed (attempt %s/%s): "
                            "%s — retrying in %.2fs", label, attempt + 1,
                            policy.retries + 1, e, delay)
                time.sleep(delay)
                delay *= 2
    if policy.on_failure == "cpu-fallback" and cpu_fallback is not None:
        log.warning("device dispatch '%s' failed %s attempt(s); "
                    "re-executing the unit on the CPU fallback path",
                    label, policy.retries + 1)
        reg.add("resilience/fallback_units", 1)
        telemetry.emit_event("device_fallback", label=label,
                             attempts=policy.retries + 1)
        _bump_capacity_epoch()
        # the fault hook injects *device* failures; the fallback arm runs
        # clean, as a healthy CPU re-execution would
        return cpu_fallback()
    raise DeviceDispatchError(
        f"device dispatch '{label}' failed after {policy.retries + 1} "
        f"attempt(s) ({last}); the journal holds every earlier unit — "
        f"re-run with --resume") from last
