"""Ledger-driven admission control for the serve daemon (ISSUE 20).

Every scenario request is priced **before** it touches the device, with
the same closed-form capacity ledger the planner trusts
(:func:`gossip_sim_tpu.obs.capacity.predict_request_bytes` — exactness
proven in tests/test_capacity.py).  Against a ``--serve-memory-budget``:

* ``predicted > budget``                  -> **413**, permanently: the
  request can never fit, the reply carries the predicted and available
  byte counts so the client can resize instead of retry.
* ``predicted > budget - bytes_in_use``   -> queued: it fits the machine
  but not the moment; it waits for lanes to retire.
* queue at ``--serve-max-queue``          -> **429**: backpressure, try
  later.

Rejections therefore cost zero device allocations — the 413/429 path
returns before any JAX call (serve_smoke gate b checks
``jax.live_arrays()`` is undisturbed).

Fairness is FIFO **per tenant** with round-robin across tenants: one
tenant spraying requests cannot starve another — each scheduling pass
the cursor advances to the next tenant with a non-empty queue, and
within a tenant order of arrival is preserved.
"""

from __future__ import annotations

from collections import OrderedDict, deque

from .request import ScenarioRequest


class RejectedRequest(Exception):
    """Admission refusal carrying the HTTP status + ledger detail."""

    def __init__(self, code: int, reason: str, detail: dict | None = None):
        super().__init__(reason)
        self.code = int(code)
        self.reason = reason
        self.detail = dict(detail or {})

    def payload(self) -> dict:
        return {"error": self.reason, "code": self.code, **self.detail}


class AdmissionController:
    """Budget accounting + per-tenant FIFO queues (not thread-safe; the
    daemon serializes access under its own lock)."""

    def __init__(self, budget_bytes: int = 0, max_queue: int = 64):
        self.budget_bytes = int(budget_bytes)      # 0 = unmetered
        self.max_queue = int(max_queue)
        self._queues: "OrderedDict[str, deque]" = OrderedDict()
        self._rr: list = []                        # tenant round-robin ring
        self._rr_idx = 0
        self._in_use = 0                           # bytes held by running lanes
        self.counters = {"received": 0, "admitted": 0, "rejected": 0,
                         "completed": 0}
        self.tenants_admitted: dict = {}
        self.tenants_rejected: dict = {}

    # -- introspection -------------------------------------------------
    def queue_depth(self) -> int:
        return sum(len(q) for q in self._queues.values())

    def bytes_in_use(self) -> int:
        return self._in_use

    def queued_bytes(self) -> int:
        return sum(r.predicted_bytes for q in self._queues.values()
                   for r in q)

    def available_bytes(self) -> int:
        """Budget headroom after running + queued commitments (what a 413
        reply reports so the client sees the real free pool)."""
        if self.budget_bytes <= 0:
            return -1
        return max(0, self.budget_bytes - self._in_use - self.queued_bytes())

    # -- intake --------------------------------------------------------
    def submit(self, req: ScenarioRequest) -> str:
        """Price and enqueue one request.  Returns ``"queued"`` or raises
        :class:`RejectedRequest` (413 over-budget / 429 queue-full)
        without any device-side effect."""
        self.counters["received"] += 1
        if self.budget_bytes > 0 and req.predicted_bytes > self.budget_bytes:
            self._note_rejected(req.tenant)
            raise RejectedRequest(
                413, "request exceeds the daemon memory budget",
                {"id": req.id, "predicted_bytes": req.predicted_bytes,
                 "budget_bytes": self.budget_bytes,
                 "available_bytes": self.available_bytes()})
        if self.queue_depth() >= self.max_queue:
            self._note_rejected(req.tenant)
            raise RejectedRequest(
                429, "admission queue is full",
                {"id": req.id, "queue_depth": self.queue_depth(),
                 "max_queue": self.max_queue})
        if req.tenant not in self._queues:
            self._queues[req.tenant] = deque()
            self._rr.append(req.tenant)
        req.status = "queued"
        self._queues[req.tenant].append(req)
        return "queued"

    def _note_rejected(self, tenant: str) -> None:
        self.counters["rejected"] += 1
        self.tenants_rejected[tenant] = self.tenants_rejected.get(tenant, 0) + 1

    def note_invalid(self, tenant: str = "invalid") -> None:
        """Count a request that failed validation before pricing (bad
        JSON, unknown knob, out-of-range value) — a 400, not a 413."""
        self.counters["received"] += 1
        self._note_rejected(tenant)

    # -- scheduling ----------------------------------------------------
    def next_admission(self):
        """Pop the next runnable request (round-robin over tenants, FIFO
        within one) if the moment's budget headroom covers it; None when
        nothing can start right now."""
        if not self._rr:
            return None
        n = len(self._rr)
        for off in range(n):
            tenant = self._rr[(self._rr_idx + off) % n]
            q = self._queues.get(tenant)
            if not q:
                continue
            req = q[0]
            if (self.budget_bytes > 0
                    and self._in_use + req.predicted_bytes > self.budget_bytes):
                continue  # fits the machine, not the moment — hold FIFO order
            q.popleft()
            self._rr_idx = (self._rr_idx + off + 1) % n
            self._in_use += req.predicted_bytes
            req.status = "running"
            self.counters["admitted"] += 1
            self.tenants_admitted[tenant] = (
                self.tenants_admitted.get(tenant, 0) + 1)
            return req
        return None

    def complete(self, req: ScenarioRequest) -> None:
        """Release a finished (or failed) request's byte reservation."""
        self._in_use = max(0, self._in_use - req.predicted_bytes)
        self.counters["completed"] += 1
