"""Gossip-as-a-service: the continuous-batching scenario daemon
(ISSUE 20).  See daemon.py for the architecture; request.py for the
request schema; admission.py for the ledger-driven admission contract;
intake.py for the HTTP + spool intake surfaces."""

from .admission import AdmissionController, RejectedRequest
from .daemon import ServeDaemon, block_rounds, run_serve
from .request import SERVE_KNOB_FIELDS, ScenarioRequest, parse_request

__all__ = [
    "AdmissionController",
    "RejectedRequest",
    "SERVE_KNOB_FIELDS",
    "ScenarioRequest",
    "ServeDaemon",
    "block_rounds",
    "parse_request",
    "run_serve",
]
