"""Scenario request specs for the gossip-as-a-service daemon (ISSUE 20).

A request is a JSON document submitted over ``POST /submit`` or dropped
into the ``--serve-spool-dir`` as ``*.json``:

    {"id": "r1", "tenant": "alice", "seed": 7, "origin_rank": 1,
     "start_ts": "0",
     "knobs": {"probability_of_rotation": 0.2, "packet_loss_rate": 0.05}}

Every field is optional except that ``knobs`` keys must come from
:data:`SERVE_KNOB_FIELDS` — the Config fields that map onto *traced*
:class:`~gossip_sim_tpu.engine.params.EngineKnobs` leaves (plus the two
impairment-window schedules), so any admissible request can ride the
daemon's one warm executable.  Compile geometry (cluster size, fanout,
active-set size, gossip mode, iteration count) is fixed by the daemon's
base config: a knob that would change the static compile key is not a
request parameter, it is a different daemon.

The only statics a request may *implicitly* flip are the coarse
impairment gates (has_loss/has_churn/has_partition): a loss-free daemon
admitting its first lossy request widens the merged static via
``merge_lane_statics`` — one documented recompile, counted on
``engine/compiles`` (tests/test_serve.py).
"""

from __future__ import annotations

import json
import time
from dataclasses import dataclass, field

#: Config fields a request may override — each maps to a traced
#: EngineKnobs leaf (engine/params.py), so admission never changes the
#: compile geometry (the impairment gates excepted, see module doc).
SERVE_KNOB_FIELDS = frozenset({
    "probability_of_rotation",
    "prune_stake_threshold",
    "min_ingress_nodes",
    "packet_loss_rate",
    "churn_fail_rate",
    "churn_recover_rate",
    "partition_at",
    "heal_at",
    "pull_fanout",
    "pull_interval",
    "pull_bloom_fp_rate",
    "pull_request_cap",
    "adaptive_switch_threshold",
    "adaptive_switch_hysteresis",
})

#: knob fields carrying a probability (validated into [0, 1])
_RATE_FIELDS = frozenset({
    "probability_of_rotation", "prune_stake_threshold",
    "packet_loss_rate", "churn_fail_rate", "churn_recover_rate",
    "pull_bloom_fp_rate", "adaptive_switch_threshold",
    "adaptive_switch_hysteresis",
})

_INT_FIELDS = frozenset({
    "min_ingress_nodes", "partition_at", "heal_at",
    "pull_fanout", "pull_interval", "pull_request_cap",
})


@dataclass
class ScenarioRequest:
    """One validated scenario request plus its scheduling state."""

    id: str
    tenant: str = "default"
    seed: int = 0
    origin_rank: int = 1
    knobs: dict = field(default_factory=dict)
    start_ts: str = ""              # Influx start_time tag (the
                                    # per-request attribution tag riding
                                    # the unchanged PR 2 wire paths)
    submitted_ts: float = 0.0
    source: str = "http"            # http | spool | journal-intake
    predicted_bytes: int = 0
    status: str = "queued"          # queued | running | done | failed
    lane: int = -1
    rounds_done: int = 0

    def spec(self) -> dict:
        """The JSON-safe spec (what the intake log / journal persists —
        enough to re-admit the request bit-exactly after a restart)."""
        return {"id": self.id, "tenant": self.tenant, "seed": self.seed,
                "origin_rank": self.origin_rank,
                "knobs": dict(self.knobs), "start_ts": self.start_ts}

    def request_config(self, base_config):
        """The request's Config: the daemon base stepped by the knob
        overrides, shaped like one solo lane-sweep point
        (num_simulations=1) so the request feeds the exact stats/Influx
        paths ``run_lane_sweep`` would solo — the serve_smoke parity
        contract."""
        return base_config.stepped(seed=self.seed,
                                   origin_rank=self.origin_rank,
                                   num_simulations=1, sweep_lanes=1,
                                   checkpoint_path="", resume_path="",
                                   **self.knobs)


def parse_request(raw, base_config, *, default_id: str) -> ScenarioRequest:
    """Validate one submitted spec (dict or JSON bytes/str) into a
    :class:`ScenarioRequest`.  Raises ``ValueError`` with a
    client-presentable message on any problem — unknown knob keys are an
    error, not a warning, so a typo'd knob can never silently run the
    base scenario."""
    if isinstance(raw, (bytes, str)):
        try:
            raw = json.loads(raw)
        except ValueError as e:
            raise ValueError(f"request body is not JSON: {e}")
    if not isinstance(raw, dict):
        raise ValueError(f"request must be a JSON object, got "
                         f"{type(raw).__name__}")
    knobs = raw.get("knobs") or {}
    if not isinstance(knobs, dict):
        raise ValueError("knobs must be an object")
    unknown = sorted(set(knobs) - SERVE_KNOB_FIELDS)
    if unknown:
        raise ValueError(
            f"unknown knob field(s) {unknown}; a request may set: "
            f"{sorted(SERVE_KNOB_FIELDS)}")
    clean = {}
    for k, v in knobs.items():
        try:
            clean[k] = int(v) if k in _INT_FIELDS else float(v)
        except (TypeError, ValueError):
            raise ValueError(f"knob {k}: expected a number, got {v!r}")
        if k in _RATE_FIELDS and not 0.0 <= clean[k] <= 1.0:
            raise ValueError(f"knob {k}: must be in [0, 1], got {v}")
    heal = clean.get("heal_at", base_config.heal_at)
    part = clean.get("partition_at", base_config.partition_at)
    if heal >= 0 and part < 0:
        raise ValueError("heal_at requires partition_at")
    if part >= 0 and 0 <= heal < part:
        raise ValueError("heal_at must not precede partition_at")
    try:
        seed = int(raw.get("seed", base_config.seed))
        rank = int(raw.get("origin_rank", base_config.origin_rank))
    except (TypeError, ValueError):
        raise ValueError("seed / origin_rank must be integers")
    if rank < 1:
        raise ValueError(f"origin_rank must be >= 1, got {rank}")
    rid = str(raw.get("id") or default_id)
    if len(rid) > 128 or any(c in rid for c in "/\\ \n\t"):
        raise ValueError(f"bad request id {rid!r} (<=128 chars, no "
                         f"slashes or whitespace)")
    return ScenarioRequest(
        id=rid,
        tenant=str(raw.get("tenant") or "default")[:64],
        seed=seed,
        origin_rank=rank,
        knobs=clean,
        start_ts=str(raw.get("start_ts") or time.time_ns()),
        submitted_ts=time.time(),
    )
