"""Request intake surfaces for the serve daemon (ISSUE 20).

Two intake paths, one admission pipeline:

* **HTTP** — mounted onto the PR 18 telemetry exporter's pluggable
  routes (obs/exporter.py), so one port serves scrapes AND submissions:

  - ``POST /submit``  body = request JSON -> 200 ``{"id", "status"}`` /
    400 invalid / 413 over-budget (with predicted + available bytes) /
    429 queue full
  - ``GET /result/<id>``  -> 200 finished result (parity snapshot +
    deterministic wire lines) / 202 still queued or running / 404
  - ``GET /serve``  -> the live serve view (lane occupancy, queue
    depth, per-tenant counters, per-lane ETA)

* **Spool** — a watched ``--serve-spool-dir``: drop ``<name>.json`` and
  the daemon picks it up at the next block boundary (renamed to
  ``.taken`` first, so each file is admitted exactly once), then writes
  ``<id>.result.json`` on completion or ``<name>.rejected.json`` with
  the refusal payload.
"""

from __future__ import annotations

import json
import logging
import os

log = logging.getLogger(__name__)


def mount_routes(server, daemon) -> None:
    """Mount the daemon's intake endpoints on a TelemetryServer."""

    def _submit(query=None, body=b""):
        return daemon.submit_raw(body or b"{}", source="http")

    def _result(query=None, tail=""):
        return daemon.get_result(tail.strip("/"))

    def _view(query=None):
        return 200, daemon.serve_view()

    server.add_route("POST", "/submit", _submit)
    server.add_route("GET", "/result/", _result)
    server.add_route("GET", "/serve", _view)


def scan_spool(daemon) -> None:
    """One pass over the watched intake directory (called from the
    daemon loop at block boundaries, under the daemon lock)."""
    spool = daemon.config.serve_spool_dir
    if not spool:
        return
    try:
        names = sorted(os.listdir(spool))
    except OSError as e:
        log.warning("serve: spool dir unreadable: %s", e)
        return
    for name in names:
        if (not name.endswith(".json") or name.endswith(".result.json")
                or name.endswith(".rejected.json")):
            continue
        path = os.path.join(spool, name)
        taken = path + ".taken"
        try:
            os.replace(path, taken)  # claim atomically: admit-once
            with open(taken) as f:
                raw = f.read()
        except OSError:
            continue  # raced away or unreadable; next pass decides
        code, payload = daemon.submit_raw(raw, source="spool")
        if code != 200:
            log.warning("serve: spool request %s rejected (%s): %s",
                        name, code, payload.get("error", payload))
            try:
                rej = os.path.join(spool, name[:-len(".json")]
                                   + ".rejected.json")
                with open(rej, "w") as f:
                    json.dump(payload, f)
            except OSError:
                pass
