"""The continuous-batching scenario daemon (ISSUE 20 tentpole).

One process holds ONE warm lane-batched executable — ``--serve-lanes K``
dynamically-membered lanes over :func:`engine.run_rounds_lanes_dyn` —
and serves scenario requests for the daemon's fixed compile geometry
(cluster, fanout, active-set size, mode, iteration count) continuously:

* requests arrive over HTTP (``POST /submit`` on the PR 18 telemetry
  plane, intake.py) or a watched ``--serve-spool-dir``;
* admission is **ledger-driven** (admission.py): every request is priced
  with the closed-form capacity ledger before it touches the device —
  over-budget requests 413 with the predicted and available byte counts
  and cost zero device allocations;
* admitted requests splice into free lanes at block boundaries
  (``--serve-block-rounds``) while co-resident lanes keep running —
  continuous batching, the Orca-style iteration-level scheduling idea
  applied to simulation scans.  Steady-state admissions re-enter the one
  warm executable with ZERO recompiles (the shapes never change); the
  single documented exception is a request that widens the impairment
  gate union (merge_lane_statics), which recompiles once and is flagged
  on the ``request_admitted`` event;
* each retiring lane harvests through the UNCHANGED per-sim paths
  (cli._harvest_lane / _finalize_sim_stats), so a request's parity
  snapshot and deterministic Influx wire lines are byte-identical to the
  same config run solo through run_lane_sweep (tools/serve_smoke.py
  gate a);
* completions journal through resilience.RunJournal: SIGTERM drains
  in-flight lanes, commits them, and exits with the resumable code 75;
  a restart replays committed results verbatim and re-admits every
  journaled-but-uncommitted request from the intake sidecar.

The daemon runs on the MAIN thread inside cli.main()'s signal_guard;
HTTP intake handlers run on the exporter's threads and only touch the
admission queues under the daemon lock — the device is driven by exactly
one thread, always.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time

import numpy as np

from ..obs import get_registry
from ..obs import telemetry as _telemetry
from ..obs.capacity import parse_size, predict_request_bytes
from ..resilience import (InfluxTee, ResumableInterrupt,
                          replay_influx_lines, restore_pubkey_counter,
                          shutdown_requested, stats_unit_payload)
from ..sinks.influx import deterministic_wire_lines
from .admission import AdmissionController, RejectedRequest
from .request import parse_request

log = logging.getLogger(__name__)


def block_rounds(total: int, requested: int) -> int:
    """The scheduler tick: the largest divisor of ``total`` that is
    <= ``requested``.  Divisibility means every lane's admission offset
    stays a block multiple, so lanes only ever retire exactly at a block
    boundary and the lane count per dispatch is constant."""
    b = max(1, min(int(requested), int(total)))
    while total % b:
        b -= 1
    return b


class _NullQueue:
    """Line sink for influx-less daemons: the InfluxTee still captures
    each request's wire lines for its result payload, the points
    themselves go nowhere."""

    def push_back(self, dp) -> None:
        pass

    def __len__(self):
        return 0


class ServeDaemon:
    """State + scheduling for one serve run (see module docstring)."""

    def __init__(self, config, json_rpc_url, dp_queue, start_ts,
                 telemetry_server):
        from .. import cli  # deferred: cli imports this package lazily too
        self._cli = cli
        self.config = config
        self.dp_queue = dp_queue
        self.start_ts = start_ts
        self.telemetry_server = telemetry_server

        self.K = max(1, int(config.serve_lanes))
        self.total = int(config.gossip_iterations)
        self.warm = min(config.warm_up_rounds, self.total)
        self.block = block_rounds(self.total, config.serve_block_rounds)
        budget = (parse_size(config.serve_memory_budget)
                  if config.serve_memory_budget else 0)
        self.admission = AdmissionController(budget, config.serve_max_queue)
        self.lock = threading.RLock()
        self.requests: dict = {}        # id -> ScenarioRequest
        self.results: dict = {}         # id -> result payload
        from ..stats.gossip_stats import GossipStatsCollection
        self.collection = GossipStatsCollection()

        self.lanes: list = [None] * self.K   # per-lane run table or None
        self.states = None                   # [K, O, ...] SimState
        self.tables = None
        self._device_ready = False
        self._seq = 0
        self._completions = 0
        self._draining = False
        self._tick = 0
        self._last_block_wall = 0.0
        self._idle_since = time.time()

        # crash-recovery plane: journal units are COMPLETIONS (commit
        # order), the intake sidecar records ADMISSION order — together
        # they reconstruct exactly the uncommitted work set on restart
        self.journal = cli._open_journal(config, "serve",
                                         {"serve_lanes": self.K})
        self.intake_path = (self.journal.path + ".intake"
                            if self.journal is not None else "")
        self.feed = InfluxTee(dp_queue if dp_queue is not None
                              else _NullQueue())
        if self.journal is not None:
            # synthetic clusters advance the global pubkey counter per
            # load; the resumed run must see the counter position the
            # interrupted run recorded (same contract as run_lane_sweep)
            restore_pubkey_counter(self.journal.header_pubkey_counter())

        # the cluster is resolved ONCE, host-side, at startup — it both
        # fixes the compile geometry and gives pricing its N before any
        # device contact
        self.accounts, self.source_label = cli.load_cluster_accounts(
            config, json_rpc_url)
        from ..identity import NodeIndex
        self.stakes = dict(self.accounts)
        self.index = NodeIndex.from_stakes(self.accounts)
        self.N = len(self.index)
        self.base_params = cli._engine_params(config, self.N).validate()
        self.static = self.base_params.static_part()

        # intake goes live as soon as the daemon can answer (the
        # telemetry port binds earlier in main(); until this point
        # /submit 404s, so clients retry briefly after discovery)
        _telemetry.get_hub().set_provider("serve", self.serve_view)
        if telemetry_server is not None:
            from .intake import mount_routes
            mount_routes(telemetry_server, self)

    # -- intake (called from HTTP/exporter threads AND the main loop) --
    def submit_raw(self, raw, source: str = "http"):
        """Validate + price + enqueue one submitted spec.  Returns
        ``(http_code, payload)``; rejections return before any device
        call."""
        from ..engine import check_lane_knobs, merge_lane_statics
        with self.lock:
            self._seq += 1
            default_id = f"req-{self._seq:04d}"
            _telemetry.emit_event("request_received", source=source)
            try:
                req = parse_request(raw, self.config, default_id=default_id)
                req.source = source
                if req.id in self.requests:
                    raise ValueError(f"duplicate request id {req.id!r}")
                if req.origin_rank > self.N:
                    raise ValueError(
                        f"origin_rank {req.origin_rank} exceeds the "
                        f"daemon cluster size {self.N}")
                if self._draining:
                    raise ValueError(
                        "daemon is draining (shutdown requested); "
                        "resubmit after restart")
                rc = req.request_config(self.config)
                params = self._cli._engine_params(rc, self.N).validate()
                # geometry check: the request must be servable by the
                # (possibly gate-widened) daemon static
                merged = merge_lane_statics([self.static,
                                             params.static_part()])
                check_lane_knobs(merged, [params.knob_values()])
            except ValueError as e:
                self.admission.note_invalid()
                _telemetry.emit_event("request_rejected", code=400,
                                      reason=str(e)[:200])
                return 400, {"error": str(e), "code": 400}
            req.predicted_bytes = predict_request_bytes(params, 1)
            try:
                self.admission.submit(req)
            except RejectedRequest as e:
                _telemetry.emit_event(
                    "request_rejected", id=req.id, tenant=req.tenant,
                    code=e.code, reason=e.reason,
                    predicted_bytes=req.predicted_bytes)
                return e.code, e.payload()
            self.requests[req.id] = req
            if source != "journal-intake":
                self._append_intake(req)
            return 200, {"id": req.id, "status": "queued",
                         "predicted_bytes": req.predicted_bytes,
                         "queue_depth": self.admission.queue_depth()}

    def get_result(self, rid: str):
        with self.lock:
            if rid in self.results:
                return 200, self.results[rid]
            req = self.requests.get(rid)
            if req is None:
                return 404, {"error": f"unknown request id {rid!r}",
                             "code": 404}
            return 202, {"id": rid, "status": req.status,
                         "lane": req.lane,
                         "rounds_done": req.rounds_done,
                         "total_rounds": self.total}

    def _append_intake(self, req) -> None:
        if not self.intake_path:
            return
        try:
            with open(self.intake_path, "a") as f:
                f.write(json.dumps(req.spec()) + "\n")
                f.flush()
                os.fsync(f.fileno())
        except OSError as e:  # degraded: lose restart re-admission only
            log.warning("serve: intake sidecar append failed: %s", e)

    # -- live view -----------------------------------------------------
    def serve_view(self) -> dict:
        """The live serve section: hub provider (``/metrics`` gauges +
        ``/status``), ``GET /serve``, and the run report's serve key all
        read this one dict."""
        with self.lock:
            lanes = []
            for i, l in enumerate(self.lanes):
                if l is None:
                    lanes.append({"lane": i, "busy": False})
                    continue
                req = l["req"]
                remaining = self.total - req.rounds_done
                eta = (round(remaining / self.block
                             * self._last_block_wall, 3)
                       if self._last_block_wall > 0 else -1.0)
                lanes.append({"lane": i, "busy": True, "id": req.id,
                              "tenant": req.tenant,
                              "rounds_done": req.rounds_done,
                              "total_rounds": self.total, "eta_s": eta})
            a = self.admission
            return {
                "enabled": True,
                "lanes": self.K,
                "busy": sum(1 for l in self.lanes if l is not None),
                "queued": a.queue_depth(),
                "block_rounds": self.block,
                "draining": self._draining,
                "received": a.counters["received"],
                "admitted": a.counters["admitted"],
                "rejected": a.counters["rejected"],
                "completed": a.counters["completed"],
                "budget_bytes": a.budget_bytes,
                "bytes_in_use": a.bytes_in_use(),
                "tenants_admitted": dict(a.tenants_admitted),
                "tenants_rejected": dict(a.tenants_rejected),
                "lane_detail": lanes,
            }

    # -- device-side scheduling (main thread only) ---------------------
    def _ensure_device(self) -> None:
        if self._device_ready:
            return
        import jax

        from ..engine import make_cluster_tables
        cli = self._cli
        reg = get_registry()
        cli._enable_compilation_cache(self.config)
        with reg.span("engine/tables"):
            self.tables = make_cluster_tables(
                self.index.stakes.astype(np.int64))
        reg.set_info("platform", jax.devices()[0].platform)
        reg.set_info("origin_batch", 1)
        reg.set_info("sweep_lanes", self.K)
        cli._note_capacity_ledger(self.config, self.base_params,
                                  lanes=self.K)
        self._device_ready = True

    def _admit(self, req, lane: int) -> None:
        import jax
        import jax.numpy as jnp

        from ..engine import (broadcast_state, init_state,
                              merge_lane_statics, splice_lane_state)
        cli = self._cli
        self._ensure_device()
        rc = req.request_config(self.config)
        sweep_point = cli._stepped_sweep_config(rc, 0, [rc.origin_rank])
        params = cli._engine_params(rc, self.N).validate()
        merged = merge_lane_statics([self.static, params.static_part()])
        widened = merged != self.static
        self.static = merged
        origin = cli.find_nth_largest_node(req.origin_rank,
                                           list(self.accounts.items()))
        origin_pubkey = origin[0]
        origin_idx = self.index.index_of(origin_pubkey)
        reg = get_registry()
        with reg.span("engine/init"):
            st = init_state(jax.random.PRNGKey(req.seed), self.tables,
                            jnp.asarray([origin_idx], dtype=jnp.int32),
                            params)
            jax.block_until_ready(st)
        if self.states is None:
            self.states = broadcast_state(st, self.K)
        else:
            self.states = splice_lane_state(self.states, lane, st)
        req.status = "running"
        req.lane = lane
        req.rounds_done = 0
        self.lanes[lane] = {"req": req, "rc": rc,
                            "sweep_point": sweep_point, "params": params,
                            "knobs": params.knob_values(),
                            "origin_idx": origin_idx,
                            "origin_pubkey": origin_pubkey, "chunks": []}
        _telemetry.emit_event("request_admitted", id=req.id,
                              tenant=req.tenant, lane=lane,
                              predicted_bytes=req.predicted_bytes,
                              gate_union=bool(widened))
        log.info("serve: admitted %s (tenant %s) into lane %d%s",
                 req.id, req.tenant, lane,
                 " [impairment gate union widened: one recompile]"
                 if widened else "")

    def _admit_ready(self) -> None:
        for lane in range(self.K):
            if self.lanes[lane] is not None:
                continue
            req = self.admission.next_admission()
            if req is None:
                return
            self._admit(req, lane)

    def _dispatch_block(self) -> None:
        import jax

        from ..engine import run_rounds_lanes_dyn, stack_knobs, stack_origins
        cli = self._cli
        reg = get_registry()
        with self.lock:
            active = [i for i, l in enumerate(self.lanes) if l is not None]
            fill = self.lanes[active[0]]
            slots = [self.lanes[i] or fill for i in range(self.K)]
            kstack = stack_knobs([s["knobs"] for s in slots])
            ostack = stack_origins([[s["origin_idx"]] for s in slots])
            start_its = [self.lanes[i]["req"].rounds_done
                         if self.lanes[i] is not None else 0
                         for i in range(self.K)]
            static, tables, states = self.static, self.tables, self.states

        t_blk = time.perf_counter()
        cm, _counted = cli._engine_call_span(reg)

        def _go(st):
            sts, rws = run_rounds_lanes_dyn(static, tables, ostack, st,
                                            kstack, self.block, start_its,
                                            detail=True)
            return sts, jax.tree_util.tree_map(np.asarray, rws)

        with cm:
            new_states, rows = cli._dispatch_supervised(
                self.config, f"serve-block-{self._tick}", _go, states)
        self._last_block_wall = time.perf_counter() - t_blk
        self._tick += 1

        with self.lock:
            self.states = new_states
            for i in active:
                l = self.lanes[i]
                l["chunks"].append({k: v[:, i] for k, v in rows.items()})
                l["req"].rounds_done += self.block
        cli._push_sim_perf_point(self.dp_queue, 0, self.start_ts,
                                 self._last_block_wall, self.block,
                                 len(active))

    def _retire_finished(self) -> None:
        for lane, l in enumerate(self.lanes):
            if l is None or l["req"].rounds_done < self.total:
                continue
            self._complete(lane, l)
            self.lanes[lane] = None

    def _complete(self, lane: int, l: dict) -> None:
        from ..engine import lane_state
        from ..stats.gossip_stats import GossipStats
        from ..constants import VALIDATOR_STAKE_DISTRIBUTION_NUM_BUCKETS
        cli = self._cli
        reg = get_registry()
        req, rc = l["req"], l["rc"]
        # stray non-request lines (perf points etc.) were already
        # live-forwarded; clear the unit buffer so the harvest below
        # captures exactly this request's wire lines
        self.feed.take_unit_lines()
        lrows = {k: np.concatenate([c[k] for c in l["chunks"]], axis=0)
                 for k in l["chunks"][0]}
        stats = GossipStats()
        stats.set_simulation_parameters(rc)
        stats.set_origin(l["origin_pubkey"])
        stats.initialize_message_stats(self.stakes)
        stats.build_validator_stake_distribution_histogram(
            VALIDATOR_STAKE_DISTRIBUTION_NUM_BUCKETS, self.stakes)
        measured = self.total - self.warm
        with reg.span("stats/harvest"):
            cli._harvest_lane(rc, l["sweep_point"], stats, lrows,
                              lane_state(self.states, lane), l["params"],
                              self.index, self.stakes, l["origin_pubkey"],
                              self.feed, 0, req.start_ts, self.warm,
                              self.total, len(self.accounts),
                              self.source_label)
            cli._finalize_sim_stats(l["sweep_point"][0], stats,
                                    self.stakes, self.collection,
                                    self.feed, 0, req.start_ts)
        reg.add("origin_iters", measured)
        reg.add("messages_delivered",
                int(lrows["delivered"][self.warm:].sum()))
        lines = self.feed.take_unit_lines()
        payload = stats_unit_payload(stats)
        result = {
            "id": req.id, "tenant": req.tenant, "status": "done",
            "spec": req.spec(), "lane": lane,
            "predicted_bytes": req.predicted_bytes,
            "snapshot": payload["snapshot"],
            "lines": lines,
            # a journaled line is one POINT body — possibly multi-line,
            # timestamps included (replay needs it verbatim) — so split
            # before normalizing to the parity wire form
            "deterministic_lines": deterministic_wire_lines(
                [ln for body in lines for ln in body.splitlines()]),
            "stats": {
                "coverage_mean": round(float(stats.coverage_stats.mean),
                                       6),
                "rmr_mean": round(float(stats.rmr_stats.mean), 6),
            },
            "wall_s": round(time.time() - req.submitted_ts, 3)
            if req.submitted_ts else 0.0,
        }
        unit = self._completions
        if self.journal is not None:
            self.journal.commit(unit, {"request": req.spec(),
                                       "sims": [[unit, payload]],
                                       "lines": lines})
        self._completions += 1
        req.status = "done"
        req.lane = -1
        self.admission.complete(req)
        self.results[req.id] = result
        _telemetry.emit_event("request_completed", id=req.id,
                              tenant=req.tenant, lane=lane,
                              rounds=self.total,
                              coverage_mean=result["stats"]
                              ["coverage_mean"])
        _telemetry.emit_event("lane_evicted", lane=lane, id=req.id,
                              reason="completed")
        log.info("serve: completed %s (tenant %s, lane %d, coverage "
                 "%.4f)", req.id, req.tenant, lane,
                 result["stats"]["coverage_mean"])
        self._write_request_report(req, rc, result)
        self._spool_result(req, result)

    def _write_request_report(self, req, rc, result) -> None:
        """Per-request run report through the unchanged obs/report.py
        schema: ``<run-report-path stem>.req-<id>.json``."""
        if not self.config.run_report_path:
            return
        try:
            from ..obs.report import (build_run_report,
                                      validate_run_report,
                                      write_run_report)
            self._cli._sync_cache_counters()
            reg = get_registry()
            reg.set_info("serve", self.serve_view())
            report = build_run_report(rc, reg, stats=result["stats"])
            problems = validate_run_report(report)
            if problems:
                log.warning("WARNING: per-request report failed schema "
                            "self-check: %s", problems)
            base, ext = os.path.splitext(self.config.run_report_path)
            path = f"{base}.req-{req.id}{ext or '.json'}"
            write_run_report(path, report)
            log.info("serve: request report written to %s", path)
        except Exception as e:  # telemetry must never kill the daemon
            log.warning("serve: per-request run report failed: %s", e)

    def _spool_result(self, req, result) -> None:
        if req.source != "spool" or not self.config.serve_spool_dir:
            return
        try:
            path = os.path.join(self.config.serve_spool_dir,
                                f"{req.id}.result.json")
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(result, f)
            os.replace(tmp, path)
        except OSError as e:
            log.warning("serve: spool result write failed: %s", e)

    # -- crash recovery ------------------------------------------------
    def _replay_journal(self) -> None:
        if self.journal is None:
            return
        k = self.journal.committed_prefix()
        for unit in range(k):
            payload = self.journal.records[unit]
            spec = payload.get("request") or {}
            req = parse_request(spec, self.config,
                                default_id=str(spec.get("id")
                                               or f"replay-{unit}"))
            req.source = "journal"
            req.status = "done"
            sims = payload.get("sims") or []
            stats = None
            if sims:
                stats = self._cli._replay_finished_sim(
                    sims[0][1], req.request_config(self.config),
                    self.stakes, self.collection)
            lines = list(payload.get("lines", []))
            # verbatim wire replay to the LIVE queue (dedup at the
            # endpoint on identical series+timestamp), never the tee —
            # these lines are already journaled
            replay_influx_lines(self.dp_queue, lines)
            a = self.admission
            a.counters["received"] += 1
            a.counters["admitted"] += 1
            a.counters["completed"] += 1
            a.tenants_admitted[req.tenant] = (
                a.tenants_admitted.get(req.tenant, 0) + 1)
            self.requests[req.id] = req
            result = {
                "id": req.id, "tenant": req.tenant, "status": "done",
                "spec": req.spec(), "replayed": True,
                "snapshot": (sims[0][1].get("snapshot") if sims
                             else None),
                "lines": lines,
                "deterministic_lines": deterministic_wire_lines(lines),
            }
            if stats is not None and not stats.is_empty():
                result["stats"] = {
                    "coverage_mean":
                        round(float(stats.coverage_stats.mean), 6),
                    "rmr_mean": round(float(stats.rmr_stats.mean), 6),
                }
            self.results[req.id] = result
            self._completions += 1
        if k:
            log.info("serve resume: %d committed request(s) replayed "
                     "verbatim from the journal", k)
        # re-admit what the interrupted daemon accepted but never
        # committed, in the original admission order
        if not self.intake_path or not os.path.exists(self.intake_path):
            return
        try:
            with open(self.intake_path) as f:
                intake_lines = f.read().splitlines()
        except OSError as e:
            log.warning("serve resume: intake sidecar unreadable: %s", e)
            return
        readmitted = 0
        for line in intake_lines:
            line = line.strip()
            if not line:
                continue
            try:
                spec = json.loads(line)
            except ValueError:
                continue
            if str(spec.get("id")) in self.requests:
                continue
            code, resp = self.submit_raw(spec, source="journal-intake")
            if code == 200:
                readmitted += 1
            else:
                log.warning("serve resume: could not re-admit %s: %s",
                            spec.get("id"), resp)
        if readmitted:
            log.info("serve resume: re-admitted %d uncommitted "
                     "request(s) from the intake sidecar", readmitted)

    # -- the loop ------------------------------------------------------
    def run(self) -> dict:
        reg = get_registry()
        reg.set_info("run_path", "serve")
        self._replay_journal()
        log.info("##### GOSSIP-AS-A-SERVICE: %d lane(s) x %d rounds "
                 "(block %d), n=%d, budget %s #####", self.K, self.total,
                 self.block, self.N,
                 self.config.serve_memory_budget or "unmetered")
        if self.telemetry_server is not None:
            log.info("serve: intake at http://127.0.0.1:%d/submit",
                     self.telemetry_server.port)
        try:
            while True:
                if shutdown_requested() and not self._draining:
                    with self.lock:
                        self._draining = True
                        busy = sum(1 for l in self.lanes
                                   if l is not None)
                    log.warning("serve: shutdown requested — draining "
                                "%d in-flight lane(s), admissions "
                                "closed", busy)
                with self.lock:
                    if not self._draining:
                        from .intake import scan_spool
                        scan_spool(self)
                        self._admit_ready()
                    any_active = any(l is not None for l in self.lanes)
                if any_active:
                    self._dispatch_block()
                    with self.lock:
                        self._retire_finished()
                        # backfill freed lanes immediately so the next
                        # block runs full — unless a shutdown arrived
                        # while this block ran (a commit's kill-after
                        # hook included): drain must not admit NEW work,
                        # only finish what is already on the device
                        if not self._draining and not shutdown_requested():
                            self._admit_ready()
                elif self._draining:
                    raise ResumableInterrupt(
                        f"serve drained ({self._completions} request(s) "
                        f"committed)")
                else:
                    time.sleep(0.05)
                with self.lock:
                    reg.set_info("serve", self.serve_view())
                    busy = sum(1 for l in self.lanes if l is not None)
                    queued = self.admission.queue_depth()
                if busy or queued:
                    self._idle_since = time.time()
                if (self.config.serve_max_requests > 0
                        and self._completions
                        >= self.config.serve_max_requests
                        and not busy):
                    log.info("serve: --serve-max-requests %d reached; "
                             "exiting", self.config.serve_max_requests)
                    break
                if (self.config.serve_idle_timeout_s > 0
                        and not busy and not queued
                        and time.time() - self._idle_since
                        > self.config.serve_idle_timeout_s):
                    log.info("serve: idle for %.1fs; exiting",
                             self.config.serve_idle_timeout_s)
                    break
        finally:
            with self.lock:
                reg.set_info("serve", self.serve_view())
            if self.journal is not None:
                self.journal.close()
        return self.summary()

    def summary(self) -> dict:
        a = self.admission
        out = {
            "requests_received": a.counters["received"],
            "requests_admitted": a.counters["admitted"],
            "requests_rejected": a.counters["rejected"],
            "requests_completed": self._completions,
            "lanes": self.K,
            "block_rounds": self.block,
        }
        sims = [s for s in self.collection.collection if not s.is_empty()]
        if sims:
            out["coverage_mean"] = float(
                np.mean([s.coverage_stats.mean for s in sims]))
            out["rmr_mean"] = float(
                np.mean([s.rmr_stats.mean for s in sims]))
        return out


def run_serve(config, json_rpc_url, dp_queue, start_ts,
              telemetry_server) -> dict:
    """cli.main()'s serve dispatch: build the daemon and run it on the
    calling (main) thread until a terminal condition or a drain-and-exit
    (ResumableInterrupt -> exit code 75 via main's existing handler)."""
    daemon = ServeDaemon(config, json_rpc_url, dp_queue, start_ts,
                         telemetry_server)
    return daemon.run()
