"""Persistent XLA compilation cache wiring + hit/miss accounting.

The dynamic-knob split (engine/params.py) makes sweeps compile-once
*within* a process; this module extends the amortization *across*
processes: point JAX's persistent compilation cache at a directory
(``--compilation-cache-dir`` or the ``GOSSIP_COMPILATION_CACHE`` env var)
and every compiled executable — the round scan, init, the oracle-parity
harnesses — is serialized there, so repeat CLI runs, CI jobs and bench
rungs skip straight to execution.

JAX's defaults only persist programs that took >= 1s to compile and are
>= some size; :func:`enable_persistent_cache` zeroes both thresholds so
CI-scale programs persist too.  Hit/miss counts are collected from
``jax.monitoring`` events and surfaced in run reports and BENCH lines
(``compilation_cache`` section).

This module imports JAX lazily: importing it costs nothing, only enabling
the cache touches the backend config.
"""

from __future__ import annotations

import logging
import os

log = logging.getLogger(__name__)

ENV_VAR = "GOSSIP_COMPILATION_CACHE"

_counts = {"hits": 0, "misses": 0}
_listener_registered = False
_enabled_dir: str | None = None


def _on_event(event: str, **kwargs) -> None:
    if event == "/jax/compilation_cache/cache_hits":
        _counts["hits"] += 1
    elif event == "/jax/compilation_cache/cache_misses":
        _counts["misses"] += 1


def enable_persistent_cache(path: str = "") -> str | None:
    """Enable JAX's persistent compilation cache at ``path``.

    ``path`` falls back to the ``GOSSIP_COMPILATION_CACHE`` env var; with
    neither set this is a no-op returning None.  Returns the directory in
    effect.  Idempotent — the CLI's sweep loops call it once per simulated
    point."""
    global _listener_registered, _enabled_dir
    path = path or os.environ.get(ENV_VAR, "")
    if not path:
        return _enabled_dir
    if path == _enabled_dir:
        # already in effect: repeat calls (one per sweep point) must not
        # rewrite jax config or reset the live cache handle
        return _enabled_dir
    import jax

    os.makedirs(path, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", path)
    for flag, value in (
            # persist every program, however small/fast — a CI sweep's
            # first process should hand its successor a warm cache
            ("jax_persistent_cache_min_entry_size_bytes", -1),
            ("jax_persistent_cache_min_compile_time_secs", 0)):
        try:
            jax.config.update(flag, value)
        except Exception:  # pragma: no cover - flag renamed in other jax
            log.debug("persistent-cache flag %s unavailable", flag)
    # JAX initializes its cache handle exactly once, on the first compile.
    # Importing the engine already compiled tiny module constants, so that
    # one-shot init ran with no directory configured and pinned the cache
    # off; reset it so the directory set above takes effect.
    try:
        from jax.experimental.compilation_cache import (compilation_cache as
                                                        _cc)
        _cc.reset_cache()
    except Exception:  # pragma: no cover - internal API drift
        log.warning("could not re-initialize the JAX compilation cache; "
                    "persistent caching may be inactive this process")
    if not _listener_registered:
        try:
            from jax import monitoring
            monitoring.register_event_listener(_on_event)
            _listener_registered = True
        except Exception:  # pragma: no cover - monitoring API drift
            log.debug("jax.monitoring listener unavailable; persistent-"
                      "cache hit/miss counts will read 0")
    if _enabled_dir != path:
        log.info("persistent compilation cache enabled at %s", path)
    _enabled_dir = path
    return path


def persistent_cache_counters() -> dict:
    """{"hits": ..., "misses": ...} observed since the cache was enabled
    (all zero when it never was)."""
    return dict(_counts)


def persistent_cache_dir() -> str | None:
    """The directory in effect, or None when the cache is disabled."""
    return _enabled_dir
