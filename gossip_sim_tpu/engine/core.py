"""The five-verb gossip round as batched dense-array kernels.

One ``SimState`` holds ``O`` independent single-origin simulations over an
``N``-node cluster (the reference simulates exactly one origin per run,
gossip_main.rs:292-647; batching origins is the north-star parallelization,
SURVEY.md §2.3).  Per round, matching gossip_main.rs:449-473:

  1. push/diffuse  — fanout-target selection + frontier relaxation
                     (replaces the sequential BFS, gossip.rs:494-615)
  2. consume       — rank inbound peers by (hop, node index) and merge into
                     the received cache (gossip.rs:618-653,
                     received_cache.rs:83-98)
  3. prune decide  — upsert-gated (score, stake) ranking + stake-threshold
                     prefix rule (received_cache.rs:38-63,100-131)
  4. prune apply   — set per-slot pruned bits in the senders' active entries
                     (push_active_set.rs:56-71,143-151)
  5. rotate        — Bernoulli(p) incremental rotation: swap one weighted
                     sample in, evict the oldest slot (gossip.rs:739-754,
                     push_active_set.rs:153-186)

Key origin-reduction insight: stakes are static, so for a fixed origin ``o``
every node ``s`` reads/writes exactly ONE active-set entry — bucket
``min(bucket(s), bucket(o))`` (push_active_set.rs:48,68; bucketing is
monotone in stake, so bucket(min) == min(bucket)).  Each of the O sims
therefore tracks a single [N, S] active-set slice instead of [N, 25, S],
and the 25-bucket structure survives only in the rotation weights.

Documented divergences from the reference (all distribution-level, none
affecting the semantics downstream of sampling):

  * WeightedShuffle -> stake-class categorical sampling (see sampler.py);
    parity is distributional (selection probability ∝ weight).
  * The per-peer pruned-origin Bloom filter (0.1 false-positive rate,
    push_active_set.rs:122-123) is an exact per-slot bit: the engine never
    over-prunes from bloom false positives.  The self-seeded entry
    (push_active_set.rs:179) is the exact ``peer != origin`` mask.
  * Inbound peers per (dest, round) are ranked exactly but only the first
    ``inbound_cap`` ranks are recorded (reference records all); ranks >= 2
    only reserve score-0 slots, so the tail is statistics-neutral in
    realistic regimes.  Dropped edges are counted in ``rows["inb_dropped"]``.
  * The received-cache entry is ``rc_slots`` physical slots; the reference's
    50-entry *insert cap* (received_cache.rs:78) is enforced exactly, but a
    pathological mix of unconditional scored inserts could exceed the
    physical slots; overflow evicts the largest node ids and is counted in
    ``rows["rc_overflow"]``.
  * On exact (score, stake) prune ties the reference's unstable sort is
    nondeterministic; the engine tie-breaks by node index ascending (the
    CPU oracle tie-breaks by pubkey bytes — craft distinct stakes in parity
    tests).
  * Per-thread entropy RNG (gossip.rs:747-753) is replaced by
    ``fold_in(key, origin)``/``fold_in(key, round)`` counter-based streams:
    deterministic by construction and independent of origin-batch chunking.
  * Initialization samples active-set peers with replacement and keeps the
    first S distinct (``init_draws`` tries); under extreme stake skew an
    entry can start underfilled where the reference's WeightedShuffle always
    fills to size.  Underfilled slots hold the sentinel ``N`` (never pushed
    to) and are topped up by rotation events over time; callers can audit
    fill via ``(state.active == N).sum()``.
"""

from __future__ import annotations

import math
from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..identity import stake_buckets_array
from .params import EngineParams
from .sampler import SamplerTables, build_sampler_tables, sample_peers

INF = jnp.int32(1 << 20)  # unreached sentinel (maps to u64::MAX, gossip.rs:490)


class ClusterTables(NamedTuple):
    """Static per-cluster device tables."""

    stakes: jax.Array    # [N + 1] i64 lamports; index N is a 0 pad (sentinel)
    buckets: jax.Array   # [N] i32 log2 stake buckets (push_active_set.rs:190-196)
    sampler: SamplerTables


class SimState(NamedTuple):
    """O batched independent single-origin simulations (the carried pytree)."""

    key: jax.Array          # [O, 2] u32 per-origin PRNG key
    active: jax.Array       # [O, N, S] i32 peer per slot, oldest->newest; N = empty
    pruned: jax.Array       # [O, N, S] bool peer-has-pruned-this-origin bit
    rc_src: jax.Array       # [O, N, C] i32 received-cache peers, sorted asc; N = empty
    rc_score: jax.Array     # [O, N, C] i32 per-peer scores (received_cache.rs:83-98)
    rc_upserts: jax.Array   # [O, N] i32 upsert counter (received_cache.rs:13-21)
    failed: jax.Array       # [O, N] bool fault-injection mask (gossip.rs:756-771)
    egress_acc: jax.Array   # [O, N] i32 measured-round egress message counts
    ingress_acc: jax.Array  # [O, N] i32 measured-round ingress message counts
    prune_acc: jax.Array    # [O, N] i32 measured-round prune messages sent
    stranded_acc: jax.Array  # [O, N] i32 measured rounds each node was stranded
    hops_hist_acc: jax.Array  # [O, H] i32 aggregate hop histogram (measured)


def make_cluster_tables(stakes_lamports: np.ndarray) -> ClusterTables:
    """Build static device tables from the per-node stake vector."""
    stakes = np.asarray(stakes_lamports, dtype=np.int64)
    buckets = stake_buckets_array(stakes.astype(np.uint64)).astype(np.int32)
    return ClusterTables(
        stakes=jnp.asarray(np.concatenate([stakes, [0]])),
        buckets=jnp.asarray(buckets),
        sampler=build_sampler_tables(buckets),
    )


# --------------------------------------------------------------------------
# small vector utilities
# --------------------------------------------------------------------------

def _row_searchsorted(sorted_rows: jax.Array, queries: jax.Array) -> jax.Array:
    """Left-bisect each query into its row of ``sorted_rows``.

    sorted_rows [..., C] ascending; queries [..., K] -> positions [..., K].
    Fixed-trip binary search (log2(C) gathers) — avoids the O(K*C)
    broadcast-compare blowup at production shapes.
    """
    C = sorted_rows.shape[-1]
    lo = jnp.zeros(queries.shape, jnp.int32)
    hi = jnp.full(queries.shape, C, jnp.int32)
    for _ in range(max(1, math.ceil(math.log2(C))) + 1):
        active = lo < hi
        mid = (lo + hi) // 2
        vals = jnp.take_along_axis(sorted_rows, jnp.minimum(mid, C - 1), axis=-1)
        less = vals < queries
        lo = jnp.where(active & less, mid + 1, lo)
        hi = jnp.where(active & ~less, mid, hi)
    return lo


def _gather_rows(mat: jax.Array, t_idx: jax.Array, pos: jax.Array) -> jax.Array:
    """mat [O, N, C]; t_idx/pos [O, ...] -> mat[o, t_idx, pos] elementwise."""
    O = mat.shape[0]
    o_idx = jnp.arange(O).reshape((O,) + (1,) * (t_idx.ndim - 1))
    return mat[o_idx, t_idx, pos]


# --------------------------------------------------------------------------
# initialization
# --------------------------------------------------------------------------

def init_state(key: jax.Array, tables: ClusterTables, origins: jax.Array,
               params: EngineParams) -> SimState:
    """Build O fresh single-origin sims with rotated-in active sets.

    Initialization mirrors ``initialize_gossip`` (gossip_main.rs:263-277 ->
    gossip.rs:805-813): every node's tracked entry is rotated from empty.
    Rotating an empty entry inserts weighted-distinct peers until the entry
    *exceeds* ``size`` and then evicts the oldest (push_active_set.rs:165-185)
    — i.e. the kept set is distinct samples #2..S+1 when more than S are
    available, else all of them.
    """
    p = params.validate()
    N, S, E = p.num_nodes, p.active_set_size, p.init_draws
    O = int(origins.shape[0])
    origins = origins.astype(jnp.int32)

    okeys = jax.vmap(jax.random.fold_in, in_axes=(None, 0))(key, origins)
    # Domain-separate the init stream from the per-round streams (both fold
    # small integers into the same per-origin key otherwise).
    draw_keys = jax.vmap(jax.random.fold_in, in_axes=(0, None))(
        okeys, 0x696E6974)
    b = tables.buckets
    k_os = jnp.minimum(b[None, :], b[origins][:, None])          # [O, N]
    self_idx = jnp.arange(N, dtype=jnp.int32)[None, :]

    def draw_step(carry, e):
        buf, cnt = carry                                         # [O,N,S+1], [O,N]
        ek = jax.vmap(jax.random.fold_in, in_axes=(0, None))(draw_keys, e)
        u = jax.vmap(lambda k: jax.random.uniform(k, (N, 2), dtype=jnp.float32))(ek)
        cand = sample_peers(tables.sampler, k_os, u[..., 0], u[..., 1])
        dup = jnp.any(buf == cand[..., None], axis=-1) | (cand == self_idx)
        ins = (~dup) & (cnt <= S)
        slot = jnp.minimum(cnt, S)
        oh = (jnp.arange(S + 1)[None, None, :] == slot[..., None]) & ins[..., None]
        buf = jnp.where(oh, cand[..., None], buf)
        return (buf, cnt + ins.astype(jnp.int32)), None

    buf0 = jnp.full((O, N, S + 1), N, dtype=jnp.int32)
    (buf, cnt), _ = lax.scan(draw_step, (buf0, jnp.zeros((O, N), jnp.int32)),
                             jnp.arange(E))
    # Evict the oldest iff the entry overfilled (push_active_set.rs:182-185).
    active = jnp.where((cnt > S)[..., None], buf[..., 1:], buf[..., :S])

    C, H = p.rc_slots, p.hist_bins
    zi = lambda shape: jnp.zeros(shape, jnp.int32)
    return SimState(
        key=okeys,
        active=active,
        pruned=jnp.zeros((O, N, S), bool),
        rc_src=jnp.full((O, N, C), N, jnp.int32),
        rc_score=zi((O, N, C)),
        rc_upserts=zi((O, N)),
        failed=jnp.zeros((O, N), bool),
        egress_acc=zi((O, N)),
        ingress_acc=zi((O, N)),
        prune_acc=zi((O, N)),
        stranded_acc=zi((O, N)),
        hops_hist_acc=zi((O, H)),
    )


# --------------------------------------------------------------------------
# the round
# --------------------------------------------------------------------------

def round_step(params: EngineParams, tables: ClusterTables, origins: jax.Array,
               state: SimState, it: jax.Array, detail: bool = False):
    """One full gossip round for all O origin-sims.  Returns (state, rows).

    ``rows`` is a dict of [O]-shaped per-round statistics; with
    ``detail=True`` it additionally carries the [O, N] stranded mask (for
    the per-iteration stranded-stake stats, gossip_stats.rs:766-843).
    """
    p = params
    N, S, F, C, K, H = (p.num_nodes, p.active_set_size, p.push_fanout,
                        p.rc_slots, p.inbound_cap, p.hist_bins)
    O = int(origins.shape[0])
    origins = origins.astype(jnp.int32)
    o1 = jnp.arange(O)
    o2 = o1[:, None]
    o3 = o1[:, None, None]
    n_idx = jnp.arange(N, dtype=jnp.int32)[None, :]

    kr = jax.vmap(jax.random.fold_in, in_axes=(0, None))(state.key, it)
    nsub = p.rot_tries + 2
    subs = jax.vmap(lambda k: jax.random.split(k, nsub))(kr)     # [O, nsub, 2]

    # ---- fault injection (gossip.rs:756-771; fires when it == when_to_fail,
    # gossip_main.rs:449-452) --------------------------------------------
    failed = state.failed
    # truncating, like the reference's `as usize` (gossip.rs:758)
    n_fail = int(p.fail_fraction * N)
    if p.fail_at >= 0 and n_fail > 0:
        def _fail(f):
            r = jax.vmap(lambda k: jax.random.uniform(k, (N,), dtype=jnp.float32))(
                subs[:, 0])
            kth = jnp.sort(r, axis=-1)[:, n_fail - 1][:, None]
            return f | (r <= kth)
        failed = lax.cond(it == p.fail_at, _fail, lambda f: f, failed)

    # ---- verb 1: push/diffuse (gossip.rs:494-615) -----------------------
    peer = state.active
    origin_col = origins[:, None, None]
    is_peer = peer < N
    # get_nodes filter: bloom-contains(origin) == pruned bit OR peer == origin
    # (self-seeded bloom, push_active_set.rs:128-141,179).
    valid = is_peer & (~state.pruned) & (peer != origin_col)
    sel = valid & (jnp.cumsum(valid, axis=-1) <= F)   # first F unpruned slots
    peer_c = jnp.minimum(peer, N - 1)
    peer_failed = failed[o3, peer_c] & is_peer
    # Failed targets consume a fanout slot but receive nothing (gossip.rs:538-541).
    tgt = jnp.where(sel & ~peer_failed, peer, N)                 # [O, N, S]

    dist0 = jnp.full((O, N), INF, jnp.int32).at[o1, origins].set(0)

    def relax(carry):
        dist, _ = carry
        cand = jnp.where(dist < INF, dist + 1, INF)[:, :, None]
        cand = jnp.broadcast_to(cand, tgt.shape)
        new = dist.at[o3, tgt].min(cand, mode="drop")
        return new, jnp.any(new != dist)

    dist, _ = lax.while_loop(lambda c: c[1], relax,
                             (dist0, jnp.bool_(True)))
    reached = dist < INF

    live = (tgt < N) & reached[:, :, None]
    edge_tgt = jnp.where(live, tgt, N)
    deg_out = jnp.sum(live, axis=-1, dtype=jnp.int32)            # [O, N]
    n_reached = jnp.sum(reached, axis=-1, dtype=jnp.int32)       # [O]
    m_push = jnp.sum(deg_out, axis=-1, dtype=jnp.int32)          # [O]

    egress_round = deg_out
    ingress_round = jnp.zeros((O, N), jnp.int32).at[o3, edge_tgt].add(
        1, mode="drop")

    # ---- verb 2: consume (gossip.rs:618-653) ----------------------------
    # Rank inbound edges per dest by (hop, src index) — index order equals
    # the reference's pubkey-string sort by NodeIndex construction
    # (gossip.rs:638-645; identity.NodeIndex).
    hop1 = jnp.minimum(dist + 1, H - 1)
    key1 = edge_tgt.reshape(O, N * S)
    key2 = (hop1[:, :, None] * N + n_idx[:, :, None]).astype(jnp.int32)
    key2 = jnp.broadcast_to(key2, (O, N, S)).reshape(O, N * S)
    tgt_s, key2_s = lax.sort((key1, key2), dimension=-1, num_keys=2)
    src_s = key2_s % N
    eidx = jnp.arange(N * S, dtype=jnp.int32)[None, :]
    is_start = jnp.concatenate(
        [jnp.ones((O, 1), bool), tgt_s[:, 1:] != tgt_s[:, :-1]], axis=1)
    seg_start = lax.cummax(jnp.where(is_start, eidx, 0), axis=1)
    rank = eidx - seg_start
    inb = jnp.full((O, N, K), N, jnp.int32).at[
        o2, tgt_s, rank].set(src_s, mode="drop")
    inb_dropped = jnp.sum((rank >= K) & (tgt_s < N), axis=-1, dtype=jnp.int32)

    # merge inbound into the received cache (received_cache.rs:83-98)
    rc_src, rc_score = state.rc_src, state.rc_score
    pos = _row_searchsorted(rc_src, inb)                         # [O, N, K]
    pos_c = jnp.minimum(pos, C - 1)
    found = (inb < N) & (pos < C) & (
        jnp.take_along_axis(rc_src, pos_c, axis=-1) == inb)
    for r in (0, 1):  # num_dups < NUM_DUPS_THRESHOLD -> score += 1
        oh = (jnp.arange(C)[None, None, :] == pos_c[..., r:r + 1])
        rc_score = rc_score + (oh & found[..., r:r + 1]).astype(jnp.int32)

    base_len = jnp.sum(rc_src < N, axis=-1, dtype=jnp.int32)

    def ins_step(ln, x):
        found_r, inb_r, r = x
        want = (inb_r < N) & ~found_r
        # scored ranks insert unconditionally; others honor the 50-entry cap
        # (received_cache.rs:92-97)
        allowed = want & ((r < 2) | (ln < p.received_cap))
        return ln + allowed.astype(jnp.int32), allowed

    _, allowed_t = lax.scan(
        ins_step, base_len,
        (jnp.moveaxis(found, -1, 0), jnp.moveaxis(inb, -1, 0),
         jnp.arange(K)))
    allowed = jnp.moveaxis(allowed_t, 0, -1)                     # [O, N, K]
    acc_src = jnp.where(allowed, inb, N)
    acc_score = (allowed & (jnp.arange(K)[None, None, :] < 2)).astype(jnp.int32)
    acc_src, acc_score = lax.sort((acc_src, acc_score), dimension=-1, num_keys=1)

    # merge two sorted-by-src lists via rank addition (no full re-sort)
    n3 = jnp.arange(N)[None, :, None]
    merged_src = jnp.full((O, N, C + K), N, jnp.int32)
    merged_score = jnp.zeros((O, N, C + K), jnp.int32)
    p_old = jnp.arange(C, dtype=jnp.int32) + _row_searchsorted(acc_src, rc_src)
    p_old = jnp.where(rc_src < N, p_old, C + K)  # sentinels -> dropped
    merged_src = merged_src.at[o3, n3, p_old].set(rc_src, mode="drop")
    merged_score = merged_score.at[o3, n3, p_old].set(rc_score, mode="drop")
    p_new = jnp.arange(K, dtype=jnp.int32) + _row_searchsorted(rc_src, acc_src)
    p_new = jnp.where(acc_src < N, p_new, C + K)
    merged_src = merged_src.at[o3, n3, p_new].set(acc_src, mode="drop")
    merged_score = merged_score.at[o3, n3, p_new].set(acc_score, mode="drop")
    rc_overflow = jnp.sum(merged_src[..., C:] < N, axis=(-2, -1),
                          dtype=jnp.int32)
    rc_src = merged_src[..., :C]
    rc_score = merged_score[..., :C]

    any_inb = inb[..., 0] < N  # a rank-0 record is one upsert (received_cache.rs:85-87)
    rc_ups = state.rc_upserts + any_inb.astype(jnp.int32)

    # ---- verb 3: prune decide (received_cache.rs:38-63,100-131) ---------
    fired = rc_ups >= p.min_num_upserts
    stake_dest = tables.stakes[:N][None, :]                      # [1, N] i64
    stake_org = tables.stakes[origins][:, None]                  # [O, 1]
    min_stake = jnp.minimum(stake_dest, stake_org)
    # f64 multiply then u64 truncation, as the reference does
    # (received_cache.rs:112-115).
    min_ingress_stake = (min_stake.astype(jnp.float64)
                         * p.prune_stake_threshold).astype(jnp.int64)

    member = rc_src < N
    m_stake = tables.stakes[rc_src]                              # pad -> 0
    neg_score = jnp.where(member, -rc_score, jnp.iinfo(jnp.int32).max)
    neg_stake = jnp.where(member, -m_stake, jnp.iinfo(jnp.int64).max)
    _, _, src_sorted = lax.sort(
        (neg_score, neg_stake, rc_src), dimension=-1, num_keys=3)
    memb_sorted = src_sorted < N
    stake_sorted = tables.stakes[src_sorted]
    cum_excl = jnp.cumsum(stake_sorted, axis=-1) - stake_sorted
    posn = jnp.arange(C)[None, None, :]
    pruned_slot = (memb_sorted
                   & (posn >= p.min_ingress_nodes)
                   & (cum_excl >= min_ingress_stake[..., None])
                   & (src_sorted != origin_col)
                   & fired[..., None])
    n_pruned = jnp.sum(pruned_slot, axis=-1, dtype=jnp.int32)    # [O, N] per pruner
    m_prunes = jnp.sum(n_pruned, axis=-1, dtype=jnp.int32)       # [O]
    # Prune messages count toward RMR's m (gossip.rs:684-687).

    # ---- verb 4: prune apply (push_active_set.rs:56-71,143-151) ---------
    pr_sorted = lax.sort(jnp.where(pruned_slot, src_sorted, N), dimension=-1)
    t_c = peer_c  # current active peers; prune touches existing entries only
    q = jnp.broadcast_to(jnp.arange(N, dtype=jnp.int32)[None, :, None],
                         (O, N, S))
    lo = jnp.zeros((O, N, S), jnp.int32)
    hi = jnp.full((O, N, S), C, jnp.int32)
    for _ in range(max(1, math.ceil(math.log2(C))) + 1):
        act = lo < hi
        mid = (lo + hi) // 2
        vals = _gather_rows(pr_sorted, t_c, jnp.minimum(mid, C - 1))
        less = vals < q
        lo = jnp.where(act & less, mid + 1, lo)
        hi = jnp.where(act & ~less, mid, hi)
    hit = (lo < C) & (_gather_rows(pr_sorted, t_c, jnp.minimum(lo, C - 1)) == q)
    pruned_bits = state.pruned | (hit & is_peer)

    # mem::take on fire: the whole entry resets (received_cache.rs:48-55)
    rc_src = jnp.where(fired[..., None], N, rc_src)
    rc_score = jnp.where(fired[..., None], 0, rc_score)
    rc_ups = jnp.where(fired, 0, rc_ups)

    # ---- verb 5: rotate (gossip.rs:739-754; push_active_set.rs:153-186) -
    b = tables.buckets
    k_os = jnp.minimum(b[None, :], b[origins][:, None])
    rot_u = jax.vmap(lambda k: jax.random.uniform(k, (N,), dtype=jnp.float32))(
        subs[:, 1])
    rotate = rot_u < p.probability_of_rotation
    chosen = jnp.full((O, N), N, jnp.int32)
    found_new = jnp.zeros((O, N), bool)
    self_i = jnp.arange(N, dtype=jnp.int32)[None, :]
    active_now = peer
    for t in range(p.rot_tries):
        u = jax.vmap(lambda k: jax.random.uniform(k, (N, 2), dtype=jnp.float32))(
            subs[:, 2 + t])
        cand = sample_peers(tables.sampler, k_os, u[..., 0], u[..., 1])
        ok = ((cand != self_i)
              & ~jnp.any(active_now == cand[..., None], axis=-1))
        take = ok & ~found_new
        chosen = jnp.where(take, cand, chosen)
        found_new = found_new | ok
    do_rot = rotate & found_new
    rot_failed = jnp.sum(rotate & ~found_new, axis=-1, dtype=jnp.int32)

    mcnt = jnp.sum(active_now < N, axis=-1, dtype=jnp.int32)
    full_row = mcnt >= S
    shift_act = jnp.concatenate([active_now[..., 1:], chosen[..., None]], axis=-1)
    shift_prn = jnp.concatenate(
        [pruned_bits[..., 1:], jnp.zeros((O, N, 1), bool)], axis=-1)
    slot_oh = (jnp.arange(S)[None, None, :] == jnp.minimum(mcnt, S - 1)[..., None])
    append_act = jnp.where(slot_oh & ~full_row[..., None],
                           chosen[..., None], active_now)
    new_active = jnp.where(do_rot[..., None],
                           jnp.where(full_row[..., None], shift_act, append_act),
                           active_now)
    new_pruned = jnp.where((do_rot & full_row)[..., None], shift_prn, pruned_bits)

    # ---- statistics (gossip_stats.rs; on-device reductions) -------------
    hr = jnp.zeros((O, H), jnp.int32).at[
        o2, jnp.minimum(dist, H - 1)].add(reached.astype(jnp.int32))
    pos_counts = hr.at[:, 0].set(0)          # HopsStat filters origin's 0 hops
    cnt = jnp.sum(pos_counts, axis=-1)
    hsum = jnp.sum(pos_counts * jnp.arange(H)[None, :], axis=-1)
    hop_mean = jnp.where(cnt > 0, hsum / jnp.maximum(cnt, 1), jnp.nan)
    csum = jnp.cumsum(pos_counts[:, 1:], axis=-1)                # [O, H-1]
    lo_i = (cnt - 1) // 2
    hi_i = cnt // 2
    val_of = lambda i: 1 + jnp.sum((csum <= i[:, None]).astype(jnp.int32), axis=-1)
    hop_median = jnp.where(cnt > 0, (val_of(lo_i) + val_of(hi_i)) / 2.0, 0.0)
    pos_hops = jnp.where(reached & (dist > 0), dist, 0)
    hop_max = jnp.max(pos_hops, axis=-1)
    hop_min = jnp.where(
        cnt > 0,
        jnp.min(jnp.where(reached & (dist > 0), dist, INF), axis=-1), 0)

    stranded = (~reached) & (~failed)
    stranded_cnt = jnp.sum(stranded, axis=-1, dtype=jnp.int32)
    m_total = m_push + m_prunes
    nn = n_reached
    rmr = jnp.where(nn > 1, m_total / jnp.maximum(nn - 1, 1) - 1.0, 0.0)
    branching = m_push / jnp.maximum(nn, 1)   # Σ|pushes[src]| / |pushes|

    measured = it >= p.warm_up_rounds
    g = measured.astype(jnp.int32)
    new_state = SimState(
        key=state.key,
        active=new_active,
        pruned=new_pruned,
        rc_src=rc_src,
        rc_score=rc_score,
        rc_upserts=rc_ups,
        failed=failed,
        egress_acc=state.egress_acc + g * egress_round,
        ingress_acc=state.ingress_acc + g * ingress_round,
        prune_acc=state.prune_acc + g * n_pruned,
        stranded_acc=state.stranded_acc + g * stranded.astype(jnp.int32),
        hops_hist_acc=state.hops_hist_acc + g * hr,
    )
    rows = {
        "coverage": (n_reached / N).astype(jnp.float32),
        "unvisited": (N - n_reached).astype(jnp.int32),
        "m": m_total,
        "n": nn,
        "rmr": rmr.astype(jnp.float32),
        "hop_mean": hop_mean.astype(jnp.float32),
        "hop_median": hop_median.astype(jnp.float32),
        "hop_max": hop_max.astype(jnp.int32),
        "hop_min": hop_min.astype(jnp.int32),
        "stranded": stranded_cnt,
        "branching": branching.astype(jnp.float32),
        "prunes_sent": m_prunes,
        "inb_dropped": inb_dropped,
        "rc_overflow": rc_overflow,
        "rot_failed": rot_failed,
    }
    if detail:
        rows["stranded_mask"] = stranded
        rows["dist"] = jnp.where(reached, dist, -1).astype(jnp.int32)
    return new_state, rows


# --------------------------------------------------------------------------
# multi-round runner
# --------------------------------------------------------------------------

@partial(jax.jit, static_argnums=(0, 4, 5), donate_argnums=(3,))
def _run(params, tables, origins, state, num_iters, detail, start_it):
    def step(st, it):
        return round_step(params, tables, origins, st, it, detail=detail)
    its = jnp.arange(num_iters) + start_it
    return lax.scan(step, state, its)


def run_rounds(params: EngineParams, tables: ClusterTables, origins: jax.Array,
               state: SimState, num_iters: int, start_it=0,
               detail: bool = False):
    """Run ``num_iters`` rounds under one jitted scan (the reference's hot
    loop, gossip_main.rs:425-565).  Returns (state, rows-of-arrays with a
    leading [num_iters] axis)."""
    return _run(params, tables, origins, state, int(num_iters), bool(detail),
                jnp.asarray(start_it, jnp.int32))
