"""The five-verb gossip round as sort-routed dense-array kernels (v2).

Same semantics and state layout as the original engine (see the docstring
history in git), re-architected around the TPU's primitive cost profile as
measured on-chip (tools/prim_bench*.py):

  * ``lax.sort`` moves data at ~1.4 ns/element (row-local sorts ~0.15),
  * gathers and scatters cost ~7-11 ns/element and serialize,
  * elementwise/VPU work is effectively free at these shapes.

Every cross-node data movement is therefore expressed as a *sort*:

  * BFS frontier propagation (gossip.rs:494-615): per hop, edges carry a
    "source is on the frontier" bit to their targets via a 1-key sort of
    ``target*2 + (1-bit)``; with one pseudo-edge appended per target, the
    run-start entries are exactly one per target, and a second 1-key sort
    (run-starts first) compacts them into a dense ``[O, N]`` frontier —
    no scatter, no gather.
  * Inbound ranking (gossip.rs:618-653): one 2-key sort by
    ``(target, hop<<14 | src)`` ranks every delivered edge; the same
    pseudo-edge trick compacts per-target inbound lists ``[O, N, K]`` and
    ingress counts without a scatter.
  * Received-cache merge (received_cache.rs:83-98): row-local sorts over
    ``C+K``-wide rows implement member lookup, score bumps, capacity-gated
    insertion and eviction.
  * Prune application (push_active_set.rs:56-71): pruner/prunee pairs and
    active-set edges meet in one shared sort keyed by
    ``peer*pack + owner`` (pack = 2^ceil(log2(N)), floor 16384, so clusters
    up to MAX_NODES = 32767 fit i32 keys); a budgeted fast path handles the common
    few-prunes case and a ``lax.cond`` falls back to the full-width sort
    when a row prunes more than ``pa_slots`` peers at once.
  * Weighted sampling (push_active_set.rs:96-111): the stake-class CDF is
    selected per (origin, node) with an elementwise ``min(bucket)`` trick
    (no per-node CDF gather), and the class->node-id translation runs
    through a sort-join instead of a table gather.

Node failure (gossip.rs:756-771) is tracked per active-set slot
(``tfail``) and maintained incrementally at rotation/failure events so the
hot path never gathers ``failed[peer]``.

Network impairments (faults.py) extend the one-shot failure with per-message
packet loss, continuous fail/recover churn, and a transient stake
bipartition.  Every impairment decision is a stateless counter hash of
``(impair_seed, iteration, node ids)`` computed bit-identically by the CPU
oracle, so parity stays testable under faults.  The blocks are gated on the
static ``EngineParams`` knobs: with all knobs at their defaults the compiled
round is the exact unimpaired graph (reference parity preserved).  Churn
rebuilds the ``tfail`` slot bits once per round via the same sort-join used
by the one-shot event; the partition side lookup is the one gather on the
impaired path (it only exists when ``partition_at >= 0``).

Documented divergences from the reference are unchanged from v1 (see
git history of this module): distributional sampling parity, exact prune
bits instead of 0.1-fp blooms, ``inbound_cap`` ranking, ``rc_slots``
physical slots, index tie-breaks, counter-based RNG streams.

Every stage of ``round_step`` is wrapped in a ``jax.named_scope`` (the
``round/*`` scopes), so an XProf/TensorBoard trace captured with
``--profile-dir`` (obs/) attributes device time to the protocol verbs.
Scopes are compile-time metadata: the emitted HLO is unchanged.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..adaptive import switch_update_arr
from ..faults import (SALT_CHURN, SALT_EDGE, edge_u32_arr, node_u32_arr,
                      rate_threshold_arr, round_basis_arr, stake_bipartition)
from ..identity import stake_buckets_array
from ..obs import capacity
from ..obs.spans import get_registry
from ..obs.trace import (TRACE_CANDIDATE, TRACE_DROPPED, TRACE_FAILED_TARGET,
                         TRACE_SUPPRESSED)
from ..pull import (PULL_DROPPED, PULL_MISS_ALREADY_HELD, PULL_MISS_BLOOM_FP,
                    PULL_MISS_CAPPED, PULL_MISS_NOT_HELD, PULL_PEER_FAILED,
                    PULL_RESPONSE, PULL_SUPPRESSED, SALT_PULL_BLOOM,
                    SALT_PULL_CLASS, SALT_PULL_LOSS, SALT_PULL_MEMBER)
from .params import EngineKnobs, EngineParams, EngineStatic
from .sampler import SamplerTables, build_sampler_tables

INF = jnp.int32(1 << 20)   # unreached sentinel (maps to u64::MAX, gossip.rs:490)
BIG = jnp.int32(0x7FFFFFFF)
BIG64 = jnp.int64(1 << 62)  # i64 twin of the BIG sort-key sentinel
# Node-id packing base for the shared i32 sort keys (peer*pack + owner).
# Chosen per cluster: 16384 keeps the round-4 key layout for N < 16384, one
# extra bit covers N up to MAX_NODES_I32.  The binding constraint is
# ((N-1)*pack + N-1)*2 + 1 < 2^31 with pack = 2^ceil(log2(N)), which holds
# through N = 32768 but collides with the BIG sentinel exactly there — so
# the i32 bound is 32767.  Past it the peer*pack+owner keys (prune apply,
# _lookup joins) switch to i64 sort keys (TPU-emulated sorts, ~2x cost;
# exact same join semantics).  The inbound (hop << pb | src) keys stay i32
# — their bound is hist_bins * pack < 2^31, checked per round_step call —
# which caps the supported cluster at MAX_NODES = 2^24 (16.7M nodes, the
# documented scale target) with the default hist_bins = 64.
MAX_NODES_I32 = 32767
MAX_NODES = 1 << 24
PACK = 16384               # default packing base (clusters with N < 16384)

#: Test hook: force the i64 sort-key paths even for clusters within the
#: i32 bound (parity tests drive the same cluster through both key widths).
#: NOT part of the jit compile key — call ``clear_compile_cache()`` after
#: toggling or the cached i32 executable keeps serving.
FORCE_I64_KEYS = False


def _keys_need_i64(num_nodes: int) -> bool:
    """True when the peer*pack+owner sort keys overflow i32 for this N."""
    return num_nodes > MAX_NODES_I32 or FORCE_I64_KEYS


def _pack_base(num_nodes: int) -> int:
    """Packing base for ``num_nodes`` node ids: smallest power of two >= N
    (floored at the historical 16384 so small clusters keep round-4 keys)."""
    return 1 << max(14, (num_nodes - 1).bit_length())


class ClusterTables(NamedTuple):
    """Static per-cluster device tables."""

    stakes: jax.Array    # [N + 1] i64 lamports; index N is a 0 pad (sentinel)
    buckets: jax.Array   # [N] i32 log2 stake buckets (push_active_set.rs:190-196)
    sampler: SamplerTables
    shi: jax.Array       # [N + 1] i32 stake >> 31 (sort-key split)
    slo: jax.Array       # [N + 1] i32 stake & 0x7fffffff
    side: jax.Array      # [N + 1] i32 stake-bipartition side (faults.py);
                         # index N is a 0 pad — only read under partition_at
    stake_decile: jax.Array  # [N] i32 stake-rank decile id, 0 (lowest
                             # stake) .. 9 (highest); segment ids for the
                             # on-device health digests (obs/health.py)


class SimState(NamedTuple):
    """O batched independent single-origin simulations (the carried pytree)."""

    key: jax.Array          # [O, 2] u32 per-origin PRNG key
    active: jax.Array       # [O, N, S] i32 peer per slot, oldest->newest; N = empty
    pruned: jax.Array       # [O, N, S] bool peer-has-pruned-this-origin bit
    tfail: jax.Array        # [O, N, S] bool peer-is-failed bit (== failed[peer])
    rc_src: jax.Array       # [O, N, C] i32 received-cache peers, sorted asc; N = empty
    rc_score: jax.Array     # [O, N, C] i32 per-peer scores (received_cache.rs:83-98)
    rc_shi: jax.Array       # [O, N, C] i32 member stake >> 31
    rc_slo: jax.Array       # [O, N, C] i32 member stake & 0x7fffffff
    rc_upserts: jax.Array   # [O, N] i32 upsert counter (received_cache.rs:13-21)
    failed: jax.Array       # [O, N] bool fault-injection mask (gossip.rs:756-771)
    egress_acc: jax.Array   # [O, N] i32 measured-round egress message counts
    ingress_acc: jax.Array  # [O, N] i32 measured-round ingress message counts
    prune_acc: jax.Array    # [O, N] i32 measured-round prune messages sent
    stranded_acc: jax.Array  # [O, N] i32 measured rounds each node was stranded
    hops_hist_acc: jax.Array  # [O, H] i32 aggregate hop histogram (measured;
                              # includes pull-sourced hops under pull modes)
    pull_hops_hist_acc: jax.Array  # [O, H] i32 pull-sourced hop histogram
                                   # (the pull-tagged slice of hops_hist_acc)
    pull_rescued_acc: jax.Array    # [O, N] i32 measured rounds each node was
                                   # rescued by a pull response (pull.py)
    health_prune_recv: jax.Array   # [O, N] i32 measured-round prune messages
                                   # *received* per node (the prunee-side twin
                                   # of prune_acc); zeros unless static.health
    health_first_round: jax.Array  # [O, N] i32 first round the origin's value
                                   # reached each node, encoded round+1 with
                                   # 0 = never reached; deliberately NOT
                                   # warm-up gated (a first delivery during
                                   # warm-up is still the first delivery);
                                   # zeros unless static.health
    adaptive_pull_on: jax.Array    # [O] bool direction bit (adaptive.py):
                                   # the pull phase runs this round iff set;
                                   # re-decided each round from push coverage
                                   # (always False outside mode="adaptive")


def make_cluster_tables(stakes_lamports: np.ndarray) -> ClusterTables:
    """Build static device tables from the per-node stake vector."""
    stakes = np.asarray(stakes_lamports, dtype=np.int64)
    if stakes.shape[0] > MAX_NODES:
        raise ValueError(
            f"engine packs (hop << pb | node) inbound sort keys into i32; "
            f"num_nodes must be <= {MAX_NODES}, got {stakes.shape[0]}")
    if not ((stakes >= 0).all() and (stakes < (1 << 62)).all()):
        raise ValueError("stakes must be in [0, 2^62)")
    buckets = stake_buckets_array(stakes.astype(np.uint64)).astype(np.int32)
    padded = np.concatenate([stakes, [0]])
    side = np.concatenate([stake_bipartition(stakes).astype(np.int32), [0]])
    # Stake-rank deciles: stable ascending sort so equal stakes tie-break by
    # node id, decile 0 = lowest-staked tenth.  Host-side numpy (like every
    # other table here) so the engine and the loop oracles share one id map.
    n = stakes.shape[0]
    order = np.argsort(stakes, kind="stable")
    rank = np.empty(n, dtype=np.int64)
    rank[order] = np.arange(n, dtype=np.int64)
    stake_decile = (rank * 10 // n).astype(np.int32)
    return ClusterTables(
        stakes=jnp.asarray(padded),
        buckets=jnp.asarray(buckets),
        sampler=build_sampler_tables(buckets),
        shi=jnp.asarray((padded >> 31).astype(np.int32)),
        slo=jnp.asarray((padded & 0x7FFFFFFF).astype(np.int32)),
        side=jnp.asarray(side),
        stake_decile=jnp.asarray(stake_decile),
    )


# --------------------------------------------------------------------------
# sort-routing utilities
# --------------------------------------------------------------------------

def _boundary(keys: jax.Array) -> jax.Array:
    """[O, M] -> mask of positions where a new key-run begins."""
    O = keys.shape[0]
    return jnp.concatenate(
        [jnp.ones((O, 1), bool), keys[:, 1:] != keys[:, :-1]], axis=1)


def _rank_in_run(run_of: jax.Array) -> jax.Array:
    """Position of each element within its (sorted, contiguous) run."""
    O, M = run_of.shape
    iot = jnp.broadcast_to(jnp.arange(M, dtype=jnp.int32)[None, :], (O, M))
    start = lax.cummax(jnp.where(_boundary(run_of), iot, 0), axis=1)
    return iot - start


def _lookup(table_vals: jax.Array, queries: jax.Array, n: int,
            pack: int = PACK) -> jax.Array:
    """Sort-join table lookup: ``table_vals[queries]`` without a gather.

    table_vals: [O, n] i32 per-origin table; queries: [O, M] i32 in [0, n).
    Entries and queries meet in one sort keyed by value; each value-run is
    headed by its (unique, always-present) table entry, whose payload is
    forward-filled through the run and routed back by original position.

    PRECONDITION: table values must lie in [0, pack) — the forward fill
    packs them as ``position*pack + value`` (i32 when ``W*pack`` fits,
    else i64) and recovers them with ``% pack``; out-of-range values would
    be silently corrupted.  Current callers pass perm indices
    (< n <= pack) and 0/1 flags.
    """
    O, M = queries.shape
    W = n + M
    iota_n = jnp.broadcast_to(
        jnp.arange(n, dtype=jnp.int32)[None, :], (O, n))
    keys = jnp.concatenate(
        [iota_n * 2, queries * 2 + 1], axis=1)                   # [O, n+M]
    vals = jnp.concatenate(
        [jnp.broadcast_to(table_vals, (O, n)),
         jnp.zeros((O, M), table_vals.dtype)], axis=1)
    pos = jnp.concatenate(
        [jnp.full((O, n), BIG), jnp.broadcast_to(
            jnp.arange(M, dtype=jnp.int32)[None, :], (O, M))], axis=1)
    sk, sv, sp = lax.sort((keys, vals, pos), dimension=-1, num_keys=1)
    have = (sk & 1) == 0
    # forward fill via one packed cummax: a query's head is the nearest
    # table entry to its left (its own value-run always starts with one).
    # i32 packing when the position*pack keys fit; the i64 twin (exact
    # same fill, 64-bit keys) covers wide joins — e.g. the rotate join at
    # W = N*(rot_tries+1) — and clusters past MAX_NODES_I32.
    if W * pack <= (1 << 31) and not FORCE_I64_KEYS:
        iw = jnp.arange(W, dtype=jnp.int32)[None, :]
        packed = jnp.where(have, iw * pack + sv.astype(jnp.int32), -1)
        fill = lax.cummax(packed, axis=1) % pack
    else:
        iw = jnp.arange(W, dtype=jnp.int64)[None, :]
        packed = jnp.where(have, iw * pack + sv.astype(jnp.int64),
                           jnp.int64(-1))
        fill = (lax.cummax(packed, axis=1) % pack).astype(jnp.int32)
    _, out = lax.sort((sp, fill.astype(jnp.int32)), dimension=-1, num_keys=1)
    return out[:, :M]


def _sample_fast(tables: ClusterTables, origins: jax.Array,
                 u_class: jax.Array, u_member: jax.Array):
    """Weighted peer draw for entry ``k = min(bucket(n), bucket(o))``.

    u_class/u_member: [O, N, T] f32.  Returns class-member positions
    [O, N, T] i32 in bucket-sorted space (translate with ``_lookup`` over
    ``sampler.perm``).  Identical math to sampler.sample_peers, but the CDF
    row is an elementwise select — ``min(b_n, b_o)`` equals ``b_n`` when
    ``b_n <= b_o`` (own row, static) and ``b_o`` otherwise (one dynamic row
    per origin) — so no per-node CDF gather is needed.
    """
    s = tables.sampler
    b = tables.buckets                                   # [N]
    b_o = tables.buckets[origins]                        # [O]
    cdf_own = s.cdf_own                                  # [N, NB]
    cdf_org = s.class_cdf[b_o]                           # [O, NB]
    own = (b[None, :] <= b_o[:, None])[..., None, None]  # [O, N, 1, 1]
    cdf = jnp.where(own, cdf_own[None, :, None, :], cdf_org[:, None, None, :])
    cls = jnp.sum((u_class[..., None] >= cdf[..., :-1]).astype(jnp.int32),
                  axis=-1)                               # [O, N, T]
    oh = (cls[..., None] == jnp.arange(s.class_cdf.shape[0])[None, None,
                                                            None, :])
    ohf = oh.astype(jnp.float32)
    start = jnp.einsum("...c,c->...", ohf,
                       s.class_start.astype(jnp.float32)).astype(jnp.int32)
    count = jnp.einsum("...c,c->...", ohf,
                       s.class_count.astype(jnp.float32)).astype(jnp.int32)
    member = start + jnp.floor(
        u_member * count.astype(jnp.float32)).astype(jnp.int32)
    member = jnp.minimum(member, start + jnp.maximum(count - 1, 0))
    return member


# --------------------------------------------------------------------------
# initialization
# --------------------------------------------------------------------------

def init_state(key: jax.Array, tables: ClusterTables, origins: jax.Array,
               params: EngineParams) -> SimState:
    """Build O fresh single-origin sims with rotated-in active sets.

    Initialization mirrors ``initialize_gossip`` (gossip_main.rs:263-277 ->
    gossip.rs:805-813): every node's tracked entry is rotated from empty.
    Rotating an empty entry inserts weighted-distinct peers until the entry
    *exceeds* ``size`` and then evicts the oldest (push_active_set.rs:165-185)
    — i.e. the kept set is distinct samples #2..S+1 when more than S are
    available, else all of them.
    """
    p = params.validate()
    N, S, E = p.num_nodes, p.active_set_size, p.init_draws
    pack = _pack_base(N)
    O = int(origins.shape[0])
    origins = origins.astype(jnp.int32)

    okeys = jax.vmap(jax.random.fold_in, in_axes=(None, 0))(key, origins)
    # Domain-separate the init stream from the per-round streams (both fold
    # small integers into the same per-origin key otherwise).
    draw_keys = jax.vmap(jax.random.fold_in, in_axes=(0, None))(
        okeys, 0x696E6974)
    self_idx = jnp.arange(N, dtype=jnp.int32)[None, :]
    perm_t = jnp.broadcast_to(tables.sampler.perm[None, :], (O, N))

    def draw_step(carry, e):
        buf, cnt = carry                                         # [O,N,S+1], [O,N]
        ek = jax.vmap(jax.random.fold_in, in_axes=(0, None))(draw_keys, e)
        u = jax.vmap(lambda k: jax.random.uniform(k, (N, 2), dtype=jnp.float32))(ek)
        member = _sample_fast(tables, origins, u[..., 0:1], u[..., 1:2])
        cand = _lookup(perm_t, member[..., 0].reshape(O, N), N,
                       pack).reshape(O, N)
        dup = jnp.any(buf == cand[..., None], axis=-1) | (cand == self_idx)
        ins = (~dup) & (cnt <= S)
        slot = jnp.minimum(cnt, S)
        oh = (jnp.arange(S + 1)[None, None, :] == slot[..., None]) & ins[..., None]
        buf = jnp.where(oh, cand[..., None], buf)
        return (buf, cnt + ins.astype(jnp.int32)), None

    buf0 = jnp.full((O, N, S + 1), N, dtype=jnp.int32)
    (buf, cnt), _ = lax.scan(draw_step, (buf0, jnp.zeros((O, N), jnp.int32)),
                             jnp.arange(E))
    # Evict the oldest iff the entry overfilled (push_active_set.rs:182-185).
    active = jnp.where((cnt > S)[..., None], buf[..., 1:], buf[..., :S])

    C, H = p.rc_slots, p.hist_bins
    # sparse representation: the stake planes are derived from the cluster
    # tables each round, so the carried arrays are zero-width (same pytree
    # structure — checkpoints, ledgers and lanes stay shape-compatible)
    Cs = 0 if p.representation == "sparse" else C
    zi = lambda shape: jnp.zeros(shape, jnp.int32)
    return SimState(
        key=okeys,
        active=active,
        pruned=jnp.zeros((O, N, S), bool),
        tfail=jnp.zeros((O, N, S), bool),
        rc_src=jnp.full((O, N, C), N, jnp.int32),
        rc_score=zi((O, N, C)),
        rc_shi=zi((O, N, Cs)),
        rc_slo=zi((O, N, Cs)),
        rc_upserts=zi((O, N)),
        failed=jnp.zeros((O, N), bool),
        egress_acc=zi((O, N)),
        ingress_acc=zi((O, N)),
        prune_acc=zi((O, N)),
        stranded_acc=zi((O, N)),
        hops_hist_acc=zi((O, H)),
        pull_hops_hist_acc=zi((O, H)),
        pull_rescued_acc=zi((O, N)),
        health_prune_recv=zi((O, N)),
        health_first_round=zi((O, N)),
        adaptive_pull_on=jnp.zeros((O,), bool),
    )


# --------------------------------------------------------------------------
# the round
# --------------------------------------------------------------------------

def _check_knob_gates(static: EngineStatic, kn: EngineKnobs) -> None:
    """Explicit-knobs consistency guard: the impairment blocks exist in the
    compiled graph only where the static gates say so, so an *active* knob
    value against a False gate would be silently ignored (e.g. a nonzero
    packet_loss_rate with has_loss=False runs loss-free) — wrong physics,
    raised as an error.  The reverse direction is allowed: an off/zero knob
    against a True gate is bit-correct (the gated blocks reduce exactly to
    the unimpaired graph at their off endpoints), which is what lets a
    knobs= sweep include 0 without a recompile.  Skipped when the knob
    leaves are traced (the internal jit path — checked at the boundary)."""
    try:
        implied = {
            "has_loss": float(kn.packet_loss_rate) > 0.0,
            "has_churn": (float(kn.churn_fail_rate) > 0.0
                          or float(kn.churn_recover_rate) > 0.0),
            "has_partition": int(kn.partition_at) >= 0,
            "has_fail": (int(kn.fail_at) >= 0
                         and float(kn.fail_fraction) > 0.0),
            # queue caps only act inside the traffic engine (traffic.py);
            # with traffic_slots == 0 they would be silently inert
            "has_traffic": (int(kn.node_ingress_cap) > 0
                            or int(kn.node_egress_cap) > 0),
        }
    except Exception:   # traced leaves have no concrete value here
        return
    missing = [g for g, want in implied.items()
               if want and not getattr(static, g)]
    if missing:
        raise ValueError(
            f"knob values require the {missing} impairment block(s) but the "
            f"EngineStatic compile key gates them out — the compiled graph "
            f"would silently ignore them. Build the EngineParams with the "
            f"target values (or a matching static) instead.")


def _split_params(params, knobs):
    """Resolve (params, knobs) call forms into (EngineStatic, EngineKnobs)
    — the single split point round_step and run_rounds share, including
    the explicit-knobs gate guard."""
    if isinstance(params, EngineParams):
        static, kn = params.split()
        if knobs is not None:
            kn = knobs
            _check_knob_gates(static, kn)
        return static, kn
    if knobs is None:
        raise TypeError("an EngineStatic compile key requires "
                        "knobs=EngineKnobs(...)")
    _check_knob_gates(params, knobs)
    return params, knobs

def round_step(params, tables: ClusterTables, origins: jax.Array,
               state: SimState, it: jax.Array, detail: bool = False,
               edge_detail: bool = False, trace: bool = False,
               knobs: EngineKnobs | None = None):
    """One full gossip round for all O origin-sims.  Returns (state, rows).

    ``params`` is either a full (concrete) :class:`EngineParams` — whose
    numeric knobs are then baked into the containing trace as constants,
    the historical behavior — or an :class:`EngineStatic` compile key, in
    which case ``knobs`` must carry the :class:`EngineKnobs` pytree of
    (possibly traced) scalars.  ``_run`` uses the second form so a sweep
    stepping any knob reuses one compiled executable; the two forms emit
    bit-identical results for equal values.  The lane runner
    (engine/lanes.py) additionally ``jax.vmap``s this function over a
    leading (state, knobs) lane axis — safe because every batched control
    structure here is lane-clean: the BFS while_loop body is a fixed
    point for converged lanes and the lax.cond branches are pure, so a
    lane inside a batch computes bit-identically to a serial call.

    ``trace`` additionally emits the flight-recorder event rows consumed by
    :mod:`gossip_sim_tpu.obs.trace` (candidate push slots with per-edge
    outcome codes, first-delivery senders, prune pairs, rotation events and
    the pre-round active-set snapshot).  The trace rows are pure extra
    outputs computed from intermediates the round already materializes: the
    state transition and every non-trace row are bit-identical with the
    flag on or off, and with it off (the default) the compiled graph is
    unchanged."""
    p, kn = _split_params(params, knobs)
    N, S, F, C, K, H = (p.num_nodes, p.active_set_size, p.push_fanout,
                        p.rc_slots, p.k_inbound, p.hist_bins)
    F = min(F, S)
    pack = _pack_base(N)
    pb = pack.bit_length() - 1          # node-id bits in shared sort keys
    # Sparse frontier representation (engine/sparse.py): replaces the
    # full-width cross-node sorts with edge-list segment reductions /
    # scatters and derives the rc_shi/rc_slo planes from ClusterTables.
    # Static compile key — with representation="dense" every branch below
    # takes the reference arm and the compiled graph is unchanged.
    sparse_mode = p.representation == "sparse"
    if sparse_mode:
        from . import sparse as _sparse
        if trace:
            raise ValueError(
                "the flight recorder requires representation='dense' — "
                "sparse rounds do not materialize the full-width edge "
                "intermediates it captures")
    if N > MAX_NODES_I32:
        # the inbound (hop << pb | src) keys and slot-compaction keys stay
        # i32 at every N; these bounds bind only past the i32 node cap
        if H * pack >= (1 << 31):
            raise ValueError(
                f"inbound sort keys (hop << {pb} | src) overflow i32: "
                f"hist_bins * pack = {H * pack} >= 2^31; reduce hist_bins "
                f"(< {(1 << 31) // pack}) for num_nodes={N}")
        if 2 * N * K >= (1 << 31):
            raise ValueError(
                f"inbound compaction keys overflow i32: 2*N*K = "
                f"{2 * N * K} >= 2^31; reduce inbound_cap for "
                f"num_nodes={N}")
    O = int(origins.shape[0])
    origins = origins.astype(jnp.int32)
    o1 = jnp.arange(O)
    origin_col = origins[:, None, None]
    NF, NK = N * F, N * K
    iota_n = jnp.arange(N, dtype=jnp.int32)[None, :]

    kr = jax.vmap(jax.random.fold_in, in_axes=(0, None))(state.key, it)
    nsub = p.rot_tries + 2
    subs = jax.vmap(lambda k: jax.random.split(k, nsub))(kr)     # [O, nsub, 2]

    with jax.named_scope("round/fault_inject"):
        # ---- fault injection (gossip.rs:756-771; fires when it == when_to_fail,
        # gossip_main.rs:449-452) --------------------------------------------
        failed, tfail = state.failed, state.tfail
        if p.has_fail:
            # truncating, like the reference's `as usize` (gossip.rs:758);
            # the f64 product matches the host double arithmetic bit-for-bit
            n_fail = jnp.floor(kn.fail_fraction * N).astype(jnp.int32)

            def _fail(ft):
                f, _ = ft
                r = jax.vmap(lambda k: jax.random.uniform(k, (N,), dtype=jnp.float32))(
                    subs[:, 0])
                kidx = jnp.clip(n_fail - 1, 0, N - 1)
                kth = jnp.sort(r, axis=-1)[:, kidx][:, None]
                f = f | (r <= kth)
                # rebuild per-slot target-failed bits (sort-join; sparse:
                # one row gather)
                q = jnp.minimum(state.active, N - 1).reshape(O, N * S)
                if sparse_mode:
                    tf = jnp.take_along_axis(f, q, axis=1).reshape(O, N, S)
                else:
                    tf = _lookup(f.astype(jnp.int32), q, N,
                                 pack).reshape(O, N, S) == 1
                return f, tf & (state.active < N)
            failed, tfail = lax.cond((it == kn.fail_at) & (n_fail > 0),
                                     _fail, lambda ft: ft, (failed, tfail))

    with jax.named_scope("round/churn"):
        # ---- continuous churn (faults.py): one hash per (iteration, node),
        # interpreted against the node's current state; recovered nodes rejoin
        # delivery immediately (their tfail bits clear this round) -------------
        if p.has_churn:
            basis_c = round_basis_arr(kn.impair_seed, it, SALT_CHURN, jnp)
            hu64 = node_u32_arr(basis_c, jnp.arange(N, dtype=jnp.uint32),
                                jnp).astype(jnp.uint64)
            fail_ev = hu64 < rate_threshold_arr(kn.churn_fail_rate, jnp)  # [N]
            rec_ev = hu64 < rate_threshold_arr(kn.churn_recover_rate, jnp)
            failed = jnp.where(failed, ~rec_ev[None, :], fail_ev[None, :])
            q = jnp.minimum(state.active, N - 1).reshape(O, N * S)
            if sparse_mode:
                tfail = (jnp.take_along_axis(failed, q, axis=1)
                         .reshape(O, N, S)) & (state.active < N)
            else:
                tfail = (_lookup(failed.astype(jnp.int32), q, N,
                                 pack).reshape(O, N, S) == 1) \
                    & (state.active < N)

    with jax.named_scope("round/verb1_push_targets"):
        # ---- verb 1: push targets (gossip.rs:494-615) -----------------------
        peer = state.active
        is_peer = peer < N
        # get_nodes filter: bloom-contains(origin) == pruned bit OR peer == origin
        # (self-seeded bloom, push_active_set.rs:128-141,179).
        valid = is_peer & (~state.pruned) & (peer != origin_col)
        if not p.has_push:
            # pull-only mode (pull.py): the push phase emits nothing — the
            # value spreads through pull responses alone.  The push
            # machinery still runs on the resulting empty edge set so state
            # layout, rotation and the row schema stay mode-invariant.
            valid = jnp.zeros_like(valid)
        # first F valid slots, failed targets consume a slot but receive nothing
        # (gossip.rs:538-541): compact (slot-order) then mask failed targets.
        skey = jnp.where(valid, jnp.arange(S, dtype=jnp.int32)[None, None, :], S)
        skey_s, peer_sf, tfail_sf = lax.sort(
            (skey, peer, tfail.astype(jnp.int32)), dimension=-1, num_keys=1)
        slot_ok = skey_s[..., :F] < S
        peerF = peer_sf[..., :F]
        # live candidate pushes; partition suppression and packet loss consume
        # the slot exactly like failed targets do (precedence: failed target >
        # partition > loss — matching the oracle's classify_edge)
        deliver_ok = slot_ok & (tfail_sf[..., :F] == 0)              # [O,N,F]
        sup_mask = drop_mask = None
        if p.has_partition:
            # window [partition_at, heal_at); heal_at < 0 = never heals,
            # partition_at < 0 = never starts (matches the oracle's
            # partition_active) — both bounds are traced knobs, so the
            # window itself is sweepable, including its off endpoint
            part_on = ((kn.partition_at >= 0) & (it >= kn.partition_at)
                       & ((kn.heal_at < 0) | (it < kn.heal_at)))
            side_dst = tables.side[jnp.minimum(peerF, N)]            # [O,N,F]
            sup_mask = (deliver_ok & part_on
                        & (tables.side[:N][None, :, None] != side_dst))
            deliver_ok = deliver_ok & ~sup_mask
        if p.has_loss:
            basis_e = round_basis_arr(kn.impair_seed, it, SALT_EDGE, jnp)
            ue = edge_u32_arr(basis_e, iota_n.astype(jnp.uint32)[:, :, None],
                              peerF.astype(jnp.uint32), jnp)
            drop_mask = deliver_ok & (
                ue.astype(jnp.uint64)
                < rate_threshold_arr(kn.packet_loss_rate, jnp))
            deliver_ok = deliver_ok & ~drop_mask
        tgt = jnp.where(deliver_ok, peerF, N)                        # [O,N,F]
        tgtf = tgt.reshape(O, NF)
        pseudo_t = jnp.broadcast_to(iota_n, (O, N))
        if trace:
            # flight recorder: candidate target per fanout slot + outcome
            # code, mirroring the oracle's classify_edge precedence
            # (failed target > partition > loss > deliverable candidate)
            trace_peers = jnp.where(slot_ok, peerF, -1)
            t_code = jnp.where(slot_ok, jnp.int32(TRACE_CANDIDATE), 0)
            t_code = jnp.where(slot_ok & (tfail_sf[..., :F] == 1),
                               TRACE_FAILED_TARGET, t_code)
            if sup_mask is not None:
                t_code = jnp.where(sup_mask, TRACE_SUPPRESSED, t_code)
            if drop_mask is not None:
                t_code = jnp.where(drop_mask, TRACE_DROPPED, t_code)
            trace_code = t_code

    with jax.named_scope("round/bfs_propagate"):
        # ---- BFS frontier relaxation ----------------------------------------
        # Hop-1 seed: the origin's own targets are a tiny slice, so the loop
        # starts at hop 1.  Dense: two 1-key sorts per hop over the (static)
        # edge/pseudo key base.  Sparse (engine/sparse.py): one segment_max
        # per hop over the N*F candidate edge list — cost tracks live edges,
        # not the node universe.
        org_tgts = tgt[o1[:, None], origins[:, None],
                       jnp.arange(F)[None, :]]                       # [O, F]
        dist0 = jnp.full((O, N), INF, jnp.int32).at[o1, origins].set(0)
        dist0 = dist0.at[o1[:, None], org_tgts].min(1, mode="drop")
        frontier1 = jnp.zeros((O, N), bool).at[
            o1[:, None], org_tgts].set(True, mode="drop")
        reached1 = frontier1.at[o1, origins].set(True)

        if sparse_mode:
            reached, dist = _sparse.bfs_reach(
                tgt, frontier1, reached1, dist0, N)
        else:
            tgt2_base = jnp.concatenate(
                [jnp.where(tgt < N, tgt * 2, BIG - 1).reshape(O, NF),
                 pseudo_t * 2 + 1], axis=1)                          # [O, NF+N]

            def bfs_body(carry):
                frontier, reached, dist, h = carry
                quiet = jnp.broadcast_to((~frontier)[:, :, None],
                                         (O, N, F)).reshape(O, NF)
                delta = jnp.concatenate(
                    [quiet.astype(jnp.int32), jnp.zeros((O, N), jnp.int32)],
                    axis=1)
                (s1,) = lax.sort((tgt2_base + delta,), dimension=-1,
                                 num_keys=1)
                k2 = jnp.where(_boundary(s1 >> 1), s1, BIG)
                (s2,) = lax.sort((k2,), dimension=-1, num_keys=1)
                dense = s2[:, :N]             # keys t*2 + (1 - any), t ascending
                newly = ((dense & 1) == 0) & ~reached
                dist = jnp.where(newly, h + 1, dist)
                return (newly, reached | newly, dist, h + 1)

            _, reached, dist, _ = lax.while_loop(
                lambda c: jnp.any(c[0]), bfs_body,
                (frontier1, reached1, dist0, jnp.int32(1)))

    with jax.named_scope("round/verb2_consume"):
        # ---- delivered edges + verb 2: consume (gossip.rs:618-653) ----------
        delivered = (tgt < N) & reached[:, :, None]                  # [O,N,F]
        deg_out = jnp.sum(delivered, axis=-1, dtype=jnp.int32)       # egress
        m_push = jnp.sum(deg_out, axis=-1, dtype=jnp.int32)          # [O]
        n_reached = jnp.sum(reached, axis=-1, dtype=jnp.int32)       # [O]
        # degraded-delivery counters: only sends from reached sources exist
        # (the oracle's BFS likewise only attempts pushes from visited nodes)
        zero_o = jnp.zeros((O,), jnp.int32)
        dropped_cnt = (jnp.sum(drop_mask & reached[:, :, None], axis=(1, 2),
                               dtype=jnp.int32) if drop_mask is not None
                       else zero_o)
        suppressed_cnt = (jnp.sum(sup_mask & reached[:, :, None], axis=(1, 2),
                                  dtype=jnp.int32) if sup_mask is not None
                          else zero_o)

        hop1 = jnp.minimum(dist + 1, H - 1)                          # [O,N] per src
        if sparse_mode:
            # engine/sparse.py: segment-sum ingress + scatter compaction
            # over the delivered edge list; the stake payloads are never
            # routed (derived from ClusterTables at the use sites)
            inb, ingress_round, inb_dropped = _sparse.rank_inbound(
                delivered, tgt, hop1, pb, pack, K, N)
            inb_shi = inb_slo = None
        else:
          # per-edge payloads, src-major (free broadcasts)
          kv = ((hop1[:, :, None] << pb) | iota_n[:, :, None]).astype(jnp.int32)
          kv = jnp.broadcast_to(kv, (O, N, F)).reshape(O, NF)
          shi_e = jnp.broadcast_to(tables.shi[None, :N, None], (O, N, F)).reshape(O, NF)
          slo_e = jnp.broadcast_to(tables.slo[None, :N, None], (O, N, F)).reshape(O, NF)
          kd = jnp.where(delivered, tgt, N).reshape(O, NF)
          # one pseudo-edge per target (ranks after real: kv = BIG)
          kd_c = jnp.concatenate([kd, pseudo_t], axis=1)             # [O, M1]
          kv_c = jnp.concatenate([kv, jnp.full((O, N), BIG)], axis=1)
          shi_c = jnp.concatenate([shi_e, jnp.zeros((O, N), jnp.int32)], axis=1)
          slo_c = jnp.concatenate([slo_e, jnp.zeros((O, N), jnp.int32)], axis=1)
          # rank inbound by (hop, src index) — index order equals the reference's
          # pubkey-string sort by NodeIndex construction (gossip.rs:638-645)
          st_, skv, shi_s, slo_s = lax.sort(
              (kd_c, kv_c, shi_c, slo_c), dimension=-1, num_keys=2)
          rank = _rank_in_run(st_)
          is_pseudo = (skv == BIG) & (st_ < N)
          real = (skv != BIG) & (st_ < N)

          if trace:
              # first-delivery sender per receiver: each target's run starts
              # with its rank-0 entry — the minimum (hop, src) inbound edge
              # when any exists, else the pseudo (kv == BIG).  One 1-key sort
              # compacts the N rank-0 entries into target order.
              fd_k = jnp.where((rank == 0) & (st_ < N), st_, BIG)
              _, fd_kv = lax.sort((fd_k, skv), dimension=-1, num_keys=1)
              fkv = fd_kv[:, :N]
              trace_first = jnp.where(fkv != BIG, fkv & (pack - 1), -1)

          # ingress counts: the pseudo entry sorts last in its run, so its rank
          # is the number of delivered edges into its target; compact -> [O, N].
          ing_k = jnp.where(is_pseudo, st_, BIG)
          _, ing_cnt = lax.sort((ing_k, rank), dimension=-1, num_keys=1)
          ingress_round = ing_cnt[:, :N]                             # [O, N]
          inb_dropped = jnp.sum(real & (rank >= K), axis=-1, dtype=jnp.int32)

          # inbound rows [O, N, K] via slot-aligned two-sort compaction
          keep = real & (rank < K)
          gk = jnp.where(keep, (st_ * K + rank) * 2, BIG)
          slot_keys = jnp.broadcast_to(
              jnp.arange(NK, dtype=jnp.int32)[None, :] * 2 + 1, (O, NK))
          ga = jnp.concatenate([gk, slot_keys], axis=1)
          kv_a = jnp.concatenate([skv, jnp.full((O, NK), BIG)], axis=1)
          shi_a = jnp.concatenate([shi_s, jnp.zeros((O, NK), jnp.int32)], axis=1)
          slo_a = jnp.concatenate([slo_s, jnp.zeros((O, NK), jnp.int32)], axis=1)
          sA, kvA, hiA, loA = lax.sort((ga, kv_a, shi_a, slo_a),
                                       dimension=-1, num_keys=1)
          gB = jnp.where(_boundary(sA >> 1), sA, BIG)
          sB, kvB, hiB, loB = lax.sort((gB, kvA, hiA, loA),
                                       dimension=-1, num_keys=1)
          inb_real = (sB[:, :NK] & 1) == 0
          inb = jnp.where(inb_real, kvB[:, :NK] & (pack - 1), N).reshape(O, N, K)
          inb_shi = jnp.where(inb_real, hiB[:, :NK], 0).reshape(O, N, K)
          inb_slo = jnp.where(inb_real, loB[:, :NK], 0).reshape(O, N, K)

    with jax.named_scope("round/rc_merge"):
        # ---- received-cache merge (received_cache.rs:83-98) -----------------
        rc_src, rc_score = state.rc_src, state.rc_score
        rc_shi, rc_slo = state.rc_shi, state.rc_slo
        kpos = jnp.arange(K, dtype=jnp.int32)[None, None, :]

        # member lookup: one row sort by (src, tag), route flags back by slot
        fk = jnp.concatenate([rc_src * 2, inb * 2 + 1], axis=-1)     # [O,N,C+K]
        fpos = jnp.concatenate(
            [jnp.broadcast_to(jnp.full((1, 1, C), BIG), (O, N, C)),
             jnp.broadcast_to(kpos, (O, N, K))], axis=-1)
        fk_s, fpos_s = lax.sort((fk, fpos), dimension=-1, num_keys=1)
        dup_s = jnp.concatenate(
            [jnp.zeros((O, N, 1), bool),
             (fk_s[..., 1:] >> 1) == (fk_s[..., :-1] >> 1)], axis=-1)
        back_k, back_d = lax.sort(
            (fpos_s, dup_s.astype(jnp.int32)), dimension=-1, num_keys=1)
        found = (back_d[..., :K] == 1) & (inb < N)                   # [O,N,K]

        # rank-order capacity scan (received_cache.rs:92-97): scored ranks (< 2)
        # insert unconditionally; the rest honor the 50-entry cap
        base_len = jnp.sum(rc_src < N, axis=-1, dtype=jnp.int32)
        want = (inb < N) & ~found
        ln = base_len
        allowed_cols = []
        for r in range(K):
            a = want[..., r] & ((r < 2) | (ln < p.received_cap))
            allowed_cols.append(a)
            ln = ln + a.astype(jnp.int32)
        allowed = jnp.stack(allowed_cols, axis=-1)                   # [O,N,K]

        # merge rows: score-bump carriers (found & rank<2) + allowed inserts
        bump = found & (kpos < 2)
        include = bump | allowed
        contrib = (kpos < 2).astype(jnp.int32)                       # +1 / score 1
        mk = jnp.concatenate(
            [jnp.where(rc_src < N, rc_src * 2, BIG),
             jnp.where(include, inb * 2 + 1, BIG)], axis=-1)         # [O,N,C+K]
        msc = jnp.concatenate(
            [rc_score, jnp.where(include, contrib, 0)], axis=-1)
        if sparse_mode:
            # sparse carries no stake payloads through the merge — the
            # rc_shi/rc_slo planes are zero-width and verb 3 derives the
            # stakes from ClusterTables by rc_src gather (the carried-dense
            # invariant rc_shi == shi[rc_src] holds by construction: every
            # insert copies the table stake and the index-N pad is 0)
            mk_s, msc_s = lax.sort((mk, msc), dimension=-1, num_keys=1)
        else:
            mhi = jnp.concatenate([rc_shi, inb_shi], axis=-1)
            mlo = jnp.concatenate([rc_slo, inb_slo], axis=-1)
            mk_s, msc_s, mhi_s, mlo_s = lax.sort(
                (mk, msc, mhi, mlo), dimension=-1, num_keys=1)
        is_dup = jnp.concatenate(
            [jnp.zeros((O, N, 1), bool),
             ((mk_s[..., 1:] >> 1) == (mk_s[..., :-1] >> 1))
             & ((mk_s[..., 1:] & 1) == 1)], axis=-1)
        nxt_dup = jnp.concatenate([is_dup[..., 1:],
                                   jnp.zeros((O, N, 1), bool)], axis=-1)
        nxt_sc = jnp.concatenate([msc_s[..., 1:],
                                  jnp.zeros((O, N, 1), jnp.int32)], axis=-1)
        msc_s = msc_s + jnp.where(nxt_dup, nxt_sc, 0)                # bump old
        valid_m = (mk_s != BIG) & ~is_dup
        ck = jnp.where(valid_m, mk_s >> 1, BIG)
        if sparse_mode:
            ck_s, csc = lax.sort((ck, msc_s), dimension=-1, num_keys=1)
        else:
            ck_s, csc, chi, clo = lax.sort(
                (ck, msc_s, mhi_s, mlo_s), dimension=-1, num_keys=1)
        n_valid = jnp.sum(valid_m, axis=-1, dtype=jnp.int32)
        rc_overflow = jnp.sum(jnp.maximum(n_valid - C, 0), axis=(-1,),
                              dtype=jnp.int32)
        rc_src = jnp.where(ck_s[..., :C] != BIG, ck_s[..., :C], N)
        rc_score = jnp.where(ck_s[..., :C] != BIG, csc[..., :C], 0)
        if not sparse_mode:
            rc_shi = jnp.where(ck_s[..., :C] != BIG, chi[..., :C], 0)
            rc_slo = jnp.where(ck_s[..., :C] != BIG, clo[..., :C], 0)

        any_inb = inb[..., 0] < N  # a rank-0 record is one upsert (received_cache.rs:85-87)
        rc_ups = state.rc_upserts + any_inb.astype(jnp.int32)

    with jax.named_scope("round/verb3_prune_decide"):
        # ---- verb 3: prune decide (received_cache.rs:38-63,100-131) ---------
        fired = rc_ups >= p.min_num_upserts
        stake_dest = tables.stakes[:N][None, :]                      # [1, N] i64
        stake_org = tables.stakes[origins][:, None]                  # [O, 1]
        min_stake = jnp.minimum(stake_dest, stake_org)
        # f64 multiply then u64 truncation, as the reference does
        # (received_cache.rs:112-115).
        min_ingress_stake = (min_stake.astype(jnp.float64)
                             * kn.prune_stake_threshold).astype(jnp.int64)

        member = rc_src < N
        if sparse_mode:
            # derive the stake planes from the cluster tables (the planes
            # the dense round carries equal shi/slo[rc_src] exactly; the
            # index-N pad is 0, matching empty slots)
            rc_shi_v = tables.shi[rc_src]
            rc_slo_v = tables.slo[rc_src]
        else:
            rc_shi_v, rc_slo_v = rc_shi, rc_slo
        mx = jnp.iinfo(jnp.int32).max
        neg_score = jnp.where(member, -rc_score, mx)
        neg_hi = jnp.where(member, -rc_shi_v, mx)
        neg_lo = jnp.where(member, -rc_slo_v, mx)
        # (score desc, stake desc, src asc): stake split keeps i64 out of the sort
        _, _, _, src_sorted, hi_sorted, lo_sorted = lax.sort(
            (neg_score, neg_hi, neg_lo, rc_src, rc_shi_v, rc_slo_v),
            dimension=-1, num_keys=4)
        memb_sorted = src_sorted < N
        stake_sorted = (hi_sorted.astype(jnp.int64) << 31) | lo_sorted.astype(
            jnp.int64)
        cum_excl = jnp.cumsum(stake_sorted, axis=-1) - stake_sorted
        posn = jnp.arange(C)[None, None, :]
        pruned_slot = (memb_sorted
                       & (posn >= kn.min_ingress_nodes)
                       & (cum_excl >= min_ingress_stake[..., None])
                       & (src_sorted != origin_col)
                       & fired[..., None])
        n_pruned = jnp.sum(pruned_slot, axis=-1, dtype=jnp.int32)    # [O, N] per pruner
        m_prunes = jnp.sum(n_pruned, axis=-1, dtype=jnp.int32)       # [O]
        # Prune messages count toward RMR's m (gossip.rs:684-687).
        if trace:
            # flight recorder: compact the sparse (pruner, prunee) pairs to
            # the first prune_cap slots via one full-width 1-key sort; the
            # writer cross-checks the captured count against prunes_sent and
            # flags truncated rounds in the manifest (never silent).  Most
            # rounds emit no prunes at all (they batch at the upsert
            # threshold), so the sort hides behind a lax.cond and zero-prune
            # rounds pay nothing.
            PC = p.prune_cap

            def _prune_pairs():
                live_flat = pruned_slot.reshape(O, N * C)
                pk_flat = jnp.where(
                    live_flat,
                    jnp.arange(N * C, dtype=jnp.int32)[None, :], BIG)
                pruner_flat = jnp.broadcast_to(
                    iota_n[:, :, None], (O, N, C)).reshape(O, N * C)
                prunee_flat = src_sorted.reshape(O, N * C)
                pks, tps, tpd = lax.sort(
                    (pk_flat, pruner_flat, prunee_flat),
                    dimension=-1, num_keys=1)
                pair_ok = pks[:, :PC] != BIG
                return (jnp.where(pair_ok, tps[:, :PC], -1),
                        jnp.where(pair_ok, tpd[:, :PC], -1))

            trace_prune_src, trace_prune_dst = lax.cond(
                m_prunes.sum() > 0, _prune_pairs,
                lambda: (jnp.full((O, PC), -1, jnp.int32),
                         jnp.full((O, PC), -1, jnp.int32)))

    with jax.named_scope("round/verb4_prune_apply"):
        # ---- verb 4: prune apply (push_active_set.rs:56-71,143-151) ---------
        # pair (pruner=t, prunee=u) must set prunee u's slot bit for peer t:
        # match key = peer * pack + owner, shared by pairs and active-set edges.
        NP = min(p.pa_slots, C)
        pk_rows = jnp.where(pruned_slot, posn.astype(jnp.int32), C)
        pk_s, psrc_s = lax.sort((pk_rows, src_sorted), dimension=-1, num_keys=1)
        over_budget = jnp.any(pk_s[..., NP:NP + 1] < C) if NP < C else jnp.array(
            False)
        t_rows = jnp.broadcast_to(iota_n[:, :, None], (O, N, C))
        pair_live = pk_s < C

        # peer*pack+owner overflows i32 past MAX_NODES_I32 — the shared
        # match keys switch to i64 there (same join, wider sort keys)
        kdt = jnp.int64 if _keys_need_i64(N) else jnp.int32
        kbig = BIG64 if _keys_need_i64(N) else BIG
        edge_keys = (jnp.minimum(peer, N - 1).astype(kdt) * pack
                     + iota_n[:, :, None]).reshape(O, N * S)
        edge_keys = jnp.where(is_peer.reshape(O, N * S), edge_keys * 2 + 1,
                              kbig)
        edge_pos = jnp.broadcast_to(
            jnp.arange(N * S, dtype=jnp.int32)[None, :], (O, N * S))

        def _apply(np_slots):
            pair_keys = jnp.where(
                pair_live[..., :np_slots],
                (t_rows[..., :np_slots].astype(kdt) * pack
                 + psrc_s[..., :np_slots]) * 2,
                kbig).reshape(O, N * np_slots)
            # pair key = pruner*pack + prunee; edge key = peer*pack + owner:
            # a hit means this slot's peer has pruned the owner for this origin
            k = jnp.concatenate([edge_keys, pair_keys], axis=1)
            ppos = jnp.concatenate(
                [edge_pos, jnp.full((O, N * np_slots), BIG)], axis=1)
            ks, pos_s = lax.sort((k, ppos), dimension=-1, num_keys=1)
            hit_s = jnp.concatenate(
                [jnp.zeros((O, 1), bool),
                 ((ks[:, 1:] >> 1) == (ks[:, :-1] >> 1))
                 & ((ks[:, 1:] & 1) == 1)], axis=1)
            _, hit_back = lax.sort((pos_s, hit_s.astype(jnp.int32)),
                                   dimension=-1, num_keys=1)
            return hit_back[:, :N * S].reshape(O, N, S) == 1

        if NP < C:
            hit = lax.cond(over_budget, lambda: _apply(C), lambda: _apply(NP))
        else:
            hit = _apply(C)
        pruned_bits = state.pruned | (hit & is_peer)

        # mem::take on fire: the whole entry resets (received_cache.rs:48-55)
        rc_src = jnp.where(fired[..., None], N, rc_src)
        rc_score = jnp.where(fired[..., None], 0, rc_score)
        rc_shi = jnp.where(fired[..., None], 0, rc_shi)
        rc_slo = jnp.where(fired[..., None], 0, rc_slo)
        rc_ups = jnp.where(fired, 0, rc_ups)

    with jax.named_scope("round/verb5_rotate"):
        # ---- verb 5: rotate (gossip.rs:739-754; push_active_set.rs:153-186) -
        rot_u = jax.vmap(lambda k: jax.random.uniform(k, (N,), dtype=jnp.float32))(
            subs[:, 1])
        rotate = rot_u < kn.probability_of_rotation
        T = p.rot_tries
        u_all = jax.vmap(
            lambda ks: jax.vmap(
                lambda k: jax.random.uniform(k, (N, 2), dtype=jnp.float32))(ks)
        )(subs[:, 2:2 + T])                                          # [O, T, N, 2]
        u_all = jnp.moveaxis(u_all, 1, 2)                            # [O, N, T, 2]
        members = _sample_fast(tables, origins, u_all[..., 0], u_all[..., 1])
        if sparse_mode:
            # class-position -> node-id translation as a direct table
            # gather (the sort-join below computes exactly perm[members])
            cands = tables.sampler.perm[members]
        else:
            perm_t = jnp.broadcast_to(tables.sampler.perm[None, :], (O, N))
            cands = _lookup(perm_t, members.reshape(O, N * T), N,
                            pack).reshape(O, N, T)

        chosen = jnp.full((O, N), N, jnp.int32)
        found_new = jnp.zeros((O, N), bool)
        self_i = jnp.arange(N, dtype=jnp.int32)[None, :]
        active_now = peer
        for t in range(T):
            cand = cands[..., t]
            ok = ((cand != self_i)
                  & ~jnp.any(active_now == cand[..., None], axis=-1))
            take = ok & ~found_new
            chosen = jnp.where(take, cand, chosen)
            found_new = found_new | ok
        do_rot = rotate & found_new
        rot_failed = jnp.sum(rotate & ~found_new, axis=-1, dtype=jnp.int32)
        if sparse_mode:
            chosen_failed = jnp.take_along_axis(
                failed, jnp.minimum(chosen, N - 1), axis=1)
        else:
            chosen_failed = _lookup(
                failed.astype(jnp.int32), jnp.minimum(chosen, N - 1), N,
                pack) == 1

        mcnt = jnp.sum(active_now < N, axis=-1, dtype=jnp.int32)
        full_row = mcnt >= S
        shift_act = jnp.concatenate([active_now[..., 1:], chosen[..., None]], axis=-1)
        shift_prn = jnp.concatenate(
            [pruned_bits[..., 1:], jnp.zeros((O, N, 1), bool)], axis=-1)
        shift_tf = jnp.concatenate(
            [tfail[..., 1:], chosen_failed[..., None]], axis=-1)
        slot_oh = (jnp.arange(S)[None, None, :] == jnp.minimum(mcnt, S - 1)[..., None])
        append_act = jnp.where(slot_oh & ~full_row[..., None],
                               chosen[..., None], active_now)
        append_tf = jnp.where(slot_oh & ~full_row[..., None],
                              chosen_failed[..., None], tfail)
        new_active = jnp.where(do_rot[..., None],
                               jnp.where(full_row[..., None], shift_act, append_act),
                               active_now)
        new_pruned = jnp.where((do_rot & full_row)[..., None], shift_prn, pruned_bits)
        new_tfail = jnp.where(do_rot[..., None],
                              jnp.where(full_row[..., None], shift_tf, append_tf),
                              tfail)

    pull_got = None
    if p.has_pull:
        with jax.named_scope("round/pull"):
            # ---- pull phase (pull.py): one request/response anti-entropy
            # exchange against this round's push outcome.  Every decision is
            # a stateless counter hash of (impair_seed, it, node ids), so the
            # CPU oracle's PullOracle makes bit-identical choices; the stake
            # weighting reuses the sampler's top-entry class CDF (weights
            # (bucket+1)^2) with hash-derived uniforms instead of PRNG draws.
            PS = p.pull_slots
            NPS = N * PS
            pull_on = (it % kn.pull_interval) == 0

            # peer draws are origin-independent: one [N, PS] table per round
            nodes_u = jnp.arange(N, dtype=jnp.uint32)[:, None]
            slots_u = jnp.arange(PS, dtype=jnp.uint32)[None, :]
            b_cls = round_basis_arr(kn.impair_seed, it, SALT_PULL_CLASS, jnp)
            b_mem = round_basis_arr(kn.impair_seed, it, SALT_PULL_MEMBER, jnp)
            u01 = lambda h: ((h >> jnp.uint32(8)).astype(jnp.float32)
                             * jnp.float32(2.0 ** -24))
            u_cls = u01(edge_u32_arr(b_cls, nodes_u, slots_u, jnp))  # [N, PS]
            u_mem = u01(edge_u32_arr(b_mem, nodes_u, slots_u, jnp))
            smp = tables.sampler
            cdf_top = smp.class_cdf[-1]                              # [NB] f32
            cls = jnp.sum((u_cls[..., None] >= cdf_top[:-1][None, None, :])
                          .astype(jnp.int32), axis=-1)               # [N, PS]
            ohf = (cls[..., None] == jnp.arange(
                cdf_top.shape[0])[None, None, :]).astype(jnp.float32)
            cstart = jnp.einsum("...c,c->...", ohf, smp.class_start.astype(
                jnp.float32)).astype(jnp.int32)
            ccount = jnp.einsum("...c,c->...", ohf, smp.class_count.astype(
                jnp.float32)).astype(jnp.int32)
            mpos = cstart + jnp.floor(
                u_mem * ccount.astype(jnp.float32)).astype(jnp.int32)
            mpos = jnp.minimum(mpos, cstart + jnp.maximum(ccount - 1, 0))
            peer_ns = _lookup(smp.perm[None, :], mpos.reshape(1, NPS), N,
                              pack).reshape(N, PS)                   # [N, PS]

            # per-slot precedence (mirrors the push phase's failed target >
            # partition > loss): dead requester / self-draw > failed peer >
            # partition suppression > request loss > arrival
            self_col = jnp.arange(N, dtype=jnp.int32)[:, None]
            slot_live = (jnp.arange(PS, dtype=jnp.int32)[None, :]
                         < kn.pull_fanout) & pull_on                 # [1, PS]
            base_ns = (peer_ns != self_col) & slot_live              # [N, PS]
            sent = base_ns[None, :, :] & (~failed)[:, :, None]       # [O,N,PS]
            if p.has_adaptive:
                # direction-optimizing switch (adaptive.py): the pull
                # phase runs only for origin-sims whose carried direction
                # bit is set — decided last round from push coverage.  A
                # gated round is bit-identical to an off-interval pull
                # round (zero counts, -1 trace slots), matching the
                # AdaptiveOracle's empty_pull_round.
                sent = sent & state.adaptive_pull_on[:, None, None]
            peer_o = jnp.broadcast_to(peer_ns[None], (O, N, PS))
            tf_pull = _lookup(failed.astype(jnp.int32),
                              peer_o.reshape(O, NPS), N,
                              pack).reshape(O, N, PS) == 1
            req_peer_failed = sent & tf_pull
            livep = sent & ~tf_pull
            pull_sup = pull_drop = None
            if p.has_partition:
                part_on_p = ((kn.partition_at >= 0) & (it >= kn.partition_at)
                             & ((kn.heal_at < 0) | (it < kn.heal_at)))
                side_dst_p = tables.side[peer_ns]                    # [N, PS]
                pull_sup = (livep & part_on_p
                            & (tables.side[:N][None, :, None]
                               != side_dst_p[None]))
                livep = livep & ~pull_sup
            if p.has_loss:
                b_loss = round_basis_arr(kn.impair_seed, it, SALT_PULL_LOSS,
                                         jnp)
                ue_p = edge_u32_arr(b_loss, nodes_u,
                                    peer_ns.astype(jnp.uint32), jnp)
                pull_drop = livep & (
                    ue_p.astype(jnp.uint64)
                    < rate_threshold_arr(kn.packet_loss_rate, jnp))[None]
                livep = livep & ~pull_drop
            arrived = livep                                          # [O,N,PS]

            # per-peer arrival ranking (for the request cap) + arrived
            # counts via the pseudo-entry sort: requests keyed by (peer,
            # flat (requester, slot) order), one pseudo per peer sorting
            # last in its run — the pseudo's rank is the peer's arrived
            # count, a request's rank its service position.
            arr_flat = arrived.reshape(O, NPS)
            peer_flat = peer_o.reshape(O, NPS)
            order = jnp.broadcast_to(
                jnp.arange(NPS, dtype=jnp.int32)[None, :], (O, NPS))
            kd_p = jnp.where(arr_flat, peer_flat, N)
            kd_pc = jnp.concatenate([kd_p, pseudo_t], axis=1)
            kv_pc = jnp.concatenate([order, jnp.full((O, N), BIG)], axis=1)
            sk_p, skv_p = lax.sort((kd_pc, kv_pc), dimension=-1, num_keys=2)
            rank_p = _rank_in_run(sk_p)
            cnt_k = jnp.where((skv_p == BIG) & (sk_p < N), sk_p, BIG)
            _, req_cnt_s = lax.sort((cnt_k, rank_p), dimension=-1, num_keys=1)
            req_in = req_cnt_s[:, :N]                                # [O, N]
            # route ranks back by flat (requester, slot) position: skv_p is
            # that position for request entries and BIG for pseudos
            _, rank_back = lax.sort((skv_p, rank_p), dimension=-1,
                                    num_keys=1)
            req_rank = rank_back[:, :NPS].reshape(O, N, PS)
            served = arrived & ((kn.pull_request_cap <= 0)
                                | (req_rank < kn.pull_request_cap))
            capped = arrived & ~served

            # response decision: peer holds (push-reached this round, the
            # origin included), requester lacks, and the requester's bloom
            # digest did not false-positive the value away
            holds = _lookup(reached.astype(jnp.int32),
                            peer_o.reshape(O, NPS), N,
                            pack).reshape(O, N, PS) == 1
            dist_safe = jnp.where(reached, dist, 0)
            d_peer = _lookup(dist_safe, peer_o.reshape(O, NPS), N,
                             pack).reshape(O, N, PS)
            lacks = (~reached)[:, :, None]
            b_fp = round_basis_arr(kn.impair_seed, it, SALT_PULL_BLOOM, jnp)
            fp = (node_u32_arr(b_fp, jnp.arange(N, dtype=jnp.uint32), jnp)
                  .astype(jnp.uint64)
                  < rate_threshold_arr(kn.pull_bloom_fp_rate, jnp))  # [N]
            transfer = served & holds & lacks & ~fp[None, :, None]
            miss = arrived & ~transfer

            # responses per peer (responder egress) via the same pseudo sort
            tr_flat = transfer.reshape(O, NPS)
            kd2 = jnp.where(tr_flat, peer_flat, N)
            kd2c = jnp.concatenate([kd2, pseudo_t], axis=1)
            kv2c = jnp.concatenate([jnp.zeros((O, NPS), jnp.int32),
                                    jnp.full((O, N), BIG)], axis=1)
            sk2, skv2 = lax.sort((kd2c, kv2c), dimension=-1, num_keys=2)
            rank2 = _rank_in_run(sk2)
            ck2 = jnp.where((skv2 == BIG) & (sk2 < N), sk2, BIG)
            _, resp_cnt_s = lax.sort((ck2, rank2), dimension=-1, num_keys=1)
            resp_out = resp_cnt_s[:, :N]                             # [O, N]

            # delivery: best (minimum) responding hop + 1 per requester
            hop_cand = jnp.where(transfer, d_peer + 1, INF)
            pull_hop = jnp.min(hop_cand, axis=-1)                    # [O, N]
            pull_got = pull_hop < INF

            pull_egress = jnp.sum(arrived, -1, dtype=jnp.int32) + resp_out
            pull_ingress = req_in + jnp.sum(transfer, -1, dtype=jnp.int32)
            zero_o = jnp.zeros((O,), jnp.int32)
            pull_counts = {
                "pull_requests": jnp.sum(arrived, (1, 2), dtype=jnp.int32),
                "pull_responses": jnp.sum(transfer, (1, 2), dtype=jnp.int32),
                "pull_misses": jnp.sum(miss, (1, 2), dtype=jnp.int32),
                "pull_dropped": (jnp.sum(pull_drop, (1, 2), dtype=jnp.int32)
                                 if pull_drop is not None else zero_o),
                "pull_suppressed": (jnp.sum(pull_sup, (1, 2), dtype=jnp.int32)
                                    if pull_sup is not None else zero_o),
                "pull_rescued": jnp.sum(pull_got, -1, dtype=jnp.int32),
            }
            if trace:
                pc = jnp.zeros((O, N, PS), jnp.int32)
                pc = jnp.where(req_peer_failed, PULL_PEER_FAILED, pc)
                if pull_sup is not None:
                    pc = jnp.where(pull_sup, PULL_SUPPRESSED, pc)
                if pull_drop is not None:
                    pc = jnp.where(pull_drop, PULL_DROPPED, pc)
                pc = jnp.where(capped, PULL_MISS_CAPPED, pc)
                pc = jnp.where(served & ~holds, PULL_MISS_NOT_HELD, pc)
                pc = jnp.where(served & holds & ~lacks,
                               PULL_MISS_ALREADY_HELD, pc)
                pc = jnp.where(served & holds & lacks & fp[None, :, None],
                               PULL_MISS_BLOOM_FP, pc)
                pc = jnp.where(transfer, PULL_RESPONSE, pc)
                trace_pull_peers = jnp.where(sent, peer_o, -1)
                trace_pull_code = pc

    # combined delivery view: push BFS plus this round's pull rescues.
    # With has_pull off these alias the push arrays and the compiled
    # graph is the exact pre-pull engine (mode=push bit-identity).
    if p.has_pull:
        reached_all = reached | pull_got
        dist_all = jnp.where(reached, dist,
                             jnp.where(pull_got, pull_hop, INF))
    else:
        reached_all, dist_all = reached, dist

    with jax.named_scope("round/round_stats"):
        # ---- statistics (gossip_stats.rs; on-device reductions) -------------
        hr = jnp.sum(
            (jnp.minimum(dist_all, H - 1)[:, :, None]
             == jnp.arange(H)[None, None, :])
            & reached_all[:, :, None], axis=1, dtype=jnp.int32)      # [O, H]
        pos_counts = hr.at[:, 0].set(0)          # HopsStat filters origin's 0 hops
        cnt = jnp.sum(pos_counts, axis=-1)
        hsum = jnp.sum(pos_counts * jnp.arange(H)[None, :], axis=-1)
        hop_mean = jnp.where(cnt > 0, hsum / jnp.maximum(cnt, 1), jnp.nan)
        csum = jnp.cumsum(pos_counts[:, 1:], axis=-1)                # [O, H-1]
        lo_i = (cnt - 1) // 2
        hi_i = cnt // 2
        val_of = lambda i: 1 + jnp.sum((csum <= i[:, None]).astype(jnp.int32), axis=-1)
        hop_median = jnp.where(cnt > 0, (val_of(lo_i) + val_of(hi_i)) / 2.0, 0.0)
        pos_hops = jnp.where(reached_all & (dist_all > 0), dist_all, 0)
        hop_max = jnp.max(pos_hops, axis=-1)
        hop_min = jnp.where(
            cnt > 0,
            jnp.min(jnp.where(reached_all & (dist_all > 0), dist_all, INF),
                    axis=-1), 0)

        # stranded excludes pull-rescued nodes; coverage counts them.  The
        # RMR rows (m/n/rmr/branching) keep their push semantics — pull
        # messages have their own counters (pull.py docstring).
        stranded = (~reached_all) & (~failed)
        stranded_cnt = jnp.sum(stranded, axis=-1, dtype=jnp.int32)
        n_reached_all = (jnp.sum(reached_all, axis=-1, dtype=jnp.int32)
                         if p.has_pull else n_reached)
        m_total = m_push + m_prunes
        nn = n_reached
        rmr = jnp.where(nn > 1, m_total / jnp.maximum(nn - 1, 1) - 1.0, 0.0)
        branching = m_push / jnp.maximum(nn, 1)   # Σ|pushes[src]| / |pushes|

        measured = it >= kn.warm_up_rounds
        g = measured.astype(jnp.int32)
        if p.has_pull:
            # pull message counts flow into the same ingress/egress stats
            # as push deliveries; the pull-tagged accumulators keep the
            # pull-sourced slice separable (hop histogram + rescue counts)
            egress_round_all = deg_out + pull_egress
            ingress_round_all = ingress_round + pull_ingress
            hr_pull = jnp.sum(
                (jnp.minimum(pull_hop, H - 1)[:, :, None]
                 == jnp.arange(H)[None, None, :])
                & pull_got[:, :, None], axis=1, dtype=jnp.int32)
            new_pull_hist = state.pull_hops_hist_acc + g * hr_pull
            new_pull_rescued = (state.pull_rescued_acc
                                + g * pull_got.astype(jnp.int32))
        else:
            egress_round_all, ingress_round_all = deg_out, ingress_round
            new_pull_hist = state.pull_hops_hist_acc
            new_pull_rescued = state.pull_rescued_acc
        if p.has_adaptive:
            # re-decide the direction bit from THIS round's push coverage
            # (adaptive.py switch_update_arr — the shared f64 formulation
            # the AdaptiveOracle evaluates on the same integer counts)
            new_adapt = switch_update_arr(
                n_reached, N, state.adaptive_pull_on,
                kn.adaptive_switch_threshold,
                kn.adaptive_switch_hysteresis, jnp)
        else:
            new_adapt = state.adaptive_pull_on
        if p.health:
            # node-health observatory (obs/health.py): prunee-side prune
            # counts via one deterministic integer segment-sum over the
            # sparse (pruner -> prunee) slots.  Prune rounds are bursty
            # (they batch at the upsert threshold), so zero-prune rounds
            # skip the scatter behind the same lax.cond the trace uses.
            def _prune_recv():
                seg = jnp.where(pruned_slot, src_sorted, N)
                seg = seg + (jnp.arange(O, dtype=jnp.int32)
                             * (N + 1))[:, None, None]
                return jax.ops.segment_sum(
                    pruned_slot.astype(jnp.int32).reshape(-1),
                    seg.reshape(-1),
                    num_segments=O * (N + 1)).reshape(O, N + 1)[:, :N]

            prune_recv_round = lax.cond(
                m_prunes.sum() > 0, _prune_recv,
                lambda: jnp.zeros((O, N), jnp.int32))
            new_health_prune_recv = (state.health_prune_recv
                                     + g * prune_recv_round)
            # first-delivery round, encoded round+1 (0 = never reached);
            # not warm-up gated — the first delivery is the first delivery
            # whenever it happens.
            new_health_first = jnp.where(
                (state.health_first_round == 0) & reached_all,
                (it + 1).astype(jnp.int32), state.health_first_round)
        else:
            new_health_prune_recv = state.health_prune_recv
            new_health_first = state.health_first_round
        new_state = SimState(
            key=state.key,
            active=new_active,
            pruned=new_pruned,
            tfail=new_tfail,
            rc_src=rc_src,
            rc_score=rc_score,
            rc_shi=rc_shi,
            rc_slo=rc_slo,
            rc_upserts=rc_ups,
            failed=failed,
            egress_acc=state.egress_acc + g * egress_round_all,
            ingress_acc=state.ingress_acc + g * ingress_round_all,
            prune_acc=state.prune_acc + g * n_pruned,
            stranded_acc=state.stranded_acc + g * stranded.astype(jnp.int32),
            hops_hist_acc=state.hops_hist_acc + g * hr,
            pull_hops_hist_acc=new_pull_hist,
            pull_rescued_acc=new_pull_rescued,
            health_prune_recv=new_health_prune_recv,
            health_first_round=new_health_first,
            adaptive_pull_on=new_adapt,
        )
        rows = {
            "coverage": (n_reached_all / N).astype(jnp.float32),
            "unvisited": (N - n_reached_all).astype(jnp.int32),
            "m": m_total,
            "n": nn,
            "rmr": rmr.astype(jnp.float32),
            "hop_mean": hop_mean.astype(jnp.float32),
            "hop_median": hop_median.astype(jnp.float32),
            "hop_max": hop_max.astype(jnp.int32),
            "hop_min": hop_min.astype(jnp.int32),
            "stranded": stranded_cnt,
            "branching": branching.astype(jnp.float32),
            "prunes_sent": m_prunes,
            "inb_dropped": inb_dropped,
            "rc_overflow": rc_overflow,
            "rot_failed": rot_failed,
            # degraded-delivery accounting (faults.py; all-zero when the
            # impairment knobs are off)
            "delivered": m_push,
            "dropped": dropped_cnt,
            "suppressed": suppressed_cnt,
            "failed_count": jnp.sum(failed, axis=-1, dtype=jnp.int32),
            # hop-histogram clamp guard: nodes whose true hop distance exceeds
            # the last bin (dist > H - 1) and was clamped into it by the
            # min(dist, H - 1) binning above; dist == H - 1 is that bin's
            # legitimate value and does not count
            "hop_clamped": jnp.sum(reached_all & (dist_all >= H), axis=-1,
                                   dtype=jnp.int32),
        }
        if p.has_pull:
            # pull-phase counters (pull.py accounting; all per-origin [O])
            rows.update(pull_counts)
        if p.has_adaptive:
            # direction-switch telemetry (adaptive.py): the bit in effect
            # this round and whether this round's coverage flipped it
            rows["adaptive_pull_active"] = state.adaptive_pull_on
            rows["adaptive_switched"] = new_adapt != state.adaptive_pull_on
        if detail or trace:
            rows["stranded_mask"] = stranded
            rows["dist"] = jnp.where(reached, dist, -1).astype(jnp.int32)
            rows["failed_mask"] = failed
            if p.has_pull:
                # pull-sourced delivery hop per node (-1 = not pull-rescued);
                # rows["dist"] stays the push-phase distance so the two
                # delivery paths remain separable downstream
                rows["pull_hop"] = jnp.where(pull_got, pull_hop,
                                             -1).astype(jnp.int32)
        if edge_detail:
            # per-edge hop matrix: the engine equivalent of the reference's
            # ``orders`` debug dump (gossip.rs:374-390) — edge (src -> tgt)
            # delivered at hop dist[src]+1; -1 marks unsent fanout slots.
            rows["push_targets"] = jnp.where(delivered, tgt, -1)
            rows["edge_hops"] = jnp.where(
                delivered, jnp.broadcast_to(hop1[:, :, None], (O, N, F)), -1)
        if trace:
            # flight-recorder rows (obs/trace.py): candidate slots + outcome
            # codes, first-delivery senders, prune pairs, rotation events,
            # and the PRE-round active-set snapshot the round pushed through
            # (verb 5 rotates only after delivery, so ``peer``/state.pruned
            # are what verb 1 actually consulted this round).
            rows["trace_peers"] = trace_peers
            rows["trace_code"] = trace_code
            rows["trace_first"] = trace_first
            rows["trace_prune_src"] = trace_prune_src
            rows["trace_prune_dst"] = trace_prune_dst
            rows["trace_rot"] = jnp.where(do_rot, chosen, -1)
            rows["trace_active"] = jnp.where(peer < N, peer, -1)
            rows["trace_pruned"] = state.pruned
            if p.has_pull:
                # flight recorder v2: pull request slots (sampled peer +
                # PULL_* outcome code per slot, pull.py)
                rows["trace_pull_peers"] = trace_pull_peers
                rows["trace_pull_code"] = trace_pull_code
    return new_state, rows


# --------------------------------------------------------------------------
# multi-round runner
# --------------------------------------------------------------------------

@partial(jax.jit, static_argnums=(0, 5, 6, 7, 8), donate_argnums=(3,))
def _run(static, tables, origins, state, knobs, num_iters, detail,
         edge_detail, trace, start_it):
    def step(st, it):
        return round_step(static, tables, origins, st, it, detail=detail,
                          edge_detail=edge_detail, trace=trace, knobs=knobs)
    its = jnp.arange(num_iters) + start_it
    return lax.scan(step, state, its)


def compiled_cache_size() -> int:
    """Number of executables in ``_run``'s jit cache (-1 if the running
    JAX version exposes no cache introspection).  The recompile-count
    regression guard (tests, tools/sweep_smoke.py) asserts on deltas of
    this value across sweep steps."""
    try:
        return int(_run._cache_size())
    except Exception:  # pragma: no cover - older/newer jax internals
        return -1


def clear_compile_cache() -> None:
    """Drop every compiled ``_run`` executable (forces a fresh compile on
    the next call) — the reference arm of compile-once equivalence checks."""
    try:
        _run.clear_cache()
    except Exception:  # pragma: no cover
        pass


def _note_compile_accounting(before: int, after: int) -> None:
    """Record executable compiles vs reuses on the shared span registry
    (``engine/compiles`` / ``engine/cache_hits``; obs/report.py)."""
    if before < 0 or after < 0:
        return
    reg = get_registry()
    if after > before:
        reg.add("engine/compiles", after - before)
    else:
        reg.add("engine/cache_hits", 1)


def run_rounds(params, tables: ClusterTables, origins: jax.Array,
               state: SimState, num_iters: int, start_it=0,
               detail: bool = False, edge_detail: bool = False,
               trace: bool = False, knobs: EngineKnobs | None = None):
    """Run ``num_iters`` rounds under one jitted scan (the reference's hot
    loop, gossip_main.rs:425-565).  Returns (state, rows-of-arrays with a
    leading [num_iters] axis).  ``edge_detail`` additionally exports the
    per-edge (src, fanout-slot) -> (target, hop) matrices per round;
    ``trace`` the flight-recorder event rows (obs/trace.py).

    The jit boundary splits ``params`` (engine/params.py): only the
    ``EngineStatic`` compile key is hashed, while the numeric knobs flow in
    as traced device scalars — so a K-sim sweep stepping any
    ``EngineKnobs`` field (rotation probability, prune threshold, the
    impairment rates/windows, warm-up boundary, ...) compiles once and
    reuses the executable K times.  Every call records either
    ``engine/compiles`` or ``engine/cache_hits`` on the default span
    registry (the recompile-count regression guard).

    The serial companion to this is :func:`engine.lanes.run_rounds_lanes`,
    which stacks the K knob vectors of a sweep into a lane axis and runs
    them as ONE batched device program instead of K calls through here."""
    static, kn = _split_params(params, knobs)
    args = (static, tables, origins, state, kn, int(num_iters),
            bool(detail), bool(edge_detail), bool(trace),
            jnp.asarray(start_it, jnp.int32))
    # capacity observatory (obs/capacity.py): BEFORE the dispatch — the
    # scan donates its state buffers, and lower() only reads avals.  A
    # single bool check when the harvest is off.
    capacity.harvest_dispatch("engine/run_rounds", _run, args)
    before = compiled_cache_size()
    out = _run(*args)
    _note_compile_accounting(before, compiled_cache_size())
    return out
