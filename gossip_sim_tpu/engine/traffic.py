"""The concurrent-traffic round as sort-routed dense-array kernels.

Implements the traffic model specified in :mod:`gossip_sim_tpu.traffic`
on the TPU engine: an M-slot **value axis** whose in-flight values all
push through ONE shared active set (one rotation schedule, one churn
mask) while keeping per-value prune bits and received-cache scoring, with
per-node ingress/egress queue caps creating cross-value contention.

The architecture mirrors ``engine/core.py round_step`` — every cross-node
data movement is a sort — but the batch axis is the value slot ``V``
instead of the origin ``O``, and propagation is **one hop per round**
(every holder pushes each round) instead of a full BFS, which is what
makes per-round queue budgets meaningful:

* candidate compaction (first F valid shared-set slots per (value,
  sender)) is verb 1's slot-key sort with a leading V axis;
* the **egress budget** is a plain exclusive cumsum per sender over the
  value-major candidate order (no sort needed);
* the **ingress budget** ranks all arrived messages of the round in one
  flat ``(target, value-major arrival order)`` sort across the whole
  value axis — the cross-value contention point;
* per-(value, target) inbound ranking, received-cache merge, prune decide
  and prune apply are verbatim verb 2-4 adaptations with ``O -> V``;
* the shared rotation is verb 5 without the origin axis, driven by
  counter-hash uniforms (traffic.py salts) instead of the PRNG — which is
  why the TrafficOracle can be bit-exact with rotation ON.

Every stochastic decision consumes the stateless counter hashes defined
in ``traffic.py``, so ``TrafficOracle`` (loop-based, independent
formulation) must match this engine bit-for-bit under packet loss +
churn (tests/test_traffic.py locks 1k nodes, M >= 16).

Traffic knobs (injection rate, queue caps, stall window) are traced
:class:`EngineKnobs` leaves: a traffic-rate or cap sweep compiles once,
and ``run_traffic_lanes`` vmaps the round over a stacked (state, knobs)
lane axis exactly like ``engine/lanes.py`` does for the single-value
engine.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..adaptive import (SALT_ADAPT_PBLOOM, SALT_ADAPT_PCLASS,
                        SALT_ADAPT_PLOSS, SALT_ADAPT_PMEMBER,
                        switch_update_arr)
from ..faults import (SALT_CHURN, edge_u32_arr, node_u32_arr,
                      rate_threshold_arr, round_basis_arr)
from ..traffic import (SALT_TRAFFIC_LOSS, SALT_TRAFFIC_OCLASS,
                       SALT_TRAFFIC_OMEMBER, SALT_TRAFFIC_RCLASS,
                       SALT_TRAFFIC_RMEMBER, SALT_TRAFFIC_ROT,
                       TRAFFIC_ACCEPTED, TRAFFIC_DEFERRED, TRAFFIC_DROPPED,
                       TRAFFIC_FAILED_TARGET, TRAFFIC_QUEUE_DROPPED,
                       TRAFFIC_SUPPRESSED, TrafficTables,
                       build_shared_active_set, class_draw_arr,
                       traffic_tables, u01_arr, value_basis_arr)
from ..obs import capacity
from .core import (BIG, INF, ClusterTables, _lookup, _note_compile_accounting,
                   _pack_base, _rank_in_run, _split_params)
from .params import EngineKnobs

__all__ = [
    "TrafficState", "init_traffic_state", "traffic_round_step",
    "run_traffic_rounds", "run_traffic_lanes", "broadcast_traffic_state",
    "device_traffic_tables", "traffic_compiled_cache_size",
    "clear_traffic_compile_cache",
]


class TrafficState(NamedTuple):
    """The carried pytree of one traffic simulation (shared network +
    M value slots).  ``V`` = EngineStatic.traffic_slots."""

    active: jax.Array      # [N, S] i32 the ONE shared active set (N = empty)
    failed: jax.Array      # [N]   bool churn failure mask
    next_vid: jax.Array    # []    i32 monotone global value-id counter
    v_live: jax.Array      # [V]   bool slot holds an in-flight value
    v_vid: jax.Array       # [V]   i32 value id (-1 = free slot)
    v_origin: jax.Array    # [V]   i32 injection origin (N = free)
    v_birth: jax.Array     # [V]   i32 injection round
    v_stall: jax.Array     # [V]   i32 consecutive no-progress rounds
    v_holder: jax.Array    # [V, N] bool node holds the value
    v_hop: jax.Array       # [V, N] i32 delivery hop (-1 = unreached)
    v_m: jax.Array         # [V]   i32 accepted msgs + prunes (RMR numerator)
    pruned: jax.Array      # [V, N, S] bool per-value prune bits on the
                           #           SHARED active-set slots
    rc_src: jax.Array      # [V, N, C] i32 received-cache peers (N = empty)
    rc_score: jax.Array    # [V, N, C] i32
    rc_shi: jax.Array      # [V, N, C] i32
    rc_slo: jax.Array      # [V, N, C] i32
    rc_upserts: jax.Array  # [V, N] i32
    # measured-round accumulators (checkpoint-carried, resume-exact)
    inj_acc: jax.Array     # [] i32 values injected
    injdrop_acc: jax.Array  # [] i32 injections dropped (slot table full)
    ret_acc: jax.Array     # [] i32 values retired
    conv_acc: jax.Array    # [] i32 retired with full coverage
    defer_acc: jax.Array   # [N] i32 egress-cap deferrals per sender
    qdrop_acc: jax.Array   # [N] i32 ingress-cap drops per receiver
    sent_acc: jax.Array    # [N] i32 wire messages per sender
    recv_acc: jax.Array    # [N] i32 accepted messages per receiver
    prune_acc: jax.Array   # [N] i32 prune messages per pruner
    # adaptive push-pull (adaptive.py; all-zero outside mode "adaptive"
    # except v_qdrop, which root-causes starvation in every traffic mode)
    v_pull: jax.Array      # [V] bool value is in its pull-rescue phase
    v_rescued: jax.Array   # [V] i32 nodes delivered via pull rescue
    v_qdrop: jax.Array     # [V] i32 ingress queue drops that hit the value
    # node-health observatory planes (obs/health.py; zeros unless
    # EngineStatic.health — the updates are compiled out with the gate off)
    health_prune_recv: jax.Array   # [N] i32 prune messages *received* per
                                   # node (prunee side; prune_acc is the
                                   # pruner side)
    health_lat_acc: jax.Array      # [N] i32 Σ first-delivery latencies
                                   # (it - v_birth + 1) over this node's
                                   # first deliveries, pull rescues included
    health_del_acc: jax.Array      # [N] i32 first-delivery count per node
                                   # (the divisor for health_lat_acc)
    health_rescued_acc: jax.Array  # [N] i32 first deliveries that arrived
                                   # via a pull rescue (subset of del_acc)


def device_traffic_tables(stakes) -> TrafficTables:
    """Host tables -> device-resident pytree (pass into the jitted scan)."""
    t = traffic_tables(np.asarray(stakes, dtype=np.int64))
    return TrafficTables(*(jnp.asarray(a) for a in t))


def init_traffic_state(stakes, params, seed: int) -> TrafficState:
    """Fresh traffic state: the shared active set (traffic.py hash init —
    the identical numpy code the oracle runs) and V empty value slots."""
    p = params.validate()
    if not p.has_traffic:
        raise ValueError("init_traffic_state requires traffic to be "
                         "engaged (traffic_values > 1 or a queue cap)")
    stakes = np.asarray(stakes, dtype=np.int64)
    N, S, C = p.num_nodes, p.active_set_size, p.rc_slots
    V = p.traffic_values
    active = build_shared_active_set(stakes, seed, S, p.init_draws)
    zi = lambda shape: jnp.zeros(shape, jnp.int32)
    return TrafficState(
        active=jnp.asarray(active),
        failed=jnp.zeros((N,), bool),
        next_vid=jnp.int32(0),
        v_live=jnp.zeros((V,), bool),
        v_vid=jnp.full((V,), -1, jnp.int32),
        v_origin=jnp.full((V,), N, jnp.int32),
        v_birth=zi((V,)),
        v_stall=zi((V,)),
        v_holder=jnp.zeros((V, N), bool),
        v_hop=jnp.full((V, N), -1, jnp.int32),
        v_m=zi((V,)),
        pruned=jnp.zeros((V, N, S), bool),
        rc_src=jnp.full((V, N, C), N, jnp.int32),
        rc_score=zi((V, N, C)),
        rc_shi=zi((V, N, C)),
        rc_slo=zi((V, N, C)),
        rc_upserts=zi((V, N)),
        inj_acc=jnp.int32(0), injdrop_acc=jnp.int32(0),
        ret_acc=jnp.int32(0), conv_acc=jnp.int32(0),
        defer_acc=zi((N,)), qdrop_acc=zi((N,)),
        sent_acc=zi((N,)), recv_acc=zi((N,)), prune_acc=zi((N,)),
        v_pull=jnp.zeros((V,), bool),
        v_rescued=zi((V,)), v_qdrop=zi((V,)),
        health_prune_recv=zi((N,)),
        health_lat_acc=zi((N,)),
        health_del_acc=zi((N,)),
        health_rescued_acc=zi((N,)),
    )


def traffic_round_step(params, tables: ClusterTables, ttables: TrafficTables,
                       state: TrafficState, it: jax.Array,
                       detail: bool = False, trace: bool = False,
                       knobs: EngineKnobs | None = None):
    """One traffic round for all V value slots.  Returns (state, rows).

    The spec (phase order, rank orders, precedence) is the module
    docstring of :mod:`gossip_sim_tpu.traffic`; ``TrafficOracle.run_round``
    is the loop-based twin of this function and the two must stay
    bit-identical."""
    p, kn = _split_params(params, knobs)
    if p.traffic_slots <= 0:
        raise ValueError("traffic_round_step requires traffic_slots > 0")
    it = jnp.asarray(it).astype(jnp.int32)
    N, S, C, K, H = (p.num_nodes, p.active_set_size, p.rc_slots,
                     p.k_inbound, p.hist_bins)
    V = p.traffic_slots
    F = min(p.push_fanout, S)
    pack = _pack_base(N)
    pb = pack.bit_length() - 1
    NF, NS = N * F, N * S
    iota_n = jnp.arange(N, dtype=jnp.int32)
    iota_v = jnp.arange(V, dtype=jnp.int32)

    with jax.named_scope("traffic/churn"):
        failed = state.failed
        if p.has_churn:
            basis_c = round_basis_arr(kn.impair_seed, it, SALT_CHURN, jnp)
            hu64 = node_u32_arr(basis_c, jnp.arange(N, dtype=jnp.uint32),
                                jnp).astype(jnp.uint64)
            fail_ev = hu64 < rate_threshold_arr(kn.churn_fail_rate, jnp)
            rec_ev = hu64 < rate_threshold_arr(kn.churn_recover_rate, jnp)
            failed = jnp.where(failed, ~rec_ev, fail_ev)

    with jax.named_scope("traffic/inject"):
        # ---- round-start injection: R counter-hashed stake-weighted
        # origins into ascending free slots (traffic.py spec) -------------
        rate = jnp.clip(kn.traffic_rate, 0, V)
        free = ~state.v_live
        freerank = jnp.cumsum(free.astype(jnp.int32)) - free.astype(jnp.int32)
        n_free = jnp.sum(free, dtype=jnp.int32)
        n_inj = jnp.minimum(rate, n_free)
        injd = rate - n_inj
        do_inj = free & (freerank < n_inj)
        b_oc = round_basis_arr(kn.impair_seed, it, SALT_TRAFFIC_OCLASS, jnp)
        b_om = round_basis_arr(kn.impair_seed, it, SALT_TRAFFIC_OMEMBER, jnp)
        ju = freerank.astype(jnp.uint32)
        origin_new = class_draw_arr(
            ttables,
            u01_arr(node_u32_arr(b_oc, ju, jnp), jnp),
            u01_arr(node_u32_arr(b_om, ju, jnp), jnp), jnp).astype(jnp.int32)
        onehot_o = iota_n[None, :] == origin_new[:, None]          # [V, N]
        v_live = state.v_live | do_inj
        v_vid = jnp.where(do_inj, state.next_vid + freerank, state.v_vid)
        v_origin = jnp.where(do_inj, origin_new, state.v_origin)
        v_birth = jnp.where(do_inj, it, state.v_birth)
        v_holder = jnp.where(do_inj[:, None], onehot_o, state.v_holder)
        v_hop = jnp.where(do_inj[:, None],
                          jnp.where(onehot_o, 0, -1), state.v_hop)
        v_m = jnp.where(do_inj, 0, state.v_m)
        pruned = jnp.where(do_inj[:, None, None], False, state.pruned)
        rc_src = jnp.where(do_inj[:, None, None], N, state.rc_src)
        rc_score = jnp.where(do_inj[:, None, None], 0, state.rc_score)
        rc_shi = jnp.where(do_inj[:, None, None], 0, state.rc_shi)
        rc_slo = jnp.where(do_inj[:, None, None], 0, state.rc_slo)
        rc_ups = jnp.where(do_inj[:, None], 0, state.rc_upserts)
        next_vid = state.next_vid + n_inj
        # adaptive direction state + starvation counters reset with the
        # slot; a fresh value always starts in its push phase
        v_pull = jnp.where(do_inj, False, state.v_pull)
        v_rescued = jnp.where(do_inj, 0, state.v_rescued)
        v_qdrop = jnp.where(do_inj, 0, state.v_qdrop)
        # the prune bits verb 1 consults this round (pre-prune-apply,
        # pre-rotation) — the flight recorder's per-value snapshot
        pruned_pre = pruned
        # pre-delivery holder/hop state: push senders, the pull-rescue
        # responders, and the requester set all consult this snapshot
        v_holder_pre, v_hop_pre = v_holder, v_hop

    with jax.named_scope("traffic/candidates"):
        # ---- verb 1 with a value axis: first F valid SHARED slots -------
        active = state.active                                       # [N, S]
        is_peer = active < N
        q = jnp.minimum(active, N - 1).reshape(1, NS)
        tfail_ns = (_lookup(failed.astype(jnp.int32)[None, :], q, N,
                            pack).reshape(N, S) == 1) & is_peer
        sender = v_live[:, None] & v_holder & (~failed)[None, :]    # [V, N]
        if p.has_adaptive:
            # direction flip (adaptive.py): a pull-phase value generates
            # NO push candidates — its bandwidth share moves to the
            # rescue requests of the nodes still missing it
            sender = sender & (~v_pull)[:, None]
        peer_b = jnp.broadcast_to(active[None], (V, N, S))
        valid = (sender[:, :, None] & is_peer[None] & ~pruned
                 & (peer_b != v_origin[:, None, None]))
        skey = jnp.where(valid, jnp.arange(S, dtype=jnp.int32)[None, None, :],
                         S)
        tf_b = jnp.broadcast_to(tfail_ns.astype(jnp.int32)[None], (V, N, S))
        skey_s, peer_sf, tfail_sf = lax.sort(
            (skey, peer_b, tf_b), dimension=-1, num_keys=1)
        slot_ok = skey_s[..., :F] < S                               # [V,N,F]
        peerF = peer_sf[..., :F]
        tfailF = tfail_sf[..., :F] == 1

    with jax.named_scope("traffic/egress_cap"):
        # ---- egress budget: exclusive cumsum per sender over the
        # value-major candidate order (m asc, fanout slot asc) ------------
        c = slot_ok.astype(jnp.int32)
        ct = jnp.moveaxis(c, 0, 1).reshape(N, V * F)
        erank_t = jnp.cumsum(ct, axis=1) - ct
        erank = jnp.moveaxis(erank_t.reshape(N, V, F), 0, 1)        # [V,N,F]
        ecap_on = kn.node_egress_cap > 0
        sent = slot_ok & (~ecap_on | (erank < kn.node_egress_cap))
        deferred = slot_ok & ~sent

    with jax.named_scope("traffic/network"):
        # ---- faults precedence on sent messages: failed target >
        # partition > per-value packet loss -------------------------------
        live_send = sent & ~tfailF
        sup_mask = drop_mask = None
        if p.has_partition:
            part_on = ((kn.partition_at >= 0) & (it >= kn.partition_at)
                       & ((kn.heal_at < 0) | (it < kn.heal_at)))
            side_dst = tables.side[jnp.minimum(peerF, N)]
            sup_mask = (live_send & part_on
                        & (tables.side[:N][None, :, None] != side_dst))
            live_send = live_send & ~sup_mask
        if p.has_loss:
            basis_e = round_basis_arr(kn.impair_seed, it, SALT_TRAFFIC_LOSS,
                                      jnp)
            vb = value_basis_arr(basis_e, v_vid, jnp)               # [V]
            ue = edge_u32_arr(vb[:, None, None],
                              iota_n.astype(jnp.uint32)[None, :, None],
                              peerF.astype(jnp.uint32), jnp)
            drop_mask = live_send & (
                ue.astype(jnp.uint64)
                < rate_threshold_arr(kn.packet_loss_rate, jnp))
            live_send = live_send & ~drop_mask
        arrived = live_send                                         # [V,N,F]

    with jax.named_scope("traffic/ingress_cap"):
        # ---- ingress budget: ONE flat (target, value-major order) sort
        # across the whole value axis — the cross-value contention point --
        L = V * NF
        tgt_flat = jnp.where(arrived, peerF, N).reshape(1, L)
        order = jnp.arange(L, dtype=jnp.int32)[None, :]
        kd_pc = jnp.concatenate([tgt_flat, iota_n[None, :]], axis=1)
        ord_pc = jnp.concatenate(
            [order, jnp.full((1, N), BIG, jnp.int32)], axis=1)
        k2, ord_s = lax.sort((kd_pc, ord_pc), dimension=-1, num_keys=2)
        rank_a = _rank_in_run(k2)
        is_ps = (ord_s == BIG) & (k2 < N)
        cnt_k = jnp.where(is_ps, k2, BIG)
        _, arr_cnt = lax.sort((cnt_k, rank_a), dimension=-1, num_keys=1)
        arrived_node = arr_cnt[0, :N]                               # [N]
        icap_on = kn.node_ingress_cap > 0
        acc_flag = ((k2 < N) & ~is_ps
                    & (~icap_on | (rank_a < kn.node_ingress_cap)))
        _, acc_back = lax.sort((ord_s, acc_flag.astype(jnp.int32)),
                               dimension=-1, num_keys=1)
        accepted = (acc_back[0, :L].reshape(V, N, F) == 1) & arrived
        qdropped = arrived & ~accepted
        accepted_node = jnp.where(icap_on,
                                  jnp.minimum(arrived_node,
                                              kn.node_ingress_cap),
                                  arrived_node)                     # [N]
        qdrop_node = arrived_node - accepted_node

    with jax.named_scope("traffic/consume"):
        # ---- verb 2 with a value axis: rank accepted inbound per
        # (value, target) by (clamped hop, src); deliver + first-sender ---
        th = v_hop + 1                                              # [V, N]
        ch = jnp.minimum(th, H - 1)
        kv = ((ch[:, :, None] << pb) | iota_n[None, :, None])
        kv = jnp.broadcast_to(kv, (V, N, F)).reshape(V, NF)
        clampf = jnp.broadcast_to((th > H - 1)[:, :, None].astype(jnp.int32),
                                  (V, N, F)).reshape(V, NF)
        shi_e = jnp.broadcast_to(tables.shi[None, :N, None],
                                 (V, N, F)).reshape(V, NF)
        slo_e = jnp.broadcast_to(tables.slo[None, :N, None],
                                 (V, N, F)).reshape(V, NF)
        kd = jnp.where(accepted, peerF, N).reshape(V, NF)
        pseudo_t = jnp.broadcast_to(iota_n[None, :], (V, N))
        kd_c = jnp.concatenate([kd, pseudo_t], axis=1)              # [V,NF+N]
        kv_c = jnp.concatenate([kv, jnp.full((V, N), BIG)], axis=1)
        cl_c = jnp.concatenate([clampf, jnp.zeros((V, N), jnp.int32)], axis=1)
        shi_c = jnp.concatenate([shi_e, jnp.zeros((V, N), jnp.int32)], axis=1)
        slo_c = jnp.concatenate([slo_e, jnp.zeros((V, N), jnp.int32)], axis=1)
        st_, skv, scl, shi_s, slo_s = lax.sort(
            (kd_c, kv_c, cl_c, shi_c, slo_c), dimension=-1, num_keys=2)
        rank = _rank_in_run(st_)
        is_pseudo = (skv == BIG) & (st_ < N)
        real = (skv != BIG) & (st_ < N)

        # rank-0 (minimum (hop, src)) entry per (value, target) run
        fd_k = jnp.where((rank == 0) & (st_ < N), st_, BIG)
        _, fd_kv, fd_cl = lax.sort((fd_k, skv, scl), dimension=-1, num_keys=1)
        fkv = fd_kv[:, :N]
        has_inb = fkv != BIG                                        # [V, N]
        first_src = jnp.where(has_inb, fkv & (pack - 1), -1)
        first_hop = jnp.where(has_inb, fkv >> pb, -1)
        first_clamped = jnp.where(has_inb, fd_cl[:, :N], 0)

        # accepted counts per (value, target) via the pseudo rank
        ing_k = jnp.where(is_pseudo, st_, BIG)
        _, ing_cnt = lax.sort((ing_k, rank), dimension=-1, num_keys=1)
        ingress_mv = ing_cnt[:, :N]                                 # [V, N]
        inb_dropped = jnp.sum(real & (rank >= K), dtype=jnp.int32)

        new_del = has_inb & ~v_holder                               # [V, N]
        v_holder = v_holder | new_del
        v_hop = jnp.where(new_del, first_hop, v_hop)
        hop_clamped = jnp.sum(new_del & (first_clamped == 1),
                              dtype=jnp.int32)
        delivered = jnp.sum(new_del, dtype=jnp.int32)
        accepted_total = jnp.sum(accepted, dtype=jnp.int32)
        redundant = accepted_total - delivered

        # inbound rows [V, N, K] via the slot-aligned two-sort compaction
        NK = N * K
        keep = real & (rank < K)
        gk = jnp.where(keep, (st_ * K + rank) * 2, BIG)
        slot_keys = jnp.broadcast_to(
            jnp.arange(NK, dtype=jnp.int32)[None, :] * 2 + 1, (V, NK))
        ga = jnp.concatenate([gk, slot_keys], axis=1)
        kv_a = jnp.concatenate([skv, jnp.full((V, NK), BIG)], axis=1)
        shi_a = jnp.concatenate([shi_s, jnp.zeros((V, NK), jnp.int32)],
                                axis=1)
        slo_a = jnp.concatenate([slo_s, jnp.zeros((V, NK), jnp.int32)],
                                axis=1)
        sA, kvA, hiA, loA = lax.sort((ga, kv_a, shi_a, slo_a),
                                     dimension=-1, num_keys=1)
        bndA = jnp.concatenate(
            [jnp.ones((V, 1), bool), (sA >> 1)[:, 1:] != (sA >> 1)[:, :-1]],
            axis=1)
        gB = jnp.where(bndA, sA, BIG)
        sB, kvB, hiB, loB = lax.sort((gB, kvA, hiA, loA),
                                     dimension=-1, num_keys=1)
        inb_real = (sB[:, :NK] & 1) == 0
        inb = jnp.where(inb_real, kvB[:, :NK] & (pack - 1), N).reshape(V, N, K)
        inb_shi = jnp.where(inb_real, hiB[:, :NK], 0).reshape(V, N, K)
        inb_slo = jnp.where(inb_real, loB[:, :NK], 0).reshape(V, N, K)

    # per-value ingress-drop attribution (starved_queue_drop root-causing;
    # tracked in every traffic mode, not just adaptive)
    qdrop_v = jnp.sum(qdropped, axis=(1, 2), dtype=jnp.int32)       # [V]
    v_qdrop = v_qdrop + qdrop_v

    pull_del = None
    adaptive_counts = {}
    if p.has_adaptive:
        with jax.named_scope("traffic/pull_rescue"):
            # ---- adaptive pull-rescue phase (adaptive.py spec) ----------
            # Per pull-phase value, every live node still missing it
            # sends pull_fanout stake-weighted requests, decorrelated per
            # value id.  Requests CONTINUE the push phase's per-node
            # egress/ingress budgets (value-major order after all push
            # messages); responses ride the reverse path of an accepted
            # request and the requester keeps the minimum
            # (clamped hop, clamp bit, peer) response — the exact loop
            # TrafficOracle runs.
            PS = p.pull_slots
            NPS = N * PS
            L2 = V * NPS
            v_pull_eff = v_pull & v_live                             # [V]
            b_pc = round_basis_arr(kn.impair_seed, it, SALT_ADAPT_PCLASS,
                                   jnp)
            b_pm = round_basis_arr(kn.impair_seed, it, SALT_ADAPT_PMEMBER,
                                   jnp)
            vb_c = value_basis_arr(b_pc, v_vid, jnp)                 # [V]
            vb_m = value_basis_arr(b_pm, v_vid, jnp)
            nodes_u = jnp.arange(N, dtype=jnp.uint32)[None, :, None]
            slots_u = jnp.arange(PS, dtype=jnp.uint32)[None, None, :]
            peers = class_draw_arr(
                ttables,
                u01_arr(edge_u32_arr(vb_c[:, None, None], nodes_u,
                                     slots_u, jnp), jnp),
                u01_arr(edge_u32_arr(vb_m[:, None, None], nodes_u,
                                     slots_u, jnp), jnp),
                jnp).astype(jnp.int32)                               # [V,N,PS]
            slot_live_p = (jnp.arange(PS, dtype=jnp.int32)[None, None, :]
                           < kn.pull_fanout)
            want = (v_pull_eff[:, None, None]
                    & (~v_holder_pre)[:, :, None]
                    & (~failed)[None, :, None]
                    & slot_live_p
                    & (peers != iota_n[None, :, None]))
            # egress budget: continue each requester's push usage in
            # value-major (value, slot) order
            push_out = jnp.sum(sent, axis=(0, 2), dtype=jnp.int32)   # [N]
            cpw = jnp.moveaxis(want.astype(jnp.int32), 0, 1
                               ).reshape(N, V * PS)
            prank = jnp.moveaxis(
                (jnp.cumsum(cpw, axis=1) - cpw).reshape(N, V, PS), 0, 1)
            p_sent = want & (~ecap_on
                             | (push_out[None, :, None] + prank
                                < kn.node_egress_cap))
            p_def = want & ~p_sent
            # network precedence: failed peer > partition > request loss
            q2 = jnp.minimum(peers, N - 1).reshape(1, L2)
            peer_failed = (_lookup(failed.astype(jnp.int32)[None, :], q2,
                                   N, pack).reshape(V, N, PS) == 1)
            live_req = p_sent & ~peer_failed
            p_failed_target = p_sent & peer_failed
            p_sup = p_drop = None
            if p.has_partition:
                part_on2 = ((kn.partition_at >= 0)
                            & (it >= kn.partition_at)
                            & ((kn.heal_at < 0) | (it < kn.heal_at)))
                side_dst2 = tables.side[jnp.minimum(peers, N)]
                p_sup = (live_req & part_on2
                         & (tables.side[:N][None, :, None] != side_dst2))
                live_req = live_req & ~p_sup
            if p.has_loss:
                b_pl = round_basis_arr(kn.impair_seed, it,
                                       SALT_ADAPT_PLOSS, jnp)
                vb_l = value_basis_arr(b_pl, v_vid, jnp)
                ue2 = edge_u32_arr(vb_l[:, None, None],
                                   iota_n.astype(jnp.uint32)[None, :, None],
                                   peers.astype(jnp.uint32), jnp)
                p_drop = live_req & (
                    ue2.astype(jnp.uint64)
                    < rate_threshold_arr(kn.packet_loss_rate, jnp))
                live_req = live_req & ~p_drop
            req_arrived = live_req                               # [V,N,PS]

            # ingress budget: requests rank per peer AFTER the round's
            # push acceptances, in value-major (value, requester, slot)
            # order — one flat sort, same pseudo-entry trick as push
            peer_flat2 = peers.reshape(1, L2)
            arr_flat2 = req_arrived.reshape(1, L2)
            order2 = jnp.arange(L2, dtype=jnp.int32)[None, :]
            kd2c = jnp.concatenate(
                [jnp.where(arr_flat2, peer_flat2, N), iota_n[None, :]],
                axis=1)
            ord2c = jnp.concatenate(
                [order2, jnp.full((1, N), BIG, jnp.int32)], axis=1)
            k3, ord3 = lax.sort((kd2c, ord2c), dimension=-1, num_keys=2)
            rank3 = _rank_in_run(k3)
            is_ps3 = (ord3 == BIG) & (k3 < N)
            cnt_k3 = jnp.where(is_ps3, k3, BIG)
            _, arrcnt3 = lax.sort((cnt_k3, rank3), dimension=-1,
                                  num_keys=1)
            req_arrived_node = arrcnt3[0, :N]                    # [N]
            _, rank_back3 = lax.sort((ord3, rank3), dimension=-1,
                                     num_keys=1)
            req_rank = rank_back3[0, :L2].reshape(V, N, PS)
            # the peer's already-consumed push ingress (< pack by the
            # validate() cap bound, so the sort-join fast path is exact)
            base_tab = jnp.clip(
                jnp.minimum(accepted_node.astype(jnp.int32),
                            jnp.maximum(kn.node_ingress_cap, 0)),
                0, pack - 1)
            base_req = _lookup(base_tab[None, :], q2, N,
                               pack).reshape(V, N, PS)
            req_acc = req_arrived & (
                ~icap_on | (base_req + req_rank < kn.node_ingress_cap))
            req_qdropped = req_arrived & ~req_acc

            # response decision: peer holds (pre-delivery state) and the
            # requester's per-value bloom digest did not false-positive
            holds_req = _lookup(v_holder_pre.astype(jnp.int32),
                                peers.reshape(V, NPS), N,
                                pack).reshape(V, N, PS) == 1
            b_pb = round_basis_arr(kn.impair_seed, it, SALT_ADAPT_PBLOOM,
                                   jnp)
            vb_b = value_basis_arr(b_pb, v_vid, jnp)
            fp_req = (node_u32_arr(vb_b[:, None],
                                   jnp.arange(N, dtype=jnp.uint32)[None, :],
                                   jnp).astype(jnp.uint64)
                      < rate_threshold_arr(kn.pull_bloom_fp_rate, jnp))
            transfer = req_acc & holds_req & ~fp_req[:, :, None]

            # delivery: minimum (clamped hop, clamp bit, peer) response
            hv = jnp.where(v_holder_pre, v_hop_pre, 0)
            d_hop = _lookup(hv, peers.reshape(V, NPS), N,
                            pack).reshape(V, N, PS)
            th2 = d_hop + 1
            ch2 = jnp.minimum(th2, H - 1)
            clampb = (th2 > H - 1).astype(jnp.int32)
            rkey = jnp.where(transfer,
                             (((ch2 << 1) | clampb) << pb) | peers, BIG)
            win = jnp.min(rkey, axis=-1)                         # [V, N]
            pull_del = (win != BIG) & ~v_holder   # push deliveries win ties
            win_ch = win >> (pb + 1)
            win_clamp = (win >> pb) & 1
            v_holder = v_holder | pull_del
            v_hop = jnp.where(pull_del, win_ch, v_hop)
            pull_clamped = jnp.sum(pull_del & (win_clamp == 1),
                                   dtype=jnp.int32)
            hop_clamped = hop_clamped + pull_clamped
            pull_hop_row = jnp.where(pull_del, win_ch, -1)       # [V, N]

            # per-value / per-node accounting
            served_v = jnp.sum(req_acc, axis=(1, 2), dtype=jnp.int32)
            resp_v = jnp.sum(transfer, axis=(1, 2), dtype=jnp.int32)
            v_m = v_m + served_v + resp_v
            v_rescued = v_rescued + jnp.sum(pull_del, axis=-1,
                                            dtype=jnp.int32)
            v_qdrop = v_qdrop + jnp.sum(req_qdropped, axis=(1, 2),
                                        dtype=jnp.int32)
            preq_out = jnp.sum(p_sent, axis=(0, 2), dtype=jnp.int32)
            p_def_node = jnp.sum(p_def, axis=(0, 2), dtype=jnp.int32)
            resp_in = jnp.sum(transfer, axis=(0, 2), dtype=jnp.int32)
            rem_node = jnp.maximum(
                kn.node_ingress_cap - accepted_node.astype(jnp.int32), 0)
            served_node = jnp.where(icap_on,
                                    jnp.minimum(req_arrived_node, rem_node),
                                    req_arrived_node)            # [N]
            pull_qdrop_node = req_arrived_node - served_node

            def _per_peer_count(mask):
                kdp = jnp.concatenate(
                    [jnp.where(mask.reshape(1, L2), peer_flat2, N),
                     iota_n[None, :]], axis=1)
                kvp = jnp.concatenate(
                    [jnp.zeros((1, L2), jnp.int32),
                     jnp.full((1, N), BIG)], axis=1)
                skp, svp = lax.sort((kdp, kvp), dimension=-1, num_keys=2)
                rkp = _rank_in_run(skp)
                ckp = jnp.where((svp == BIG) & (skp < N), skp, BIG)
                _, cntp = lax.sort((ckp, rkp), dimension=-1, num_keys=1)
                return cntp[0, :N]

            resp_peer = _per_peer_count(transfer)                # [N]
            zero_a = jnp.int32(0)
            adaptive_counts = {
                "pull_sent": jnp.sum(p_sent, dtype=jnp.int32),
                "pull_deferred": jnp.sum(p_def, dtype=jnp.int32),
                "pull_failed_target": jnp.sum(p_failed_target,
                                              dtype=jnp.int32),
                "pull_suppressed": (jnp.sum(p_sup, dtype=jnp.int32)
                                    if p_sup is not None else zero_a),
                "pull_dropped": (jnp.sum(p_drop, dtype=jnp.int32)
                                 if p_drop is not None else zero_a),
                "pull_arrived": jnp.sum(req_arrived, dtype=jnp.int32),
                "pull_queue_dropped": jnp.sum(req_qdropped,
                                              dtype=jnp.int32),
                "pull_served": jnp.sum(served_v, dtype=jnp.int32),
                "pull_responses": jnp.sum(resp_v, dtype=jnp.int32),
                "pull_rescued": jnp.sum(pull_del, dtype=jnp.int32),
                "pull_active_values": jnp.sum(v_pull_eff,
                                              dtype=jnp.int32),
            }

    with jax.named_scope("traffic/rc_merge"):
        # ---- received-cache merge (verb 2 tail, O -> V) -----------------
        kpos = jnp.arange(K, dtype=jnp.int32)[None, None, :]
        fk = jnp.concatenate([rc_src * 2, inb * 2 + 1], axis=-1)
        fpos = jnp.concatenate(
            [jnp.broadcast_to(jnp.full((1, 1, C), BIG), (V, N, C)),
             jnp.broadcast_to(kpos, (V, N, K))], axis=-1)
        fk_s, fpos_s = lax.sort((fk, fpos), dimension=-1, num_keys=1)
        dup_s = jnp.concatenate(
            [jnp.zeros((V, N, 1), bool),
             (fk_s[..., 1:] >> 1) == (fk_s[..., :-1] >> 1)], axis=-1)
        back_k, back_d = lax.sort(
            (fpos_s, dup_s.astype(jnp.int32)), dimension=-1, num_keys=1)
        found = (back_d[..., :K] == 1) & (inb < N)

        base_len = jnp.sum(rc_src < N, axis=-1, dtype=jnp.int32)
        want = (inb < N) & ~found
        ln = base_len
        allowed_cols = []
        for r in range(K):
            a = want[..., r] & ((r < 2) | (ln < p.received_cap))
            allowed_cols.append(a)
            ln = ln + a.astype(jnp.int32)
        allowed = jnp.stack(allowed_cols, axis=-1)

        bump = found & (kpos < 2)
        include = bump | allowed
        contrib = (kpos < 2).astype(jnp.int32)
        mk = jnp.concatenate(
            [jnp.where(rc_src < N, rc_src * 2, BIG),
             jnp.where(include, inb * 2 + 1, BIG)], axis=-1)
        msc = jnp.concatenate(
            [rc_score, jnp.where(include, contrib, 0)], axis=-1)
        mhi = jnp.concatenate([rc_shi, inb_shi], axis=-1)
        mlo = jnp.concatenate([rc_slo, inb_slo], axis=-1)
        mk_s, msc_s, mhi_s, mlo_s = lax.sort(
            (mk, msc, mhi, mlo), dimension=-1, num_keys=1)
        is_dup = jnp.concatenate(
            [jnp.zeros((V, N, 1), bool),
             ((mk_s[..., 1:] >> 1) == (mk_s[..., :-1] >> 1))
             & ((mk_s[..., 1:] & 1) == 1)], axis=-1)
        nxt_dup = jnp.concatenate([is_dup[..., 1:],
                                   jnp.zeros((V, N, 1), bool)], axis=-1)
        nxt_sc = jnp.concatenate([msc_s[..., 1:],
                                  jnp.zeros((V, N, 1), jnp.int32)], axis=-1)
        msc_s = msc_s + jnp.where(nxt_dup, nxt_sc, 0)
        valid_m = (mk_s != BIG) & ~is_dup
        ck = jnp.where(valid_m, mk_s >> 1, BIG)
        ck_s, csc, chi, clo = lax.sort(
            (ck, msc_s, mhi_s, mlo_s), dimension=-1, num_keys=1)
        n_valid = jnp.sum(valid_m, axis=-1, dtype=jnp.int32)
        rc_overflow = jnp.sum(jnp.maximum(n_valid - C, 0), dtype=jnp.int32)
        rc_src = jnp.where(ck_s[..., :C] != BIG, ck_s[..., :C], N)
        rc_score = jnp.where(ck_s[..., :C] != BIG, csc[..., :C], 0)
        rc_shi = jnp.where(ck_s[..., :C] != BIG, chi[..., :C], 0)
        rc_slo = jnp.where(ck_s[..., :C] != BIG, clo[..., :C], 0)
        any_inb = inb[..., 0] < N
        rc_ups = rc_ups + any_inb.astype(jnp.int32)

    with jax.named_scope("traffic/prune_decide"):
        # ---- verb 3 with a value axis (origin = the value's origin) -----
        fired = (rc_ups >= p.min_num_upserts) & v_live[:, None]
        stake_dest = tables.stakes[:N][None, :]
        stake_org = tables.stakes[jnp.minimum(v_origin, N)][:, None]
        min_stake = jnp.minimum(stake_dest, stake_org)              # [V, N]
        min_ingress_stake = (min_stake.astype(jnp.float64)
                             * kn.prune_stake_threshold).astype(jnp.int64)
        member = rc_src < N
        mx = jnp.iinfo(jnp.int32).max
        neg_score = jnp.where(member, -rc_score, mx)
        neg_hi = jnp.where(member, -rc_shi, mx)
        neg_lo = jnp.where(member, -rc_slo, mx)
        _, _, _, src_sorted, hi_sorted, lo_sorted = lax.sort(
            (neg_score, neg_hi, neg_lo, rc_src, rc_shi, rc_slo),
            dimension=-1, num_keys=4)
        memb_sorted = src_sorted < N
        stake_sorted = ((hi_sorted.astype(jnp.int64) << 31)
                        | lo_sorted.astype(jnp.int64))
        cum_excl = jnp.cumsum(stake_sorted, axis=-1) - stake_sorted
        posn = jnp.arange(C)[None, None, :]
        pruned_slot = (memb_sorted
                       & (posn >= kn.min_ingress_nodes)
                       & (cum_excl >= min_ingress_stake[..., None])
                       & (src_sorted != v_origin[:, None, None])
                       & fired[..., None])
        n_pruned = jnp.sum(pruned_slot, axis=-1, dtype=jnp.int32)   # [V, N]
        m_prunes = jnp.sum(n_pruned, axis=-1, dtype=jnp.int32)      # [V]
        accepted_mv = jnp.sum(ingress_mv, axis=-1, dtype=jnp.int32)  # [V]
        v_m = v_m + accepted_mv + m_prunes

    with jax.named_scope("traffic/prune_apply"):
        # ---- verb 4 with a value axis on the SHARED edge keys -----------
        NP = min(p.pa_slots, C)
        pk_rows = jnp.where(pruned_slot, posn.astype(jnp.int32), C)
        pk_s, psrc_s = lax.sort((pk_rows, src_sorted), dimension=-1,
                                num_keys=1)
        over_budget = (jnp.any(pk_s[..., NP:NP + 1] < C) if NP < C
                       else jnp.array(False))
        t_rows = jnp.broadcast_to(iota_n[None, :, None], (V, N, C))
        pair_live = pk_s < C
        edge_keys = (jnp.minimum(active, N - 1) * pack
                     + iota_n[:, None]).reshape(NS)
        edge_keys = jnp.where(is_peer.reshape(NS), edge_keys * 2 + 1, BIG)
        edge_keys = jnp.broadcast_to(edge_keys[None, :], (V, NS))
        edge_pos = jnp.broadcast_to(
            jnp.arange(NS, dtype=jnp.int32)[None, :], (V, NS))

        def _apply(np_slots):
            pair_keys = jnp.where(
                pair_live[..., :np_slots],
                (t_rows[..., :np_slots] * pack + psrc_s[..., :np_slots]) * 2,
                BIG).reshape(V, N * np_slots)
            k = jnp.concatenate([edge_keys, pair_keys], axis=1)
            ppos = jnp.concatenate(
                [edge_pos, jnp.full((V, N * np_slots), BIG)], axis=1)
            ks, pos_s = lax.sort((k, ppos), dimension=-1, num_keys=1)
            hit_s = jnp.concatenate(
                [jnp.zeros((V, 1), bool),
                 ((ks[:, 1:] >> 1) == (ks[:, :-1] >> 1))
                 & ((ks[:, 1:] & 1) == 1)], axis=1)
            _, hit_back = lax.sort((pos_s, hit_s.astype(jnp.int32)),
                                   dimension=-1, num_keys=1)
            return hit_back[:, :NS].reshape(V, N, S) == 1

        if NP < C:
            hit = lax.cond(over_budget, lambda: _apply(C),
                           lambda: _apply(NP))
        else:
            hit = _apply(C)
        pruned = pruned | (hit & is_peer[None])
        rc_src = jnp.where(fired[..., None], N, rc_src)
        rc_score = jnp.where(fired[..., None], 0, rc_score)
        rc_shi = jnp.where(fired[..., None], 0, rc_shi)
        rc_slo = jnp.where(fired[..., None], 0, rc_slo)
        rc_ups = jnp.where(fired, 0, rc_ups)

    with jax.named_scope("traffic/rotate"):
        # ---- verb 5, shared: ONE hash-driven rotation schedule ----------
        T = p.rot_tries
        b_rot = round_basis_arr(kn.impair_seed, it, SALT_TRAFFIC_ROT, jnp)
        b_rc = round_basis_arr(kn.impair_seed, it, SALT_TRAFFIC_RCLASS, jnp)
        b_rm = round_basis_arr(kn.impair_seed, it, SALT_TRAFFIC_RMEMBER, jnp)
        u_rot = u01_arr(node_u32_arr(b_rot, jnp.arange(N, dtype=jnp.uint32),
                                     jnp), jnp)
        rotate = u_rot < kn.probability_of_rotation
        nodes_u = jnp.arange(N, dtype=jnp.uint32)[:, None]
        tries_u = jnp.arange(T, dtype=jnp.uint32)[None, :]
        cands = class_draw_arr(
            ttables,
            u01_arr(edge_u32_arr(b_rc, nodes_u, tries_u, jnp), jnp),
            u01_arr(edge_u32_arr(b_rm, nodes_u, tries_u, jnp), jnp),
            jnp).astype(jnp.int32)                                  # [N, T]
        chosen = jnp.full((N,), N, jnp.int32)
        found_new = jnp.zeros((N,), bool)
        for t in range(T):
            cand = cands[:, t]
            ok = ((cand != iota_n)
                  & ~jnp.any(active == cand[:, None], axis=-1))
            take = ok & ~found_new
            chosen = jnp.where(take, cand, chosen)
            found_new = found_new | ok
        do_rot = rotate & found_new
        cnt = jnp.sum(is_peer, axis=-1, dtype=jnp.int32)
        full_row = cnt >= S
        shift_act = jnp.concatenate([active[:, 1:], chosen[:, None]], axis=-1)
        slot_oh = (jnp.arange(S)[None, :]
                   == jnp.minimum(cnt, S - 1)[:, None])
        append_act = jnp.where(slot_oh & ~full_row[:, None],
                               chosen[:, None], active)
        new_active = jnp.where(do_rot[:, None],
                               jnp.where(full_row[:, None], shift_act,
                                         append_act),
                               active)
        shift_prn = jnp.concatenate(
            [pruned[:, :, 1:], jnp.zeros((V, N, 1), bool)], axis=-1)
        pruned = jnp.where((do_rot & full_row)[None, :, None],
                           shift_prn, pruned)

    with jax.named_scope("traffic/retire"):
        # ---- stall tracking, retirement, slot recycle -------------------
        prog_cnt = jnp.sum(new_del, axis=-1, dtype=jnp.int32)       # [V]
        if pull_del is not None:
            # pull-rescue deliveries count as progress (they reset the
            # stall clock exactly like push first deliveries)
            prog_cnt = prog_cnt + jnp.sum(pull_del, axis=-1,
                                          dtype=jnp.int32)
        progress = prog_cnt > 0                                     # [V]
        v_stall = jnp.where(~v_live, 0,
                            jnp.where(do_inj | progress, 0,
                                      state.v_stall + 1))
        holders = jnp.sum(v_holder, axis=-1, dtype=jnp.int32)       # [V]
        full_v = holders == N
        retire = v_live & (full_v | (v_stall >= kn.traffic_stall_rounds))
        v_live_post = v_live & ~retire
        hops_sum = jnp.sum(jnp.where(v_holder, v_hop, 0), axis=-1,
                           dtype=jnp.int32)
        # adaptive direction switch (end of round, survivors only;
        # adaptive.py switch_update_arr — the shared f64 formulation)
        if p.has_adaptive:
            new_v_pull = jnp.where(
                v_live_post,
                switch_update_arr(holders, N, v_pull,
                                  kn.adaptive_switch_threshold,
                                  kn.adaptive_switch_hysteresis, jnp),
                False)
            switched = jnp.sum(v_live_post & new_v_pull & ~v_pull,
                               dtype=jnp.int32)
        else:
            new_v_pull = v_pull
            switched = jnp.int32(0)

    with jax.named_scope("traffic/round_stats"):
        g = (it >= kn.warm_up_rounds).astype(jnp.int32)
        node_deferred = jnp.sum(deferred, axis=(0, 2),
                                dtype=jnp.int32)                    # [N] src
        sent_node = jnp.sum(sent, axis=(0, 2), dtype=jnp.int32)
        n_retired = jnp.sum(retire, dtype=jnp.int32)
        n_conv = jnp.sum(retire & full_v, dtype=jnp.int32)
        zero_s = jnp.int32(0)
        if pull_del is not None:
            # pull-rescue traffic joins every per-node accounting stream:
            # requests are requester egress + peer ingress, responses are
            # peer egress + requester ingress, deferrals/queue drops join
            # the same depth counters the oracle's shared loops fill
            node_deferred = node_deferred + p_def_node
            sent_node_all = sent_node + preq_out + resp_peer
            recv_node_all = (accepted_node.astype(jnp.int32)
                             + served_node + resp_in)
            qdrop_node_all = (qdrop_node.astype(jnp.int32)
                              + pull_qdrop_node)
            inflow_node = accepted_node.astype(jnp.int32) + served_node
        else:
            sent_node_all = sent_node
            recv_node_all = accepted_node.astype(jnp.int32)
            qdrop_node_all = qdrop_node.astype(jnp.int32)
            inflow_node = accepted_node.astype(jnp.int32)
        if p.health:
            # node-health observatory planes (obs/health.py): first
            # deliveries (push + pull rescues, disjoint by construction —
            # rescues only reach non-holders) feed per-node latency
            # sums/counts against the value's injection round; prunee-side
            # prune counts come from one deterministic integer segment-sum
            # over the sparse (pruner -> prunee) slots, skipped entirely on
            # zero-prune rounds behind the same lax.cond the trace uses.
            del_nv = new_del.astype(jnp.int32)                   # [V, N]
            resc_nv = (pull_del.astype(jnp.int32)
                       if pull_del is not None
                       else jnp.zeros((V, N), jnp.int32))
            del_all = del_nv + resc_nv
            lat_v = it - v_birth + 1                             # [V]
            lat_node = jnp.sum(del_all * lat_v[:, None], axis=0,
                               dtype=jnp.int32)
            del_node = jnp.sum(del_all, axis=0, dtype=jnp.int32)
            resc_node = jnp.sum(resc_nv, axis=0, dtype=jnp.int32)

            def _prune_recv():
                seg = jnp.where(pruned_slot, src_sorted, N).reshape(-1)
                return jax.ops.segment_sum(
                    pruned_slot.astype(jnp.int32).reshape(-1), seg,
                    num_segments=N + 1)[:N]

            prune_recv_node = lax.cond(
                jnp.sum(m_prunes) > 0, _prune_recv,
                lambda: jnp.zeros((N,), jnp.int32))
            new_health_prune_recv = (state.health_prune_recv
                                     + g * prune_recv_node)
            new_health_lat = state.health_lat_acc + g * lat_node
            new_health_del = state.health_del_acc + g * del_node
            new_health_resc = state.health_rescued_acc + g * resc_node
        else:
            new_health_prune_recv = state.health_prune_recv
            new_health_lat = state.health_lat_acc
            new_health_del = state.health_del_acc
            new_health_resc = state.health_rescued_acc
        new_state = TrafficState(
            active=new_active, failed=failed, next_vid=next_vid,
            v_live=v_live_post, v_vid=v_vid, v_origin=v_origin,
            v_birth=v_birth, v_stall=v_stall, v_holder=v_holder,
            v_hop=v_hop, v_m=v_m, pruned=pruned,
            rc_src=rc_src, rc_score=rc_score, rc_shi=rc_shi, rc_slo=rc_slo,
            rc_upserts=rc_ups,
            inj_acc=state.inj_acc + g * n_inj,
            injdrop_acc=state.injdrop_acc + g * injd,
            ret_acc=state.ret_acc + g * n_retired,
            conv_acc=state.conv_acc + g * n_conv,
            defer_acc=state.defer_acc + g * node_deferred,
            qdrop_acc=state.qdrop_acc + g * qdrop_node_all,
            sent_acc=state.sent_acc + g * sent_node_all,
            recv_acc=state.recv_acc + g * recv_node_all,
            prune_acc=state.prune_acc
            + g * jnp.sum(n_pruned, axis=0, dtype=jnp.int32),
            v_pull=new_v_pull, v_rescued=v_rescued, v_qdrop=v_qdrop,
            health_prune_recv=new_health_prune_recv,
            health_lat_acc=new_health_lat,
            health_del_acc=new_health_del,
            health_rescued_acc=new_health_resc,
        )
        rows = {
            "injected": n_inj,
            "inject_dropped": injd,
            "live": jnp.sum(v_live_post, dtype=jnp.int32),
            "sends": jnp.sum(sent, dtype=jnp.int32),
            "deferred": jnp.sum(deferred, dtype=jnp.int32),
            "failed_target": jnp.sum(sent & tfailF, dtype=jnp.int32),
            "suppressed": (jnp.sum(sup_mask, dtype=jnp.int32)
                           if sup_mask is not None else zero_s),
            "dropped": (jnp.sum(drop_mask, dtype=jnp.int32)
                        if drop_mask is not None else zero_s),
            "arrived": jnp.sum(arrived, dtype=jnp.int32),
            "queue_dropped": jnp.sum(qdropped, dtype=jnp.int32),
            "accepted": accepted_total,
            "delivered": delivered,
            "redundant": redundant,
            "prunes_sent": jnp.sum(m_prunes, dtype=jnp.int32),
            "retired": n_retired,
            "converged": n_conv,
            "hop_clamped": hop_clamped,
            "qdepth_max": jnp.max(node_deferred),
            "inflow_max": jnp.max(inflow_node).astype(jnp.int32),
            "inb_dropped": inb_dropped,
            "rc_overflow": rc_overflow,
            # per-value retirement records (valid where ret_mask)
            "ret_mask": retire,
            "ret_vid": v_vid,
            "ret_origin": v_origin,
            "ret_birth": v_birth,
            "ret_holders": holders,
            "ret_m": v_m,
            "ret_full": full_v,
            "ret_hops_sum": hops_sum,
            # starvation root-causing (terminal-cause attribution)
            "ret_rescued": v_rescued,
            "ret_qdrop": v_qdrop,
        }
        if p.has_adaptive:
            # adaptive pull-rescue counters (sim_adaptive series) + the
            # end-of-round direction flips
            rows.update(adaptive_counts)
            rows["switched_to_pull"] = switched
        if detail or trace:
            rows["live_mask"] = v_live_post
            rows["t_holder"] = v_holder
            rows["t_hop"] = jnp.where(v_holder, v_hop, -1)
            rows["node_deferred"] = node_deferred
            rows["node_queue_dropped"] = qdrop_node_all
            rows["node_sent"] = sent_node_all
            rows["node_recv"] = recv_node_all
        if trace:
            # flight recorder v3 (obs/trace.py): value-slot event rows.
            # codes: accepted(1) / failed_target(2) / suppressed(3) /
            # dropped(4) / deferred(5) / queue_dropped(6), the faults
            # precedence extended by the queue caps (traffic.py).
            code = jnp.zeros((V, N, F), jnp.int32)
            code = jnp.where(slot_ok, TRAFFIC_DEFERRED, code)
            code = jnp.where(sent & tfailF, TRAFFIC_FAILED_TARGET, code)
            if sup_mask is not None:
                code = jnp.where(sup_mask, TRAFFIC_SUPPRESSED, code)
            if drop_mask is not None:
                code = jnp.where(drop_mask, TRAFFIC_DROPPED, code)
            code = jnp.where(qdropped, TRAFFIC_QUEUE_DROPPED, code)
            code = jnp.where(accepted, TRAFFIC_ACCEPTED, code)
            rows["trace_peers"] = jnp.where(slot_ok, peerF, -1)
            rows["trace_code"] = code
            rows["trace_first"] = first_src
            rows["trace_vid"] = jnp.where(v_live, v_vid, -1)
            rows["trace_origin"] = jnp.where(v_live, v_origin, -1)
            rows["trace_active"] = jnp.where(is_peer, active, -1)
            rows["trace_pruned"] = pruned_pre
            rows["trace_failed"] = failed
            rows["trace_prunes"] = m_prunes
            if p.has_adaptive:
                # trace schema v4: the per-value direction bit in effect
                # this round + per-node rescue deliveries (hop, -1 none)
                rows["trace_value_pull"] = (v_pull & v_live).astype(
                    jnp.int8)
                rows["trace_pull_hop"] = pull_hop_row
            PC = p.traffic_prune_cap

            def _prune_pairs():
                live_flat = pruned_slot.reshape(V, N * C)
                pk_flat = jnp.where(
                    live_flat,
                    jnp.arange(N * C, dtype=jnp.int32)[None, :], BIG)
                pruner_flat = jnp.broadcast_to(
                    iota_n[None, :, None], (V, N, C)).reshape(V, N * C)
                prunee_flat = src_sorted.reshape(V, N * C)
                pks, tps, tpd = lax.sort(
                    (pk_flat, pruner_flat, prunee_flat),
                    dimension=-1, num_keys=1)
                pair_ok = pks[:, :PC] != BIG
                return (jnp.where(pair_ok, tps[:, :PC], -1),
                        jnp.where(pair_ok, tpd[:, :PC], -1))

            rows["trace_prune_src"], rows["trace_prune_dst"] = lax.cond(
                jnp.sum(m_prunes) > 0, _prune_pairs,
                lambda: (jnp.full((V, PC), -1, jnp.int32),
                         jnp.full((V, PC), -1, jnp.int32)))
    return new_state, rows


# --------------------------------------------------------------------------
# multi-round runners (serial scan + lane-batched scan)
# --------------------------------------------------------------------------

@partial(jax.jit, static_argnums=(0, 5, 6, 7), donate_argnums=(3,))
def _run_traffic(static, tables, ttables, state, knobs, num_iters, detail,
                 trace, start_it):
    def step(st, it):
        return traffic_round_step(static, tables, ttables, st, it,
                                  detail=detail, trace=trace, knobs=knobs)
    its = jnp.arange(num_iters) + start_it
    return lax.scan(step, state, its)


@partial(jax.jit, static_argnums=(0, 5, 6), donate_argnums=(3,))
def _run_traffic_lanes(static, tables, ttables, lane_state, lane_knobs,
                       num_iters, detail, start_it):
    def step(st, it):
        return jax.vmap(
            lambda s, k: traffic_round_step(static, tables, ttables, s, it,
                                            detail=detail, knobs=k)
        )(st, lane_knobs)
    its = jnp.arange(num_iters) + start_it
    return lax.scan(step, lane_state, its)


def traffic_compiled_cache_size() -> int:
    try:
        return int(_run_traffic._cache_size()
                   + _run_traffic_lanes._cache_size())
    except Exception:  # pragma: no cover - jax internals moved
        return -1


def clear_traffic_compile_cache() -> None:
    try:
        _run_traffic.clear_cache()
        _run_traffic_lanes.clear_cache()
    except Exception:  # pragma: no cover
        pass


def run_traffic_rounds(params, tables: ClusterTables,
                       ttables: TrafficTables, state: TrafficState,
                       num_iters: int, start_it=0, detail: bool = False,
                       trace: bool = False,
                       knobs: EngineKnobs | None = None):
    """Run ``num_iters`` traffic rounds under one jitted scan.  Same
    compile-once contract as :func:`engine.core.run_rounds`: only the
    :class:`EngineStatic` key is hashed, every traffic knob is traced, and
    each call records ``engine/compiles`` or ``engine/cache_hits``."""
    static, kn = _split_params(params, knobs)
    args = (static, tables, ttables, state, kn, int(num_iters),
            bool(detail), bool(trace), jnp.asarray(start_it, jnp.int32))
    capacity.harvest_dispatch("engine/run_traffic_rounds", _run_traffic,
                              args)
    before = traffic_compiled_cache_size()
    out = _run_traffic(*args)
    _note_compile_accounting(before, traffic_compiled_cache_size())
    return out


def broadcast_traffic_state(state: TrafficState, lanes: int) -> TrafficState:
    """Tile one TrafficState across ``lanes`` identical lanes (the
    engine/lanes.py ``broadcast_state`` contract: tiling is bit-exact
    because init consumes only static geometry + the seed)."""
    return TrafficState(
        *(jnp.broadcast_to(jnp.asarray(x)[None],
                           (lanes,) + tuple(jnp.shape(x)))
          for x in state))


def traffic_lane_state(states: TrafficState, lane: int) -> TrafficState:
    """One lane's TrafficState view out of a ``[K, ...]`` batch."""
    return TrafficState(*(x[lane] for x in states))


def run_traffic_lanes(static, tables: ClusterTables, ttables: TrafficTables,
                      lane_state: TrafficState, lane_knobs: EngineKnobs,
                      num_iters: int, start_it=0, detail: bool = False):
    """Lane-batched traffic sweep: K stacked knob vectors run as ONE
    batched device program (engine/lanes.py contract: each lane is
    bit-identical to a serial :func:`run_traffic_rounds` call).  Trace
    rows are not offered in lane mode (same restriction as lanes.py)."""
    args = (static, tables, ttables, lane_state, lane_knobs,
            int(num_iters), bool(detail), jnp.asarray(start_it, jnp.int32))
    capacity.harvest_dispatch("engine/run_traffic_lanes",
                              _run_traffic_lanes, args)
    before = traffic_compiled_cache_size()
    out = _run_traffic_lanes(*args)
    _note_compile_accounting(before, traffic_compiled_cache_size())
    return out
