"""Device-resident sweep lanes: the sweep axis as one batched program.

PR 4 made a K-sim knob sweep compile once, but the K points still executed
serially — one engine call per point, with a host round-trip between them,
so the device idled while Python harvested.  Since every sweep knob is a
traced :class:`EngineKnobs` leaf, the whole sweep folds onto the device
instead: stack K knob vectors into a leading **lane** axis (each leaf
``()`` -> ``[K]``), tile the initial :class:`SimState` the same way
(``[O, ...]`` -> ``[K, O, ...]``), and ``jax.vmap`` :func:`round_step`
over that axis inside one jitted ``lax.scan``.  A whole loss x churn x
fanout grid then runs as ONE compiled executable with ONE harvest
transfer — the overlap strategy of "The Algorithm of Pipelined Gossiping"
(PAPERS.md) applied to parameter studies, and the same
batch-many-propagations pattern GASim uses.

Bit-exactness contract (tests/test_sweep_compile.py, tools/lane_smoke.py):
a lane's rows and final state are bit-identical to a serial
:func:`run_rounds` call with the same static key and that lane's knobs.
This holds by construction, not luck:

* every per-round reduction that crosses the node axis is integer
  (histograms, counts, cumsums) or elementwise-float on integer inputs,
  so batching cannot reorder a float accumulation;
* the BFS ``lax.while_loop`` body is a fixed point once a lane's frontier
  empties (all targets key as "no frontier source", so ``newly`` stays
  all-False and ``dist``/``reached`` freeze) — under vmap the loop runs to
  the slowest lane while converged lanes step as no-ops, which is exactly
  the "per-lane early-exit becomes masking" rule a rectangular batched
  scan needs;
* ``lax.cond`` branches (fail event, prune capture, prune-apply budget)
  are pure, so vmap's execute-both-and-select keeps per-lane selections
  exact.

The lane runner has its own jit cache (``_run_lanes``) but records into
the same ``engine/compiles`` / ``engine/cache_hits`` registry counters as
:func:`run_rounds`, so the run-report compile accounting covers lane-mode
sweeps unchanged: one compile for the whole sweep, one cache hit per
further lane batch.

Flight-recorder ``trace`` rows are not offered here: per-lane trace
segments would interleave K sims' event streams in one capture buffer,
and the CLI forbids ``--trace-dir`` in lane mode with a clear error
instead (ISSUE 6).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..obs import capacity
from .core import (SimState, _check_knob_gates, _note_compile_accounting,
                   round_step)
from .params import EngineKnobs, EngineStatic


def stack_knobs(knob_list) -> EngineKnobs:
    """K per-lane :class:`EngineKnobs` -> one pytree of ``[K]`` leaves.

    Each leaf keeps its fixed traced dtype (np.stack of same-dtype scalars
    never promotes), so the stacked pytree presents one abstract value per
    leaf — ``[K]`` of the contract dtype — to the jit cache regardless of
    the concrete knob values."""
    knob_list = list(knob_list)
    if not knob_list:
        raise ValueError("stack_knobs needs at least one lane")
    return EngineKnobs(*(np.stack([getattr(k, f) for k in knob_list])
                         for f in EngineKnobs._fields))


def num_lanes(knobs: EngineKnobs) -> int:
    """Lane count of a stacked knob pytree."""
    return int(np.shape(knobs.impair_seed)[0])


def broadcast_state(state: SimState, lanes: int) -> SimState:
    """Tile one ``[O, ...]`` SimState across ``lanes`` identical lanes.

    Lane-eligible sweeps share init geometry (init_state consumes only
    static fields + the PRNG key), so every lane starts from the same
    state a serial point would — tiling is bit-exact, and the K-1 extra
    ``init_state`` calls of the serial sweep are simply skipped."""
    return SimState(*(jnp.broadcast_to(x[None], (lanes,) + tuple(x.shape))
                      for x in state))


def lane_state(states: SimState, lane: int) -> SimState:
    """One lane's ``[O, ...]`` SimState view out of a ``[K, O, ...]``
    batch (the shape every serial consumer — checkpointing aside —
    expects)."""
    return SimState(*(x[lane] for x in states))


def stack_origins(origin_list) -> jnp.ndarray:
    """K per-lane origin index sequences -> one ``[K, O]`` i32 array.

    The dynamic-membership runner (:func:`run_rounds_lanes_dyn`) vmaps
    the origin axis per lane, so co-resident scenario requests may seed
    different origins; every lane must carry the same origin *count* O
    (the compile geometry)."""
    origin_list = [np.asarray(o, np.int32).reshape(-1) for o in origin_list]
    if not origin_list:
        raise ValueError("stack_origins needs at least one lane")
    widths = {o.shape[0] for o in origin_list}
    if len(widths) != 1:
        raise ValueError(f"all lanes must carry the same origin count "
                         f"(got widths {sorted(widths)})")
    return jnp.asarray(np.stack(origin_list))


def splice_lane_state(states: SimState, lane: int, state: SimState) -> SimState:
    """Admit one ``[O, ...]`` SimState into lane ``lane`` of a
    ``[K, O, ...]`` batch, leaving every other lane's buffers untouched.

    This is the admission half of dynamic lane membership: a retired
    lane's slot is overwritten with a fresh request's state while the
    surviving lanes keep their exact bits (tests/test_serve.py proves
    the no-op property for survivors)."""
    lane = int(lane)
    return SimState(*(b.at[lane].set(x) for b, x in zip(states, state)))


def check_lane_knobs(static: EngineStatic, knob_list) -> None:
    """Per-lane gate guard: every lane's knob vector must be servable by
    the (unioned) static compile key — an active knob against a False
    gate would silently simulate wrong physics (core._check_knob_gates)."""
    for kn in knob_list:
        _check_knob_gates(static, kn)


@partial(jax.jit, static_argnums=(0, 5, 6), donate_argnums=(3,))
def _run_lanes(static, tables, origins, states, knobs, num_iters, detail,
               start_it):
    def step(st, it):
        def one(s, k):
            return round_step(static, tables, origins, s, it, detail=detail,
                              knobs=k)
        return jax.vmap(one)(st, knobs)
    its = jnp.arange(num_iters) + start_it
    return lax.scan(step, states, its)


def lane_cache_size() -> int:
    """Executables in the lane runner's jit cache (-1 if the running JAX
    exposes no introspection) — the lane-mode arm of the recompile-count
    regression guards."""
    try:
        return int(_run_lanes._cache_size())
    except Exception:  # pragma: no cover - older/newer jax internals
        return -1


def clear_lane_cache() -> None:
    """Drop every compiled lane executable (forces a fresh compile on the
    next call)."""
    try:
        _run_lanes.clear_cache()
    except Exception:  # pragma: no cover
        pass


@partial(jax.jit, static_argnums=(0, 5, 6), donate_argnums=(3,))
def _run_lanes_dyn(static, tables, origins, states, knobs, num_iters,
                   detail, start_its):
    # Dynamic-membership variant: ``origins`` is [K, O] (each lane seeds
    # its own origin set) and ``start_its`` is [K] (each lane is at its
    # own round offset).  ``r + s0`` reproduces _run_lanes's
    # ``arange + start_it`` i64 arithmetic per lane, so a lane admitted
    # at wall-block b with offset s0 hashes the exact same per-round
    # impairment counters a solo run of that scenario would.
    def step(st, r):
        def one(s, k, o, s0):
            return round_step(static, tables, o, s, r + s0, detail=detail,
                              knobs=k)
        return jax.vmap(one)(st, knobs, origins, start_its)
    return lax.scan(step, states, jnp.arange(num_iters))


def dyn_lane_cache_size() -> int:
    """Executables in the dynamic-membership runner's jit cache (-1 when
    the running JAX exposes no introspection)."""
    try:
        return int(_run_lanes_dyn._cache_size())
    except Exception:  # pragma: no cover - older/newer jax internals
        return -1


def clear_dyn_lane_cache() -> None:
    """Drop every compiled dynamic-lane executable."""
    try:
        _run_lanes_dyn.clear_cache()
    except Exception:  # pragma: no cover
        pass


def run_rounds_lanes_dyn(static: EngineStatic, tables, origins,
                         states: SimState, knobs: EngineKnobs,
                         num_iters: int, start_its, detail: bool = False):
    """One block of K dynamically-membered lanes as one jitted scan.

    The serve daemon's execution primitive (ISSUE 20): ``origins`` is a
    ``[K, O]`` i32 array (:func:`stack_origins`) and ``start_its`` a
    ``[K]`` i32 vector — each lane runs rounds ``start_its[k] ..
    start_its[k] + num_iters`` of its own scenario, so freshly admitted
    requests (offset 0) ride the same dispatch as lanes deep into their
    run.  Idle lanes simply keep stepping their last state; their rows
    are discarded host-side (masking is scheduling, not arithmetic), so
    an evicted lane is a bit-exact no-op for survivors.  Shapes are
    fixed by (K, O, num_iters): steady-state admissions re-enter one
    warm executable with zero recompiles.  Compile accounting lands on
    the same ``engine/compiles`` / ``engine/cache_hits`` counters as
    every other runner."""
    args = (static, tables, jnp.asarray(origins, jnp.int32), states, knobs,
            int(num_iters), bool(detail),
            jnp.asarray(start_its, jnp.int32))
    capacity.harvest_dispatch("engine/run_rounds_lanes_dyn", _run_lanes_dyn,
                              args)
    before = dyn_lane_cache_size()
    out = _run_lanes_dyn(*args)
    _note_compile_accounting(before, dyn_lane_cache_size())
    return out


def run_rounds_lanes(static: EngineStatic, tables, origins, states: SimState,
                     knobs: EngineKnobs, num_iters: int, start_it=0,
                     detail: bool = False):
    """Run ``num_iters`` rounds of K lanes as one jitted scan.

    ``states`` carries a leading lane axis (:func:`broadcast_state`);
    ``knobs`` is a stacked pytree of ``[K]`` leaves (:func:`stack_knobs`).
    Returns ``(states, rows)`` where every rows leaf has shape
    ``[num_iters, K, ...]`` — slice a lane with
    :func:`gossip_sim_tpu.stats.aggregate.lane_rows` to feed the serial
    per-sim stats paths unchanged.  Records ``engine/compiles`` /
    ``engine/cache_hits`` on the shared span registry exactly like
    :func:`run_rounds`."""
    args = (static, tables, origins, states, knobs, int(num_iters),
            bool(detail), jnp.asarray(start_it, jnp.int32))
    capacity.harvest_dispatch("engine/run_rounds_lanes", _run_lanes, args)
    before = lane_cache_size()
    out = _run_lanes(*args)
    _note_compile_accounting(before, lane_cache_size())
    return out
