"""Device-resident sweep lanes: the sweep axis as one batched program.

PR 4 made a K-sim knob sweep compile once, but the K points still executed
serially — one engine call per point, with a host round-trip between them,
so the device idled while Python harvested.  Since every sweep knob is a
traced :class:`EngineKnobs` leaf, the whole sweep folds onto the device
instead: stack K knob vectors into a leading **lane** axis (each leaf
``()`` -> ``[K]``), tile the initial :class:`SimState` the same way
(``[O, ...]`` -> ``[K, O, ...]``), and ``jax.vmap`` :func:`round_step`
over that axis inside one jitted ``lax.scan``.  A whole loss x churn x
fanout grid then runs as ONE compiled executable with ONE harvest
transfer — the overlap strategy of "The Algorithm of Pipelined Gossiping"
(PAPERS.md) applied to parameter studies, and the same
batch-many-propagations pattern GASim uses.

Bit-exactness contract (tests/test_sweep_compile.py, tools/lane_smoke.py):
a lane's rows and final state are bit-identical to a serial
:func:`run_rounds` call with the same static key and that lane's knobs.
This holds by construction, not luck:

* every per-round reduction that crosses the node axis is integer
  (histograms, counts, cumsums) or elementwise-float on integer inputs,
  so batching cannot reorder a float accumulation;
* the BFS ``lax.while_loop`` body is a fixed point once a lane's frontier
  empties (all targets key as "no frontier source", so ``newly`` stays
  all-False and ``dist``/``reached`` freeze) — under vmap the loop runs to
  the slowest lane while converged lanes step as no-ops, which is exactly
  the "per-lane early-exit becomes masking" rule a rectangular batched
  scan needs;
* ``lax.cond`` branches (fail event, prune capture, prune-apply budget)
  are pure, so vmap's execute-both-and-select keeps per-lane selections
  exact.

The lane runner has its own jit cache (``_run_lanes``) but records into
the same ``engine/compiles`` / ``engine/cache_hits`` registry counters as
:func:`run_rounds`, so the run-report compile accounting covers lane-mode
sweeps unchanged: one compile for the whole sweep, one cache hit per
further lane batch.

Flight-recorder ``trace`` rows are not offered here: per-lane trace
segments would interleave K sims' event streams in one capture buffer,
and the CLI forbids ``--trace-dir`` in lane mode with a clear error
instead (ISSUE 6).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..obs import capacity
from .core import (SimState, _check_knob_gates, _note_compile_accounting,
                   round_step)
from .params import EngineKnobs, EngineStatic


def stack_knobs(knob_list) -> EngineKnobs:
    """K per-lane :class:`EngineKnobs` -> one pytree of ``[K]`` leaves.

    Each leaf keeps its fixed traced dtype (np.stack of same-dtype scalars
    never promotes), so the stacked pytree presents one abstract value per
    leaf — ``[K]`` of the contract dtype — to the jit cache regardless of
    the concrete knob values."""
    knob_list = list(knob_list)
    if not knob_list:
        raise ValueError("stack_knobs needs at least one lane")
    return EngineKnobs(*(np.stack([getattr(k, f) for k in knob_list])
                         for f in EngineKnobs._fields))


def num_lanes(knobs: EngineKnobs) -> int:
    """Lane count of a stacked knob pytree."""
    return int(np.shape(knobs.impair_seed)[0])


def broadcast_state(state: SimState, lanes: int) -> SimState:
    """Tile one ``[O, ...]`` SimState across ``lanes`` identical lanes.

    Lane-eligible sweeps share init geometry (init_state consumes only
    static fields + the PRNG key), so every lane starts from the same
    state a serial point would — tiling is bit-exact, and the K-1 extra
    ``init_state`` calls of the serial sweep are simply skipped."""
    return SimState(*(jnp.broadcast_to(x[None], (lanes,) + tuple(x.shape))
                      for x in state))


def lane_state(states: SimState, lane: int) -> SimState:
    """One lane's ``[O, ...]`` SimState view out of a ``[K, O, ...]``
    batch (the shape every serial consumer — checkpointing aside —
    expects)."""
    return SimState(*(x[lane] for x in states))


def check_lane_knobs(static: EngineStatic, knob_list) -> None:
    """Per-lane gate guard: every lane's knob vector must be servable by
    the (unioned) static compile key — an active knob against a False
    gate would silently simulate wrong physics (core._check_knob_gates)."""
    for kn in knob_list:
        _check_knob_gates(static, kn)


@partial(jax.jit, static_argnums=(0, 5, 6), donate_argnums=(3,))
def _run_lanes(static, tables, origins, states, knobs, num_iters, detail,
               start_it):
    def step(st, it):
        def one(s, k):
            return round_step(static, tables, origins, s, it, detail=detail,
                              knobs=k)
        return jax.vmap(one)(st, knobs)
    its = jnp.arange(num_iters) + start_it
    return lax.scan(step, states, its)


def lane_cache_size() -> int:
    """Executables in the lane runner's jit cache (-1 if the running JAX
    exposes no introspection) — the lane-mode arm of the recompile-count
    regression guards."""
    try:
        return int(_run_lanes._cache_size())
    except Exception:  # pragma: no cover - older/newer jax internals
        return -1


def clear_lane_cache() -> None:
    """Drop every compiled lane executable (forces a fresh compile on the
    next call)."""
    try:
        _run_lanes.clear_cache()
    except Exception:  # pragma: no cover
        pass


def run_rounds_lanes(static: EngineStatic, tables, origins, states: SimState,
                     knobs: EngineKnobs, num_iters: int, start_it=0,
                     detail: bool = False):
    """Run ``num_iters`` rounds of K lanes as one jitted scan.

    ``states`` carries a leading lane axis (:func:`broadcast_state`);
    ``knobs`` is a stacked pytree of ``[K]`` leaves (:func:`stack_knobs`).
    Returns ``(states, rows)`` where every rows leaf has shape
    ``[num_iters, K, ...]`` — slice a lane with
    :func:`gossip_sim_tpu.stats.aggregate.lane_rows` to feed the serial
    per-sim stats paths unchanged.  Records ``engine/compiles`` /
    ``engine/cache_hits`` on the shared span registry exactly like
    :func:`run_rounds`."""
    args = (static, tables, origins, states, knobs, int(num_iters),
            bool(detail), jnp.asarray(start_it, jnp.int32))
    capacity.harvest_dispatch("engine/run_rounds_lanes", _run_lanes, args)
    before = lane_cache_size()
    out = _run_lanes(*args)
    _note_compile_accounting(before, lane_cache_size())
    return out
