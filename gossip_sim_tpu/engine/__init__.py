"""TPU engine: the five-verb gossip round as jitted dense-array kernels.

This is the TPU-native backend.  State lives in dense arrays indexed by node
id ``i in [0, N)`` (pubkeys exist only at the I/O edge, see ``identity``);
one ``SimState`` batches ``O`` independent single-origin simulations (the
reference runs one origin per simulation, gossip_main.rs:292-647 — the origin
axis is therefore embarrassingly parallel and is this framework's main
scaling axis, vmapped on one chip and sharded over the device mesh).

64-bit types are enabled here because lamport stakes exceed 2**53 and the
prune stake-threshold arithmetic (received_cache.rs:112-115) must match the
reference's u64/f64 semantics.  Import this package before running other JAX
code so the flag takes effect globally.
"""

import jax

jax.config.update("jax_enable_x64", True)

from .params import (  # noqa: E402
    EngineKnobs,
    EngineParams,
    EngineStatic,
    merge_lane_statics,
)
from .sampler import SamplerTables, build_sampler_tables  # noqa: E402
from .cache import (  # noqa: E402
    enable_persistent_cache,
    persistent_cache_counters,
    persistent_cache_dir,
)
from .core import (  # noqa: E402
    ClusterTables,
    SimState,
    clear_compile_cache,
    compiled_cache_size,
    init_state,
    make_cluster_tables,
    round_step,
    run_rounds,
)
from .lanes import (  # noqa: E402
    broadcast_state,
    check_lane_knobs,
    clear_dyn_lane_cache,
    clear_lane_cache,
    dyn_lane_cache_size,
    lane_cache_size,
    lane_state,
    num_lanes,
    run_rounds_lanes,
    run_rounds_lanes_dyn,
    splice_lane_state,
    stack_knobs,
    stack_origins,
)

__all__ = [
    "EngineKnobs",
    "EngineParams",
    "EngineStatic",
    "merge_lane_statics",
    "broadcast_state",
    "check_lane_knobs",
    "clear_lane_cache",
    "lane_cache_size",
    "lane_state",
    "num_lanes",
    "run_rounds_lanes",
    "run_rounds_lanes_dyn",
    "splice_lane_state",
    "stack_knobs",
    "stack_origins",
    "clear_dyn_lane_cache",
    "dyn_lane_cache_size",
    "SamplerTables",
    "build_sampler_tables",
    "ClusterTables",
    "SimState",
    "clear_compile_cache",
    "compiled_cache_size",
    "enable_persistent_cache",
    "persistent_cache_counters",
    "persistent_cache_dir",
    "init_state",
    "make_cluster_tables",
    "round_step",
    "run_rounds",
]
