"""Sparse frontier kernels for the gossip round (representation="sparse").

The dense engine (core.py) expresses every cross-node data movement as a
full-width sort: BFS relaxation is two ``[O, N*F + N]`` sorts per hop, the
inbound ranking is a pair of 4-wide sorts over ``N*F + N + N*K`` elements,
and the received cache carries four ``[O, N, C]`` planes.  That shape is
what the capacity observatory (obs/capacity.py) measured as the 16 GB
all-origins wall at N ≈ 3.9k — the ``rc_*`` planes dominate the ledger and
the sort workspaces dominate the XLA temp bytes.

The sparse representation (selected by the static
``EngineStatic.representation`` compile key) reroutes the round over the
bounded candidate edge list — at most ``N * push_fanout`` live edges per
origin — using segment reductions and deterministic scatters:

* **BFS propagation** (:func:`bfs_reach`): per hop, each candidate edge
  carries its source's frontier bit to its target through ONE
  ``segment_max`` over the edge list (segment id = target, per origin).
  Cost tracks live edges, not the ``N + N*F`` sort width, and no payload
  planes ride along.
* **Inbound ranking** (:func:`rank_inbound`): ingress counts are a single
  ``segment_sum`` over delivered edges; the top-K inbound compaction keeps
  the reference (hop, src)-rank sort but drops both stake payload planes
  and replaces the dense slot-alignment double sort with one deterministic
  scatter (unique (target, rank) indices).
* **Received cache**: the ``rc_shi``/``rc_slo`` stake planes are never
  carried — ``SimState`` holds them as zero-width ``[O, N, 0]`` arrays and
  verb 3 derives them as ``tables.shi[rc_src]`` / ``tables.slo[rc_src]``.
  This is exact, not approximate: every dense insert copies the table
  stake for its source and the index-N pad is 0 (matching empty slots), so
  the carried planes always equal the gather.  Two of the four ``[O, N, C]``
  planes vanish from the ledger — the received-cache bytes halve.
* **Table joins**: the ``_lookup`` sort-joins (tfail rebuild, rotation
  candidate translation) become direct row gathers — on the sparse path
  gathers beat sorting the whole table width through every query.

Everything else (verb 1 slot selection, the rc merge scan, prune
decide/apply, rotation, stats) is shared with the dense round in
``core.round_step`` — the sparse arms are selected per site, so the two
representations produce bit-identical states and rows by construction,
and ``representation="dense"`` compiles a graph with no sparse code in it
(the gate ``tools/sparse_smoke.py`` enforces both directions).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

# BIG and the rank helper are shared with the dense kernels; resolved
# lazily because core imports this module inside round_step.

def _big():
    from .core import BIG
    return BIG


def _rank_in_run(run_of):
    from .core import _rank_in_run as rir
    return rir(run_of)


def bfs_reach(tgt: jax.Array, frontier1: jax.Array, reached1: jax.Array,
              dist0: jax.Array, n: int):
    """Frontier relaxation over the candidate edge list via segment_max.

    ``tgt``: [O, N, F] i32 candidate delivery targets (n = no delivery);
    ``frontier1``/``reached1``/``dist0``: the hop-1 seed (origin's own
    targets), exactly as the dense BFS builds it.  Returns
    ``(reached, dist)`` — bit-identical to the dense two-sort relaxation:
    per hop every edge whose source sits on the frontier raises its
    target's "any inbound" bit; empty segments come back at the i32
    minimum, which never passes the ``> 0`` test.
    """
    O, N, F = tgt.shape
    assert N == n
    seg = (jnp.where(tgt < n, tgt, n)
           + (jnp.arange(O, dtype=jnp.int32) * (n + 1))[:, None, None])
    seg_flat = seg.reshape(-1)

    def body(carry):
        frontier, reached, dist, h = carry
        val = jnp.broadcast_to(frontier[:, :, None],
                               tgt.shape).astype(jnp.int32).reshape(-1)
        got = jax.ops.segment_max(val, seg_flat,
                                  num_segments=O * (n + 1))
        newly = (got.reshape(O, n + 1)[:, :n] > 0) & ~reached
        dist = jnp.where(newly, h + 1, dist)
        return (newly, reached | newly, dist, h + 1)

    _, reached, dist, _ = lax.while_loop(
        lambda c: jnp.any(c[0]), body,
        (frontier1, reached1, dist0, jnp.int32(1)))
    return reached, dist


def rank_inbound(delivered: jax.Array, tgt: jax.Array, hop1: jax.Array,
                 pb: int, pack: int, k: int, n: int):
    """Top-K inbound compaction + ingress counts over delivered edges.

    ``delivered``: [O, N, F] bool delivered-edge mask; ``tgt`` the targets;
    ``hop1`` [O, N] the per-source delivery hop.  Returns
    ``(inb, ingress_round, inb_dropped)`` with ``inb``: [O, N, K] i32
    inbound source per rank (n = empty), bit-identical to the dense
    double-sort compaction:

    * ranks come from the same (target, hop << pb | src) sort — index
      order equals the reference's pubkey sort by NodeIndex construction
      (gossip.rs:638-645) — but with no stake payload planes riding along;
    * the [O, N, K] slot alignment is ONE deterministic scatter (each kept
      edge owns the unique slot ``target*K + rank``) instead of the dense
      two-sort round trip over ``N*F + N*K`` elements;
    * ingress counts are a ``segment_sum`` over delivered edges, and the
      truncation count is ``sum(max(ingress - K, 0))`` — the same value
      the dense rank >= K census produces.
    """
    BIG = _big()
    O, N, F = tgt.shape
    NF = N * F
    iota_n = jnp.arange(N, dtype=jnp.int32)[None, :]

    # ingress via one segment_sum over the delivered edge list
    seg = (jnp.where(delivered, tgt, n)
           + (jnp.arange(O, dtype=jnp.int32) * (n + 1))[:, None, None])
    ingress_round = jax.ops.segment_sum(
        delivered.astype(jnp.int32).reshape(-1), seg.reshape(-1),
        num_segments=O * (n + 1)).reshape(O, n + 1)[:, :n]
    inb_dropped = jnp.sum(jnp.maximum(ingress_round - k, 0), axis=-1,
                          dtype=jnp.int32)

    # rank by (target, hop << pb | src); undelivered edges key at target n
    # and sort to the tail of the row, outside every real run
    kv = ((hop1[:, :, None] << pb) | iota_n[:, :, None]).astype(jnp.int32)
    kv = jnp.broadcast_to(kv, (O, N, F)).reshape(O, NF)
    kd = jnp.where(delivered, tgt, n).reshape(O, NF)
    st_, skv = lax.sort((kd, kv), dimension=-1, num_keys=2)
    rank = _rank_in_run(st_)
    keep = (st_ < n) & (rank < k)

    # deterministic scatter: kept edges own unique slots target*K + rank;
    # everything else aims one past the buffer and mode="drop" discards it
    rows = jnp.broadcast_to(jnp.arange(O, dtype=jnp.int32)[:, None],
                            (O, NF))
    idx = jnp.where(keep, st_ * k + rank, n * k)
    buf = jnp.full((O, n * k), BIG, jnp.int32)
    buf = buf.at[rows, idx].set(skv, mode="drop")
    inb = jnp.where(buf != BIG, buf & (pack - 1), n).reshape(O, n, k)
    return inb, ingress_round, inb_dropped
