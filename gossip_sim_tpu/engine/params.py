"""Engine parameters: static compile geometry vs dynamic (traced) knobs.

Mirrors the reference's flat ``Config`` (gossip.rs:111-133) plus the dense
shapes the TPU formulation introduces.  ``EngineParams`` stays the single
user-facing NamedTuple (the CLI, checkpoints and tests construct it as
before), but the jit boundary splits it in two:

* ``EngineStatic`` — shape/structure fields (array extents, ranking widths,
  iteration-loop structure) plus the *coarse* graph-selection booleans
  (``has_loss``/``has_churn``/``has_partition``/``has_fail``).  This tuple
  is the only hashable compile key: a new value compiles a new executable.
* ``EngineKnobs`` — every numeric tuning knob, carried as a pytree of
  fixed-dtype numpy scalars that flow into ``round_step``/``_run`` as
  *traced* device scalars.  Stepping any knob across a K-sim sweep
  (gossip_main.rs:774-951) therefore reuses one compiled executable K
  times: sweep cost is ``compile + K*run`` instead of ``K*(compile+run)``.

The knob dtypes are part of the bit-exactness contract with both the CPU
oracle and the pre-split engine (which baked the knobs in as weakly-typed
Python constants):

* ``probability_of_rotation`` is f32 — it is compared against f32 uniforms,
  and a weak f64 literal in that comparison was cast to f32 anyway;
* the stake-threshold / impairment rates are f64 — the oracle evaluates
  them in host double precision (``int(rate * 2**32)``,
  received_cache.rs:112-115) and the engine must match bit-for-bit;
* iteration boundaries are i32 (the traced iteration counter's dtype) and
  ``impair_seed`` is u32 (the counter-hash lane width, faults.py).
"""

from __future__ import annotations

from typing import NamedTuple

import numpy as np

from ..constants import (MIN_NUM_UPSERTS, NUM_PUSH_ACTIVE_SET_ENTRIES,
                         RECEIVED_CACHE_CAPACITY)


def _resolve_prune_cap(trace_prune_cap: int, num_nodes: int,
                       rc_slots: int) -> int:
    """Flight-recorder prune-pair capture width (0 = auto 16*N, never more
    than the theoretical N*rc_slots maximum) — the single source both
    EngineParams and EngineStatic resolve through."""
    cap = trace_prune_cap or 16 * num_nodes
    return min(cap, num_nodes * rc_slots)


def _resolve_k_inbound(inbound_cap: int, push_fanout: int) -> int:
    """Inbound ranking width (0 = auto-size from the fanout)."""
    if inbound_cap > 0:
        return inbound_cap
    return max(16, 2 * push_fanout)


def _resolve_pull_slots(pull_slots: int, pull_fanout: int) -> int:
    """Physical pull-request slots per node (0 = auto: max(8, fanout)).

    The slot count is the *static* array width; the traced ``pull_fanout``
    knob masks slots beyond itself, so a PULL_FANOUT sweep within the
    resolved width reuses one compiled executable (sweeping past it flips
    the static shape and recompiles once — same contract as push_fanout
    vs k_inbound)."""
    if pull_slots > 0:
        return pull_slots
    return max(8, pull_fanout)


class EngineKnobs(NamedTuple):
    """Dynamic numeric knobs, traced into the compiled round.

    Each leaf is a fixed-dtype numpy scalar so every sweep step presents
    the identical abstract value (shape ``()``, same dtype) to the jit
    cache — the executable compiled for step 1 serves steps 2..K.
    Construct via :meth:`EngineParams.split`.
    """

    probability_of_rotation: np.float32   # gossip_main.rs:124
    prune_stake_threshold: np.float64     # received_cache.rs:112-115 (f64)
    min_ingress_nodes: np.int32           # gossip_main.rs:135
    warm_up_rounds: np.int32              # measured-round boundary
    fail_at: np.int32                     # --when-to-fail; -1 = never
    fail_fraction: np.float64             # --fraction-to-fail (host double)
    packet_loss_rate: np.float64          # faults.py Bernoulli rates: the
    churn_fail_rate: np.float64           # u32 thresholds derive from f64
    churn_recover_rate: np.float64        # products exactly like the oracle
    partition_at: np.int32                # bipartition window start
    heal_at: np.int32                     # bipartition window end (-1 never)
    impair_seed: np.uint32                # counter-hash seed (faults.py)
    # pull-gossip knobs (pull.py); the pull phase itself is gated on the
    # static ``gossip_mode`` — these only shape it, so a PULL sweep
    # (fanout/interval/bloom-fp/cap) reuses one compiled executable
    pull_fanout: np.int32                 # pull requests per node per round
    pull_interval: np.int32               # rounds between pull exchanges
    pull_bloom_fp_rate: np.float64        # bloom false-positive probability
    pull_request_cap: np.int32            # served requests per peer (<=0 off)
    # adaptive push-pull knobs (gossip_mode="adaptive"); the direction
    # switch is compiled in under the static mode, these only position it —
    # an ADAPTIVE_THRESHOLD sweep reuses one compiled executable
    adaptive_switch_threshold: np.float64  # coverage fraction flipping a
                                           # sim/value into its pull phase
    adaptive_switch_hysteresis: np.float64  # window below the threshold
                                            # before flipping back to push
    # concurrent-traffic knobs (traffic.py); the traffic engine itself is
    # gated on the static ``traffic_slots`` — these only shape it, so a
    # traffic-rate or queue-cap sweep reuses one compiled executable
    traffic_rate: np.int32                # values injected per round
    node_ingress_cap: np.int32            # msgs accepted/node/round (<=0 off)
    node_egress_cap: np.int32             # msgs sent/node/round (<=0 off)
    traffic_stall_rounds: np.int32        # no-progress rounds before retire


class EngineStatic(NamedTuple):
    """Static compile geometry: array shapes, ranking widths, and the
    coarse booleans selecting which impairment blocks exist in the graph.
    Hashable — this tuple (plus array shapes/dtypes) IS the jit cache key;
    changing any field compiles a new executable."""

    num_nodes: int
    push_fanout: int
    active_set_size: int
    min_num_upserts: int
    received_cap: int
    rc_slots: int
    inbound_cap: int
    hist_bins: int
    rot_tries: int
    init_draws: int
    pa_slots: int
    trace_prune_cap: int
    # Coarse graph-selection gates.  With all four False the compiled round
    # is the exact unimpaired reference graph; a knob crossing its on/off
    # boundary (e.g. packet_loss_rate 0 -> 0.1) flips a gate and recompiles
    # once, after which any further numeric stepping is compile-free.
    has_fail: bool = False
    has_loss: bool = False
    has_churn: bool = False
    has_partition: bool = False
    # Gossip mode selects which protocol phases exist in the compiled graph
    # (pull.py): "push" is the reference graph (bit-identical to the
    # pre-pull engine), "pull" disables the push phase, "push-pull" runs
    # both, "adaptive" compiles both phases plus the direction-optimizing
    # switch (push while coverage is low, pull-phase activation once it
    # crosses the traced threshold).  ``pull_slots`` is the RESOLVED static
    # pull-request width (0 when the mode has no pull phase).
    gossip_mode: str = "push"
    pull_slots: int = 0
    # Concurrent-traffic geometry (traffic.py / engine/traffic.py):
    # ``traffic_slots`` is the static M-value slot capacity (the state's
    # value axis).  0 = the traffic subsystem is OFF and no traffic code
    # exists in any compiled graph — the M=1/caps-off bit-identity gate.
    traffic_slots: int = 0
    # Node-health observatory gate (obs/health.py): True compiles the
    # per-node health-plane accumulation (prune-received counts,
    # first-delivery rounds/latencies) into the round.  False (default)
    # leaves the health planes untouched zeros and the compiled graph
    # free of any health code — the same bit-identity contract as the
    # trace/traffic gates (parity snapshots and deterministic Influx
    # wire lines are byte-identical with the gate off).
    health: bool = False
    # Round-representation selector (engine/sparse.py): "dense" compiles
    # the reference full-width [O,N]-plane sort graph, bit-identical to a
    # build without the key.  "sparse" compiles the frontier/edge-list
    # round: segment-sum routing over the O(N*fanout) candidate edges,
    # scatter compaction into the inbound ranking, and the rc_shi/rc_slo
    # received-cache planes derived from ClusterTables instead of carried
    # (state keeps them as zero-width [O,N,0] arrays).  Static gate —
    # each value is its own executable; the outputs are bit-exact.
    representation: str = "dense"

    @property
    def num_buckets(self) -> int:
        return NUM_PUSH_ACTIVE_SET_ENTRIES

    @property
    def has_impairments(self) -> bool:
        return self.has_loss or self.has_churn or self.has_partition

    @property
    def has_traffic(self) -> bool:
        return self.traffic_slots > 0

    @property
    def has_pull(self) -> bool:
        return self.gossip_mode != "push"

    @property
    def has_push(self) -> bool:
        return self.gossip_mode != "pull"

    @property
    def has_adaptive(self) -> bool:
        return self.gossip_mode == "adaptive"

    @property
    def prune_cap(self) -> int:
        return _resolve_prune_cap(self.trace_prune_cap, self.num_nodes,
                                  self.rc_slots)

    @property
    def traffic_prune_cap(self) -> int:
        """Flight-recorder prune-pair capture width per (value, round) in
        traffic mode: the single-origin cap bounded to 4*N — the capture
        buffer carries a whole value axis, and per-value prune bursts are
        far smaller than the all-prunes-for-one-origin bursts the 16*N
        default was sized for.  Truncation is counted, never silent."""
        return min(self.prune_cap, 4 * self.num_nodes)

    @property
    def k_inbound(self) -> int:
        return _resolve_k_inbound(self.inbound_cap, self.push_fanout)


def merge_lane_statics(statics) -> EngineStatic:
    """The union compile key for a set of sweep lanes (engine/lanes.py).

    A lane-batched sweep runs K knob vectors through ONE compiled
    executable, so every lane must share one ``EngineStatic``.  Two kinds
    of per-lane drift are reconcilable without changing any lane's bits:

    * the coarse impairment gates (``has_fail``/``has_loss``/``has_churn``/
      ``has_partition``) OR together — a gated block evaluated at its off
      knob endpoint reduces exactly to the unimpaired graph (the PR-4
      contract ``_check_knob_gates`` encodes), so e.g. a packet-loss sweep
      starting at rate 0 runs its 0 lane through the loss-gated graph
      bit-identically;
    * ``pull_slots`` takes the max — slots beyond a lane's traced
      ``pull_fanout`` are masked per slot, and the per-slot hash draws
      depend only on (node, slot), so widening never perturbs a lane.

    Any other field differing between lanes is a genuine shape/structure
    divergence (one executable cannot serve both) and raises ``ValueError``
    naming the fields, so callers fall back to the serial sweep loudly.
    """
    statics = list(statics)
    if not statics:
        raise ValueError("merge_lane_statics needs at least one lane")
    merged = statics[0]._replace(
        has_fail=any(s.has_fail for s in statics),
        has_loss=any(s.has_loss for s in statics),
        has_churn=any(s.has_churn for s in statics),
        has_partition=any(s.has_partition for s in statics),
        pull_slots=max(s.pull_slots for s in statics),
    )
    for s in statics:
        norm = s._replace(has_fail=merged.has_fail, has_loss=merged.has_loss,
                          has_churn=merged.has_churn,
                          has_partition=merged.has_partition,
                          pull_slots=merged.pull_slots)
        if norm != merged:
            diff = sorted(f for f in EngineStatic._fields
                          if getattr(norm, f) != getattr(merged, f))
            raise ValueError(
                f"sweep lanes disagree on static compile-key field(s) "
                f"{diff}; only traced-knob sweeps can share one lane-batched "
                f"executable")
    return merged


class EngineParams(NamedTuple):
    """The full user-facing parameter set (static + dynamic, concrete)."""

    num_nodes: int
    push_fanout: int = 6                 # gossip_main.rs:90
    active_set_size: int = 12            # gossip_main.rs:97
    probability_of_rotation: float = 0.013333  # gossip_main.rs:124 (1/75)
    prune_stake_threshold: float = 0.15  # gossip_main.rs:142
    min_ingress_nodes: int = 2           # gossip_main.rs:135
    warm_up_rounds: int = 200            # gossip_main.rs:223
    fail_at: int = -1                    # --when-to-fail; -1 = never
    fail_fraction: float = 0.0           # --fraction-to-fail

    min_num_upserts: int = MIN_NUM_UPSERTS          # received_cache.rs:21
    received_cap: int = RECEIVED_CACHE_CAPACITY     # received_cache.rs:78

    # Network-impairment / fault-injection knobs (faults.py; no reference
    # equivalent beyond the one-shot fail_at above).  All decisions are
    # stateless counter hashes of (impair_seed, iteration, node ids), shared
    # bit-exactly with the oracle's FaultInjector.  With every knob at its
    # default the compiled round is IDENTICAL to the unimpaired engine
    # (the blocks are gated on the EngineStatic booleans derived here).
    packet_loss_rate: float = 0.0    # per-message Bernoulli drop probability
    churn_fail_rate: float = 0.0     # per-iteration P(alive node fails)
    churn_recover_rate: float = 0.0  # per-iteration P(failed node recovers)
    partition_at: int = -1           # iteration the stake bipartition starts
    heal_at: int = -1                # iteration it heals (-1 = never)
    impair_seed: int = 0             # hash seed for all impairment streams

    # Pull-gossip (anti-entropy) knobs (pull.py).  ``gossip_mode`` is the
    # static phase selector: "push" (default) compiles the exact reference
    # graph, "pull" disables the push phase, "push-pull" runs both.  The
    # numeric knobs are traced (EngineKnobs), so sweeping any of them
    # reuses one compiled executable; every pull decision is a stateless
    # counter hash of (impair_seed, iteration, node ids) shared bit-exactly
    # with the oracle's PullOracle.
    gossip_mode: str = "push"
    pull_fanout: int = 2             # pull requests per live node per round
    pull_interval: int = 1           # rounds between pull exchanges
    pull_bloom_fp_rate: float = 0.1  # bloom FP probability (Solana's 0.1)
    pull_request_cap: int = 0        # requests served per peer per round
                                     # (<= 0 = unlimited)
    pull_slots: int = 0              # physical pull-request slots per node
                                     # (static shape; 0 = auto:
                                     # max(8, pull_fanout) so fanout sweeps
                                     # within 8 compile once)

    # Adaptive push-pull (gossip_mode="adaptive"): direction-optimizing
    # gossip per "Implementing Push-Pull Efficiently in GraphBLAS" — push
    # while the infected set is small, activate the pull phase once
    # coverage crosses the switch threshold (and push RMR explodes).  Both
    # knobs are traced (EngineKnobs): threshold sweeps compile once.  The
    # decision compares integer coverage counts against ``threshold * N``
    # in f64, identically in both backends (bit-exact by construction).
    adaptive_switch_threshold: float = 0.9   # coverage fraction that flips
                                             # a sim/value into pull phase
    adaptive_switch_hysteresis: float = 0.05  # flip back to push only when
                                              # coverage < thr - hysteresis

    # Concurrent-traffic knobs (traffic.py).  ``traffic_values`` is the
    # static M-value slot capacity; with the default 1 AND both queue caps
    # off the traffic subsystem is fully gated out and the simulator is
    # bit-identical to the pre-traffic engine.  The numeric knobs are
    # traced (EngineKnobs), so traffic-rate / queue-cap sweeps reuse one
    # compiled executable; every traffic decision is a stateless counter
    # hash shared bit-exactly with the oracle's TrafficOracle.
    traffic_values: int = 1          # concurrent value slots (static M)
    traffic_rate: int = 1            # new values injected per round
    node_ingress_cap: int = 0        # msgs accepted per node per round
                                     # across ALL values (<= 0 = no cap)
    node_egress_cap: int = 0         # msgs sent per node per round across
                                     # ALL values (<= 0 = no cap; excess
                                     # candidates defer to the next round)
    traffic_stall_rounds: int = 3    # consecutive no-progress rounds
                                     # before a value retires un-converged

    # Dense-shape knobs (TPU formulation only; see engine/core.py for the
    # documented divergences they introduce):
    rc_slots: int = 64      # physical received-cache slots per (origin, node)
    inbound_cap: int = 0    # inbound peers ranked per (origin, dest, round);
                            # 0 = auto: max(16, 2*push_fanout) so fanout
                            # sweeps can't silently truncate scoring
    hist_bins: int = 64     # on-device hop-histogram bins
    rot_tries: int = 8      # rejection-sampling tries per rotation event
    init_draws: int = 64    # candidate draws per entry at initialization
    pa_slots: int = 8       # prune-apply fast-path budget (pruned peers per
                            # row per round); overflow falls back to the
                            # full-width sort via lax.cond — exact either way
    trace_prune_cap: int = 0  # flight-recorder (obs/trace.py) prune-pair
                              # slots captured per (origin, round); 0 = auto
                              # (16*num_nodes — the first prune burst is
                              # nearly synchronized across nodes, so the
                              # cap must hold several pairs per node at
                              # once).  Overflow is counted, never silently
                              # dropped — only the trace truncates, the
                              # simulation itself is unaffected.
    health: bool = False    # node-health observatory (obs/health.py):
                            # accumulate the per-node health planes
                            # (prune-received, first-delivery) inside the
                            # jitted round scan.  Static gate — off, the
                            # compiled round carries zero health code and
                            # every output is bit-identical to today.
    representation: str = "dense"  # round representation (engine/sparse.py):
                            # "dense" = the reference full-width sort graph
                            # (bit-identical to a build without the key);
                            # "sparse" = frontier/edge-list segment-sum
                            # routing with the rc_shi/rc_slo planes derived
                            # from ClusterTables instead of carried — same
                            # bits, ~half the received-cache memory.

    @property
    def num_buckets(self) -> int:
        return NUM_PUSH_ACTIVE_SET_ENTRIES

    @property
    def has_impairments(self) -> bool:
        """True when any fault-injection knob beyond the reference's one-shot
        ``fail_at`` is active (selects the impairment-aware compiled round)."""
        return (self.packet_loss_rate > 0.0 or self.has_churn
                or self.partition_at >= 0)

    @property
    def has_churn(self) -> bool:
        return self.churn_fail_rate > 0.0 or self.churn_recover_rate > 0.0

    @property
    def has_traffic(self) -> bool:
        """True when the concurrent-traffic subsystem (traffic.py) is
        engaged: more than one value slot, or a queue cap constraining the
        single-value stream.  False = the compiled graphs carry zero
        traffic code (the M=1/caps-off bit-identity contract)."""
        return (self.traffic_values > 1 or self.node_ingress_cap > 0
                or self.node_egress_cap > 0)

    @property
    def has_pull(self) -> bool:
        """True when the gossip mode includes the pull (anti-entropy)
        phase (pull.py)."""
        return self.gossip_mode != "push"

    @property
    def has_push(self) -> bool:
        return self.gossip_mode != "pull"

    @property
    def pull_slots_resolved(self) -> int:
        """Resolved static pull-request width (``pull_slots``; 0 = auto:
        max(8, pull_fanout))."""
        return _resolve_pull_slots(self.pull_slots, self.pull_fanout)

    @property
    def prune_cap(self) -> int:
        """Resolved flight-recorder prune-pair capture width per round
        (``trace_prune_cap``; 0 = auto: 16*num_nodes, never more than the
        theoretical N*rc_slots maximum)."""
        return _resolve_prune_cap(self.trace_prune_cap, self.num_nodes,
                                  self.rc_slots)

    @property
    def k_inbound(self) -> int:
        """Resolved inbound ranking width (``inbound_cap``; 0 = auto-size
        from the fanout).  Truncation beyond this is counted per round in
        ``rows["inb_dropped"]`` and warned about by the CLI."""
        return _resolve_k_inbound(self.inbound_cap, self.push_fanout)

    def static_part(self) -> EngineStatic:
        """The hashable compile key this parameter set selects."""
        return EngineStatic(
            num_nodes=self.num_nodes,
            push_fanout=self.push_fanout,
            active_set_size=self.active_set_size,
            min_num_upserts=self.min_num_upserts,
            received_cap=self.received_cap,
            rc_slots=self.rc_slots,
            inbound_cap=self.inbound_cap,
            hist_bins=self.hist_bins,
            rot_tries=self.rot_tries,
            init_draws=self.init_draws,
            pa_slots=self.pa_slots,
            trace_prune_cap=self.trace_prune_cap,
            has_fail=self.fail_at >= 0 and self.fail_fraction > 0.0,
            has_loss=self.packet_loss_rate > 0.0,
            has_churn=self.has_churn,
            has_partition=self.partition_at >= 0,
            gossip_mode=self.gossip_mode,
            pull_slots=self.pull_slots_resolved if self.has_pull else 0,
            traffic_slots=self.traffic_values if self.has_traffic else 0,
            health=self.health,
            representation=self.representation,
        )

    def knob_values(self) -> EngineKnobs:
        """The dynamic knobs, canonicalized to their traced dtypes."""
        return EngineKnobs(
            probability_of_rotation=np.float32(self.probability_of_rotation),
            prune_stake_threshold=np.float64(self.prune_stake_threshold),
            min_ingress_nodes=np.int32(self.min_ingress_nodes),
            warm_up_rounds=np.int32(self.warm_up_rounds),
            fail_at=np.int32(self.fail_at),
            fail_fraction=np.float64(self.fail_fraction),
            packet_loss_rate=np.float64(self.packet_loss_rate),
            churn_fail_rate=np.float64(self.churn_fail_rate),
            churn_recover_rate=np.float64(self.churn_recover_rate),
            partition_at=np.int32(self.partition_at),
            heal_at=np.int32(self.heal_at),
            impair_seed=np.uint32(self.impair_seed & 0xFFFFFFFF),
            pull_fanout=np.int32(self.pull_fanout),
            pull_interval=np.int32(max(1, self.pull_interval)),
            pull_bloom_fp_rate=np.float64(self.pull_bloom_fp_rate),
            pull_request_cap=np.int32(self.pull_request_cap),
            adaptive_switch_threshold=np.float64(
                self.adaptive_switch_threshold),
            adaptive_switch_hysteresis=np.float64(
                self.adaptive_switch_hysteresis),
            traffic_rate=np.int32(self.traffic_rate),
            node_ingress_cap=np.int32(self.node_ingress_cap),
            node_egress_cap=np.int32(self.node_egress_cap),
            traffic_stall_rounds=np.int32(max(1, self.traffic_stall_rounds)),
        )

    def split(self) -> tuple[EngineStatic, EngineKnobs]:
        """(static compile key, traced knob pytree) — the jit boundary."""
        return self.static_part(), self.knob_values()

    def validate(self) -> "EngineParams":
        assert self.num_nodes >= 2
        # The node-id cap (engine/core.py MAX_NODES) is enforced with a
        # ValueError in make_cluster_tables.
        # Enough physical slots for the reference's insert cap (or for every
        # possible peer, whichever is smaller) so the 50-entry cap semantics
        # (received_cache.rs:78) hold without overflow eviction.
        assert self.rc_slots >= min(self.received_cap, self.num_nodes - 1), (
            "rc_slots too small for the received-cache insert cap")
        assert self.k_inbound >= 2, "need at least the two scored ranks"
        assert self.init_draws > self.active_set_size
        for r in (self.packet_loss_rate, self.churn_fail_rate,
                  self.churn_recover_rate):
            assert 0.0 <= r <= 1.0, "impairment rates must be in [0, 1]"
        if self.partition_at >= 0 and self.heal_at >= 0:
            assert self.heal_at >= self.partition_at, (
                "heal_at must not precede partition_at")
        assert self.gossip_mode in ("push", "pull", "push-pull",
                                    "adaptive"), (
            f"unknown gossip_mode: {self.gossip_mode!r}")
        assert self.representation in ("dense", "sparse"), (
            f"unknown representation: {self.representation!r}")
        if self.representation == "sparse":
            assert self.gossip_mode == "push", (
                "the sparse frontier round implements the push phase only; "
                "pull/adaptive modes need the dense representation")
            assert not self.has_traffic, (
                "the sparse frontier round does not carry the traffic "
                "subsystem yet; use representation='dense' with traffic")
        if self.gossip_mode == "adaptive":
            assert 0.0 < self.adaptive_switch_threshold <= 1.0, (
                "adaptive_switch_threshold must be in (0, 1]")
            assert 0.0 <= self.adaptive_switch_hysteresis \
                < self.adaptive_switch_threshold, (
                "adaptive_switch_hysteresis must be in "
                "[0, adaptive_switch_threshold)")
        if self.has_pull:
            assert self.pull_fanout >= 1, "pull_fanout must be >= 1"
            assert self.pull_interval >= 1, "pull_interval must be >= 1"
            assert 0.0 <= self.pull_bloom_fp_rate <= 1.0, (
                "pull_bloom_fp_rate must be in [0, 1]")
            assert self.pull_fanout <= self.pull_slots_resolved, (
                "pull_fanout exceeds the static pull_slots width — raise "
                "EngineParams.pull_slots")
        assert self.traffic_values >= 1, "traffic_values must be >= 1"
        if self.has_traffic:
            assert self.traffic_rate >= 0, "traffic_rate must be >= 0"
            assert self.traffic_stall_rounds >= 1, (
                "traffic_stall_rounds must be >= 1")
            assert self.gossip_mode in ("push", "adaptive"), (
                "the traffic subsystem models concurrent PUSH streams; "
                "fixed pull modes are not supported with traffic_values "
                "> 1 or queue caps — per-value pull RESCUES are: use "
                "--gossip-mode adaptive (adaptive.py)")
            assert not (self.fail_at >= 0 and self.fail_fraction > 0.0), (
                "one-shot fail_at uses PRNG draws the traffic oracle "
                "cannot replay; use churn_fail_rate with traffic instead")
            if self.gossip_mode == "adaptive":
                # the pull-rescue ingress continuation routes the peer's
                # consumed push budget through the i32 sort-join fast path,
                # whose packed values must stay under the minimum node-id
                # packing base (engine/core.py PACK)
                assert self.node_ingress_cap < 16384, (
                    "adaptive traffic requires node_ingress_cap < 16384 "
                    "(sort-key packing bound); caps that large are "
                    "equivalent to no cap — use 0")
        return self
