"""Static engine parameters (hashable; baked into each compiled round step).

Mirrors the reference's flat ``Config`` (gossip.rs:111-133) plus the dense
shapes the TPU formulation introduces.  Sweeps (gossip_main.rs:774-951) step
one field per simulation; each distinct value compiles once and is cached.
"""

from __future__ import annotations

from typing import NamedTuple

from ..constants import (MIN_NUM_UPSERTS, NUM_PUSH_ACTIVE_SET_ENTRIES,
                         RECEIVED_CACHE_CAPACITY)


class EngineParams(NamedTuple):
    """Static (compile-time) simulation parameters."""

    num_nodes: int
    push_fanout: int = 6                 # gossip_main.rs:90
    active_set_size: int = 12            # gossip_main.rs:97
    probability_of_rotation: float = 0.013333  # gossip_main.rs:124 (1/75)
    prune_stake_threshold: float = 0.15  # gossip_main.rs:142
    min_ingress_nodes: int = 2           # gossip_main.rs:135
    warm_up_rounds: int = 200            # gossip_main.rs:223
    fail_at: int = -1                    # --when-to-fail; -1 = never
    fail_fraction: float = 0.0           # --fraction-to-fail

    min_num_upserts: int = MIN_NUM_UPSERTS          # received_cache.rs:21
    received_cap: int = RECEIVED_CACHE_CAPACITY     # received_cache.rs:78

    # Network-impairment / fault-injection knobs (faults.py; no reference
    # equivalent beyond the one-shot fail_at above).  All decisions are
    # stateless counter hashes of (impair_seed, iteration, node ids), shared
    # bit-exactly with the oracle's FaultInjector.  With every knob at its
    # default the compiled round is IDENTICAL to the unimpaired engine
    # (the blocks are gated on these static fields).
    packet_loss_rate: float = 0.0    # per-message Bernoulli drop probability
    churn_fail_rate: float = 0.0     # per-iteration P(alive node fails)
    churn_recover_rate: float = 0.0  # per-iteration P(failed node recovers)
    partition_at: int = -1           # iteration the stake bipartition starts
    heal_at: int = -1                # iteration it heals (-1 = never)
    impair_seed: int = 0             # hash seed for all impairment streams

    # Dense-shape knobs (TPU formulation only; see engine/core.py for the
    # documented divergences they introduce):
    rc_slots: int = 64      # physical received-cache slots per (origin, node)
    inbound_cap: int = 0    # inbound peers ranked per (origin, dest, round);
                            # 0 = auto: max(16, 2*push_fanout) so fanout
                            # sweeps can't silently truncate scoring
    hist_bins: int = 64     # on-device hop-histogram bins
    rot_tries: int = 8      # rejection-sampling tries per rotation event
    init_draws: int = 64    # candidate draws per entry at initialization
    pa_slots: int = 8       # prune-apply fast-path budget (pruned peers per
                            # row per round); overflow falls back to the
                            # full-width sort via lax.cond — exact either way
    trace_prune_cap: int = 0  # flight-recorder (obs/trace.py) prune-pair
                              # slots captured per (origin, round); 0 = auto
                              # (16*num_nodes — the first prune burst is
                              # nearly synchronized across nodes, so the
                              # cap must hold several pairs per node at
                              # once).  Overflow is counted, never silently
                              # dropped — only the trace truncates, the
                              # simulation itself is unaffected.

    @property
    def num_buckets(self) -> int:
        return NUM_PUSH_ACTIVE_SET_ENTRIES

    @property
    def has_impairments(self) -> bool:
        """True when any fault-injection knob beyond the reference's one-shot
        ``fail_at`` is active (selects the impairment-aware compiled round)."""
        return (self.packet_loss_rate > 0.0 or self.has_churn
                or self.partition_at >= 0)

    @property
    def has_churn(self) -> bool:
        return self.churn_fail_rate > 0.0 or self.churn_recover_rate > 0.0

    @property
    def prune_cap(self) -> int:
        """Resolved flight-recorder prune-pair capture width per round
        (``trace_prune_cap``; 0 = auto: 16*num_nodes, never more than the
        theoretical N*rc_slots maximum)."""
        cap = self.trace_prune_cap or 16 * self.num_nodes
        return min(cap, self.num_nodes * self.rc_slots)

    @property
    def k_inbound(self) -> int:
        """Resolved inbound ranking width (``inbound_cap``; 0 = auto-size
        from the fanout).  Truncation beyond this is counted per round in
        ``rows["inb_dropped"]`` and warned about by the CLI."""
        if self.inbound_cap > 0:
            return self.inbound_cap
        return max(16, 2 * self.push_fanout)

    def validate(self) -> "EngineParams":
        assert self.num_nodes >= 2
        # The node-id cap (engine/core.py MAX_NODES) is enforced with a
        # ValueError in make_cluster_tables.
        # Enough physical slots for the reference's insert cap (or for every
        # possible peer, whichever is smaller) so the 50-entry cap semantics
        # (received_cache.rs:78) hold without overflow eviction.
        assert self.rc_slots >= min(self.received_cap, self.num_nodes - 1), (
            "rc_slots too small for the received-cache insert cap")
        assert self.k_inbound >= 2, "need at least the two scored ranks"
        assert self.init_draws > self.active_set_size
        for r in (self.packet_loss_rate, self.churn_fail_rate,
                  self.churn_recover_rate):
            assert 0.0 <= r <= 1.0, "impairment rates must be in [0, 1]"
        if self.partition_at >= 0 and self.heal_at >= 0:
            assert self.heal_at >= self.partition_at, (
                "heal_at must not precede partition_at")
        return self
