"""Stake-class weighted peer sampling — the TPU replacement for WeightedShuffle.

The reference drives active-set selection with
``solana_gossip::weighted_shuffle::WeightedShuffle`` (push_active_set.rs:164):
a stake-weight-proportional permutation consumed lazily until the entry is
full.  Its per-candidate weight for entry ``k`` is ``(min(bucket_j, k) + 1)^2``
(push_active_set.rs:96-111) — it depends on the candidate *only through its
stake bucket*.  With 25 buckets there are only 25 distinct weight values per
entry, so sampling factorizes exactly:

  1. draw the *bucket class* from a 25-way categorical with mass
     ``count[c] * (min(c, k) + 1)^2``  (a 25-entry CDF per ``k``, precomputed
     once per cluster — stakes are static);
  2. draw a node uniformly *within* the class (equal weights inside a class);
  3. map through the bucket-sorted permutation back to the node id.

One draw costs a 25-way compare + two gathers instead of an O(N) weighted
shuffle — and the distribution is exactly selection-probability ∝ weight,
which is the parity contract (SURVEY.md §7: statistical parity at the
sampling boundary, exact parity downstream).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from ..constants import NUM_PUSH_ACTIVE_SET_ENTRIES

NB = NUM_PUSH_ACTIVE_SET_ENTRIES  # 25


class SamplerTables(NamedTuple):
    """Static per-cluster sampling tables (all device arrays)."""

    perm: jax.Array          # [N] i32  node ids sorted by bucket (stable)
    class_start: jax.Array   # [NB] i32 offset of each bucket class in perm
    class_count: jax.Array   # [NB] i32 nodes per bucket class
    class_cdf: jax.Array     # [NB, NB] f32 normalized inclusive CDF per entry k
    cdf_own: jax.Array       # [N, NB] f32 == class_cdf[bucket(n)] (static
                             # per-node row, avoids a per-node CDF gather)


def build_sampler_tables(buckets: np.ndarray) -> SamplerTables:
    """Precompute the class tables from per-node stake buckets (static)."""
    buckets = np.asarray(buckets, dtype=np.int32)
    n = buckets.shape[0]
    perm = np.argsort(buckets, kind="stable").astype(np.int32)
    class_count = np.bincount(buckets, minlength=NB).astype(np.int32)
    class_start = np.concatenate([[0], np.cumsum(class_count)[:-1]]).astype(np.int32)

    # mass[k, c] = count[c] * (min(c, k) + 1)^2   (push_active_set.rs:96-111)
    c = np.arange(NB)
    weight = (np.minimum(c[None, :], np.arange(NB)[:, None]) + 1) ** 2
    mass = class_count[None, :].astype(np.float64) * weight
    cdf = np.cumsum(mass, axis=1)
    totals = cdf[:, -1:]
    totals = np.where(totals == 0, 1.0, totals)
    cdf = (cdf / totals).astype(np.float32)
    cdf[:, -1] = 1.0

    return SamplerTables(
        perm=jnp.asarray(perm),
        class_start=jnp.asarray(class_start),
        class_count=jnp.asarray(class_count),
        class_cdf=jnp.asarray(cdf),
        cdf_own=jnp.asarray(cdf[buckets]),
    )


def sample_peers(tables: SamplerTables, k_entry: jax.Array,
                 u_class: jax.Array, u_member: jax.Array) -> jax.Array:
    """Draw one weighted peer per element.

    k_entry:  [...] i32 — the active-set entry index (0..24) whose weight
              profile to use; for origin-reduced state this is
              ``min(bucket(node), bucket(origin))`` (push_active_set.rs:48).
    u_class:  [...] f32 uniforms in [0, 1) — class draw.
    u_member: [...] f32 uniforms in [0, 1) — within-class draw.

    Returns node ids with P(node j) ∝ (min(bucket_j, k) + 1)^2, sampled
    *with* replacement; callers do rejection/dedup for without-replacement
    semantics (push_active_set.rs:165-177 skips already-present peers).
    """
    cdf_rows = tables.class_cdf[k_entry]                  # [..., NB]
    cls = jnp.sum((u_class[..., None] >= cdf_rows[..., :-1]).astype(jnp.int32),
                  axis=-1)                                # [...] in [0, NB)
    count = tables.class_count[cls]
    member = tables.class_start[cls] + jnp.floor(
        u_member * count.astype(jnp.float32)).astype(jnp.int32)
    member = jnp.minimum(member, tables.class_start[cls] + jnp.maximum(count - 1, 0))
    return tables.perm[member]
