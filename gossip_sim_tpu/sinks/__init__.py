"""Metrics sinks (reference: influx_db.rs)."""

from .influx import (DatapointQueue, InfluxDataPoint, InfluxDB, InfluxThread,
                     Tracker, load_dotenv)

__all__ = ["DatapointQueue", "InfluxDataPoint", "InfluxDB", "InfluxThread",
           "Tracker", "load_dotenv"]
