"""InfluxDB line-protocol sink (reference: influx_db.rs).

A background reporter thread polls a shared datapoint queue every 100 ms
(1 ms once the ``start`` sentinel arrives) and POSTs line-protocol strings to
InfluxDB's ``/write`` endpoint with basic auth (influx_db.rs:148-206,36-97).
The ``end`` sentinel plus a dequeued==sent tracker drains the queue before
exit (influx_db.rs:23,100-144,189-202) — here the tracker is a plain locked
object rather than the reference's ``static mut`` accessed under ``unsafe``
(a hazard SURVEY.md §5 flags as not worth carrying forward).

Series and field names are the compatibility contract
(influx_db.rs:252-603): ``rmr``, ``coverage``/``branching_factor`` (generic
``data``), ``hops_stat``, ``stranded_node_stats``, ``iteration``,
``simulation_config``, ``validator_stake_distribution``, ``config``,
``stranded_node_iterations``, ``stranded_node_histogram``,
``aggregate_hops_histogram``, ``{egress,ingress,prune}_message_count``.
Extensions beyond the reference: ``delivery`` / ``coverage_recovery``
(fault injection, faults.py), ``sim_perf`` (runtime telemetry, obs/:
round-block wall time, throughput, sender queue depth), ``sim_trace``
(flight-recorder segment flushes, obs/trace.py), ``sim_pull``
(pull-phase request/response/miss/rescue counters, pull.py) and
``sim_capacity`` (memory/FLOP footprint: ledger totals, peak RSS, XLA
temp bytes — obs/capacity.py, obs/memwatch.py).
"""

from __future__ import annotations

import base64
import logging
import os
import threading
import time
import urllib.error
import urllib.parse
import urllib.request
from collections import deque

from ..obs import telemetry as _telemetry

log = logging.getLogger(__name__)


def load_dotenv(path: str = ".env") -> bool:
    """Minimal dotenv: KEY=VALUE lines -> os.environ (existing keys win).

    Replaces the reference's ``dotenv::dotenv()`` (gossip_main.rs:244-246).
    """
    if not os.path.exists(path):
        return False
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line or line.startswith("#") or "=" not in line:
                continue
            key, _, value = line.partition("=")
            os.environ.setdefault(key.strip(), value.strip().strip("'\""))
    return True


def get_timestamp_now() -> str:
    """Nanosecond timestamp + newline (influx_db.rs:25-32)."""
    return f"{time.time_ns()}\n"


class DatapointQueue:
    """Shared FIFO between the simulation and the reporter thread
    (the reference's ``Arc<Mutex<VecDeque<InfluxDataPoint>>>``,
    gossip_main.rs:730-769)."""

    def __init__(self):
        self._dq = deque()
        self._lock = threading.Lock()

    def push_back(self, dp: "InfluxDataPoint") -> None:
        with self._lock:
            self._dq.append(dp)

    def pop_front(self):
        with self._lock:
            return self._dq.popleft() if self._dq else None

    def __len__(self):
        with self._lock:
            return len(self._dq)

    def drain_deterministic_lines(self) -> list:
        """Drain the queue into its deterministic wire payload: every line
        with the per-point ns timestamp (the trailing token) stripped and
        the telemetry-only series (``sim_perf``, ``sim_capacity``,
        ``sim_node_health``) dropped — the first two are wall-clock-
        valued, the third exists only under the opt-in ``--health`` gate,
        and none of the three may perturb simulation parity.  This is THE
        normalized form two runs of the same simulation must agree on —
        the lane-sweep parity tests and tools/lane_smoke.py both diff it,
        so the Influx bit-exactness contract has one definition."""
        raw = []
        while len(self):
            raw.extend(self.pop_front().data().splitlines())
        return deterministic_wire_lines(raw)


def deterministic_wire_lines(lines) -> list:
    """Normalize raw line-protocol strings into the deterministic wire
    payload (the same filter/strip :meth:`DatapointQueue.
    drain_deterministic_lines` applies) — shared with the serve daemon,
    whose per-request result carries its lines in this exact form so the
    serve_smoke parity diff and the lane-sweep parity diff agree on one
    definition."""
    out = []
    for ln in lines:
        if (not ln or ln.startswith("sim_perf")
                or ln.startswith("sim_capacity")
                or ln.startswith("sim_node_health")):
            continue
        out.append(ln.rsplit(" ", 1)[0])
    return out


class Tracker:
    """dequeued==sent drain tracker (influx_db.rs:100-144)."""

    def __init__(self):
        self._lock = threading.Lock()
        self.dequeued = 0
        self.sent = 0

    def add_dequeued(self):
        with self._lock:
            self.dequeued += 1

    def add_sent(self):
        with self._lock:
            self.sent += 1

    def equal(self) -> bool:
        with self._lock:
            return self.sent == self.dequeued


class InfluxDataPoint:
    """Line-protocol string builder (influx_db.rs:252-603)."""

    def __init__(self, start_timestamp: str = "0", simulation_iter: int = 0):
        self.datapoint = ""
        self.timestamp = get_timestamp_now()
        self.simulation_iteration = simulation_iter
        self.start_timestamp = start_timestamp

    def data(self) -> str:
        return self.datapoint

    # -- sentinels (influx_db.rs:290-318) ---------------------------------

    def set_start(self):
        self.datapoint += "start"

    def is_start(self) -> bool:
        return self.datapoint == "start"

    def set_last_datapoint(self):
        self.datapoint += "end"

    def last_datapoint(self) -> bool:
        return self.datapoint == "end"

    # -- timestamps -------------------------------------------------------

    def get_timestamp_now(self) -> str:
        # 1 us sleep so consecutive points never collide on the same ns
        # timestamp (influx takes only one of equal-timestamp points,
        # influx_db.rs:320-332).
        time.sleep(1e-6)
        return get_timestamp_now()

    def append_timestamp(self):
        self.datapoint += self.timestamp

    def set_and_append_timestamp(self):
        self.datapoint += self.get_timestamp_now()

    # -- series builders (influx_db.rs:346-602) ---------------------------

    def create_rmr_data_point(self, result):
        rmr, m, n = result
        self.datapoint += (
            f"rmr,simulation_iter={self.simulation_iteration},"
            f"start_time={self.start_timestamp} rmr={rmr},m={m},n={n} ")
        self.append_timestamp()

    def create_data_point(self, data: float, stat_type: str):
        self.datapoint += (
            f"{stat_type},simulation_iter={self.simulation_iteration},"
            f"start_time={self.start_timestamp} data={data} ")
        self.append_timestamp()

    def create_hops_stat_point(self, stat):
        self.datapoint += (
            f"hops_stat,simulation_iter={self.simulation_iteration},"
            f"start_time={self.start_timestamp} "
            f"mean={stat.mean},median={stat.median},max={stat.max} ")
        self.append_timestamp()

    def create_stranded_node_stat_point(self, stat):
        self.datapoint += (
            f"stranded_node_stats,simulation_iter={self.simulation_iteration},"
            f"start_time={self.start_timestamp} "
            f"count={stat.count},mean={stat.mean_stake},"
            f"median={stat.median_stake},max={stat.max_stake},"
            f"min={stat.min_stake} ")
        self.append_timestamp()

    def create_iteration_point(self, gossip_iter: int, simulation_iter_val: int):
        self.datapoint += (
            f"iteration,simulation_iter={self.simulation_iteration},"
            f"start_time={self.start_timestamp} "
            f"gossip_iter={gossip_iter},simulation_iter_val={simulation_iter_val} ")
        self.append_timestamp()

    def create_test_type_point(self, num_simulations, gossip_iterations,
                               warm_up_rounds, step_size, node_count,
                               probability_of_rotation, api, start_value,
                               test_type):
        self.datapoint += (
            f"simulation_config,start_time={self.start_timestamp} "
            f"num_simulations={num_simulations},"
            f"gossip_iterations_per_simulation={gossip_iterations},"
            f"warm_up_rounds={warm_up_rounds},"
            f"step_size={step_size},"
            f"node_count={node_count},"
            f"probability_of_rotation={probability_of_rotation},"
            f"api=\"{api}\","
            f"start_value=\"{start_value}\","
            f"test_type=\"{test_type}\" ")
        self.append_timestamp()

    def create_validator_stake_distribution_histogram_point(self, histogram):
        for bucket, count in histogram.items():
            self.datapoint += (
                f"validator_stake_distribution,"
                f"start_time={self.start_timestamp} "
                f"bucket={bucket},count={count} ")
            self.set_and_append_timestamp()

    def create_config_point(self, push_fanout, active_set_size, origin_rank,
                            prune_stake_threshold, min_ingress_nodes,
                            fraction_to_fail, rotation_probability):
        self.datapoint += (
            f"config,simulation_iter={self.simulation_iteration},"
            f"start_time={self.start_timestamp} "
            f"push_fanout={push_fanout},"
            f"active_set_size={active_set_size},"
            f"origin_rank={origin_rank},"
            f"prune_stake_threshold={prune_stake_threshold},"
            f"min_ingress_nodes={min_ingress_nodes},"
            f"fraction_to_fail={fraction_to_fail},"
            f"rotation_probability={rotation_probability} ")
        self.append_timestamp()

    def create_stranded_iteration_point(self, total_stranded,
                                        mean_iter_stranded_per_node,
                                        mean_stranded_per_iter,
                                        mean_iter_stranded,
                                        median_iter_stranded,
                                        mean_weighted_stake,
                                        median_weighted_stake):
        self.datapoint += (
            f"stranded_node_iterations,"
            f"simulation_iter={self.simulation_iteration},"
            f"start_time={self.start_timestamp} "
            f"total_stranded={total_stranded},"
            f"mean_iter_stranded_per_node={mean_iter_stranded_per_node},"
            f"mean_stranded_per_iter={mean_stranded_per_iter},"
            f"mean_iter_stranded={mean_iter_stranded},"
            f"median_iter_stranded={median_iter_stranded},"
            f"mean_weighted_stake={mean_weighted_stake},"
            f"median_weighted_stake={median_weighted_stake} ")
        self.append_timestamp()

    def create_histogram_point(self, data_type: str, histogram):
        for bucket, count in histogram.items():
            bucket_max = histogram.min_entry + (bucket + 1) * histogram.bucket_range - 1
            self.datapoint += f"{data_type} bucket={bucket_max},count={count} "
            self.set_and_append_timestamp()

    def create_delivery_point(self, delivered, dropped, suppressed,
                              failed_count):
        """Degraded-delivery counters under fault injection (faults.py):
        per-iteration on the single-origin path, run-level means on the
        all-origins aggregate path."""
        self.datapoint += (
            f"delivery,simulation_iter={self.simulation_iteration},"
            f"start_time={self.start_timestamp} "
            f"delivered={delivered},dropped={dropped},"
            f"suppressed={suppressed},failed={failed_count} ")
        self.append_timestamp()

    def create_recovery_point(self, origins, mean_iters, max_iters,
                              unrecovered):
        """Iterations-to-recover coverage after a partition heal.

        mean/max cover origins that DID recover; when none did they are
        0 and ``unrecovered == origins`` disambiguates from an instant
        recovery (same convention on the single-origin and aggregate
        paths, and never a NaN on the wire)."""
        self.datapoint += (
            f"coverage_recovery,simulation_iter={self.simulation_iteration},"
            f"start_time={self.start_timestamp} "
            f"origins={origins},mean_iters={mean_iters},"
            f"max_iters={max_iters},unrecovered={unrecovered} ")
        self.append_timestamp()

    def create_sim_perf_point(self, round_wall_s, origin_iters_per_sec,
                              queue_depth, iters):
        """Runtime-telemetry series (obs/): wall time and throughput of one
        measured round block plus the sender queue depth at emission time —
        the live "is the sim keeping up / is the sink backed up" signal."""
        self.datapoint += (
            f"sim_perf,simulation_iter={self.simulation_iteration},"
            f"start_time={self.start_timestamp} "
            f"round_wall_s={round_wall_s},"
            f"origin_iters_per_sec={origin_iters_per_sec},"
            f"queue_depth={queue_depth},iters={iters} ")
        self.append_timestamp()

    def create_sim_pull_point(self, requests, responses, misses, dropped,
                              suppressed, rescued):
        """Pull-phase series (pull.py): request/response/miss message
        counts plus loss/partition casualties and the nodes rescued by a
        pull response — per-iteration on the single-origin path, run-level
        means on the all-origins aggregate path."""
        self.datapoint += (
            f"sim_pull,simulation_iter={self.simulation_iteration},"
            f"start_time={self.start_timestamp} "
            f"requests={requests},responses={responses},"
            f"misses={misses},dropped={dropped},"
            f"suppressed={suppressed},rescued={rescued} ")
        self.append_timestamp()

    def create_sim_trace_point(self, rounds, delivered_edges, prunes,
                               bytes_written):
        """Flight-recorder series (obs/trace.py): one point per trace
        segment flush — rounds captured, delivered edges and prune pairs
        recorded, and the compressed bytes written to --trace-dir."""
        self.datapoint += (
            f"sim_trace,simulation_iter={self.simulation_iteration},"
            f"start_time={self.start_timestamp} "
            f"rounds={rounds},delivered_edges={delivered_edges},"
            f"prunes={prunes},bytes_written={bytes_written} ")
        self.append_timestamp()

    def create_sim_traffic_point(self, it, values: dict):
        """Concurrent-traffic series (traffic.py): one point per measured
        round with the whole contention picture — injections, live values,
        wire/deferred/dropped message counts across the value axis, queue
        depths, retirements.  ``values`` carries the stats.traffic
        ROUND_FIELDS ints (deterministic — the wire line joins the
        parity-snapshot surface the smoke gates diff)."""
        fields = ",".join(f"{k}={int(v)}" for k, v in sorted(values.items()))
        self.datapoint += (
            f"sim_traffic,simulation_iter={self.simulation_iteration},"
            f"start_time={self.start_timestamp} "
            f"iteration={int(it)},{fields} ")
        self.append_timestamp()

    def create_sim_traffic_summary_point(self, summary: dict):
        """End-of-run traffic aggregate (stats/traffic.py summary()):
        per-value latency/coverage/RMR aggregates + queue totals."""
        parts = []
        for k, v in sorted(summary.items()):
            parts.append(f"{k}={float(v)}" if isinstance(v, float)
                         else f"{k}={int(v)}")
        self.datapoint += (
            f"sim_traffic_summary,simulation_iter="
            f"{self.simulation_iteration},"
            f"start_time={self.start_timestamp} " + ",".join(parts) + " ")
        self.append_timestamp()

    def create_sim_adaptive_point(self, it, values: dict):
        """Adaptive push-pull series (adaptive.py): one point per measured
        round with the direction-switch picture — on the traffic path the
        stats.traffic ADAPTIVE_ROUND_FIELDS ints (pull-rescue message
        counts, values in pull phase, switch events); on the single-origin
        path the 0/1 direction bit + switch flag.  Deterministic — the
        wire line joins the parity-snapshot surface the smoke gates
        diff."""
        fields = ",".join(f"{k}={int(v)}" for k, v in sorted(values.items()))
        self.datapoint += (
            f"sim_adaptive,simulation_iter={self.simulation_iteration},"
            f"start_time={self.start_timestamp} "
            f"iteration={int(it)},{fields} ")
        self.append_timestamp()

    def create_sim_capacity_point(self, values: dict):
        """Capacity-observatory series (obs/capacity.py + obs/memwatch.py):
        one end-of-run point — ledger totals (bytes, bytes/node, dense
        N^2 share), peak host RSS / device bytes-in-use, and the XLA
        cost-harvest peaks (temp/argument/output bytes, FLOPs).  Carries
        wall-clock-dependent values (RSS), so drain_deterministic_lines
        drops it alongside sim_perf — enabling capacity never moves a
        bit on the parity wire surface."""
        parts = []
        for k, v in sorted(values.items()):
            parts.append(f"{k}={float(v)}" if isinstance(v, float)
                         else f"{k}={int(v)}")
        self.datapoint += (
            f"sim_capacity,simulation_iter={self.simulation_iteration},"
            f"start_time={self.start_timestamp} " + ",".join(parts) + " ")
        self.append_timestamp()

    def create_sim_node_health_point(self, block: int, values: dict):
        """Node-health observatory series (obs/health.py): one point per
        measured harvest block with the flattened digest — per-metric
        totals, hot-node (id, count) pairs and load-imbalance Gini.  The
        values themselves are deterministic integers, but the series only
        exists under the opt-in ``--health`` gate, so
        drain_deterministic_lines drops it alongside sim_perf /
        sim_capacity — enabling health never moves a bit on the parity
        wire surface."""
        parts = []
        for k, v in sorted(values.items()):
            parts.append(f"{k}={float(v)}" if isinstance(v, float)
                         else f"{k}={int(v)}")
        self.datapoint += (
            f"sim_node_health,simulation_iter={self.simulation_iteration},"
            f"start_time={self.start_timestamp} "
            f"block={int(block)}," + ",".join(parts) + " ")
        self.append_timestamp()

    def create_messages_point(self, messages_direction: str, messages,
                              simulation_iter_val: int):
        for bucket, count in messages.items():
            self.datapoint += (
                f"{messages_direction},simulation_iter={simulation_iter_val},"
                f"start_time={self.start_timestamp} "
                f"bucket={bucket},count={count} ")
            self.set_and_append_timestamp()


class InfluxDB:
    """HTTP POST of line protocol to /write?db=... with basic auth
    (influx_db.rs:36-97,205-250)."""

    def __init__(self, endpoint: str, username: str, password: str,
                 database: str, tracker: Tracker | None = None,
                 timeout: float = 10.0, max_retries: int = 3,
                 retry_base: float = 0.5, max_queue: int = 1024,
                 spool_path: str = ""):
        self.url = endpoint.rstrip("/") + "/write"
        self.database = database
        self.username = username
        self.password = password
        self.tracker = tracker
        self.timeout = timeout
        self.max_retries = max_retries
        self.retry_base = retry_base
        self.max_queue = max_queue
        self.spool_path = spool_path  # durable on-disk line-protocol spool
        self.dropped_points = 0   # points lost after retries / queue overflow
        self.spooled_points = 0   # points diverted to the spool file
        self.points_sent = 0      # points acknowledged 2xx by the endpoint
        self.retry_count = 0      # transient-failure retries attempted
        self._send_q = None
        self._send_lock = threading.Lock()
        self._spool_lock = threading.Lock()

    def _count_dropped(self, body: str | None = None):
        """A point exhausted its retries (or the queue overflowed): spool
        it durably when --influx-spool is configured — the point keeps its
        original per-point timestamps, so tools/influx_replay.py re-sends
        exactly what the run would have written — else count it lost."""
        if body and self.spool_path and self._spool(body):
            with self._send_lock:
                self.spooled_points += 1
            _telemetry.emit_event("influx_spool", points=1,
                                  path=self.spool_path)
            return
        with self._send_lock:
            self.dropped_points += 1
        _telemetry.emit_event("influx_drop", points=1)

    def _spool(self, body: str) -> bool:
        """Append one point's line-protocol body to the spool file.
        Append-mode writes of a single buffered payload are atomic enough
        for line protocol (the replayer skips any torn final line).
        Returns False — falling back to the dropped count — if the spool
        itself is unwritable."""
        try:
            with self._spool_lock:
                with open(self.spool_path, "a") as f:
                    f.write(body if body.endswith("\n") else body + "\n")
            return True
        except OSError as err:
            log.error("influx spool %s unwritable (%s); counting point "
                      "as dropped", self.spool_path, err)
            return False

    def sender_stats(self) -> dict:
        """Delivery accounting for end-of-run logging and the run report."""
        with self._send_lock:
            return {
                "points_sent": self.points_sent,
                "dropped_points": self.dropped_points,
                "spooled_points": self.spooled_points,
                "retries": self.retry_count,
            }

    def _post(self, body: str):
        """POST one line-protocol body; retry transient failures with
        exponential backoff + jitter, then count the point as dropped.  The
        tracker is marked sent exactly once either way so the drain loop
        (InfluxThread) terminates instead of hanging on lost points."""
        import random

        url = f"{self.url}?{urllib.parse.urlencode({'db': self.database})}"
        auth = base64.b64encode(
            f"{self.username}:{self.password}".encode()).decode()
        req = urllib.request.Request(
            url, data=body.encode(),
            headers={"Authorization": f"Basic {auth}"})
        try:
            delay = self.retry_base
            for attempt in range(self.max_retries + 1):
                err = None
                retryable = True
                try:
                    with urllib.request.urlopen(
                            req, timeout=self.timeout) as resp:
                        if 200 <= resp.status < 300:
                            with self._send_lock:
                                self.points_sent += 1
                            return
                        err = f"HTTP status {resp.status}"
                except urllib.error.HTTPError as exc:
                    err = f"HTTP status {exc.code}"
                    # permanent client errors (bad auth, malformed body)
                    # never succeed on retry — fail fast so a config error
                    # can't back-pressure the whole send queue
                    retryable = exc.code >= 500 or exc.code in (408, 429)
                except (urllib.error.URLError, OSError) as exc:
                    err = exc
                if retryable and attempt < self.max_retries:
                    with self._send_lock:
                        self.retry_count += 1
                    _telemetry.emit_event("influx_retry",
                                          attempt=attempt + 1,
                                          error=str(err)[:200])
                    log.warning("InfluxDB send failed (attempt %s/%s): %s — "
                                "retrying in %.2fs", attempt + 1,
                                self.max_retries + 1, err, delay)
                    time.sleep(delay * (1.0 + 0.5 * random.random()))
                    delay *= 2
                else:
                    self._count_dropped(body)
                    log.error("%s InfluxDB point after %s attempt(s): %s",
                              "Spooling" if self.spool_path else "Dropping",
                              attempt + 1, err)
                    return
        finally:
            if self.tracker is not None:
                self.tracker.add_sent()

    def send_data_points(self, datapoint: InfluxDataPoint):
        # Async send like the reference (one async_std task per point,
        # influx_db.rs:81-96), but through a single persistent worker so a
        # slow endpoint can't accumulate thousands of live sender threads.
        # The queue is bounded: a stalled endpoint sheds points (counted in
        # ``dropped_points``) instead of growing without limit.
        with self._send_lock:
            if self._send_q is None:
                import queue
                self._send_q = queue.Queue(maxsize=self.max_queue)

                def _worker():
                    while True:
                        body = self._send_q.get()
                        try:
                            self._post(body)
                        except Exception as err:  # one bad point must not
                            # kill the drain: _post counts sent in finally,
                            # but anything else raised here would leave the
                            # Tracker unequal and InfluxThread hung forever
                            log.error("influx sender error: %s", err)

                threading.Thread(target=_worker, daemon=True).start()
        import queue
        try:
            self._send_q.put_nowait(datapoint.data())
        except queue.Full:
            self._count_dropped(datapoint.data())
            # still mark it sent: the drain tracker must converge
            if self.tracker is not None:
                self.tracker.add_sent()
            log.error("InfluxDB send queue full (%s); %s point",
                      self.max_queue,
                      "spooling" if self.spool_path else "dropping")


class InfluxThread:
    """Reporter loop (influx_db.rs:146-204).

    Instances are join-able handles that keep the underlying ``InfluxDB``
    reachable after the drain, so end-of-run logging and the run report
    (obs/report.py) can surface dropped-point / retry accounting instead of
    burying it in the drain log."""

    def __init__(self, endpoint: str, username: str, password: str,
                 database: str, datapoint_queue: DatapointQueue,
                 spool_path: str = ""):
        self.tracker = Tracker()
        self.db = InfluxDB(endpoint, username, password, database,
                           self.tracker, spool_path=spool_path)
        self._queue = datapoint_queue
        self._thread: threading.Thread | None = None

    def run(self):
        """The reporter loop body (blocks until the end sentinel drains)."""
        wait_time = 0.1
        rx_last_datapoint = False
        draining_logged = False
        while True:
            dp = self._queue.pop_front()
            if dp is not None:
                if dp.last_datapoint():
                    rx_last_datapoint = True
                elif dp.is_start():
                    wait_time = 0.001
                else:
                    self.db.send_data_points(dp)
                    self.tracker.add_dequeued()
            if rx_last_datapoint:
                if not draining_logged:
                    draining_logged = True
                    log.info("Last simulation datapoint recorded. "
                             "Draining Queue...")
                if self.tracker.equal():
                    if self.db.dropped_points:
                        log.warning("WARNING: %s InfluxDB point(s) dropped "
                                    "(send failures after retries or queue "
                                    "overflow)", self.db.dropped_points)
                    if self.db.spooled_points:
                        log.warning("WARNING: %s InfluxDB point(s) spooled "
                                    "to %s; re-send with "
                                    "tools/influx_replay.py",
                                    self.db.spooled_points,
                                    self.db.spool_path)
                    log.info("Queue Drained. Exiting...")
                    break
            time.sleep(wait_time)

    def join(self, timeout: float | None = None):
        if self._thread is not None:
            self._thread.join(timeout)

    def is_alive(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    def sender_stats(self) -> dict:
        """points_sent / dropped_points / retries (InfluxDB.sender_stats)."""
        return self.db.sender_stats()

    @staticmethod
    def start(endpoint: str, username: str, password: str, database: str,
              datapoint_queue: DatapointQueue):
        """Run the reporter loop inline (the reference's thread body)."""
        InfluxThread(endpoint, username, password, database,
                     datapoint_queue).run()

    @staticmethod
    def spawn(endpoint: str, username: str, password: str, database: str,
              datapoint_queue: DatapointQueue,
              spool_path: str = "") -> "InfluxThread":
        """Run the loop in a daemon thread; returns the join-able handle
        (the reference's std::thread::spawn, gossip_main.rs:746-768).
        ``spool_path`` diverts retry-exhausted / overflow points to a
        durable line-protocol spool (tools/influx_replay.py re-sends)."""
        it = InfluxThread(endpoint, username, password, database,
                          datapoint_queue, spool_path=spool_path)
        it._thread = threading.Thread(target=it.run, daemon=True)
        it._thread.start()
        return it
