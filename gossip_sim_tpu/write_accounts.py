"""``write-accounts`` binary: snapshot mainnet vote accounts to YAML
(reference: write_accounts_main.rs).

Pulls vote accounts over JSON-RPC, optionally keeps only zero-staked nodes
(``--zero-stakes``) or filters them out (``-f``), then writes the first N as
a ``{pubkey: stake}`` YAML account file (write_accounts_main.rs:62-125).
"""

from __future__ import annotations

import argparse
import logging
import sys

from .constants import API_MAINNET_BETA, get_json_rpc_url
from .ingest import fetch_vote_accounts_rpc, write_accounts_yaml

log = logging.getLogger("gossip_sim_tpu.write_accounts")


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="write-accounts",
        description="write solana vote accounts to a yaml file")
    p.add_argument("--url", dest="json_rpc_url", default=API_MAINNET_BETA,
                   metavar="URL_OR_MONIKER", help="solana's json rpc url")
    p.add_argument("--num-nodes", type=int, default=(1 << 64) - 1,
                   metavar="NUMBER_OF_NODES_TO_SIMULATE",
                   help="number of nodes to simulate. default is all")
    p.add_argument("--account-file", default="", metavar="PATH",
                   help="yaml of solana accounts to write to")
    p.add_argument("--zero-stakes", action="store_true",
                   help="set if you only want zero-staked nodes")
    p.add_argument("--filter-zero-staked-nodes", "-f", action="store_true",
                   help="Filter out all zero-staked nodes")
    return p


def write_accounts(accounts: dict, num_nodes: int, account_file: str,
                   zero_stakes_only: bool) -> dict:
    """Select the first N (optionally zero-staked-only) accounts and write
    them (write_accounts_main.rs:62-125)."""
    items = list(accounts.items())
    if zero_stakes_only:
        items = [(pk, s) for pk, s in items if s == 0]
    selected = dict(items[:num_nodes])
    log.info("writing %s accounts to %s", len(selected), account_file)
    write_accounts_yaml(account_file, selected)
    return selected


def main(argv=None) -> int:
    logging.basicConfig(
        level=logging.INFO,
        format="[%(asctime)s %(levelname)s %(name)s] %(message)s")
    args = build_parser().parse_args(argv)
    if not args.account_file:
        log.error("need --account-file <path> to write to")
        return 1
    url = get_json_rpc_url(args.json_rpc_url)
    log.info("json_rpc_url: %s", url)
    accounts = fetch_vote_accounts_rpc(url)
    if args.filter_zero_staked_nodes:
        accounts = {pk: s for pk, s in accounts.items() if s != 0}
    write_accounts(accounts, args.num_nodes, args.account_file,
                   args.zero_stakes)
    return 0


if __name__ == "__main__":
    sys.exit(main())
