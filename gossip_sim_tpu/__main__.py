"""``python -m gossip_sim_tpu`` — the gossip-sim experiment driver
(reference binary: gossip-sim, gossip_main.rs)."""

import sys

from .cli import main

sys.exit(main())
