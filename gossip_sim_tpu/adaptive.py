"""Adaptive push-pull: direction-optimizing gossip, shared by both backends.

Push gossip is cheap while the infected set is small and ruinously
redundant once it is large: with coverage c, a push round moves ~c*N*fanout
messages to deliver ~(1-c)*N new values, so the marginal cost per delivery
explodes exactly when the value is almost everywhere.  Pull has the mirror
profile — each *missing* node asks a few peers, so its cost scales with
(1-c)*N and its hit rate with c.  "Implementing Push-Pull Efficiently in
GraphBLAS" (PAPERS.md) turns this into the direction-optimizing rule this
module implements: **push while coverage is low, flip to pull once
coverage crosses a threshold**.

``gossip_mode="adaptive"`` applies the rule in both engines:

* **Single-origin engine** (engine/core.py): the pull (anti-entropy) phase
  of ``pull.py`` is gated per origin-sim on a carried boolean
  (``SimState.adaptive_pull_on``).  Each round the switch re-evaluates on
  the round's *push* coverage: the pull phase activates for the NEXT round
  once ``n_reached >= threshold * N`` and deactivates once coverage falls
  below ``(threshold - hysteresis) * N`` (coverage is re-derived per round
  in this model, so churn/loss can drop it back under the bar; the
  hysteresis window stops the direction bit from thrashing at the
  boundary).  The push phase always runs — in the memoryless
  BFS-per-round model it *is* the value's presence — so "flip to pull"
  means "start paying for the reverse direction only when it can do
  last-mile work", which is where all of pull's rescue value and almost
  none of its cost lives (vs ``push-pull``, which pays pull every round).
* **Traffic engine** (engine/traffic.py): the switch is per *value*.  A
  value whose coverage crosses the threshold stops generating push
  candidates (freeing its share of every sender's egress budget — the
  direction flip is a real bandwidth reallocation under queue caps) and
  enters its **pull-rescue phase**: every live node still missing the
  value sends ``pull_fanout`` stake-weighted pull requests for it.
  Requests ride the SAME per-node egress/ingress queue budgets as push
  traffic (ranked after the round's push messages, in value-major order),
  so rescues compete for bandwidth honestly; a holder answers an accepted
  request unless the requester's bloom digest false-positives the value
  away.  Rescue deliveries are tagged per value (``rescued_by_pull`` in
  the retirement record) — the measurable fix for BENCH_r07's
  queue-drop starvation, where push alone converges 0 of 80 values.

Switch decision (bit-exact by construction in both backends): integer
coverage counts compared against f64 products, with one shared
formulation (:func:`switch_update_arr`):

    up   = float64(n_covered) >= threshold * N
    down = float64(n_covered) <  (threshold - hysteresis) * N
    on'  = up ? True : (down ? False : on)

Both knobs are traced :class:`EngineKnobs` leaves, so a threshold sweep
compiles once and runs lane-batched.

Determinism contract for the traffic pull-rescue (the faults.py
philosophy): every stochastic choice is a stateless counter hash,
decorrelated per value through ``traffic.value_basis`` so two values in
their pull phase draw independent peers/loss/bloom coins:

    peer draw   class/member u01 of edge-hash(value_basis(b, vid), node, slot)
    request loss edge-hash(value_basis(b, vid), requester, peer) < rate * 2^32
    bloom FP    node-hash(value_basis(b, vid), requester)        < rate * 2^32

``TrafficOracle`` (traffic.py) and the sort-routed traffic engine consume
these through the same ``*_arr`` helpers, so the 1k-node parity tests hold
bit-for-bit under loss + churn with the switch active.

Everything here is numpy-only: importing this module never touches JAX.
"""

from __future__ import annotations

import numpy as np

from .pull import PullOracle, PullRound

# domain-separation salts for the traffic pull-rescue hash streams
# (faults.py convention; SHA-256 round constants, distinct from every
# existing SALT_* in faults.py / pull.py / traffic.py)
SALT_ADAPT_PCLASS = 0x59F111F1   # rescue peer draw: stake-class uniform
SALT_ADAPT_PMEMBER = 0x923F82A4  # rescue peer draw: within-class uniform
SALT_ADAPT_PLOSS = 0xAB1C5ED5    # per-(value, requester, peer) request loss
SALT_ADAPT_PBLOOM = 0xD807AA98   # per-(value, requester) bloom-FP event


def switch_update_arr(n_covered, num_nodes, prev_on, threshold, hysteresis,
                      xp=np):
    """The direction switch, one formulation for both backends.

    ``n_covered``: integer coverage count(s) (any shape); ``prev_on``:
    matching bool(s).  ``threshold``/``hysteresis`` are f64 scalars (traced
    on the engine side).  All arithmetic is f64 with one fixed operation
    order — integer count widened to f64, thresholds multiplied against
    f64(N) — so numpy (oracle) and jax.numpy (engine) lanes agree
    bit-for-bit."""
    cov = xp.asarray(n_covered).astype(xp.float64)
    n = xp.asarray(num_nodes).astype(xp.float64)
    thr = xp.asarray(threshold).astype(xp.float64)
    hyst = xp.asarray(hysteresis).astype(xp.float64)
    up = cov >= thr * n
    down = cov < (thr - hyst) * n
    return xp.where(up, True, xp.where(down, False, prev_on))


def switch_update(n_covered: int, num_nodes: int, prev_on: bool,
                  threshold: float, hysteresis: float) -> bool:
    """Scalar twin of :func:`switch_update_arr` (oracle loops)."""
    return bool(switch_update_arr(np.int64(n_covered), np.int64(num_nodes),
                                  np.bool_(prev_on), threshold, hysteresis))


def empty_pull_round(num_nodes: int, pull_slots: int) -> PullRound:
    """The all-zero PullRound an inactive pull round reports — identical
    to what ``PullOracle.run_round`` returns off its interval, so a
    switch-gated round and an interval-gated round are indistinguishable
    downstream (exactly like the engine, whose gated pull block emits
    zero counts and -1 peer slots)."""
    n, ps = int(num_nodes), int(pull_slots)
    return PullRound(0, 0, 0, 0, 0, {}, np.zeros(n, np.int64),
                     np.zeros(n, np.int64), np.full((n, ps), -1, np.int16),
                     np.zeros((n, ps), np.int8), np.full(n, -1, np.int16))


class AdaptiveOracle:
    """CPU-oracle adaptive direction switch for the single-origin path.

    Wraps a :class:`pull.PullOracle` behind the carried ``pull_active``
    bit and re-evaluates the switch each round on the round's push
    coverage — the identical spec the engine's ``round/pull`` gating +
    end-of-round ``switch_update_arr`` implement, so the 1k-node parity
    test (tests/test_adaptive.py / tools/adaptive_smoke.py) checks the
    sort-routed engine against this class bit-for-bit under loss + churn.

    Drop-in for ``PullOracle`` in ``oracle/cluster.run_pull``: a round
    where the switch (or the inner pull interval) is off returns the same
    empty :class:`PullRound` an off-interval ``PullOracle`` round does.
    ``switch_rounds`` records every flip as ``(iteration, new_state)`` —
    the oracle twin of the engine's ``adaptive_switched`` row.
    """

    def __init__(self, stakes, *, adaptive_switch_threshold: float = 0.9,
                 adaptive_switch_hysteresis: float = 0.05, **pull_kwargs):
        self.inner = PullOracle(stakes, **pull_kwargs)
        self.n = self.inner.n
        self.pull_slots = self.inner.pull_slots
        self.threshold = float(adaptive_switch_threshold)
        self.hysteresis = float(adaptive_switch_hysteresis)
        self.pull_active = False
        self.switch_rounds = []   # [(iteration, now_on)] flip history

    def pull_round_active(self, it: int) -> bool:
        """Whether this round's pull exchange will actually run."""
        return self.pull_active and self.inner.pull_round_active(it)

    def run_round(self, it: int, hops, failed) -> PullRound:
        """One adaptive round: run (or gate) the pull exchange against
        this round's push outcome, then update the direction bit from the
        push coverage for the next round."""
        hops = np.asarray(hops)
        if self.pull_active:
            res = self.inner.run_round(it, hops, failed)
        else:
            res = empty_pull_round(self.n, self.pull_slots)
        n_reached = int(np.count_nonzero(hops >= 0))
        new_on = switch_update(n_reached, self.n, self.pull_active,
                               self.threshold, self.hysteresis)
        if new_on != self.pull_active:
            self.switch_rounds.append((int(it), bool(new_on)))
        self.pull_active = new_on
        return res
