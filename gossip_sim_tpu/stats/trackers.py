"""Per-node message-count trackers with stake-bucketed histograms
(reference: gossip_stats.rs:359-461) and the outbound branching factor
(gossip_stats.rs:1168-1191)."""

from __future__ import annotations

from .histogram import Histogram


class EgressIngressMessageTracker:
    def __init__(self):
        self.counts = {}  # pubkey -> cumulative message count
        self.count_per_bucket = []
        self.histogram = Histogram()

    def initialize_counts_map(self, stakes):
        for pk in stakes:
            self.counts[pk] = 0

    def update_message_counts(self, new_messages):
        for pk, n in new_messages.items():
            self.counts[pk] += n

    def build_histogram(self, num_buckets, stakes):
        sorted_stakes = sorted(stakes.items(), key=lambda kv: -kv[1])
        self.count_per_bucket = [0] * num_buckets
        self.histogram.build_from_map(num_buckets, self.counts, sorted_stakes,
                                      self.count_per_bucket)

    def normalize_message_counts(self):
        self.histogram.normalize_histogram(self.count_per_bucket)

    def clear(self):
        for pk in self.counts:
            self.counts[pk] = 0


def branching_factor_outbound(pushes):
    """Mean outbound degree over visited nodes: sum(|pushes[src]|) / |pushes|
    (gossip_stats.rs:1174-1190)."""
    if not pushes:
        return 0.0
    return sum(len(d) for d in pushes.values()) / len(pushes)
