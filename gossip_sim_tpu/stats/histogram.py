"""Fixed-bucket histogram (reference: gossip_stats.rs:549-743).

Two build modes:
  * ``build`` — bucket raw u64 values into ``num_buckets`` equal ranges over
    [lower_bound, upper_bound] (gossip_stats.rs:575-619).
  * ``build_from_map`` — bucket nodes **by stake** and sum each node's message
    count into its stake bucket (gossip_stats.rs:621-666); used for the
    egress/ingress/prune message histograms.
``normalize_histogram`` divides each bucket by a per-bucket node count
(gossip_stats.rs:672-682).
"""

from __future__ import annotations

import logging

log = logging.getLogger(__name__)


class Histogram:
    def __init__(self):
        self.entries = {}  # bucket -> count, kept sorted by bucket on read
        self.min_entry = 0
        self.max_entry = 0
        self.bucket_range = 0
        self.num_buckets = 0

    def build(self, upper_bound, lower_bound, num_buckets, values):
        # NOTE: like the reference (gossip_stats.rs:608-611), only
        # ``bucket == num_buckets`` is clamped — when
        # (upper-lower)/num_buckets truncates, in-range values near the
        # upper bound land in buckets beyond num_buckets-1.  Kept for
        # output parity with the reference's BTreeMap behavior.
        self.min_entry = int(lower_bound)
        self.max_entry = int(upper_bound)
        self.num_buckets = int(num_buckets)
        if upper_bound == lower_bound or lower_bound + 1 == upper_bound:
            log.warning("histogram: max and min entries equal or off by 1")
            self.bucket_range = 1
        else:
            # floor to >= 1: the reference divides by an unchecked u64 range
            # (gossip_stats.rs:588) and would panic when range < num_buckets
            self.bucket_range = max(
                1, (self.max_entry - self.min_entry) // self.num_buckets)
        self.entries = {b: 0 for b in range(self.num_buckets)}
        for v in values:
            v = int(v)
            if self.min_entry <= v <= self.max_entry:
                bucket = (v - self.min_entry) // self.bucket_range
                if bucket == self.num_buckets:
                    bucket -= 1
                self.entries[bucket] = self.entries.get(bucket, 0) + 1
            else:
                log.error("histogram: entry %s outside [%s, %s]",
                          v, self.min_entry, self.max_entry)

    def build_from_counts(self, upper_bound, lower_bound, num_buckets,
                          value_counts):
        """``build`` semantics fed pre-binned data: ``value_counts`` maps a
        value -> how many times it occurred.  Avoids materializing raw-value
        arrays when the source is an on-device histogram."""
        self.min_entry = int(lower_bound)
        self.max_entry = int(upper_bound)
        self.num_buckets = int(num_buckets)
        if upper_bound == lower_bound or lower_bound + 1 == upper_bound:
            log.warning("histogram: max and min entries equal or off by 1")
            self.bucket_range = 1
        else:
            self.bucket_range = max(
                1, (self.max_entry - self.min_entry) // self.num_buckets)
        self.entries = {b: 0 for b in range(self.num_buckets)}
        for v, n in value_counts.items():
            v = int(v)
            if self.min_entry <= v <= self.max_entry:
                bucket = (v - self.min_entry) // self.bucket_range
                if bucket == self.num_buckets:
                    bucket -= 1
                self.entries[bucket] = self.entries.get(bucket, 0) + int(n)
            else:
                log.error("histogram: entry %s outside [%s, %s]",
                          v, self.min_entry, self.max_entry)

    def build_from_map(self, num_buckets, counts, sorted_stakes, count_per_bucket):
        """counts: {pubkey: message count}; sorted_stakes: [(pubkey, stake)]
        descending by stake. Buckets are stake ranges; values are summed
        message counts (gossip_stats.rs:621-666)."""
        self.min_entry = 0
        self.max_entry = int(sorted_stakes[0][1])
        self.num_buckets = int(num_buckets)
        if self.max_entry == self.min_entry:
            log.warning("histogram: max and min entries equal")
            self.bucket_range = 1
        else:
            self.bucket_range = (self.max_entry - self.min_entry) // self.num_buckets
            if self.bucket_range == 0:
                self.bucket_range = 1
        self.entries = {b: 0 for b in range(self.num_buckets)}
        for pubkey, stake in sorted_stakes:
            msgs = counts[pubkey]
            if self.min_entry <= stake <= self.max_entry:
                bucket = (int(stake) - self.min_entry) // self.bucket_range
                if bucket >= self.num_buckets:
                    bucket = self.num_buckets - 1
                self.entries[bucket] = self.entries.get(bucket, 0) + msgs
                count_per_bucket[bucket] += 1
            else:
                log.error("message histogram: stake %s outside bounds", stake)

    def normalize_histogram(self, normalization_vector):
        for bucket in list(self.entries):
            n = normalization_vector[bucket]
            if n:
                self.entries[bucket] //= n

    def items(self):
        return sorted(self.entries.items())
