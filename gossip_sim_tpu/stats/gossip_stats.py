"""Per-simulation statistics hub (reference: gossip_stats.rs:1228-1965).

Collects hops, coverage, RMR, stranded, branching factor and message-count
series across measured rounds; runs the end-of-simulation calculations and
builds histograms.  ``GossipStatsCollection`` aggregates across sweep runs.
"""

from __future__ import annotations

import logging
from dataclasses import dataclass, field

from ..config import Config, StepSize, Testing
from .collections import StatCollection
from .histogram import Histogram
from .hops import HopsStatCollection
from .stranded import StrandedNodeCollection
from .trackers import EgressIngressMessageTracker, branching_factor_outbound

log = logging.getLogger(__name__)


@dataclass
class SimulationParameters:
    """Config snapshot stored with each GossipStats
    (gossip_stats.rs:1193-1226)."""

    gossip_push_fanout: int = 0
    gossip_active_set_size: int = 0
    gossip_iterations: int = 0
    origin_rank: int = 0
    probability_of_rotation: float = 0.0
    prune_stake_threshold: float = 0.0
    min_ingress_nodes: int = 0
    fraction_to_fail: float = 0.0
    when_to_fail: int = 0
    packet_loss_rate: float = 0.0
    churn_fail_rate: float = 0.0
    churn_recover_rate: float = 0.0
    partition_at: int = -1
    heal_at: int = -1
    gossip_mode: str = "push"
    pull_fanout: int = 0
    pull_interval: int = 1
    pull_bloom_fp_rate: float = 0.0
    pull_request_cap: int = 0
    test_type: Testing = Testing.NO_TEST
    num_simulations: int = 0
    step_size: StepSize = field(default_factory=lambda: StepSize(0, True))


class GossipStats:
    def __init__(self):
        self.hops_stats = HopsStatCollection()
        self.coverage_stats = StatCollection("Coverage")
        self.rmr_stats = StatCollection("RMR")
        self.stranded_node_collection = StrandedNodeCollection()
        self.outbound_branching_factors = StatCollection("Outbound Branching Factor")
        self.origin = None
        self.simulation_parameters = SimulationParameters()
        self.failed_nodes = set()
        self.egress_messages = EgressIngressMessageTracker()
        self.ingress_messages = EgressIngressMessageTracker()
        self.prune_messages = EgressIngressMessageTracker()
        self.validator_stake_distribution = Histogram()
        # degraded-delivery series (faults.py); empty unless impairments ran
        self.delivered_stats = StatCollection("Delivered Messages")
        self.dropped_stats = StatCollection("Dropped Messages")
        self.suppressed_stats = StatCollection("Suppressed Messages")
        self.failed_count_series = []
        # pull-phase series (pull.py); empty unless a pull mode ran
        self.pull_requests_stats = StatCollection("Pull Requests")
        self.pull_responses_stats = StatCollection("Pull Responses")
        self.pull_misses_stats = StatCollection("Pull Misses")
        self.pull_dropped_stats = StatCollection("Pull Dropped Requests")
        self.pull_suppressed_stats = StatCollection(
            "Pull Suppressed Requests")
        self.pull_rescued_stats = StatCollection("Pull Rescued Nodes")
        # adaptive direction-switch series (adaptive.py); empty unless
        # gossip_mode "adaptive" ran.  active is the 0/1 direction bit in
        # effect each measured round, switched flags the rounds whose
        # coverage flipped it
        self.adaptive_active_series = []
        self.adaptive_switched_series = []
        # iterations from heal_at until coverage regained the recovery
        # threshold; None = no heal configured or never measured, -1 = never
        # recovered within the run
        self.recovery_iterations = None
        # full post-heal (iteration, coverage) samples — fed by both
        # backends for every iteration >= heal_at including warm-up rounds,
        # so the metric is iteration-exact and agrees with the all-origins
        # aggregate path (stats/aggregate.py add_batch)
        self._post_heal_coverage = []

    # -- setup ---------------------------------------------------------------

    def set_simulation_parameters(self, config: Config):
        self.simulation_parameters = SimulationParameters(
            gossip_push_fanout=config.gossip_push_fanout,
            gossip_active_set_size=config.gossip_active_set_size,
            gossip_iterations=config.gossip_iterations,
            origin_rank=config.origin_rank,
            probability_of_rotation=config.probability_of_rotation,
            prune_stake_threshold=config.prune_stake_threshold,
            min_ingress_nodes=config.min_ingress_nodes,
            fraction_to_fail=config.fraction_to_fail,
            when_to_fail=config.when_to_fail,
            packet_loss_rate=config.packet_loss_rate,
            churn_fail_rate=config.churn_fail_rate,
            churn_recover_rate=config.churn_recover_rate,
            partition_at=config.partition_at,
            heal_at=config.heal_at,
            gossip_mode=config.gossip_mode,
            pull_fanout=config.pull_fanout,
            pull_interval=config.pull_interval,
            pull_bloom_fp_rate=config.pull_bloom_fp_rate,
            pull_request_cap=config.pull_request_cap,
            test_type=config.test_type,
            num_simulations=config.num_simulations,
            step_size=config.step_size,
        )

    def set_origin(self, origin):
        self.origin = origin

    def parity_snapshot(self) -> dict:
        """Every deterministic per-sim series/counter as one dict — THE
        bit-exactness surface two runs of the same simulation must agree
        on.  Both the lane-sweep regression tests and the
        tools/lane_smoke.py CI gate diff this snapshot, so the parity
        contract has exactly one definition; extend it here when a new
        stats field lands and every parity check picks it up."""
        return {
            "coverage": list(self.coverage_stats.collection),
            "rmr": list(self.rmr_stats.collection),
            "branching": list(self.outbound_branching_factors.collection),
            "hops": list(self.hops_stats.raw_hop_collection),
            "stranded": dict(self.stranded_node_collection.stranded_nodes),
            "egress": dict(self.egress_messages.counts),
            "ingress": dict(self.ingress_messages.counts),
            "prunes": dict(self.prune_messages.counts),
            "delivered": list(self.delivered_stats.collection),
            "dropped": list(self.dropped_stats.collection),
            "suppressed": list(self.suppressed_stats.collection),
            "failed_count_series": list(self.failed_count_series),
            "failed_nodes": set(self.failed_nodes),
            "pull_requests": list(self.pull_requests_stats.collection),
            "pull_responses": list(self.pull_responses_stats.collection),
            "pull_misses": list(self.pull_misses_stats.collection),
            "pull_dropped": list(self.pull_dropped_stats.collection),
            "pull_suppressed": list(self.pull_suppressed_stats.collection),
            "pull_rescued": list(self.pull_rescued_stats.collection),
            "adaptive_active": list(self.adaptive_active_series),
            "adaptive_switched": list(self.adaptive_switched_series),
            "recovery_iterations": self.recovery_iterations,
        }

    def initialize_message_stats(self, stakes):
        self.egress_messages.initialize_counts_map(stakes)
        self.ingress_messages.initialize_counts_map(stakes)
        self.prune_messages.initialize_counts_map(stakes)

    def set_failed_nodes(self, failed_nodes):
        self.failed_nodes.update(failed_nodes)

    # -- per-round inserts ---------------------------------------------------

    def insert_coverage(self, value):
        self.coverage_stats.push(value)

    def insert_rmr(self, rmr):
        self.rmr_stats.push(rmr)

    def insert_hops_stat(self, distances):
        """distances: {pubkey: hops} or iterable of hops."""
        hops = (list(distances.values()) if isinstance(distances, dict)
                else list(distances))
        self.hops_stats.insert(hops)

    def insert_stranded_nodes(self, stranded_nodes, stakes):
        self.stranded_node_collection.insert_nodes(stranded_nodes, stakes)

    def calculate_outbound_branching_factor(self, pushes):
        self.outbound_branching_factors.push(branching_factor_outbound(pushes))

    def insert_branching_factor(self, value):
        self.outbound_branching_factors.push(value)

    def update_message_counts(self, egress, ingress):
        self.egress_messages.update_message_counts(egress)
        self.ingress_messages.update_message_counts(ingress)

    def update_prune_counts(self, prunes):
        self.prune_messages.update_message_counts(prunes)

    def insert_delivery(self, delivered, dropped, suppressed, failed_count):
        """Per-round degraded-delivery counters (faults.py)."""
        self.delivered_stats.push(delivered)
        self.dropped_stats.push(dropped)
        self.suppressed_stats.push(suppressed)
        self.failed_count_series.append(int(failed_count))

    def has_delivery_stats(self):
        return not self.delivered_stats.is_empty()

    def insert_pull(self, requests, responses, misses, dropped, suppressed,
                    rescued):
        """Per-round pull-phase counters (pull.py)."""
        self.pull_requests_stats.push(requests)
        self.pull_responses_stats.push(responses)
        self.pull_misses_stats.push(misses)
        self.pull_dropped_stats.push(dropped)
        self.pull_suppressed_stats.push(suppressed)
        self.pull_rescued_stats.push(rescued)

    def has_pull_stats(self):
        return not self.pull_requests_stats.is_empty()

    def insert_adaptive(self, active, switched):
        """Per-round adaptive direction-switch telemetry (adaptive.py)."""
        self.adaptive_active_series.append(int(active))
        self.adaptive_switched_series.append(int(switched))

    def has_adaptive_stats(self):
        return bool(self.adaptive_active_series)

    def note_post_heal_coverage(self, it, coverage):
        """Record one post-heal (iteration, coverage) sample.  Both backends
        feed every iteration >= heal_at — warm-up rounds included — so the
        recovery metric below sees the true iteration axis."""
        self._post_heal_coverage.append((int(it), float(coverage)))

    def calc_recovery_iterations(self, heal_at, threshold=None):
        """Iterations after ``heal_at`` until coverage regains ``threshold``
        (COVERAGE_RECOVERY_THRESHOLD by default), measured on the full
        post-heal series — 0 means coverage was already at the bar on the
        heal iteration itself, matching the all-origins aggregate path.
        Sets ``recovery_iterations`` (-1 = never recovered in this run)."""
        from ..constants import COVERAGE_RECOVERY_THRESHOLD
        if threshold is None:
            threshold = COVERAGE_RECOVERY_THRESHOLD
        if heal_at < 0 or not self._post_heal_coverage:
            self.recovery_iterations = None
            return None
        for it, cov in self._post_heal_coverage:
            if cov >= threshold:
                self.recovery_iterations = it - heal_at
                break
        else:
            self.recovery_iterations = -1
        return self.recovery_iterations

    # -- end-of-simulation ---------------------------------------------------

    def build_stranded_node_histogram(self, upper_bound, lower_bound, num_buckets):
        self.stranded_node_collection.build_histogram(
            upper_bound, lower_bound, num_buckets)

    def build_aggregate_hops_stats_histogram(self, upper_bound, lower_bound,
                                             num_buckets):
        self.hops_stats.build_histogram(upper_bound, lower_bound, num_buckets)

    def build_message_histograms(self, num_buckets, normalize, stakes):
        self.egress_messages.build_histogram(num_buckets, stakes)
        self.ingress_messages.build_histogram(num_buckets, stakes)
        if normalize:
            self.egress_messages.normalize_message_counts()
            self.ingress_messages.normalize_message_counts()

    def build_prune_histogram(self, num_buckets, normalize, stakes):
        self.prune_messages.build_histogram(num_buckets, stakes)
        if normalize:
            self.prune_messages.normalize_message_counts()

    def build_validator_stake_distribution_histogram(self, num_buckets, stakes):
        vals = sorted(stakes.values(), reverse=True)
        self.validator_stake_distribution.build(vals[0], 0, num_buckets, vals)

    def run_all_calculations(self):
        """(gossip_stats.rs:1858-1867)"""
        self.coverage_stats.calculate_stats()
        self.rmr_stats.calculate_stats()
        self.hops_stats.aggregate_hop_stats()
        self.hops_stats.calc_last_delivery_hop_stats()
        self.stranded_node_collection.calculate_stats()
        self.outbound_branching_factors.calculate_stats()
        if self.has_delivery_stats():
            self.delivered_stats.calculate_stats()
            self.dropped_stats.calculate_stats()
            self.suppressed_stats.calculate_stats()
        if self.has_pull_stats():
            for sc in (self.pull_requests_stats, self.pull_responses_stats,
                       self.pull_misses_stats, self.pull_dropped_stats,
                       self.pull_suppressed_stats, self.pull_rescued_stats):
                sc.calculate_stats()
        sp = self.simulation_parameters
        if sp.heal_at >= 0:
            self.calc_recovery_iterations(sp.heal_at)

    # -- accessors -----------------------------------------------------------

    def get_coverage_stats(self):
        return self.coverage_stats.summary()

    def get_rmr_stats(self):
        return self.rmr_stats.summary()

    def get_rmr_by_index(self, index):
        return self.rmr_stats.get_stat_by_index(index)

    def get_per_hop_stats_by_index(self, i):
        s = self.hops_stats.per_round_stats[i]
        return (s.mean, s.median, s.max, s.min)

    def get_hops_stat_by_iteration(self, i):
        return self.hops_stats.get_stat_by_iteration(i)

    def get_aggregate_hop_stats(self):
        s = self.hops_stats.aggregate_stats
        return (s.mean, s.median, s.max, s.min)

    def get_last_delivery_hop_stats(self):
        self.hops_stats.calc_last_delivery_hop_stats()
        s = self.hops_stats.last_delivery_hop_stats
        return (s.mean, s.median, s.max, s.min)

    def get_stranded_stats(self):
        """11-tuple matching gossip_stats.rs:1572-1602."""
        c = self.stranded_node_collection
        return (c.total_stranded_iterations,
                c.stranded_iterations_per_node,
                c.mean_stranded_per_iteration,
                c.mean_stranded_iterations_per_stranded_node,
                c.median_stranded_iterations_per_stranded_node,
                c.stranded_node_mean_stake,
                c.stranded_node_median_stake,
                c.stranded_node_max_stake,
                c.stranded_node_min_stake,
                c.weighted_stranded_node_mean_stake,
                c.weighted_stranded_node_median_stake)

    def get_stranded_node_stats_by_iteration(self, i):
        return self.stranded_node_collection.per_iter_stats[i]

    def get_outbound_branching_factor_by_index(self, i):
        return self.outbound_branching_factors.get_stat_by_index(i)

    def get_stranded_node_histogram(self):
        return self.stranded_node_collection.histogram

    def get_aggregate_hop_stat_histogram(self):
        return self.hops_stats.histogram

    def get_egress_messages_histogram(self):
        return self.egress_messages.histogram

    def get_ingress_messages_histogram(self):
        return self.ingress_messages.histogram

    def get_prune_message_histogram(self):
        return self.prune_messages.histogram

    def get_validator_stake_distribution_histogram(self):
        return self.validator_stake_distribution

    def is_empty(self):
        return self.coverage_stats.is_empty()

    # -- printing ------------------------------------------------------------

    def _print_stat_collection(self, sc):
        log.info("%s Mean: %.6f", sc.collection_type, sc.mean)
        log.info("%s Median: %.6f", sc.collection_type, sc.median)
        log.info("%s Max: %.6f", sc.collection_type, sc.max)
        log.info("%s Min: %.6f", sc.collection_type, sc.min)

    def _print_histogram(self, name, hist):
        log.info("|---- %s HISTOGRAM W/ %s BUCKETS ----|", name, hist.num_buckets)
        for bucket, count in hist.items():
            lo = hist.min_entry + bucket * hist.bucket_range
            hi = hist.min_entry + (bucket + 1) * hist.bucket_range - 1
            if lo == hi:
                log.info("Bucket: %s: Count: %s", hi, count)
            else:
                log.info("Bucket: %s-%s: Count: %s", lo, hi, count)

    def print_all(self):
        """(gossip_stats.rs:1869-1883)"""
        log.info("|---- COVERAGE STATS ----|")
        self._print_stat_collection(self.coverage_stats)
        log.info("|---- RELATIVE MESSAGE REDUNDANCY (RMR) STATS ----|")
        self._print_stat_collection(self.rmr_stats)
        agg = self.hops_stats.aggregate_stats
        log.info("|---- AGGREGATE HOP STATS ----|")
        log.info("Aggregate Hops Mean: %.6f", agg.mean)
        log.info("Aggregate Hops Median: %.2f", agg.median)
        log.info("Aggregate Hops Max: %s", agg.max)
        self._print_histogram("HOPS STATS", self.hops_stats.histogram)
        ldh = self.hops_stats.last_delivery_hop_stats
        log.info("|---- LAST DELIVERY HOP STATS ----|")
        log.info("LDH Mean: %.6f  Median: %.2f  Max: %s  Min: %s",
                 ldh.mean, ldh.median, ldh.max, ldh.min)
        c = self.stranded_node_collection
        log.info("|---- STRANDED NODE STATS ----|")
        log.info("Total stranded node iterations: %s", c.total_stranded_iterations)
        log.info("Mean iterations a node was stranded: %.6f",
                 c.stranded_iterations_per_node)
        log.info("Mean nodes stranded per iteration: %.6f",
                 c.mean_stranded_per_iteration)
        log.info("Mean iterations a stranded node was stranded: %.6f",
                 c.mean_stranded_iterations_per_stranded_node)
        log.info("Median iterations a stranded node was stranded: %s",
                 c.median_stranded_iterations_per_stranded_node)
        log.info("Mean stake: %.2f  Median stake: %s  Max: %s  Min: %s",
                 c.stranded_node_mean_stake, c.stranded_node_median_stake,
                 c.stranded_node_max_stake, c.stranded_node_min_stake)
        log.info("Mean weighted stake: %.2f  Median weighted stake: %s",
                 c.weighted_stranded_node_mean_stake,
                 c.weighted_stranded_node_median_stake)
        self._print_histogram("STRANDED NODES", c.histogram)
        log.info("Total stranded nodes: %s", c.stranded_count())
        log.info("Total failed: %s", len(self.failed_nodes))
        log.info("|---- OUTBOUND BRANCHING FACTOR ----|")
        self._print_stat_collection(self.outbound_branching_factors)
        self._print_histogram("EGRESS MESSAGES", self.egress_messages.histogram)
        if self.has_delivery_stats():
            log.info("|---- DEGRADED DELIVERY STATS ----|")
            for sc in (self.delivered_stats, self.dropped_stats,
                       self.suppressed_stats):
                self._print_stat_collection(sc)
            if self.failed_count_series:
                log.info("Failed nodes (last measured round): %s",
                         self.failed_count_series[-1])
        if self.has_pull_stats():
            log.info("|---- PULL (ANTI-ENTROPY) STATS ----|")
            for sc in (self.pull_requests_stats, self.pull_responses_stats,
                       self.pull_misses_stats, self.pull_rescued_stats):
                self._print_stat_collection(sc)
            log.info("Pull dropped total: %s  Pull suppressed total: %s",
                     int(sum(self.pull_dropped_stats.collection)),
                     int(sum(self.pull_suppressed_stats.collection)))
        if self.recovery_iterations is not None:
            if self.recovery_iterations >= 0:
                log.info("Coverage recovered %s iteration(s) after heal",
                         self.recovery_iterations)
            else:
                log.info("Coverage did NOT recover after heal within the run")


class GossipStatsCollection:
    """Across-simulation aggregation (gossip_stats.rs:1886-1965)."""

    def __init__(self):
        self.collection = []
        self.num_sims = 0

    def set_number_of_simulations(self, n):
        self.num_sims = n

    def push(self, stats: GossipStats):
        self.collection.append(stats)

    def is_empty(self):
        return not self.collection

    def print_all(self, gossip_iterations, warm_up_rounds, test_type):
        measured = gossip_iterations - warm_up_rounds
        log.info("|--- GOSSIP STATS COLLECTION ACROSS ALL %s SIMULATION(S) ---|",
                 self.num_sims)
        log.info("|--- Gossip Iterations: %s", gossip_iterations)
        log.info("|--- Warm Up Rounds: %s", warm_up_rounds)
        log.info("|--- Total Measured Rounds For Gossip Stats: %s", measured)
        log.info("|--- Test Type: %s", test_type)
        for i, stats in enumerate(self.collection):
            log.info("Simulation Iteration: %s, Origin: %s", i, stats.origin)
            stats.print_all()
        total = sum(s.stranded_node_collection.total_stranded_iterations
                    for s in self.collection)
        log.info("Total stranded node iterations across all simulations %s", total)
