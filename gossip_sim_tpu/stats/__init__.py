"""Statistics suite (reference: gossip_stats.rs)."""

from .collections import StatCollection
from .gossip_stats import GossipStats, GossipStatsCollection, SimulationParameters
from .histogram import Histogram
from .hops import HopsStat, HopsStatCollection
from .stranded import StrandedNodeCollection, StrandedNodeStats
from .trackers import EgressIngressMessageTracker, branching_factor_outbound

__all__ = [
    "EgressIngressMessageTracker",
    "GossipStats",
    "GossipStatsCollection",
    "Histogram",
    "HopsStat",
    "HopsStatCollection",
    "SimulationParameters",
    "StatCollection",
    "StrandedNodeCollection",
    "StrandedNodeStats",
    "branching_factor_outbound",
]
