"""Statistics suite (reference: gossip_stats.rs).

Per-edge accounting shared with the flight recorder (delivered edges,
first-delivery trees, redundancy attribution, stranded root-causing) lives
in :mod:`gossip_sim_tpu.stats.edges`; import it directly — it is left out
of the package namespace so the stats package stays importable without
pulling the obs trace schema in.
"""

from .collections import StatCollection
from .gossip_stats import GossipStats, GossipStatsCollection, SimulationParameters
from .histogram import Histogram
from .hops import HopsStat, HopsStatCollection
from .stranded import StrandedNodeCollection, StrandedNodeStats
from .trackers import EgressIngressMessageTracker, branching_factor_outbound

__all__ = [
    "EgressIngressMessageTracker",
    "GossipStats",
    "GossipStatsCollection",
    "Histogram",
    "HopsStat",
    "HopsStatCollection",
    "SimulationParameters",
    "StatCollection",
    "StrandedNodeCollection",
    "StrandedNodeStats",
    "branching_factor_outbound",
]
