"""Concurrent-traffic statistics (traffic.py subsystem).

The single-value stats suite (gossip_stats.py) is built around one origin
per simulation; a traffic run instead produces **per-round contention
series** (queue depths, deferrals, drops across the whole value axis) and
**per-value retirement records** (coverage, latency, RMR per injected
value).  ``TrafficStats`` collects both, mirrors ``GossipStats``'s
deterministic ``parity_snapshot()`` contract (tools/traffic_smoke.py and
the engine-vs-oracle CLI parity tests diff it), and serializes through
``state_dict``/``load_state_dict`` for checkpoint-v6 resume.
"""

from __future__ import annotations

import json

import numpy as np

#: the per-round series every backend feeds (engine rows / TrafficRound
#: fields share these names; keep in sync with tests/test_traffic.py)
ROUND_FIELDS = [
    "injected", "inject_dropped", "live", "sends", "deferred",
    "failed_target", "suppressed", "dropped", "arrived", "queue_dropped",
    "accepted", "delivered", "redundant", "prunes_sent", "retired",
    "converged", "hop_clamped", "qdepth_max", "inflow_max",
]

#: per-value retirement record keys (traffic.retire_record); the last
#: three are the starvation root-causing fields (ISSUE 11) — every record
#: carries an explicit terminal cause plus its rescue/queue-drop evidence
RECORD_FIELDS = ["vid", "origin", "birth", "retired_at", "latency_rounds",
                 "holders", "coverage", "m", "rmr", "converged", "mean_hop",
                 "rescued_by_pull", "qdrops", "cause"]

#: the per-round adaptive pull-rescue series (engine rows / TrafficRound
#: fields share these names; fed only under gossip_mode "adaptive" and
#: emitted as the ``sim_adaptive`` Influx series)
ADAPTIVE_ROUND_FIELDS = [
    "pull_sent", "pull_deferred", "pull_failed_target", "pull_suppressed",
    "pull_dropped", "pull_arrived", "pull_queue_dropped", "pull_served",
    "pull_responses", "pull_rescued", "pull_active_values",
    "switched_to_pull",
]


class TrafficStats:
    """Per-round series + per-value records of one traffic simulation."""

    def __init__(self):
        self.rounds = {k: [] for k in ROUND_FIELDS}
        self.adaptive_rounds = {k: [] for k in ADAPTIVE_ROUND_FIELDS}
        self.iterations = []
        self.records = []          # retirement record dicts, vid order
        self.final = {}            # end-of-run accumulator summary

    # -- feeds ------------------------------------------------------------

    def feed_round(self, it: int, values: dict) -> None:
        self.iterations.append(int(it))
        for k in ROUND_FIELDS:
            self.rounds[k].append(int(values[k]))
        if "pull_sent" in values:
            # adaptive mode: the pull-rescue series rides along
            for k in ADAPTIVE_ROUND_FIELDS:
                self.adaptive_rounds[k].append(int(values[k]))

    def feed_records(self, records) -> None:
        self.records.extend(records)

    def feed_final(self, final: dict) -> None:
        """End-of-run totals read off the engine/oracle state: the
        measured-round accumulators plus the live (unfinished) value
        count."""
        self.final = {k: (int(v) if np.isscalar(v) or isinstance(v, int)
                          else [int(x) for x in v])
                      for k, v in final.items()}

    def is_empty(self) -> bool:
        return not self.iterations

    # -- parity / persistence --------------------------------------------

    def parity_snapshot(self) -> dict:
        """Every deterministic series/record as one dict — the traffic
        twin of GossipStats.parity_snapshot (one definition of the
        bit-exactness surface; tools/traffic_smoke.py diffs it).  The
        adaptive series appears only when it was fed (mode "adaptive"),
        so push-mode snapshots keep their pre-adaptive shape."""
        snap = {
            "iterations": list(self.iterations),
            "rounds": {k: list(v) for k, v in self.rounds.items()},
            "records": [
                {f: rec[f] for f in RECORD_FIELDS} for rec in self.records],
            "final": dict(self.final),
        }
        if any(self.adaptive_rounds.values()):
            snap["adaptive_rounds"] = {
                k: list(v) for k, v in self.adaptive_rounds.items()}
        return snap

    def state_dict(self) -> dict:
        return self.parity_snapshot()

    def load_state_dict(self, d: dict) -> None:
        self.iterations = [int(x) for x in d.get("iterations", [])]
        self.rounds = {k: [int(x) for x in d.get("rounds", {}).get(k, [])]
                       for k in ROUND_FIELDS}
        self.adaptive_rounds = {
            k: [int(x) for x in d.get("adaptive_rounds", {}).get(k, [])]
            for k in ADAPTIVE_ROUND_FIELDS}
        self.records = []
        for r in d.get("records", []):
            rec = dict(r)
            # pre-v7 checkpoints: records predate the root-causing fields
            rec.setdefault("rescued_by_pull", 0)
            rec.setdefault("qdrops", 0)
            rec.setdefault("cause", "converged" if rec.get("converged")
                           else "stalled")
            self.records.append(rec)
        self.final = dict(d.get("final", {}))

    def to_json(self) -> str:
        return json.dumps(self.parity_snapshot(), sort_keys=True)

    # -- aggregation ------------------------------------------------------

    def summary(self) -> dict:
        """Flat aggregate dict for the run report, the end-of-run Influx
        point, and the CLI summary line."""
        recs = self.records
        lat = np.asarray([r["latency_rounds"] for r in recs], np.float64)
        cov = np.asarray([r["coverage"] for r in recs], np.float64)
        rmr = np.asarray([r["rmr"] for r in recs], np.float64)
        tot = {k: int(np.sum(self.rounds[k], dtype=np.int64))
               for k in ("injected", "inject_dropped", "sends", "deferred",
                         "queue_dropped", "dropped", "suppressed",
                         "delivered", "redundant", "accepted",
                         "prunes_sent", "retired", "converged",
                         "hop_clamped")}
        causes = [r.get("cause") for r in recs]
        pull_qdrop = int(np.sum(self.adaptive_rounds["pull_queue_dropped"],
                                dtype=np.int64))
        pull_def = int(np.sum(self.adaptive_rounds["pull_deferred"],
                              dtype=np.int64))
        out = {
            "measured_rounds": len(self.iterations),
            "values_injected": tot["injected"],
            "values_retired": tot["retired"],
            "values_converged": tot["converged"],
            "values_stranded": tot["retired"] - tot["converged"],
            # terminal-cause attribution (traffic.terminal_cause): every
            # retired value is exactly one of converged / rescued_by_pull
            # / starved_queue_drop / stalled
            "values_rescued": causes.count("rescued_by_pull"),
            "values_starved_queue_drop": causes.count("starved_queue_drop"),
            "values_stalled": causes.count("stalled"),
            "nodes_rescued": int(sum(r.get("rescued_by_pull", 0)
                                     for r in recs)),
            "values_unfinished": int(self.final.get("live_at_end", 0)),
            "inject_dropped": tot["inject_dropped"],
            "sends": tot["sends"],
            "delivered": tot["delivered"],
            "redundant": tot["redundant"],
            "loss_dropped": tot["dropped"],
            "suppressed": tot["suppressed"],
            "queue_deferred": tot["deferred"],
            "queue_dropped": tot["queue_dropped"],
            # queue-drop side attribution (node health observatory): the
            # ingress side is everything the receiver-cap sort discarded —
            # push arrivals over node_ingress_cap plus pull requests over
            # the serving peer's remaining budget (exactly what qdrop_acc
            # accumulates per node); the egress side is the sender-cap
            # deferrals (defer_acc).  "queue_dropped" above keeps its
            # historical push-only meaning.
            "queue_dropped_ingress": tot["queue_dropped"] + pull_qdrop,
            "queue_deferred_egress": tot["deferred"] + pull_def,
            "prunes_sent": tot["prunes_sent"],
            "hop_clamped": tot["hop_clamped"],
            "qdepth_max": int(max(self.rounds["qdepth_max"], default=0)),
            "inflow_max": int(max(self.rounds["inflow_max"], default=0)),
            "live_max": int(max(self.rounds["live"], default=0)),
        }
        if any(self.adaptive_rounds.values()):
            # adaptive pull-rescue totals (sim_adaptive series aggregate)
            out.update({f"adaptive_{k}": int(np.sum(self.adaptive_rounds[k],
                                                    dtype=np.int64))
                        for k in ("pull_sent", "pull_responses",
                                  "pull_rescued", "pull_deferred",
                                  "pull_queue_dropped",
                                  "switched_to_pull")})
        if len(recs):
            out.update({
                "value_latency_mean": float(lat.mean()),
                "value_latency_p50": float(np.percentile(lat, 50)),
                "value_latency_p90": float(np.percentile(lat, 90)),
                "value_latency_max": int(lat.max()),
                "value_coverage_mean": float(cov.mean()),
                "value_coverage_min": float(cov.min()),
                "value_rmr_mean": float(rmr.mean()),
            })
        else:
            out.update({
                "value_latency_mean": 0.0, "value_latency_p50": 0.0,
                "value_latency_p90": 0.0, "value_latency_max": 0,
                "value_coverage_mean": 0.0, "value_coverage_min": 0.0,
                "value_rmr_mean": 0.0,
            })
        return out


class TrafficStatsCollection:
    """Sweep-ordered TrafficStats (one per sweep point)."""

    def __init__(self):
        self.collection = []
        self.points = []      # the swept knob value per point

    def push(self, point_value, stats: TrafficStats) -> None:
        self.points.append(point_value)
        self.collection.append(stats)

    def is_empty(self) -> bool:
        return not self.collection

    def summaries(self) -> list:
        return [dict(point=p, **s.summary())
                for p, s in zip(self.points, self.collection)]
