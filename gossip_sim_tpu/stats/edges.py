"""Shared per-edge accounting over flight-recorder arrays (obs/trace.py).

The stats layer's scalar aggregates (coverage, RMR, stranded counts) and
the trace tooling (tools/trace_report.py, tools/trace_smoke.py) must agree
on what counts as a delivered edge, a first delivery, and a redundant
delivery — so the definitions live here once, as pure-numpy functions over
single-round trace arrays (no leading round/origin axes; callers slice).

Conventions (matching obs/trace.py):

* ``peers``  [N, F] int   candidate target per fanout slot, -1 empty
* ``code``   [N, F] int   slot outcome (TRACE_* codes)
* ``dist``   [N]    int   hop distance from origin, -1 unreached
* ``first_src`` [N] int   first-delivery sender per receiver, -1 none
* ``active`` [N, S] int   pre-round active set, -1 empty
* ``pruned`` [N, S] bool  pre-round per-slot pruned bits
* ``failed`` [N]    bool  node-failure mask
"""

from __future__ import annotations

import numpy as np

from ..obs.trace import (TRACE_CANDIDATE, TRACE_DROPPED, TRACE_FAILED_TARGET,
                         TRACE_SUPPRESSED)

# stranded-path failure causes (explain_stranded)
CAUSE_PRUNED = "pruned"
CAUSE_SENDER_UNREACHED = "sender_unreached"
CAUSE_SENDER_FAILED = "sender_failed"
CAUSE_FANOUT_TRUNCATED = "fanout_truncated"
CAUSE_SUPPRESSED = "suppressed"
CAUSE_DROPPED = "dropped"
CAUSE_TARGET_FAILED = "target_failed"
CAUSE_NO_SENDERS = "no_potential_senders"
CAUSE_INCONSISTENT = "inconsistent_delivered"
# push-stranded node rescued by the pull (anti-entropy) phase (pull.py):
# not stranded in the stats layer, but the push-path failure analysis is
# still reported so "pull papered over a push hole" stays visible
CAUSE_RESCUED_BY_PULL = "rescued_by_pull"
# concurrent-traffic queue-cap outcomes (traffic.py, trace schema v3):
# the slot's candidate message was never sent (sender's egress budget
# exhausted — deferred to a later round) or arrived but was dropped by
# the receiver's ingress budget.  Per-value traffic arrays slice straight
# into explain_stranded (active shared, pruned/peers/code/dist per value).
CAUSE_EGRESS_DEFERRED = "egress_deferred"
CAUSE_QUEUE_DROPPED = "queue_dropped"


def delivered_mask(code: np.ndarray, dist: np.ndarray) -> np.ndarray:
    """[N, F] bool: slots that actually carried a message this round — a
    deliverable candidate pushed by a source the BFS reached."""
    return (code == TRACE_CANDIDATE) & (dist >= 0)[:, None]


def delivered_edges(peers: np.ndarray, code: np.ndarray,
                    dist: np.ndarray) -> np.ndarray:
    """Delivered (src, dst) pairs as an ``[E, 2]`` int array."""
    src, slot = np.nonzero(delivered_mask(code, dist))
    return np.stack([src, peers[src, slot]], axis=1).astype(np.int64)


def edge_keys(edges: np.ndarray, num_nodes: int) -> np.ndarray:
    """Pack [E, 2] (src, dst) pairs into sortable int64 keys."""
    return edges[:, 0].astype(np.int64) * num_nodes + edges[:, 1]


def first_delivery_edges(first_src: np.ndarray,
                         dist: np.ndarray) -> np.ndarray:
    """First-delivery (src, dst, hop) rows [E, 3] for every receiver that
    was reached through gossip this round (``dist > 0``; the origin's own
    dist-0 entry is the tree root, not an edge)."""
    dst = np.nonzero((dist > 0) & (first_src >= 0))[0]
    return np.stack([first_src[dst], dst, dist[dst]], axis=1).astype(np.int64)


def build_delivery_tree(first_src: np.ndarray, dist: np.ndarray,
                        origin: int):
    """-> (parent [N] int, ok bool).  ``parent[n]`` is the first-delivery
    sender for reached non-origin nodes, -1 otherwise.  ``ok`` is True iff
    every reached node's parent chain terminates at the origin with strictly
    decreasing hop distance — i.e. the recorded first deliveries really form
    a tree rooted at the origin."""
    n = dist.shape[0]
    parent = np.full(n, -1, np.int64)
    reached = (dist > 0) & (first_src >= 0)
    parent[reached] = first_src[reached]
    ok = bool(dist[origin] == 0)
    # every reached node needs a recorded first delivery ...
    ok &= not np.any((dist > 0) & (first_src < 0))
    if ok and reached.any():
        p = parent[reached]
        # ... whose sender is reached exactly one hop closer to the origin
        ok = bool(np.all(dist[p] >= 0) and np.all(dist[p] + 1
                                                  == dist[reached]))
    return parent, ok


def redundant_edge_counts(peers: np.ndarray, code: np.ndarray,
                          dist: np.ndarray, first_src: np.ndarray,
                          num_nodes: int) -> dict:
    """Redundant deliveries per edge this round: a delivered edge
    ``src -> dst`` is redundant when ``src`` is not ``dst``'s first-delivery
    sender (RMR's numerator is exactly these plus prune messages).
    Returns ``{(src, dst): count}`` (count is 1 per round per edge)."""
    edges = delivered_edges(peers, code, dist)
    if edges.shape[0] == 0:
        return {}
    red = edges[first_src[edges[:, 1]] != edges[:, 0]]
    keys, counts = np.unique(edge_keys(red, num_nodes), return_counts=True)
    return {(int(k) // num_nodes, int(k) % num_nodes): int(c)
            for k, c in zip(keys, counts)}


def explain_stranded(active: np.ndarray, pruned: np.ndarray,
                     peers: np.ndarray, code: np.ndarray, dist: np.ndarray,
                     failed: np.ndarray, origin: int,
                     pull_hop: np.ndarray | None = None) -> list:
    """Root-cause every stranded node of one round.

    A node is stranded when it is unreached and not failed (the stats
    layer's definition).  For each, every *potential sender* — a node whose
    pre-round active set contains it — is classified by why its path failed:

    * ``pruned``            the slot's pruned bit was set for this origin
    * ``sender_unreached``  the sender itself never got the message
      (``sender_failed`` when the sender was down outright)
    * ``fanout_truncated``  the slot was valid but beyond the first
      ``push_fanout`` valid slots, so no push was attempted
    * ``suppressed`` / ``dropped``  the push was attempted by a reached
      sender and lost to the partition / packet loss
    * ``target_failed``     can only appear for failed targets, i.e. never
      for a stranded node; listed for completeness
    * ``inconsistent_delivered``  a reached sender's slot claims delivery —
      impossible for a stranded node; flags a corrupt trace

    ``pull_hop`` (trace schema v2, pull modes): per-node pull delivery hop,
    -1 = none.  A push-unreached node with a pull rescue is NOT stranded —
    its entry carries ``rescued_by_pull`` in the summary (with the push-path
    causes preserved), so the analysis still shows why push alone would
    have stranded it.

    Returns ``[{node, causes: [{sender, slot, cause}], summary: {...}}]``
    with one entry per push-unreached non-failed node (``causes`` empty and
    summary ``no_potential_senders`` when nobody even pointed at it).
    """
    stranded = np.nonzero((dist < 0) & ~failed)[0]
    out = []
    for r in stranded:
        senders, slots = np.nonzero(active == r)
        causes = []
        for s, slot in zip(senders.tolist(), slots.tolist()):
            if pruned[s, slot]:
                cause = CAUSE_PRUNED
            elif dist[s] < 0:
                cause = CAUSE_SENDER_FAILED if failed[s] \
                    else CAUSE_SENDER_UNREACHED
            else:
                k = np.nonzero(peers[s] == r)[0]
                if k.size == 0:
                    cause = CAUSE_FANOUT_TRUNCATED
                else:
                    from ..traffic import (TRAFFIC_DEFERRED,
                                           TRAFFIC_QUEUE_DROPPED)
                    c = int(code[s, k[0]])
                    cause = {
                        TRACE_SUPPRESSED: CAUSE_SUPPRESSED,
                        TRACE_DROPPED: CAUSE_DROPPED,
                        TRACE_FAILED_TARGET: CAUSE_TARGET_FAILED,
                        # traffic (v3) queue-cap outcomes
                        TRAFFIC_DEFERRED: CAUSE_EGRESS_DEFERRED,
                        TRAFFIC_QUEUE_DROPPED: CAUSE_QUEUE_DROPPED,
                    }.get(c, CAUSE_INCONSISTENT)
            causes.append({"sender": int(s), "slot": int(slot),
                           "cause": cause})
        summary = {}
        for c in causes:
            summary[c["cause"]] = summary.get(c["cause"], 0) + 1
        if not causes:
            summary[CAUSE_NO_SENDERS] = 1
        entry = {"node": int(r), "causes": causes, "summary": summary}
        if pull_hop is not None and pull_hop[r] >= 0:
            summary[CAUSE_RESCUED_BY_PULL] = 1
            entry["pull_hop"] = int(pull_hop[r])
            entry["stranded"] = False
        elif pull_hop is not None:
            entry["stranded"] = True
        out.append(entry)
    return out


def diff_delivered(peers_a, code_a, dist_a, peers_b, code_b, dist_b,
                   num_nodes: int) -> dict:
    """Edge-by-edge delivered-set diff of one round between two traces
    (e.g. baseline vs packet-loss run).  Returns packed-key sets split into
    common / only_a / only_b plus counts."""
    ka = set(edge_keys(delivered_edges(peers_a, code_a, dist_a),
                       num_nodes).tolist())
    kb = set(edge_keys(delivered_edges(peers_b, code_b, dist_b),
                       num_nodes).tolist())
    return {
        "common": ka & kb,
        "only_a": ka - kb,
        "only_b": kb - ka,
        "n_a": len(ka),
        "n_b": len(kb),
    }


def unpack_edge(key: int, num_nodes: int) -> tuple:
    return int(key) // num_nodes, int(key) % num_nodes
