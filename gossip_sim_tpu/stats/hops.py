"""Hop-count statistics (reference: gossip_stats.rs:27-227).

``HopsStat`` filters unreached (u64::MAX) and origin (0) distances, then takes
mean/median/max/min (gossip_stats.rs:46-98).  ``HopsStatCollection``
accumulates raw hops across rounds (keeping 0s, dropping unreached —
gossip_stats.rs:163-175), producing aggregate stats, last-delivery-hop stats
(stats over per-round max, gossip_stats.rs:196-204) and a histogram.
"""

from __future__ import annotations

from ..constants import UNREACHED
from .histogram import Histogram


class HopsStat:
    def __init__(self, hops=None):
        if not hops:
            self.mean = 0.0
            self.median = 0.0
            self.max = 0
            self.min = 0
            return
        filtered = sorted(h for h in hops if h != UNREACHED and h != 0)
        count = len(filtered)
        self.mean = (sum(filtered) / count) if count else float("nan")
        if count == 0:
            self.median = 0.0
        elif count == 1:
            self.median = float(filtered[0])
        elif count % 2 == 0:
            mid = count // 2
            self.median = (filtered[mid - 1] + filtered[mid]) / 2.0
        else:
            self.median = float(filtered[count // 2])
        self.max = filtered[-1] if filtered else 0
        self.min = filtered[0] if filtered else 0


class HopsStatCollection:
    def __init__(self):
        self.per_round_stats = []
        self.raw_hop_collection = []
        self.aggregate_stats = HopsStat()
        self.last_delivery_hop_stats = HopsStat()
        self.histogram = Histogram()

    def insert(self, hops):
        self.per_round_stats.append(HopsStat(list(hops)))
        self.raw_hop_collection.extend(h for h in hops if h != UNREACHED)

    def get_stat_by_iteration(self, index):
        return self.per_round_stats[index]

    def aggregate_hop_stats(self):
        self.aggregate_stats = HopsStat(self.raw_hop_collection)

    def calc_last_delivery_hop_stats(self):
        self.last_delivery_hop_stats = HopsStat(
            [s.max for s in self.per_round_stats])

    def build_histogram(self, upper_bound, lower_bound, num_buckets):
        self.histogram.build(upper_bound, lower_bound, num_buckets,
                             self.raw_hop_collection)
