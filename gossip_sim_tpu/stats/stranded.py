"""Stranded-node statistics (reference: gossip_stats.rs:745-1166).

``StrandedNodeStats``: per-iteration stake stats over the stranded set.
``StrandedNodeCollection``: cumulative per-node stranded counts plus plain and
*weighted* stake stats — each strand event re-counts the node's stake
(gossip_stats.rs:974-1028) — and a stranded-count histogram.
"""

from __future__ import annotations

from .histogram import Histogram


def _median(sorted_vals):
    n = len(sorted_vals)
    if n == 0:
        return 0.0
    if n % 2 == 0:
        return (sorted_vals[n // 2 - 1] + sorted_vals[n // 2]) / 2.0
    return float(sorted_vals[n // 2])


class StrandedNodeStats:
    """Per-iteration stranded stake stats (gossip_stats.rs:766-843)."""

    def __init__(self, stranded_nodes=None, stakes=None):
        if not stranded_nodes:
            self.count = 0
            self.mean_stake = 0.0
            self.median_stake = 0.0
            self.max_stake = 0
            self.min_stake = 0
            return
        vals = sorted(stakes[pk] for pk in stranded_nodes)
        self.count = len(vals)
        self.mean_stake = sum(vals) / len(vals)
        self.median_stake = _median(vals)
        self.max_stake = vals[-1]
        self.min_stake = vals[0]


class StrandedNodeCollection:
    def __init__(self):
        self.per_iter_stats = []
        self.stranded_nodes = {}  # pubkey -> (stake, times_stranded)
        self.total_gossip_iterations = 0
        self.total_stranded_iterations = 0
        self.mean_stranded_per_iteration = 0.0
        self.mean_stranded_iterations_per_stranded_node = 0.0
        self.median_stranded_iterations_per_stranded_node = 0.0
        self.stranded_iterations_per_node = 0.0
        self.total_nodes = 0
        self.total_stranded_stake = 0
        self.stranded_node_mean_stake = 0.0
        self.stranded_node_median_stake = 0.0
        self.stranded_node_max_stake = 0
        self.stranded_node_min_stake = 0
        self.weighted_total_stranded_stake = 0
        self.weighted_stranded_node_mean_stake = 0.0
        self.weighted_stranded_node_median_stake = 0.0
        self.histogram = Histogram()

    def insert_nodes(self, stranded_nodes, stakes):
        """Record one iteration's stranded set (gossip_stats.rs:1040-1061)."""
        self.per_iter_stats.append(StrandedNodeStats(stranded_nodes, stakes))
        for pk in stranded_nodes:
            if pk in self.stranded_nodes:
                stake, count = self.stranded_nodes[pk]
                self.stranded_nodes[pk] = (stake, count + 1)
            elif pk in stakes:
                self.stranded_nodes[pk] = (stakes[pk], 1)
        self.total_gossip_iterations += 1
        if self.total_nodes == 0:
            self.total_nodes = len(stakes)

    def calculate_stats(self):
        """(gossip_stats.rs:964-1038)"""
        self.total_stranded_iterations = 0
        self.total_stranded_stake = 0
        self.weighted_total_stranded_stake = 0
        iter_counts, stranded_stakes, weighted_stakes = [], [], []
        for stake, times in self.stranded_nodes.values():
            self.total_stranded_iterations += times
            iter_counts.append(times)
            self.total_stranded_stake += stake
            self.weighted_total_stranded_stake += stake * times
            stranded_stakes.append(stake)
            weighted_stakes.extend([stake] * times)

        count = len(self.stranded_nodes)
        self.mean_stranded_per_iteration = (
            self.total_stranded_iterations / self.total_gossip_iterations
            if self.total_gossip_iterations else 0.0)
        self.stranded_node_mean_stake = (
            self.total_stranded_stake / count if count else float("nan"))
        self.mean_stranded_iterations_per_stranded_node = (
            self.total_stranded_iterations / count if count else float("nan"))
        self.weighted_stranded_node_mean_stake = (
            self.weighted_total_stranded_stake / self.total_stranded_iterations
            if self.total_stranded_iterations else float("nan"))
        self.stranded_iterations_per_node = (
            self.total_stranded_iterations / self.total_nodes
            if self.total_nodes else 0.0)

        iter_counts.sort()
        stranded_stakes.sort()
        weighted_stakes.sort()
        self.median_stranded_iterations_per_stranded_node = _median(iter_counts)
        self.stranded_node_median_stake = _median(stranded_stakes)
        self.weighted_stranded_node_median_stake = _median(weighted_stakes)
        self.stranded_node_max_stake = stranded_stakes[-1] if stranded_stakes else 0
        self.stranded_node_min_stake = stranded_stakes[0] if stranded_stakes else 0

    def get_sorted_stranded(self):
        """Sorted by (times stranded desc, stake desc)
        (gossip_stats.rs:1069-1083)."""
        return sorted(self.stranded_nodes.items(),
                      key=lambda kv: (-kv[1][1], -kv[1][0]))

    def stranded_count(self):
        return len(self.stranded_nodes)

    def build_histogram(self, upper_bound, lower_bound, num_buckets):
        self.histogram.build(
            upper_bound, lower_bound, num_buckets,
            [times for _, times in self.stranded_nodes.values()])
