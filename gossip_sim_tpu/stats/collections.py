"""Generic f64 series -> mean/median/max/min (reference: gossip_stats.rs:229-347)."""

from __future__ import annotations


def _seq_sum(values):
    """Plain sequential f64 accumulation (Python's builtin ``sum`` is
    compensated since 3.12; the reference's ``iter().sum::<f64>()`` is not)."""
    acc = 0.0
    for v in values:
        acc += v
    return acc


class StatCollection:
    def __init__(self, collection_type=""):
        self.collection = []
        self.mean = 0.0
        self.median = 0.0
        self.max = 0.0
        self.min = 0.0
        self.collection_type = collection_type

    def push(self, value):
        self.collection.append(float(value))

    def calculate_stats(self):
        data = sorted(self.collection)
        n = len(data)
        self.mean = _seq_sum(data) / n if n else float("nan")
        if n == 0:
            self.median = float("nan")
        elif n % 2 == 0:
            self.median = (data[n // 2 - 1] + data[n // 2]) / 2.0
        else:
            self.median = data[n // 2]
        self.max = data[-1] if data else 0.0
        self.min = data[0] if data else 0.0

    def get_stat_by_index(self, index):
        return self.collection[index]

    def is_empty(self):
        return not self.collection

    def summary(self):
        return (self.mean, self.median, self.max, self.min)
